#!/bin/bash
# Hyper-parameter grid batcher — damping x kfac-update-freq sweep, the
# reference's hyper-search driver (batch-hyper.sh:1-27: damping x freq grid
# fanned out across nodes). On TPU the sweep runs sequentially per host (or
# fan it out across pod slices by exporting a different grid slice per
# invocation via GRID_OFFSET/GRID_STRIDE).
#
# Usage: [dnn=resnet110] [nworkers=4] bash batch-hyper.sh

dnn="${dnn:-resnet110}"
nworkers="${nworkers:-1}"
epochs="${epochs:-60}"
dampings="${dampings:-0.03 0.01 0.003 0.001}"
freqs="${freqs:-1 5 10 50}"
offset="${GRID_OFFSET:-0}"
stride="${GRID_STRIDE:-1}"

cd "$(dirname "$0")"
i=0
for damping in $dampings; do
  for kfac in $freqs; do
    if [ $(( i % stride )) -eq "$offset" ]; then
      echo "=== grid[$i]: damping=$damping kfac_update_freq=$kfac ==="
      dnn="$dnn" nworkers="$nworkers" epochs="$epochs" \
        damping="$damping" kfac="$kfac" bash train_cifar10.sh "$@"
    fi
    i=$(( i + 1 ))
  done
done
