"""Closed-loop autotuner (kfac_pytorch_tpu/autotune.py).

Pins the tentpole contracts:

1. The arbiter is the ONLY writer of the runtime knobs: the
   KFACParamScheduler and the StragglerGovernor propose factors /
   stretches and never assign ``fac_update_freq`` /
   ``kfac_update_freq`` / ``damping`` themselves (a ``__setattr__``
   guard proves every write happens inside ``arbiter._commit``), and
   the composed result is schedule x stretch x tuner over the
   construction-time base.
2. The scheduler x governor interplay that used to be last-writer-wins
   is now order-free: an epoch advance mid-stretch decays the BASE
   while the stretch stays in force; recovery removes only the stretch
   (ManualClock, fully deterministic).
3. The controller converges to a planted optimum on a deterministic
   synthetic phase-time feed (no wall clock anywhere), with hysteresis
   (no knob flap inside the dwell window, cooldown after a revert,
   bounded probing in steady state).
4. The drift-band gate: on the modeled chip a measured phase ratio
   outside [optimistic, conservative] VETOES an otherwise-improving
   candidate; on any other platform the same feed commits (advisory).
5. Knob changes reuse the compiled variant cache (frequency moves
   compile nothing new when revisited) while a ``comm_precision``
   change clears it through the registered invalidator — and the
   mid-run fp32 -> bf16 -> fp32 wire switch keeps the EF-residual
   state structure consistent and checkpoints restorable.
6. Decisions are artifacts: JSONL decision log, ``report()`` block for
   bench extras, and log lines in the shared ``incident``
   event grammar (kfac-obs renders tuning timelines for free).
"""

import json
import logging

import numpy as np
import pytest

from kfac_pytorch_tpu import autotune
from kfac_pytorch_tpu.resilience.retry import ManualClock
from kfac_pytorch_tpu.resilience.straggler import StragglerGovernor

pytestmark = pytest.mark.core


class _FakePrecond:
    """Knob-attribute-only stand-in (jax-free, like the governor's)."""

    def __init__(self, fac=1, kfac=10, damping=0.03,
                 comm_precision=None, axis_name=None):
        self.fac_update_freq = fac
        self.kfac_update_freq = kfac
        self.damping = damping
        self.comm_precision = comm_precision
        self.axis_name = axis_name


class _GuardedPrecond(_FakePrecond):
    """Asserts every knob write happens inside the arbiter's apply —
    the single-writer enforcement of the acceptance criteria."""

    def __init__(self, *a, **kw):
        object.__setattr__(self, '_armed', False)
        super().__init__(*a, **kw)
        object.__setattr__(self, '_armed', True)

    def __setattr__(self, name, value):
        if name in autotune.KNOB_ATTRS and getattr(self, '_armed', False):
            assert autotune.in_apply(), \
                f'direct (non-arbiter) write of {name}'
        object.__setattr__(self, name, value)


# ---------------------------------------------------------------------------
# the arbiter: composition, adoption, single-writer enforcement
# ---------------------------------------------------------------------------

def test_arbiter_composes_schedule_stretch_tuner():
    pre = _FakePrecond(fac=1, kfac=10, damping=0.04)
    arb = autotune.arbiter_for(pre)
    assert autotune.arbiter_for(pre) is arb  # one per precond
    arb.propose('schedule', freq_factor=2.0, damping_factor=0.5)
    assert (pre.fac_update_freq, pre.kfac_update_freq) == (2, 20)
    assert abs(pre.damping - 0.02) < 1e-12
    arb.propose('straggler', stretch=4)
    assert (pre.fac_update_freq, pre.kfac_update_freq) == (8, 80)
    assert abs(pre.damping - 0.02) < 1e-12  # stretch leaves damping alone
    # tuner absolute override replaces base x schedule, stretch still on
    arb.propose('tuner', kfac_update_freq=5)
    assert pre.kfac_update_freq == 20          # 5 x stretch 4
    arb.propose('straggler', stretch=1)
    assert (pre.fac_update_freq, pre.kfac_update_freq) == (2, 5)
    # clearing the override returns to base x schedule
    arb.propose('tuner', kfac_update_freq=None)
    assert pre.kfac_update_freq == 20


def test_arbiter_freq_floor_and_int_truncation():
    # reference semantics: int() truncation then a floor of 1
    pre = _FakePrecond(fac=1, kfac=2)
    arb = autotune.arbiter_for(pre)
    arb.propose('schedule', freq_factor=0.1)
    assert (pre.fac_update_freq, pre.kfac_update_freq) == (1, 1)


def test_arbiter_adopts_external_direct_write():
    pre = _FakePrecond(fac=1, kfac=10)
    arb = autotune.arbiter_for(pre)
    arb.propose('straggler', stretch=2)
    assert pre.kfac_update_freq == 20
    # a legacy caller writes the attrs directly: adopted as the new
    # base, stretch/schedule/tuner state reset (the old governor
    # collision rule, now in one place)
    pre.fac_update_freq, pre.kfac_update_freq = 4, 40
    arb.propose('straggler', stretch=1)
    assert (pre.fac_update_freq, pre.kfac_update_freq) == (4, 40)
    assert arb.base['kfac_update_freq'] == 40


def test_adoption_keeps_stretch_and_schedule_incremental():
    """The adoption regressions: (a) an external write of ONE knob
    must not bake an in-force straggler stretch into the untouched
    frequency base — recovery still removes it; (b) a schedule advance
    after adoption decays INCREMENTALLY from the adopted value, never
    re-applying the whole cumulative factor to an already-decayed
    base."""
    # (a) damping written externally while the governor is stretched
    pre = _FakePrecond(fac=1, kfac=10, damping=0.04)
    arb = autotune.arbiter_for(pre)
    arb.propose('straggler', stretch=4)
    assert pre.kfac_update_freq == 40
    pre.damping = 0.01                       # external, damping only
    arb.propose('straggler', stretch=1)      # recovery
    assert (pre.fac_update_freq, pre.kfac_update_freq) == (1, 10)
    assert abs(pre.damping - 0.01) < 1e-12   # external value survives
    # (b) epoch decay, external damping write, next epoch decay:
    # cumulative factor 0.25 at epoch 2 applies as one more halving of
    # the ADOPTED value (0.01 -> 0.005), not 0.01 * 0.25
    pre2 = _FakePrecond(fac=1, kfac=10, damping=0.04)
    arb2 = autotune.arbiter_for(pre2)
    arb2.propose('schedule', damping_factor=0.5)   # epoch 1: 0.02
    assert abs(pre2.damping - 0.02) < 1e-12
    pre2.damping = 0.01                            # external mid-run
    arb2.propose('schedule', damping_factor=0.25)  # epoch 2
    assert abs(pre2.damping - 0.005) < 1e-12
    # an external FREQ write supersedes the stretch (the old governor
    # rule): the written cadence is the new unstretched base
    pre3 = _FakePrecond(fac=1, kfac=10)
    arb3 = autotune.arbiter_for(pre3)
    arb3.propose('straggler', stretch=2)
    pre3.fac_update_freq, pre3.kfac_update_freq = 4, 40
    arb3.propose('straggler', stretch=2)     # still degraded
    assert (pre3.fac_update_freq, pre3.kfac_update_freq) == (8, 80)
    arb3.propose('straggler', stretch=1)
    assert (pre3.fac_update_freq, pre3.kfac_update_freq) == (4, 40)


def test_tuner_damping_override_applies_and_clears():
    pre = _FakePrecond(fac=1, kfac=10, damping=0.04)
    arb = autotune.arbiter_for(pre)
    arb.propose('schedule', damping_factor=0.5)
    assert abs(pre.damping - 0.02) < 1e-12
    arb.propose('tuner', damping=0.007)      # absolute override
    assert abs(pre.damping - 0.007) < 1e-12
    arb.propose('schedule', damping_factor=0.25)  # override still wins
    assert abs(pre.damping - 0.007) < 1e-12
    arb.propose('tuner', damping=None)       # cleared -> base x schedule
    assert abs(pre.damping - 0.01) < 1e-12


def test_tick_attributes_interval_to_previous_dispatch():
    """The trainer feed: build_train_step ticks BEFORE the dispatch
    updates last_phases, so the phases argument names the dispatch the
    just-ended interval covered — tick must attribute the interval to
    the phases passed NOW (an off-by-one here buckets every refresh
    spike under the preceding steady step's phase set, where the
    outlier screen discards it)."""
    pre = _FakePrecond(fac=1, kfac=4)
    t = {'now': 0.0}
    ctl = autotune.KnobController(pre, window=4, settle=0, tune=(),
                                  clock=lambda: t['now'])
    # dispatch sequence: refresh (10 s) then three steady (1 s) —
    # each tick happens before the NEXT dispatch, carrying the phase
    # set of the dispatch whose interval just ended
    seq = [(('pred', 'stats', 'decomp', 'gather'), 10.0),
           (('pred',), 1.0), (('pred',), 1.0), (('pred',), 1.0)]
    ctl.tick(0, ())                       # first tick: nothing recorded
    for i, (phases, dt) in enumerate(seq):
        t['now'] += dt
        ctl.tick(i + 1, phases)
    acc = ctl.last_window['measured']
    # the 10 s interval landed on the refresh phase set, not 'pred'
    assert ctl.last_window['time_s'] == pytest.approx(3.25)
    refresh_label = [k for k in acc if 'ComputeInverse' in k]
    assert refresh_label, acc


def test_arbiter_rejects_unknown_proposer_and_knob():
    pre = _FakePrecond()
    arb = autotune.arbiter_for(pre)
    with pytest.raises(KeyError):
        arb.propose('tuner', basis_update_freq=7)
    with pytest.raises(KeyError):
        arb.propose('cosmic_rays', stretch=2)


def test_arbiter_elastic_records_compose_nothing():
    pre = _FakePrecond(fac=2, kfac=20)
    arb = autotune.arbiter_for(pre)
    arb.propose('elastic', from_world=2, to_world=3, lr_factor=1.5)
    assert (pre.fac_update_freq, pre.kfac_update_freq) == (2, 20)
    assert arb.records == [{'from_world': 2, 'to_world': 3,
                            'lr_factor': 1.5}]


def test_arbiter_rebases_cohorts_once_per_change():
    calls = []

    class _P(_FakePrecond):
        def rebase_cohorts(self):
            calls.append(1)

    pre = _P(fac=1, kfac=10)
    arb = autotune.arbiter_for(pre)
    arb.propose('straggler', stretch=2)       # freq change -> 1 rebase
    assert len(calls) == 1
    arb.propose('straggler', stretch=2)       # no-op -> no rebase
    assert len(calls) == 1
    arb.propose('schedule', damping_factor=0.5)   # damping only -> none
    assert len(calls) == 1
    arb.propose('tuner', kfac_update_freq=7)  # composed change -> 1 more
    assert len(calls) == 2


def test_arbiter_invalidator_fires_only_on_comm_precision():
    pre = _FakePrecond(comm_precision='fp32')
    arb = autotune.arbiter_for(pre)
    cleared = []
    arb.add_invalidator(lambda: cleared.append(1))
    arb.propose('straggler', stretch=2)
    assert not cleared                         # freq moves reuse cache
    arb.propose('tuner', comm_precision='bf16')
    assert len(cleared) == 1
    assert pre.comm_precision == 'bf16'
    arb.propose('tuner', comm_precision='bf16')
    assert len(cleared) == 1                   # unchanged -> no clear


def test_scheduler_and_governor_never_write_knobs_directly():
    """The acceptance-criteria pin: every fac/kfac_update_freq/damping
    mutation flows through the arbiter — asserted at the setattr level
    while the real scheduler and governor run their full paths."""
    from kfac_pytorch_tpu.scheduler import KFACParamScheduler
    pre = _GuardedPrecond(fac=1, kfac=10, damping=0.03)
    sched = KFACParamScheduler(pre, damping_alpha=0.5,
                               damping_schedule=[1],
                               update_freq_alpha=2,
                               update_freq_schedule=[1])
    clk = ManualClock()
    gov = StragglerGovernor(pre, budget=1.0, decay=0.5, warmup=0,
                            clock=clk.monotonic, sleep=clk.sleep)
    sched.step(1)
    for dt in (5.0, 5.0, 5.0):
        gov.observe(dt)
    assert gov.level >= 1
    for _ in range(10):
        gov.observe(0.01)
    assert gov.level == 0
    ctl = autotune.KnobController(pre, window=2, settle=0,
                                  tune=('kfac_update_freq',),
                                  freq_bounds=(1, 80))
    for _ in range(8):
        ctl.record(('pred',), 0.01)
    # all three proposers ran full cycles; _GuardedPrecond asserted
    # in_apply() on every knob write along the way
    assert autotune.arbiter_for(pre).changes >= 3


def test_scheduler_epoch_mid_stretch_then_recover_ordering():
    """The satellite regression: stretch -> epoch decay -> recover on a
    ManualClock. The old direct writes lost one side's intent at each
    hand-off; through the arbiter both survive in either order."""
    from kfac_pytorch_tpu.scheduler import KFACParamScheduler
    pre = _FakePrecond(fac=1, kfac=10, damping=0.03)
    sched = KFACParamScheduler(pre, update_freq_alpha=2,
                               update_freq_schedule=[1])
    clk = ManualClock()
    gov = StragglerGovernor(pre, budget=1.0, decay=0.5, warmup=0,
                            stretch=2, clock=clk.monotonic,
                            sleep=clk.sleep)
    # 1) the governor stretches
    for dt in (5.0, 5.0, 5.0):
        gov.observe(dt)
    level = gov.level
    assert level >= 1
    stretch = 2 ** level
    assert pre.kfac_update_freq == 10 * stretch
    # 2) an epoch advance mid-stretch: the schedule decays the BASE
    #    while the stretch stays in force (neither clobbers the other)
    sched.step(1)
    assert pre.kfac_update_freq == 20 * stretch
    assert pre.fac_update_freq == 2 * stretch
    # 3) recovery removes ONLY the stretch: the epoch's cadence survives
    for _ in range(10):
        gov.observe(0.01)
    assert gov.level == 0
    assert (pre.fac_update_freq, pre.kfac_update_freq) == (2, 20)


# ---------------------------------------------------------------------------
# the controller: deterministic synthetic feeds (no wall clock)
# ---------------------------------------------------------------------------

def _feed(ctl, pre, model, steps):
    """Drive ``ctl`` with a synthetic per-step cost model
    ``model(kfac_update_freq, i_in_window) -> (phases, seconds)``;
    returns steps actually fed."""
    fed = 0
    while fed < steps:
        F = pre.kfac_update_freq
        for i in range(F):
            phases, cost = model(F, i)
            ctl.record(phases, cost)
            fed += 1
            if fed >= steps:
                break
    return fed


def _amortized(F, i):
    """Refresh cost 0.5 amortized over the window: optimum = max freq."""
    if i == 0:
        return ('pred', 'stats', 'decomp', 'gather'), 0.51
    return ('pred',), 0.01


def test_controller_converges_to_planted_optimum():
    pre = _FakePrecond(fac=1, kfac=1)
    ctl = autotune.KnobController(pre, window=16, settle=1,
                                  rel_improve=0.03, dwell_windows=1,
                                  cooldown=2, steady_every=0,
                                  tune=('kfac_update_freq',),
                                  freq_bounds=(1, 8))
    _feed(ctl, pre, _amortized, 400)
    assert pre.kfac_update_freq == 8          # the planted optimum
    assert ctl.state == 'steady'
    assert ctl.commits == 3                   # 1 -> 2 -> 4 -> 8
    assert ctl.windows <= 30                  # bounded probe budget
    k = ctl.report()
    assert k['knobs']['kfac_update_freq'] == 8
    assert k['state'] == 'steady'


def test_controller_converges_down_from_pessimal_high_freq():
    """Stale-side optimum: when every step's cost GROWS with the
    cadence (a stand-in for staleness pricing), the controller must
    climb DOWN the ladder too."""
    pre = _FakePrecond(fac=1, kfac=8)

    def model(F, i):
        phases = ('pred', 'stats', 'decomp', 'gather') if i == 0 \
            else ('pred',)
        return phases, 0.01 + 0.002 * F + (0.001 if i == 0 else 0.0)

    ctl = autotune.KnobController(pre, window=16, settle=1,
                                  rel_improve=0.03, dwell_windows=1,
                                  cooldown=2, steady_every=0,
                                  tune=('kfac_update_freq',),
                                  freq_bounds=(1, 8))
    _feed(ctl, pre, model, 600)
    assert pre.kfac_update_freq == 1
    assert ctl.state == 'steady'


def test_controller_hysteresis_no_flap_on_flat_profile():
    """A flat cost profile must settle, not oscillate: every probe
    reverts (no >rel_improve gain), candidates go on cooldown, and the
    controller reaches steady with the original knob intact."""
    pre = _FakePrecond(fac=1, kfac=4)
    ctl = autotune.KnobController(pre, window=8, settle=1,
                                  rel_improve=0.03, dwell_windows=2,
                                  cooldown=4, steady_every=0,
                                  tune=('kfac_update_freq',),
                                  freq_bounds=(1, 8))
    _feed(ctl, pre, lambda F, i: (('pred',), 0.01), 600)
    assert ctl.state == 'steady'
    assert pre.kfac_update_freq == 4
    assert ctl.commits == 0
    assert ctl.reverts == 2                   # 8 and 2 each tried once


def test_controller_dwell_blocks_probes_after_commit():
    """Hysteresis: after a commit the controller holds the committed
    config for dwell_windows full windows before probing again."""
    pre = _FakePrecond(fac=1, kfac=1)
    ctl = autotune.KnobController(pre, window=16, settle=1,
                                  rel_improve=0.03, dwell_windows=3,
                                  cooldown=2, steady_every=0,
                                  tune=('kfac_update_freq',),
                                  freq_bounds=(1, 8))
    # run until the first commit lands
    while ctl.commits == 0:
        _feed(ctl, pre, _amortized, 16)
    assert ctl.state == 'dwell'
    committed = pre.kfac_update_freq
    start = ctl.windows
    while ctl.state == 'dwell':
        # the knob may only change at the dwell->probe transition —
        # while still dwelling it must hold the committed value
        assert pre.kfac_update_freq == committed
        _feed(ctl, pre, _amortized, 1)
    assert ctl.windows - start >= 3


def test_controller_discards_windows_under_straggler_stretch():
    """A host emergency is not a tuning signal: while the governor's
    stretch is in force the controller accumulates nothing."""
    pre = _FakePrecond(fac=1, kfac=4)
    arb = autotune.arbiter_for(pre)
    ctl = autotune.KnobController(pre, window=4, settle=0,
                                  tune=('kfac_update_freq',))
    arb.propose('straggler', stretch=2)
    for _ in range(40):
        ctl.record(('pred',), 5.0)            # catastrophic step times
    assert ctl.windows == 0 and ctl.state == 'baseline'
    arb.propose('straggler', stretch=1)
    for _ in range(6):
        ctl.record(('pred',), 0.01)
    assert ctl.windows >= 1                   # measuring again


def test_controller_seeds_from_perfmodel_prior():
    """Before any measurement: an eigen-variant predicted block (huge
    fenced decomposition cost) seeds kfac_update_freq to the ladder
    value minimizing predicted steady step time."""
    from kfac_pytorch_tpu import perfmodel
    pre = _FakePrecond(fac=1, kfac=1)
    ctl = autotune.KnobController(pre, window=4, settle=0,
                                  tune=('kfac_update_freq',),
                                  freq_bounds=(1, 512),
                                  predicted=perfmodel.predict_block(),
                                  variant='eigen_dp')
    ctl.record(('pred',), 0.01)               # first record triggers seed
    # decomp ~73 s vs model ~0.11 s: the prior pushes to the ladder top
    assert pre.kfac_update_freq == 512
    assert any(d['kind'] == 'seed' for d in ctl.decisions)


def test_prior_best_freq_prefers_cheap_decomp_low_freq():
    predicted = {'scenarios': {'central': {'phases_s': {
        'Model': 0.1, 'Precondition': 0.01, 'ComputeFactor': 0.01,
        'ComputeInverse_chol': 0.001,
        'ComputeInverse_eigh_full': 50.0}}}}
    # Cholesky variant: decomp negligible -> freq 1 is optimal
    assert autotune.prior_best_freq(predicted, 'inverse_dp',
                                    [1, 2, 4, 8]) == 1
    # eigen variant: decomp dominant -> max freq
    assert autotune.prior_best_freq(predicted, 'eigen_dp',
                                    [1, 2, 4, 8]) == 8
    assert autotune.prior_best_freq({'scenarios': {}}, 'eigen_dp',
                                    [1, 2]) is None


# ---------------------------------------------------------------------------
# the drift gate: veto on the modeled chip, advisory elsewhere
# ---------------------------------------------------------------------------

def _veto_harness(platform):
    """Probe window improves (passes the objective) but its measured
    'Precondition' marginal sits far outside the predicted band."""
    from kfac_pytorch_tpu import perfmodel
    pre = _FakePrecond(fac=1, kfac=4)
    ctl = autotune.KnobController(pre, window=4, settle=0,
                                  rel_improve=0.03, dwell_windows=1,
                                  cooldown=2, steady_every=0,
                                  tune=('kfac_update_freq',),
                                  freq_bounds=(1, 8),
                                  predicted=perfmodel.predict_block(),
                                  platform=platform, variant='eigen_dp')
    ctl._seeded = 'done'                      # isolate the gate from seeding
    for _ in range(4):                        # baseline window: 0.6 s steps
        ctl.record(('pred',), 0.6)
    assert ctl.state == 'probe'
    for _ in range(4):                        # probe window: 0.5 s -> improved
        ctl.record(('pred',), 0.5)
    return pre, ctl


def test_drift_veto_on_modeled_chip():
    """0.5 s measured Precondition vs a ~0.008 s predicted band on the
    modeled chip: the candidate improved the objective but is VETOED —
    the tuner can never silently regress a modeled phase."""
    pre, ctl = _veto_harness('TPU v5e')
    assert ctl.vetoes == 1 and ctl.commits == 0
    assert pre.kfac_update_freq != 8          # the vetoed value never stuck
    veto = next(d for d in ctl.decisions if d['kind'] == 'veto')
    assert veto['value'] == 8
    assert 'Precondition' in veto['violations']


def test_drift_gate_advisory_off_the_modeled_chip():
    """The SAME feed on an unmodeled platform commits: the band is
    advisory (violations counted, knob applied)."""
    pre, ctl = _veto_harness('cpu_fallback')
    assert ctl.vetoes == 0 and ctl.commits == 1
    assert ctl.advisory_violations >= 1
    assert pre.kfac_update_freq != 4          # the probe value stuck


def test_no_predicted_block_means_no_gate():
    pre = _FakePrecond(fac=1, kfac=4)
    ctl = autotune.KnobController(pre, window=4, settle=0,
                                  tune=('kfac_update_freq',))
    assert ctl._drift_veto({'Precondition': 99.0}, 'kfac_update_freq',
                           8) is False


# ---------------------------------------------------------------------------
# comm-mode decision (advisory, analytic)
# ---------------------------------------------------------------------------

def test_decide_comm_mode_amortization_crossover():
    vols = {'inverse': 1000.0, 'pred': 100.0}
    # at freq 1 the gather ships every step: pred is 10x cheaper
    mode, per_step = autotune.decide_comm_mode(vols, 1)
    assert mode == 'pred' and per_step['inverse'] == 1000.0
    # at freq 100 the gather amortizes to 10 B/step: inverse wins
    mode, per_step = autotune.decide_comm_mode(vols, 100)
    assert mode == 'inverse' and per_step['inverse'] == 10.0


def test_comm_mode_decision_recorded_once_from_plan():
    from kfac_pytorch_tpu import plan as plan_mod

    class _Bucket:
        n_rows, dim = 4, 16

    class _Pred:
        dg, da, k_per_dev = 8, 8, 2

    class _Plan:
        # the real byte model (the tuner must price both roads through
        # plan.comm_volume, never a restated formula)
        comm_volume = plan_mod.FactorPlan.comm_volume
        comm_mode = 'inverse'
        buckets = {16: _Bucket()}
        pred_groups = (_Pred(),)
        num_devices = 2

    pre = _FakePrecond(fac=1, kfac=8, comm_precision='fp32',
                       axis_name='batch')
    pre.plan = _Plan()
    pre.method = 'chol'
    pre.comm_mode = 'inverse'
    ctl = autotune.KnobController(pre, window=2, settle=0, tune=())
    for _ in range(4):
        ctl.record(('pred',), 0.01)
    assert ctl.comm_mode_choice in ('inverse', 'pred')
    assert len([d for d in ctl.decisions
                if d['kind'] == 'comm_mode']) == 1  # one-shot


# ---------------------------------------------------------------------------
# artifacts: decision log, counters, incident grammar
# ---------------------------------------------------------------------------

def test_decision_log_jsonl(tmp_path):
    log_path = tmp_path / 'sub' / 'autotune-decisions.jsonl'
    pre = _FakePrecond(fac=1, kfac=1)
    ctl = autotune.KnobController(pre, window=16, settle=1,
                                  dwell_windows=1, cooldown=2,
                                  steady_every=0,
                                  tune=('kfac_update_freq',),
                                  freq_bounds=(1, 8),
                                  decision_log=str(log_path))
    _feed(ctl, pre, _amortized, 400)
    lines = [json.loads(ln) for ln in
             log_path.read_text().splitlines()]
    kinds = [d['kind'] for d in lines]
    assert 'probe' in kinds and 'commit' in kinds and 'steady' in kinds
    assert all('window' in d and 'step' in d for d in lines)


def test_counts_and_registry_collector():
    from kfac_pytorch_tpu.obs import metrics
    pre = _FakePrecond(fac=1, kfac=1)
    ctl = autotune.KnobController(pre, window=16, settle=1,
                                  dwell_windows=1, cooldown=2,
                                  steady_every=0,
                                  tune=('kfac_update_freq',),
                                  freq_bounds=(1, 8))
    _feed(ctl, pre, _amortized, 400)
    c = ctl.counts()
    assert c['autotune_commits'] == ctl.commits > 0
    reg = metrics.Registry()
    ctl.collect(reg)
    snap = reg.snapshot()
    assert snap['autotune/kfac_update_freq'] == pre.kfac_update_freq
    assert snap['autotune/commits'] == ctl.commits


def test_autotune_log_lines_speak_the_incident_grammar():
    """The shared-grammar contract: the controller's run-log lines are
    parsed into typed events by incident.EVENT_PATTERNS — kfac-obs
    renders tuning timelines with zero new aggregate code."""
    from kfac_pytorch_tpu.resilience.incident import IncidentReport
    records = []

    class _Capture(logging.Handler):
        def emit(self, record):
            records.append(record.getMessage())

    log = logging.getLogger('test_autotune_grammar')
    log.setLevel(logging.INFO)
    log.addHandler(_Capture())
    try:
        pre = _FakePrecond(fac=1, kfac=1)
        ctl = autotune.KnobController(pre, window=16, settle=1,
                                      dwell_windows=1, cooldown=2,
                                      steady_every=0,
                                      tune=('kfac_update_freq',),
                                      freq_bounds=(1, 8), log=log)
        _feed(ctl, pre, _amortized, 400)
        # and one veto line (rig the gate through the harness)
        _, vctl = _veto_harness('TPU v5e')
        vctl.log = log
    finally:
        log.handlers.clear()
    rep = IncidentReport(host_id=0).scrape_lines(records)
    kinds = [e['kind'] for e in rep.events]
    assert 'autotune_probe' in kinds
    assert 'autotune_commit' in kinds
    assert 'autotune_steady' in kinds
    commit = next(e for e in rep.events if e['kind'] == 'autotune_commit')
    assert commit['knob'] == 'kfac_update_freq'
    steady = next(e for e in rep.events if e['kind'] == 'autotune_steady')
    assert int(steady['kfac']) == pre.kfac_update_freq


def test_veto_log_line_speaks_the_grammar():
    from kfac_pytorch_tpu.resilience.incident import IncidentReport
    records = []

    class _Capture(logging.Handler):
        def emit(self, record):
            records.append(record.getMessage())

    log = logging.getLogger('test_autotune_veto_grammar')
    log.setLevel(logging.INFO)
    log.addHandler(_Capture())
    try:
        from kfac_pytorch_tpu import perfmodel
        pre = _FakePrecond(fac=1, kfac=4)
        ctl = autotune.KnobController(
            pre, window=4, settle=0, rel_improve=0.03, dwell_windows=1,
            cooldown=2, steady_every=0, tune=('kfac_update_freq',),
            freq_bounds=(1, 8), predicted=perfmodel.predict_block(),
            platform='TPU v5e', variant='eigen_dp', log=log)
        ctl._seeded = 'done'
        for _ in range(4):
            ctl.record(('pred',), 0.6)
        for _ in range(4):
            ctl.record(('pred',), 0.5)
    finally:
        log.handlers.clear()
    rep = IncidentReport(host_id=0).scrape_lines(records)
    veto = [e for e in rep.events if e['kind'] == 'autotune_veto']
    assert veto and veto[0]['knob'] == 'kfac_update_freq'


# ---------------------------------------------------------------------------
# jax integration: variant-cache reuse + the mid-run wire-dtype switch
# ---------------------------------------------------------------------------

def _jax_trainer(variant='eigen_dp', ndev=1, kfac_freq=2,
                 comm_precision='fp32'):
    import flax.linen as linen
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import Mesh

    import kfac_pytorch_tpu as kfac
    from kfac_pytorch_tpu import nn as knn
    from kfac_pytorch_tpu import training

    class MLP(linen.Module):
        @linen.compact
        def __call__(self, x, train=True):
            x = knn.Dense(8, name='fc1')(x)
            x = linen.relu(x)
            return knn.Dense(3, name='fc2')(x)

    def ce(outputs, batch):
        return optax.softmax_cross_entropy_with_integer_labels(
            outputs, batch['label']).mean()

    rng = np.random.RandomState(0)
    batch = {'input': jnp.asarray(rng.randn(8, 5), jnp.float32),
             'label': jnp.asarray(rng.randint(0, 3, 8))}
    mesh = (Mesh(np.array(jax.devices()[:ndev]), ('batch',))
            if ndev > 1 else None)
    axis = 'batch' if ndev > 1 else None
    model = MLP()
    pre = kfac.KFAC(variant=variant, lr=0.05, damping=0.003,
                    kfac_update_freq=kfac_freq, num_devices=ndev,
                    axis_name=axis, bucket_fn=lambda d: 16,
                    comm_precision=comm_precision)
    tx = training.sgd(0.05, momentum=0.9)
    state = training.init_train_state(model, tx, pre,
                                      jax.random.PRNGKey(0),
                                      batch['input'])
    step = training.build_train_step(model, tx, pre, ce, axis_name=axis,
                                     mesh=mesh)
    return step, state, pre, batch


def test_freq_knob_changes_reuse_variant_cache():
    """The compile-count guard of the acceptance criteria: a tuner /
    straggler / schedule frequency move through the arbiter compiles
    NOTHING new — the frequency is host-side dispatch gating over the
    same variant set — while a ``comm_precision`` change clears the
    cache (the registered invalidator) so no stale program can keep
    the old wire dtype."""
    step, state, pre, batch = _jax_trainer(kfac_freq=2)
    arb = autotune.arbiter_for(pre)
    for _ in range(5):
        state, _ = step(state, batch, lr=0.05, damping=0.003)
    baseline = set(step.variants)
    assert baseline                        # warmed past every variant
    # a pure kfac_update_freq move (the tuner's bread and butter)
    # re-times the SAME dispatch combos: zero new programs
    arb.propose('tuner', kfac_update_freq=4)
    for _ in range(9):
        state, _ = step(state, batch, lr=0.05, damping=0.003)
    assert set(step.variants) == baseline, (
        sorted(map(str, set(step.variants) - baseline)))

    # the full trajectory a controller run would drive: tuner overrides
    # up and down the ladder, a schedule decay stretching the stats
    # cadence, a straggler emergency + recovery. The FIRST pass may
    # fill in dispatch combos the warmup never hit (stats-off steps) —
    # that is the bounded variant set completing, not churn
    def play(s):
        moves = (('tuner', {'kfac_update_freq': 1}),
                 ('schedule', {'freq_factor': 2.0, 'damping_factor': 0.5}),
                 ('straggler', {'stretch': 2}),
                 ('straggler', {'stretch': 1}),
                 ('tuner', {'kfac_update_freq': 4}),
                 ('schedule', {'freq_factor': 1.0, 'damping_factor': 1.0}))
        for source, kw in moves:
            arb.propose(source, **kw)
            for _ in range(6):
                s, _ = step(s, batch, lr=0.05, damping=0.003)
        return s

    state = play(state)
    grown = set(step.variants)
    assert baseline <= grown           # never cleared by a cadence move
    # the compile-count guard proper: REPLAYING the whole trajectory —
    # every cadence revisited — compiles exactly nothing
    state = play(state)
    assert set(step.variants) == grown, (
        sorted(map(str, set(step.variants) - grown)))


def test_mid_run_comm_precision_switch_fp32_bf16_fp32(tmp_path):
    """The PR 8 follow-on satellite: the tuner switches the wire dtype
    mid-run through the arbiter. fp32 -> bf16 must clear the compiled
    variants and seed a zero EF residual host-side; bf16 -> fp32 must
    drop it again; a checkpoint written in the bf16 era restores into
    a bf16-era trainer byte-exactly; and the post-switch fp32 state
    checkpoints/restores cleanly (structure = a never-compressed run)."""
    import jax
    import numpy as onp

    from kfac_pytorch_tpu.utils.checkpoint import (restore_checkpoint,
                                                   save_checkpoint)
    step, state, pre, batch = _jax_trainer(variant='eigen', ndev=2,
                                           kfac_freq=1)
    arb = autotune.arbiter_for(pre)
    for _ in range(3):
        state, m = step(state, batch, lr=0.05, damping=0.003)
    assert state.kfac_state.comm_err is None          # fp32: no residual
    # -> bf16 (what a tuner commit of comm_precision does)
    arb.propose('tuner', comm_precision='bf16')
    assert not step.variants                          # cache cleared
    for _ in range(3):
        state, m = step(state, batch, lr=0.05, damping=0.003)
    assert np.isfinite(float(m['loss']))
    assert state.kfac_state.comm_err is not None      # EF residual live
    assert pre._tracks_comm_err
    save_checkpoint(str(tmp_path / 'bf16'), 0, state)
    # -> back to fp32: residual dropped host-side, run keeps training
    arb.propose('tuner', comm_precision='fp32')
    assert not step.variants
    for _ in range(3):
        state, m = step(state, batch, lr=0.05, damping=0.003)
    assert np.isfinite(float(m['loss']))
    assert state.kfac_state.comm_err is None
    # the post-switch state checkpoints like a never-compressed run
    save_checkpoint(str(tmp_path / 'fp32'), 0, state)
    f32_step, f32_fresh, _, _ = _jax_trainer(variant='eigen', ndev=2,
                                             kfac_freq=1)
    restored = restore_checkpoint(str(tmp_path / 'fp32'), 0, f32_fresh)
    assert restored.kfac_state.comm_err is None
    restored = jax.tree.map(onp.asarray, restored)
    restored, m = f32_step(restored, batch, lr=0.05, damping=0.003)
    assert np.isfinite(float(m['loss']))
    # and the bf16-era checkpoint restores byte-exactly into a
    # bf16-configured trainer (the switch stranded nothing)
    b16_step, b16_fresh, _, _ = _jax_trainer(variant='eigen', ndev=2,
                                             kfac_freq=1,
                                             comm_precision='bf16')
    restored16 = restore_checkpoint(str(tmp_path / 'bf16'), 0, b16_fresh)
    assert restored16.kfac_state.comm_err is not None
    restored16 = jax.tree.map(onp.asarray, restored16)
    restored16, m = b16_step(restored16, batch, lr=0.05, damping=0.003)
    assert np.isfinite(float(m['loss']))


def test_controller_live_on_jax_trainer_converges():
    """End-to-end: the controller rides a REAL jitted trainer through
    ``record`` with a synthetic cost model keyed off the actual
    dispatched phase set — the knob lands on the planted optimum and
    every dispatch ran against a consistent compiled variant."""
    step, state, pre, batch = _jax_trainer(kfac_freq=1)
    ctl = autotune.KnobController(pre, window=8, settle=1,
                                  rel_improve=0.03, dwell_windows=1,
                                  cooldown=2, steady_every=0,
                                  tune=('kfac_update_freq',),
                                  freq_bounds=(1, 4))
    for _ in range(250):
        state, _ = step(state, batch, lr=0.05, damping=0.003)
        phases = step.last_phases
        cost = 0.41 if 'decomp' in phases else 0.01   # planted: amortize
        ctl.record(phases, cost)
        if ctl.state == 'steady':
            break
    assert pre.kfac_update_freq == 4
    assert ctl.state == 'steady'


# ---------------------------------------------------------------------------
# knob-arbiter state across generations (elastic shrink -> relaunch)
# ---------------------------------------------------------------------------
# An elastic shrink kills the trainer and relaunches it at the new
# world size: a NEW process, a NEW preconditioner, a NEW arbiter — but
# the tuner's artifacts must survive the generation boundary. Two
# contracts, previously only asserted within one generation:
#
# - the decision log is APPEND-only across relaunches (same
#   KFAC_TRACE_DIR -> same autotune-decisions.jsonl), so generation
#   1's trajectory lands after generation 0's instead of clobbering it;
# - a relaunch that restores the adopted knob values (the pod
#   supervisor re-exports them; elastic_resume re-applies state) gets
#   an arbiter whose BASE is the adopted cadence — a later schedule
#   advance composes incrementally from it, and the tuner does not
#   regress to the cold-start default.


def test_decision_log_appends_across_generations(tmp_path):
    log_path = tmp_path / 'trace' / 'autotune-decisions.jsonl'

    def make_ctl(pre):
        return autotune.KnobController(
            pre, window=16, settle=1, dwell_windows=1, cooldown=2,
            steady_every=0, tune=('kfac_update_freq',),
            freq_bounds=(1, 8), decision_log=str(log_path))

    # generation 0: converge to the planted optimum, decisions logged
    pre0 = _FakePrecond(fac=1, kfac=1)
    _feed(make_ctl(pre0), pre0, _amortized, 400)
    assert pre0.kfac_update_freq == 8
    gen0 = log_path.read_text().splitlines()
    assert any(json.loads(ln)['kind'] == 'commit' for ln in gen0)

    # shrink -> relaunch: fresh precond restored to the adopted knobs,
    # fresh controller pointed at the SAME decision log
    adopted = autotune._capture(pre0)
    pre1 = _FakePrecond(fac=adopted['fac_update_freq'],
                        kfac=adopted['kfac_update_freq'],
                        damping=adopted['damping'])
    _feed(make_ctl(pre1), pre1, _amortized, 120)

    lines = log_path.read_text().splitlines()
    # generation 0's trajectory is intact (append, never truncate) and
    # generation 1 wrote after it
    assert lines[:len(gen0)] == gen0
    assert len(lines) > len(gen0)
    # the relaunched window counter restarting (a fresh controller)
    # marks the generation boundary in the artifact itself
    gen1 = [json.loads(ln) for ln in lines[len(gen0):]]
    assert gen1[0]['window'] <= 1
    # and the adopted cadence holds — no regression to the cold default
    assert pre1.kfac_update_freq == 8


def test_arbiter_adopted_base_survives_relaunch_composition():
    # generation 0: the tuner committed an absolute override
    pre0 = _FakePrecond(fac=1, kfac=2, damping=0.04)
    arb0 = autotune.arbiter_for(pre0)
    arb0.propose('tuner', kfac_update_freq=8)
    assert pre0.kfac_update_freq == 8

    # relaunch: the restored knob values are the new construction-time
    # base (single-writer enforcement stays on through the guard)
    adopted = autotune._capture(pre0)
    pre1 = _GuardedPrecond(fac=adopted['fac_update_freq'],
                           kfac=adopted['kfac_update_freq'],
                           damping=adopted['damping'])
    arb1 = autotune.arbiter_for(pre1)
    assert arb1.base['kfac_update_freq'] == 8
    assert arb1.base['damping'] == pytest.approx(0.04)

    # an epoch-schedule advance in the new generation composes
    # INCREMENTALLY from the adopted base, not the old generation's
    # pre-tuner default (2)
    arb1.propose('schedule', freq_factor=2.0)
    assert pre1.kfac_update_freq == 16
    # elastic provenance records compose nothing (record-only lane)
    arb1.propose('elastic', gen=1, world=2)
    assert pre1.kfac_update_freq == 16
    assert arb1.records and arb1.records[-1]['gen'] == 1
    # a straggler stretch then multiplies the adopted-base schedule,
    # and recovery restores exactly the composed value
    arb1.propose('straggler', stretch=2)
    assert pre1.kfac_update_freq == 32
    arb1.propose('straggler', stretch=1)
    assert pre1.kfac_update_freq == 16


# ---------------------------------------------------------------------------
# the decomp_impl ladder (the inverse-free lane of ROADMAP item 5)
# ---------------------------------------------------------------------------

class _DecompPrecond(_FakePrecond):
    """Fake preconditioner carrying the decomp_impl knob surface."""

    def __init__(self, method='cholesky', decomp_impl='xla', **kw):
        super().__init__(**kw)
        self.method = method
        self.decomp_impl = decomp_impl


def test_decomp_impls_restated_tuple_matches_preconditioner():
    # autotune must stay stdlib-importable, so it restates the canon
    from kfac_pytorch_tpu import preconditioner
    assert autotune.DECOMP_IMPLS == preconditioner.DECOMP_IMPLS


def test_controller_decomp_impl_commits_planted_optimum():
    """NS-ladder commit under a planted optimum: the newton_schulz rung
    is genuinely faster, the controller probes it, commits, and goes
    steady on it — the decomp_impl analog of the freq planted-optimum
    tests."""
    pre = _DecompPrecond(method='cholesky', decomp_impl='xla', kfac=4)
    ctl = autotune.KnobController(pre, window=8, settle=1,
                                  rel_improve=0.03, dwell_windows=1,
                                  cooldown=2, steady_every=0,
                                  tune=('decomp_impl',))

    def model(F, i):
        # cholesky refresh costs 0.4; the NS rung replaces it with 0.1
        decomp = 0.4 if pre.decomp_impl == 'xla' else 0.1
        if i == 0:
            return ('pred', 'stats', 'decomp'), 0.01 + decomp
        return ('pred',), 0.01

    _feed(ctl, pre, model, 200)
    assert pre.decomp_impl == 'newton_schulz'
    assert ctl.state == 'steady'
    assert ctl.commits == 1
    assert ctl.vetoes == 0                    # zero spurious vetoes
    kinds = [d['kind'] for d in ctl.decisions]
    assert 'commit' in kinds


def test_controller_decomp_impl_reverts_when_slower():
    """The revert side of the ladder: an iterative rung that does NOT
    beat the cold kernel reverts and cools down — the knob never
    flaps."""
    pre = _DecompPrecond(method='eigh', decomp_impl='xla', kfac=4)
    ctl = autotune.KnobController(pre, window=8, settle=1,
                                  rel_improve=0.03, dwell_windows=1,
                                  cooldown=3, steady_every=0,
                                  tune=('decomp_impl',))

    def model(F, i):
        # subspace is SLOWER here (the CPU-like regime)
        decomp = 0.2 if pre.decomp_impl == 'xla' else 0.35
        if i == 0:
            return ('pred', 'stats', 'decomp'), 0.01 + decomp
        return ('pred',), 0.01

    _feed(ctl, pre, model, 200)
    assert pre.decomp_impl == 'xla'           # reverted, stays cold
    assert ctl.state == 'steady'
    assert ctl.commits == 0
    assert ctl.reverts >= 1


def test_quality_gate_vetoes_accuracy_regressing_rung():
    """The numerical-health gate: a rung that IS faster but raises the
    badness counter during its probe window never commits (counted as
    a veto, decision log says 'quality'), and the controller settles
    steady on the original knob."""
    pre = _DecompPrecond(method='cholesky', decomp_impl='xla', kfac=4)
    events = {'n': 0}
    ctl = autotune.KnobController(pre, window=8, settle=1,
                                  rel_improve=0.03, dwell_windows=1,
                                  cooldown=2, steady_every=0,
                                  tune=('decomp_impl',),
                                  quality_gate=lambda: events['n'])

    def model(F, i):
        if pre.decomp_impl == 'newton_schulz':
            events['n'] += 1                  # health events every step
            decomp = 0.05                     # ...but much faster
        else:
            decomp = 0.4
        if i == 0:
            return ('pred', 'stats', 'decomp'), 0.01 + decomp
        return ('pred',), 0.01

    _feed(ctl, pre, model, 300)
    assert pre.decomp_impl == 'xla'           # the fast-but-wrong rung
    assert ctl.commits == 0                   # never committed
    assert ctl.quality_vetoes >= 1
    assert ctl.state == 'steady'
    vetoes = [d for d in ctl.decisions if d['kind'] == 'veto']
    assert vetoes and vetoes[0].get('reason') == 'quality'
    assert ctl.report()['quality_vetoes'] == ctl.quality_vetoes


def test_arbiter_decomp_impl_is_trace_affecting():
    """A decomp_impl change fires the variant-cache invalidators (the
    kernel is baked into the traced programs) and direct external
    writes are adopted as the new base, like comm_precision."""
    pre = _DecompPrecond(method='eigh', decomp_impl='xla')
    arb = autotune.arbiter_for(pre)
    cleared = []
    arb.add_invalidator(lambda: cleared.append(1))
    arb.propose('tuner', decomp_impl='subspace')
    assert pre.decomp_impl == 'subspace'
    assert cleared == [1]
    with pytest.raises(ValueError, match='decomp_impl'):
        arb.propose('tuner', decomp_impl='bogus')
    # external write adopted as base, tuner override dropped
    pre.decomp_impl = 'xla'
    arb.adopt_external()
    assert arb.base['decomp_impl'] == 'xla'
    assert 'decomp_impl' not in arb.tuner


def test_decomp_impl_seeded_from_perfmodel_prior():
    """On the modeled chip the fenced eigh constants say the iterative
    rung wins by orders of magnitude: the controller seeds
    decomp_impl from the perfmodel prior before any measurement."""
    from kfac_pytorch_tpu import perfmodel
    block = perfmodel.predict_block()
    pre = _DecompPrecond(method='eigh', decomp_impl='xla', kfac=4)
    ctl = autotune.KnobController(pre, window=8, settle=1,
                                  tune=('decomp_impl',),
                                  predicted=block)
    ctl.record(('pred',), 0.01)               # first record triggers seed
    assert pre.decomp_impl == 'subspace'
    seeds = [d for d in ctl.decisions if d['kind'] == 'seed']
    assert seeds and seeds[0]['knob'] == 'decomp_impl'
    # the priors themselves: iterative rungs orders under the fenced
    # QDWH seconds on the modeled chip
    priors = perfmodel.decomp_impl_priors(block, 'eigh')
    assert priors['subspace'] < 0.1 * priors['xla']


# ---------------------------------------------------------------------------
# the capture_impl ladder (fused Pallas capture kernels, ISSUE 19)
# ---------------------------------------------------------------------------

class _CapturePrecond(_FakePrecond):
    """Fake preconditioner carrying the capture_impl knob surface."""

    def __init__(self, capture_impl='xla', **kw):
        super().__init__(**kw)
        self.capture_impl = capture_impl


def test_capture_impls_restated_tuple_matches_preconditioner():
    # autotune must stay stdlib-importable, so it restates the canon
    from kfac_pytorch_tpu import preconditioner
    assert autotune.CAPTURE_IMPLS == preconditioner.CAPTURE_IMPLS
    # the ladder probes concrete rungs only ('auto' is a policy, not a
    # program) and every rung is a valid knob value
    assert 'auto' not in autotune.CAPTURE_LADDER
    assert set(autotune.CAPTURE_LADDER) < set(autotune.CAPTURE_IMPLS)


def test_controller_capture_impl_commits_planted_optimum():
    """Fused-capture commit under a planted optimum: the pallas rung is
    genuinely faster, the controller probes it, commits, and goes
    steady on it — the capture analog of the decomp ladder tests."""
    pre = _CapturePrecond(capture_impl='xla', kfac=4)
    ctl = autotune.KnobController(pre, window=8, settle=1,
                                  rel_improve=0.03, dwell_windows=1,
                                  cooldown=2, steady_every=0,
                                  tune=('capture_impl',))

    def model(F, i):
        # unfused capture costs 0.4/window; the fused kernels cost 0.1
        stats = 0.4 if pre.capture_impl == 'xla' else 0.1
        if i == 0:
            return ('pred', 'stats', 'decomp'), 0.01 + stats
        return ('pred',), 0.01

    _feed(ctl, pre, model, 200)
    assert pre.capture_impl == 'pallas'
    assert ctl.state == 'steady'
    assert ctl.commits == 1
    assert ctl.vetoes == 0                    # zero spurious vetoes
    kinds = [d['kind'] for d in ctl.decisions]
    assert 'commit' in kinds


def test_controller_capture_impl_reverts_when_slower():
    """The revert side: a fused rung that does NOT beat the unfused
    capture reverts and cools down — the knob never flaps."""
    pre = _CapturePrecond(capture_impl='xla', kfac=4)
    ctl = autotune.KnobController(pre, window=8, settle=1,
                                  rel_improve=0.03, dwell_windows=1,
                                  cooldown=3, steady_every=0,
                                  tune=('capture_impl',))

    def model(F, i):
        # fused is SLOWER here (tiny F: fusion overhead dominates)
        stats = 0.2 if pre.capture_impl == 'xla' else 0.35
        if i == 0:
            return ('pred', 'stats', 'decomp'), 0.01 + stats
        return ('pred',), 0.01

    _feed(ctl, pre, model, 200)
    assert pre.capture_impl == 'xla'          # reverted, stays unfused
    assert ctl.state == 'steady'
    assert ctl.commits == 0
    assert ctl.reverts >= 1


def test_quality_gate_vetoes_regressing_capture_rung():
    """A capture rung that IS faster but raises the badness counter
    during its probe window never commits (quality veto) — the same
    numerical-health gate the decomp ladder gets."""
    pre = _CapturePrecond(capture_impl='xla', kfac=4)
    events = {'n': 0}
    ctl = autotune.KnobController(pre, window=8, settle=1,
                                  rel_improve=0.03, dwell_windows=1,
                                  cooldown=2, steady_every=0,
                                  tune=('capture_impl',),
                                  quality_gate=lambda: events['n'])

    def model(F, i):
        if pre.capture_impl == 'pallas':
            events['n'] += 1                  # health events every step
            stats = 0.05                      # ...but much faster
        else:
            stats = 0.4
        if i == 0:
            return ('pred', 'stats', 'decomp'), 0.01 + stats
        return ('pred',), 0.01

    _feed(ctl, pre, model, 300)
    assert pre.capture_impl == 'xla'          # the fast-but-wrong rung
    assert ctl.commits == 0
    assert ctl.quality_vetoes >= 1
    assert ctl.state == 'steady'
    vetoes = [d for d in ctl.decisions if d['kind'] == 'veto']
    assert vetoes and vetoes[0].get('reason') == 'quality'


def test_arbiter_capture_impl_is_trace_affecting():
    """A capture_impl change fires the variant-cache invalidators (the
    capture kernels are baked into the traced programs) and direct
    external writes are adopted as the new base."""
    pre = _CapturePrecond(capture_impl='xla')
    arb = autotune.arbiter_for(pre)
    cleared = []
    arb.add_invalidator(lambda: cleared.append(1))
    arb.propose('tuner', capture_impl='pallas')
    assert pre.capture_impl == 'pallas'
    assert cleared == [1]
    with pytest.raises(ValueError, match='capture_impl'):
        arb.propose('tuner', capture_impl='bogus')
    # external write adopted as base, tuner override dropped
    pre.capture_impl = 'xla'
    arb.adopt_external()
    assert arb.base['capture_impl'] == 'xla'
    assert 'capture_impl' not in arb.tuner


def test_capture_impl_hidden_when_legacy_none():
    """capture_impl=None is the legacy capture path: the rung is
    invisible to the tuner — no seed, no candidates, no knob writes —
    so pre-ISSUE-19 configs tune exactly as before."""
    pre = _FakePrecond(kfac=4)                # no capture_impl attr
    ctl = autotune.KnobController(pre, window=8, settle=1,
                                  rel_improve=0.03, dwell_windows=1,
                                  cooldown=2, steady_every=0,
                                  tune=('capture_impl',))
    _feed(ctl, pre, _amortized, 200)
    assert getattr(pre, 'capture_impl', None) is None
    assert ctl.commits == 0
    assert not any(d.get('knob') == 'capture_impl' for d in ctl.decisions)


def test_controller_capture_auto_probes_the_other_rung():
    """'auto' resolves to the fused rung as the effective program, so
    the only candidate is 'xla' — and when unfused is genuinely faster
    the controller commits the concrete rung."""
    pre = _CapturePrecond(capture_impl='auto', kfac=4)
    ctl = autotune.KnobController(pre, window=8, settle=1,
                                  rel_improve=0.03, dwell_windows=1,
                                  cooldown=2, steady_every=0,
                                  tune=('capture_impl',))

    def model(F, i):
        eff = ('pallas' if pre.capture_impl == 'auto'
               else pre.capture_impl)
        stats = 0.4 if eff == 'pallas' else 0.1
        if i == 0:
            return ('pred', 'stats', 'decomp'), 0.01 + stats
        return ('pred',), 0.01

    _feed(ctl, pre, model, 200)
    assert pre.capture_impl == 'xla'
    assert ctl.commits == 1


def test_capture_impl_seeded_from_perfmodel_prior():
    """On the modeled chip the fused capture kernels halve the factor
    phase's HBM bytes: the controller seeds capture_impl from the
    perfmodel prior before any measurement."""
    from kfac_pytorch_tpu import perfmodel
    block = perfmodel.predict_block()
    pre = _CapturePrecond(capture_impl='xla', kfac=4)
    ctl = autotune.KnobController(pre, window=8, settle=1,
                                  tune=('capture_impl',),
                                  predicted=block)
    ctl.record(('pred',), 0.01)               # first record triggers seed
    assert pre.capture_impl == 'pallas'
    seeds = [d for d in ctl.decisions if d['kind'] == 'seed']
    assert seeds and seeds[0]['knob'] == 'capture_impl'
    # the prior itself: fused strictly under unfused on the HBM-bound
    # factor phase (CAPTURE_FUSION_BYTES_FACTOR halves the bytes term)
    priors = perfmodel.capture_impl_priors(block)
    assert priors['pallas'] < priors['xla']
