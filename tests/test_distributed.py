"""Distributed semantics on a virtual 8-device CPU mesh.

Validates the core SPMD claims of the design (plan.py / engine.py):

1. MPD variants under shard_map == single-device full-batch run (factor
   pmean ≙ the reference allreduce, inv.py:94-103).
2. DP variants use the *owner's local-batch* statistics only — no factor
   communication (the paper's contribution, inv_dp.py:60-95).
3. The sharded factor state rows hold exactly what the owner computed.
"""

import functools

import flax.linen as linen
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

import kfac_pytorch_tpu as kfac
from kfac_pytorch_tpu import capture, ops
from kfac_pytorch_tpu import nn as knn


class MLP(linen.Module):
    @linen.compact
    def __call__(self, x):
        x = knn.Dense(8, name='fc1')(x)
        x = linen.relu(x)
        x = knn.Dense(3, name='fc2')(x)
        return x


def _data(b=8):
    rng = np.random.RandomState(0)
    return (jnp.asarray(rng.randn(b, 5), jnp.float32),
            jnp.asarray(rng.randn(b, 3), jnp.float32))


def _capture_full(model, variables, x, y):
    loss_fn = lambda out: jnp.mean((out - y) ** 2)
    return capture.value_and_grad_with_capture(model, loss_fn, variables, x)


def _sharded_step(model, precond, mesh, axis):
    pspecs = precond.state_pspecs(axis)

    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(), pspecs, P(axis), P(axis)),
        out_specs=(P(), pspecs))
    def step(params, state, x, y):
        loss_fn = lambda out: jnp.mean((out - y) ** 2)
        _, _, grads, acts, gs, _ = capture.value_and_grad_with_capture(
            model, loss_fn, {'params': params}, x, axis_name=axis)
        # autodiff already psummed param grads across the axis
        grads = kfac.parallel.average_grads(grads, axis)
        return precond.step(state, grads, acts, gs, axis_name=axis)

    return step


@pytest.mark.parametrize('ndev,distribute', [(2, False), (8, None)])
def test_mpd_eigen_matches_single_device(ndev, distribute):
    """Sharded MPD == full-batch single device (also exercises the
    factor-wise split auto rule when ndev > #layers, eigen.py:66-71)."""
    model = MLP()
    x, y = _data(8)
    variables = capture.init(model, jax.random.PRNGKey(0), x)
    metas = capture.collect_layer_meta(model, variables, x)

    p1 = kfac.KFAC(variant='eigen', num_devices=1, axis_name=None,
                   bucket_fn=lambda d: 16)
    p1.setup(metas)
    _, _, grads, acts, gs, _ = _capture_full(model, variables, x, y)
    want, _ = p1.step(p1.init(), grads, acts, gs)

    mesh = Mesh(np.array(jax.devices()[:ndev]), ('batch',))
    pN = kfac.KFAC(variant='eigen', num_devices=ndev, axis_name='batch',
                   bucket_fn=lambda d: 16,
                   distribute_layer_factors=distribute)
    pN.setup(metas)
    if ndev == 8:
        assert pN.plan is not None
    step = _sharded_step(model, pN, mesh, 'batch')
    got, _ = step(variables['params'], pN.init(), x, y)
    for name in metas:
        np.testing.assert_allclose(np.asarray(got[name]['kernel']),
                                   np.asarray(want[name]['kernel']),
                                   rtol=1e-3, atol=1e-4)
        np.testing.assert_allclose(np.asarray(got[name]['bias']),
                                   np.asarray(want[name]['bias']),
                                   rtol=1e-3, atol=1e-4)


@pytest.mark.parametrize('variant', ['eigen_dp', 'inverse_dp'])
def test_dp_uses_owner_local_stats(variant):
    """DP preds must come from owner-shard-only factors; oracle recomputes
    per-shard stats on the host."""
    ndev = 2
    lr, damping, decay, kl = 0.1, 0.003, 0.95, 0.001
    model = MLP()
    x, y = _data(8)
    variables = capture.init(model, jax.random.PRNGKey(0), x)
    metas = capture.collect_layer_meta(model, variables, x)

    mesh = Mesh(np.array(jax.devices()[:ndev]), ('batch',))
    pN = kfac.KFAC(variant=variant, num_devices=ndev, axis_name='batch',
                   bucket_fn=lambda d: 16, lr=lr, damping=damping,
                   factor_decay=decay, kl_clip=kl)
    pN.setup(metas)
    step = _sharded_step(model, pN, mesh, 'batch')
    got, new_state = step(variables['params'], pN.init(), x, y)

    # --- host oracle ----------------------------------------------------
    # per-shard capture (local loss = mean over local batch)
    shard_stats = []
    for d in range(ndev):
        xs, ys = x[d * 4:(d + 1) * 4], y[d * 4:(d + 1) * 4]
        _, _, sg, sa, sgs, _ = _capture_full(model, variables, xs, ys)
        shard_stats.append((sg, sa, sgs))
    # full-batch grads = pmean of shard grads
    grads = jax.tree.map(
        lambda *g: sum(np.asarray(v) for v in g) / ndev,
        *[s[0] for s in shard_stats])

    names = list(metas)
    preds, gmats = [], []
    for i, name in enumerate(names):
        owner = i % ndev  # round-robin (inv.py:62-77)
        _, sa, sgs = shard_stats[owner]
        A = np.asarray(ops.compute_a_dense(sa[name]['a'], True))
        G = np.asarray(ops.compute_g_dense(sgs[name]['g'], True))
        mA = decay * A + (1 - decay) * np.eye(A.shape[0], dtype=np.float32)
        mG = decay * G + (1 - decay) * np.eye(G.shape[0], dtype=np.float32)
        gm = np.concatenate([np.asarray(grads[name]['kernel']).T,
                             np.asarray(grads[name]['bias'])[:, None]], 1)
        if variant == 'eigen_dp':
            dA, QA = np.linalg.eigh(mA)
            dG, QG = np.linalg.eigh(mG)
            dA, dG = dA * (dA > 1e-10), dG * (dG > 1e-10)
            v2 = (QG.T @ gm @ QA) / (np.outer(dG, dA) + damping)
            preds.append(QG @ v2 @ QA.T)
        else:
            pi = np.sqrt((np.trace(mA) / mA.shape[0])
                         / (np.trace(mG) / mG.shape[0]))
            Ad = mA + np.sqrt(damping) * pi * np.eye(mA.shape[0])
            Gd = mG + np.sqrt(damping) / pi * np.eye(mG.shape[0])
            preds.append(np.linalg.inv(Gd) @ gm @ np.linalg.inv(Ad))
        gmats.append(gm)
    vg = sum(float(np.sum(p * g)) for p, g in zip(preds, gmats)) * lr ** 2
    nu = min(1.0, np.sqrt(kl / abs(vg)))

    for name, pred in zip(names, preds):
        gk = np.concatenate([np.asarray(got[name]['kernel']).T,
                             np.asarray(got[name]['bias'])[:, None]], 1)
        np.testing.assert_allclose(gk, pred * nu, rtol=1e-3, atol=1e-4)

    # --- sharded state rows hold the owner's local running averages -----
    b16 = np.asarray(new_state.factors['16'])
    # bucket rows are device-major: dev0 [fc1A, fc1G], dev1 [fc2A, fc2G]
    _, sa0, sgs0 = shard_stats[0]
    A0 = np.asarray(ops.compute_a_dense(sa0['fc1']['a'], True))
    want_row0 = decay * np.asarray(ops.identity_pad(jnp.asarray(A0), 16)) \
        + (1 - decay) * np.eye(16, dtype=np.float32)
    np.testing.assert_allclose(b16[0], want_row0, rtol=1e-4, atol=1e-5)
