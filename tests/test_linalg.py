"""Batched symmetric linalg + exactness of identity padding."""

import jax
import numpy as np
import jax.numpy as jnp
import pytest

from kfac_pytorch_tpu import ops

pytestmark = pytest.mark.core


def _spd(rng, *shape):
    a = rng.randn(*shape).astype(np.float32)
    return a @ np.swapaxes(a, -1, -2) + shape[-1] * np.eye(shape[-1],
                                                           dtype=np.float32)


def test_psd_inverse_batched():
    rng = np.random.RandomState(0)
    x = _spd(rng, 5, 8, 8)
    inv = np.asarray(ops.psd_inverse(jnp.asarray(x)))
    np.testing.assert_allclose(inv, np.linalg.inv(x), rtol=1e-3, atol=1e-4)


def test_sym_eig_reconstructs():
    rng = np.random.RandomState(1)
    x = _spd(rng, 3, 6, 6)
    d, q = ops.sym_eig(jnp.asarray(x))
    rec = np.asarray(q) @ (np.asarray(d)[..., None] * np.swapaxes(np.asarray(q), -1, -2))
    np.testing.assert_allclose(rec, x, rtol=1e-3, atol=1e-3)


def test_jacobi_eigh_matches_numpy():
    """Matmul-form Jacobi sweeps vs numpy eigh: eigenvalues, orthonormal
    eigenvectors, reconstruction — batched, single, and odd dims."""
    rng = np.random.RandomState(3)
    for shape in [(4, 16, 16), (2, 64, 64), (33, 33), (1, 9, 9)]:
        x = _spd(rng, *shape) / shape[-1]
        w, v = ops.jacobi_eigh(jnp.asarray(x))
        w, v = np.asarray(w), np.asarray(v)
        n = shape[-1]
        w_ref = np.linalg.eigvalsh(x)
        scale = np.abs(w_ref).max()
        np.testing.assert_allclose(w, w_ref, atol=1e-4 * scale, rtol=1e-4)
        # ascending order, orthonormal, reconstructs
        assert (np.diff(w, axis=-1) >= -1e-5 * scale).all()
        vtv = np.swapaxes(v, -1, -2) @ v
        np.testing.assert_allclose(vtv, np.broadcast_to(np.eye(n), vtv.shape),
                                   atol=5e-5)
        rec = v @ (w[..., None] * np.swapaxes(v, -1, -2))
        np.testing.assert_allclose(rec, x, atol=1e-4 * scale, rtol=1e-4)


def test_jacobi_paired_rotation_matches_dense():
    """'paired' (permute pairs adjacent, rotate 2x2 blocks elementwise)
    and 'dense' (packed-J matmuls) are two evaluations of the same
    rotation sequence — results must agree to rounding noise."""
    rng = np.random.RandomState(7)
    for shape in [(2, 16, 16), (1, 30, 30), (21, 21)]:
        x = _spd(rng, *shape) / shape[-1]
        wd, vd = ops.jacobi_eigh(jnp.asarray(x), rotate='dense')
        wp, vp = ops.jacobi_eigh(jnp.asarray(x), rotate='paired')
        np.testing.assert_allclose(np.asarray(wd), np.asarray(wp),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.abs(np.asarray(vd)),
                                   np.abs(np.asarray(vp)),
                                   rtol=1e-3, atol=1e-3)
    import pytest
    with pytest.raises(ValueError):
        ops.jacobi_eigh(jnp.eye(4), rotate='nope')


def test_sym_eig_jacobi_impl_dispatch():
    rng = np.random.RandomState(4)
    x = _spd(rng, 2, 12, 12)
    d1, q1 = ops.sym_eig(jnp.asarray(x), impl='jacobi')
    d2, q2 = ops.sym_eig(jnp.asarray(x), impl='xla')
    np.testing.assert_allclose(np.asarray(d1), np.asarray(d2),
                               rtol=1e-4, atol=1e-3)
    # same eigenspaces: |Q1^T Q2| is a signed permutation (identity here,
    # eigenvalues are distinct and both sorted ascending)
    m = np.abs(np.swapaxes(np.asarray(q1), -1, -2) @ np.asarray(q2))
    np.testing.assert_allclose(m, np.broadcast_to(np.eye(12), m.shape),
                               atol=1e-2)


def test_clamp_eigvals():
    d = jnp.asarray([-1.0, 1e-12, 0.5])
    out = np.asarray(ops.clamp_eigvals(d, 1e-10))
    np.testing.assert_allclose(out, [0.0, 0.0, 0.5])


def test_add_scaled_identity_vector():
    x = jnp.zeros((2, 3, 3))
    out = np.asarray(ops.add_scaled_identity(x, jnp.asarray([1.0, 2.0])))
    np.testing.assert_allclose(out[0], np.eye(3))
    np.testing.assert_allclose(out[1], 2 * np.eye(3))


def test_masked_trace():
    x = jnp.asarray(np.diag([1.0, 2.0, 3.0, 4.0]).astype(np.float32))
    assert float(ops.masked_trace(x, 2)) == 3.0
    batch = jnp.stack([x, x])
    np.testing.assert_allclose(
        np.asarray(ops.masked_trace(batch, jnp.asarray([2, 3]))), [3.0, 6.0])


def test_identity_pad_exact_for_eigen_pred():
    """Padding factors with identity must not change the preconditioned
    gradient (the exactness claim in ops/linalg.py)."""
    rng = np.random.RandomState(2)
    da, dg, pad = 5, 4, 3
    A = _spd(rng, da, da)
    G = _spd(rng, dg, dg)
    grad = rng.randn(dg, da).astype(np.float32)
    damping = 0.01

    def eigen_pred(A, G, grad):
        dA, QA = np.linalg.eigh(A)
        dG, QG = np.linalg.eigh(G)
        v1 = QG.T @ grad @ QA
        v2 = v1 / (np.outer(dG, dA) + damping)
        return QG @ v2 @ QA.T

    want = eigen_pred(A, G, grad)
    Ap = np.asarray(ops.identity_pad(jnp.asarray(A), da + pad))
    Gp = np.asarray(ops.identity_pad(jnp.asarray(G), dg + pad))
    gp = np.zeros((dg + pad, da + pad), np.float32)
    gp[:dg, :da] = grad
    got = eigen_pred(Ap, Gp, gp)[:dg, :da]
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    # explicit-inverse path
    want_inv = np.linalg.inv(G + 0.1 * np.eye(dg)) @ grad @ np.linalg.inv(
        A + 0.1 * np.eye(da))
    got_inv = (np.linalg.inv(Gp + 0.1 * np.eye(dg + pad)) @ gp
               @ np.linalg.inv(Ap + 0.1 * np.eye(da + pad)))[:dg, :da]
    np.testing.assert_allclose(got_inv, want_inv, rtol=1e-4, atol=1e-5)


def test_subspace_eigh_tracks_drifting_factor():
    """Orthogonal-iteration warm eigh (the MXU-shaped warm kernel): from
    the PREVIOUS factor's eigenbasis, one tracking step on the drifted
    factor must deliver an orthonormal basis whose Rayleigh spectrum
    reconstructs the new factor — including a rank-deficient factor (the
    K-FAC regime) and the damped-inverse operator the preconditioner
    actually applies."""
    rng = np.random.RandomState(11)
    for shape, rank in [((3, 24, 24), None), ((2, 32, 32), 8)]:
        n = shape[-1]
        if rank is None:
            x0 = _spd(rng, *shape) / n
        else:  # rank-deficient: a a^T with a [*, n, rank]
            a = rng.randn(*shape[:-1], rank).astype(np.float32)
            x0 = a @ np.swapaxes(a, -1, -2) / n
        _, q0 = np.linalg.eigh(x0)
        drift = _spd(rng, *shape) / n
        x1 = (0.95 * x0 + 0.05 * drift).astype(np.float32)

        w, q = ops.subspace_eigh(jnp.asarray(x1), jnp.asarray(q0))
        w, q = np.asarray(w), np.asarray(q)
        qtq = np.swapaxes(q, -1, -2) @ q
        np.testing.assert_allclose(
            qtq, np.broadcast_to(np.eye(n), qtq.shape), atol=5e-5)
        rec = q @ (w[..., None] * np.swapaxes(q, -1, -2))
        scale = np.abs(x1).max()
        assert np.max(np.abs(rec - x1)) < 0.04 * scale, \
            np.max(np.abs(rec - x1)) / scale
        # the operator that matters: (X + lam I)^-1 via the decomposition.
        # The rank-deficient case concentrates its error in a tight
        # near-degenerate eigenvalue cluster whose members the tracker
        # deliberately leaves mixed (Tikhonov-suppressed rotations); with
        # damping below the cluster scale the inverse amplifies that, so
        # its bound is looser — the spectrum itself must still be right.
        lam = 1e-2
        op = q @ (np.swapaxes(q, -1, -2) /
                  (np.maximum(w, 0) + lam)[..., :, None])
        exact = np.linalg.inv(x1 + lam * np.eye(n, dtype=np.float32))
        err = (np.abs(op - exact).max(axis=(-2, -1))
               / np.abs(exact).max(axis=(-2, -1)))
        assert (err < (0.05 if rank is None else 0.25)).all(), err
        w_true = np.linalg.eigvalsh(x1)
        w_scale = np.abs(w_true).max()
        assert np.max(np.abs(np.sort(w, axis=-1) - w_true)) < 0.02 * w_scale
        # more steps -> tighter reconstruction
        w3, q3 = ops.subspace_eigh(jnp.asarray(x1), jnp.asarray(q0),
                                   steps=3)
        rec3 = (np.asarray(q3) @ (np.asarray(w3)[..., None]
                                  * np.swapaxes(np.asarray(q3), -1, -2)))
        assert np.max(np.abs(rec3 - x1)) <= np.max(np.abs(rec - x1)) + 1e-5


def test_sym_eig_subspace_dispatch():
    """impl='subspace' falls back to XLA QDWH with no basis (cold) and
    runs the tracker when a basis exists; 'auto' resolves to subspace."""
    rng = np.random.RandomState(12)
    x0 = _spd(rng, 2, 16, 16) / 16
    d_cold, q_cold = ops.sym_eig(jnp.asarray(x0), impl='subspace')
    d_xla, q_xla = ops.sym_eig(jnp.asarray(x0), impl='xla')
    np.testing.assert_allclose(np.asarray(d_cold), np.asarray(d_xla),
                               rtol=1e-5, atol=1e-6)
    x1 = 0.97 * x0 + 0.03 * _spd(rng, 2, 16, 16) / 16
    d1, q1 = ops.sym_eig(jnp.asarray(x1), impl='subspace', basis=q_cold)
    rec = (np.asarray(q1) @ (np.asarray(d1)[..., None]
                             * np.swapaxes(np.asarray(q1), -1, -2)))
    np.testing.assert_allclose(rec, x1, atol=0.04 * np.abs(x1).max())
    import os
    assert os.environ.get('KFAC_EIGH_IMPL', 'xla') == 'xla'  # test env
    d_auto, _ = ops.sym_eig(jnp.asarray(x1), impl='auto', basis=q_cold)
    np.testing.assert_allclose(np.asarray(d_auto), np.asarray(d1),
                               rtol=1e-5, atol=1e-6)


def test_subspace_eigh_constant_diagonal_slot_no_nan():
    """A batch slot whose factor is an exact multiple of identity (the
    all-padding bucket-slot case) has zero Rayleigh spread — the
    regularized rotation must come out 0, not 0/0 = NaN."""
    x = jnp.stack([2.0 * jnp.eye(8), jnp.zeros((8, 8))])
    q0 = jnp.stack([jnp.eye(8), jnp.eye(8)])
    w, q = ops.subspace_eigh(x, q0)
    assert np.isfinite(np.asarray(w)).all()
    assert np.isfinite(np.asarray(q)).all()
    np.testing.assert_allclose(np.asarray(w)[0], 2.0, atol=1e-5)
    np.testing.assert_allclose(np.asarray(w)[1], 0.0, atol=1e-5)
    qtq = np.swapaxes(np.asarray(q), -1, -2) @ np.asarray(q)
    np.testing.assert_allclose(qtq, np.broadcast_to(np.eye(8), qtq.shape),
                               atol=1e-4)


def test_subspace_eigh_chained_tracking_no_accumulation():
    """50 chained warm fulls over a running-average factor stream (the
    cold_restart_every window at stat_decay=0.95): the damped-inverse
    operator error vs exact eigh must stay small THROUGHOUT — tracking
    error must not accumulate across the chain."""
    rng = np.random.RandomState(0)
    n, B, lam = 48, 24, 0.03

    A = np.eye(n, dtype=np.float32)
    q = jnp.asarray(np.eye(n, dtype=np.float32))
    track = jax.jit(lambda a, b: ops.subspace_eigh(a, b))
    errs = []
    for _ in range(50):
        a = rng.randn(B, n).astype(np.float32)
        A = 0.95 * A + 0.05 * (a.T @ a) / B
        w_ex, q_ex = np.linalg.eigh(A)
        wj, q = track(jnp.asarray(A), q)
        w, qn = np.asarray(wj), np.asarray(q)
        op = qn @ (qn.T / (np.maximum(w, 0) + lam)[:, None])
        ex = q_ex @ (q_ex.T / (np.maximum(w_ex, 0) + lam)[:, None])
        errs.append(np.abs(op - ex).max() / np.abs(ex).max())
    assert max(errs) < 0.06, (max(errs), errs[-5:])
    # no upward trend: the last 10 no worse than the first 10's envelope
    assert max(errs[-10:]) < max(errs[:10]) + 0.02, errs


def test_newton_schulz_inverse_warm_and_residual():
    """Seeded with the exact previous inverse under small drift, two NS
    iterations reach f32 noise; a garbage seed reports a large residual
    (the engine's fallback gate)."""
    rng = np.random.RandomState(5)
    a0 = _spd(rng, 3, 32, 32) / 32
    x0 = np.linalg.inv(a0)
    drift = _spd(rng, 3, 32, 32) / 32
    a1 = (0.95 * a0 + 0.05 * drift).astype(np.float32)

    x, resid = ops.newton_schulz_inverse(jnp.asarray(a1), jnp.asarray(x0))
    x, resid = np.asarray(x), np.asarray(resid)
    assert (resid < 1e-2).all(), resid
    np.testing.assert_allclose(x, np.linalg.inv(a1), rtol=5e-3, atol=1e-4)
    np.testing.assert_allclose(x, np.swapaxes(x, -1, -2), atol=1e-6)

    _, bad = ops.newton_schulz_inverse(jnp.asarray(a1),
                                       jnp.zeros_like(jnp.asarray(a1)))
    assert (np.asarray(bad) >= 1.0 - 1e-6).all()  # ||I|| — gate rejects


def test_warm_inverse_per_slot_gate():
    """ADVICE r2: the NS acceptance gate is per-slot — a zero-seeded slot
    falls back to the exact Cholesky inverse while its healthy
    bucket-mates keep the NS result (no bucket-wide cold restart)."""
    rng = np.random.RandomState(7)
    a0 = _spd(rng, 3, 32, 32) / 32
    drift = _spd(rng, 3, 32, 32) / 32
    a1 = (0.97 * a0 + 0.03 * drift).astype(np.float32)
    seed = np.linalg.inv(a0).astype(np.float32)
    seed[1] = 0.0  # slot 1: stale-to-death seed; 0 and 2 healthy

    out = np.asarray(ops.warm_inverse(jnp.asarray(a1), jnp.asarray(seed)))
    ns, resid = ops.newton_schulz_inverse(jnp.asarray(a1),
                                          jnp.asarray(seed))
    ns, resid = np.asarray(ns), np.asarray(resid)
    assert resid[1] >= 1.0 - 1e-6 and (resid[[0, 2]] < 0.05).all()
    # healthy slots: the NS result verbatim
    np.testing.assert_array_equal(out[0], ns[0])
    np.testing.assert_array_equal(out[2], ns[2])
    # failed slot: the batched Cholesky inverse, exact
    chol = np.asarray(ops.psd_inverse(jnp.asarray(a1)))
    np.testing.assert_array_equal(out[1], chol[1])
    np.testing.assert_allclose(out[1], np.linalg.inv(a1[1]),
                               rtol=5e-3, atol=1e-4)
    # all-healthy fast path: identical to plain NS
    good = np.linalg.inv(a0).astype(np.float32)
    out2 = np.asarray(ops.warm_inverse(jnp.asarray(a1), jnp.asarray(good)))
    ns2, _ = ops.newton_schulz_inverse(jnp.asarray(a1), jnp.asarray(good))
    np.testing.assert_array_equal(out2, np.asarray(ns2))
