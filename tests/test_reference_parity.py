"""Golden cross-implementation parity: run the ACTUAL reference
implementation (torch CPU, /root/reference, read-only) and this framework
on identical weights and data, and compare the preconditioned gradients.

This is the strongest parity evidence available: not an oracle we wrote,
but the reference's own numerics. Skipped when the reference checkout or
torch is unavailable."""

import os
import sys
import types

import numpy as np
import pytest

REF = '/root/reference'
pytestmark = [
    pytest.mark.core,
    pytest.mark.skipif(not os.path.isdir(os.path.join(REF, 'kfac')),
                       reason='reference checkout not available'),
]

B, DIN, DH, DOUT = 16, 4, 8, 3
LR, DAMPING, KL_CLIP, DECAY = 0.1, 0.01, 0.001, 0.95


@pytest.fixture(scope='module')
def torch_side():
    torch = pytest.importorskip('torch')
    import torch.distributed as dist

    if 'horovod' not in sys.modules:  # stub so kfac.backend imports
        hvd = types.ModuleType('horovod.torch')
        hvd.init = lambda *a, **k: None
        sys.modules['horovod'] = types.ModuleType('horovod')
        sys.modules['horovod.torch'] = hvd
    sys.path.insert(0, REF)
    os.environ.setdefault('MASTER_ADDR', '127.0.0.1')
    os.environ.setdefault('MASTER_PORT', '29572')
    if not dist.is_initialized():
        dist.init_process_group('gloo', rank=0, world_size=1)
    import kfac as ref_kfac
    import kfac.backend as ref_backend
    ref_backend.init('Torch')
    return torch, ref_kfac


def _data(seed=0):
    rng = np.random.RandomState(seed)
    return (rng.randn(B, DIN).astype(np.float32),
            rng.randint(0, DOUT, B),
            rng.randn(DH, DIN).astype(np.float32) * 0.5,   # w1 [out, in]
            rng.randn(DH).astype(np.float32) * 0.1,
            rng.randn(DOUT, DH).astype(np.float32) * 0.5,  # w2 [out, in]
            rng.randn(DOUT).astype(np.float32) * 0.1)


def _reference_precond_grads(torch, ref_kfac, variant, steps=1):
    x, y, w1, b1, w2, b2 = _data()
    model = torch.nn.Sequential(torch.nn.Linear(DIN, DH), torch.nn.ReLU(),
                                torch.nn.Linear(DH, DOUT))
    with torch.no_grad():
        model[0].weight.copy_(torch.from_numpy(w1))
        model[0].bias.copy_(torch.from_numpy(b1))
        model[2].weight.copy_(torch.from_numpy(w2))
        model[2].bias.copy_(torch.from_numpy(b2))
    pre = ref_kfac.get_kfac_module(variant)(
        model, lr=LR, damping=DAMPING, fac_update_freq=1,
        kfac_update_freq=1, kl_clip=KL_CLIP, factor_decay=DECAY)
    for _ in range(steps):
        model.zero_grad()
        loss = torch.nn.functional.cross_entropy(
            model(torch.from_numpy(x)), torch.from_numpy(y))
        loss.backward()
        pre.step()
    return {
        'w1': model[0].weight.grad.numpy().copy(),
        'b1': model[0].bias.grad.numpy().copy(),
        'w2': model[2].weight.grad.numpy().copy(),
        'b2': model[2].bias.grad.numpy().copy(),
    }


def _ours_precond_grads(variant, steps=1):
    import jax
    import jax.numpy as jnp
    import optax
    from flax import linen

    import kfac_pytorch_tpu as kfac
    from kfac_pytorch_tpu import capture
    from kfac_pytorch_tpu import nn as knn

    x, y, w1, b1, w2, b2 = _data()

    class MLP(linen.Module):
        @linen.compact
        def __call__(self, x):
            x = knn.Dense(DH, name='l1')(x)
            x = linen.relu(x)
            return knn.Dense(DOUT, name='l2')(x)

    model = MLP()
    variables = capture.init(model, jax.random.PRNGKey(0), jnp.asarray(x))
    params = {'l1': {'kernel': jnp.asarray(w1.T), 'bias': jnp.asarray(b1)},
              'l2': {'kernel': jnp.asarray(w2.T), 'bias': jnp.asarray(b2)}}

    pre = kfac.get_kfac_module(variant)(
        lr=LR, damping=DAMPING, fac_update_freq=1, kfac_update_freq=1,
        kl_clip=KL_CLIP, factor_decay=DECAY)
    metas = capture.collect_layer_meta(model, {'params': params},
                                      jnp.asarray(x))
    pre.setup(metas)
    state = pre.init()

    def loss_fn(outputs):
        return optax.softmax_cross_entropy_with_integer_labels(
            outputs, jnp.asarray(y)).mean()

    for _ in range(steps):
        _, _, grads, acts, gs, _ = capture.value_and_grad_with_capture(
            model, loss_fn, {'params': params}, jnp.asarray(x))
        new_grads, state = pre.step(state, grads, acts, gs)
    return {
        'w1': np.asarray(new_grads['l1']['kernel']).T,
        'b1': np.asarray(new_grads['l1']['bias']),
        'w2': np.asarray(new_grads['l2']['kernel']).T,
        'b2': np.asarray(new_grads['l2']['bias']),
    }


# Multi-step parity holds for the eigen variants. The inverse variants
# intentionally deviate after step 1: the reference's _add_value_to_diagonal
# mutates damping into its STORED running-average factors in place
# (inv.py:106-129), so damping compounds across inverse updates there;
# this framework applies damping to a temporary (see engine.py module doc).
@pytest.mark.parametrize('variant,steps', [
    ('eigen_dp', 1), ('inverse_dp', 1), ('eigen', 1), ('inverse', 1),
    ('eigen_dp', 3), ('eigen', 3),
])
def test_preconditioned_grads_match_reference(torch_side, variant, steps):
    torch, ref_kfac = torch_side
    ref = _reference_precond_grads(torch, ref_kfac, variant, steps)
    ours = _ours_precond_grads(variant, steps)
    for k in ref:
        np.testing.assert_allclose(
            ours[k], ref[k], atol=2e-4, rtol=2e-3,
            err_msg=f'{variant} step{steps} param {k}')


def _conv_data(seed=3):
    rng = np.random.RandomState(seed)
    return (rng.randn(8, 3, 6, 6).astype(np.float32),      # NCHW for torch
            rng.randint(0, DOUT, 8),
            rng.randn(4, 3, 3, 3).astype(np.float32) * 0.4,  # [out,in,kh,kw]
            rng.randn(4).astype(np.float32) * 0.1,
            rng.randn(DOUT, 4 * 6 * 6).astype(np.float32) * 0.2,
            rng.randn(DOUT).astype(np.float32) * 0.1)


def _reference_conv_grads(torch, ref_kfac, variant):
    x, y, wc, bc, wl, bl = _conv_data()
    model = torch.nn.Sequential(
        torch.nn.Conv2d(3, 4, 3, stride=1, padding=1), torch.nn.ReLU(),
        torch.nn.Flatten(), torch.nn.Linear(4 * 6 * 6, DOUT))
    with torch.no_grad():
        model[0].weight.copy_(torch.from_numpy(wc))
        model[0].bias.copy_(torch.from_numpy(bc))
        model[3].weight.copy_(torch.from_numpy(wl))
        model[3].bias.copy_(torch.from_numpy(bl))
    pre = ref_kfac.get_kfac_module(variant)(
        model, lr=LR, damping=DAMPING, fac_update_freq=1,
        kfac_update_freq=1, kl_clip=KL_CLIP, factor_decay=DECAY)
    model.zero_grad()
    loss = torch.nn.functional.cross_entropy(
        model(torch.from_numpy(x)), torch.from_numpy(y))
    loss.backward()
    pre.step()
    return {'conv_w': model[0].weight.grad.numpy().copy(),
            'conv_b': model[0].bias.grad.numpy().copy(),
            'fc_w': model[3].weight.grad.numpy().copy(),
            'fc_b': model[3].bias.grad.numpy().copy()}


def _ours_conv_grads(variant):
    import jax
    import jax.numpy as jnp
    import optax
    from flax import linen

    import kfac_pytorch_tpu as kfac
    from kfac_pytorch_tpu import capture
    from kfac_pytorch_tpu import nn as knn

    x, y, wc, bc, wl, bl = _conv_data()
    x_nhwc = np.transpose(x, (0, 2, 3, 1))

    class CNN(linen.Module):
        @linen.compact
        def __call__(self, x):
            x = knn.Conv(4, (3, 3), strides=(1, 1), padding=((1, 1), (1, 1)),
                         name='c')(x)
            x = linen.relu(x)
            # match torch Flatten of NCHW: [N, C*H*W] with C outermost
            x = x.transpose(0, 3, 1, 2).reshape(x.shape[0], -1)
            return knn.Dense(DOUT, name='f')(x)

    model = CNN()
    params = {
        'c': {'kernel': jnp.asarray(np.transpose(wc, (2, 3, 1, 0))),
              'bias': jnp.asarray(bc)},
        'f': {'kernel': jnp.asarray(wl.T), 'bias': jnp.asarray(bl)},
    }
    pre = kfac.get_kfac_module(variant)(
        lr=LR, damping=DAMPING, fac_update_freq=1, kfac_update_freq=1,
        kl_clip=KL_CLIP, factor_decay=DECAY)
    metas = capture.collect_layer_meta(model, {'params': params},
                                      jnp.asarray(x_nhwc))
    pre.setup(metas)
    state = pre.init()

    def loss_fn(outputs):
        return optax.softmax_cross_entropy_with_integer_labels(
            outputs, jnp.asarray(y)).mean()

    _, _, grads, acts, gs, _ = capture.value_and_grad_with_capture(
        model, loss_fn, {'params': params}, jnp.asarray(x_nhwc))
    new_grads, state = pre.step(state, grads, acts, gs)
    return {'conv_w': np.transpose(np.asarray(new_grads['c']['kernel']),
                                   (3, 2, 0, 1)),
            'conv_b': np.asarray(new_grads['c']['bias']),
            'fc_w': np.asarray(new_grads['f']['kernel']).T,
            'fc_b': np.asarray(new_grads['f']['bias'])}


@pytest.mark.parametrize('variant', ['eigen_dp', 'inverse_dp'])
def test_conv_preconditioned_grads_match_reference(torch_side, variant):
    torch, ref_kfac = torch_side
    ref = _reference_conv_grads(torch, ref_kfac, variant)
    ours = _ours_conv_grads(variant)
    for k in ref:
        np.testing.assert_allclose(
            ours[k], ref[k], atol=5e-4, rtol=5e-3,
            err_msg=f'{variant} param {k}')


def test_param_scheduler_matches_reference(torch_side):
    """KFACParamScheduler epoch-decay parity (reference base.py:233-301)."""
    torch, ref_kfac = torch_side
    from kfac_pytorch_tpu import KFACParamScheduler
    import kfac_pytorch_tpu as kfac

    model = torch.nn.Sequential(torch.nn.Linear(DIN, DOUT))
    ref_pre = ref_kfac.get_kfac_module('eigen_dp')(
        model, lr=LR, damping=0.03, fac_update_freq=2, kfac_update_freq=10)
    ref_sched = ref_kfac.KFACParamScheduler(
        ref_pre, damping_alpha=0.5, damping_schedule=[3, 6],
        update_freq_alpha=10, update_freq_schedule=[4])

    ours_pre = kfac.KFAC(variant='eigen_dp', lr=LR, damping=0.03,
                         fac_update_freq=2, kfac_update_freq=10)
    ours_sched = KFACParamScheduler(
        ours_pre, damping_alpha=0.5, damping_schedule=[3, 6],
        update_freq_alpha=10, update_freq_schedule=[4])

    for epoch in range(1, 9):
        ref_sched.step(epoch)
        ours_sched.step(epoch)
        # the reference publishes live values through param_groups, which
        # the preconditioner reads back each step (base.py:188-193)
        g = ref_pre.param_groups[0]
        np.testing.assert_allclose(ours_pre.damping, g['damping'],
                                   err_msg=f'epoch {epoch}')
        assert ours_pre.fac_update_freq == int(g['fac_update_freq']), epoch
        assert ours_pre.kfac_update_freq == int(g['kfac_update_freq']), \
            epoch


@pytest.mark.parametrize('variant', ['inverse_dp', 'inverse'])
def test_inverse_multistep_deviation_is_bounded(torch_side, variant):
    """The documented damping-accumulation deviation stays small (the
    reference compounds +sqrt(damping)*pi onto its factors each update)."""
    torch, ref_kfac = torch_side
    ref = _reference_precond_grads(torch, ref_kfac, variant, 3)
    ours = _ours_precond_grads(variant, 3)
    for k in ref:
        denom = np.abs(ref[k]).max()
        rel = np.abs(ours[k] - ref[k]).max() / max(denom, 1e-9)
        assert rel < 0.15, (variant, k, rel)


def test_f1mc_preconditioned_grads_match_reference(torch_side):
    """F1mc composition parity: factors from a pseudo-label backward,
    update from the real-loss gradients. The reference only ships the
    sampler (examples/utils.py:82-90); the composition is exercised here
    through its hook toggle (kfac_preconditioner_base.py:119-129) with
    FIXED pseudo labels so both sides see identical draws."""
    torch, ref_kfac = torch_side
    x, y, w1, b1, w2, b2 = _data()
    y_mc = np.random.RandomState(7).randint(0, DOUT, B)

    # --- torch oracle: MC backward with hooks armed -> factor stats;
    # real backward with hooks off -> the grads that get preconditioned
    model = torch.nn.Sequential(torch.nn.Linear(DIN, DH), torch.nn.ReLU(),
                                torch.nn.Linear(DH, DOUT))
    with torch.no_grad():
        model[0].weight.copy_(torch.from_numpy(w1))
        model[0].bias.copy_(torch.from_numpy(b1))
        model[2].weight.copy_(torch.from_numpy(w2))
        model[2].bias.copy_(torch.from_numpy(b2))
    pre = ref_kfac.get_kfac_module('eigen_dp')(
        model, lr=LR, damping=DAMPING, fac_update_freq=1,
        kfac_update_freq=1, kl_clip=KL_CLIP, factor_decay=DECAY)
    torch.nn.functional.cross_entropy(
        model(torch.from_numpy(x)), torch.from_numpy(y_mc)).backward()
    model.zero_grad()
    pre.set_hook_enabled(False)
    torch.nn.functional.cross_entropy(
        model(torch.from_numpy(x)), torch.from_numpy(y)).backward()
    pre.set_hook_enabled(True)
    pre.step()
    ref = {
        'w1': model[0].weight.grad.numpy().copy(),
        'b1': model[0].bias.grad.numpy().copy(),
        'w2': model[2].weight.grad.numpy().copy(),
        'b2': model[2].bias.grad.numpy().copy(),
    }

    # --- ours: the same composition through the train-step F1mc path,
    # with a fixed-label sampler standing in for the categorical draw
    import jax
    import jax.numpy as jnp
    import optax
    from flax import linen

    import kfac_pytorch_tpu as kfac
    from kfac_pytorch_tpu import nn as knn, training

    class MLP(linen.Module):
        @linen.compact
        def __call__(self, xx, train=True):
            xx = knn.Dense(DH, name='l1')(xx)
            xx = linen.relu(xx)
            return knn.Dense(DOUT, name='l2')(xx)

    mlp = MLP()
    pre_j = kfac.get_kfac_module('eigen_dp')(
        lr=LR, damping=DAMPING, fac_update_freq=1, kfac_update_freq=1,
        kl_clip=KL_CLIP, factor_decay=DECAY)
    tx = training.sgd(LR)
    batch = {'input': jnp.asarray(x), 'label': jnp.asarray(y)}

    def ce(outputs, b):
        return optax.softmax_cross_entropy_with_integer_labels(
            outputs, b['label']).mean()

    state = training.init_train_state(mlp, tx, pre_j, jax.random.PRNGKey(0),
                                      batch['input'])
    state = state.replace(params={
        'l1': {'kernel': jnp.asarray(w1.T), 'bias': jnp.asarray(b1)},
        'l2': {'kernel': jnp.asarray(w2.T), 'bias': jnp.asarray(b2)}})
    step = training.build_train_step(
        mlp, tx, pre_j, ce, fisher_type='F1mc',
        fisher_sample_fn=lambda rng, out: jnp.asarray(y_mc), donate=False)
    before = jax.tree.map(np.asarray, state.params)
    state2, _ = step(state, batch, lr=LR, damping=DAMPING)
    # recover the preconditioned grads from the plain-SGD update:
    # p' = p - LR * g_precond
    ours = {
        'w1': (before['l1']['kernel']
               - np.asarray(state2.params['l1']['kernel'])).T / LR,
        'b1': (before['l1']['bias']
               - np.asarray(state2.params['l1']['bias'])) / LR,
        'w2': (before['l2']['kernel']
               - np.asarray(state2.params['l2']['kernel'])).T / LR,
        'b2': (before['l2']['bias']
               - np.asarray(state2.params['l2']['bias'])) / LR,
    }
    for k in ref:
        np.testing.assert_allclose(ours[k], ref[k], atol=2e-4, rtol=2e-3,
                                   err_msg=f'F1mc param {k}')
