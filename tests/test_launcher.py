"""launch_tpu.sh driven end-to-end (VERDICT r2 §2.6 'launchers: partial'
— the script replaces the reference's mpirun/ssh launchers,
launch_horovod.sh:32 / launch_torch.sh:26-45, but had never itself been
exercised by a test): the pod-preset arg injection, and a real
two-process jax.distributed run where BOTH workers go through the
launcher script."""

import os
import subprocess

import pytest

from tests.helpers import communicate_all, free_port

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LAUNCHER = os.path.join(REPO, 'launch_tpu.sh')


def _clean_env(**extra):
    env = {k: v for k, v in os.environ.items()
           if k not in ('XLA_FLAGS', 'JAX_PLATFORMS',
                        'JAX_COORDINATOR_ADDRESS')}
    env.update(extra)
    return env


def test_pod_preset_injects_num_devices(tmp_path):
    """pod=N sources configs/podN and appends --num-devices so the preset
    wins over an earlier default (argparse last-occurrence-wins)."""
    dump = tmp_path / 'argdump.py'
    dump.write_text('import sys; print("ARGS", " ".join(sys.argv[1:]))\n')
    out = subprocess.run(
        ['bash', LAUNCHER, str(dump), '--num-devices', '1', '--foo'],
        env=_clean_env(pod='8'), capture_output=True, text=True,
        timeout=60)
    assert out.returncode == 0, out.stderr
    args = [l for l in out.stdout.splitlines() if l.startswith('ARGS')][0]
    assert args.endswith('--num-devices 1 --foo --num-devices 8'), args

    # unknown preset must fail loudly, not run with the wrong mesh
    bad = subprocess.run(
        ['bash', LAUNCHER, str(dump)], env=_clean_env(pod='3'),
        capture_output=True, text=True, timeout=60)
    assert bad.returncode != 0
    assert 'no such mesh preset' in bad.stderr


def test_supervise_mode_wraps_trainer_in_supervisor(tmp_path):
    """KFAC_SUPERVISE=1 routes the trainer through the kfac-supervise
    restart loop (resilience/supervisor.py) instead of exec'ing it
    directly."""
    dump = tmp_path / 'argdump.py'
    dump.write_text('print("CHILD RAN")\n')
    out = subprocess.run(
        ['bash', LAUNCHER, str(dump), '--flag'],
        env=_clean_env(KFAC_SUPERVISE='1', KFAC_MAX_RESTARTS='0',
                       JAX_PLATFORMS='cpu'),
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr[-2000:]
    assert 'CHILD RAN' in out.stdout
    assert 'supervisor: launching' in (out.stdout + out.stderr)


_WORKER = '''
import os
os.environ['XLA_FLAGS'] = '--xla_force_host_platform_device_count=4'
import jax
jax.config.update('jax_platforms', 'cpu')
import sys
sys.path.insert(0, {repo!r})
from kfac_pytorch_tpu.parallel import mesh as kmesh
# launch_tpu.sh exported KFAC_TPU_MULTIHOST because the coordinator env
# was present — exactly the launcher contract under test
assert kmesh.maybe_initialize_distributed(), 'launcher env not honored'
assert jax.process_count() == 2, jax.process_count()
assert len(jax.devices()) == 8, len(jax.devices())
import numpy as np, jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from jax.experimental import multihost_utils
mesh = Mesh(np.array(jax.devices()), ('b',))
pid = jax.process_index()
loc = jnp.arange(4.0) + 4 * pid
g = multihost_utils.host_local_array_to_global_array(loc, mesh, P('b'))
total = float(np.asarray(jax.jit(lambda x: x.sum())(g)
                         .addressable_data(0)))
assert total == 28.0, total  # sum(range(8)) across both processes
print('LAUNCHER OK', total, flush=True)
'''


@pytest.mark.slow
def test_two_process_launch_through_script(tmp_path):
    """Both workers start as `bash launch_tpu.sh worker.py` with only the
    documented pod env (coordinator address + process ids): the script's
    env plumbing (envs.conf sourcing, KFAC_TPU_MULTIHOST export, exec)
    must carry a real jax.distributed cross-process psum."""
    worker = tmp_path / 'worker.py'
    worker.write_text(_WORKER.format(repo=REPO))
    base = _clean_env(
        JAX_COORDINATOR_ADDRESS=f'127.0.0.1:{free_port()}',
        JAX_NUM_PROCESSES='2')
    procs = []
    try:
        for pid in range(2):
            env = dict(base, JAX_PROCESS_ID=str(pid))
            procs.append(subprocess.Popen(
                ['bash', LAUNCHER, str(worker)], env=env,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True))
        outs = communicate_all(procs)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out[-2000:]
        assert 'LAUNCHER OK 28.0' in out, out[-800:]
