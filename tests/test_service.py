"""Multi-tenant training service (kfac_pytorch_tpu/service/).

Pins the tentpole contracts with NO subprocesses (the real-process
drill lives in tests/test_service_chaos.py behind -m slow):

1. Spec validation is strict and total: unknown fields, malformed
   tenants, unregistered trainers, unsafe argv/env all fail at submit
   time, with EVERY problem named in one error.
2. The queue is durable and crash-safe: submission spools atomically,
   ingest is idempotent across a crash between job-write and
   spool-remove (no duplicated jobs), torn job files are skipped and
   retried (never deleted), and a scheduler restart requeues every
   RUNNING job (no lost jobs) without charging the tenant's budget.
3. Monotonic job epochs make every transition a CAS: a stale
   observation cannot move a job — which is exactly what bounds a
   fenced generation's many per-host exits to ONE requeue.
4. The admission controller packs jobs onto live capacity, launches
   one kfac-pod-supervise per rank with a per-tenant namespace and a
   per-job heartbeat-port block; an EXPLICIT port pinned by two
   co-resident specs fails loudly instead of bind-racing.
5. Exits classify through the existing rc grammar (0/113/114/115/116/
   117/signals) into requeue-with-backoff or job_lost at budget
   exhaustion; a capacity loss (pool_shrink) kills + requeues
   uncharged.
6. Service events land in the shared incident grammar, so kfac-obs
   renders admit -> failure -> requeue -> done per tenant — and the
   new --follow mode tails them live.
7. The multi-tenant policy lanes (ISSUE 17): priority preemption is a
   checkpoint-suspend (victims park SUSPENDED uncharged, their port
   blocks release for re-allocation, the preemptor admits the cycle
   the slots free), weighted fair share orders admission, a draining
   host suspend-migrates its preemptible jobs off (non-preemptible
   ones finish in place), and queue demand drives scale-request.json
   for an external capacity responder.
"""

import json
import logging
import os
import threading
import time

import pytest

from kfac_pytorch_tpu.obs import aggregate, metrics
from kfac_pytorch_tpu.resilience.incident import IncidentReport
from kfac_pytorch_tpu.resilience import atomic_write_json
from kfac_pytorch_tpu.service import (
    AdmissionController, JobQueue, PortAllocator, PortConflictError,
    SpecError, classify_rc, validate_spec)
from kfac_pytorch_tpu.service.scheduler import RC_SUSPENDED, SUSPEND_KEY

pytestmark = pytest.mark.core


# ---------------------------------------------------------------------------
# spec validation
# ---------------------------------------------------------------------------

def _spec(**over):
    base = {'tenant': 'alice', 'trainer': 'cifar10_resnet',
            'args': ['--epochs', '3'], 'knobs': {'kfac_autotune': True},
            'hosts': 1, 'priority': 0, 'retry_budget': 2}
    base.update(over)
    return base


def test_spec_roundtrip_and_argv():
    spec = validate_spec(_spec(knobs={'kfac_autotune': True,
                                      'kfac_update_freq': 10,
                                      'trace': None,
                                      'speed': False}))
    assert spec.tenant == 'alice'
    argv = spec.trainer_argv()
    # bare flag for True, flag+value for scalars, False/None omitted,
    # knobs (sorted) before free-form args
    assert argv == ['--kfac-autotune', '--kfac-update-freq', '10',
                    '--epochs', '3']
    assert validate_spec(spec.to_dict()).to_dict() == spec.to_dict()


def test_spec_rejects_everything_at_once():
    bad = {'tenant': 'Not Valid!', 'trainer': 'rm -rf /',
           'args': ['ok', 7, 'has\nnewline'], 'knobs': {'BAD-KNOB': 1},
           'env': {'PATH': '/evil'}, 'hosts': 0, 'retry_budget': -1,
           'surprise': True}
    with pytest.raises(SpecError) as ei:
        validate_spec(bad)
    text = str(ei.value)
    for frag in ('tenant', 'trainer', 'args[1]', 'args[2]', 'BAD-KNOB',
                 "env key 'PATH'", "'hosts'", "'retry_budget'",
                 'surprise'):
        assert frag in text, (frag, text)


def test_spec_kfac_knob_table():
    """kfac_*-prefixed knobs validate against the shared knob table
    (spec.KFAC_KNOBS): the decomposition-wall knobs are requestable,
    a typo fails at submit time, and the table stays in lockstep with
    the trainers' --kfac-* surface."""
    spec = validate_spec(_spec(knobs={'kfac_decomp_impl': 'newton_schulz',
                                      'kfac_decomp_shard': True}))
    argv = spec.trainer_argv()
    assert '--kfac-decomp-impl' in argv and 'newton_schulz' in argv
    assert '--kfac-decomp-shard' in argv
    with pytest.raises(SpecError, match='kfac_decomp_imp'):
        validate_spec(_spec(knobs={'kfac_decomp_imp': 'xla'}))  # typo
    # the table covers every --kfac-* flag the trainers expose (the
    # lockstep pin: adding a trainer flag without tabling it breaks
    # here, not in a tenant's 3am submit)
    import re as _re
    from kfac_pytorch_tpu.service.spec import KFAC_KNOBS, TRAINERS
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    flags = set()
    for rel in TRAINERS.values():
        src = open(os.path.join(repo, rel)).read()
        flags |= {m[2:].replace('-', '_') for m in _re.findall(
            r"add_argument\('(--kfac-[a-z-]+)'", src)}
    assert flags <= KFAC_KNOBS, flags - KFAC_KNOBS


def test_spec_env_allows_only_kfac_jax():
    spec = validate_spec(_spec(env={'KFAC_COMM_PRECISION': 'bf16',
                                    'JAX_PLATFORMS': 'cpu'}))
    assert spec.env['KFAC_COMM_PRECISION'] == 'bf16'
    with pytest.raises(SpecError):
        validate_spec(_spec(env={'LD_PRELOAD': 'x'}))


def test_spec_registry_extension():
    with pytest.raises(SpecError):
        validate_spec(_spec(trainer='mini'))
    spec = validate_spec(_spec(trainer='mini'),
                         trainers={'mini': 'tests/chaos_trainer.py'})
    assert spec.trainer == 'mini'


# ---------------------------------------------------------------------------
# the durable queue
# ---------------------------------------------------------------------------

def test_queue_submit_ingest_assigns_ids(tmp_path):
    q = JobQueue(tmp_path)
    q.submit(_spec())
    q.submit(_spec(tenant='bob'))
    created = q.ingest()
    assert [r['id'] for r in created] == [1, 2]
    assert not os.listdir(q.incoming)
    jobs = q.jobs()
    assert [(r['id'], r['state'], r['epoch']) for r in jobs] == \
        [(1, 'queued', 0), (2, 'queued', 0)]
    assert jobs[0]['spec']['tenant'] == 'alice'


def test_queue_ingest_idempotent_across_crash(tmp_path):
    """Crash between job-file write and spool remove: the restarted
    ingest completes the cleanup WITHOUT duplicating the job."""
    q = JobQueue(tmp_path)
    name = q.submit(_spec())
    q.ingest()
    assert len(q.jobs()) == 1
    # resurrect the spool entry exactly as a crash would have left it
    from kfac_pytorch_tpu.resilience import atomic_write_json
    atomic_write_json(os.path.join(q.incoming, name), _spec())
    assert q.ingest() == []
    assert len(q.jobs()) == 1          # no duplicate
    assert not os.listdir(q.incoming)  # cleanup completed


def test_queue_rejects_invalid_spool_to_rejected(tmp_path):
    q = JobQueue(tmp_path)
    from kfac_pytorch_tpu.resilience import atomic_write_json
    atomic_write_json(os.path.join(q.incoming, 'spec-bad.json'),
                      {'tenant': 'x y', 'trainer': 'nope'})
    assert q.ingest() == []
    assert not os.listdir(q.incoming)
    names = os.listdir(q.rejected)
    assert 'spec-bad.json' in names
    reason = json.load(open(os.path.join(q.rejected,
                                         'spec-bad.json.reason')))
    assert reason['problems']


def test_queue_torn_job_file_skipped_never_deleted(tmp_path):
    q = JobQueue(tmp_path)
    q.submit(_spec())
    q.ingest()
    torn = os.path.join(q.jobs_dir, 'job-000099.json')
    with open(torn, 'w') as f:
        f.write('{"id": 99, "state": "que')   # torn mid-write
    jobs = q.jobs()
    assert [r['id'] for r in jobs] == [1]     # the good job still reads
    assert os.path.exists(torn)               # never deleted


def test_queue_transition_epoch_cas(tmp_path):
    """The fencing-aware requeue bound: two observers holding the same
    epoch — the first transition wins, the second no-ops."""
    q = JobQueue(tmp_path)
    q.submit(_spec())
    rec = q.ingest()[0]
    running = q.claim(rec)
    assert running['epoch'] == 1 and running['attempt'] == 1
    # two copies of the SAME observation (e.g. two fenced host exits)
    obs_a, obs_b = dict(running), dict(running)
    first = q.requeue(obs_a, rc=117, reason='fenced', backoff_s=1.0)
    assert first is not None and first['requeues'] == 1
    assert q.requeue(obs_b, rc=117, reason='fenced') is None
    assert q.read(rec['id'])['requeues'] == 1  # exactly once


def test_queue_recover_requeues_running_jobs(tmp_path):
    q = JobQueue(tmp_path)
    q.submit(_spec())
    q.submit(_spec(tenant='bob'))
    a, b = q.ingest()
    q.claim(a)
    recovered = JobQueue(tmp_path).recover()
    assert [r['id'] for r in recovered] == [a['id']]
    states = {r['id']: r['state'] for r in q.jobs()}
    assert states == {a['id']: 'queued', b['id']: 'queued'}
    # a bounced controller never burns the tenant's budget
    assert q.read(a['id']).get('charged_requeues', 0) == 0


# ---------------------------------------------------------------------------
# rc grammar + port allocation
# ---------------------------------------------------------------------------

def test_classify_rc_grammar():
    assert classify_rc(0) == 'done'
    assert classify_rc(113) == 'crash'
    assert classify_rc(114) == 'hang'
    assert classify_rc(115) == 'peer_dead'
    assert classify_rc(116) == 'join_failed'
    assert classify_rc(117) == 'fenced'
    assert classify_rc(-9) == 'signal'
    assert classify_rc(1) == 'crash'
    assert classify_rc(None) == 'unknown'


def test_port_allocator_disjoint_blocks_and_explicit_conflict():
    alloc = PortAllocator(base=8600, stride=16)
    assert alloc.claim(1) == 8600
    assert alloc.claim(2) == 8616
    alloc.release(1)
    assert alloc.claim(3) == 8600          # freed blocks are reusable
    assert alloc.claim(4, explicit=9000) == 9000
    with pytest.raises(PortConflictError):
        alloc.claim(5, explicit=9000)      # explicit double-pin: loud
    with pytest.raises(PortConflictError):
        alloc.claim(6, explicit=8616)      # pin onto a derived block


# ---------------------------------------------------------------------------
# the admission controller (fake processes — no subprocess anywhere)
# ---------------------------------------------------------------------------

class _FakeProc:
    _next_pid = 50000

    def __init__(self):
        _FakeProc._next_pid += 1
        self.pid = _FakeProc._next_pid
        self.rc = None

    def poll(self):
        return self.rc

    def wait(self, timeout=None):
        return self.rc if self.rc is not None else 0


class _FakePopen:
    """Records every launch; hands out settable fake processes."""

    def __init__(self):
        self.launches = []   # (argv, env)
        self.procs = []

    def __call__(self, argv, env=None, **kw):
        proc = _FakeProc()
        self.launches.append((list(argv), dict(env or {})))
        self.procs.append(proc)
        return proc


def _controller(tmp_path, *, hosts=None, popen=None, wall=None, **kw):
    popen = popen or _FakePopen()
    killed = []
    ctl = AdmissionController(
        tmp_path / 'svc', hosts=hosts or {'h0': 2},
        trainers={'mini': 'tests/chaos_trainer.py'},
        popen=popen, killer=lambda p: killed.append(p.pid),
        wall=wall or time.time, backoff_base=0.5, backoff_max=4.0,
        log=logging.getLogger('svc-test'), **kw)
    ctl._test_killed = killed
    return ctl, popen


def _mini(**over):
    return _spec(trainer='mini',
                 args=['--epochs', '2', '--checkpoint-dir', '{ckpt}'],
                 knobs={}, **over)


def test_admit_namespaces_env_and_ports(tmp_path):
    ctl, popen = _controller(tmp_path)
    ctl.queue.submit(_mini())
    ctl.queue.submit(_mini(tenant='bob'))
    ctl.step()
    assert len(popen.launches) == 2
    (argv_a, env_a), (argv_b, env_b) = popen.launches
    # one kfac-pod-supervise per rank, trainer script resolved from the
    # extended registry, {ckpt} substituted into the tenant namespace
    assert 'kfac_pytorch_tpu.resilience.elastic' in argv_a
    assert any(a.endswith('tests/chaos_trainer.py') for a in argv_a)
    ckpt = argv_a[argv_a.index('--checkpoint-dir') + 1]
    assert '{ckpt}' not in ckpt
    assert os.path.join('tenants', 'alice', 'job-000001', 'ckpt') in ckpt
    # per-tenant env namespace
    assert env_a['KFAC_TENANT'] == 'alice'
    assert env_a['KFAC_JOB_ID'] == 'job-000001'
    assert 'alice' in env_a['KFAC_TRACE_DIR']
    # the advertised prom path IS the file the exporter writes: the
    # scheduler exports it pre-namespaced, trainer-side namespacing is
    # then the identity
    assert env_a['KFAC_PROM_FILE'].endswith(
        'metrics-alice-job-000001.prom')
    assert metrics.namespaced_prom_path(
        env_a['KFAC_PROM_FILE'],
        {'KFAC_TENANT': 'alice', 'KFAC_JOB_ID': 'job-000001'}) \
        == env_a['KFAC_PROM_FILE']
    assert env_b['KFAC_TENANT'] == 'bob'
    # per-job lease subdirectory + disjoint heartbeat port blocks for
    # two jobs sharing host h0 (the satellite-1 collision fix)
    lease_a = argv_a[argv_a.index('--lease-dir') + 1]
    lease_b = argv_b[argv_b.index('--lease-dir') + 1]
    assert lease_a != lease_b
    assert env_a['KFAC_HB_PORT'] != env_b['KFAC_HB_PORT']
    jobs = {r['id']: r for r in ctl.queue.jobs()}
    assert jobs[1]['state'] == 'running' and jobs[1]['port'] == 8600
    assert jobs[2]['port'] == 8616
    assert jobs[1]['placement'] == {'0': 'h0'}


def test_admit_respects_capacity_and_priority(tmp_path):
    ctl, popen = _controller(tmp_path, hosts={'h0': 1})
    ctl.queue.submit(_mini())                      # job 1, priority 0
    ctl.queue.submit(_mini(tenant='bob', priority=5))  # job 2
    ctl.step()
    # one slot: only the HIGH-priority job runs
    assert len(popen.launches) == 1
    assert popen.launches[0][1]['KFAC_TENANT'] == 'bob'
    assert ctl.queue.read(1)['state'] == 'queued'
    # completion frees the slot; the next cycle admits the other job
    popen.procs[0].rc = 0
    ctl.step()
    assert ctl.queue.read(2)['state'] == 'done'
    assert len(popen.launches) == 2
    assert popen.launches[1][1]['KFAC_TENANT'] == 'alice'


def test_explicit_port_conflict_fails_loudly(tmp_path, caplog):
    ctl, popen = _controller(tmp_path)
    ctl.queue.submit(_mini(env={'KFAC_HB_PORT': '9100'}))
    ctl.queue.submit(_mini(tenant='bob', env={'KFAC_HB_PORT': '9100'}))
    with caplog.at_level(logging.ERROR, logger='svc-test'):
        ctl.step()
    assert len(popen.launches) == 1       # the pinned winner launched
    assert ctl.queue.read(1)['state'] == 'running'
    lost = ctl.queue.read(2)
    assert lost['state'] == 'lost'
    assert lost['last_reason'] == 'port_conflict'
    assert 'KFAC_HB_PORT=9100' in caplog.text
    assert 'job_lost' in caplog.text


def test_reap_classifies_requeues_with_backoff_then_loses(tmp_path,
                                                          caplog):
    now = [1000.0]
    ctl, popen = _controller(tmp_path, wall=lambda: now[0])
    ctl.queue.submit(_mini(retry_budget=1))
    with caplog.at_level(logging.WARNING, logger='svc-test'):
        ctl.step()
        popen.procs[0].rc = 114            # watchdog hang verdict
        ctl.step()
        rec = ctl.queue.read(1)
        assert rec['state'] == 'queued'
        assert rec['last_reason'] == 'hang'
        assert rec['charged_requeues'] == 1
        assert rec['not_before'] == pytest.approx(1000.5)  # backoff
        # not ready yet: nothing admits before the backoff expires
        ctl.step()
        assert len(popen.launches) == 1
        now[0] += 1.0
        ctl.step()                         # relaunch (attempt 2)
        assert len(popen.launches) == 2
        popen.procs[1].rc = 115            # peer death this time
        ctl.step()                         # budget (1) spent -> lost
    rec = ctl.queue.read(1)
    assert rec['state'] == 'lost'
    assert rec['last_reason'] == 'peer_dead'
    assert 'job_requeue job=1 tenant=alice rc=114 class=hang' \
        in caplog.text
    assert 'job_lost job=1 tenant=alice rc=115 class=peer_dead' \
        in caplog.text


def test_fenced_generation_requeues_exactly_once(tmp_path, caplog):
    """Both ranks of a 2-host job exit fenced (117): ONE requeue."""
    ctl, popen = _controller(tmp_path, hosts={'h0': 1, 'h1': 1})
    ctl.queue.submit(_mini(hosts=2))
    with caplog.at_level(logging.WARNING, logger='svc-test'):
        ctl.step()
        assert len(popen.launches) == 2    # one supervisor per rank
        assert ctl.queue.read(1)['placement'] == {'0': 'h0', '1': 'h1'}
        popen.procs[0].rc = 117
        popen.procs[1].rc = 117
        ctl.step()
    rec = ctl.queue.read(1)
    assert rec['state'] == 'queued'
    assert rec['last_reason'] == 'fenced'
    assert rec['requeues'] == 1            # exactly once
    assert caplog.text.count('job_requeue job=1') == 1


def test_one_clean_rank_completes_a_shrunken_job(tmp_path):
    """A 2-host job whose pod shrank: the fenced rank exits 117, the
    survivor carries the schedule to DONE — the job is DONE, and the
    already-dead rank is not double-judged."""
    ctl, popen = _controller(tmp_path, hosts={'h0': 1, 'h1': 1})
    ctl.queue.submit(_mini(hosts=2))
    ctl.step()
    popen.procs[0].rc = 117                # fenced rank first
    ctl.step()
    assert ctl.queue.read(1)['state'] == 'running'  # survivor still up
    popen.procs[1].rc = 0                  # survivor finishes
    ctl.step()
    rec = ctl.queue.read(1)
    assert rec['state'] == 'done'
    assert rec['exit_rcs'] == {'0': 117, '1': 0}


def test_pool_shrink_kills_and_requeues_uncharged(tmp_path, caplog):
    ctl, popen = _controller(tmp_path, hosts={'h0': 1, 'h1': 1})
    ctl.queue.submit(_mini())
    ctl.queue.submit(_mini(tenant='bob'))
    with caplog.at_level(logging.WARNING, logger='svc-test'):
        ctl.step()
        assert len(popen.launches) == 2
        victim_host = ctl.queue.read(1)['placement']['0']
        keep = {h: s for h, s in ctl.hosts.items() if h != victim_host}
        from kfac_pytorch_tpu.resilience import atomic_write_json
        atomic_write_json(ctl.hosts_path, {'hosts': keep})
        ctl.step()
    rec = ctl.queue.read(1)
    assert rec['state'] == 'queued'
    assert rec['last_reason'] == 'host_lost'
    assert rec.get('charged_requeues', 0) == 0   # not the tenant's fault
    assert rec['not_before'] <= time.time()      # no backoff either
    assert popen.procs[0].pid in ctl._test_killed  # SIGKILLed the group
    assert 'pool_shrink slots=2 -> 1' in caplog.text
    assert ctl.queue.read(2)['state'] == 'running'  # bystander untouched
    # grow the pool back: the displaced job re-admits
    from kfac_pytorch_tpu.resilience import atomic_write_json
    atomic_write_json(ctl.hosts_path,
                      {'hosts': {victim_host: 1, **keep}})
    ctl.step()
    assert ctl.queue.read(1)['state'] == 'running'
    assert 'pool_grow' in caplog.text


def test_pool_slot_drain_logs_without_displacement(tmp_path, caplog):
    """A slot-count-only capacity edit (h0: 2 -> 1, a drain) lands on
    the timeline as pool_shrink but displaces nothing — the job
    finishes in place and over-commitment bleeds off."""
    ctl, popen = _controller(tmp_path, hosts={'h0': 2})
    ctl.queue.submit(_mini())
    with caplog.at_level(logging.WARNING, logger='svc-test'):
        ctl.step()
        from kfac_pytorch_tpu.resilience import atomic_write_json
        atomic_write_json(ctl.hosts_path, {'hosts': {'h0': 1}})
        ctl.step()
    assert 'pool_shrink slots=2 -> 1 lost=[]' in caplog.text
    assert ctl.queue.read(1)['state'] == 'running'
    assert not ctl._test_killed
    # and growing the slot count back logs pool_grow
    with caplog.at_level(logging.WARNING, logger='svc-test'):
        from kfac_pytorch_tpu.resilience import atomic_write_json
        atomic_write_json(ctl.hosts_path, {'hosts': {'h0': 2}})
        ctl.step()
    assert 'pool_grow slots=1 -> 2' in caplog.text


def test_host_loss_after_clean_exit_is_done_not_requeued(tmp_path):
    """The reap-before-refresh ordering: a job that FINISHED on a host
    removed in the same cycle is marked done — requeueing it would
    re-run a completed schedule (the zero-duplicated contract)."""
    ctl, popen = _controller(tmp_path, hosts={'h0': 1})
    ctl.queue.submit(_mini())
    ctl.step()
    popen.procs[0].rc = 0                  # finished...
    from kfac_pytorch_tpu.resilience import atomic_write_json
    atomic_write_json(ctl.hosts_path, {'hosts': {'h1': 1}})  # ...host gone
    ctl.step()
    rec = ctl.queue.read(1)
    assert rec['state'] == 'done'
    assert rec['requeues'] == 0


def test_mid_spawn_failure_requeues_and_kills_spawned_ranks(tmp_path,
                                                           caplog):
    """A launch that dies between rank spawns (EMFILE, vanished
    script) must kill the ranks that DID start and requeue the job —
    never crash the loop or orphan a half-admitted process group."""
    class _FailingPopen(_FakePopen):
        def __call__(self, argv, env=None, **kw):
            if len(self.launches) == 1:
                raise OSError('spawn failed (simulated EMFILE)')
            return super().__call__(argv, env=env, **kw)

    popen = _FailingPopen()
    ctl, popen = _controller(tmp_path, hosts={'h0': 2}, popen=popen)
    ctl.queue.submit(_mini(hosts=2))
    with caplog.at_level(logging.ERROR, logger='svc-test'):
        ctl.step()                         # must not raise
    rec = ctl.queue.read(1)
    assert rec['state'] == 'queued'
    assert rec['last_reason'] == 'launch_failed'
    assert popen.procs[0].pid in ctl._test_killed
    assert 'failed mid-spawn' in caplog.text
    assert 1 not in ctl.running


def test_queue_read_only_attach_creates_nothing(tmp_path):
    missing = tmp_path / 'nope'
    q = JobQueue(missing, create=False)
    assert q.jobs() == [] and q.counts()['queued'] == 0
    assert not missing.exists()


def test_scheduler_restart_recovers_without_losing_jobs(tmp_path):
    ctl, popen = _controller(tmp_path)
    ctl.queue.submit(_mini())
    ctl.step()
    assert ctl.queue.read(1)['state'] == 'running'
    # a NEW controller over the same service dir (the old one was
    # SIGKILLed): recover() requeues, the next step relaunches
    ctl2, popen2 = _controller(tmp_path)
    ctl2.queue.recover(log=ctl2.log)
    assert ctl2.queue.read(1)['state'] == 'queued'
    ctl2.step()
    rec = ctl2.queue.read(1)
    assert rec['state'] == 'running' and rec['attempt'] == 2
    assert len(popen2.launches) == 1


# ---------------------------------------------------------------------------
# prometheus namespacing + collision (satellite 2)
# ---------------------------------------------------------------------------

def test_prom_path_namespaced_by_tenant_job(tmp_path):
    env = {'KFAC_TENANT': 'alice', 'KFAC_JOB_ID': 'job-000003'}
    p = str(tmp_path / 'metrics.prom')
    out = metrics.namespaced_prom_path(p, env)
    assert out == str(tmp_path / 'metrics-alice-job-000003.prom')
    # already-namespaced and service-free paths are left alone
    assert metrics.namespaced_prom_path(out, env) == out
    assert metrics.namespaced_prom_path(p, {}) == p
    assert metrics.namespaced_prom_path(None, env) is None


def test_prom_exporter_collision_guard(tmp_path):
    path = str(tmp_path / 'node.prom')
    a = metrics.PrometheusTextfileExporter(path)
    with pytest.raises(ValueError, match='already exported'):
        metrics.PrometheusTextfileExporter(path)
    a.close()
    b = metrics.PrometheusTextfileExporter(path)   # released -> fine
    b.close()


def test_two_tenant_jobs_same_default_path_do_not_clobber(tmp_path):
    """The satellite-2 scenario end-to-end: two jobs handed the SAME
    textfile path export side by side once namespaced."""
    shared = str(tmp_path / 'metrics.prom')
    paths = []
    for tenant, job in (('alice', 'job-000001'), ('bob', 'job-000002')):
        env = {'KFAC_TENANT': tenant, 'KFAC_JOB_ID': job}
        exp = metrics.PrometheusTextfileExporter(
            metrics.namespaced_prom_path(shared, env))
        exp.export({'loss': 1.0}, step=1, wall=0.0,
                   kinds={'loss': 'gauge'})
        paths.append(exp.path)
        exp.close()
    assert len(set(paths)) == 2
    for p in paths:
        assert os.path.exists(p)
        assert 'kfac_loss 1.0' in open(p).read()


# ---------------------------------------------------------------------------
# the shared incident grammar + kfac-obs (follow, recursion)
# ---------------------------------------------------------------------------

SERVICE_LOG = """\
2026-08-03 10:00:01,000 service: pool_grow slots=0 -> 3 added=['h0', 'h1', 'h2']
2026-08-03 10:00:02,000 service: job_admit job=1 tenant=alice trainer=mini host=h0 attempt=1 port=8600
2026-08-03 10:00:03,000 service: job_admit job=2 tenant=bob trainer=mini host=h1 attempt=1 port=8616
2026-08-03 10:01:00,000 service: pool_shrink slots=3 -> 2 lost=['h0']
2026-08-03 10:01:00,500 service: job_requeue job=1 tenant=alice rc=-9 class=host_lost attempt=1 backoff_s=0.0
2026-08-03 10:01:05,000 service: job_admit job=1 tenant=alice trainer=mini host=h1 attempt=2 port=8600
2026-08-03 10:02:00,000 service: job_done job=1 tenant=alice attempts=2
2026-08-03 10:02:01,000 service: job_lost job=2 tenant=bob rc=117 class=fenced attempts=3
"""


def test_incident_grammar_scrapes_service_events(tmp_path):
    log_path = tmp_path / 'service.log'
    log_path.write_text(SERVICE_LOG)
    report = IncidentReport().scrape_path(str(log_path))
    kinds = [e['kind'] for e in report.events]
    assert kinds.count('job_admit') == 3
    assert 'job_requeue' in kinds and 'job_done' in kinds
    assert 'job_lost' in kinds
    assert 'pool_shrink' in kinds and 'pool_grow' in kinds
    req = next(e for e in report.events if e['kind'] == 'job_requeue')
    assert req['job'] == 1 and req['tenant'] == 'alice'
    assert req['rc'] == -9 and req['why'] == 'host_lost'
    lost = next(e for e in report.events if e['kind'] == 'job_lost')
    assert lost['rc'] == 117 and lost['why'] == 'fenced'


def test_obs_timeline_orders_admit_failure_requeue_done(tmp_path):
    log_path = tmp_path / 'service.log'
    log_path.write_text(SERVICE_LOG)
    timeline = aggregate.build_timeline([str(log_path)])
    alice = [e for e in timeline['events']
             if e['detail'].get('tenant') == 'alice']
    kinds = [e['kind'] for e in alice]
    assert kinds == ['job_admit', 'job_requeue', 'job_admit',
                     'job_done']
    walls = [e['wall_aligned'] for e in alice]
    assert walls == sorted(walls) and all(w is not None for w in walls)


def test_obs_recursive_expansion_finds_nested_namespaces(tmp_path):
    ns = tmp_path / 'tenants' / 'alice' / 'job-000001' / 'logs'
    ns.mkdir(parents=True)
    (ns / 'host0.out').write_text('DONE final_step=8 epochs=2\n')
    (tmp_path / 'service.log').write_text(SERVICE_LOG)
    flat = aggregate.expand_paths([str(tmp_path)])
    assert str(ns / 'host0.out') not in flat
    deep = aggregate.expand_paths([str(tmp_path)], recursive=True)
    assert str(ns / 'host0.out') in deep
    assert str(tmp_path / 'service.log') in deep
    timeline = aggregate.build_timeline([str(tmp_path)], recursive=True)
    kinds = {e['kind'] for e in timeline['events']}
    assert 'run_done' in kinds and 'job_admit' in kinds


def test_obs_follow_streams_new_events(tmp_path):
    import io
    log_path = tmp_path / 'service.log'
    lines = SERVICE_LOG.splitlines(keepends=True)
    log_path.write_text(''.join(lines[:3]))

    def append_later():
        time.sleep(0.15)
        with open(log_path, 'a') as f:
            f.writelines(lines[3:])

    t = threading.Thread(target=append_later)
    t.start()
    out = io.StringIO()
    timeline = aggregate.follow([str(log_path)], interval=0.05,
                                duration=0.6, out=out)
    t.join()
    text = out.getvalue()
    # early events printed once, late events picked up live
    assert text.count('job_admit') == 3
    assert 'job_done' in text and 'pool_shrink' in text
    assert len(timeline['events']) == 8


def test_obs_follow_survives_incident_rotation(tmp_path):
    """A requeued job's fresh supervisor incarnation rotates
    incident.json to .prev and starts over: the new incarnation's
    event at the same index/kind must still stream (the dedup key
    carries the wall stamp)."""
    import io

    from kfac_pytorch_tpu.resilience import atomic_write_json
    inc = tmp_path / 'incident-host0.json'
    atomic_write_json(str(inc), {'host_id': 0, 'events': [
        {'kind': 'launch', 'wall': 100.0, 'gen': 0}]})

    def rotate_later():
        time.sleep(0.15)
        os.replace(inc, str(inc) + '.prev')
        atomic_write_json(str(inc), {'host_id': 0, 'events': [
            {'kind': 'launch', 'wall': 200.0, 'gen': 0}]})

    t = threading.Thread(target=rotate_later)
    t.start()
    out = io.StringIO()
    aggregate.follow([str(inc)], interval=0.05, duration=0.6, out=out)
    t.join()
    assert out.getvalue().count('launch') == 2


# ---------------------------------------------------------------------------
# multi-tenant policy: preemption / fair share / migration / autoscale
# ---------------------------------------------------------------------------

def test_suspend_rc_pinned_across_layers():
    """scheduler.py spells RC_SUSPENDED / SUSPEND_KEY as literals (to
    stay importable without the pod-supervisor stack): pin them equal
    to the resilience layer's, and to the rc grammar everywhere the
    suspend verdict travels."""
    from kfac_pytorch_tpu.resilience import elastic
    from kfac_pytorch_tpu.resilience.supervisor import STOP_RC_NAMES
    assert RC_SUSPENDED == elastic.RC_SUSPENDED == 119
    assert SUSPEND_KEY == elastic.SUSPEND_KEY == 'suspend.json'
    assert STOP_RC_NAMES['suspended'] == 119
    assert classify_rc(RC_SUSPENDED) == 'suspended'


def test_spec_weight_and_preemptible_validation():
    spec = validate_spec(_spec(weight=2.5, preemptible=False))
    assert spec.weight == 2.5 and spec.preemptible is False
    assert validate_spec(spec.to_dict()).to_dict() == spec.to_dict()
    # defaults: weight 1.0, preemptible True
    spec = validate_spec(_spec())
    assert spec.weight == 1.0 and spec.preemptible is True
    with pytest.raises(SpecError, match='weight'):
        validate_spec(_spec(weight=0))
    with pytest.raises(SpecError, match='weight'):
        validate_spec(_spec(weight=True))      # a bool is not a number
    with pytest.raises(SpecError, match='preemptible'):
        validate_spec(_spec(preemptible=1))


def test_preemption_suspends_victims_and_admits_high_priority(tmp_path,
                                                              caplog):
    """The whole preemption arc on fakes: an unplaceable high-priority
    job checkpoint-suspends BOTH running victims (request delivered
    into each pod's lease namespace), their RC_SUSPENDED exits park
    them uncharged, the preemptor admits the same cycle the slots
    free, and the victims resume once it finishes."""
    ctl, popen = _controller(tmp_path)              # h0: 2 slots
    ctl.queue.submit(_mini())                       # job 1 (alice)
    ctl.queue.submit(_mini(tenant='bob'))           # job 2
    with caplog.at_level(logging.WARNING, logger='svc-test'):
        ctl.step()
        assert len(popen.launches) == 2
        ctl.queue.submit(_mini(tenant='carol', priority=10, hosts=2,
                               preemptible=False))  # job 3: full pool
        ctl.step()
        # both victims asked to suspend; nothing new launched yet
        assert len(popen.launches) == 2
        for jid in (1, 2):
            run = ctl.running[jid]
            assert run.suspend is not None
            assert run.suspend['reason'] == 'preempt'
            # the request is a key the victim's supervisors read as
            # plain suspend.json (their backend root is the lease dir)
            req = ctl.coord.get(ctl._lease_key(run, SUSPEND_KEY))
            assert req is not None
            assert req.value['job'] == jid and req.value['by'] == 3
            assert req.value['reason'] == 'preempt'
        assert caplog.text.count('job_preempt') == 2
        assert ('job_preempt job=1 tenant=alice victim_of=3 '
                'priority=0 by_priority=10') in caplog.text
        # the supervisors land the checkpoint-suspend
        popen.procs[0].rc = RC_SUSPENDED
        popen.procs[1].rc = RC_SUSPENDED
        ctl.step()
        for jid, tenant in ((1, 'alice'), (2, 'bob')):
            rec = ctl.queue.read(jid)
            assert rec['state'] == 'suspended'
            assert rec['last_rc'] == RC_SUSPENDED
            assert rec['last_reason'] == 'preempt'
            assert rec['requeues'] == 0                  # uncharged
            assert rec.get('charged_requeues', 0) == 0
            assert rec['last_hosts'] == 'h0'
            # exactly-once: one park, one line per victim
            assert caplog.text.count(f'job_suspend job={jid} ') == 1
        # the preemptor admitted in the SAME cycle the slots freed
        assert ctl.queue.read(3)['state'] == 'running'
        assert len(popen.launches) == 4                  # 2 ranks
        # preemptor done -> victims resume (same host: no migrate edge)
        popen.procs[2].rc = 0
        popen.procs[3].rc = 0
        ctl.step()
    assert ctl.queue.read(3)['state'] == 'done'
    for jid in (1, 2):
        rec = ctl.queue.read(jid)
        assert rec['state'] == 'running' and rec['attempt'] == 2
        assert rec['last_reason'] == 'resume'
    assert len(popen.launches) == 6
    assert 'job_migrate' not in caplog.text


def test_suspend_releases_port_block_for_reallocation(tmp_path):
    """Satellite 2: a suspended job's KFAC_HB_PORT block releases (the
    preemptor can re-pin the same port without a conflict) and is
    re-claimed at resume."""
    ctl, popen = _controller(tmp_path, hosts={'h0': 1})
    ctl.queue.submit(_mini(env={'KFAC_HB_PORT': '9100'}))
    ctl.step()
    assert ctl.queue.read(1)['port'] == 9100
    # a higher-priority job pinning the SAME explicit port: only
    # admissible because the suspend released the victim's block
    ctl.queue.submit(_mini(tenant='bob', priority=5,
                           env={'KFAC_HB_PORT': '9100'}))
    ctl.step()                           # suspend requested
    assert ctl.running[1].suspend is not None
    popen.procs[0].rc = RC_SUSPENDED
    ctl.step()                           # parked; bob admits on the pin
    assert ctl.queue.read(1)['state'] == 'suspended'
    rec2 = ctl.queue.read(2)
    assert rec2['state'] == 'running' and rec2['port'] == 9100
    assert len(popen.launches) == 2      # no PortConflictError path
    # bob finishes: job 1 resumes and RE-claims its pinned block
    popen.procs[1].rc = 0
    ctl.step()
    rec1 = ctl.queue.read(1)
    assert rec1['state'] == 'running' and rec1['port'] == 9100
    assert rec1['attempt'] == 2


def test_weighted_fair_share_orders_admission(tmp_path, caplog):
    """Equal priority, one free slot: the tenant with the LOWER
    weighted dominant share (used / slots / weight) wins it — weight
    scales entitlement, and the accounting lands as tenant_share."""
    ctl, popen = _controller(tmp_path, hosts={'h0': 3})
    ctl.queue.submit(_mini(weight=1.0))                    # job 1 alice
    ctl.queue.submit(_mini(tenant='bob', weight=4.0))      # job 2
    with caplog.at_level(logging.WARNING, logger='svc-test'):
        ctl.step()
        assert len(popen.launches) == 2                    # 1 slot left
        ctl.queue.submit(_mini(weight=1.0))                # job 3 alice
        ctl.queue.submit(_mini(tenant='bob', weight=4.0))  # job 4
        ctl.step()
    # alice: 1/3/1 = 0.333 > bob: 1/3/4 = 0.083 -> bob is under-served
    assert ctl.queue.read(4)['state'] == 'running'
    assert ctl.queue.read(3)['state'] == 'queued'
    assert ('tenant_share tenant=alice used=1 of=3 weight=1.0 '
            'share=0.333') in caplog.text
    assert ('tenant_share tenant=bob used=1 of=3 weight=4.0 '
            'share=0.083') in caplog.text


def test_drain_suspends_and_migrates_preemptible_jobs(tmp_path, caplog):
    """A hosts.json entry flipped to draining: its preemptible job is
    ASKED to suspend (never killed), parks with its last placement
    stamped, and resumes on a DIFFERENT host — the job_migrate edge —
    once capacity frees there."""
    ctl, popen = _controller(tmp_path, hosts={'h0': 1, 'h1': 1})
    ctl.queue.submit(_mini())                       # job 1 -> h0
    ctl.queue.submit(_mini(tenant='bob'))           # job 2 -> h1
    with caplog.at_level(logging.WARNING, logger='svc-test'):
        ctl.step()
        assert ctl.queue.read(2)['placement'] == {'0': 'h1'}
        atomic_write_json(ctl.hosts_path,
                          {'hosts': {'h0': 1, 'h1': {'slots': 1,
                                                     'draining': True}}})
        ctl.step()
        # zero-loss: a drain asks, it does not kill
        assert 'pool_shrink slots=2 -> 1' in caplog.text
        assert ctl.running[2].suspend is not None
        assert ctl.running[2].suspend['reason'] == 'drain'
        assert ctl.running[1].suspend is None       # other host: untouched
        assert not ctl._test_killed
        popen.procs[1].rc = RC_SUSPENDED
        ctl.step()
        rec = ctl.queue.read(2)
        assert rec['state'] == 'suspended'
        assert rec['last_reason'] == 'drain'
        assert rec['last_hosts'] == 'h1'
        # h0 still busy: the suspension parks until capacity frees
        popen.procs[0].rc = 0
        ctl.step()                  # job 1 done -> job 2 resumes on h0
    assert ctl.queue.read(1)['state'] == 'done'
    rec = ctl.queue.read(2)
    assert rec['state'] == 'running'
    assert rec['placement'] == {'0': 'h0'}
    assert ('job_migrate job=2 tenant=bob from=h1 to=h0 attempt=2'
            in caplog.text)


def test_drain_leaves_non_preemptible_jobs_in_place(tmp_path):
    ctl, popen = _controller(tmp_path, hosts={'h0': 1})
    ctl.queue.submit(_mini(preemptible=False))
    ctl.step()
    atomic_write_json(ctl.hosts_path,
                      {'hosts': {'h0': {'slots': 1, 'draining': True}}})
    ctl.step()
    assert ctl.running[1].suspend is None      # finishes in place
    assert not ctl._test_killed
    popen.procs[0].rc = 0
    ctl.step()
    assert ctl.queue.read(1)['state'] == 'done'


def test_autoscale_emits_scale_requests_on_demand_change(tmp_path,
                                                         caplog):
    """Queue-driven capacity requests: scale-request.json carries live
    demand, re-emitted only on CHANGE; a responder growing hosts.json
    is adopted by the ordinary refresh and the queue drains into it."""
    ctl, popen = _controller(tmp_path, hosts={'h0': 1}, autoscale=True)
    for tenant in ('alice', 'bob', 'carol'):
        ctl.queue.submit(_mini(tenant=tenant))
    with caplog.at_level(logging.WARNING, logger='svc-test'):
        ctl.step()
        req = ctl.coord.get('scale-request.json').value
        assert req['desired_slots'] == 3 and req['capacity'] == 1
        assert caplog.text.count('scale_request') == 1
        ctl.step()                       # demand unchanged: no re-emit
        assert caplog.text.count('scale_request') == 1
        # the responder answers: capacity grows, the queue drains
        atomic_write_json(ctl.hosts_path, {'hosts': {'h0': 1, 'a0': 2}})
        ctl.step()
        assert 'pool_grow' in caplog.text
        states = {r['id']: r['state'] for r in ctl.queue.jobs()}
        assert states == {1: 'running', 2: 'running', 3: 'running'}
        for p in popen.procs:
            p.rc = 0
        ctl.step()                       # demand drops: a new request
    assert ctl.coord.get('scale-request.json').value['desired_slots'] == 0
    assert caplog.text.count('scale_request') == 2


def test_suspend_grace_escalates_to_sigkill_and_still_parks(tmp_path,
                                                            caplog):
    """A victim that never winds down is SIGKILLed past the grace
    deadline — and the -9 exits STILL park it SUSPENDED (run.suspend
    routes the verdict), uncharged: the last banked checkpoint carries
    the resume."""
    ctl, popen = _controller(tmp_path, hosts={'h0': 1},
                             suspend_grace=0.0)
    ctl.queue.submit(_mini())
    with caplog.at_level(logging.WARNING, logger='svc-test'):
        ctl.step()
        ctl.queue.submit(_mini(tenant='bob', priority=5))
        ctl.step()                       # suspend requested, grace 0
        assert ctl.running[1].suspend is not None
        ctl.step()                       # deadline passed: escalate
        assert popen.procs[0].pid in ctl._test_killed
        assert 'suspend grace' in caplog.text
        popen.procs[0].rc = -9           # the SIGKILL lands
        ctl.step()
    rec = ctl.queue.read(1)
    assert rec['state'] == 'suspended'
    assert rec['last_reason'] == 'preempt'
    assert rec['requeues'] == 0 and rec.get('charged_requeues', 0) == 0
    assert ctl.queue.read(2)['state'] == 'running'


def test_watchless_scan_skip_returns_cached_verdict(tmp_path):
    """Satellite 1's degraded half: with no watch events, no dirty
    flag and no due backoff, step(scan=False) answers from the cached
    verdict WITHOUT re-reading the job table; a capacity edit re-arms
    the scan."""
    ctl, popen = _controller(tmp_path)
    ctl.queue.submit(_mini())
    assert ctl.step() is True
    calls = []
    orig = ctl.queue.jobs
    ctl.queue.jobs = lambda: calls.append(1) or orig()
    assert ctl.step(ingest=False, scan=False) is True
    assert calls == []                   # the scan really was skipped
    atomic_write_json(ctl.hosts_path, {'hosts': {'h0': 4}})
    assert ctl.step(ingest=False, scan=False) is True
    assert calls == [1]                  # hosts change forced the scan


MT_SERVICE_LOG = """\
2026-08-03 11:00:01,000 service: tenant_share tenant=alice used=2 of=4 weight=1.0 share=0.500
2026-08-03 11:00:01,100 service: scale_request desired=6 capacity=4 queued=2 suspended=0
2026-08-03 11:00:02,000 service: job_preempt job=1 tenant=alice victim_of=3 priority=0 by_priority=10 grace_s=30.0
2026-08-03 11:00:02,500 pod-supervisor: suspending on request — trainer stopped (grace checkpoint banked, trainer rc was -15), exiting rc=119 with no further commits [resilience: suspended=1]
2026-08-03 11:00:03,000 service: job_suspend job=1 tenant=alice rc=119 reason=preempt hosts=h0 attempt=1
2026-08-03 11:00:09,000 service: job_migrate job=1 tenant=alice from=h0 to=h1 attempt=2
"""


def test_incident_grammar_scrapes_multi_tenant_events(tmp_path):
    """Every ISSUE-17 emit site speaks the shared grammar: the five
    service events plus the supervisor's suspend verdict scrape with
    their fields intact (kfac-obs needs zero new aggregation code)."""
    log_path = tmp_path / 'service.log'
    log_path.write_text(MT_SERVICE_LOG)
    report = IncidentReport().scrape_path(str(log_path))
    events = {e['kind']: e for e in report.events}
    assert set(events) >= {'tenant_share', 'scale_request',
                           'job_preempt', 'suspended', 'job_suspend',
                           'job_migrate'}
    assert events['tenant_share']['tenant'] == 'alice'
    assert events['tenant_share']['used'] == 2
    assert events['tenant_share']['weight'] == 1.0
    assert events['scale_request']['desired'] == 6
    assert events['scale_request']['capacity'] == 4
    pre = events['job_preempt']
    assert pre['job'] == 1 and pre['victim_of'] == 3
    assert pre['priority'] == 0 and pre['by_priority'] == 10
    sup = events['suspended']
    assert sup['rc'] == 119 and sup['trainer_rc'] == -15
    susp = events['job_suspend']
    assert susp['rc'] == 119 and susp['why'] == 'preempt'
    assert susp['on'] == 'h0'
    mig = events['job_migrate']
    assert mig['from'] == 'h0' and mig['to'] == 'h1'
    # and the per-tenant timeline keeps causal order
    timeline = aggregate.build_timeline([str(log_path)])
    kinds = [e['kind'] for e in timeline['events']
             if e['detail'].get('tenant') == 'alice'
             and e['kind'].startswith('job_')]
    assert kinds == ['job_preempt', 'job_suspend', 'job_migrate']
