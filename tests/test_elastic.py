"""Elastic world-size resume (utils.reshard_kfac_state, beyond the
reference): a checkpoint taken at one mesh size restores into another.

The stacked-bucket factor layout is device-major per world size, so the
transport must re-map every layer's A/G blocks across the two plans.
Oracles:
  - MPD 'eigen' factor stats are world-size invariant (pmean = global
    batch), so resharding an nd=2 state to nd=4 must reproduce the
    factors of a NATIVE nd=4 run on the same batches — an independent
    end-to-end check of the transport;
  - the 2 -> 4 -> 2 roundtrip is bit-exact on every true factor block;
  - training continues from the resharded state (decomp re-zeroed ->
    the trainer's factors_only degrade path, then a normal step).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh

import kfac_pytorch_tpu as kfac
from kfac_pytorch_tpu import capture, training, utils as kutils
from tests.helpers import TinyCNN

pytestmark = pytest.mark.core

B, HW = 8, 8


def _batch(seed=0):
    rng = np.random.RandomState(seed)
    return {'input': jnp.asarray(rng.randn(B, HW, HW, 3), jnp.float32),
            'label': jnp.asarray(rng.randint(0, 10, B))}


def _ce(outputs, batch):
    return optax.softmax_cross_entropy_with_integer_labels(
        outputs, batch['label']).mean()


def _make(nd, model):
    axis = 'batch' if nd > 1 else None
    mesh = (Mesh(np.array(jax.devices()[:nd]), ('batch',)) if nd > 1
            else None)
    pre = kfac.KFAC(variant='eigen', lr=0.1, damping=0.003,
                    fac_update_freq=1, kfac_update_freq=2,
                    num_devices=nd, axis_name=axis)
    tx = training.sgd(0.1, momentum=0.9)
    state = training.init_train_state(model, tx, pre,
                                      jax.random.PRNGKey(0),
                                      _batch()['input'])
    step = training.build_train_step(model, tx, pre, _ce,
                                     axis_name=axis, mesh=mesh,
                                     donate=False)
    return pre, state, step


def _run(step, state, n):
    for i in range(n):
        state, m = step(state, _batch(i), lr=0.1, damping=0.003)
    return state, float(m['loss'])


# the grow tests step worlds of size 2, 3 AND 4 on one batch stream:
# 12 divides by all three (shard_map rejects uneven batch shards)
B3 = 12


def _batch3(seed=0):
    rng = np.random.RandomState(seed)
    return {'input': jnp.asarray(rng.randn(B3, HW, HW, 3), jnp.float32),
            'label': jnp.asarray(rng.randint(0, 10, B3))}


def _run3(step, state, n):
    for i in range(n):
        state, m = step(state, _batch3(i), lr=0.1, damping=0.003)
    return state, float(m['loss'])


def _layer_blocks(pre, factors):
    """{layer path: (A block, G block)} in true dims via the plan map."""
    out = {}
    for i, meta in enumerate(pre.plan.metas):
        ba, ra, bg, rg, _ = pre.plan.layer_rows[i]
        da, dg = meta.in_dim, meta.out_dim
        out[meta.path] = (
            np.asarray(factors[str(ba)])[ra, :da, :da],
            np.asarray(factors[str(bg)])[rg, :dg, :dg])
    return out


def test_reshard_matches_native_world_and_roundtrips():
    model = TinyCNN(batch_norm=False)
    pre2, state2, step2 = _make(2, model)
    pre4, state4, step4 = _make(4, model)
    state2, _ = _run(step2, state2, 3)
    state4, _ = _run(step4, state4, 3)

    resharded = kutils.reshard_kfac_state(pre2, pre4, state2.kfac_state)

    # layout sanity: the resharded state has the nd=4 plan's shapes
    jax.tree.map(lambda a, b: np.testing.assert_equal(a.shape, b.shape),
                 resharded.factors, state4.kfac_state.factors)
    assert int(resharded.step) == int(state2.kfac_state.step)

    # world-size-invariant MPD stats: transported factors equal the
    # NATIVE nd=4 run's, layer by layer (reduction-order tolerance)
    got = _layer_blocks(pre4, resharded.factors)
    want = _layer_blocks(pre4, state4.kfac_state.factors)
    for path in want:
        for g, w in zip(got[path], want[path]):
            np.testing.assert_allclose(g, w, rtol=1e-4, atol=1e-6)

    # roundtrip 2 -> 4 -> 2 is exact on every true block
    back = kutils.reshard_kfac_state(pre4, pre2, resharded)
    got2 = _layer_blocks(pre2, back.factors)
    orig = _layer_blocks(pre2, state2.kfac_state.factors)
    for path in orig:
        for g, w in zip(got2[path], orig[path]):
            np.testing.assert_array_equal(g, w)


def test_training_continues_after_reshard():
    model = TinyCNN(batch_norm=False)
    pre2, state2, step2 = _make(2, model)
    pre4, state4, step4 = _make(4, model)
    state2, _ = _run(step2, state2, 3)

    carried = kutils.reshard_kfac_state(pre2, pre4, state2.kfac_state)
    # adopt params/opt state as a real resume would — through the host
    # (a disk restore lands there anyway); leaves committed to the old
    # 2-device mesh cannot feed the 4-device step directly
    host = jax.device_get
    state = state4.replace(step=host(state2.step),
                           params=host(state2.params),
                           opt_state=host(state2.opt_state),
                           extra_vars=host(state2.extra_vars),
                           kfac_state=host(carried))
    state, loss = _run(step4, state, 3)
    assert np.isfinite(loss), loss
    # the decomposition re-populated after the resumed inverse updates
    assert any(bool(jnp.any(x != 0))
               for x in jax.tree.leaves(state.kfac_state.decomp))


def test_reshard_uneven_world_with_pad_rows_roundtrips():
    """Shrink edge case: a world size that does not divide the slot
    count — the device-major bucket layout then carries dummy pad rows
    in one plan and not the other, and the transport must land every
    TRUE block while ignoring the padding. 5 Dense layers = 10 factor
    slots: nd=4 pads (10 % 4 != 0), nd=2 does not."""
    from kfac_pytorch_tpu import nn as knn
    import flax.linen as linen

    class FiveMLP(linen.Module):
        @linen.compact
        def __call__(self, x, train=True):
            x = x.reshape((x.shape[0], -1))
            for i, w in enumerate((17, 13, 11, 9)):
                x = linen.relu(knn.Dense(w, name=f'd{i}')(x))
            return knn.Dense(10, name='out')(x)

    model = FiveMLP()
    pre2, state2, step2 = _make(2, model)
    pre4, state4, step4 = _make(4, model)
    # the two plans pad differently (device-major rows per world size),
    # so rows genuinely move between real and dummy positions
    pad4 = sum(1 for b in pre4.plan.buckets.values()
               for s in b.slot_of_row if s is None)
    pad2 = sum(1 for b in pre2.plan.buckets.values()
               for s in b.slot_of_row if s is None)
    assert pad4 > pad2 > 0 or (pad4 > 0 and pad2 == 0), (pad4, pad2)

    state2, _ = _run(step2, state2, 3)
    up = kutils.reshard_kfac_state(pre2, pre4, state2.kfac_state)
    back = kutils.reshard_kfac_state(pre4, pre2, up)
    got = _layer_blocks(pre2, back.factors)
    want = _layer_blocks(pre2, state2.kfac_state.factors)
    for path in want:
        for g, w in zip(got[path], want[path]):
            np.testing.assert_array_equal(g, w)
    # and training continues in the padded world
    host = jax.device_get
    state = state4.replace(step=host(state2.step),
                           params=host(state2.params),
                           opt_state=host(state2.opt_state),
                           extra_vars=host(state2.extra_vars),
                           kfac_state=host(up))
    state, loss = _run(step4, state, 2)
    assert np.isfinite(loss), loss


def test_reshard_grow_uneven_world_with_pad_rows(tmp_path, monkeypatch):
    """The GROW direction (ISSUE 6): a 2-shard state reshards UP into a
    3-shard world whose device-major layout needs pad rows the 2-shard
    plan never had. Oracles: the transported factors match a NATIVE
    nd=3 run (MPD stats are world-size invariant), the new plan's pad
    rows stay exactly at the fresh zero init (pad-row-exact: growing
    must never scatter true data into a dummy slot), the 2->3->2
    roundtrip is bit-exact, and the full elastic_resume path routes a
    2-stamped checkpoint into the 3-world trainer."""
    from kfac_pytorch_tpu import nn as knn, resilience
    from kfac_pytorch_tpu.utils import checkpoint as ckpt
    import flax.linen as linen

    class FiveMLP(linen.Module):
        @linen.compact
        def __call__(self, x, train=True):
            x = x.reshape((x.shape[0], -1))
            for i, w in enumerate((17, 13, 11, 9)):
                x = linen.relu(knn.Dense(w, name=f'd{i}')(x))
            return knn.Dense(10, name='out')(x)

    model = FiveMLP()
    pre2, state2, step2 = _make(2, model)
    pre3, state3, step3 = _make(3, model)
    # 10 factor slots: the nd=3 device-major layout needs a DIFFERENT
    # pad-row pattern than nd=2 — growing genuinely moves rows between
    # true and dummy positions
    pad3 = [(b, r) for b, bucket in pre3.plan.buckets.items()
            for r, s in enumerate(bucket.slot_of_row) if s is None]
    pad2 = sum(1 for b in pre2.plan.buckets.values()
               for s in b.slot_of_row if s is None)
    assert pad3 and len(pad3) != pad2, (pad3, pad2)

    state2, _ = _run3(step2, state2, 3)
    up = kutils.reshard_kfac_state(pre2, pre3, state2.kfac_state)

    # layout sanity: the grown state has the nd=3 plan's shapes
    jax.tree.map(lambda a, b: np.testing.assert_equal(a.shape, b.shape),
                 up.factors, state3.kfac_state.factors)
    # every true block landed exactly where the nd=3 plan maps it
    got = _layer_blocks(pre3, up.factors)
    want = _layer_blocks(pre2, state2.kfac_state.factors)
    for path in want:
        for g, w in zip(got[path], want[path]):
            np.testing.assert_array_equal(g, w)
    # pad rows stayed bit-identical to the fresh init — nothing leaked
    # into slots no layer owns
    fresh3 = pre3.init()
    for b, r in pad3:
        np.testing.assert_array_equal(
            np.asarray(up.factors[str(b)])[r],
            np.asarray(fresh3.factors[str(b)])[r])

    # grow roundtrip 2 -> 3 -> 2 is exact
    back = kutils.reshard_kfac_state(pre3, pre2, up)
    got2 = _layer_blocks(pre2, back.factors)
    orig = _layer_blocks(pre2, state2.kfac_state.factors)
    for path in orig:
        for g, w in zip(got2[path], orig[path]):
            np.testing.assert_array_equal(g, w)

    # the full grow-relaunch path: checkpoint + stamp at world 2,
    # trainer relaunches at world 3 — params/opt state restore
    # bit-identical, factors arrive via the transport, and the hook
    # callback fires with the right worlds
    monkeypatch.setattr(ckpt, '_HAS_ORBAX', False)
    ckpt.save_checkpoint(tmp_path, 0, state2)
    ckpt.write_world_stamp(tmp_path, 2, gen=5)
    assert ckpt.read_world_stamp_info(tmp_path) == {'num_devices': 2,
                                                    'gen': 5}
    changes = []

    def make_old(nd):
        pre = kfac.KFAC(variant='eigen', lr=0.1, damping=0.003,
                        fac_update_freq=1, kfac_update_freq=2,
                        num_devices=nd,
                        axis_name='batch' if nd > 1 else None)
        pre.setup(pre3.plan.metas)
        return pre

    restored, epoch, old_world = resilience.elastic_resume(
        tmp_path, 5, pre3, state3, make_precond=make_old,
        on_world_change=lambda ow, nw: changes.append((ow, nw)))
    assert epoch == 0 and old_world == 2
    assert changes == [(2, 3)]
    host = jax.device_get
    jax.tree.map(np.testing.assert_array_equal,
                 host(restored.params), host(state2.params))
    jax.tree.map(np.testing.assert_array_equal,
                 host(restored.opt_state), host(state2.opt_state))
    # and training continues in the grown (padded) world
    state, loss = _run3(step3, restored, 2)
    assert np.isfinite(loss), loss


def test_reshard_grow_world_roundtrip_is_identity():
    """Acceptance pin: N -> M -> N equals N for a grow (N < M), on the
    ENTIRE factor pytree — not just the true blocks — because the
    roundtrip lands back in the N-layout where every row is a true row
    or a pad row both sides zero-initialized identically."""
    model = TinyCNN(batch_norm=False)
    pre2, state2, step2 = _make(2, model)
    pre4, _, _ = _make(4, model)
    state2, _ = _run(step2, state2, 4)
    up = kutils.reshard_kfac_state(pre2, pre4, state2.kfac_state)
    back = kutils.reshard_kfac_state(pre4, pre2, up)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        back.factors, state2.kfac_state.factors)
    assert int(back.step) == int(state2.kfac_state.step)


def test_ekfac_scales_zero_filled_then_reaccumulate_on_grow():
    """E-KFAC grow edge case (ISSUE 6 satellite): growing 2 -> 3, the
    transported state carries only the FACTORS; the basis-bound scales
    come back zero-FILLED for every shard — including the brand-new
    third shard's rows — and re-accumulate after the first inverse
    update in the grown world."""
    model = TinyCNN(batch_norm=False)

    def _make_ekfac(nd):
        axis = 'batch' if nd > 1 else None
        mesh = (Mesh(np.array(jax.devices()[:nd]), ('batch',)) if nd > 1
                else None)
        pre = kfac.KFAC(variant='ekfac', lr=0.1, damping=0.03,
                        fac_update_freq=1, kfac_update_freq=2,
                        num_devices=nd, axis_name=axis)
        tx = training.sgd(0.1, momentum=0.9)
        state = training.init_train_state(model, tx, pre,
                                          jax.random.PRNGKey(0),
                                          _batch()['input'])
        step = training.build_train_step(model, tx, pre, _ce,
                                         axis_name=axis, mesh=mesh,
                                         donate=False)
        return pre, state, step

    pre2, state2, step2 = _make_ekfac(2)
    pre3, state3, step3 = _make_ekfac(3)
    state2, _ = _run3(step2, state2, 4)
    assert any(np.any(np.asarray(v) != 0)
               for v in state2.kfac_state.decomp['scales'].values())

    carried = kutils.reshard_kfac_state(pre2, pre3, state2.kfac_state)
    # scales zero-filled across ALL shards of the grown world
    assert all(not np.any(np.asarray(v))
               for v in carried.decomp['scales'].values())
    host = jax.device_get
    state = state3.replace(step=host(state2.step),
                           params=host(state2.params),
                           opt_state=host(state2.opt_state),
                           extra_vars=host(state2.extra_vars),
                           kfac_state=host(carried))
    state, loss = _run3(step3, state, 4)
    assert np.isfinite(loss), loss
    # basis AND moments rebuilt by the resumed inverse updates
    assert any(np.any(np.asarray(v) != 0)
               for v in state.kfac_state.decomp['scales'].values())


def test_ekfac_scales_reaccumulate_after_transport():
    """E-KFAC shrink edge case: the transported state carries only the
    FACTORS — the eigenbasis-bound scales re-initialize to zero and must
    re-accumulate after the first inverse update in the new world (they
    are meaningless against a recomputed basis, so carrying them would
    be wrong, not just unnecessary)."""
    model = TinyCNN(batch_norm=False)

    def _make_ekfac(nd):
        axis = 'batch' if nd > 1 else None
        mesh = (Mesh(np.array(jax.devices()[:nd]), ('batch',)) if nd > 1
                else None)
        pre = kfac.KFAC(variant='ekfac', lr=0.1, damping=0.03,
                        fac_update_freq=1, kfac_update_freq=2,
                        num_devices=nd, axis_name=axis)
        tx = training.sgd(0.1, momentum=0.9)
        state = training.init_train_state(model, tx, pre,
                                          jax.random.PRNGKey(0),
                                          _batch()['input'])
        step = training.build_train_step(model, tx, pre, _ce,
                                         axis_name=axis, mesh=mesh,
                                         donate=False)
        return pre, state, step

    pre2, state2, step2 = _make_ekfac(2)
    pre1, state1, step1 = _make_ekfac(1)
    state2, _ = _run(step2, state2, 4)
    assert any(np.any(np.asarray(v) != 0)
               for v in state2.kfac_state.decomp['scales'].values())

    carried = kutils.reshard_kfac_state(pre2, pre1, state2.kfac_state)
    # scales zeroed by the transport (basis-bound, like the decomp)
    assert all(not np.any(np.asarray(v))
               for v in carried.decomp['scales'].values())
    host = jax.device_get
    state = state1.replace(step=host(state2.step),
                           params=host(state2.params),
                           opt_state=host(state2.opt_state),
                           extra_vars=host(state2.extra_vars),
                           kfac_state=host(carried))
    state, loss = _run(step1, state, 4)
    assert np.isfinite(loss), loss
    # the resumed inverse updates rebuilt basis AND moments
    assert any(np.any(np.asarray(v) != 0)
               for v in state.kfac_state.decomp['scales'].values())


def test_elastic_resume_reshards_stamped_checkpoint(tmp_path, monkeypatch):
    """The full elastic-resume path a shrunken pod's relaunch takes:
    checkpoint + world stamp written at nd=2, trainer comes back at
    nd=4 — elastic_resume restores against the OLD structure, reshards
    the factors, and training continues; without a stamp (or with a
    matching one) it behaves exactly like auto_resume."""
    from kfac_pytorch_tpu import resilience
    from kfac_pytorch_tpu.utils import checkpoint as ckpt
    monkeypatch.setattr(ckpt, '_HAS_ORBAX', False)
    model = TinyCNN(batch_norm=False)
    pre2, state2, step2 = _make(2, model)
    state2, _ = _run(step2, state2, 3)
    ckpt.save_checkpoint(tmp_path, 0, state2)
    ckpt.write_world_stamp(tmp_path, 2)

    pre4, state4, step4 = _make(4, model)

    def make_old(nd):
        pre = kfac.KFAC(variant='eigen', lr=0.1, damping=0.003,
                        fac_update_freq=1, kfac_update_freq=2,
                        num_devices=nd,
                        axis_name='batch' if nd > 1 else None)
        pre.setup(pre4.plan.metas)
        return pre

    restored, epoch, old_world = resilience.elastic_resume(
        tmp_path, 5, pre4, state4, make_precond=make_old)
    assert epoch == 0 and old_world == 2
    assert int(restored.step) == int(state2.step)
    # the transported factors match a direct reshard of the live state
    want = kutils.reshard_kfac_state(pre2, pre4, state2.kfac_state)
    got = _layer_blocks(pre4, restored.kfac_state.factors)
    ref = _layer_blocks(pre4, want.factors)
    for path in ref:
        for g, w in zip(got[path], ref[path]):
            np.testing.assert_array_equal(g, w)
    state, loss = _run(step4, restored, 2)
    assert np.isfinite(loss), loss

    # matching stamp -> plain auto_resume territory (no reshard)
    ckpt.write_world_stamp(tmp_path, 4)
    ckpt.save_checkpoint(tmp_path, 1, state)
    again, epoch2, ow2 = resilience.elastic_resume(
        tmp_path, 5, pre4, state4, make_precond=make_old)
    assert epoch2 == 1 and ow2 is None

    # nothing restorable -> (None, None, old_world)
    empty = tmp_path / 'empty'
    none_state, none_epoch, _ = resilience.elastic_resume(
        empty, 5, pre4, state4, make_precond=make_old)
    assert none_state is None and none_epoch is None


def test_reshard_rejects_mismatched_layer_sets():
    model = TinyCNN(batch_norm=False)
    pre2, state2, _ = _make(2, model)
    other = kfac.KFAC(variant='eigen', lr=0.1, damping=0.003,
                      num_devices=4, axis_name='batch')
    x = _batch()['input']

    from kfac_pytorch_tpu import nn as knn
    import flax.linen as linen

    class Different(linen.Module):
        @linen.compact
        def __call__(self, x, train=True):
            x = x.reshape((x.shape[0], -1))
            return knn.Dense(10, name='other')(x)

    dm = Different()
    variables = capture.init(dm, jax.random.PRNGKey(0), x)
    other.setup(capture.collect_layer_meta(dm, variables, x))
    with pytest.raises(AssertionError, match='same layer set'):
        kutils.reshard_kfac_state(pre2, other, state2.kfac_state)
