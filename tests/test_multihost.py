"""Multi-process jax.distributed validation — the launch-layer path.

Spawns two real Python processes on localhost, each owning 4 virtual CPU
devices, and runs the full sharded K-FAC train step over the joint
8-device mesh: exercises ``parallel.mesh.maybe_initialize_distributed``
(the launcher contract of launch_tpu.sh — the mpirun/hostfile replacement,
reference: launch_horovod.sh:32) plus cross-process batch sharding via
``host_local_array_to_global_array``. Both processes must see the same
decreasing loss."""

import os
import subprocess
import sys

import pytest

from tests.helpers import communicate_all, free_port, run_two_process  # noqa: F401

_WORKER = r'''
import os, sys
os.environ['XLA_FLAGS'] = '--xla_force_host_platform_device_count=4'
import jax
jax.config.update('jax_platforms', 'cpu')
sys.path.insert(0, %(repo)r)
from kfac_pytorch_tpu.parallel import mesh as kmesh
assert kmesh.maybe_initialize_distributed(), 'init path not taken'
import numpy as np, jax.numpy as jnp, optax
import kfac_pytorch_tpu as kfac
from kfac_pytorch_tpu import training
import flax.linen as nn
from kfac_pytorch_tpu.nn import Dense

pid = jax.process_index()
assert jax.process_count() == 2, jax.process_count()
assert len(jax.devices()) == 8, len(jax.devices())

class MLP(nn.Module):
    @nn.compact
    def __call__(self, x, train=True):
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(Dense(32)(x))
        return Dense(10)(x)

from jax.sharding import Mesh, PartitionSpec as P
from jax.experimental import multihost_utils
mesh = Mesh(np.array(jax.devices()), ('batch',))
rng = np.random.RandomState(0)
precond = kfac.KFAC(variant='eigen_dp', lr=0.1, damping=0.003,
                    num_devices=8, axis_name='batch')
tx = training.sgd(0.1, momentum=0.9)
x_local = rng.randn(16, 8, 8, 3)[pid*8:(pid+1)*8].astype(np.float32)
y_local = rng.randint(0, 10, 16)[pid*8:(pid+1)*8]
batch = {
    'input': multihost_utils.host_local_array_to_global_array(
        jnp.asarray(x_local), mesh, P('batch')),
    'label': multihost_utils.host_local_array_to_global_array(
        jnp.asarray(y_local), mesh, P('batch')),
}
model = MLP()
state = training.init_train_state(model, tx, precond, jax.random.PRNGKey(0),
                                  jnp.zeros((2, 8, 8, 3), jnp.float32))
ce = lambda out, b: optax.softmax_cross_entropy_with_integer_labels(
    out, b['label']).mean()
step = training.build_train_step(model, tx, precond, ce,
                                 axis_name='batch', mesh=mesh)
ls = []
for i in range(4):
    state, m = step(state, batch, lr=0.1, damping=0.003)
    ls.append(float(np.asarray(m['loss'].addressable_data(0))))
assert ls[-1] < ls[0], ls
ckdir = os.environ.get('KFAC_TEST_CKPT_DIR')
if ckdir:
    # every process calls save/restore: orbax coordinates through global
    # barriers (rank-0-only calls would hang the other ranks)
    from kfac_pytorch_tpu import utils as kutils
    kutils.save_checkpoint(ckdir, 0, state)
    kutils.wait_for_checkpoints()
    restored = kutils.restore_checkpoint(ckdir, 0, state)
    assert int(np.asarray(restored.step.addressable_data(0))) == 4
    print('CKPT OK', flush=True)
print(f'LOSSES {ls[0]:.6f} {ls[-1]:.6f}', flush=True)
'''


_COMPOSITE_WORKER = r'''
import os, sys
os.environ['XLA_FLAGS'] = ('--xla_force_host_platform_device_count='
                           + os.environ.get('KFAC_CHIPS_PER_HOST', '4'))
import jax
jax.config.update('jax_platforms', 'cpu')
sys.path.insert(0, %(repo)r)
# the pod-preset arg injection must reach the trainer argv through the
# multihost path too (launch_tpu.sh appends from configs/pod8)
assert sys.argv[-2:] == ['--num-devices', '8'], sys.argv
from kfac_pytorch_tpu.parallel import mesh as kmesh
assert kmesh.maybe_initialize_distributed(), 'launcher env not honored'
import functools
import numpy as np, jax.numpy as jnp, optax
from flax import linen
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
import kfac_pytorch_tpu as kfac
from kfac_pytorch_tpu import capture
from kfac_pytorch_tpu.parallel import tp

assert jax.process_count() == 2, jax.process_count()
assert len(jax.devices()) == 8, len(jax.devices())

# composite ('data', 'model') mesh laid out the way a pod would be:
# the model/TP axis inside each host (ICI), data parallelism across the
# two processes (DCN) — devices 0-3 belong to process 0, 4-7 to 1
ND, NM = 2, 4
mesh = Mesh(np.array(jax.devices()).reshape(ND, NM), ('data', 'model'))
B, DIN, DH_L, DOUT = 8, 6, 4, 5

class TPMLP(linen.Module):
    axis: object = 'model'
    @linen.compact
    def __call__(self, x, train=True):
        x = tp.ColumnParallelDense(DH_L, axis=self.axis, name='l1')(x)
        x = linen.relu(x)
        return tp.RowParallelDense(DOUT, axis=self.axis, name='l2')(x)

rng = np.random.RandomState(0)
x = rng.randn(B, DIN).astype(np.float32)
y = rng.randint(0, DOUT, B)
gp = {'l1': {'slice': {
          'kernel': (rng.randn(DIN, NM * DH_L) * 0.3).astype(np.float32),
          'bias': np.zeros(NM * DH_L, np.float32)}},
      'l2': {'slice': {
          'kernel': (rng.randn(NM * DH_L, DOUT) * 0.3).astype(np.float32)},
          'bias': np.zeros(DOUT, np.float32)}}
pspecs = {'l1': {'slice': {'kernel': P(None, 'model'),
                           'bias': P('model')}},
          'l2': {'slice': {'kernel': P('model', None)}, 'bias': P()}}

pre = kfac.KFAC(variant='eigen_dp', lr=0.1, damping=0.003,
                fac_update_freq=1, kfac_update_freq=1,
                num_devices=ND, axis_name='data')
local = TPMLP(axis=None)
xs = jnp.asarray(x[:2])
variables = capture.init(local, jax.random.PRNGKey(0), xs)
pre.setup(capture.collect_layer_meta(local, variables, xs))
kstate = jax.tree.map(lambda a: jnp.stack([a] * NM), pre.init())
kspecs = jax.tree.map(lambda s: P('model', *s), pre.state_pspecs('data'),
                      is_leaf=lambda v: isinstance(v, P))
model = TPMLP(axis='model')

def ce(out, y):
    return optax.softmax_cross_entropy_with_integer_labels(out, y).mean()

@functools.partial(
    jax.shard_map, mesh=mesh,
    in_specs=(pspecs, kspecs, P('data'), P('data')),
    out_specs=(pspecs, kspecs, P()))
def step(params, kstate, x, y):
    loss, _, grads, acts, gs, _ = capture.value_and_grad_with_capture(
        model, lambda out: ce(out, y), {'params': params}, x,
        axis_name=('data', 'model'))
    capture.check_local_mean_loss(loss, (x, y), 'data')
    grads = kfac.parallel.average_grads(grads, 'data')
    # the row-parallel forward already psummed over 'model', so the
    # local-mean loss varies over 'data' only
    loss = kfac.parallel.pmean(loss, 'data')
    k = jax.tree.map(lambda a: a[0], kstate)
    new_grads, k = pre.step(k, grads, acts, gs, axis_name='data')
    params = jax.tree.map(lambda p, g: p - 0.1 * g, params, new_grads)
    return params, jax.tree.map(lambda a: a[None], k), loss

jitted = jax.jit(step)
put = lambda v, specs: jax.tree.map(
    lambda a, s: jax.device_put(jnp.asarray(a), NamedSharding(mesh, s)),
    v, specs)
gp = put(gp, pspecs)
kstate = put(kstate, kspecs)
xg = jax.device_put(jnp.asarray(x), NamedSharding(mesh, P('data')))
yg = jax.device_put(jnp.asarray(y), NamedSharding(mesh, P('data')))
losses = []
for i in range(3):
    gp, kstate, loss = jitted(gp, kstate, xg, yg)
    losses.append(float(np.asarray(loss.addressable_data(0))))
assert losses[-1] < losses[0], losses
print('COMPOSITE LOSSES ' + ' '.join('%%.6f' %% l for l in losses),
      flush=True)
'''


_PIPELINE_WORKER = r'''
import os, sys
os.environ['XLA_FLAGS'] = '--xla_force_host_platform_device_count=4'
import jax
jax.config.update('jax_platforms', 'cpu')
sys.path.insert(0, %(repo)r)
from kfac_pytorch_tpu.parallel import mesh as kmesh
assert kmesh.maybe_initialize_distributed(), 'init path not taken'
import functools
import numpy as np, jax.numpy as jnp
from flax import linen
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from kfac_pytorch_tpu import nn as knn
from kfac_pytorch_tpu.parallel.pipeline import gpipe

assert jax.process_count() == 2 and len(jax.devices()) == 8

# ('data', 'pipe') = (2, 4) with the PIPE axis ALTERNATING hosts per
# stage: process 0 owns device ids 0-3, process 1 owns 4-7; the layout
# below gives pipe rows [0,4,1,5] and [2,6,3,7], so EVERY neighbor hop
# (0-1, 1-2, 2-3) crosses the process boundary
devs = (np.array(jax.devices()).reshape(2, 2, 2)
        .transpose(1, 2, 0).reshape(2, 4))       # [data=2, pipe=4]
mesh = Mesh(devs, ('data', 'pipe'))
B, D, M, S = 8, 12, 4, 4

class Stage(linen.Module):
    @linen.compact
    def __call__(self, h):
        return jax.nn.gelu(knn.Dense(D, name='fc')(h))

stage = Stage()
stacked = jax.tree.map(
    lambda *a: jnp.stack(a),
    *[stage.init(jax.random.PRNGKey(i), jnp.zeros((1, D)))['params']
      for i in range(S)])
rng = np.random.RandomState(0)
x = rng.randn(B, D).astype(np.float32)
y = rng.randn(B, D).astype(np.float32)
pspec = jax.tree.map(lambda _: P('pipe'), stacked)

@functools.partial(
    jax.shard_map, mesh=mesh,
    in_specs=(pspec, P('data'), P('data')),
    out_specs=(pspec, P()))
def step(params_stacked, x, y):
    params = jax.tree.map(lambda a: a[0], params_stacked)

    def loss_fn(p):
        out = gpipe(lambda pp, h: stage.apply({'params': pp}, h),
                    p, x, M, 'pipe')
        err = ((out - y) ** 2).mean()
        err = jnp.where(jax.lax.axis_index('pipe') == S - 1, err, 0.0)
        return jax.lax.pmean(jax.lax.psum(err, 'pipe'), 'data')

    loss, grads = jax.value_and_grad(loss_fn)(params)
    params = jax.tree.map(lambda p, g: p - 0.1 * g, params, grads)
    return jax.tree.map(lambda a: a[None], params), loss

jitted = jax.jit(step)
put = lambda v, s: jax.tree.map(
    lambda a, sp: jax.device_put(jnp.asarray(a), NamedSharding(mesh, sp)),
    v, s)
params = put(stacked, pspec)
xg = jax.device_put(jnp.asarray(x), NamedSharding(mesh, P('data')))
yg = jax.device_put(jnp.asarray(y), NamedSharding(mesh, P('data')))
losses = []
for i in range(3):
    params, loss = jitted(params, xg, yg)
    losses.append(float(np.asarray(loss.addressable_data(0))))
assert losses[-1] < losses[0], losses
print('PIPE LOSSES ' + ' '.join('%%.6f' %% l for l in losses), flush=True)
'''


@pytest.mark.slow
def test_two_process_pipeline_across_hosts():
    """dp+pp across TWO jax.distributed processes with the PIPELINE axis
    crossing the process boundary — every gpipe ppermute hop is a
    cross-host collective-permute (the pipeline-over-DCN scenario no
    single-process mesh can exercise). Both processes must agree on a
    decreasing loss trajectory."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker = _PIPELINE_WORKER % {'repo': repo}
    base = {k: v for k, v in os.environ.items()
            if k not in ('XLA_FLAGS', 'JAX_PLATFORMS')}
    base.update(JAX_COORDINATOR_ADDRESS=f'127.0.0.1:{free_port()}',
                KFAC_TPU_MULTIHOST='1', JAX_NUM_PROCESSES='2')
    run_two_process(lambda pid: [sys.executable, '-c', worker], base,
                    'PIPE LOSSES')


@pytest.mark.slow
def test_two_process_composite_dp_tp_through_launcher(tmp_path):
    """VERDICT r3 #7: one composite (dp+tp) K-FAC step family across TWO
    real jax.distributed processes — the model axis inside each process
    (the pod's ICI domain), data across the processes (the DCN domain) —
    launched THROUGH `bash launch_tpu.sh` with the pod=8 preset, whose
    --num-devices injection must reach the worker argv. The closest a
    pod-less box gets to reference launch_horovod.sh:32 semantics."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker = tmp_path / 'worker.py'
    worker.write_text(_COMPOSITE_WORKER % {'repo': repo})
    base = {k: v for k, v in os.environ.items()
            if k not in ('XLA_FLAGS', 'JAX_PLATFORMS',
                         'JAX_COORDINATOR_ADDRESS')}
    base.update(JAX_COORDINATOR_ADDRESS=f'127.0.0.1:{free_port()}',
                pod='8')   # configs/pod8 supplies JAX_NUM_PROCESSES=2
    # both processes must observe the identical global loss trajectory
    run_two_process(
        lambda pid: ['bash', os.path.join(repo, 'launch_tpu.sh'),
                     str(worker)],
        base, 'COMPOSITE LOSSES')


@pytest.mark.slow
def test_two_process_distributed_kfac_training(tmp_path):
    # subprocess.communicate(timeout=...) below bounds the test's runtime
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker = _WORKER % {'repo': repo}
    base = {k: v for k, v in os.environ.items()
            if k not in ('XLA_FLAGS', 'JAX_PLATFORMS')}
    base.update(JAX_COORDINATOR_ADDRESS=f'127.0.0.1:{free_port()}',
                KFAC_TPU_MULTIHOST='1', JAX_NUM_PROCESSES='2',
                KFAC_TEST_CKPT_DIR=str(tmp_path / 'ckpt'))
    # identical global loss trajectory on both processes
    outs = run_two_process(lambda pid: [sys.executable, '-c', worker],
                           base, 'LOSSES')
    # the all-ranks checkpoint round-trip completed on every process
    assert all('CKPT OK' in o for o in outs), [o[-800:] for o in outs]
