"""Model zoo sanity: shapes, param counts (vs the reference's published
table, examples/cifar_resnet.py:10-20), and KFAC layer discovery."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kfac_pytorch_tpu import capture, models


def _count(params):
    return sum(np.prod(p.shape) for p in jax.tree.leaves(params))


def test_cifar_resnet20_params_and_layers():
    model = models.resnet20()
    x = jnp.ones((2, 32, 32, 3))
    variables = capture.init(model, jax.random.PRNGKey(0), x)
    n = _count(variables['params'])
    assert n == 269_722, n  # exact match with the reference model's
    # parameter count (torch sum(p.numel()) on examples/cifar_resnet.py)
    metas = capture.collect_layer_meta(model, variables, x, train=False)
    # 20 layers: 19 convs + fc
    assert len(metas) == 20
    out = model.apply(variables, x, train=False)
    assert out.shape == (2, 10)


def test_cifar_resnet110_layer_count():
    model = models.resnet110()
    x = jnp.ones((1, 32, 32, 3))
    variables = capture.init(model, jax.random.PRNGKey(0), x)
    metas = capture.collect_layer_meta(model, variables, x, train=False)
    assert len(metas) == 110


def test_vgg16_builds():
    model = models.vgg16(num_classes=100)
    x = jnp.ones((2, 32, 32, 3))
    variables = capture.init(model, jax.random.PRNGKey(0), x)
    out = model.apply(variables, x, train=False)
    assert out.shape == (2, 100)
    metas = capture.collect_layer_meta(model, variables, x, train=False)
    assert len(metas) == 14  # 13 convs + classifier


def test_wide_resnet_and_resnext_forward():
    import jax
    import jax.numpy as jnp
    from kfac_pytorch_tpu import capture, models
    for name in ('wrn-28-10', 'resnext50'):
        model = models.get_model(name, num_classes=10)
        x = jnp.ones((2, 32, 32, 3), jnp.float32)
        variables = capture.init(model, jax.random.PRNGKey(0), x,
                                 train=False)
        out = model.apply(variables, x, train=False)
        assert out.shape == (2, 10), name


def test_inception_v4_forward():
    import jax
    import jax.numpy as jnp
    from kfac_pytorch_tpu import capture, models
    model = models.get_model('inceptionv4', num_classes=7)
    x = jnp.ones((1, 128, 128, 3), jnp.float32)
    variables = capture.init(model, jax.random.PRNGKey(0), x, train=False)
    out = model.apply(variables, x, train=False)
    assert out.shape == (1, 7)


def test_densenet121_params_and_forward():
    """torchvision densenet121: 7,978,856 params; every conv must be a
    K-FAC capture layer (120 convs + fc)."""
    model = models.get_model('densenet121', num_classes=1000)
    x = jnp.ones((1, 64, 64, 3))
    variables = capture.init(model, jax.random.PRNGKey(0), x, train=False)
    n = _count(variables['params'])
    assert abs(n - 7_978_856) / 7_978_856 < 0.01, n
    out = model.apply(variables, x, train=False)
    assert out.shape == (1, 1000)
    metas = capture.collect_layer_meta(model, variables, x, train=False)
    assert len(metas) == 121, len(metas)  # 120 convs + fc


def test_densenet201_layer_count():
    model = models.get_model('densenet201', num_classes=10)
    x = jnp.ones((1, 32, 32, 3))
    variables = capture.init(model, jax.random.PRNGKey(0), x, train=False)
    metas = capture.collect_layer_meta(model, variables, x, train=False)
    # 2*(6+12+48+32) block convs + stem + 3 transitions + fc = 201 heads
    assert len(metas) == 2 * 98 + 1 + 3 + 1, len(metas)


def test_imagenet_resnet50_params():
    model = models.resnet50()
    x = jnp.ones((1, 64, 64, 3))
    variables = capture.init(model, jax.random.PRNGKey(0), x)
    n = _count(variables['params'])
    # torchvision resnet50: 25,557,032 params
    assert abs(n - 25_557_032) / 25_557_032 < 0.01, n
    metas = capture.collect_layer_meta(model, variables, x, train=False)
    assert len(metas) == 54  # 53 convs + fc (BASELINE.md: 54-56 layers)
