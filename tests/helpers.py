"""Shared test helpers."""

import socket
import subprocess

from kfac_pytorch_tpu.models.tiny import TinyCNN  # noqa: F401 (re-export)


def free_port():
    with socket.socket() as s:
        s.bind(('127.0.0.1', 0))
        return s.getsockname()[1]


def communicate_all(procs, timeout=450):
    """communicate() with every process of a multi-process drill; on any
    timeout, kill them all and surface EVERY worker's output — the stuck
    worker is usually blocked on a failed peer's init barrier, so the
    root cause lives in the peer's stdout."""
    outs = []
    for p in procs:
        try:
            outs.append(p.communicate(timeout=timeout)[0])
        except subprocess.TimeoutExpired:
            for q in procs:
                if q.poll() is None:
                    q.kill()
            everything = list(outs)
            for q in procs[len(outs):]:
                everything.append(q.communicate()[0])
            raise AssertionError(
                f'worker timed out; all outputs: {everything}')
    return outs
