"""Shared test helpers."""

from kfac_pytorch_tpu.models.tiny import TinyCNN  # noqa: F401 (re-export)
