"""Shared test helpers."""

import flax.linen as linen

from kfac_pytorch_tpu import nn as knn


class TinyCNN(linen.Module):
    """Small conv+dense model so each compiled step variant is cheap."""

    @linen.compact
    def __call__(self, x, train=True):
        x = knn.Conv(8, (3, 3), name='c1')(x)
        x = linen.relu(x)
        x = knn.Conv(8, (3, 3), strides=(2, 2), name='c2')(x)
        x = linen.relu(x)
        x = x.reshape(x.shape[0], -1)
        return knn.Dense(10, name='fc')(x)
