"""Shared test helpers."""

import functools
import socket
import subprocess

from kfac_pytorch_tpu.models.tiny import TinyCNN  # noqa: F401 (re-export)


@functools.lru_cache(maxsize=1)
def shard_map_body_autodiff_broken():
    """True when this backend mis-transposes autodiff taken INSIDE a
    shard_map body: under the compat shim's legacy shard_map
    (``check_rep=False``, no vma tracking) a replicated operand's
    cotangent never receives its cross-axis psum, so in-body grads of
    replicated inputs come back rank-local (and forward psums double
    replicated cotangents instead).

    Probed once per session with a 2-device reduction: the grad of
    ``psum((w * x).sum())`` w.r.t. replicated ``w`` must be the GLOBAL
    x-sum. K-FAC's own step path never differentiates inside shard_map
    (capture feeds explicit operands and its collectives are forward-
    only), so only in-body-autodiff ORACLE tests key off this probe.
    """
    import kfac_pytorch_tpu  # noqa: F401 — installs the jax.shard_map shim
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    if len(jax.devices()) < 2:
        return True
    mesh = Mesh(np.array(jax.devices()[:2]), ('probe',))

    @functools.partial(jax.shard_map, mesh=mesh,
                       in_specs=(P(), P('probe')), out_specs=P())
    def g(w, x):
        return jax.grad(
            lambda w: jax.lax.psum((w * x).sum(), 'probe'))(w)

    x = jnp.arange(6, dtype=jnp.float32).reshape(2, 3)
    got = np.asarray(g(jnp.ones((3,), jnp.float32), x))
    return not np.allclose(got, np.asarray(x.sum(0)))


def free_port():
    with socket.socket() as s:
        s.bind(('127.0.0.1', 0))
        return s.getsockname()[1]


def run_two_process(argv_fn, env, tag):
    """Spawn two coordinated jax.distributed workers, collect both
    outputs, assert both exited 0 and printed an identical ``tag`` line
    (the cross-process agreement check every multihost drill ends with).
    ``argv_fn(pid) -> argv list``; ``env`` gets JAX_PROCESS_ID added per
    worker. Returns the two full outputs."""
    procs = []
    try:
        for pid in range(2):
            procs.append(subprocess.Popen(
                argv_fn(pid), env=dict(env, JAX_PROCESS_ID=str(pid)),
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True))
        outs = communicate_all(procs)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out[-2000:]
    lines = []
    for i, o in enumerate(outs):
        tagged = [l for l in o.splitlines() if l.startswith(tag)]
        # a worker can exit 0 without ever reaching the tag print (e.g. a
        # skipped drill body); indexing [-1] directly would surface that
        # as an opaque IndexError with no worker output (ADVICE r4)
        assert tagged, (f'worker {i} exited 0 but never printed a '
                        f'{tag!r} line; output tail: {o[-2000:]}')
        lines.append(tagged[-1])
    assert lines[0] == lines[1], lines
    return outs


def communicate_all(procs, timeout=450):
    """communicate() with every process of a multi-process drill; on any
    timeout, kill them all and surface EVERY worker's output — the stuck
    worker is usually blocked on a failed peer's init barrier, so the
    root cause lives in the peer's stdout."""
    outs = []
    for p in procs:
        try:
            outs.append(p.communicate(timeout=timeout)[0])
        except subprocess.TimeoutExpired:
            for q in procs:
                if q.poll() is None:
                    q.kill()
            everything = list(outs)
            for q in procs[len(outs):]:
                everything.append(q.communicate()[0])
            raise AssertionError(
                f'worker timed out; all outputs: {everything}')
    return outs
