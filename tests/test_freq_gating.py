"""Update-frequency gating: the trainer must pick compiled step variants
so factor/inverse state changes ONLY on schedule steps (reference
steps-%-freq gating, kfac_preconditioner_base.py:198-213, with the hook
cost gated out on non-update steps, :122-130)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

import kfac_pytorch_tpu as kfac
from kfac_pytorch_tpu import models, training
from tests.helpers import TinyCNN


def _setup(fac_freq, inv_freq):
    model = TinyCNN()
    precond = kfac.KFAC(variant='eigen_dp', lr=0.1, damping=0.003,
                        fac_update_freq=fac_freq,
                        kfac_update_freq=inv_freq)
    tx = training.sgd(0.1, momentum=0.9)
    x = jnp.asarray(np.random.RandomState(0).randn(4, 16, 16, 3),
                    jnp.float32)
    batch = {'input': x, 'label': jnp.asarray([0, 1, 2, 3])}
    state = training.init_train_state(model, tx, precond,
                                      jax.random.PRNGKey(0), x)

    def ce(outputs, b):
        return optax.softmax_cross_entropy_with_integer_labels(
            outputs, b['label']).mean()

    step = training.build_train_step(model, tx, precond, ce,
                                     extra_mutable=('batch_stats',),
                                     donate=False)
    return step, state, batch


def _norms(state):
    f = float(sum(jnp.abs(x).sum()
                  for x in jax.tree.leaves(state.kfac_state.factors)))
    d = float(sum(jnp.abs(x).sum()
                  for x in jax.tree.leaves(state.kfac_state.decomp)))
    return f, d


def test_factor_and_inverse_update_only_on_schedule():
    step, state, batch = _setup(fac_freq=2, inv_freq=4)
    f_hist, d_hist = [], []
    prev_f, prev_d = _norms(state)
    for i in range(8):
        state, _ = step(state, batch, lr=0.1, damping=0.003)
        f, d = _norms(state)
        f_hist.append(f != prev_f)
        d_hist.append(d != prev_d)
        prev_f, prev_d = f, d
    # factors change on steps 0, 2, 4, 6 (0-indexed step counter)
    assert f_hist == [True, False, True, False, True, False, True, False]
    # decomposition changes on steps 0 and 4
    assert d_hist == [True, False, False, False, True, False, False, False]


def test_params_update_every_step_regardless():
    step, state, batch = _setup(fac_freq=5, inv_freq=5)
    prev = jax.tree.leaves(state.params)[0]
    for _ in range(3):
        state, _ = step(state, batch, lr=0.1, damping=0.003)
        cur = jax.tree.leaves(state.params)[0]
        assert not np.allclose(np.asarray(prev), np.asarray(cur))
        prev = cur


def test_hook_enabled_false_freezes_factor_state():
    model = TinyCNN()
    precond = kfac.KFAC(variant='eigen_dp', lr=0.1, damping=0.003,
                        fac_update_freq=1, kfac_update_freq=1,
                        hook_enabled=False)
    tx = training.sgd(0.1, momentum=0.9)
    x = jnp.asarray(np.random.RandomState(0).randn(4, 16, 16, 3),
                    jnp.float32)
    batch = {'input': x, 'label': jnp.asarray([0, 1, 2, 3])}
    st = training.init_train_state(model, tx, precond,
                                   jax.random.PRNGKey(0), x)

    def ce(outputs, b):
        return optax.softmax_cross_entropy_with_integer_labels(
            outputs, b['label']).mean()

    s2 = training.build_train_step(model, tx, precond, ce,
                                   extra_mutable=('batch_stats',),
                                   donate=False)
    before = _norms(st)
    p0 = jax.tree.leaves(st.params)[0]
    st, _ = s2(st, batch, lr=0.1, damping=0.003)
    assert _norms(st) == before          # frozen factor/decomp state
    p1 = jax.tree.leaves(st.params)[0]
    assert not np.allclose(np.asarray(p0), np.asarray(p1))  # still trains
