"""Long-context sequence-parallel training: the TransformerLM with ring
attention sharded over a 'seq' mesh axis, end-to-end through the K-FAC
train step. Capability beyond the reference (SURVEY.md §5.7 — absent
there); correctness anchor: sequence-parallel logits/updates must match
the single-device model with the same params."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import kfac_pytorch_tpu as kfac
from kfac_pytorch_tpu import capture, models, training

VOCAB, B, L, NDEV = 64, 4, 64, 8


def _lm(seq_axis):
    return models.transformer_lm(
        vocab_size=VOCAB, n_layer=2, n_head=8, d_model=64, max_len=L,
        seq_axis=seq_axis)


def _batch(seed=0):
    rng = np.random.RandomState(seed)
    toks = rng.randint(0, VOCAB, (B, L))
    return {'input': jnp.asarray(toks[:, :]),
            'label': jnp.asarray(np.roll(toks, -1, axis=1))}


def _ce(outputs, batch):
    return optax.softmax_cross_entropy_with_integer_labels(
        outputs, batch['label']).mean()


@pytest.fixture(scope='module')
def mesh():
    return Mesh(np.array(jax.devices()[:NDEV]), ('seq',))


def test_seq_parallel_forward_matches_dense(mesh):
    # init with the seq_axis=None twin (same param structure; ring needs
    # the axis bound, so init/trace happen outside shard_map on the twin)
    twin = _lm(None)
    batch = _batch()
    variables = capture.init(twin, jax.random.PRNGKey(0), batch['input'],
                             train=False)
    ref = twin.apply(variables, batch['input'], train=False)

    sp = _lm('seq')
    out = jax.jit(jax.shard_map(
        lambda v, t: sp.apply(v, t, train=False),
        mesh=mesh, in_specs=(P(), P(None, 'seq')),
        out_specs=P(None, 'seq')))(variables, batch['input'])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-4, rtol=2e-4)


def test_seq_parallel_kfac_training_step(mesh):
    twin = _lm(None)
    sp = _lm('seq')
    batch = _batch(seed=1)
    local_len = L // NDEV

    precond = kfac.KFAC(variant='eigen_dp', lr=0.1, damping=0.003,
                        num_devices=NDEV, axis_name='seq',
                        exclude_vocabulary_size=VOCAB)
    tx = training.sgd(0.1, momentum=0.9)
    # setup/init on the twin with a local-shard-shaped sample (layer dims
    # are sequence-length independent)
    state = training.init_train_state(
        twin, tx, precond, jax.random.PRNGKey(0),
        batch['input'][:, :local_len])

    step = training.build_train_step(
        sp, tx, precond, _ce, axis_name='seq', mesh=mesh,
        batch_specs=P(None, 'seq'))

    losses = []
    for _ in range(6):
        state, metrics = step(state, batch, lr=0.1, damping=0.003)
        losses.append(float(metrics['loss']))
    assert all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0], losses


def test_seq_parallel_grads_match_dense(mesh):
    """Param gradients from the sequence-sharded model == dense model."""
    twin = _lm(None)
    sp = _lm('seq')
    batch = _batch(seed=2)
    variables = capture.init(twin, jax.random.PRNGKey(1), batch['input'],
                             train=False)

    def dense_loss(params):
        out = twin.apply({'params': params}, batch['input'], train=False)
        return _ce(out, batch)

    def sharded_loss_fn(params, toks, labels):
        out = sp.apply({'params': params}, toks, train=False)
        loss = optax.softmax_cross_entropy_with_integer_labels(
            out, labels).mean()
        return jax.lax.pmean(loss, 'seq')

    def sp_grads(params, toks, labels):
        # pmean'd loss: autodiff already yields the global-mean gradient
        return jax.grad(sharded_loss_fn)(params, toks, labels)

    g_dense = jax.grad(dense_loss)(variables['params'])
    g_sp = jax.jit(jax.shard_map(
        sp_grads, mesh=mesh,
        in_specs=(P(), P(None, 'seq'), P(None, 'seq')),
        out_specs=P()))(variables['params'], batch['input'],
                        batch['label'])
    flat_d, _ = jax.flatten_util.ravel_pytree(g_dense)
    flat_s, _ = jax.flatten_util.ravel_pytree(g_sp)
    np.testing.assert_allclose(np.asarray(flat_s), np.asarray(flat_d),
                               atol=5e-4, rtol=5e-4)
