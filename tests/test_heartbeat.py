"""Peer-heartbeat unit drills (resilience/heartbeat.py).

Everything here is wall-clock-free (ManualClock + manual ``poll_once``
driving) or sub-second (the real TCP responder on a loopback port). The
real multi-process detection drill — SIGKILL one host of a two-process
pod, survivor exits RC_PEER_DEAD — lives in tests/test_pod_chaos.py
behind ``-m slow``.
"""

import logging
import os

import pytest

from kfac_pytorch_tpu import resilience
from kfac_pytorch_tpu.resilience.heartbeat import (
    RC_PEER_DEAD, FileLeaseTransport, JoinAnnouncer, PeerHeartbeat,
    TcpHeartbeatTransport, format_peer_addrs, heartbeat_from_env,
    parse_peer_addrs, read_join_announcements)
from kfac_pytorch_tpu.resilience.retry import ManualClock
from kfac_pytorch_tpu.utils.runlog import parse_resilience_suffix


@pytest.fixture(autouse=True)
def _reset_counters():
    resilience.counters.reset()
    yield
    resilience.counters.reset()


def _pair(tmp_path, clock0, clock1, **kw):
    """Two in-process hosts sharing a lease dir, manual polling."""
    deaths = []

    def on_dead(peer, info):
        deaths.append((peer, info))

    kw.setdefault('interval', 1.0)
    kw.setdefault('deadline', 5.0)
    kw.setdefault('startup_grace', 30.0)
    h0 = PeerHeartbeat(FileLeaseTransport(tmp_path, 0), 0, 2,
                       clock=clock0.monotonic, on_dead=on_dead, **kw)
    h1 = PeerHeartbeat(FileLeaseTransport(tmp_path, 1), 1, 2,
                       clock=clock1.monotonic, on_dead=on_dead, **kw)
    return h0, h1, deaths


def test_live_peers_are_never_declared_dead(tmp_path):
    c0, c1 = ManualClock(), ManualClock()
    h0, h1, deaths = _pair(tmp_path, c0, c1)
    for _ in range(20):
        assert h0.poll_once() == []
        assert h1.poll_once() == []
        c0.sleep(1.0)
        c1.sleep(1.0)
    assert deaths == []
    assert h0.dead_peers() == {} and h1.dead_peers() == {}


def test_silent_peer_declared_dead_after_deadline(tmp_path):
    c0, c1 = ManualClock(), ManualClock()
    h0, h1, deaths = _pair(tmp_path, c0, c1, deadline=5.0)
    for _ in range(3):  # both beating: seen and advancing
        h0.poll_once(); h1.poll_once(); c0.sleep(1.0); c1.sleep(1.0)
    # host 1 goes silent (no more polls); host 0 keeps polling
    silent = 0
    while not deaths and silent < 50:
        h0.poll_once()
        c0.sleep(1.0)
        silent += 1
    assert deaths and deaths[0][0] == 1
    info = deaths[0][1]
    # detection latency: just past the 5s deadline, never anywhere near
    # a watchdog-scale timeout
    assert 5.0 < info['detect_s'] <= 7.0
    assert info['never_seen'] is False
    assert resilience.counters.get('peer_dead') == 1
    # declared once, not re-declared on later polls
    h0.poll_once()
    assert len(deaths) == 1


def test_restarted_peer_with_reset_sequence_stays_alive(tmp_path):
    """A crash-restarted peer resets its sequence to 1 under a new pid;
    liveness is (pid, seq) IDENTITY change, not seq growth — judging by
    the dead process's high-water mark would turn every crash restart
    into a pod shrink."""
    c0 = ManualClock()
    deaths = []
    h0 = PeerHeartbeat(FileLeaseTransport(tmp_path, 0), 0, 2,
                       interval=1.0, deadline=4.0, startup_grace=30.0,
                       clock=c0.monotonic,
                       on_dead=lambda p, i: deaths.append((p, i)))
    t1 = FileLeaseTransport(tmp_path, 1)
    # peer ran a long time (seq 300), then its process died...
    t1.publish({'host': 1, 'seq': 300, 'pid': 111, 'step': 300})
    h0.poll_once()
    c0.sleep(2.0)
    # ...and the supervisor relaunched it: NEW pid, seq starts over
    for seq in range(1, 12):
        t1.publish({'host': 1, 'seq': seq, 'pid': 222, 'step': seq})
        h0.poll_once()
        c0.sleep(1.0)
    assert deaths == [], deaths
    # and a genuinely silent restarted peer still dies on schedule
    for _ in range(8):
        h0.poll_once()
        c0.sleep(1.0)
    assert deaths and deaths[0][0] == 1


def test_rejoined_peer_new_gen_not_misread_as_stale(tmp_path):
    """The grow-path regression (ISSUE 6 satellite): a host re-admitted
    at a later GENERATION restarts its sequence counter — under a
    recycled pid, judging it by the previous generation's high-water
    mark would declare the rejoined host dead on arrival. Liveness
    identity is (pid, gen, seq), and the monitor's rebase() on a
    generation change forgets the old tracking entirely."""
    c0 = ManualClock()
    deaths = []
    h0 = PeerHeartbeat(FileLeaseTransport(tmp_path, 0), 0, 2,
                       interval=1.0, deadline=4.0, startup_grace=30.0,
                       clock=c0.monotonic, gen=0,
                       on_dead=lambda p, i: deaths.append((p, i)))
    t1 = FileLeaseTransport(tmp_path, 1)
    # peer ran to seq 500 at generation 0, then was lost...
    t1.publish({'host': 1, 'seq': 500, 'pid': 111, 'gen': 0, 'step': 500})
    h0.poll_once()
    c0.sleep(2.0)
    # ...the pod shrank (gen 1) and re-grew (gen 2); the monitor rebases
    h0.rebase(peers=[1], gen=2)
    # the rejoined host comes back under the SAME (recycled) pid with a
    # reset counter but the NEW generation — it must read as alive
    for seq in range(1, 10):
        t1.publish({'host': 1, 'seq': seq, 'pid': 111, 'gen': 2,
                    'step': seq})
        h0.poll_once()
        c0.sleep(1.0)
    assert deaths == [], deaths
    # and identity still catches a FROZEN payload: same (pid, gen, seq)
    # not advancing past the deadline is a death
    for _ in range(8):
        h0.poll_once()
        c0.sleep(1.0)
    assert deaths and deaths[0][0] == 1


def test_rebase_clears_dead_records_and_restarts_grace(tmp_path):
    """rebase() must (a) drop dead-peer records — the new membership was
    agreed AROUND the deaths, and a carried record would re-fire the
    reaction every generation — and (b) restart the startup grace, so a
    just-admitted member slow to its first beat is not declared dead
    with the OLD grace long spent."""
    c0 = ManualClock()
    deaths = []
    h0 = PeerHeartbeat(FileLeaseTransport(tmp_path, 0), 0, 2,
                       interval=1.0, deadline=2.0, startup_grace=5.0,
                       clock=c0.monotonic,
                       on_dead=lambda p, i: deaths.append((p, i)))
    h0.poll_once()  # arms the grace clock
    c0.sleep(6.0)   # past grace, peer 1 never seen
    h0.poll_once()
    assert deaths and h0.dead_peers()
    h0.rebase(peers=[1], gen=1)
    assert h0.dead_peers() == {}
    deaths.clear()
    # fresh grace: 4s of silence right after the rebase is NOT a death
    c0.sleep(4.0)
    h0.poll_once()
    assert deaths == []
    assert h0.gen == 1


def test_join_announcer_roundtrip_and_withdraw(tmp_path):
    assert read_join_announcements(tmp_path) == {}
    ann = JoinAnnouncer(tmp_path, 3, addr='10.0.0.3:8476')
    ann.announce()
    ann.announce()  # republish: seq advances under one pid
    seen = read_join_announcements(tmp_path)
    assert list(seen) == [3]
    assert seen[3]['addr'] == '10.0.0.3:8476'
    assert seen[3]['seq'] == 2 and seen[3]['pid'] == os.getpid()
    ann.withdraw()
    assert read_join_announcements(tmp_path) == {}
    ann.withdraw()  # idempotent
    # junk in the lease dir is not an announcement
    (tmp_path / 'join-notanint.json').write_text('{}')
    (tmp_path / 'join-5.json').write_text('not json')
    assert read_join_announcements(tmp_path) == {}


def test_peer_addr_spec_roundtrip():
    spec = '0=10.0.0.1:8478,2=hostb:9000'
    addrs = parse_peer_addrs(spec)
    assert addrs == {0: ('10.0.0.1', 8478), 2: ('hostb', 9000)}
    assert format_peer_addrs(addrs) == spec
    with pytest.raises(ValueError, match='rank=host:port'):
        parse_peer_addrs('garbage')


def test_heartbeat_from_env_tcp(monkeypatch):
    """The tcp contract launch_tpu.sh exports for real (no shared
    filesystem) pods: transport comes up bound, peers parsed, and the
    generation rides into the published payload."""
    from kfac_pytorch_tpu.resilience import heartbeat as hb_mod
    monkeypatch.setenv(hb_mod.ENV_TRANSPORT, 'tcp')
    monkeypatch.setenv(hb_mod.ENV_HOST, '0')
    monkeypatch.setenv(hb_mod.ENV_HOSTS, '2')
    monkeypatch.setenv(hb_mod.ENV_PORT, '0')  # ephemeral: test only
    monkeypatch.setenv(hb_mod.ENV_PEERS, '1=127.0.0.1:19')
    monkeypatch.setenv(hb_mod.ENV_GEN, '3')
    hb = heartbeat_from_env()
    try:
        assert isinstance(hb.transport, TcpHeartbeatTransport)
        assert hb.transport.peer_addrs == {1: ('127.0.0.1', 19)}
        assert hb.gen == 3
        # publish stamps the generation (rejoin-vs-stale disambiguation)
        hb._publish()
        import json
        assert json.loads(hb.transport._payload)['gen'] == 3
    finally:
        hb.stop()
    # tcp without a peer map is a configuration error, not a silent
    # heartbeat-less run
    monkeypatch.delenv(hb_mod.ENV_PEERS)
    with pytest.raises(ValueError, match='KFAC_HB_PEERS'):
        heartbeat_from_env()


def test_peer_never_seen_respects_startup_grace(tmp_path):
    c0 = ManualClock()
    deaths = []
    h0 = PeerHeartbeat(FileLeaseTransport(tmp_path, 0), 0, 2,
                       interval=1.0, deadline=2.0, startup_grace=10.0,
                       clock=c0.monotonic,
                       on_dead=lambda p, i: deaths.append((p, i)))
    for _ in range(9):  # within grace: a slow-to-start peer is not dead
        assert h0.poll_once() == []
        c0.sleep(1.0)
    assert deaths == []
    c0.sleep(2.5)  # past the grace
    h0.poll_once()
    assert deaths and deaths[0][0] == 1 and deaths[0][1]['never_seen']


def test_stop_beat_fault_makes_peers_declare_us_dead(tmp_path):
    """The heartbeat-loss drill (KFAC_FAULT_HB_STOP_STEP semantics):
    host 1 keeps polling (it is alive and watching) but stops PUBLISHING
    at step 3 — host 0 must declare it dead while host 1 still sees
    host 0 as alive."""
    c0, c1 = ManualClock(), ManualClock()
    h0, h1, deaths = _pair(tmp_path, c0, c1, deadline=4.0)
    h1.stop_beat_step = 3
    for step in range(30):
        h1.tick(step)
        h0.poll_once()
        h1.poll_once()
        c0.sleep(1.0)
        c1.sleep(1.0)
        if deaths:
            break
    assert deaths and deaths[0][0] == 1
    assert h1._suppressed
    # the zombie's own monitor still sees host 0 alive — fencing is the
    # pod supervisor's job, not the monitor's
    assert h1.dead_peers() == {}


def test_declared_dead_line_is_machine_greppable(tmp_path, caplog):
    c0, c1 = ManualClock(), ManualClock()
    h0, h1, deaths = _pair(tmp_path, c0, c1, deadline=3.0)
    h0.poll_once(); h1.poll_once()
    with caplog.at_level(logging.ERROR,
                         logger='kfac_pytorch_tpu.resilience.heartbeat'):
        while not deaths:
            c0.sleep(1.0)
            h0.poll_once()
    counts = {}
    for rec in caplog.records:
        counts = parse_resilience_suffix(rec.getMessage())
        if counts:
            break
    assert counts.get('peer_dead') == 1
    assert counts.get('peer') == 1
    assert counts.get('detect_s', 0) > 3.0


def test_publish_failure_is_survived_and_counted(tmp_path):
    c0 = ManualClock()

    class FlakyTransport(FileLeaseTransport):
        fails = 0

        def publish(self, payload):
            if FlakyTransport.fails < 2:
                FlakyTransport.fails += 1
                raise OSError('EIO')
            super().publish(payload)

    h0 = PeerHeartbeat(FlakyTransport(tmp_path, 0), 0, 2, interval=1.0,
                       deadline=5.0, clock=c0.monotonic,
                       on_dead=lambda p, i: None)
    h0.poll_once(); h0.poll_once(); h0.poll_once()
    assert resilience.counters.get('hb_publish_errors') == 2
    # the third publish landed
    assert os.path.exists(tmp_path / 'hb-0.json')


def test_background_thread_detects_real_death(tmp_path):
    """Real threads, real (tiny) clocks: host 1's beats stop and host
    0's background monitor fires the on_dead callback without anyone
    driving poll_once."""
    import threading
    fired = threading.Event()
    h0 = PeerHeartbeat(FileLeaseTransport(tmp_path, 0), 0, 2,
                       interval=0.05, deadline=0.4, startup_grace=5.0,
                       on_dead=lambda p, i: fired.set())
    h1 = PeerHeartbeat(FileLeaseTransport(tmp_path, 1), 1, 2,
                       interval=0.05, deadline=0.4, startup_grace=5.0,
                       on_dead=lambda p, i: None)
    h0.start()
    h1.start()
    try:
        import time
        time.sleep(0.3)        # both beating
        assert not fired.is_set()
        h1.stop()              # host 1 "dies"
        assert fired.wait(10), 'peer death never detected'
        assert 1 in h0.dead_peers()
    finally:
        h0.stop()
        h1.stop()


def test_tcp_transport_roundtrip_and_death():
    t0 = TcpHeartbeatTransport(0, 0, {}, bind_host='127.0.0.1')
    t1 = TcpHeartbeatTransport(1, 0, {0: ('127.0.0.1', t0.port)},
                               bind_host='127.0.0.1', timeout=2.0)
    t0.peer_addrs = {1: ('127.0.0.1', t1.port)}
    try:
        t0.publish({'host': 0, 'seq': 7})
        t1.publish({'host': 1, 'seq': 3})
        assert t1.read_peers()[0]['seq'] == 7
        assert t0.read_peers()[1]['seq'] == 3
        t1.close()  # "host 1 died": connection refused -> absent
        assert 1 not in t0.read_peers()
    finally:
        t0.close()
        t1.close()


def test_heartbeat_from_env(tmp_path, monkeypatch):
    from kfac_pytorch_tpu.resilience import heartbeat as hb_mod
    assert heartbeat_from_env() is None  # no pod contract in env
    monkeypatch.setenv(hb_mod.ENV_DIR, str(tmp_path))
    monkeypatch.setenv(hb_mod.ENV_HOST, '1')
    monkeypatch.setenv(hb_mod.ENV_HOSTS, '3')
    monkeypatch.setenv(hb_mod.ENV_INTERVAL, '0.5')
    monkeypatch.setenv(hb_mod.ENV_DEADLINE, '2.5')
    monkeypatch.setenv(hb_mod.ENV_HB_STOP, '9')
    hb = heartbeat_from_env()
    assert hb is not None
    assert hb.host_id == 1 and hb.peers == [0, 2]
    assert hb.interval == 0.5 and hb.deadline == 2.5
    assert hb.stop_beat_step == 9
    monkeypatch.setenv(hb_mod.ENV_HOSTS, '1')
    assert heartbeat_from_env() is None  # single host: no heartbeat
    assert resilience.RC_PEER_DEAD == RC_PEER_DEAD == 115


def test_torn_lease_json_never_crashes_the_monitor(tmp_path):
    """Satellite (ISSUE 7): a reader catching a file mid-replace (or a
    genuinely torn write from a crashed peer) costs one poll, never the
    monitor thread — and a later intact payload resumes liveness."""
    c0 = ManualClock()
    deaths = []
    h0 = PeerHeartbeat(FileLeaseTransport(tmp_path, 0), 0, 2,
                       interval=1.0, deadline=5.0, startup_grace=30.0,
                       clock=c0.monotonic,
                       on_dead=lambda p, i: deaths.append((p, i)))
    t1 = FileLeaseTransport(tmp_path, 1)
    t1.publish({'host': 1, 'seq': 1, 'pid': 9})
    assert h0.poll_once() == []
    # the peer's lease is torn mid-write: skip-and-retry, no crash
    (tmp_path / 'hb-1.json').write_text('{"host": 1, "se')
    for _ in range(3):
        assert h0.poll_once() == []
        c0.sleep(1.0)
    # intact again before the deadline: still alive
    t1.publish({'host': 1, 'seq': 2, 'pid': 9})
    h0.poll_once()
    assert deaths == []
    # and a transport whose read_peers RAISES ValueError is survived
    class TornTransport(FileLeaseTransport):
        def read_peers(self):
            raise ValueError('torn beyond parsing')
    h0.transport = TornTransport(tmp_path, 0)
    assert h0.poll_once() == []


def test_stale_generation_payload_never_refreshes_liveness(tmp_path):
    """TCP-hardening satellite: a payload from BEFORE the last elastic
    world change (delayed, duplicated, or a dead incarnation's lease)
    must not keep a slot alive — the (pid, gen, seq) identity only
    counts at the monitor's own generation or newer."""
    c0 = ManualClock()
    deaths = []
    h0 = PeerHeartbeat(FileLeaseTransport(tmp_path, 0), 0, 2,
                       interval=1.0, deadline=4.0, startup_grace=6.0,
                       clock=c0.monotonic, gen=2,
                       on_dead=lambda p, i: deaths.append((p, i)))
    t1 = FileLeaseTransport(tmp_path, 1)
    # stale-generation stream: advancing seqs, but gen 1 < monitor gen 2
    for seq in range(1, 10):
        t1.publish({'host': 1, 'seq': seq, 'pid': 9, 'gen': 1})
        h0.poll_once()
        c0.sleep(1.0)
    assert deaths and deaths[0][0] == 1, 'stale gen kept a ghost alive'
    assert deaths[0][1]['never_seen'] is True
    # current-generation payloads DO count (and a future gen tolerates
    # a peer that committed the next world change slightly before us)
    c1 = ManualClock()
    deaths2 = []
    h1 = PeerHeartbeat(FileLeaseTransport(tmp_path, 0), 0, 2,
                       interval=1.0, deadline=4.0, startup_grace=6.0,
                       clock=c1.monotonic, gen=2,
                       on_dead=lambda p, i: deaths2.append((p, i)))
    for seq in range(1, 10):
        t1.publish({'host': 1, 'seq': seq, 'pid': 9,
                    'gen': 2 if seq < 5 else 3})
        h1.poll_once()
        c1.sleep(1.0)
    assert deaths2 == []


def _chaos_monitor(tmp_path, transport, cfg, clock, **kw):
    from kfac_pytorch_tpu.resilience.chaos_net import ChaosTransport
    deaths = []
    wrapped = ChaosTransport(transport, cfg, 0, clock=clock.monotonic,
                             wall=clock.monotonic)
    kw.setdefault('interval', 1.0)
    kw.setdefault('deadline', 6.0)
    kw.setdefault('startup_grace', 30.0)
    h = PeerHeartbeat(wrapped, 0, 2, clock=clock.monotonic,
                      on_dead=lambda p, i: deaths.append((p, i)), **kw)
    return h, wrapped, deaths


def test_tcp_duplicated_reordered_payloads_keep_liveness_identity():
    """TCP-hardening satellite: ChaosTransport duplication + reordering
    over a REAL TcpHeartbeatTransport pair must never regress the
    (pid, gen, seq) liveness identity into a false death while the
    publisher advances — and a FROZEN publisher whose stale payloads
    keep being redelivered still dies on schedule."""
    from kfac_pytorch_tpu.resilience.chaos_net import NetFaultConfig
    t0 = TcpHeartbeatTransport(0, 0, {}, bind_host='127.0.0.1')
    t1 = TcpHeartbeatTransport(1, 0, {0: ('127.0.0.1', t0.port)},
                               bind_host='127.0.0.1', timeout=2.0)
    t0.peer_addrs = {1: ('127.0.0.1', t1.port)}
    clock = ManualClock()
    cfg = NetFaultConfig(seed=9, delay=2.5, dup=0.7, reorder=0.9)
    h0, wrapped, deaths = _chaos_monitor(None, t0, cfg, clock)
    try:
        for seq in range(1, 25):
            t1.publish({'host': 1, 'seq': seq, 'pid': 42, 'gen': 0})
            h0.poll_once()
            clock.sleep(1.0)
        # duplicated/reordered deliveries happened, yet no false death
        kinds = {k for k, _, _ in wrapped.trace}
        assert 'dup' in kinds and 'reorder' in kinds, kinds
        assert deaths == []
        # publisher freezes: stale redeliveries of the same identity
        # must NOT reset the silence clock — death within the deadline
        # window (+ drained delay), not postponed indefinitely
        polls_to_death = 0
        while not deaths and polls_to_death < 30:
            h0.poll_once()
            clock.sleep(1.0)
            polls_to_death += 1
        assert deaths and deaths[0][0] == 1
        # bound: residual delayed deliveries (<= delay) + one dup
        # redelivery poll + the deadline itself + poll granularity
        assert polls_to_death <= 2.5 + 1 + 6.0 + 2
    finally:
        t0.close()
        t1.close()


def test_heartbeat_from_env_wraps_transport_in_chaos(tmp_path,
                                                     monkeypatch):
    from kfac_pytorch_tpu.resilience import chaos_net
    from kfac_pytorch_tpu.resilience import heartbeat as hb_mod
    from kfac_pytorch_tpu.resilience.chaos_net import ChaosTransport
    monkeypatch.setenv(hb_mod.ENV_DIR, str(tmp_path))
    monkeypatch.setenv(hb_mod.ENV_HOST, '0')
    monkeypatch.setenv(hb_mod.ENV_HOSTS, '2')
    hb = heartbeat_from_env()
    assert not isinstance(hb.transport, ChaosTransport)  # env off
    monkeypatch.setenv(chaos_net.ENV_NET_SEED, '4')
    monkeypatch.setenv(chaos_net.ENV_NET_IDMAP, '0=0,1=2')
    hb = heartbeat_from_env()
    assert isinstance(hb.transport, ChaosTransport)
    assert hb.transport.cfg.idmap == {0: 0, 1: 2}
