"""Training-loop integration: K-FAC + SGD on tiny problems, single device
and sharded mesh, with BatchNorm state threading and freq-gated dispatch."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh

import kfac_pytorch_tpu as kfac
from kfac_pytorch_tpu import models, training

from tests.helpers import TinyCNN


def _batch(n=16, classes=10, hw=16):
    rng = np.random.RandomState(0)
    return {'input': jnp.asarray(rng.randn(n, hw, hw, 3), jnp.float32),
            'label': jnp.asarray(rng.randint(0, classes, n))}


def _ce(outputs, batch):
    return optax.softmax_cross_entropy_with_integer_labels(
        outputs, batch['label']).mean()


def test_kfac_training_reduces_loss_resnet20():
    model = models.resnet20()
    batch = _batch()
    precond = kfac.KFAC(variant='eigen_dp', lr=0.1, damping=0.003,
                        fac_update_freq=2, kfac_update_freq=2,
                        num_devices=1, axis_name=None)
    tx = training.sgd(0.1, momentum=0.9, weight_decay=5e-4)
    state = training.init_train_state(model, tx, precond,
                                      jax.random.PRNGKey(0), batch['input'])
    step = training.build_train_step(model, tx, precond, _ce,
                                     extra_mutable=('batch_stats',))
    losses = []
    for _ in range(6):
        state, m = step(state, batch, lr=0.1, damping=0.003)
        losses.append(float(m['loss']))
    assert losses[-1] < losses[0], losses
    assert int(state.step) == 6
    assert int(state.kfac_state.step) == 6


def test_sgd_baseline_no_precond():
    model = models.resnet20()
    batch = _batch()
    tx = training.sgd(0.1, momentum=0.9)
    state = training.init_train_state(model, tx, None,
                                      jax.random.PRNGKey(0), batch['input'])
    step = training.build_train_step(model, tx, None, _ce,
                                     extra_mutable=('batch_stats',))
    state, m0 = step(state, batch)  # state is donated: always re-thread
    l0 = float(m0['loss'])
    state, _ = step(state, batch)
    state, m = step(state, batch)
    assert float(m['loss']) < l0


@pytest.mark.parametrize('variant', ['eigen_dp', 'eigen'])
def test_amortized_basis_training_tracks_full_eigh(variant):
    """basis_update_freq through the trainer's host gating on a 4-device
    mesh: the amortized run (full eigh every 4 steps, eigenvalue-only
    refresh in between) must stay close to the every-step-full-eigh run
    and must dispatch the refresh variant (no silent full recompute)."""
    ndev = 4
    mesh = Mesh(np.array(jax.devices()[:ndev]), ('batch',))
    batch = _batch(n=8)

    def run(basis_freq):
        model = TinyCNN()
        precond = kfac.KFAC(variant=variant, lr=0.05, damping=0.003,
                            num_devices=ndev, axis_name='batch',
                            basis_update_freq=basis_freq)
        tx = training.sgd(0.05, momentum=0.9)
        state = training.init_train_state(
            model, tx, precond, jax.random.PRNGKey(0), batch['input'])
        step = training.build_train_step(model, tx, precond, _ce,
                                         axis_name='batch', mesh=mesh)
        losses = []
        for _ in range(8):
            state, m = step(state, batch, lr=0.05, damping=0.003)
            losses.append(float(m['loss']))
        return losses

    full = run(None)
    amort = run(4)
    assert all(np.isfinite(amort)), amort
    assert amort[-1] < amort[0], amort
    # same opening step (step 0 is a full decomposition in both), and the
    # trajectories stay in the same basin
    np.testing.assert_allclose(amort[0], full[0], rtol=1e-5)
    assert abs(amort[-1] - full[-1]) < 0.35 * abs(full[0] - full[-1]) + 1e-3


def test_warm_start_training_tracks_cold(monkeypatch):
    """warm_start_basis through the trainer's host gating (jacobi eigh,
    4-device mesh): step 0 is a cold full decomposition, later fulls
    re-diagonalize in the stored basis — trajectory must track the cold
    run."""
    monkeypatch.setenv('KFAC_EIGH_IMPL', 'jacobi')
    ndev = 4
    mesh = Mesh(np.array(jax.devices()[:ndev]), ('batch',))
    batch = _batch(n=8, hw=4)  # tiny dims: jacobi buckets stay <= 64

    import flax.linen as linen
    from kfac_pytorch_tpu.nn import Dense

    class MLP(linen.Module):
        @linen.compact
        def __call__(self, x, train=True):
            x = x.reshape((x.shape[0], -1))
            x = linen.relu(Dense(32)(x))
            return Dense(10)(x)

    def run(warm):
        model = MLP()
        precond = kfac.KFAC(variant='eigen_dp', lr=0.05, damping=0.003,
                            kfac_update_freq=2, num_devices=ndev,
                            axis_name='batch', warm_start_basis=warm)
        tx = training.sgd(0.05, momentum=0.9)
        state = training.init_train_state(
            model, tx, precond, jax.random.PRNGKey(0), batch['input'])
        step = training.build_train_step(model, tx, precond, _ce,
                                         axis_name='batch', mesh=mesh)
        losses = []
        for _ in range(8):
            state, m = step(state, batch, lr=0.05, damping=0.003)
            losses.append(float(m['loss']))
        return losses

    cold = run(False)
    warm = run(True)
    assert all(np.isfinite(warm)), warm
    np.testing.assert_allclose(warm[0], cold[0], rtol=1e-5)
    assert abs(warm[-1] - cold[-1]) < 0.25 * abs(cold[0] - cold[-1]) + 1e-3


def test_warm_streak_cold_restart_gating():
    """Host gating (_warm_basis_gate): the first full is cold, subsequent
    fulls warm, and every cold_restart_every-th full goes cold again to
    reset the chained basis' accumulated orthogonality error. Non-inverse
    steps must not advance the streak."""
    seen = {'yes': False}
    precond_like = type('P', (), {'warm_start_basis': True,
                                  'cold_restart_every': 3})()
    gate = lambda s, ui=True, ub=True: training._warm_basis_gate(
        precond_like, seen, s, ui, ub)
    decisions = [gate(s) for s in range(6)]
    # cold, then 3 warm, then forced cold, then warm again
    assert decisions == [False, True, True, True, False, True], decisions
    # a step without an inverse update leaves the record untouched
    before = dict(seen)
    gate(6, ui=False)
    assert seen == before


def test_sharded_training_runs_and_matches_replicated_params():
    """Full train step under shard_map on 4 devices: runs, loss finite,
    params stay replicated (vma-checked by construction)."""
    ndev = 4
    mesh = Mesh(np.array(jax.devices()[:ndev]), ('batch',))
    model = models.resnet20()
    batch = _batch(n=8)
    precond = kfac.KFAC(variant='eigen_dp', lr=0.1, damping=0.003,
                        num_devices=ndev, axis_name='batch')
    tx = training.sgd(0.1, momentum=0.9)
    state = training.init_train_state(model, tx, precond,
                                      jax.random.PRNGKey(0), batch['input'])
    step = training.build_train_step(model, tx, precond, _ce,
                                     axis_name='batch', mesh=mesh,
                                     extra_mutable=('batch_stats',))
    state, m = step(state, batch, lr=0.1, damping=0.003)
    assert np.isfinite(float(m['loss']))
    state, m2 = step(state, batch, lr=0.1, damping=0.003)
    assert np.isfinite(float(m2['loss']))


def _one_f1mc_step(model, batch, fisher_type, seed=0):
    precond = kfac.KFAC(variant='eigen_dp', lr=0.1, damping=0.003,
                        fac_update_freq=1, kfac_update_freq=1,
                        num_devices=1, axis_name=None)
    tx = training.sgd(0.1, momentum=0.9)
    state = training.init_train_state(model, tx, precond,
                                      jax.random.PRNGKey(0), batch['input'])
    step = training.build_train_step(model, tx, precond, _ce,
                                     fisher_type=fisher_type,
                                     fisher_seed=seed)
    state, m = step(state, batch, lr=0.1, damping=0.003)
    assert np.isfinite(float(m['loss']))
    return state, precond


def test_f1mc_changes_g_factors_only():
    """F1mc's pseudo-label backward must change the G factors (different
    cotangents) but not the A factors (same forward activations), and the
    sampler must be seed-reproducible (reference capability surface:
    examples/utils.py:82-90 + pytorch_cifar10_resnet.py:74-75)."""
    model = TinyCNN()
    batch = _batch()
    s_emp, precond = _one_f1mc_step(model, batch, 'Femp')
    s_mc, _ = _one_f1mc_step(model, batch, 'F1mc')
    s_mc_same, _ = _one_f1mc_step(model, batch, 'F1mc')
    s_mc_other, _ = _one_f1mc_step(model, batch, 'F1mc', seed=123)

    g_diff = 0
    for ba, ra, bg, rg, _owner in precond.plan.layer_rows:
        a_emp = np.asarray(s_emp.kfac_state.factors[str(ba)][ra])
        a_mc = np.asarray(s_mc.kfac_state.factors[str(ba)][ra])
        np.testing.assert_allclose(a_emp, a_mc, atol=1e-5)
        g_emp = np.asarray(s_emp.kfac_state.factors[str(bg)][rg])
        g_mc = np.asarray(s_mc.kfac_state.factors[str(bg)][rg])
        g_diff += int(not np.allclose(g_emp, g_mc, atol=1e-6))
    assert g_diff > 0, 'F1mc produced identical G factors to Femp'

    # identical seed -> identical factors; different seed -> different Gs
    for k in s_mc.kfac_state.factors:
        np.testing.assert_array_equal(
            np.asarray(s_mc.kfac_state.factors[k]),
            np.asarray(s_mc_same.kfac_state.factors[k]))
    assert any(
        not np.allclose(np.asarray(s_mc.kfac_state.factors[str(bg)][rg]),
                        np.asarray(s_mc_other.kfac_state.factors[str(bg)][rg]),
                        atol=1e-6)
        for _, _, bg, rg, _ in precond.plan.layer_rows)

    # the parameter update itself must differ (factors feed the precond)
    diff = jax.tree.map(lambda a, b: float(jnp.max(jnp.abs(a - b))),
                        s_emp.params, s_mc.params)
    assert max(jax.tree.leaves(diff)) > 0


def test_f1mc_on_mesh_runs_and_differs_from_femp():
    """F1mc under shard_map (sampler key folds the device index — same
    per-device stream recipe as dropout): the sharded step runs, its G
    factors differ from Femp's, and the run is seed-reproducible."""
    ndev = 4
    mesh = Mesh(np.array(jax.devices()[:ndev]), ('batch',))
    model = TinyCNN()
    batch = _batch(n=8)

    def one(fisher_type):
        precond = kfac.KFAC(variant='eigen_dp', lr=0.1, damping=0.003,
                            fac_update_freq=1, kfac_update_freq=1,
                            num_devices=ndev, axis_name='batch')
        tx = training.sgd(0.1, momentum=0.9)
        state = training.init_train_state(
            model, tx, precond, jax.random.PRNGKey(0), batch['input'])
        step = training.build_train_step(model, tx, precond, _ce,
                                         axis_name='batch', mesh=mesh,
                                         fisher_type=fisher_type)
        state, m = step(state, batch, lr=0.1, damping=0.003)
        assert np.isfinite(float(m['loss']))
        return state, precond

    s_emp, precond = one('Femp')
    s_mc, _ = one('F1mc')
    s_mc2, _ = one('F1mc')
    changed = any(
        not np.allclose(np.asarray(s_emp.kfac_state.factors[str(bg)][rg]),
                        np.asarray(s_mc.kfac_state.factors[str(bg)][rg]),
                        atol=1e-6)
        for _, _, bg, rg, _ in precond.plan.layer_rows)
    assert changed, 'mesh F1mc left all G factors identical to Femp'
    for k in s_mc.kfac_state.factors:
        np.testing.assert_array_equal(
            np.asarray(s_mc.kfac_state.factors[k]),
            np.asarray(s_mc2.kfac_state.factors[k]))


def test_warm_start_subspace_training_tracks_cold(monkeypatch):
    """warm_start_basis with the subspace tracker (KFAC_EIGH_IMPL=auto
    resolves to it) through the trainer's host gating on a 4-device mesh:
    the warm trajectory must track the cold-eigh run."""
    monkeypatch.setenv('KFAC_EIGH_IMPL', 'auto')
    ndev = 4
    mesh = Mesh(np.array(jax.devices()[:ndev]), ('batch',))
    batch = _batch(n=8, hw=4)

    import flax.linen as linen
    from kfac_pytorch_tpu.nn import Dense

    class MLP(linen.Module):
        @linen.compact
        def __call__(self, x, train=True):
            x = x.reshape((x.shape[0], -1))
            x = linen.relu(Dense(32)(x))
            return Dense(10)(x)

    def run(warm):
        model = MLP()
        precond = kfac.KFAC(variant='eigen_dp', lr=0.05, damping=0.003,
                            kfac_update_freq=2, num_devices=ndev,
                            axis_name='batch', warm_start_basis=warm)
        tx = training.sgd(0.05, momentum=0.9)
        state = training.init_train_state(
            model, tx, precond, jax.random.PRNGKey(0), batch['input'])
        step = training.build_train_step(model, tx, precond, _ce,
                                         axis_name='batch', mesh=mesh)
        losses = []
        for _ in range(8):
            state, m = step(state, batch, lr=0.05, damping=0.003)
            losses.append(float(m['loss']))
        return losses

    cold = run(False)
    warm = run(True)
    assert all(np.isfinite(warm)), warm
    np.testing.assert_allclose(warm[0], cold[0], rtol=1e-5)
    assert abs(warm[-1] - cold[-1]) < 0.25 * abs(cold[0] - cold[-1]) + 1e-3


def test_warm_start_newton_schulz_training_tracks_cold():
    """warm_start_basis on the Cholesky flagship (inverse_dp) through the
    trainer's host gating on a 4-device mesh: warm inverse updates are
    Newton-Schulz seeded by the stored inverse — the trajectory must
    track the cold-Cholesky run."""
    ndev = 4
    mesh = Mesh(np.array(jax.devices()[:ndev]), ('batch',))
    batch = _batch(n=8, hw=4)

    import flax.linen as linen
    from kfac_pytorch_tpu.nn import Dense

    class MLP(linen.Module):
        @linen.compact
        def __call__(self, x, train=True):
            x = x.reshape((x.shape[0], -1))
            x = linen.relu(Dense(32)(x))
            return Dense(10)(x)

    def run(warm):
        model = MLP()
        precond = kfac.KFAC(variant='inverse_dp', lr=0.05, damping=0.003,
                            kfac_update_freq=2, num_devices=ndev,
                            axis_name='batch', warm_start_basis=warm)
        tx = training.sgd(0.05, momentum=0.9)
        state = training.init_train_state(
            model, tx, precond, jax.random.PRNGKey(0), batch['input'])
        step = training.build_train_step(model, tx, precond, _ce,
                                         axis_name='batch', mesh=mesh)
        losses = []
        for _ in range(8):
            state, m = step(state, batch, lr=0.05, damping=0.003)
            losses.append(float(m['loss']))
        return losses

    cold = run(False)
    warm = run(True)
    assert all(np.isfinite(warm)), warm
    np.testing.assert_allclose(warm[0], cold[0], rtol=1e-5)
    # NS converges to the same inverses to f32 noise — tighter than the
    # eigen tracking bound
    assert abs(warm[-1] - cold[-1]) < 0.05 * abs(cold[0] - cold[-1]) + 1e-4


def test_warm_tracking_resume_semantics():
    """Post-resume warm-tracking behavior (VERDICT r2 #8): the host-side
    record (step_fn.warm_tracking) is per-process, so a fresh step_fn
    over a restored state must (a) notice the restored decomposition,
    (b) run its FIRST inverse update as a cold full (no stored basis in
    this process), (c) restart the cold_restart_every streak from zero.
    Restoring the saved record instead continues the streak exactly."""
    import flax.linen as linen
    from kfac_pytorch_tpu.nn import Dense

    class MLP(linen.Module):
        @linen.compact
        def __call__(self, x, train=True):
            x = x.reshape((x.shape[0], -1))
            return Dense(10)(linen.relu(Dense(16)(x)))

    batch = _batch(n=4, hw=4)

    def make():
        model = MLP()
        precond = kfac.KFAC(variant='inverse_dp', lr=0.05, damping=0.003,
                            kfac_update_freq=2, num_devices=1,
                            axis_name=None, warm_start_basis=True)
        tx = training.sgd(0.05, momentum=0.9)
        state = training.init_train_state(
            model, tx, precond, jax.random.PRNGKey(0), batch['input'])
        step = training.build_train_step(model, tx, precond, _ce)
        return step, state

    step, state = make()
    for _ in range(6):  # inverse updates at steps 0, 2, 4
        state, _ = step(state, batch, lr=0.05, damping=0.003)
    pre = dict(step.warm_tracking)
    assert pre['yes'] and pre['last_full'] == 4
    assert pre['warm_streak'] == 2  # step-0 full cold, 2 and 4 warm

    # "resume": fresh step_fn (new process's empty record), same state
    step2, _ = make()
    assert 'last_full' not in step2.warm_tracking
    state, _ = step2(state, batch, lr=0.05, damping=0.003)  # step 6: full
    post = dict(step2.warm_tracking)
    assert post['yes'] is True          # restored decomposition noticed
    assert post['last_full'] == 6       # the full ran...
    assert post['warm_streak'] == 0     # ...cold, streak restarted

    # explicit continuity: restoring the saved record keeps the streak
    step3, _ = make()
    step3.warm_tracking.update(pre)
    state, _ = step3(state, batch, lr=0.05, damping=0.003)  # step 7
    state, _ = step3(state, batch, lr=0.05, damping=0.003)  # step 8: full
    assert step3.warm_tracking['warm_streak'] == pre['warm_streak'] + 1


# ---------------------------------------------------------------------------
# elastic world-change hooks (ISSUE 6: batch/LR rescaling on grow/shrink)
# ---------------------------------------------------------------------------

def test_world_change_rescale_global_batch_invariant():
    """Global-fixed deployments (the example trainers, the chaos drill):
    the optimization trajectory is untouched, so lr_factor is exactly 1
    and the per-host share re-derives — the hook RECORDS, not perturbs,
    which is what keeps the churn drill schedule-equivalent."""
    r = training.world_change_rescale(3, 2, lr=0.1, global_batch=96)
    assert r.lr == 0.1 and r.lr_factor == 1.0
    assert r.global_batch == 96 and r.per_host_batch == 48
    assert r.log_line() == ('WORLD_RESCALE from_world=3 to_world=2 '
                            'global_batch=96 lr=0.1 lr_factor=1')
    # uneven split rounds UP so no example is dropped
    r = training.world_change_rescale(2, 3, lr=0.1, global_batch=8)
    assert r.per_host_batch == 3 and r.global_batch == 8


def test_world_change_rescale_per_host_batch_scales_lr():
    """Per-host-fixed pods: the global batch scales with the world and
    the lr follows under the chosen rule — the accuracy half of
    train-through-churn (linear rule per Goyal et al., sqrt, or
    record-only)."""
    grow = training.world_change_rescale(2, 3, lr=0.1, per_host_batch=64)
    assert grow.global_batch == 192 and grow.per_host_batch == 64
    assert grow.lr_factor == pytest.approx(1.5)
    assert grow.lr == pytest.approx(0.15)
    shrink = training.world_change_rescale(4, 1, lr=0.1,
                                           per_host_batch=32,
                                           lr_scaling='sqrt')
    assert shrink.lr_factor == pytest.approx(0.5)
    assert shrink.lr == pytest.approx(0.05)
    rec = training.world_change_rescale(4, 1, lr=0.1, per_host_batch=32,
                                        lr_scaling='none')
    assert rec.lr == 0.1 and rec.lr_factor == 1.0
    assert rec.global_batch == 32


def test_world_change_rescale_validates_inputs():
    with pytest.raises(ValueError, match='exactly one'):
        training.world_change_rescale(2, 3, lr=0.1)
    with pytest.raises(ValueError, match='exactly one'):
        training.world_change_rescale(2, 3, lr=0.1, global_batch=8,
                                      per_host_batch=4)
    with pytest.raises(ValueError, match='lr_scaling'):
        training.world_change_rescale(2, 3, lr=0.1, per_host_batch=4,
                                      lr_scaling='cubic')
    with pytest.raises(ValueError, match='world sizes'):
        training.world_change_rescale(0, 3, lr=0.1, global_batch=8)


def test_world_rescale_line_matches_incident_grammar():
    """The hook's protocol line is parsed by the SAME pattern table the
    incident scraper and kfac-obs share — a drift in either direction
    fails here."""
    from kfac_pytorch_tpu.resilience.incident import EVENT_PATTERNS
    line = training.world_change_rescale(
        2, 3, lr=0.05, per_host_batch=64).log_line()
    pat = dict(EVENT_PATTERNS)['world_rescale']
    m = pat.search(line)
    assert m, line
    assert m.group('from') == '2' and m.group('to') == '3'
    assert m.group('global_batch') == '192'
    assert float(m.group('lr_factor')) == pytest.approx(1.5)
