"""Pins the analytic perf model (VERDICT r4 #1): the committed
cost-analysis inputs, the fenced-constant eigh fit, the scenario
arithmetic, and the predicted block's shape — so the `predicted`
numbers BENCH_r05.json carries are reproducible and a silent change to
any ingredient fails loudly here."""

import json
import os
import subprocess
import sys

import pytest

from kfac_pytorch_tpu import perfmodel

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_inputs_are_official_resnet50():
    inputs = perfmodel.load_inputs()
    meta = inputs['meta']
    assert meta['official'] is True
    assert (meta['model'], meta['batch'], meta['img']) == ('resnet50', 32,
                                                           224)
    # all nine programs present with positive totals
    for tag in ('sgd', 'inverse_dp_base', 'inverse_dp_factor',
                'inverse_dp_full', 'eigen_dp_base', 'eigen_dp_factor',
                'eigen_dp_full', 'eigen_dp_refresh', 'ekfac_factor'):
        assert inputs['programs'][tag]['flops'] > 0, tag
        assert inputs['programs'][tag]['bytes'] > 0, tag
    # bucket table sane: ResNet-50's largest factor dim is 4608
    # (reference scripts/inverse_model.py:19-20); every bucket holds rows
    dims = [d for _, d in inputs['buckets']]
    assert max(dims) >= 4608
    assert all(r >= 1 for r, _ in inputs['buckets'])


def test_model_flops_sanity():
    """ResNet-50 fwd is ~4 GFLOPs/img at 224^2 (x3 for fwd+bwd, x32
    batch ~= 4e11); the counted sgd-program total must sit in that
    magnitude band — catches a units mixup or a silently-swapped inputs
    file."""
    inputs = perfmodel.load_inputs()
    sgd = inputs['programs']['sgd']['flops']
    assert 1.5e11 < sgd < 2.0e12, sgd


def test_eigh_fit_reproduces_fenced_points():
    _, _, fn = perfmodel.eigh_time_model()
    for rows, dim, secs in perfmodel.FENCED_EIGH_POINTS:
        assert abs(fn(rows, dim) - secs) / secs < 1e-6, (rows, dim)
    # monotone in both arguments (the fit must extrapolate sanely to
    # the 4608 bucket)
    assert fn(1, 4608) > fn(1, 2304) > fn(1, 512) > 0
    assert fn(8, 1024) > fn(4, 1024)


def test_phase_costs_nonnegative_and_ordered():
    inputs = perfmodel.load_inputs()
    ph = perfmodel.phase_costs(inputs)
    for name, (f, b) in ph.items():
        assert f >= 0 and b >= 0, (name, f, b)
    # the factor phase exists and the Cholesky phase is analytic > 0
    assert ph['factor'][0] > 0
    assert ph['inverse_chol'][0] > 0


def test_scenarios_ordered_and_variants_complete():
    pred = perfmodel.predict()
    variants = ('sgd', 'inverse_dp_freq1', 'inverse_dp_freq10',
                'eigen_dp_freq10_cold', 'eigen_dp_freq10_basis100',
                'ekfac_freq10_basis100')
    for v in variants:
        o = pred['optimistic'][v]['iter_s']
        c = pred['central'][v]['iter_s']
        k = pred['conservative'][v]['iter_s']
        assert 0 < o < c < k, (v, o, c, k)
        # vs_baseline arithmetic: imgs/s over the 0.487 s anchor's rate
        got = pred['central'][v]['vs_baseline']
        want = (perfmodel.BATCH / c) / (perfmodel.BATCH
                                        / perfmodel.BASELINE_ITER_S)
        assert abs(got - want) < 0.01 + 0.005 * want, (v, got, want)


def test_quantified_eigen_path_gap():
    """The model must reproduce the round-2 discovery AS A NUMBER: the
    reference's default variant (cold eigen_dp, its deployed freq-10
    cadence) is dominated by the fenced QDWH seconds-per-bucket term and
    cannot compete with the Cholesky flagship on this chip — in EVERY
    scenario, including optimistic."""
    pred = perfmodel.predict()
    for scen in perfmodel.SCENARIOS:
        cold = pred[scen]['eigen_dp_freq10_cold']['iter_s']
        chol = pred[scen]['inverse_dp_freq10']['iter_s']
        assert cold > 5 * chol, (scen, cold, chol)
        # and the amortized rescue recovers most of the gap
        rescued = pred[scen]['eigen_dp_freq10_basis100']['iter_s']
        assert rescued < cold / 2, (scen, rescued, cold)


def test_predict_block_shape():
    blk = perfmodel.predict_block()
    assert blk['predicted_not_measured'] is True
    assert 'error' not in blk, blk.get('error')
    assert blk['anchor']['reference_kfac_iter_s'] == 0.487
    assert blk['headline']['value'] == \
        blk['scenarios']['central']['inverse_dp_freq1']['imgs_per_s']
    # the assumptions block must disclose its own weakest points
    a = blk['assumptions']
    assert 'eigh_fit' in a and 'fenced_points' in a['eigh_fit']
    assert 'skinny_floor_datapoint' in a


@pytest.mark.slow
def test_derivation_script_smoke(tmp_path):
    """The derivation pipeline itself stays runnable: tiny-config run
    produces a structurally-valid inputs file that predict() accepts."""
    out = tmp_path / 'inputs.json'
    env = {k: v for k, v in os.environ.items()
           if k not in ('XLA_FLAGS', 'JAX_PLATFORMS')}
    env.update(KFAC_PLATFORM='cpu', DERIVE_MODEL='resnet20',
               DERIVE_IMG='32', DERIVE_BATCH='8')
    subprocess.run([sys.executable, 'scripts/derive_perf_inputs.py',
                    '--out', str(out)], cwd=REPO, env=env, check=True,
                   timeout=900, stdout=subprocess.DEVNULL)
    inputs = json.loads(out.read_text())
    assert inputs['meta']['official'] is False
    pred = perfmodel.predict(inputs)  # arithmetic accepts the structure
    assert pred['central']['inverse_dp_freq1']['iter_s'] > 0
