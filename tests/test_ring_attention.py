"""Sequence/context-parallel attention tests: ring attention and Ulysses
all-to-all must match dense single-device softmax attention exactly
(values AND gradients) with the sequence sharded over the virtual mesh."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kfac_pytorch_tpu.parallel import ring_attention, ulysses_attention

B, H, L, D = 2, 8, 32, 8


def dense_attention(q, k, v, causal=False, kv_mask=None):
    s = jnp.einsum('bhqd,bhkd->bhqk', q, k) / np.sqrt(D)
    if causal:
        qpos = jnp.arange(L)[:, None]
        s = jnp.where(qpos >= jnp.arange(L)[None, :], s, -1e30)
    if kv_mask is not None:
        s = jnp.where(kv_mask[:, None, None, :], s, -1e30)
    return jnp.einsum('bhqk,bhkd->bhqd', jax.nn.softmax(s, axis=-1), v)


def _qkv(seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(B, H, L, D), jnp.float32)
    return mk(), mk(), mk()


def _sharded(fn, mesh, n):
    """Wrap attention fn in shard_map with the sequence axis sharded."""
    spec = P(None, None, 'seq', None)
    return jax.jit(jax.shard_map(
        fn, mesh=mesh, in_specs=(spec, spec, spec, P(None, 'seq')),
        out_specs=spec))


@pytest.fixture(scope='module')
def mesh():
    devs = jax.devices()[:8]
    return Mesh(np.array(devs), ('seq',))


@pytest.mark.parametrize('causal', [False, True])
@pytest.mark.parametrize('impl', [ring_attention, ulysses_attention])
def test_matches_dense(mesh, causal, impl):
    q, k, v = _qkv()
    kv_mask = jnp.asarray(
        np.random.RandomState(1).rand(B, L) > 0.2)

    fn = functools.partial(impl, axis_name='seq', causal=causal)
    out = _sharded(lambda q, k, v, m: fn(q, k, v, kv_mask=m > 0.5),
                   mesh, 8)(q, k, v, kv_mask.astype(jnp.float32))
    ref = dense_attention(q, k, v, causal=causal, kv_mask=kv_mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize('impl', [ring_attention, ulysses_attention])
def test_gradients_match_dense(mesh, impl):
    q, k, v = _qkv(seed=2)

    def loss_ring(q, k, v):
        spec = P(None, None, 'seq', None)
        out = jax.shard_map(
            functools.partial(impl, axis_name='seq', causal=True),
            mesh=mesh, in_specs=(spec,) * 3, out_specs=spec)(q, k, v)
        return (out ** 2).sum()

    def loss_dense(q, k, v):
        return (dense_attention(q, k, v, causal=True) ** 2).sum()

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_dense):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, rtol=5e-4)


def test_single_device_degenerate_path():
    q, k, v = _qkv(seed=3)
    out = ring_attention(q, k, v, axis_name=None, causal=True)
    ref = dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_ulysses_rejects_indivisible_heads(mesh):
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, 3, L, D), jnp.float32)  # 3 heads, 8 devs
    spec = P(None, None, 'seq', None)
    with pytest.raises(ValueError, match='ulysses'):
        jax.jit(jax.shard_map(
            functools.partial(ulysses_attention, axis_name='seq'),
            mesh=mesh, in_specs=(spec,) * 3, out_specs=spec))(q, q, q)


def test_fully_padded_rows_do_not_nan(mesh):
    q, k, v = _qkv(seed=4)
    kv_mask = jnp.zeros((B, L), jnp.float32)  # everything masked
    spec = P(None, None, 'seq', None)
    out = jax.jit(jax.shard_map(
        lambda q, k, v, m: ring_attention(q, k, v, 'seq', kv_mask=m > 0.5),
        mesh=mesh, in_specs=(spec,) * 3 + (P(None, 'seq'),),
        out_specs=spec))(q, k, v, kv_mask)
    assert np.isfinite(np.asarray(out)).all()
