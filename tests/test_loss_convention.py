"""The LOCAL-mean loss convention guard (capture.check_local_mean_loss):
it must reject, at trace time, the exact round-3 postmortem mistake — a
loss psum/pmean-normalized across the K-FAC world before the capture
backward (scripts/repro_mpd_eigen_orthogonal_axis.py mistake #1) — while
the convention-respecting local-mean loss passes untouched, both through
build_train_step (guard applied automatically) and in a direct shard_map
harness (guard called explicitly, one line)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax import linen
from jax.sharding import Mesh, PartitionSpec as P

import kfac_pytorch_tpu as kfac
from kfac_pytorch_tpu import capture, training
from kfac_pytorch_tpu import nn as knn

pytestmark = pytest.mark.core

B, DIN, DOUT, ND = 8, 6, 4, 4


class MLP(linen.Module):
    @linen.compact
    def __call__(self, x):
        return knn.Dense(DOUT, name='fc')(x)


def _data():
    rng = np.random.RandomState(0)
    return (jnp.asarray(rng.randn(B, DIN), jnp.float32),
            jnp.asarray(rng.randn(B, DOUT), jnp.float32))


def _mesh():
    return Mesh(np.array(jax.devices()[:ND]), ('batch',))


def _direct_harness(global_norm):
    model = MLP()
    x, y = _data()
    variables = capture.init(model, jax.random.PRNGKey(0), x)

    @functools.partial(jax.shard_map, mesh=_mesh(),
                       in_specs=(P(), P('batch'), P('batch')),
                       out_specs=P())
    def step(params, x, y):
        def loss_fn(out):
            if global_norm:
                # the postmortem's mistake: globally-psum-normalized loss
                return jax.lax.psum(((out - y) ** 2).sum() / y.size,
                                    'batch')
            return ((out - y) ** 2).mean()   # LOCAL mean: the convention

        loss, _, grads, acts, gs, _ = capture.value_and_grad_with_capture(
            model, loss_fn, {'params': params}, x, axis_name='batch')
        capture.check_local_mean_loss(loss, (x, y), 'batch')
        return jax.lax.pmean(loss, 'batch')

    return step(variables['params'], x, y)


def test_direct_capture_guard_rejects_global_psum_loss():
    with pytest.raises(ValueError, match='convention'):
        _direct_harness(global_norm=True)


def test_direct_capture_guard_passes_local_mean_loss():
    assert np.isfinite(float(_direct_harness(global_norm=False)))


def _run_train_step(loss_fn, use_kfac=True):
    model = MLP()
    x, y = _data()
    batch = {'input': x, 'label': y}
    precond = None
    if use_kfac:
        precond = kfac.KFAC(variant='eigen_dp', lr=0.1, damping=0.003,
                            fac_update_freq=1, kfac_update_freq=1,
                            num_devices=ND, axis_name='batch')
    tx = training.sgd(0.1)
    state = training.init_train_state(model, tx, precond,
                                      jax.random.PRNGKey(0), x)
    step = training.build_train_step(model, tx, precond, loss_fn,
                                     axis_name='batch', mesh=_mesh())
    return step(state, batch, lr=0.1, damping=0.003)


def test_build_train_step_guard_rejects_pmean_loss():
    def bad(outputs, batch):
        return jax.lax.pmean(((outputs - batch['label']) ** 2).mean(),
                             'batch')

    with pytest.raises(ValueError, match='convention'):
        _run_train_step(bad)


def test_build_train_step_guard_rejects_pmean_loss_sgd_path():
    """precond=None takes the plain value_and_grad branch, where
    average_grads still divides psummed grads by world size — a
    pre-pmean'd loss double-normalizes, so the guard covers it too."""
    def bad(outputs, batch):
        return jax.lax.pmean(((outputs - batch['label']) ** 2).mean(),
                             'batch')

    with pytest.raises(ValueError, match='convention'):
        _run_train_step(bad, use_kfac=False)


def test_build_train_step_local_mean_loss_passes():
    def good(outputs, batch):
        return ((outputs - batch['label']) ** 2).mean()

    for use_kfac in (True, False):
        state, metrics = _run_train_step(good, use_kfac=use_kfac)
        assert np.isfinite(float(metrics['loss']))
