"""Capture machinery: activations and output-gradients must match the
hand-derived values a torch hook would have seen
(reference: kfac_preconditioner_base.py:122-130)."""

import flax.linen as linen
import jax
import jax.numpy as jnp
import numpy as np

from kfac_pytorch_tpu import capture
from kfac_pytorch_tpu import nn as knn


class MLP(linen.Module):
    @linen.compact
    def __call__(self, x):
        x = knn.Dense(8, name='fc1')(x)
        x = linen.relu(x)
        x = knn.Dense(3, name='fc2')(x)
        return x


class ConvNet(linen.Module):
    @linen.compact
    def __call__(self, x):
        x = knn.Conv(4, (3, 3), strides=(2, 2), padding='SAME', name='c1')(x)
        x = linen.relu(x)
        x = x.reshape(x.shape[0], -1)
        x = knn.Dense(2, name='head')(x)
        return x


def test_meta_discovery():
    model = MLP()
    x = jnp.ones((4, 5))
    variables = capture.init(model, jax.random.PRNGKey(0), x)
    assert set(variables) == {'params'}  # capture collections stripped
    metas = capture.collect_layer_meta(model, variables, x)
    assert list(metas) == ['fc1', 'fc2']
    m1 = metas['fc1']
    assert (m1.kind, m1.in_dim, m1.out_dim, m1.use_bias) == ('dense', 6, 8, True)


def test_meta_discovery_conv_and_vocab_exclusion():
    model = ConvNet()
    x = jnp.ones((2, 8, 8, 3))
    variables = capture.init(model, jax.random.PRNGKey(0), x)
    metas = capture.collect_layer_meta(model, variables, x)
    mc = metas['c1']
    assert mc.kind == 'conv' and mc.in_dim == 3 * 3 * 3 + 1 and mc.out_dim == 4
    assert mc.padding == ((0, 1), (0, 1))  # SAME for 8->4 with k3 s2
    metas2 = capture.collect_layer_meta(model, variables, x,
                                        exclude_vocabulary_size=2)
    assert list(metas2) == ['c1']


def test_capture_matches_manual_backprop():
    model = MLP()
    x = jnp.asarray(np.random.RandomState(0).randn(4, 5), jnp.float32)
    y = jnp.asarray(np.random.RandomState(1).randn(4, 3), jnp.float32)
    variables = capture.init(model, jax.random.PRNGKey(0), x)
    params = variables['params']

    loss_fn = lambda out: jnp.mean((out - y) ** 2)
    loss, out, grads, acts, gs, _ = capture.value_and_grad_with_capture(
        model, loss_fn, variables, x)

    # manual forward with explicit intermediates
    w1, b1 = params['fc1']['kernel'], params['fc1']['bias']
    w2, b2 = params['fc2']['kernel'], params['fc2']['bias']

    def manual(w1, b1, w2, b2, y1_tap, y2_tap):
        y1 = x @ w1 + b1 + y1_tap
        h = jax.nn.relu(y1)
        y2 = h @ w2 + b2 + y2_tap
        return jnp.mean((y2 - y) ** 2)

    z1, z2 = jnp.zeros((4, 8)), jnp.zeros((4, 3))
    mloss = manual(w1, b1, w2, b2, z1, z2)
    g1, g2 = jax.grad(manual, argnums=(4, 5))(w1, b1, w2, b2, z1, z2)
    mgrads = jax.grad(lambda p: manual(p['fc1']['kernel'], p['fc1']['bias'],
                                       p['fc2']['kernel'], p['fc2']['bias'],
                                       z1, z2))(params)

    np.testing.assert_allclose(float(loss), float(mloss), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(capture.layer_act(acts, type(
        'M', (), {'path': ('fc1',)})())), np.asarray(x), atol=1e-6)
    # fc2's input is relu(y1)
    h = np.asarray(jax.nn.relu(x @ w1 + b1))
    np.testing.assert_allclose(
        np.asarray(acts['fc2']['a']), h, atol=1e-6)
    np.testing.assert_allclose(np.asarray(gs['fc1']['g']), np.asarray(g1),
                               atol=1e-6)
    np.testing.assert_allclose(np.asarray(gs['fc2']['g']), np.asarray(g2),
                               atol=1e-6)
    for lyr in ('fc1', 'fc2'):
        for p in ('kernel', 'bias'):
            np.testing.assert_allclose(np.asarray(grads[lyr][p]),
                                       np.asarray(mgrads[lyr][p]), atol=1e-6)


def test_plain_apply_has_no_capture_overhead():
    model = MLP()
    x = jnp.ones((2, 5))
    variables = capture.init(model, jax.random.PRNGKey(0), x)
    out = model.apply(variables, x)  # no mutable collections, no taps
    out2, acts, _ = capture.apply_with_capture(model, variables, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(out2), atol=1e-6)
    assert 'fc1' in acts


def test_conv_capture_g_shape_and_value():
    model = ConvNet()
    x = jnp.asarray(np.random.RandomState(2).randn(2, 8, 8, 3), jnp.float32)
    variables = capture.init(model, jax.random.PRNGKey(1), x)
    loss_fn = lambda out: jnp.sum(out ** 2)
    _, _, grads, acts, gs, _ = capture.value_and_grad_with_capture(
        model, loss_fn, variables, x)
    assert gs['c1']['g'].shape == (2, 4, 4, 4)  # NHWC output grad
    assert acts['c1']['a'].shape == (2, 8, 8, 3)


def test_vocab_exclusion_only_trailing_head():
    """vocab == 4*hidden collision: the KFACLSTMCell gate projections must
    stay preconditioned; only the trailing pre-softmax decoder is dropped
    (with a warning about the interior dim match)."""
    import warnings

    from kfac_pytorch_tpu.models.rnn import wikitext_lstm

    m = wikitext_lstm(64, embed_dim=16, hidden_dim=16, num_layers=1,
                      dropout=0.0, kfac_lstm=True)
    toks = jnp.zeros((2, 4), jnp.int32)
    variables = capture.init(m, jax.random.PRNGKey(0), toks, train=False)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter('always')
        metas = capture.collect_layer_meta(m, variables, toks, train=False,
                                           exclude_vocabulary_size=64)
    assert set(metas) == {'lstm_scan_0/ih', 'lstm_scan_0/hh'}, metas
    assert any('not the trailing pre-softmax head' in str(x.message)
               for x in w)
