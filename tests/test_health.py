"""Numerical-health guard chaos drills (beyond reference, health.py).

The acceptance drill: with a NaN-gradient fault injected at step k,
training runs to completion with finite loss, and params/opt_state and
the K-FAC factor state are BIT-identical to a run whose data schedule
simply skipped batch k — the EMA is uncontaminated and the trajectory
never forks. Plus: ladder escalation/degrade/recover semantics, and the
no-new-compiled-variants guarantee on the healthy path.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import kfac_pytorch_tpu as kfac
from kfac_pytorch_tpu import faults, training
from kfac_pytorch_tpu import health as health_lib
from kfac_pytorch_tpu.utils.metrics import HealthMonitor
from kfac_pytorch_tpu.utils.runlog import health_suffix

from tests.helpers import TinyCNN


def _batches(n_batches, n=8, hw=8, seed=0):
    rng = np.random.RandomState(seed)
    return [{'input': jnp.asarray(rng.randn(n, hw, hw, 3), jnp.float32),
             'label': jnp.asarray(rng.randint(0, 10, n))}
            for _ in range(n_batches)]


def _ce(outputs, batch):
    return optax.softmax_cross_entropy_with_integer_labels(
        outputs, batch['label']).mean()


def _run(batches, health=True):
    """Fresh model/precond/state, one step per batch; returns the final
    state, the per-step metrics and the step_fn (variant introspection)."""
    model = TinyCNN()
    precond = kfac.KFAC(variant='eigen_dp', lr=0.05, damping=0.003,
                        fac_update_freq=1, kfac_update_freq=1,
                        num_devices=1, axis_name=None, health=health)
    tx = training.sgd(0.05, momentum=0.9)
    state = training.init_train_state(model, tx, precond,
                                      jax.random.PRNGKey(0),
                                      batches[0]['input'])
    step = training.build_train_step(model, tx, precond, _ce)
    mets = []
    for b in batches:
        state, m = step(state, b, lr=0.05, damping=0.003)
        mets.append({k: float(v) for k, v in m.items()})
    return state, mets, step


def _assert_trees_equal(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_nan_batch_skips_update_and_ema(monkeypatch):
    """The acceptance chaos drill: NaN gradients at step 2 -> that batch
    is skipped in-jit, the run finishes finite, and params/opt_state/
    factors/decomp are BIT-identical to a run whose schedule never
    contained batch 2."""
    batches = _batches(5)
    monkeypatch.setenv(faults.ENV_NAN_GRAD, '2')
    faulted, mets, _ = _run(batches)
    monkeypatch.delenv(faults.ENV_NAN_GRAD)
    control, cmets, _ = _run(batches[:2] + batches[3:])

    # the fault fired exactly once, at step 2, and every loss is finite
    assert [m['health/ok'] for m in mets] == [1, 1, 0, 1, 1]
    assert mets[-1]['health/skipped'] == 1
    assert all(np.isfinite(m['loss']) for m in mets)
    # an isolated failure must not climb the damping ladder (that would
    # fork the post-skip trajectory from the control run)
    assert mets[-1]['health/rung'] == 0

    _assert_trees_equal(faulted.params, control.params)
    _assert_trees_equal(faulted.opt_state, control.opt_state)
    _assert_trees_equal(faulted.kfac_state.factors,
                        control.kfac_state.factors)
    _assert_trees_equal(faulted.kfac_state.decomp, control.kfac_state.decomp)
    # only the counters differ: the faulted run saw one more batch
    assert int(faulted.step) == 5 and int(control.step) == 4
    assert int(faulted.kfac_state.step) == 5


def test_consecutive_failures_climb_ladder_then_recover(monkeypatch):
    """4 consecutive bad batches: the ladder climbs to the top rung
    (degraded SGD), healthy steps then reset it after recover_after."""
    cfg = health_lib.HealthConfig(escalate_after=2, damping_factor=10.0,
                                  max_rungs=2, recover_after=2)
    monkeypatch.setenv(faults.ENV_NAN_GRAD, '2:6')
    batches = _batches(10, seed=1)
    state, mets, _ = _run(batches, health=cfg)

    assert [m['health/ok'] for m in mets] == [1, 1, 0, 0, 0, 0, 1, 1, 1, 1]
    assert mets[-1]['health/skipped'] == 4
    # rung after each step: 1st failure doesn't escalate, 2nd does, top
    # rung holds through the streak AND through the first healthy step,
    # then recover_after healthy steps reset it
    assert [m['health/rung'] for m in mets] == [0, 0, 0, 1, 2, 2, 2, 0, 0, 0]
    assert all(np.isfinite(m['loss']) for m in mets)
    for leaf in jax.tree.leaves(state.params):
        assert np.all(np.isfinite(np.asarray(leaf)))


def test_transition_functions():
    """Pure-function semantics of the ladder state machine."""
    cfg = health_lib.HealthConfig(escalate_after=2, damping_factor=10.0,
                                  max_rungs=3, recover_after=2)
    h = health_lib.HealthState.init()
    h = health_lib.on_bad_batch(h, cfg)
    assert int(h.bad_streak) == 1 and int(h.rung) == 0
    h = health_lib.on_bad_batch(h, cfg)
    assert int(h.rung) == 1 and int(h.skipped) == 2
    # non-finite preconditioner output escalates like a skipped batch
    h = health_lib.on_good_batch(h, cfg, jnp.asarray(False))
    assert int(h.rung) == 2 and int(h.fallbacks) == 1
    assert float(health_lib.effective_damping(h, 0.003, cfg)) == (
        pytest.approx(0.3))
    assert not bool(health_lib.degraded(h, cfg))
    h = health_lib.on_bad_batch(h, cfg)
    assert int(h.rung) == 3 and bool(health_lib.degraded(h, cfg))
    # rung saturates at max_rungs
    h = health_lib.on_bad_batch(h, cfg)
    assert int(h.rung) == 3
    # recovery: recover_after consecutive healthy steps reset the ladder
    h = health_lib.on_good_batch(h, cfg, jnp.asarray(True))
    assert int(h.rung) == 3 and int(h.bad_streak) == 0
    h = health_lib.on_good_batch(h, cfg, jnp.asarray(True))
    assert int(h.rung) == 0 and int(h.good_streak) == 2


def test_healthy_path_compiles_same_variant_count(monkeypatch):
    """The guard adds no compiled step variants: same dispatch keys with
    health on, health off, and health on + a configured (unfired) fault."""
    batches = _batches(4, seed=2)
    _, _, step_on = _run(batches, health=True)
    _, _, step_off = _run(batches, health=False)
    assert set(step_on.variants) == set(step_off.variants)
    monkeypatch.setenv(faults.ENV_NAN_GRAD, '100')  # never fires in 4 steps
    _, mets, step_armed = _run(batches, health=True)
    assert set(step_armed.variants) == set(step_on.variants)
    assert all(m['health/ok'] == 1 for m in mets)


def test_stats_fault_triggers_skip(monkeypatch):
    """NaN captured (a, g) statistics with FINITE gradients still skip the
    batch — the screen covers the factor statistics, not just grads."""
    monkeypatch.setenv(faults.ENV_STATS, '1')
    batches = _batches(3, seed=3)
    state, mets, _ = _run(batches)
    assert [m['health/ok'] for m in mets] == [1, 0, 1]
    assert mets[-1]['health/skipped'] == 1
    for leaf in jax.tree.leaves(state.kfac_state.factors):
        assert np.all(np.isfinite(np.asarray(leaf)))


def test_guard_off_nan_contaminates(monkeypatch):
    """Negative control: with health=False the same injected batch
    permanently poisons params — the guard is what prevents it."""
    monkeypatch.setenv(faults.ENV_NAN_GRAD, '1')
    batches = _batches(3, seed=4)
    state, mets, _ = _run(batches, health=False)
    assert not any(k.startswith('health/') for k in mets[0])
    assert state.health is None
    bad = any(not np.all(np.isfinite(np.asarray(leaf)))
              for leaf in jax.tree.leaves(state.params))
    assert bad, 'NaN batch should contaminate an unguarded run'


def test_health_monitor_and_suffix():
    """Host-side monitor: diffs cumulative counters, counts per-epoch
    deltas, formats the run-log suffix (empty when clean)."""
    mon = HealthMonitor()
    mon.update({'health/ok': 1, 'health/skipped': 0, 'health/fallbacks': 0,
                'health/rung': 0, 'health/bad_streak': 0})
    assert health_suffix(mon.epoch_flush()) == ''
    mon.update({'health/ok': 0, 'health/skipped': 2, 'health/fallbacks': 1,
                'health/rung': 1, 'health/bad_streak': 2})
    s = health_suffix(mon.epoch_flush())
    assert s == ' [health: skipped=2 sgd_fallbacks=1 max_rung=1]'
    # flush reset the epoch accumulators; cumulative totals keep running
    assert health_suffix(mon.epoch_flush()) == ''
    assert mon.skipped == 2 and mon.fallbacks == 1
    # metrics without health/* are a no-op (guard disabled)
    mon.update({'loss': 1.0})


def test_resolve():
    assert health_lib.resolve(True) == health_lib.HealthConfig()
    assert health_lib.resolve(False) is None
    assert health_lib.resolve(None) is None
    cfg = health_lib.HealthConfig(max_rungs=5)
    assert health_lib.resolve(cfg) is cfg
    with pytest.raises(TypeError):
        health_lib.resolve('yes')
