"""Factory-surface parity tests (reference: kfac/__init__.py:8-16,
kfac/dp_kfac.py:4-39) and profiling helpers."""

import jax
import jax.numpy as jnp
import pytest

import kfac_pytorch_tpu as kfac
from kfac_pytorch_tpu.utils import profiling


def test_get_kfac_module_binds_variant():
    for name in kfac.KFAC_VARIANTS:
        factory = kfac.get_kfac_module(name)
        p = factory(lr=0.2, damping=0.01)
        assert p.variant == name
        assert p.lr == 0.2


def test_get_kfac_module_unknown_raises():
    with pytest.raises(KeyError):
        kfac.get_kfac_module('nope')


def test_dp_kfac_facade_selects_dp_variants():
    assert kfac.DP_KFAC(inv_type='eigen').variant == 'eigen_dp'
    assert kfac.DP_KFAC(inv_type='inverse').variant == 'inverse_dp'


def test_variant_table_matches_reference_semantics():
    # MPD variants allreduce factor stats; DP variants keep them local
    assert kfac.KFAC(variant='inverse').stats_reduce == 'pmean'
    assert kfac.KFAC(variant='eigen').stats_reduce == 'pmean'
    assert kfac.KFAC(variant='inverse_dp').stats_reduce == 'local'
    assert kfac.KFAC(variant='eigen_dp').stats_reduce == 'local'
    # comm modes: eigen forces inverse comm (eigen.py:52); dp comm preds
    assert kfac.KFAC(variant='eigen').comm_mode == 'inverse'
    assert kfac.KFAC(variant='eigen_dp').comm_mode == 'pred'
    assert kfac.KFAC(variant='inverse').comm_mode == 'pred'
    assert kfac.KFAC(
        variant='inverse', communicate_inverse_or_not=True
    ).comm_mode == 'inverse'


def test_time_steps_returns_steady_state_stats():
    calls = []

    def fake_step(state, batch, **kw):
        calls.append(1)
        return state, jnp.float32(0.0)

    mean, std, state = profiling.time_steps(fake_step, 0, None, iters=4,
                                            warmup=2)
    assert len(calls) == 6
    assert mean >= 0 and std >= 0


def test_exclude_parts_breakdown_shape():
    def make_step(excl):
        def step(state, batch, **kw):
            return state, jnp.float32(len(excl))
        return step, 0

    out = profiling.exclude_parts_breakdown(make_step, None, iters=2)
    assert set(out) == {'Total', 'Rest'} | set(profiling.PHASES)
    assert all(v >= 0 for v in out.values())


def test_speed_report_logs_real_units(caplog):
    """speed_report must emit the canonical parseable SPEED line with the
    caller-supplied per-iteration unit count."""
    import logging

    calls = {'n': 0}

    def fake_step(state, batch, **kw):
        calls['n'] += 1
        return state, {'loss': jnp.float32(1.0)}

    log = logging.getLogger('speed-test')
    with caplog.at_level(logging.INFO, logger='speed-test'):
        profiling.speed_report(log, fake_step, 0, None, 256,
                               unit='imgs/sec', iters=3, warmup=1)
    assert calls['n'] == 4
    msg = caplog.records[-1].getMessage()
    assert msg.startswith('SPEED: iter time ') and 'imgs/sec' in msg
    # the canonical format round-trips through the log parser
    import os as _os
    import sys as _sys
    _sys.path.insert(0, _os.path.join(_os.path.dirname(__file__), '..'))
    from scripts.parse_logs import SPEED_RE
    assert SPEED_RE.search('x ' + msg)
