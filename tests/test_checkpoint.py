"""Checkpoint / resume tests (reference semantics: rank-0 save of
{model, optimizer} (examples/utils.py:11-18), ImageNet auto-resume by
scanning checkpoint-{epoch} downward (pytorch_imagenet_resnet.py:162-167,
305-312); upgrade: K-FAC factor state round-trips too)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import kfac_pytorch_tpu as kfac
from kfac_pytorch_tpu import models, training
from kfac_pytorch_tpu.utils import checkpoint


@pytest.fixture(scope='module')
def trained_state():
    model = models.get_model('resnet20')
    precond = kfac.KFAC(variant='eigen_dp', lr=0.1, damping=0.003)
    tx = training.sgd(0.1, momentum=0.9)
    x = jnp.ones((4, 16, 16, 3), jnp.float32)
    state = training.init_train_state(model, tx, precond,
                                      jax.random.PRNGKey(0), x)

    def ce(outputs, b):
        return optax.softmax_cross_entropy_with_integer_labels(
            outputs, b['label']).mean()

    step = training.build_train_step(model, tx, precond, ce,
                                     extra_mutable=('batch_stats',))
    batch = {'input': x, 'label': jnp.asarray([0, 1, 2, 3])}
    state, _ = step(state, batch, lr=0.1, damping=0.003)
    return state


def test_save_restore_roundtrip(tmp_path, trained_state):
    checkpoint.save_checkpoint(tmp_path, 3, trained_state)
    target = jax.tree.map(np.zeros_like, trained_state)
    restored = checkpoint.restore_checkpoint(tmp_path, 3, target)
    for a, b in zip(jax.tree.leaves(trained_state),
                    jax.tree.leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_restore_without_kfac_state(tmp_path, trained_state):
    # reference behavior: K-FAC state NOT checkpointed; factors rebuild
    checkpoint.save_checkpoint(tmp_path, 1, trained_state,
                               include_kfac=False)
    target = jax.tree.map(np.zeros_like,
                          trained_state.replace(kfac_state=None))
    restored = checkpoint.restore_checkpoint(tmp_path, 1, target)
    np.testing.assert_array_equal(
        np.asarray(jax.tree.leaves(restored.params)[0]),
        np.asarray(jax.tree.leaves(trained_state.params)[0]))
    assert restored.kfac_state is None


def test_find_resume_epoch_scans_downward(tmp_path, trained_state):
    assert checkpoint.find_resume_epoch(tmp_path, 10) is None
    checkpoint.save_checkpoint(tmp_path, 2, trained_state)
    checkpoint.save_checkpoint(tmp_path, 5, trained_state)
    # scans from max_epoch downward and returns the newest present
    assert checkpoint.find_resume_epoch(tmp_path, 10) == 5
    assert checkpoint.find_resume_epoch(tmp_path, 4) == 2


@pytest.mark.slow
def test_preemption_guard_saves_and_exits(tmp_path):
    """SIGTERM drill (beyond-reference §5.3): the trainer saves the live
    TrainState inside the grace window, exits cleanly, and the
    checkpoint restores."""
    import os
    import re
    import signal
    import subprocess
    import sys
    import time as _time

    env = dict(os.environ, KFAC_PLATFORM='cpu', KFAC_HOST_DEVICES='1')
    logf = tmp_path / 'out.log'
    with open(logf, 'w') as f:
        proc = subprocess.Popen(
            [sys.executable, 'examples/cifar10_resnet.py', '--model',
             'resnet20', '--epochs', '50', '--batch-size', '16',
             '--kfac-update-freq', '5', '--kfac-cov-update-freq', '5',
             '--num-devices', '1',
             '--checkpoint-dir', str(tmp_path / 'ckpt')],
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            env=env, stdout=f, stderr=subprocess.STDOUT)
        try:
            deadline = _time.time() + 420
            while _time.time() < deadline:
                if 'epoch 0:' in logf.read_text():
                    break
                if proc.poll() is not None:
                    raise AssertionError(
                        'trainer died early:\n' + logf.read_text()[-2000:])
                _time.sleep(2)
            else:
                raise AssertionError('epoch 0 never appeared:\n'
                                     + logf.read_text()[-2000:])
            proc.send_signal(signal.SIGTERM)
            rc = proc.wait(timeout=180)
        finally:
            if proc.poll() is None:
                proc.kill()
    out = logf.read_text()
    assert rc == 0, (rc, out[-2000:])
    assert ('preempted in epoch' in out          # mid-train-loop save path
            or 'preempted after epoch' in out), out[-2000:]  # post-val path
    epochs = [int(m) for m in re.findall(r'checkpoint-(\d+)',
                                         ' '.join(os.listdir(tmp_path / 'ckpt')))]
    assert epochs, os.listdir(tmp_path / 'ckpt')
    # the saved checkpoint restores into a fresh state skeleton
    model = models.resnet20()
    precond = kfac.KFAC(variant='eigen_dp', lr=0.1, damping=0.003,
                        fac_update_freq=5, kfac_update_freq=5,
                        num_devices=1, axis_name=None)
    # the trainer passes an lr *schedule* into sgd — match its opt_state
    # tree structure, not just its shapes
    tx = training.sgd(lambda s: 0.1, momentum=0.9, weight_decay=5e-4)
    skel = training.init_train_state(model, tx, precond,
                                     jax.random.PRNGKey(0),
                                     jnp.zeros((16, 32, 32, 3)))
    restored = checkpoint.restore_checkpoint(str(tmp_path / 'ckpt'),
                                             max(epochs), skel)
    assert int(restored.step) > 0


def test_prune_and_find_mixed_layouts(tmp_path):
    """Retention x resume scanning on a directory holding BOTH orbax-style
    checkpoint dirs and pickle-fallback ``.pkl`` files (a run that crossed
    an environment change)."""
    import os

    from kfac_pytorch_tpu.utils.checkpoint import (find_resume_epoch,
                                                   prune_checkpoints)
    (tmp_path / 'checkpoint-0').mkdir()
    (tmp_path / 'checkpoint-1.pkl').write_bytes(b'x')
    (tmp_path / 'checkpoint-2').mkdir()
    (tmp_path / 'checkpoint-3.pkl').write_bytes(b'x')
    # a stale atomic-write tmp file must be invisible to both
    (tmp_path / 'checkpoint-4.pkl.tmp').write_bytes(b'x')
    assert find_resume_epoch(tmp_path, 10) == 3
    assert find_resume_epoch(tmp_path, 2) == 2
    prune_checkpoints(str(tmp_path), 2)
    assert sorted(os.listdir(tmp_path)) == [
        'checkpoint-2', 'checkpoint-3.pkl', 'checkpoint-4.pkl.tmp']
    assert find_resume_epoch(tmp_path, 10) == 3
    # retention removes dir and pkl layouts alike
    prune_checkpoints(str(tmp_path), 1)
    assert not (tmp_path / 'checkpoint-2').exists()
    assert find_resume_epoch(tmp_path, 10) == 3
    assert (tmp_path / 'checkpoint-4.pkl.tmp').exists()


def test_pkl_save_is_atomic(tmp_path, monkeypatch):
    """The pickle fallback writes tmp-then-rename: after a successful save
    no ``.pkl.tmp`` residue exists and the file round-trips."""
    import numpy as _np

    monkeypatch.setattr(checkpoint, '_HAS_ORBAX', False)
    payload = {'w': _np.arange(16, dtype=_np.float32)}
    checkpoint.save_checkpoint(tmp_path, 7, payload)
    assert (tmp_path / 'checkpoint-7.pkl').exists()
    assert not (tmp_path / 'checkpoint-7.pkl.tmp').exists()
    restored = checkpoint.restore_checkpoint(tmp_path, 7, payload)
    _np.testing.assert_array_equal(restored['w'], payload['w'])


def test_auto_resume_restores_pre_health_checkpoint(tmp_path,
                                                    trained_state):
    """A checkpoint written before the health guard existed (no
    ``TrainState.health`` subtree) must still auto-resume: the structure
    mismatch is NOT corruption — auto_resume retries against a
    health-less target and the trainer re-seeds the counters."""
    old_state = trained_state.replace(health=None)
    checkpoint.save_checkpoint(tmp_path, 4, old_state)
    target = jax.tree.map(np.zeros_like, trained_state)
    assert target.health is not None  # current-code skeleton HAS the leaf
    restored, epoch = checkpoint.auto_resume(tmp_path, 10, target)
    assert epoch == 4
    assert restored.health is None  # step_fn upgrades on first call
    np.testing.assert_array_equal(
        np.asarray(jax.tree.leaves(restored.params)[0]),
        np.asarray(jax.tree.leaves(old_state.params)[0]))


def test_preemption_guard_uninstall():
    """uninstall() restores the previously-installed handlers: no chained
    guard leaks across constructions (tests / long-lived drivers)."""
    import signal

    before = signal.getsignal(signal.SIGTERM)
    g1 = checkpoint.PreemptionGuard()
    assert signal.getsignal(signal.SIGTERM) == g1._handler
    g2 = checkpoint.PreemptionGuard()
    assert signal.getsignal(signal.SIGTERM) == g2._handler
    # un-nest in reverse order: each uninstall restores what it displaced
    g2.uninstall()
    assert signal.getsignal(signal.SIGTERM) == g1._handler
    g1.uninstall()
    assert signal.getsignal(signal.SIGTERM) == before
    # idempotent: a second uninstall is a no-op
    g1.uninstall()
    assert signal.getsignal(signal.SIGTERM) == before


def test_prune_checkpoints(tmp_path):
    """Retention keeps the N newest epochs, ignores orbax tmp dirs and
    foreign names, and is a no-op with keep=0/None."""
    import os

    from kfac_pytorch_tpu.utils.checkpoint import prune_checkpoints
    for e in (0, 1, 2, 10):
        os.makedirs(tmp_path / f'checkpoint-{e}')
    (tmp_path / 'checkpoint-11.orbax-checkpoint-tmp').mkdir()
    (tmp_path / 'other-file').write_text('x')
    prune_checkpoints(str(tmp_path), None)
    prune_checkpoints(str(tmp_path), 0)
    assert sorted(os.listdir(tmp_path)) == [
        'checkpoint-0', 'checkpoint-1', 'checkpoint-10', 'checkpoint-11'
        '.orbax-checkpoint-tmp', 'checkpoint-2', 'other-file']
    prune_checkpoints(str(tmp_path), 2)
    assert sorted(p for p in os.listdir(tmp_path)
                  if p.startswith('checkpoint-') and '.' not in p) == [
        'checkpoint-10', 'checkpoint-2']
    # tmp dir and foreign file untouched
    assert (tmp_path / 'checkpoint-11.orbax-checkpoint-tmp').exists()
    assert (tmp_path / 'other-file').exists()


# -- the durable checkpoint plane (manifests + object store) --------------

def test_save_commits_content_hash_manifest(tmp_path, trained_state):
    """Every successful save writes a manifest LAST: the content hashes
    of every blob, stamped with the world.json lineage when present."""
    import json

    checkpoint.write_world_stamp(tmp_path, 4, gen=2, lineage=1)
    checkpoint.save_checkpoint(tmp_path, 6, trained_state)
    manifest = json.loads(
        (tmp_path / 'checkpoint-6.manifest.json').read_text())
    assert manifest['epoch'] == 6 and manifest['blobs']
    assert manifest['num_devices'] == 4
    assert manifest['gen'] == 2 and manifest['lineage'] == 1
    from kfac_pytorch_tpu.store import PosixStore
    from kfac_pytorch_tpu.store.manifest import verify_epoch
    assert verify_epoch(PosixStore(str(tmp_path)), manifest) == []


def test_async_save_defers_manifest_until_durable(tmp_path,
                                                  trained_state):
    """block=False: the manifest (the commit point) must not exist
    before wait_for_checkpoints confirms the tree is durable."""
    if not checkpoint._HAS_ORBAX:
        pytest.skip('orbax not available')
    checkpoint.save_checkpoint(tmp_path, 1, trained_state, block=False)
    manifest = tmp_path / 'checkpoint-1.manifest.json'
    checkpoint.wait_for_checkpoints()
    assert manifest.exists()


def test_corrupt_manifested_epoch_scans_down(tmp_path, monkeypatch,
                                             caplog):
    """Bit-rot inside a COMMITTED epoch: the restore's hash check
    raises CheckpointCorruptError and auto_resume lands on the older
    committed epoch — the same length is the corruption shape only a
    content hash catches."""
    import logging

    monkeypatch.setattr(checkpoint, '_HAS_ORBAX', False)
    payload = {'w': np.arange(64, dtype=np.float32)}
    checkpoint.save_checkpoint(tmp_path, 0, payload)
    checkpoint.save_checkpoint(tmp_path, 1, payload)
    raw = bytearray((tmp_path / 'checkpoint-1.pkl').read_bytes())
    raw[-1] ^= 0xFF
    (tmp_path / 'checkpoint-1.pkl').write_bytes(bytes(raw))
    assert checkpoint.find_resume_epoch(tmp_path, 10) == 1
    with pytest.raises(checkpoint.CheckpointCorruptError):
        checkpoint.restore_checkpoint(tmp_path, 1, payload)
    with caplog.at_level(logging.WARNING):
        restored, epoch = checkpoint.auto_resume(tmp_path, 10, payload)
    assert epoch == 0
    np.testing.assert_array_equal(restored['w'], payload['w'])
    assert any('ckpt: corrupt blob key=checkpoint-1.pkl epoch=1 '
               'reason=hash_mismatch' in rec.getMessage()
               for rec in caplog.records)


def test_store_give_up_exits_rc_120(tmp_path, monkeypatch, caplog):
    """A dead object store is LOUD: save exits SystemExit(120)
    (RC_STORE_LOST), never a silent scan-down or a wedge."""
    import logging

    monkeypatch.setattr(checkpoint, '_HAS_ORBAX', False)
    monkeypatch.setenv('KFAC_STORE_BACKEND', 'http')
    monkeypatch.setenv('KFAC_STORE_ADDR', '127.0.0.1:1')
    with caplog.at_level(logging.ERROR):
        with pytest.raises(SystemExit) as exc:
            checkpoint.save_checkpoint(tmp_path, 0,
                                       {'w': np.zeros(8)})
    assert exc.value.code == 120
    assert any('checkpoint store lost' in rec.getMessage()
               and 'store_lost=1' in rec.getMessage()
               for rec in caplog.records)


def test_pickle_roundtrip_through_http_store(tmp_path, monkeypatch):
    """KFAC_STORE_BACKEND=http: the pickle save/resume path runs
    entirely against the object server — no checkpoint blobs or
    manifests on the local filesystem."""
    from kfac_pytorch_tpu.store import StoreHttpServer
    monkeypatch.setattr(checkpoint, '_HAS_ORBAX', False)
    srv = StoreHttpServer('127.0.0.1', 0).start()
    try:
        monkeypatch.setenv('KFAC_STORE_BACKEND', 'http')
        monkeypatch.setenv('KFAC_STORE_ADDR', srv.address)
        payload = {'w': np.arange(32, dtype=np.float32)}
        checkpoint.save_checkpoint(tmp_path, 2, payload)
        assert not (tmp_path / 'checkpoint-2.pkl').exists()
        assert checkpoint.find_resume_epoch(tmp_path, 10) == 2
        restored, epoch = checkpoint.auto_resume(tmp_path, 10, payload)
        assert epoch == 2
        np.testing.assert_array_equal(restored['w'], payload['w'])
        # retention applies to the remote copies too
        checkpoint.save_checkpoint(tmp_path, 3, payload)
        checkpoint.prune_checkpoints(str(tmp_path), 1)
        assert checkpoint.find_resume_epoch(tmp_path, 10) == 3
        assert checkpoint.auto_resume(tmp_path, 2, payload) == (None,
                                                                None)
    finally:
        srv.stop()
