"""Checkpoint / resume tests (reference semantics: rank-0 save of
{model, optimizer} (examples/utils.py:11-18), ImageNet auto-resume by
scanning checkpoint-{epoch} downward (pytorch_imagenet_resnet.py:162-167,
305-312); upgrade: K-FAC factor state round-trips too)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import kfac_pytorch_tpu as kfac
from kfac_pytorch_tpu import models, training
from kfac_pytorch_tpu.utils import checkpoint


@pytest.fixture(scope='module')
def trained_state():
    model = models.get_model('resnet20')
    precond = kfac.KFAC(variant='eigen_dp', lr=0.1, damping=0.003)
    tx = training.sgd(0.1, momentum=0.9)
    x = jnp.ones((4, 16, 16, 3), jnp.float32)
    state = training.init_train_state(model, tx, precond,
                                      jax.random.PRNGKey(0), x)

    def ce(outputs, b):
        return optax.softmax_cross_entropy_with_integer_labels(
            outputs, b['label']).mean()

    step = training.build_train_step(model, tx, precond, ce,
                                     extra_mutable=('batch_stats',))
    batch = {'input': x, 'label': jnp.asarray([0, 1, 2, 3])}
    state, _ = step(state, batch, lr=0.1, damping=0.003)
    return state


def test_save_restore_roundtrip(tmp_path, trained_state):
    checkpoint.save_checkpoint(tmp_path, 3, trained_state)
    target = jax.tree.map(np.zeros_like, trained_state)
    restored = checkpoint.restore_checkpoint(tmp_path, 3, target)
    for a, b in zip(jax.tree.leaves(trained_state),
                    jax.tree.leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_restore_without_kfac_state(tmp_path, trained_state):
    # reference behavior: K-FAC state NOT checkpointed; factors rebuild
    checkpoint.save_checkpoint(tmp_path, 1, trained_state,
                               include_kfac=False)
    target = jax.tree.map(np.zeros_like,
                          trained_state.replace(kfac_state=None))
    restored = checkpoint.restore_checkpoint(tmp_path, 1, target)
    np.testing.assert_array_equal(
        np.asarray(jax.tree.leaves(restored.params)[0]),
        np.asarray(jax.tree.leaves(trained_state.params)[0]))
    assert restored.kfac_state is None


def test_find_resume_epoch_scans_downward(tmp_path, trained_state):
    assert checkpoint.find_resume_epoch(tmp_path, 10) is None
    checkpoint.save_checkpoint(tmp_path, 2, trained_state)
    checkpoint.save_checkpoint(tmp_path, 5, trained_state)
    # scans from max_epoch downward and returns the newest present
    assert checkpoint.find_resume_epoch(tmp_path, 10) == 5
    assert checkpoint.find_resume_epoch(tmp_path, 4) == 2
