"""Harness-utility tests: LR schedules, losses, metrics, data pipeline
(reference surfaces: examples/utils.py:6-121, transformer/Optim.py:40-63)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kfac_pytorch_tpu import data
from kfac_pytorch_tpu.utils import losses, lr, metrics


# -- LR schedules -----------------------------------------------------------

def test_warmup_multistep_shape():
    sched = lr.warmup_multistep(0.1, steps_per_epoch=10, warmup_epochs=2,
                                decay_epochs=[5, 8], scale=4.0)
    # warmup starts near base_lr/scale and reaches base_lr*scale
    assert float(sched(0)) < 0.11
    np.testing.assert_allclose(float(sched(20)), 0.4, rtol=1e-6)
    # decays by 0.1 at epochs 5 and 8
    np.testing.assert_allclose(float(sched(51)), 0.04, rtol=1e-5)
    np.testing.assert_allclose(float(sched(81)), 0.004, rtol=1e-5)


def test_polynomial_decay_endpoints():
    sched = lr.polynomial_decay(1.0, total_steps=100, power=2.0,
                                warmup_steps=10)
    np.testing.assert_allclose(float(sched(5)), 0.5, rtol=1e-6)  # warmup
    np.testing.assert_allclose(float(sched(10)), 1.0, rtol=1e-6)
    assert float(sched(100)) < 1e-6                              # decayed out


def test_inverse_sqrt_peaks_at_warmup():
    sched = lr.inverse_sqrt(d_model=512, warmup_steps=100)
    vals = [float(sched(s)) for s in (1, 50, 100, 200, 1000)]
    assert np.argmax(vals) == 2                    # max exactly at warmup
    assert vals[-1] < vals[2]


def test_lr_schedules_traceable_under_jit():
    for sched in (lr.warmup_multistep(0.1, 10, 0, [5]),
                  lr.polynomial_decay(0.1, 100),
                  lr.inverse_sqrt(64)):
        out = jax.jit(sched)(jnp.int32(7))
        assert np.isfinite(float(out))


# -- losses -----------------------------------------------------------------

def test_label_smoothing_zero_equals_ce():
    logits = jnp.asarray(np.random.RandomState(0).randn(8, 10), jnp.float32)
    labels = jnp.arange(8) % 10
    ls = losses.label_smoothing_cross_entropy(logits, labels, smoothing=0.0)
    logp = jax.nn.log_softmax(logits)
    ce = -logp[jnp.arange(8), labels].mean()
    np.testing.assert_allclose(float(ls), float(ce), rtol=1e-6)


def test_label_smoothing_penalizes_overconfidence():
    confident = jnp.asarray([[20.0, -20.0]])
    labels = jnp.asarray([0])
    sm = losses.label_smoothing_cross_entropy(confident, labels,
                                              smoothing=0.1)
    hard = losses.label_smoothing_cross_entropy(confident, labels,
                                                smoothing=0.0)
    assert float(sm) > float(hard)


def test_sample_pseudo_labels_follows_distribution():
    logits = jnp.log(jnp.asarray([[0.99, 0.01]])).repeat(1000, axis=0)
    labs = losses.sample_pseudo_labels(jax.random.PRNGKey(0), logits)
    assert float((labs == 0).mean()) > 0.95


# -- metrics ----------------------------------------------------------------

def test_metric_weighted_average():
    m = metrics.Metric('loss')
    m.update(1.0, n=1)
    m.update(3.0, n=3)
    np.testing.assert_allclose(m.avg, 2.5)


def test_accuracy_and_topk():
    logits = jnp.asarray([[0.1, 0.9, 0.0], [0.8, 0.15, 0.1]])
    labels = jnp.asarray([1, 2])
    np.testing.assert_allclose(float(metrics.accuracy(logits, labels)), 0.5)
    np.testing.assert_allclose(
        float(metrics.topk_accuracy(logits, labels, k=2)), 0.5)
    np.testing.assert_allclose(
        float(metrics.topk_accuracy(logits, labels, k=3)), 1.0)


# -- data pipeline ----------------------------------------------------------

def test_synthetic_dataset_deterministic():
    x1, y1 = data.synthetic_classification(16, (8, 8, 3), 10, seed=1)
    x2, y2 = data.synthetic_classification(16, (8, 8, 3), 10, seed=1)
    np.testing.assert_array_equal(x1, x2)
    np.testing.assert_array_equal(y1, y2)
    assert y1.min() >= 0 and y1.max() < 10


def test_load_cifar10_standard_pickle_format(tmp_path):
    """Format-compatibility regression: load_cifar10 must read the exact
    cifar-10-batches-py layout torchvision writes (CHW uint8 rows,
    bytes-keyed dicts, 5 train batches + test_batch) and produce NHWC
    uint8 that the Loader then normalizes."""
    import pickle
    base = tmp_path / 'cifar-10-batches-py'
    base.mkdir()

    def blob(n, seed):
        r = np.random.RandomState(seed)
        return {b'data': r.randint(0, 256, (n, 3072), dtype=np.uint8),
                b'labels': r.randint(0, 10, n).tolist()}

    for i in range(1, 6):
        with open(base / f'data_batch_{i}', 'wb') as f:
            pickle.dump(blob(20, i), f)
    with open(base / 'test_batch', 'wb') as f:
        pickle.dump(blob(12, 9), f)

    (xtr, ytr), (xte, yte) = data.load_cifar10(str(tmp_path))
    assert xtr.shape == (100, 32, 32, 3) and xtr.dtype == np.uint8
    assert xte.shape == (12, 32, 32, 3) and ytr.shape == (100,)
    # CHW->HWC transpose correctness: channel 0 of image 0 must equal the
    # first 1024 bytes of its row
    with open(base / 'data_batch_1', 'rb') as f:
        raw = pickle.load(f, encoding='bytes')[b'data'][0]
    np.testing.assert_array_equal(xtr[0, :, :, 0].ravel(), raw[:1024])
    # Loader normalizes uint8 inputs to float32 CIFAR statistics
    loader = data.Loader(xtr, ytr, batch_size=10, train=False)
    b = next(loader.epoch())
    assert b['input'].dtype == np.float32
    assert abs(float(b['input'].mean())) < 1.0  # roughly standardized


def test_loader_shards_cover_dataset():
    x, y = data.synthetic_classification(32, (4, 4, 3), 10, seed=0)
    loader = data.Loader(x, y, batch_size=8, train=False)
    batches = list(loader.epoch())
    assert sum(b['input'].shape[0] for b in batches) == 32


def test_loader_process_shards_are_disjoint_and_cover():
    x, y = data.synthetic_classification(32, (2, 2, 3), 10, seed=0)
    seen = []
    for i in range(4):  # 4 simulated processes, same seed
        loader = data.Loader(x, y, batch_size=4, train=True, seed=7,
                             shard=(i, 4))
        assert loader.steps_per_epoch == 2  # 32 / (4 * 4)
        for b in loader.epoch():
            seen.extend(np.asarray(b['label']).tolist())
    assert len(seen) == 32  # disjoint shards, full coverage
    ref = data.Loader(x, y, batch_size=16, train=True, seed=7,
                      shard=(0, 1))
    ref_labels = [l for b in ref.epoch()
                  for l in np.asarray(b['label']).tolist()]
    assert sorted(seen) == sorted(ref_labels)


def test_metric_sync_single_process_noop():
    m = metrics.Metric('loss')
    m.update(2.0, n=4)
    m.sync()
    np.testing.assert_allclose(m.avg, 2.0)


def test_augment_preserves_shape_and_range():
    rng = np.random.RandomState(0)
    x = rng.rand(4, 32, 32, 3).astype(np.float32)
    out = data.augment_cifar(rng, x)
    assert out.shape == x.shape
    assert np.isfinite(out).all()


def test_summary_writer_tensorboard_roundtrip(tmp_path):
    """Native event files must load in stock TensorBoard (scalars arrive
    as migrated tensor values)."""
    pytest.importorskip('tensorboard')
    from kfac_pytorch_tpu.utils.summary import SummaryWriter
    w = SummaryWriter(str(tmp_path))
    w.add_scalar('train/loss', 2.5, 0)
    w.add_scalar('val/accuracy', 0.875, 7)
    w.close()
    from tensorboard.backend.event_processing import event_file_loader
    import glob as _glob
    f = _glob.glob(str(tmp_path) + '/events.out.tfevents.*')[0]
    got = []
    for e in event_file_loader.EventFileLoader(f).Load():
        for v in e.summary.value:
            got.append((e.step, v.tag, float(v.tensor.float_val[0])))
    assert got == [(0, 'train/loss', 2.5), (7, 'val/accuracy', 0.875)], got


def test_summary_native_reader_roundtrip(tmp_path):
    """read_scalars is the writer's exact inverse (no tensorboard
    install needed): every series comes back tagged, stepped and in
    order — the basis for scripts/plot_digits_ab.py's TB-scalar plots."""
    from kfac_pytorch_tpu.utils.summary import SummaryWriter, read_scalars
    w = SummaryWriter(str(tmp_path))
    for e in range(3):
        w.add_scalar('train/loss', 2.5 - e, e)
        w.add_scalar('val/accuracy', 0.5 + 0.1 * e, e)
    w.add_scalar('train/lr', 0.1, 99)
    w.close()
    got = read_scalars(str(tmp_path))
    assert got['train/loss'] == [(0, 2.5), (1, 1.5), (2, 0.5)]
    assert got['val/accuracy'] == [(0, 0.5), (1, pytest.approx(0.6)),
                                   (2, pytest.approx(0.7))]
    assert got['train/lr'] == [(99, pytest.approx(0.1))]

    # a truncated tail (live writer mid-record / killed run) must skip
    # the partial record, not crash the whole read
    import glob as _glob
    f = _glob.glob(str(tmp_path) + '/events.out.tfevents.*')[0]
    data = open(f, 'rb').read()
    open(f, 'wb').write(data[:-7])
    trunc = read_scalars(str(tmp_path))
    assert trunc['train/loss'] == got['train/loss']
    assert trunc.get('train/lr', []) == []  # clipped final record dropped


def test_setup_run_logging_rank0_only_file(tmp_path, monkeypatch):
    """Process 0 gets the per-run file; peer processes stream only — on a
    shared filesystem their identical timestamp suffix would otherwise
    truncate each other's file (mode='w')."""
    import logging as _logging
    from kfac_pytorch_tpu.utils.runlog import setup_run_logging

    log, path = setup_run_logging(str(tmp_path), 'run', 'a', None, 'bs8',
                                  process_id=0)
    log.info('hello from rank 0')
    assert path is not None and path.endswith('.log')
    for h in _logging.getLogger().handlers:
        h.flush()
    assert 'hello from rank 0' in open(path).read()
    assert 'run_a_bs8' in os.path.basename(path)  # None part dropped

    _, peer_path = setup_run_logging(str(tmp_path), 'run', 'a', 'bs8',
                                     process_id=1)
    assert peer_path is None
    assert not any(isinstance(h, _logging.FileHandler)
                   for h in _logging.getLogger().handlers)

    # launcher-exported rank is picked up from the environment
    monkeypatch.setenv('JAX_PROCESS_ID', '3')
    _, env_path = setup_run_logging(str(tmp_path), 'run', 'b')
    assert env_path is None
    _logging.basicConfig(force=True)  # restore for later tests


def test_loader_prefetch_identical_and_propagates():
    """Prefetched epochs must yield byte-identical batch sequences to the
    synchronous path (the producer just runs ahead), and producer
    exceptions must surface at the consuming site."""
    x = (np.arange(64 * 8 * 8 * 3) % 255).reshape(64, 8, 8, 3) \
        .astype(np.uint8)
    y = np.arange(64) % 10

    a = data.Loader(x, y, 16, train=True, seed=3, shard=(0, 1))
    b = data.Loader(x, y, 16, train=True, seed=3, shard=(0, 1))
    for ba, bb in zip(a.epoch(prefetch_depth=0), b.epoch(prefetch_depth=2)):
        np.testing.assert_array_equal(ba['input'], bb['input'])
        np.testing.assert_array_equal(ba['label'], bb['label'])

    def boom():
        yield {'input': 1}
        raise RuntimeError('producer failed')

    it = data.prefetch(boom(), depth=2)
    assert next(it) == {'input': 1}
    with pytest.raises(RuntimeError, match='producer failed'):
        next(it)

    # abandoning mid-epoch must not perturb later epochs (per-epoch child
    # RNG) and must release the producer thread (stop-aware puts)
    import threading as _threading
    c = data.Loader(x, y, 16, train=True, seed=3, shard=(0, 1))
    d = data.Loader(x, y, 16, train=True, seed=3, shard=(0, 1))
    next(c.epoch(prefetch_depth=2))  # abandon after one batch
    for _ in d.epoch(prefetch_depth=0):
        pass
    for bc, bd in zip(c.epoch(prefetch_depth=2), d.epoch(prefetch_depth=0)):
        np.testing.assert_array_equal(bc['input'], bd['input'])
    import gc, time as _time
    gc.collect()  # drop the abandoned generator -> its finally fires
    _time.sleep(0.5)
    leaked = [t for t in _threading.enumerate()
              if t.daemon and 'prefetch' in repr(t.name).lower()]
    assert not leaked, leaked

    # explicit close(): deterministic producer release without relying on
    # refcounting (ADVICE r2) — also usable as a context manager
    e = data.Loader(x, y, 16, train=True, seed=3, shard=(0, 1))
    it = e.epoch(prefetch_depth=2)
    next(it)
    it.close()
    _time.sleep(0.3)
    leaked = [t for t in _threading.enumerate()
              if t.daemon and 'prefetch' in repr(t.name).lower()]
    assert not leaked, leaked
    with e.epoch(prefetch_depth=2) as it2:
        next(it2)
    _time.sleep(0.3)
    leaked = [t for t in _threading.enumerate()
              if t.daemon and 'prefetch' in repr(t.name).lower()]
    assert not leaked, leaked


def test_parse_logs_all_speed_formats(tmp_path):
    """scripts/parse_logs.py must recognize every trainer's SPEED line
    (cifar 'iter time .. imgs/sec', imagenet 'iter .. imgs/s',
    longcontext 'iter time .. tokens/sec') and the epoch metric lines."""
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..'))
    from scripts import parse_logs

    cases = {
        'cifar.log': ('x SPEED: iter time 0.4489 +- 0.0841 s '
                      '(imgs/sec 17.8)', (0.4489, 0.0841, 17.8, 'imgs/s')),
        'imagenet.log': ('x SPEED: iter 0.9580 +- 0.0751 s (8.4 imgs/s)',
                         (0.958, 0.0751, 8.4, 'imgs/s')),
        'longctx.log': ('x SPEED: iter time 0.0129 +- 0.0004 s '
                        '(tokens/sec 39791.8)',
                        (0.0129, 0.0004, 39791.8, 'tok/s')),
    }
    for name, (line, want) in cases.items():
        p = tmp_path / name
        p.write_text(line + '\n2026-01-01 epoch 0: train_loss 1.0 '
                     'val_loss 1.0 val_acc 0.5 (10.0s)\n')
        r = parse_logs.parse(str(p))
        assert r['speed'] == want, (name, r['speed'])
        assert r['epochs'], name
