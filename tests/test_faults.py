"""Deterministic fault-injection harness drills (faults.py).

Each drill arms an env-configured fault, runs the real trainer/checkpoint
path, and asserts the matching guard absorbs it: eigh blowup -> last-good
/identity decomposition fallback, corrupted factor block -> identity
re-init heal, SIGTERM -> PreemptionGuard flag, truncated/failed
checkpoint writes -> atomic save + scan-downward auto_resume.
"""

import os
import pickle
import signal

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import kfac_pytorch_tpu as kfac
from kfac_pytorch_tpu import faults, training
from kfac_pytorch_tpu.utils import checkpoint

from tests.helpers import TinyCNN


def _batches(n_batches, n=8, hw=8, seed=0):
    rng = np.random.RandomState(seed)
    return [{'input': jnp.asarray(rng.randn(n, hw, hw, 3), jnp.float32),
             'label': jnp.asarray(rng.randint(0, 10, n))}
            for _ in range(n_batches)]


def _ce(outputs, batch):
    return optax.softmax_cross_entropy_with_integer_labels(
        outputs, batch['label']).mean()


def _build(batches, variant='eigen_dp'):
    model = TinyCNN()
    precond = kfac.KFAC(variant=variant, lr=0.05, damping=0.003,
                        fac_update_freq=1, kfac_update_freq=1,
                        num_devices=1, axis_name=None)
    tx = training.sgd(0.05, momentum=0.9)
    state = training.init_train_state(model, tx, precond,
                                      jax.random.PRNGKey(0),
                                      batches[0]['input'])
    step = training.build_train_step(model, tx, precond, _ce)
    return state, step


def _all_finite(tree):
    return all(np.all(np.isfinite(np.asarray(leaf)))
               for leaf in jax.tree.leaves(tree))


def test_parse_steps():
    assert faults.parse_steps(None) == ()
    assert faults.parse_steps('') == ()
    assert faults.parse_steps('7') == (7,)
    assert faults.parse_steps('3,5,9') == (3, 5, 9)
    assert faults.parse_steps('4:8') == (4, 5, 6, 7)
    assert faults.parse_steps('1, 3:5,3') == (1, 3, 4)


def test_from_env_validation(monkeypatch):
    monkeypatch.setenv(faults.ENV_CKPT, 'bogus')
    with pytest.raises(ValueError):
        faults.from_env()
    monkeypatch.setenv(faults.ENV_CKPT, 'truncate')
    assert faults.from_env().ckpt_mode == 'truncate'
    monkeypatch.setenv(faults.ENV_CKPT, 'eio_once')
    assert faults.from_env().ckpt_mode == 'eio_once'
    monkeypatch.delenv(faults.ENV_CKPT)
    assert faults.from_env() == faults.FaultConfig()
    assert not faults.from_env().any_injit


def test_from_env_rejects_unknown_fault_vars(monkeypatch):
    """A typo'd drill variable must fail the build loudly — a chaos test
    whose fault never armed would otherwise pass vacuously."""
    monkeypatch.setenv('KFAC_FAULT_NAN_GRAD_STEPS', '3')  # plural typo
    with pytest.raises(ValueError, match='NAN_GRAD_STEPS'):
        faults.from_env()


def test_from_env_rejects_malformed_specs(monkeypatch):
    monkeypatch.setenv(faults.ENV_EIGH, '3:x')
    with pytest.raises(ValueError, match='malformed step spec'):
        faults.from_env()
    monkeypatch.delenv(faults.ENV_EIGH)
    monkeypatch.setenv(faults.ENV_HANG, 'seven')
    with pytest.raises(ValueError, match=faults.ENV_HANG):
        faults.from_env()
    monkeypatch.delenv(faults.ENV_HANG)
    monkeypatch.setenv(faults.ENV_SLOW_SECS, 'fast')
    with pytest.raises(ValueError, match=faults.ENV_SLOW_SECS):
        faults.from_env()
    monkeypatch.delenv(faults.ENV_SLOW_SECS)
    monkeypatch.setenv(faults.ENV_CRASH_MODE, 'sigsegv')
    with pytest.raises(ValueError, match=faults.ENV_CRASH_MODE):
        faults.from_env()


def test_maybe_slow_uses_injected_sleep(monkeypatch):
    monkeypatch.setenv(faults.ENV_SLOW, '2,4')
    monkeypatch.setenv(faults.ENV_SLOW_SECS, '3.5')
    cfg = faults.from_env()
    slept = []
    for s in range(6):
        faults.maybe_slow(cfg, s, sleep=slept.append)
    assert slept == [3.5, 3.5]


def test_once_dir_latch_fires_exactly_once_across_processes(tmp_path,
                                                            monkeypatch):
    """The cross-RESTART latch: the first claimant wins, every later
    claim (same step, e.g. a supervised relaunch replaying the faulted
    epoch) is refused — this is what makes the supervisor chaos drills
    terminate."""
    monkeypatch.setenv(faults.ENV_ONCE_DIR, str(tmp_path))
    assert faults._claim_once('crash-5')
    assert not faults._claim_once('crash-5')
    assert faults._claim_once('hang-5')  # distinct fault, own token
    monkeypatch.delenv(faults.ENV_ONCE_DIR)
    # without the dir the latch always fires
    assert faults._claim_once('crash-5')


def test_eigh_blowup_falls_back_to_identity_then_recovers(monkeypatch):
    """Non-finite decomposition output on the COLD first inverse update:
    the guard substitutes the identity (plain pass-through), the stored
    state stays finite, and the next (unfaulted) decomposition recovers a
    real eigenbasis."""
    monkeypatch.setenv(faults.ENV_EIGH, '0')
    batches = _batches(3, seed=5)
    state, step = _build(batches)
    rungs = []
    for b in batches:
        state, m = step(state, b, lr=0.05, damping=0.003)
        rungs.append(float(m['health/rung']))
        assert np.isfinite(float(m['loss']))
        assert _all_finite(state.kfac_state.decomp)
        assert _all_finite(state.params)
    # the blowup was absorbed in-engine: the batch itself stayed applied
    # and never counted against the trainer-level ladder
    assert float(m['health/skipped']) == 0
    assert rungs == [0.0, 0.0, 0.0]
    # step 0's guarded decomposition is the identity basis; step 1's is a
    # real eigh again (eigenvectors differ from the identity)
    evecs = np.asarray(next(iter(state.kfac_state.decomp['evecs'].values())))
    eye = np.eye(evecs.shape[-1])
    assert not np.allclose(evecs[0], eye)


def test_eigh_blowup_warm_keeps_last_good(monkeypatch):
    """An eigh blowup AFTER a good decomposition exists keeps the last
    good one bit-exactly (not the identity)."""
    monkeypatch.setenv(faults.ENV_EIGH, '1')
    batches = _batches(3, seed=6)
    state, step = _build(batches)
    state, _ = step(state, batches[0], lr=0.05, damping=0.003)
    good = jax.tree.map(np.asarray, state.kfac_state.decomp)
    state, m = step(state, batches[1], lr=0.05, damping=0.003)
    for k in good['evecs']:
        np.testing.assert_array_equal(
            np.asarray(state.kfac_state.decomp['evecs'][k]),
            good['evecs'][k])
    assert np.isfinite(float(m['loss']))
    state, _ = step(state, batches[2], lr=0.05, damping=0.003)
    assert _all_finite(state.kfac_state.decomp)


def test_factor_corruption_heals_by_identity_reinit(monkeypatch):
    """Silent-data-corruption drill: a stored factor block corrupted at
    step 1 (post-guard, exactly as a flipped bit would land) is detected
    at step 2's factor update and re-initialized to the identity; the
    decomposition guard bridges the corrupted step."""
    monkeypatch.setenv(faults.ENV_FACTOR, '1')
    batches = _batches(4, seed=7)
    state, step = _build(batches)
    state, _ = step(state, batches[0], lr=0.05, damping=0.003)
    state, m1 = step(state, batches[1], lr=0.05, damping=0.003)
    # corruption landed in the stored factors...
    assert not _all_finite(state.kfac_state.factors)
    # ...but never reached the decomposition or the params
    assert _all_finite(state.kfac_state.decomp)
    assert _all_finite(state.params)
    assert np.isfinite(float(m1['loss']))
    # next factor update heals: corrupted rows re-init to identity
    state, m2 = step(state, batches[2], lr=0.05, damping=0.003)
    assert _all_finite(state.kfac_state.factors)
    state, m3 = step(state, batches[3], lr=0.05, damping=0.003)
    assert _all_finite(state.params) and np.isfinite(float(m3['loss']))


def test_sigterm_fault_trips_preemption_guard(monkeypatch):
    """Host-side SIGTERM at step 1: PreemptionGuard converts it into the
    cooperative stop flag; the one-shot latch fires exactly once."""
    monkeypatch.setenv(faults.ENV_SIGTERM, '1')
    faults.reset_sigterm_fault()
    guard = checkpoint.PreemptionGuard()
    try:
        batches = _batches(3, seed=8)
        state, step = _build(batches)
        state, _ = step(state, batches[0], lr=0.05, damping=0.003)
        assert not guard.triggered
        state, _ = step(state, batches[1], lr=0.05, damping=0.003)
        assert guard.triggered
        # one-shot: replaying the fault step doesn't re-deliver
        guard._flag = False
        faults.maybe_sigterm(faults.from_env(), 1)
        assert not guard.triggered
    finally:
        guard.uninstall()
        faults.reset_sigterm_fault()


def test_checkpoint_truncate_then_auto_resume_falls_back(tmp_path,
                                                         monkeypatch):
    """'truncate' drill: a torn object under the FINAL name, with no
    manifest. The manifest-aware resume scan now refuses the epoch
    outright (it used to select it and rely on auto_resume crashing into
    the truncation), and auto_resume lands on the older committed one."""
    monkeypatch.setattr(checkpoint, '_HAS_ORBAX', False)
    payload = {'w': np.arange(1000, dtype=np.float32), 'epoch': np.int32(0)}
    checkpoint.save_checkpoint(tmp_path, 0, payload)
    monkeypatch.setenv(faults.ENV_CKPT, 'truncate')
    checkpoint.save_checkpoint(tmp_path, 1, {'w': np.ones(1000)})
    monkeypatch.delenv(faults.ENV_CKPT)
    assert (tmp_path / 'checkpoint-1.pkl').exists()
    assert not (tmp_path / 'checkpoint-1.manifest.json').exists()
    with pytest.raises(Exception):
        checkpoint.restore_checkpoint(tmp_path, 1, payload)
    # the torn epoch is skipped without ever being read
    assert checkpoint.find_resume_epoch(tmp_path, 10) == 0
    restored, epoch = checkpoint.auto_resume(tmp_path, 10, payload)
    assert epoch == 0
    np.testing.assert_array_equal(restored['w'], payload['w'])


def test_checkpoint_fail_leaves_no_final_file(tmp_path, monkeypatch):
    """'fail' drill: the write dies after a partial tmp file — the atomic
    path must leave no final file behind, so resume never sees it."""
    monkeypatch.setattr(checkpoint, '_HAS_ORBAX', False)
    monkeypatch.setenv(faults.ENV_CKPT, 'fail')
    with pytest.raises(OSError):
        checkpoint.save_checkpoint(tmp_path, 3, {'w': np.zeros(100)})
    assert not (tmp_path / 'checkpoint-3.pkl').exists()
    assert (tmp_path / 'checkpoint-3.pkl.tmp').exists()
    # the partial tmp is invisible to resume scanning and pruning
    assert checkpoint.find_resume_epoch(tmp_path, 10) is None
    monkeypatch.delenv(faults.ENV_CKPT)
    checkpoint.save_checkpoint(tmp_path, 3, {'w': np.zeros(100)})
    assert (tmp_path / 'checkpoint-3.pkl').exists()
    assert checkpoint.find_resume_epoch(tmp_path, 10) == 3


def test_auto_resume_nothing_restorable(tmp_path, monkeypatch):
    monkeypatch.setattr(checkpoint, '_HAS_ORBAX', False)
    state, epoch = checkpoint.auto_resume(tmp_path, 10, None)
    assert state is None and epoch is None
    # ALL checkpoints corrupt -> still (None, None), not a crash
    for e in (0, 2):
        (tmp_path / f'checkpoint-{e}.pkl').write_bytes(b'garbage')
    state, epoch = checkpoint.auto_resume(tmp_path, 10, None)
    assert state is None and epoch is None
