"""KFACParamScheduler parity tests (reference semantics:
kfac_preconditioner_base.py:233-301 — multiplicative decay of damping and
update frequencies at listed epochs, with start-epoch fast-forward for
checkpoint resume, pytorch_imagenet_resnet.py:281-287)."""

import kfac_pytorch_tpu as kfac
from kfac_pytorch_tpu import KFACParamScheduler


def _precond(damping=0.03, fac=1, freq=10):
    return kfac.KFAC(variant='eigen_dp', damping=damping,
                     fac_update_freq=fac, kfac_update_freq=freq)


def test_damping_decays_at_schedule_epochs():
    p = _precond(damping=0.03)
    s = KFACParamScheduler(p, damping_alpha=0.5, damping_schedule=[2, 4])
    assert p.damping == 0.03
    s.step(1)
    assert p.damping == 0.03
    s.step(2)
    assert abs(p.damping - 0.015) < 1e-12
    s.step(4)
    assert abs(p.damping - 0.0075) < 1e-12
    # moving past the last boundary does not decay again
    s.step(9)
    assert abs(p.damping - 0.0075) < 1e-12


def test_update_freq_growth_and_floor():
    p = _precond(fac=1, freq=10)
    s = KFACParamScheduler(p, update_freq_alpha=10,
                           update_freq_schedule=[3])
    s.step(3)
    assert p.fac_update_freq == 10
    assert p.kfac_update_freq == 100
    # shrinking alpha floors at 1
    p2 = _precond(fac=1, freq=2)
    s2 = KFACParamScheduler(p2, update_freq_alpha=0.1,
                            update_freq_schedule=[0])
    s2.step(0)
    assert p2.fac_update_freq == 1
    assert p2.kfac_update_freq == 1


def test_start_epoch_fast_forward_matches_stepping():
    a = _precond(damping=0.03)
    KFACParamScheduler(a, damping_alpha=0.5, damping_schedule=[1, 2],
                       start_epoch=5)
    b = _precond(damping=0.03)
    sb = KFACParamScheduler(b, damping_alpha=0.5, damping_schedule=[1, 2])
    for e in range(1, 6):
        sb.step(e)
    assert abs(a.damping - b.damping) < 1e-12


def test_step_without_arg_advances_by_one():
    p = _precond(damping=0.04)
    s = KFACParamScheduler(p, damping_alpha=0.5, damping_schedule=[1])
    s.step()
    assert s.epoch == 1
    assert abs(p.damping - 0.02) < 1e-12
