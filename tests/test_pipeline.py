"""GPipe pipeline (parallel/pipeline.py) on the CPU mesh: the pipelined
model must equal the sequential composition exactly — outputs, loss, and
every stage's parameter gradients (the backward schedule is autodiff
through the ppermuted forward scan, so this pins that whole mechanism)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen
from jax.sharding import Mesh, PartitionSpec as P

from kfac_pytorch_tpu import nn as knn
from kfac_pytorch_tpu.parallel.pipeline import gpipe

S, M, B, D = 4, 8, 16, 12   # stages, microbatches, batch, width


class Stage(linen.Module):
    """One homogeneous pipeline stage: Dense + gelu (width-preserving)."""
    @linen.compact
    def __call__(self, h):
        return jax.nn.gelu(knn.Dense(D, name='fc')(h))


def _params(seed):
    rng = np.random.RandomState(seed)
    return {'fc': {'kernel': jnp.asarray(rng.randn(D, D) * 0.4,
                                         jnp.float32),
                   'bias': jnp.asarray(rng.randn(D) * 0.1, jnp.float32)}}


def test_gpipe_matches_sequential():
    x = jnp.asarray(np.random.RandomState(0).randn(B, D), jnp.float32)
    y = jnp.asarray(np.random.RandomState(1).randn(B, D), jnp.float32)
    stage = Stage()
    stacked = jax.tree.map(lambda *a: jnp.stack(a),
                           *[_params(i) for i in range(S)])
    mesh = Mesh(np.array(jax.devices()[:S]), ('pipe',))

    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(jax.tree.map(lambda _: P('pipe'), stacked), P(), P()),
        out_specs=(P(), jax.tree.map(lambda _: P('pipe'), stacked)))
    def piped(params_stacked, x, y):
        params = jax.tree.map(lambda a: a[0], params_stacked)

        def loss_fn(p):
            out = gpipe(lambda pp, h: stage.apply({'params': pp}, h),
                        p, x, M, 'pipe')
            # outputs are valid on the LAST stage only (zeros elsewhere):
            # the loss must be computed there alone, then psum-replicated
            err = ((out - y) ** 2).mean()
            err = jnp.where(jax.lax.axis_index('pipe') == S - 1, err, 0.0)
            return jax.lax.psum(err, 'pipe')

        loss, grads = jax.value_and_grad(loss_fn)(params)
        return loss, jax.tree.map(lambda a: a[None], grads)

    loss_p, grads_p = piped(stacked, x, y)

    def seq_loss(params_stacked):
        h = x
        for i in range(S):
            p = jax.tree.map(lambda a: a[i], params_stacked)
            h = stage.apply({'params': p}, h)
        return ((h - y) ** 2).mean()

    loss_s, grads_s = jax.value_and_grad(seq_loss)(stacked)
    np.testing.assert_allclose(float(loss_p), float(loss_s), rtol=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6),
        grads_p, grads_s)


def test_gpipe_bf16_stage():
    """A bf16 stage (bench-model dtype) must pipeline: the scan carry is
    resolved to the stage OUTPUT dtype (ADVICE r3 — the old f32 zero-sum
    carry mismatched lax.scan's carry type), and the result must track
    the f32 sequential composition to bf16 accuracy."""
    x = jnp.asarray(np.random.RandomState(3).randn(B, D), jnp.float32)
    stage = Stage()
    stacked = jax.tree.map(lambda *a: jnp.stack(a),
                           *[_params(20 + i) for i in range(S)])
    stacked_bf = jax.tree.map(lambda a: a.astype(jnp.bfloat16), stacked)
    mesh = Mesh(np.array(jax.devices()[:S]), ('pipe',))

    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(jax.tree.map(lambda _: P('pipe'), stacked_bf), P()),
        out_specs=P())
    def piped(params_stacked, x):
        params = jax.tree.map(lambda a: a[0], params_stacked)
        out = gpipe(lambda pp, h: stage.apply({'params': pp}, h),
                    params, x, M, 'pipe')
        return jax.lax.psum(out, 'pipe')

    out = piped(stacked_bf, x.astype(jnp.bfloat16))
    assert out.dtype == jnp.bfloat16, out.dtype
    h = x
    for i in range(S):
        p = jax.tree.map(lambda a: a[i], stacked)
        h = stage.apply({'params': p}, h)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(h), rtol=0.1, atol=0.1)


def test_gpipe_single_microbatch_and_order():
    """M=1 (pure model parallelism, maximal bubble) still matches, and
    outputs come back in input order for M > 1."""
    x = jnp.asarray(np.random.RandomState(2).randn(B, D), jnp.float32)
    stage = Stage()
    stacked = jax.tree.map(lambda *a: jnp.stack(a),
                           *[_params(10 + i) for i in range(S)])
    mesh = Mesh(np.array(jax.devices()[:S]), ('pipe',))

    def run(m):
        @functools.partial(
            jax.shard_map, mesh=mesh,
            in_specs=(jax.tree.map(lambda _: P('pipe'), stacked), P()),
            out_specs=P())
        def piped(params_stacked, x):
            params = jax.tree.map(lambda a: a[0], params_stacked)
            out = gpipe(lambda pp, h: stage.apply({'params': pp}, h),
                        params, x, m, 'pipe')
            return jax.lax.psum(out, 'pipe')  # valid only on last stage
        return piped(stacked, x)

    h = x
    for i in range(S):
        p = jax.tree.map(lambda a: a[i], stacked)
        h = stage.apply({'params': p}, h)
    for m in (1, 2, 8):
        np.testing.assert_allclose(np.asarray(run(m)), np.asarray(h),
                                   rtol=1e-5, atol=1e-6)
