"""Test config: force an 8-device virtual CPU mesh before JAX initializes.

The reference has no cluster-free multi-node test path (SURVEY.md §4); here
every distributed code path runs on a simulated mesh
(--xla_force_host_platform_device_count), the JAX-native equivalent.
"""

import os

# XLA_FLAGS is read when the CPU client initializes (lazily), so setting it
# here is early enough; JAX_PLATFORMS is captured at jax import time (which
# already happened in sitecustomize), so the platform must go through
# jax.config instead.
os.environ['XLA_FLAGS'] = (
    os.environ.get('XLA_FLAGS', '')
    + ' --xla_force_host_platform_device_count=8')

try:
    import jax  # noqa: E402
except ModuleNotFoundError:
    # jax-less CI lanes (the fleet-sim job) run only the stdlib suites
    # (tests/test_sim.py, tests/test_lint.py); any jax-dependent test
    # module still fails loudly at its own import.
    jax = None

if jax is not None:
    jax.config.update('jax_platforms', 'cpu')
    # fp32 matmuls in tests: exact math, not MXU bf16 passthrough.
    jax.config.update('jax_default_matmul_precision', 'highest')


def pytest_configure(config):
    config.addinivalue_line(
        'markers', 'slow: multi-minute end-to-end drills (subprocess '
        "trainers etc.); deselect with -m 'not slow'")
    config.addinivalue_line(
        'markers', 'core: ~1-minute core subset (golden torch-reference '
        'parity, engine/preconditioner, factors/linalg, loss-convention '
        "guard); run with -m core (VERDICT r3 #9)")
    config.addinivalue_line(
        'markers', 'nightly: opt-in 20-40-epoch CPU training gates '
        '(VERDICT r4 weak #6) — skipped unless the -m expression names '
        "nightly or KFAC_NIGHTLY=1; run with -m nightly")


def pytest_collection_modifyitems(config, items):
    # nightly is OPT-IN: multi-10-minute CPU trainings must not ride
    # along with -m slow (the CI chaos job) or a bare pytest run. They
    # run only when explicitly selected: '-m nightly' (or any -m
    # expression mentioning it), or KFAC_NIGHTLY=1 for driver scripts
    # that cannot pass marker expressions.
    import pytest as _pytest
    if 'nightly' in (config.option.markexpr or '') \
            or os.environ.get('KFAC_NIGHTLY'):
        return
    skip = _pytest.mark.skip(
        reason='nightly tier: run with -m nightly (or KFAC_NIGHTLY=1)')
    for item in items:
        if 'nightly' in item.keywords:
            item.add_marker(skip)
