"""Resilient-runtime unit drills (kfac_pytorch_tpu/resilience/).

Everything here is wall-clock-free or sub-second: retry/backoff under a
ManualClock, the watchdog with an injected expiry action, the straggler
governor driven by the deterministic slow-step fault, the supervisor
restart loop on trivial children, and the transient-checkpoint /
next-batch retry paths. The multi-minute subprocess drills (real
SIGKILL, real hang) live in tests/test_chaos.py behind ``-m slow``.
"""

import os
import random
import signal
import sys

import jax
import numpy as np
import optax
import pytest

import kfac_pytorch_tpu as kfac
from kfac_pytorch_tpu import data as kdata
from kfac_pytorch_tpu import faults, resilience, training
from kfac_pytorch_tpu.resilience import retry as retry_mod
from kfac_pytorch_tpu.resilience.retry import ManualClock, RetryPolicy
from kfac_pytorch_tpu.resilience.straggler import StragglerGovernor
from kfac_pytorch_tpu.resilience.supervisor import Supervisor
from kfac_pytorch_tpu.resilience.watchdog import RC_HANG, StepWatchdog
from kfac_pytorch_tpu.utils import checkpoint, runlog

from tests.helpers import TinyCNN


@pytest.fixture(autouse=True)
def _reset_counters():
    resilience.counters.reset()
    yield
    resilience.counters.reset()


# ---------------------------------------------------------------------------
# retry: attempts, jitter bounds, deadline — all on the fake clock
# ---------------------------------------------------------------------------

def test_retry_attempt_count_and_jitter_bounds():
    clock = ManualClock()
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 4:
            raise OSError('transient')
        return 'ok'

    pol = RetryPolicy(attempts=5, base_delay=1.0, multiplier=2.0,
                      jitter=0.5, max_delay=100.0)
    out = retry_mod.call_with_retry(flaky, policy=pol, clock=clock,
                                    rng=random.Random(0))
    assert out == 'ok'
    assert len(calls) == 4          # 3 failures + 1 success
    assert len(clock.sleeps) == 3   # one backoff per retry
    for k, s in enumerate(clock.sleeps):
        nominal = 1.0 * 2.0 ** k
        assert nominal * 0.5 <= s <= nominal * 1.5, (k, s)
    assert resilience.counters.get('io_retries') == 3


def test_retry_exhaustion_reraises_last_exception():
    clock = ManualClock()

    def always():
        raise OSError('persistent')

    with pytest.raises(OSError, match='persistent'):
        retry_mod.call_with_retry(
            always, policy=RetryPolicy(attempts=3, base_delay=0.1),
            clock=clock, rng=random.Random(0))
    assert len(clock.sleeps) == 2  # no sleep after the final attempt


def test_retry_non_retryable_exception_propagates_immediately():
    calls = []

    def bad():
        calls.append(1)
        raise KeyError('logic bug, not a transient')

    with pytest.raises(KeyError):
        retry_mod.call_with_retry(bad, policy=RetryPolicy(attempts=5),
                                  clock=ManualClock())
    assert len(calls) == 1


def test_retry_deadline_stops_early():
    clock = ManualClock()
    calls = []

    def flaky():
        calls.append(1)
        clock.now += 1.0  # each attempt costs a second
        raise OSError('transient')

    # 10 attempts allowed, but the 4s deadline forbids backoffs that
    # would land past it
    pol = RetryPolicy(attempts=10, base_delay=2.0, multiplier=2.0,
                      jitter=0.0, deadline=4.0)
    with pytest.raises(OSError):
        retry_mod.call_with_retry(flaky, policy=pol, clock=clock,
                                  rng=random.Random(0))
    # attempt 1 at t=0 (fails at t=1, +2s backoff -> t=3 < 4 ok),
    # attempt 2 fails at t=4, next backoff 4s would end at t=8 > 4: stop
    assert len(calls) == 2


def test_resumable_iter_rebuilds_and_fast_forwards():
    fired = []

    def make():
        def gen():
            for i in range(6):
                if i == 3 and not fired:
                    fired.append(1)
                    raise OSError('producer died')
                yield i
        return gen()

    out = list(retry_mod.resumable_iter(
        make, policy=RetryPolicy(attempts=3, base_delay=0.1),
        clock=ManualClock(), rng=random.Random(0)))
    assert out == [0, 1, 2, 3, 4, 5]
    assert resilience.counters.get('data_retries') == 1


def test_resumable_iter_failure_during_fast_forward_uses_budget():
    """A second transient failure hitting the REPLAY (not just the live
    read) must draw from the same retry budget, not escape uncaught."""
    builds = []

    def make():
        attempt = len(builds)
        builds.append(1)

        def gen():
            for i in range(6):
                # build 0 dies at i=3 (live read); build 1 dies at i=1
                # (mid fast-forward); build 2 runs clean
                if (attempt, i) in ((0, 3), (1, 1)):
                    raise OSError(f'flaky at build {attempt} item {i}')
                yield i
        return gen()

    out = list(retry_mod.resumable_iter(
        make, policy=RetryPolicy(attempts=4, base_delay=0.1),
        clock=ManualClock(), rng=random.Random(0)))
    assert out == [0, 1, 2, 3, 4, 5]
    assert resilience.counters.get('data_retries') == 2


def test_resumable_iter_persistent_failure_raises():
    def make():
        def gen():
            raise OSError('dead storage')
            yield  # pragma: no cover
        return gen()

    with pytest.raises(OSError, match='dead storage'):
        list(retry_mod.resumable_iter(
            make, policy=RetryPolicy(attempts=2, base_delay=0.1),
            clock=ManualClock()))


# ---------------------------------------------------------------------------
# next-batch retry through the real Loader + injected data fault
# ---------------------------------------------------------------------------

def test_loader_next_batch_retry_delivers_unfaulted_sequence(monkeypatch):
    x, y = kdata.synthetic_classification(32, (4, 4, 3), 10, seed=3)
    control = list(kdata.Loader(x, y, 8, train=True, seed=7,
                                shard=(0, 1)).epoch(prefetch_depth=0))

    faults.reset_data_fault()
    monkeypatch.setenv(faults.ENV_DATA, '2')
    try:
        faulted = list(kdata.Loader(x, y, 8, train=True, seed=7,
                                    shard=(0, 1)).epoch(
            retry=RetryPolicy(attempts=3, base_delay=0.01)))
    finally:
        faults.reset_data_fault()
    assert len(faulted) == len(control) == 4
    for a, b in zip(faulted, control):
        np.testing.assert_array_equal(a['input'], b['input'])
        np.testing.assert_array_equal(a['label'], b['label'])
    assert resilience.counters.get('data_retries') == 1


def test_loader_without_retry_propagates_data_fault(monkeypatch):
    x, y = kdata.synthetic_classification(32, (4, 4, 3), 10, seed=3)
    faults.reset_data_fault()
    monkeypatch.setenv(faults.ENV_DATA, '1')
    try:
        with pytest.raises(OSError):
            list(kdata.Loader(x, y, 8, train=True, seed=7,
                              shard=(0, 1)).epoch(prefetch_depth=0))
    finally:
        faults.reset_data_fault()


# ---------------------------------------------------------------------------
# watchdog
# ---------------------------------------------------------------------------

def test_watchdog_trips_with_stack_dump(caplog):
    import threading
    tripped = threading.Event()
    wd = StepWatchdog(0.1, action=tripped.set)
    with caplog.at_level('ERROR', logger='kfac_pytorch_tpu.resilience'
                                         '.watchdog'):
        wd.arm(tag='step 7')
        assert tripped.wait(10), 'watchdog never tripped'
    wd.stop()
    text = caplog.text
    assert 'step deadline exceeded' in text
    assert 'MainThread' in text  # the all-thread stack dump
    assert resilience.counters.get('watchdog_trips') == 1


def test_watchdog_disarm_prevents_trip():
    import threading
    import time
    tripped = threading.Event()
    wd = StepWatchdog(0.15, action=tripped.set)
    for _ in range(3):
        wd.arm()
        wd.disarm()
    time.sleep(0.4)
    assert not tripped.is_set()
    wd.stop()


def test_watchdog_paused_ignores_arm():
    import threading
    import time
    tripped = threading.Event()
    wd = StepWatchdog(0.15, action=tripped.set)
    wd.arm()
    with wd.paused():
        wd.arm()  # e.g. a nested step during the final blocking save
        time.sleep(0.4)
    assert not tripped.is_set()
    # after the pause the watchdog still works
    wd.arm()
    assert tripped.wait(10)
    wd.stop()


def test_watchdog_rejects_nonpositive_deadline():
    with pytest.raises(ValueError):
        StepWatchdog(0)


# ---------------------------------------------------------------------------
# straggler governor (pure + through the real train step via slow fault)
# ---------------------------------------------------------------------------

class _FakePrecond:
    fac_update_freq = 1
    kfac_update_freq = 10


def test_straggler_governor_stretch_and_restore():
    pre = _FakePrecond()
    clk = ManualClock()
    gov = StragglerGovernor(pre, budget=1.0, decay=0.5, warmup=1,
                            clock=clk.monotonic, sleep=clk.sleep)
    for s in range(20):
        gov.tick(s)
        clk.sleep(5.0 if 3 <= s < 8 else 0.1)
    assert gov.degrades >= 1 and gov.recoveries == 1
    assert gov.level == 0
    assert (pre.fac_update_freq, pre.kfac_update_freq) == (1, 10)


def test_straggler_governor_respects_external_rebase():
    pre = _FakePrecond()
    clk = ManualClock()
    gov = StragglerGovernor(pre, budget=1.0, decay=0.5, warmup=0,
                            clock=clk.monotonic, sleep=clk.sleep)
    for dt in (5.0, 5.0, 5.0):
        gov.observe(dt)
    assert gov.level >= 1
    # a KFACParamScheduler epoch step rewrites the freqs under us
    pre.fac_update_freq, pre.kfac_update_freq = 4, 40
    for _ in range(10):
        gov.observe(0.01)
    # recovery must NOT clobber the scheduler's values with stale ones
    assert (pre.fac_update_freq, pre.kfac_update_freq) == (4, 40)
    assert gov.level == 0


def test_slow_step_fault_stretches_freqs_then_recovers(monkeypatch):
    """The acceptance drill: KFAC_FAULT_SLOW_STEP stretches
    kfac_update_freq via the governor, recovery restores it — fully
    deterministic on a ManualClock (the fault's sleep and the governor's
    measurements share it)."""
    monkeypatch.setenv(faults.ENV_SLOW, '3:7')
    monkeypatch.setenv(faults.ENV_SLOW_SECS, '5.0')
    rng = np.random.RandomState(0)
    batches = [{'input': np.asarray(rng.randn(8, 8, 8, 3), np.float32),
                'label': rng.randint(0, 10, 8)}
               for _ in range(16)]

    model = TinyCNN()
    precond = kfac.KFAC(variant='eigen', lr=0.05, damping=0.003,
                        fac_update_freq=1, kfac_update_freq=2,
                        num_devices=1, axis_name=None)
    tx = training.sgd(0.05)
    state = training.init_train_state(model, tx, precond,
                                      jax.random.PRNGKey(0),
                                      batches[0]['input'])
    clk = ManualClock()
    gov = StragglerGovernor(precond, budget=1.0, decay=0.5, warmup=1,
                            stretch=2, clock=clk.monotonic,
                            sleep=clk.sleep)

    def ce(outputs, batch):
        return optax.softmax_cross_entropy_with_integer_labels(
            outputs, batch['label']).mean()

    step = training.build_train_step(model, tx, precond, ce,
                                     straggler=gov)
    base = precond.kfac_update_freq
    stretched_seen = []
    for b in batches:
        state, _ = step(state, b, lr=0.05, damping=0.003)
        stretched_seen.append(precond.kfac_update_freq)
    assert max(stretched_seen) > base, 'slow fault never stretched freqs'
    assert gov.degrades >= 1 and gov.recoveries >= 1
    assert precond.kfac_update_freq == base, 'recovery did not restore'
    assert precond.fac_update_freq == 1


# ---------------------------------------------------------------------------
# supervisor
# ---------------------------------------------------------------------------

def _counter_child(path, fail_times, rc=1):
    prog = (f'import os,sys;p={str(path)!r};'
            'n=int(open(p).read()) if os.path.exists(p) else 0;'
            f"open(p,'w').write(str(n+1));"
            f'sys.exit(0 if n>={fail_times} else {rc})')
    return [sys.executable, '-c', prog]


def test_supervisor_restarts_crash_until_success(tmp_path):
    sup = Supervisor(_counter_child(tmp_path / 'n', 2), max_restarts=5,
                     backoff_base=0.01, clock=ManualClock(),
                     rng=random.Random(0))
    assert sup.run() == 0
    assert sup.counts() == {'restarts': 2, 'crashes': 2, 'hangs': 0}


def test_supervisor_classifies_hang_rc_and_gives_up(tmp_path):
    sup = Supervisor([sys.executable, '-c', f'import sys;sys.exit({RC_HANG})'],
                     max_restarts=1, backoff_base=0.01,
                     clock=ManualClock(), rng=random.Random(0))
    assert sup.run() == RC_HANG
    assert sup.hangs == 2 and sup.crashes == 0 and sup.restarts == 1


def test_supervisor_stop_rc_propagates_without_restart(tmp_path):
    sup = Supervisor([sys.executable, '-c', 'import sys;sys.exit(7)'],
                     max_restarts=5, stop_rcs=(7,), backoff_base=0.01,
                     clock=ManualClock())
    assert sup.run() == 7
    assert sup.restarts == 0


def test_supervisor_forwards_sigterm_to_trainer(tmp_path):
    """Under KFAC_SUPERVISE=1 the supervisor is the process the platform
    SIGTERMs on preemption: it must forward the signal to the trainer
    (whose PreemptionGuard owns the grace-window save) and stop the
    restart loop instead of counting the exit as a crash."""
    import subprocess
    import time
    marker = tmp_path / 'graceful'
    child_prog = (
        'import signal, sys, time\n'
        f'marker = {str(marker)!r}\n'
        'def h(s, f):\n'
        "    open(marker, 'w').write('saved')\n"
        '    sys.exit(0)\n'
        'signal.signal(signal.SIGTERM, h)\n'
        "print('READY', flush=True)\n"
        'time.sleep(60)\n')
    child_file = tmp_path / 'child.py'
    child_file.write_text(child_prog)
    sup = subprocess.Popen(
        [sys.executable, '-m', 'kfac_pytorch_tpu.resilience.supervisor',
         '--max-restarts', '3', '--backoff-base', '0.05', '--',
         sys.executable, '-u', str(child_file)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    try:
        # READY is printed AFTER the child installed its handler, so the
        # forwarded signal cannot race the installation
        while True:
            line = sup.stdout.readline()
            assert line, 'supervisor/child died before READY'
            if 'READY' in line:
                break
        time.sleep(0.1)
        sup.send_signal(signal.SIGTERM)
        out, _ = sup.communicate(timeout=60)
    finally:
        if sup.poll() is None:
            sup.kill()
    assert sup.returncode == 0, out[-2000:]
    assert marker.exists(), out[-2000:]  # the grace-window path ran
    assert 'forwarding to trainer' in out
    assert 'not restarting' in out


def test_counter_deltas_per_epoch_view():
    now = {'io_retries': 3, 'watchdog_trips': 1, 'straggler_level': 2}
    prev = {'io_retries': 3, 'watchdog_trips': 0}
    d = runlog.counter_deltas(now, prev)
    assert d == {'io_retries': 0, 'watchdog_trips': 1,
                 'straggler_level': 2}  # gauge passes through
    # an incident-free epoch after an incident formats to ''
    assert runlog.resilience_suffix(
        runlog.counter_deltas({'io_retries': 3}, {'io_retries': 3})) == ''


def test_supervisor_main_requires_command(capsys):
    from kfac_pytorch_tpu.resilience import supervisor as sup_mod
    with pytest.raises(SystemExit):
        sup_mod.main(['--max-restarts', '2'])


def test_supervisor_main_runs_command():
    from kfac_pytorch_tpu.resilience import supervisor as sup_mod
    rc = sup_mod.main(['--max-restarts', '0', '--',
                       sys.executable, '-c', 'pass'])
    assert rc == 0


# ---------------------------------------------------------------------------
# transient checkpoint write (eio_once) under a retry policy
# ---------------------------------------------------------------------------

def test_ckpt_eio_once_without_retry_raises(tmp_path, monkeypatch):
    monkeypatch.setattr(checkpoint, '_HAS_ORBAX', False)
    monkeypatch.setenv(faults.ENV_CKPT, 'eio_once')
    faults.reset_ckpt_fault()
    with pytest.raises(OSError):
        checkpoint.save_checkpoint(tmp_path, 0, {'w': np.zeros(8)})
    assert not (tmp_path / 'checkpoint-0.pkl').exists()
    # the transient cleared: the next save succeeds
    checkpoint.save_checkpoint(tmp_path, 0, {'w': np.zeros(8)})
    assert (tmp_path / 'checkpoint-0.pkl').exists()
    faults.reset_ckpt_fault()


def test_ckpt_eio_once_with_retry_succeeds(tmp_path, monkeypatch):
    monkeypatch.setattr(checkpoint, '_HAS_ORBAX', False)
    monkeypatch.setenv(faults.ENV_CKPT, 'eio_once')
    faults.reset_ckpt_fault()
    payload = {'w': np.arange(16, dtype=np.float32)}
    checkpoint.save_checkpoint(
        tmp_path, 2, payload,
        retry=RetryPolicy(attempts=3, base_delay=0.01))
    assert (tmp_path / 'checkpoint-2.pkl').exists()
    assert resilience.counters.get('io_retries') == 1
    monkeypatch.delenv(faults.ENV_CKPT)
    restored = checkpoint.restore_checkpoint(
        tmp_path, 2, payload, retry=RetryPolicy(attempts=2,
                                                base_delay=0.01))
    np.testing.assert_array_equal(restored['w'], payload['w'])
    faults.reset_ckpt_fault()


def test_auto_resume_with_retry_policy(tmp_path, monkeypatch):
    monkeypatch.setattr(checkpoint, '_HAS_ORBAX', False)
    payload = {'w': np.ones(4, np.float32)}
    checkpoint.save_checkpoint(tmp_path, 1, payload)
    restored, epoch = checkpoint.auto_resume(
        tmp_path, 5, payload, retry=RetryPolicy(attempts=2,
                                                base_delay=0.01))
    assert epoch == 1
    np.testing.assert_array_equal(restored['w'], payload['w'])


# ---------------------------------------------------------------------------
# runlog: flush hooks + resilience suffix; PreemptionGuard interplay
# ---------------------------------------------------------------------------

def test_resilience_suffix_formatting():
    assert runlog.resilience_suffix({}) == ''
    assert runlog.resilience_suffix({'io_retries': 0}) == ''
    s = runlog.resilience_suffix({'io_retries': 2, 'watchdog_trips': 1,
                                  'straggler_level': 0})
    assert s == ' [resilience: io_retries=2 watchdog_trips=1]'


def test_flush_hooks_chain_under_preemption_guard():
    """runlog's SIGTERM flush must not steal the exit from a
    PreemptionGuard installed over it: the guard's cooperative flag is
    set, the process survives, and the flush hook ran as the chained
    predecessor."""
    runlog.uninstall_flush_hooks()
    runlog.install_flush_hooks()
    try:
        guard = checkpoint.PreemptionGuard()
        try:
            os.kill(os.getpid(), signal.SIGTERM)
            assert guard.triggered  # alive and cooperatively flagged
        finally:
            guard.uninstall()
    finally:
        runlog.uninstall_flush_hooks()


def test_flush_hooks_install_idempotent_and_uninstall_restores():
    runlog.uninstall_flush_hooks()
    before = signal.getsignal(signal.SIGTERM)
    runlog.install_flush_hooks()
    runlog.install_flush_hooks()  # idempotent
    assert signal.getsignal(signal.SIGTERM) is runlog._sigterm_flush
    runlog.uninstall_flush_hooks()
    assert signal.getsignal(signal.SIGTERM) is before


def test_preemption_guard_install_uninstall_reinstall():
    """The satellite drill: a guard can be installed, uninstalled and
    reinstalled; each uninstall restores the prior handler and a
    reinstalled guard still converts SIGTERM into the cooperative flag.
    """
    before = signal.getsignal(signal.SIGTERM)
    g1 = checkpoint.PreemptionGuard()
    g1.uninstall()
    assert signal.getsignal(signal.SIGTERM) == (
        before if before is not None else signal.SIG_DFL)
    g2 = checkpoint.PreemptionGuard()
    try:
        os.kill(os.getpid(), signal.SIGTERM)
        assert g2.triggered
        assert not g1.triggered  # g1 is fully retired, its flag untouched
    finally:
        g2.uninstall()
    assert signal.getsignal(signal.SIGTERM) == (
        before if before is not None else signal.SIG_DFL)


# ---------------------------------------------------------------------------
# supervisor satellites: --stop-rc names, machine-greppable give-up
# ---------------------------------------------------------------------------

def test_parse_stop_rc_accepts_names_and_numbers():
    from kfac_pytorch_tpu.resilience.heartbeat import RC_PEER_DEAD
    from kfac_pytorch_tpu.resilience.supervisor import parse_stop_rc
    assert parse_stop_rc('114') == RC_HANG
    assert parse_stop_rc('hang') == RC_HANG
    assert parse_stop_rc('peer_dead') == RC_PEER_DEAD
    assert parse_stop_rc('peer-dead') == RC_PEER_DEAD
    assert parse_stop_rc('crash') == faults.CRASH_RC
    assert parse_stop_rc('7') == 7
    import argparse
    with pytest.raises(argparse.ArgumentTypeError, match='unknown'):
        parse_stop_rc('sideways')


def test_supervisor_main_accepts_stop_rc_name():
    """--stop-rc peer_dead propagates 115 without restarting (the
    single-host deployment posture: a pod problem is not fixable by a
    local restart loop)."""
    from kfac_pytorch_tpu.resilience import supervisor as sup_mod
    from kfac_pytorch_tpu.resilience.heartbeat import RC_PEER_DEAD
    rc = sup_mod.main(
        ['--max-restarts', '5', '--backoff-base', '0.01',
         '--stop-rc', 'peer_dead', '--',
         sys.executable, '-c', f'import sys; sys.exit({RC_PEER_DEAD})'])
    assert rc == RC_PEER_DEAD


def test_supervisor_give_up_line_is_machine_greppable(caplog):
    """The incident scraper must not have to parse prose: the final
    give-up log line carries [resilience: ... gave_up=1 ...]."""
    sup = Supervisor([sys.executable, '-c', 'import sys; sys.exit(3)'],
                     max_restarts=1, backoff_base=0.01,
                     clock=ManualClock(), rng=random.Random(0))
    with caplog.at_level('INFO', logger='kfac_pytorch_tpu.resilience'
                                        '.supervisor'):
        assert sup.run() == 3
    give_up = [r.getMessage() for r in caplog.records
               if 'giving up' in r.getMessage()]
    assert give_up
    counts = runlog.parse_resilience_suffix(give_up[-1])
    assert counts.get('gave_up') == 1
    assert counts.get('crashes') == 2


# ---------------------------------------------------------------------------
# watchdog satellite: final counters reach the log before the hard exit
# ---------------------------------------------------------------------------

def test_watchdog_expire_emits_final_counters_and_flushes(caplog):
    """The epoch line that would have carried this epoch's counters
    never comes after an abort — the watchdog itself must emit the
    cumulative [resilience: ...] snapshot and run the runlog flush
    before exiting, so the incident report sees the last step's
    counters."""
    import threading
    resilience.counters.bump('io_retries', 3)
    flushed = []
    orig_flush = runlog.flush_all_handlers
    tripped = threading.Event()
    try:
        runlog.flush_all_handlers = lambda: (flushed.append(1),
                                             orig_flush())[1]
        wd = StepWatchdog(0.1, action=tripped.set)
        with caplog.at_level('ERROR', logger='kfac_pytorch_tpu'
                                             '.resilience.watchdog'):
            wd.arm(tag='step 9')
            assert tripped.wait(10)
        wd.stop()
    finally:
        runlog.flush_all_handlers = orig_flush
    final = [r.getMessage() for r in caplog.records
             if 'final counters' in r.getMessage()]
    assert final, 'no final-counters line before the abort'
    counts = runlog.parse_resilience_suffix(final[-1])
    assert counts.get('watchdog_trips') == 1
    assert counts.get('io_retries') == 3
    assert flushed, 'runlog flush hook did not run before the exit'


# ---------------------------------------------------------------------------
# mesh satellite: coordinator startup race retries instead of crashing
# ---------------------------------------------------------------------------

def test_maybe_initialize_distributed_retries_coordinator_race(
        monkeypatch):
    from kfac_pytorch_tpu.parallel import mesh as kmesh
    calls = []

    def flaky_init(coordinator_address, num_processes, process_id):
        calls.append((coordinator_address, num_processes, process_id))
        if len(calls) < 3:
            raise RuntimeError('failed to connect to coordinator')

    monkeypatch.setattr(jax.distributed, 'initialize', flaky_init)
    monkeypatch.setenv('JAX_COORDINATOR_ADDRESS', 'hostA:8476')
    monkeypatch.setenv('KFAC_TPU_MULTIHOST', '1')
    monkeypatch.setenv('JAX_NUM_PROCESSES', '2')
    monkeypatch.setenv('JAX_PROCESS_ID', '1')
    pol = RetryPolicy(attempts=4, base_delay=0.0, jitter=0.0,
                      retry_on=(RuntimeError,))
    assert kmesh.maybe_initialize_distributed(retry=pol) is True
    assert len(calls) == 3  # two coordinator races, then success
    assert calls[0] == ('hostA:8476', 2, 1)
    assert resilience.counters.get('dist_init_retries') == 2
    # elastic-relaunch overrides beat the env
    calls.clear()
    assert kmesh.maybe_initialize_distributed(
        retry=pol, coordinator_address='hostB:8476', num_processes=1,
        process_id=0) is True
    assert calls[-1] == ('hostB:8476', 1, 0)
    # no coordination env -> no-op, nothing called
    monkeypatch.delenv('JAX_COORDINATOR_ADDRESS')
    calls.clear()
    assert kmesh.maybe_initialize_distributed() is False
    assert calls == []


def test_maybe_initialize_distributed_default_policy_skips_permanent(
        monkeypatch):
    """The default retry policy only retries connection-SHAPED
    RuntimeErrors: a permanent one ('already initialized', bad address)
    must surface after a single attempt, not burn the whole backoff
    budget re-raising itself."""
    from kfac_pytorch_tpu.parallel import mesh as kmesh
    calls = []

    def permanent(coordinator_address, num_processes, process_id):
        calls.append(1)
        raise RuntimeError('jax.distributed is already initialized')

    monkeypatch.setattr(jax.distributed, 'initialize', permanent)
    monkeypatch.setenv('JAX_COORDINATOR_ADDRESS', 'hostA:8476')
    monkeypatch.setenv('KFAC_TPU_MULTIHOST', '1')
    monkeypatch.setenv('JAX_NUM_PROCESSES', '2')
    monkeypatch.setenv('JAX_PROCESS_ID', '0')
    with pytest.raises(RuntimeError, match='already initialized'):
        kmesh.maybe_initialize_distributed()  # default policy
    assert len(calls) == 1


def test_maybe_initialize_distributed_fail_fast_opt_out(monkeypatch):
    from kfac_pytorch_tpu.parallel import mesh as kmesh

    def always_down(**kw):
        raise RuntimeError('failed to connect to coordinator')

    monkeypatch.setattr(jax.distributed, 'initialize', always_down)
    monkeypatch.setenv('JAX_COORDINATOR_ADDRESS', 'hostA:8476')
    monkeypatch.setenv('KFAC_TPU_MULTIHOST', '1')
    monkeypatch.setenv('JAX_NUM_PROCESSES', '2')
    monkeypatch.setenv('JAX_PROCESS_ID', '0')
    with pytest.raises(RuntimeError):
        kmesh.maybe_initialize_distributed(retry=False)
    assert resilience.counters.get('dist_init_retries') == 0


# ---------------------------------------------------------------------------
# world stamp (elastic resume routing)
# ---------------------------------------------------------------------------

def test_world_stamp_roundtrip_and_absence(tmp_path):
    assert checkpoint.read_world_stamp(tmp_path) is None
    checkpoint.write_world_stamp(tmp_path, 4)
    assert checkpoint.read_world_stamp(tmp_path) == 4
    checkpoint.write_world_stamp(tmp_path, 2)  # overwrite on shrink
    assert checkpoint.read_world_stamp(tmp_path) == 2
    # corrupt stamp reads as "no stamp" (same-world resume), not a crash
    (tmp_path / 'world.json').write_text('not json')
    assert checkpoint.read_world_stamp(tmp_path) is None


# ---------------------------------------------------------------------------
# pod supervisor (fast paths; the real two-process SIGKILL drill is in
# tests/test_pod_chaos.py behind -m slow)
# ---------------------------------------------------------------------------

def test_pod_supervisor_clean_exit_writes_incident(tmp_path):
    import json
    from kfac_pytorch_tpu.resilience.elastic import PodSupervisor
    sup = PodSupervisor([sys.executable, '-c', 'pass'], host_id=0,
                        num_hosts=1, lease_dir=str(tmp_path / 'lease'),
                        max_restarts=2, backoff_base=0.01,
                        poll_period=0.02)
    assert sup.run() == 0
    report = json.loads(
        (tmp_path / 'lease' / 'incident-host0.json').read_text())
    assert report['host_id'] == 0
    assert report['gave_up'] is False
    kinds = [e['kind'] for e in report['events']]
    assert 'launch' in kinds and 'trainer_exit' in kinds


def test_pod_supervisor_crash_loop_gives_up_with_incident(tmp_path):
    import json
    from kfac_pytorch_tpu.resilience.elastic import PodSupervisor
    sup = PodSupervisor([sys.executable, '-c', 'import sys;sys.exit(3)'],
                        host_id=0, num_hosts=1,
                        lease_dir=str(tmp_path / 'lease'),
                        max_restarts=1, backoff_base=0.01,
                        poll_period=0.02, rng=random.Random(0))
    assert sup.run() == 3
    assert sup.crashes == 2 and sup.restarts == 1
    report = json.loads(
        (tmp_path / 'lease' / 'incident-host0.json').read_text())
    assert report['gave_up'] is True
    assert report['counters']['crashes'] == 2


def test_pod_supervisor_substitutes_world_placeholders(tmp_path):
    from kfac_pytorch_tpu.resilience.elastic import PodSupervisor
    sup = PodSupervisor(['trainer', '--host-id', '{host_id}',
                         '--num-hosts', '{num_hosts}', '--tag',
                         'gen{gen}', '--plain'],
                        host_id=2, num_hosts=3,
                        lease_dir=str(tmp_path / 'lease'))
    assert sup._child_argv() == ['trainer', '--host-id', '2',
                                 '--num-hosts', '3', '--tag', 'gen0',
                                 '--plain']
    # after a (simulated) shrink the rank and world re-derive
    sup.members = [1, 2]
    sup.gen = 1
    assert sup._child_argv() == ['trainer', '--host-id', '1',
                                 '--num-hosts', '2', '--tag', 'gen1',
                                 '--plain']
    env = sup._child_env()
    assert env['JAX_PROCESS_ID'] == '1'
    assert env['JAX_NUM_PROCESSES'] == '2'
    assert env['KFAC_POD_GEN'] == '1'
    assert env['KFAC_HB_HOST'] == '1'
    assert env['KFAC_HB_HOSTS'] == '2'
    assert env['KFAC_HB_DIR'].endswith('trainer-gen1')


def test_pod_supervisor_clears_stale_protocol_files_at_startup(tmp_path):
    """A pod restart reuses the lease dir (the runbook): stale shrink
    claims and heartbeat leases from the previous incarnation must be
    scrubbed at generation 0, or every healthy host would read "peers
    are shrinking around me" and fence itself."""
    import json
    from kfac_pytorch_tpu.resilience.elastic import PodSupervisor
    lease = tmp_path / 'lease'
    # previous incarnation's debris: a completed shrink + old leases
    (lease / 'shrink-gen1').mkdir(parents=True)
    (lease / 'shrink-gen1' / 'survivor-1.json').write_text(
        '{"host": 1, "addr": null}')
    (lease / 'sup').mkdir()
    (lease / 'sup' / 'hb-1.json').write_text(
        '{"host": 1, "seq": 900, "pid": 1}')
    (lease / 'trainer-gen0').mkdir()
    (lease / 'incident-host1.json').write_text('{}')  # artifact: kept
    sup = PodSupervisor([sys.executable, '-c', 'pass'], host_id=0,
                        num_hosts=1, lease_dir=str(lease),
                        max_restarts=1, backoff_base=0.01,
                        poll_period=0.02)
    assert sup.run() == 0  # no self-fence, clean completion
    assert not (lease / 'shrink-gen1').exists()
    assert not (lease / 'sup' / 'hb-1.json').exists()
    assert (lease / 'incident-host1.json').exists()
    report = json.loads((lease / 'incident-host0.json').read_text())
    assert not any(e['kind'] == 'fenced' for e in report['events'])


def test_pod_supervisor_stop_rc_propagates(tmp_path):
    from kfac_pytorch_tpu.resilience.elastic import PodSupervisor
    sup = PodSupervisor([sys.executable, '-c', 'import sys;sys.exit(7)'],
                        host_id=0, num_hosts=1,
                        lease_dir=str(tmp_path / 'lease'),
                        max_restarts=5, stop_rcs=(7,),
                        backoff_base=0.01, poll_period=0.02)
    assert sup.run() == 7
    assert sup.restarts == 0


def test_guard_final_save_runs_with_watchdog_paused(tmp_path, monkeypatch):
    """The PreemptionGuard grace-window save must not race the step
    watchdog: inside ``paused()`` even a save far exceeding the step
    deadline cannot trip it."""
    import threading
    import time
    monkeypatch.setattr(checkpoint, '_HAS_ORBAX', False)
    tripped = threading.Event()
    wd = StepWatchdog(0.1, action=tripped.set)
    guard = checkpoint.PreemptionGuard()
    try:
        wd.arm()
        os.kill(os.getpid(), signal.SIGTERM)
        assert guard.should_stop()
        with wd.paused():
            time.sleep(0.3)  # a "slow" final save, > deadline
            checkpoint.save_checkpoint(tmp_path, 0, {'w': np.zeros(4)})
        assert not tripped.is_set()
    finally:
        guard.uninstall()
        wd.stop()
    assert (tmp_path / 'checkpoint-0.pkl').exists()
