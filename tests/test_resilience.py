"""Resilient-runtime unit drills (kfac_pytorch_tpu/resilience/).

Everything here is wall-clock-free or sub-second: retry/backoff under a
ManualClock, the watchdog with an injected expiry action, the straggler
governor driven by the deterministic slow-step fault, the supervisor
restart loop on trivial children, and the transient-checkpoint /
next-batch retry paths. The multi-minute subprocess drills (real
SIGKILL, real hang) live in tests/test_chaos.py behind ``-m slow``.
"""

import os
import random
import signal
import sys

import jax
import numpy as np
import optax
import pytest

import kfac_pytorch_tpu as kfac
from kfac_pytorch_tpu import data as kdata
from kfac_pytorch_tpu import faults, resilience, training
from kfac_pytorch_tpu.resilience import retry as retry_mod
from kfac_pytorch_tpu.resilience.retry import ManualClock, RetryPolicy
from kfac_pytorch_tpu.resilience.straggler import StragglerGovernor
from kfac_pytorch_tpu.resilience.supervisor import Supervisor
from kfac_pytorch_tpu.resilience.watchdog import RC_HANG, StepWatchdog
from kfac_pytorch_tpu.utils import checkpoint, runlog

from tests.helpers import TinyCNN


@pytest.fixture(autouse=True)
def _reset_counters():
    resilience.counters.reset()
    yield
    resilience.counters.reset()


# ---------------------------------------------------------------------------
# retry: attempts, jitter bounds, deadline — all on the fake clock
# ---------------------------------------------------------------------------

def test_retry_attempt_count_and_jitter_bounds():
    clock = ManualClock()
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 4:
            raise OSError('transient')
        return 'ok'

    pol = RetryPolicy(attempts=5, base_delay=1.0, multiplier=2.0,
                      jitter=0.5, max_delay=100.0)
    out = retry_mod.call_with_retry(flaky, policy=pol, clock=clock,
                                    rng=random.Random(0))
    assert out == 'ok'
    assert len(calls) == 4          # 3 failures + 1 success
    assert len(clock.sleeps) == 3   # one backoff per retry
    for k, s in enumerate(clock.sleeps):
        nominal = 1.0 * 2.0 ** k
        assert nominal * 0.5 <= s <= nominal * 1.5, (k, s)
    assert resilience.counters.get('io_retries') == 3


def test_retry_exhaustion_reraises_last_exception():
    clock = ManualClock()

    def always():
        raise OSError('persistent')

    with pytest.raises(OSError, match='persistent'):
        retry_mod.call_with_retry(
            always, policy=RetryPolicy(attempts=3, base_delay=0.1),
            clock=clock, rng=random.Random(0))
    assert len(clock.sleeps) == 2  # no sleep after the final attempt


def test_retry_non_retryable_exception_propagates_immediately():
    calls = []

    def bad():
        calls.append(1)
        raise KeyError('logic bug, not a transient')

    with pytest.raises(KeyError):
        retry_mod.call_with_retry(bad, policy=RetryPolicy(attempts=5),
                                  clock=ManualClock())
    assert len(calls) == 1


def test_retry_deadline_stops_early():
    clock = ManualClock()
    calls = []

    def flaky():
        calls.append(1)
        clock.now += 1.0  # each attempt costs a second
        raise OSError('transient')

    # 10 attempts allowed, but the 4s deadline forbids backoffs that
    # would land past it
    pol = RetryPolicy(attempts=10, base_delay=2.0, multiplier=2.0,
                      jitter=0.0, deadline=4.0)
    with pytest.raises(OSError):
        retry_mod.call_with_retry(flaky, policy=pol, clock=clock,
                                  rng=random.Random(0))
    # attempt 1 at t=0 (fails at t=1, +2s backoff -> t=3 < 4 ok),
    # attempt 2 fails at t=4, next backoff 4s would end at t=8 > 4: stop
    assert len(calls) == 2


def test_resumable_iter_rebuilds_and_fast_forwards():
    fired = []

    def make():
        def gen():
            for i in range(6):
                if i == 3 and not fired:
                    fired.append(1)
                    raise OSError('producer died')
                yield i
        return gen()

    out = list(retry_mod.resumable_iter(
        make, policy=RetryPolicy(attempts=3, base_delay=0.1),
        clock=ManualClock(), rng=random.Random(0)))
    assert out == [0, 1, 2, 3, 4, 5]
    assert resilience.counters.get('data_retries') == 1


def test_resumable_iter_failure_during_fast_forward_uses_budget():
    """A second transient failure hitting the REPLAY (not just the live
    read) must draw from the same retry budget, not escape uncaught."""
    builds = []

    def make():
        attempt = len(builds)
        builds.append(1)

        def gen():
            for i in range(6):
                # build 0 dies at i=3 (live read); build 1 dies at i=1
                # (mid fast-forward); build 2 runs clean
                if (attempt, i) in ((0, 3), (1, 1)):
                    raise OSError(f'flaky at build {attempt} item {i}')
                yield i
        return gen()

    out = list(retry_mod.resumable_iter(
        make, policy=RetryPolicy(attempts=4, base_delay=0.1),
        clock=ManualClock(), rng=random.Random(0)))
    assert out == [0, 1, 2, 3, 4, 5]
    assert resilience.counters.get('data_retries') == 2


def test_resumable_iter_persistent_failure_raises():
    def make():
        def gen():
            raise OSError('dead storage')
            yield  # pragma: no cover
        return gen()

    with pytest.raises(OSError, match='dead storage'):
        list(retry_mod.resumable_iter(
            make, policy=RetryPolicy(attempts=2, base_delay=0.1),
            clock=ManualClock()))


# ---------------------------------------------------------------------------
# next-batch retry through the real Loader + injected data fault
# ---------------------------------------------------------------------------

def test_loader_next_batch_retry_delivers_unfaulted_sequence(monkeypatch):
    x, y = kdata.synthetic_classification(32, (4, 4, 3), 10, seed=3)
    control = list(kdata.Loader(x, y, 8, train=True, seed=7,
                                shard=(0, 1)).epoch(prefetch_depth=0))

    faults.reset_data_fault()
    monkeypatch.setenv(faults.ENV_DATA, '2')
    try:
        faulted = list(kdata.Loader(x, y, 8, train=True, seed=7,
                                    shard=(0, 1)).epoch(
            retry=RetryPolicy(attempts=3, base_delay=0.01)))
    finally:
        faults.reset_data_fault()
    assert len(faulted) == len(control) == 4
    for a, b in zip(faulted, control):
        np.testing.assert_array_equal(a['input'], b['input'])
        np.testing.assert_array_equal(a['label'], b['label'])
    assert resilience.counters.get('data_retries') == 1


def test_loader_without_retry_propagates_data_fault(monkeypatch):
    x, y = kdata.synthetic_classification(32, (4, 4, 3), 10, seed=3)
    faults.reset_data_fault()
    monkeypatch.setenv(faults.ENV_DATA, '1')
    try:
        with pytest.raises(OSError):
            list(kdata.Loader(x, y, 8, train=True, seed=7,
                              shard=(0, 1)).epoch(prefetch_depth=0))
    finally:
        faults.reset_data_fault()


# ---------------------------------------------------------------------------
# watchdog
# ---------------------------------------------------------------------------

def test_watchdog_trips_with_stack_dump(caplog):
    import threading
    tripped = threading.Event()
    wd = StepWatchdog(0.1, action=tripped.set)
    with caplog.at_level('ERROR', logger='kfac_pytorch_tpu.resilience'
                                         '.watchdog'):
        wd.arm(tag='step 7')
        assert tripped.wait(10), 'watchdog never tripped'
    wd.stop()
    text = caplog.text
    assert 'step deadline exceeded' in text
    assert 'MainThread' in text  # the all-thread stack dump
    assert resilience.counters.get('watchdog_trips') == 1


def test_watchdog_disarm_prevents_trip():
    import threading
    import time
    tripped = threading.Event()
    wd = StepWatchdog(0.15, action=tripped.set)
    for _ in range(3):
        wd.arm()
        wd.disarm()
    time.sleep(0.4)
    assert not tripped.is_set()
    wd.stop()


def test_watchdog_paused_ignores_arm():
    import threading
    import time
    tripped = threading.Event()
    wd = StepWatchdog(0.15, action=tripped.set)
    wd.arm()
    with wd.paused():
        wd.arm()  # e.g. a nested step during the final blocking save
        time.sleep(0.4)
    assert not tripped.is_set()
    # after the pause the watchdog still works
    wd.arm()
    assert tripped.wait(10)
    wd.stop()


def test_watchdog_rejects_nonpositive_deadline():
    with pytest.raises(ValueError):
        StepWatchdog(0)


# ---------------------------------------------------------------------------
# straggler governor (pure + through the real train step via slow fault)
# ---------------------------------------------------------------------------

class _FakePrecond:
    fac_update_freq = 1
    kfac_update_freq = 10


def test_straggler_governor_stretch_and_restore():
    pre = _FakePrecond()
    clk = ManualClock()
    gov = StragglerGovernor(pre, budget=1.0, decay=0.5, warmup=1,
                            clock=clk.monotonic, sleep=clk.sleep)
    for s in range(20):
        gov.tick(s)
        clk.sleep(5.0 if 3 <= s < 8 else 0.1)
    assert gov.degrades >= 1 and gov.recoveries == 1
    assert gov.level == 0
    assert (pre.fac_update_freq, pre.kfac_update_freq) == (1, 10)


def test_straggler_governor_respects_external_rebase():
    pre = _FakePrecond()
    clk = ManualClock()
    gov = StragglerGovernor(pre, budget=1.0, decay=0.5, warmup=0,
                            clock=clk.monotonic, sleep=clk.sleep)
    for dt in (5.0, 5.0, 5.0):
        gov.observe(dt)
    assert gov.level >= 1
    # a KFACParamScheduler epoch step rewrites the freqs under us
    pre.fac_update_freq, pre.kfac_update_freq = 4, 40
    for _ in range(10):
        gov.observe(0.01)
    # recovery must NOT clobber the scheduler's values with stale ones
    assert (pre.fac_update_freq, pre.kfac_update_freq) == (4, 40)
    assert gov.level == 0


def test_slow_step_fault_stretches_freqs_then_recovers(monkeypatch):
    """The acceptance drill: KFAC_FAULT_SLOW_STEP stretches
    kfac_update_freq via the governor, recovery restores it — fully
    deterministic on a ManualClock (the fault's sleep and the governor's
    measurements share it)."""
    monkeypatch.setenv(faults.ENV_SLOW, '3:7')
    monkeypatch.setenv(faults.ENV_SLOW_SECS, '5.0')
    rng = np.random.RandomState(0)
    batches = [{'input': np.asarray(rng.randn(8, 8, 8, 3), np.float32),
                'label': rng.randint(0, 10, 8)}
               for _ in range(16)]

    model = TinyCNN()
    precond = kfac.KFAC(variant='eigen', lr=0.05, damping=0.003,
                        fac_update_freq=1, kfac_update_freq=2,
                        num_devices=1, axis_name=None)
    tx = training.sgd(0.05)
    state = training.init_train_state(model, tx, precond,
                                      jax.random.PRNGKey(0),
                                      batches[0]['input'])
    clk = ManualClock()
    gov = StragglerGovernor(precond, budget=1.0, decay=0.5, warmup=1,
                            stretch=2, clock=clk.monotonic,
                            sleep=clk.sleep)

    def ce(outputs, batch):
        return optax.softmax_cross_entropy_with_integer_labels(
            outputs, batch['label']).mean()

    step = training.build_train_step(model, tx, precond, ce,
                                     straggler=gov)
    base = precond.kfac_update_freq
    stretched_seen = []
    for b in batches:
        state, _ = step(state, b, lr=0.05, damping=0.003)
        stretched_seen.append(precond.kfac_update_freq)
    assert max(stretched_seen) > base, 'slow fault never stretched freqs'
    assert gov.degrades >= 1 and gov.recoveries >= 1
    assert precond.kfac_update_freq == base, 'recovery did not restore'
    assert precond.fac_update_freq == 1


# ---------------------------------------------------------------------------
# supervisor
# ---------------------------------------------------------------------------

def _counter_child(path, fail_times, rc=1):
    prog = (f'import os,sys;p={str(path)!r};'
            'n=int(open(p).read()) if os.path.exists(p) else 0;'
            f"open(p,'w').write(str(n+1));"
            f'sys.exit(0 if n>={fail_times} else {rc})')
    return [sys.executable, '-c', prog]


def test_supervisor_restarts_crash_until_success(tmp_path):
    sup = Supervisor(_counter_child(tmp_path / 'n', 2), max_restarts=5,
                     backoff_base=0.01, clock=ManualClock(),
                     rng=random.Random(0))
    assert sup.run() == 0
    assert sup.counts() == {'restarts': 2, 'crashes': 2, 'hangs': 0}


def test_supervisor_classifies_hang_rc_and_gives_up(tmp_path):
    sup = Supervisor([sys.executable, '-c', f'import sys;sys.exit({RC_HANG})'],
                     max_restarts=1, backoff_base=0.01,
                     clock=ManualClock(), rng=random.Random(0))
    assert sup.run() == RC_HANG
    assert sup.hangs == 2 and sup.crashes == 0 and sup.restarts == 1


def test_supervisor_stop_rc_propagates_without_restart(tmp_path):
    sup = Supervisor([sys.executable, '-c', 'import sys;sys.exit(7)'],
                     max_restarts=5, stop_rcs=(7,), backoff_base=0.01,
                     clock=ManualClock())
    assert sup.run() == 7
    assert sup.restarts == 0


def test_supervisor_forwards_sigterm_to_trainer(tmp_path):
    """Under KFAC_SUPERVISE=1 the supervisor is the process the platform
    SIGTERMs on preemption: it must forward the signal to the trainer
    (whose PreemptionGuard owns the grace-window save) and stop the
    restart loop instead of counting the exit as a crash."""
    import subprocess
    import time
    marker = tmp_path / 'graceful'
    child_prog = (
        'import signal, sys, time\n'
        f'marker = {str(marker)!r}\n'
        'def h(s, f):\n'
        "    open(marker, 'w').write('saved')\n"
        '    sys.exit(0)\n'
        'signal.signal(signal.SIGTERM, h)\n'
        "print('READY', flush=True)\n"
        'time.sleep(60)\n')
    child_file = tmp_path / 'child.py'
    child_file.write_text(child_prog)
    sup = subprocess.Popen(
        [sys.executable, '-m', 'kfac_pytorch_tpu.resilience.supervisor',
         '--max-restarts', '3', '--backoff-base', '0.05', '--',
         sys.executable, '-u', str(child_file)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    try:
        # READY is printed AFTER the child installed its handler, so the
        # forwarded signal cannot race the installation
        while True:
            line = sup.stdout.readline()
            assert line, 'supervisor/child died before READY'
            if 'READY' in line:
                break
        time.sleep(0.1)
        sup.send_signal(signal.SIGTERM)
        out, _ = sup.communicate(timeout=60)
    finally:
        if sup.poll() is None:
            sup.kill()
    assert sup.returncode == 0, out[-2000:]
    assert marker.exists(), out[-2000:]  # the grace-window path ran
    assert 'forwarding to trainer' in out
    assert 'not restarting' in out


def test_counter_deltas_per_epoch_view():
    now = {'io_retries': 3, 'watchdog_trips': 1, 'straggler_level': 2}
    prev = {'io_retries': 3, 'watchdog_trips': 0}
    d = runlog.counter_deltas(now, prev)
    assert d == {'io_retries': 0, 'watchdog_trips': 1,
                 'straggler_level': 2}  # gauge passes through
    # an incident-free epoch after an incident formats to ''
    assert runlog.resilience_suffix(
        runlog.counter_deltas({'io_retries': 3}, {'io_retries': 3})) == ''


def test_supervisor_main_requires_command(capsys):
    from kfac_pytorch_tpu.resilience import supervisor as sup_mod
    with pytest.raises(SystemExit):
        sup_mod.main(['--max-restarts', '2'])


def test_supervisor_main_runs_command():
    from kfac_pytorch_tpu.resilience import supervisor as sup_mod
    rc = sup_mod.main(['--max-restarts', '0', '--',
                       sys.executable, '-c', 'pass'])
    assert rc == 0


# ---------------------------------------------------------------------------
# transient checkpoint write (eio_once) under a retry policy
# ---------------------------------------------------------------------------

def test_ckpt_eio_once_without_retry_raises(tmp_path, monkeypatch):
    monkeypatch.setattr(checkpoint, '_HAS_ORBAX', False)
    monkeypatch.setenv(faults.ENV_CKPT, 'eio_once')
    faults.reset_ckpt_fault()
    with pytest.raises(OSError):
        checkpoint.save_checkpoint(tmp_path, 0, {'w': np.zeros(8)})
    assert not (tmp_path / 'checkpoint-0.pkl').exists()
    # the transient cleared: the next save succeeds
    checkpoint.save_checkpoint(tmp_path, 0, {'w': np.zeros(8)})
    assert (tmp_path / 'checkpoint-0.pkl').exists()
    faults.reset_ckpt_fault()


def test_ckpt_eio_once_with_retry_succeeds(tmp_path, monkeypatch):
    monkeypatch.setattr(checkpoint, '_HAS_ORBAX', False)
    monkeypatch.setenv(faults.ENV_CKPT, 'eio_once')
    faults.reset_ckpt_fault()
    payload = {'w': np.arange(16, dtype=np.float32)}
    checkpoint.save_checkpoint(
        tmp_path, 2, payload,
        retry=RetryPolicy(attempts=3, base_delay=0.01))
    assert (tmp_path / 'checkpoint-2.pkl').exists()
    assert resilience.counters.get('io_retries') == 1
    monkeypatch.delenv(faults.ENV_CKPT)
    restored = checkpoint.restore_checkpoint(
        tmp_path, 2, payload, retry=RetryPolicy(attempts=2,
                                                base_delay=0.01))
    np.testing.assert_array_equal(restored['w'], payload['w'])
    faults.reset_ckpt_fault()


def test_auto_resume_with_retry_policy(tmp_path, monkeypatch):
    monkeypatch.setattr(checkpoint, '_HAS_ORBAX', False)
    payload = {'w': np.ones(4, np.float32)}
    checkpoint.save_checkpoint(tmp_path, 1, payload)
    restored, epoch = checkpoint.auto_resume(
        tmp_path, 5, payload, retry=RetryPolicy(attempts=2,
                                                base_delay=0.01))
    assert epoch == 1
    np.testing.assert_array_equal(restored['w'], payload['w'])


# ---------------------------------------------------------------------------
# runlog: flush hooks + resilience suffix; PreemptionGuard interplay
# ---------------------------------------------------------------------------

def test_resilience_suffix_formatting():
    assert runlog.resilience_suffix({}) == ''
    assert runlog.resilience_suffix({'io_retries': 0}) == ''
    s = runlog.resilience_suffix({'io_retries': 2, 'watchdog_trips': 1,
                                  'straggler_level': 0})
    assert s == ' [resilience: io_retries=2 watchdog_trips=1]'


def test_flush_hooks_chain_under_preemption_guard():
    """runlog's SIGTERM flush must not steal the exit from a
    PreemptionGuard installed over it: the guard's cooperative flag is
    set, the process survives, and the flush hook ran as the chained
    predecessor."""
    runlog.uninstall_flush_hooks()
    runlog.install_flush_hooks()
    try:
        guard = checkpoint.PreemptionGuard()
        try:
            os.kill(os.getpid(), signal.SIGTERM)
            assert guard.triggered  # alive and cooperatively flagged
        finally:
            guard.uninstall()
    finally:
        runlog.uninstall_flush_hooks()


def test_flush_hooks_install_idempotent_and_uninstall_restores():
    runlog.uninstall_flush_hooks()
    before = signal.getsignal(signal.SIGTERM)
    runlog.install_flush_hooks()
    runlog.install_flush_hooks()  # idempotent
    assert signal.getsignal(signal.SIGTERM) is runlog._sigterm_flush
    runlog.uninstall_flush_hooks()
    assert signal.getsignal(signal.SIGTERM) is before


def test_preemption_guard_install_uninstall_reinstall():
    """The satellite drill: a guard can be installed, uninstalled and
    reinstalled; each uninstall restores the prior handler and a
    reinstalled guard still converts SIGTERM into the cooperative flag.
    """
    before = signal.getsignal(signal.SIGTERM)
    g1 = checkpoint.PreemptionGuard()
    g1.uninstall()
    assert signal.getsignal(signal.SIGTERM) == (
        before if before is not None else signal.SIG_DFL)
    g2 = checkpoint.PreemptionGuard()
    try:
        os.kill(os.getpid(), signal.SIGTERM)
        assert g2.triggered
        assert not g1.triggered  # g1 is fully retired, its flag untouched
    finally:
        g2.uninstall()
    assert signal.getsignal(signal.SIGTERM) == (
        before if before is not None else signal.SIG_DFL)


# ---------------------------------------------------------------------------
# supervisor satellites: --stop-rc names, machine-greppable give-up
# ---------------------------------------------------------------------------

def test_parse_stop_rc_accepts_names_and_numbers():
    from kfac_pytorch_tpu.resilience.heartbeat import RC_PEER_DEAD
    from kfac_pytorch_tpu.resilience.supervisor import parse_stop_rc
    assert parse_stop_rc('114') == RC_HANG
    assert parse_stop_rc('hang') == RC_HANG
    assert parse_stop_rc('peer_dead') == RC_PEER_DEAD
    assert parse_stop_rc('peer-dead') == RC_PEER_DEAD
    assert parse_stop_rc('crash') == faults.CRASH_RC
    assert parse_stop_rc('7') == 7
    import argparse
    with pytest.raises(argparse.ArgumentTypeError, match='unknown'):
        parse_stop_rc('sideways')


def test_supervisor_main_accepts_stop_rc_name():
    """--stop-rc peer_dead propagates 115 without restarting (the
    single-host deployment posture: a pod problem is not fixable by a
    local restart loop)."""
    from kfac_pytorch_tpu.resilience import supervisor as sup_mod
    from kfac_pytorch_tpu.resilience.heartbeat import RC_PEER_DEAD
    rc = sup_mod.main(
        ['--max-restarts', '5', '--backoff-base', '0.01',
         '--stop-rc', 'peer_dead', '--',
         sys.executable, '-c', f'import sys; sys.exit({RC_PEER_DEAD})'])
    assert rc == RC_PEER_DEAD


def test_supervisor_give_up_line_is_machine_greppable(caplog):
    """The incident scraper must not have to parse prose: the final
    give-up log line carries [resilience: ... gave_up=1 ...]."""
    sup = Supervisor([sys.executable, '-c', 'import sys; sys.exit(3)'],
                     max_restarts=1, backoff_base=0.01,
                     clock=ManualClock(), rng=random.Random(0))
    with caplog.at_level('INFO', logger='kfac_pytorch_tpu.resilience'
                                        '.supervisor'):
        assert sup.run() == 3
    give_up = [r.getMessage() for r in caplog.records
               if 'giving up' in r.getMessage()]
    assert give_up
    counts = runlog.parse_resilience_suffix(give_up[-1])
    assert counts.get('gave_up') == 1
    assert counts.get('crashes') == 2


# ---------------------------------------------------------------------------
# watchdog satellite: final counters reach the log before the hard exit
# ---------------------------------------------------------------------------

def test_watchdog_expire_emits_final_counters_and_flushes(caplog):
    """The epoch line that would have carried this epoch's counters
    never comes after an abort — the watchdog itself must emit the
    cumulative [resilience: ...] snapshot and run the runlog flush
    before exiting, so the incident report sees the last step's
    counters."""
    import threading
    resilience.counters.bump('io_retries', 3)
    flushed = []
    orig_flush = runlog.flush_all_handlers
    tripped = threading.Event()
    try:
        runlog.flush_all_handlers = lambda: (flushed.append(1),
                                             orig_flush())[1]
        wd = StepWatchdog(0.1, action=tripped.set)
        with caplog.at_level('ERROR', logger='kfac_pytorch_tpu'
                                             '.resilience.watchdog'):
            wd.arm(tag='step 9')
            assert tripped.wait(10)
        wd.stop()
    finally:
        runlog.flush_all_handlers = orig_flush
    final = [r.getMessage() for r in caplog.records
             if 'final counters' in r.getMessage()]
    assert final, 'no final-counters line before the abort'
    counts = runlog.parse_resilience_suffix(final[-1])
    assert counts.get('watchdog_trips') == 1
    assert counts.get('io_retries') == 3
    assert flushed, 'runlog flush hook did not run before the exit'


# ---------------------------------------------------------------------------
# mesh satellite: coordinator startup race retries instead of crashing
# ---------------------------------------------------------------------------

def test_maybe_initialize_distributed_retries_coordinator_race(
        monkeypatch):
    from kfac_pytorch_tpu.parallel import mesh as kmesh
    calls = []

    def flaky_init(coordinator_address, num_processes, process_id):
        calls.append((coordinator_address, num_processes, process_id))
        if len(calls) < 3:
            raise RuntimeError('failed to connect to coordinator')

    monkeypatch.setattr(jax.distributed, 'initialize', flaky_init)
    monkeypatch.setenv('JAX_COORDINATOR_ADDRESS', 'hostA:8476')
    monkeypatch.setenv('KFAC_TPU_MULTIHOST', '1')
    monkeypatch.setenv('JAX_NUM_PROCESSES', '2')
    monkeypatch.setenv('JAX_PROCESS_ID', '1')
    pol = RetryPolicy(attempts=4, base_delay=0.0, jitter=0.0,
                      retry_on=(RuntimeError,))
    assert kmesh.maybe_initialize_distributed(retry=pol) is True
    assert len(calls) == 3  # two coordinator races, then success
    assert calls[0] == ('hostA:8476', 2, 1)
    assert resilience.counters.get('dist_init_retries') == 2
    # elastic-relaunch overrides beat the env
    calls.clear()
    assert kmesh.maybe_initialize_distributed(
        retry=pol, coordinator_address='hostB:8476', num_processes=1,
        process_id=0) is True
    assert calls[-1] == ('hostB:8476', 1, 0)
    # no coordination env -> no-op, nothing called
    monkeypatch.delenv('JAX_COORDINATOR_ADDRESS')
    calls.clear()
    assert kmesh.maybe_initialize_distributed() is False
    assert calls == []


def test_maybe_initialize_distributed_default_policy_skips_permanent(
        monkeypatch):
    """The default retry policy only retries connection-SHAPED
    RuntimeErrors: a permanent one ('already initialized', bad address)
    must surface after a single attempt, not burn the whole backoff
    budget re-raising itself."""
    from kfac_pytorch_tpu.parallel import mesh as kmesh
    calls = []

    def permanent(coordinator_address, num_processes, process_id):
        calls.append(1)
        raise RuntimeError('jax.distributed is already initialized')

    monkeypatch.setattr(jax.distributed, 'initialize', permanent)
    monkeypatch.setenv('JAX_COORDINATOR_ADDRESS', 'hostA:8476')
    monkeypatch.setenv('KFAC_TPU_MULTIHOST', '1')
    monkeypatch.setenv('JAX_NUM_PROCESSES', '2')
    monkeypatch.setenv('JAX_PROCESS_ID', '0')
    with pytest.raises(RuntimeError, match='already initialized'):
        kmesh.maybe_initialize_distributed()  # default policy
    assert len(calls) == 1


def test_maybe_initialize_distributed_fail_fast_opt_out(monkeypatch):
    from kfac_pytorch_tpu.parallel import mesh as kmesh

    def always_down(**kw):
        raise RuntimeError('failed to connect to coordinator')

    monkeypatch.setattr(jax.distributed, 'initialize', always_down)
    monkeypatch.setenv('JAX_COORDINATOR_ADDRESS', 'hostA:8476')
    monkeypatch.setenv('KFAC_TPU_MULTIHOST', '1')
    monkeypatch.setenv('JAX_NUM_PROCESSES', '2')
    monkeypatch.setenv('JAX_PROCESS_ID', '0')
    with pytest.raises(RuntimeError):
        kmesh.maybe_initialize_distributed(retry=False)
    assert resilience.counters.get('dist_init_retries') == 0


# ---------------------------------------------------------------------------
# world stamp (elastic resume routing)
# ---------------------------------------------------------------------------

def test_world_stamp_roundtrip_and_absence(tmp_path):
    assert checkpoint.read_world_stamp(tmp_path) is None
    checkpoint.write_world_stamp(tmp_path, 4)
    assert checkpoint.read_world_stamp(tmp_path) == 4
    checkpoint.write_world_stamp(tmp_path, 2)  # overwrite on shrink
    assert checkpoint.read_world_stamp(tmp_path) == 2
    # corrupt stamp reads as "no stamp" (same-world resume), not a crash
    (tmp_path / 'world.json').write_text('not json')
    assert checkpoint.read_world_stamp(tmp_path) is None


# ---------------------------------------------------------------------------
# pod supervisor (fast paths; the real two-process SIGKILL drill is in
# tests/test_pod_chaos.py behind -m slow)
# ---------------------------------------------------------------------------

def test_pod_supervisor_clean_exit_writes_incident(tmp_path):
    import json
    from kfac_pytorch_tpu.resilience.elastic import PodSupervisor
    sup = PodSupervisor([sys.executable, '-c', 'pass'], host_id=0,
                        num_hosts=1, lease_dir=str(tmp_path / 'lease'),
                        max_restarts=2, backoff_base=0.01,
                        poll_period=0.02)
    assert sup.run() == 0
    report = json.loads(
        (tmp_path / 'lease' / 'incident-host0.json').read_text())
    assert report['host_id'] == 0
    assert report['gave_up'] is False
    kinds = [e['kind'] for e in report['events']]
    assert 'launch' in kinds and 'trainer_exit' in kinds


def test_pod_supervisor_crash_loop_gives_up_with_incident(tmp_path):
    import json
    from kfac_pytorch_tpu.resilience.elastic import PodSupervisor
    sup = PodSupervisor([sys.executable, '-c', 'import sys;sys.exit(3)'],
                        host_id=0, num_hosts=1,
                        lease_dir=str(tmp_path / 'lease'),
                        max_restarts=1, backoff_base=0.01,
                        poll_period=0.02, rng=random.Random(0))
    assert sup.run() == 3
    assert sup.crashes == 2 and sup.restarts == 1
    report = json.loads(
        (tmp_path / 'lease' / 'incident-host0.json').read_text())
    assert report['gave_up'] is True
    assert report['counters']['crashes'] == 2


def test_pod_supervisor_substitutes_world_placeholders(tmp_path):
    from kfac_pytorch_tpu.resilience.elastic import PodSupervisor
    sup = PodSupervisor(['trainer', '--host-id', '{host_id}',
                         '--num-hosts', '{num_hosts}', '--tag',
                         'gen{gen}', '--plain'],
                        host_id=2, num_hosts=3,
                        lease_dir=str(tmp_path / 'lease'))
    assert sup._child_argv() == ['trainer', '--host-id', '2',
                                 '--num-hosts', '3', '--tag', 'gen0',
                                 '--plain']
    # after a (simulated) shrink the rank and world re-derive
    sup.members = [1, 2]
    sup.gen = 1
    assert sup._child_argv() == ['trainer', '--host-id', '1',
                                 '--num-hosts', '2', '--tag', 'gen1',
                                 '--plain']
    env = sup._child_env()
    assert env['JAX_PROCESS_ID'] == '1'
    assert env['JAX_NUM_PROCESSES'] == '2'
    assert env['KFAC_POD_GEN'] == '1'
    assert env['KFAC_HB_HOST'] == '1'
    assert env['KFAC_HB_HOSTS'] == '2'
    assert env['KFAC_HB_DIR'].endswith('trainer-gen1')


def test_pod_supervisor_clears_stale_protocol_files_at_startup(tmp_path):
    """A pod restart reuses the lease dir (the runbook): stale shrink
    claims and heartbeat leases from the previous incarnation must be
    scrubbed at generation 0, or every healthy host would read "peers
    are shrinking around me" and fence itself."""
    import json
    from kfac_pytorch_tpu.resilience.elastic import PodSupervisor
    lease = tmp_path / 'lease'
    # previous incarnation's debris: a completed shrink + old leases
    (lease / 'shrink-gen1').mkdir(parents=True)
    (lease / 'shrink-gen1' / 'survivor-1.json').write_text(
        '{"host": 1, "addr": null}')
    (lease / 'sup').mkdir()
    (lease / 'sup' / 'hb-1.json').write_text(
        '{"host": 1, "seq": 900, "pid": 1}')
    (lease / 'trainer-gen0').mkdir()
    (lease / 'incident-host1.json').write_text('{}')  # artifact: kept
    sup = PodSupervisor([sys.executable, '-c', 'pass'], host_id=0,
                        num_hosts=1, lease_dir=str(lease),
                        max_restarts=1, backoff_base=0.01,
                        poll_period=0.02)
    assert sup.run() == 0  # no self-fence, clean completion
    assert not (lease / 'shrink-gen1').exists()
    assert not (lease / 'sup' / 'hb-1.json').exists()
    assert (lease / 'incident-host1.json').exists()
    report = json.loads((lease / 'incident-host0.json').read_text())
    assert not any(e['kind'] == 'fenced' for e in report['events'])


def test_pod_supervisor_stop_rc_propagates(tmp_path):
    from kfac_pytorch_tpu.resilience.elastic import PodSupervisor
    sup = PodSupervisor([sys.executable, '-c', 'import sys;sys.exit(7)'],
                        host_id=0, num_hosts=1,
                        lease_dir=str(tmp_path / 'lease'),
                        max_restarts=5, stop_rcs=(7,),
                        backoff_base=0.01, poll_period=0.02)
    assert sup.run() == 7
    assert sup.restarts == 0


def test_pod_supervisor_suspend_request_stops_trainer_rc119(tmp_path):
    """The scheduler's checkpoint-suspend lane: a ``suspend.json``
    marker landing in the lease namespace mid-run stops the (healthy)
    trainer at the boundary and exits RC_SUSPENDED — a verdict the
    scheduler asked for, never charged as a crash."""
    import json
    from kfac_pytorch_tpu.resilience.elastic import (PodSupervisor,
                                                     RC_SUSPENDED)
    lease = tmp_path / 'lease'
    # the trainer itself delivers the request once it is running (the
    # gen-0 scrub would eat a marker planted before launch — see the
    # stale-marker test below), then sleeps until SIGTERMed
    child = [sys.executable, '-c',
             'import json, os, sys, time\n'
             'with open(os.path.join(sys.argv[1], "suspend.json"), '
             '"w") as f:\n'
             '    json.dump({"job": 1, "reason": "preempt", '
             '"by": 2}, f)\n'
             'time.sleep(600)\n', str(lease)]
    sup = PodSupervisor(child, host_id=0, num_hosts=1,
                        lease_dir=str(lease), max_restarts=1,
                        backoff_base=0.01, poll_period=0.02,
                        hb_interval=0.05)
    assert sup.run() == RC_SUSPENDED
    assert sup.crashes == 0 and sup.restarts == 0  # not budgeted
    report = json.loads((lease / 'incident-host0.json').read_text())
    kinds = [e['kind'] for e in report['events']]
    assert 'suspended' in kinds
    assert not any(k in kinds for k in ('fenced', 'crash'))
    assert report['counters'].get('suspended') == 1


def test_pod_supervisor_scrubs_stale_suspend_marker_at_startup(tmp_path):
    """A resume reuses the job's lease dir: a suspend request left over
    from the PREVIOUS life (the scheduler's delete was lost) must be
    scrubbed at generation 0, or the freshly resumed pod would
    re-suspend the moment its suspend lane first polls."""
    import json
    from kfac_pytorch_tpu.resilience.elastic import PodSupervisor
    lease = tmp_path / 'lease'
    lease.mkdir()
    (lease / 'suspend.json').write_text(
        '{"job": 1, "reason": "preempt"}')
    sup = PodSupervisor([sys.executable, '-c', 'import time; '
                         'time.sleep(0.5)'],
                        host_id=0, num_hosts=1, lease_dir=str(lease),
                        max_restarts=1, backoff_base=0.01,
                        poll_period=0.02, hb_interval=0.05)
    assert sup.run() == 0          # the stale request never re-fires
    assert not (lease / 'suspend.json').exists()
    report = json.loads((lease / 'incident-host0.json').read_text())
    assert not any(e['kind'] == 'suspended' for e in report['events'])


# ---------------------------------------------------------------------------
# pod supervisor GROW lane (join announcements, grow barrier, --join
# mode; the real 3-host churn drill is in tests/test_pod_chaos.py
# behind -m slow)
# ---------------------------------------------------------------------------

def _world_gated_trainer(tmp_path, exit_world):
    """A trainer that finishes (rc 0) only at the given world size and
    sleeps otherwise — the first generation runs until the supervisor
    stops it for the grow, the enlarged generation exits clean."""
    trainer = tmp_path / 'trainer.py'
    trainer.write_text(
        'import sys, time\n'
        f'if sys.argv[1] != {str(exit_world)!r}:\n'
        '    time.sleep(600)\n')
    return [sys.executable, str(trainer), '{num_hosts}']


def test_pod_supervisor_grow_admits_announced_joiner(tmp_path):
    """The incumbent side of the rejoin protocol: a join announcement
    appears, the supervisor stops its (healthy) trainer at the next
    boundary, runs the grow barrier with the joiner's claim, and
    relaunches at the enlarged world/generation — none of it charged to
    the crash budget."""
    import json
    import threading
    from kfac_pytorch_tpu.resilience.elastic import PodSupervisor
    from kfac_pytorch_tpu.resilience.heartbeat import JoinAnnouncer
    lease = tmp_path / 'lease'
    sup = PodSupervisor(_world_gated_trainer(tmp_path, '2'),
                        host_id=0, num_hosts=1, lease_dir=str(lease),
                        max_restarts=1, backoff_base=0.01,
                        settle=0.2, grow_timeout=5.0,
                        poll_period=0.02, child_kill_grace=1.0)

    def joiner():
        # keep announcing (the real JoinAnnouncer republishes too —
        # the supervisor's gen-0 scrub may eat an announcement that
        # landed before startup), then claim into the barrier once the
        # incumbent opens it
        import time
        ann = JoinAnnouncer(lease, 1, addr='hostb:8476')
        deadline = time.monotonic() + 10
        claim_dir = lease / 'grow-gen1'
        while time.monotonic() < deadline:
            ann.announce()
            if (claim_dir / 'member-0.json').exists():
                resilience.atomic_write_json(
                    str(claim_dir / 'member-1.json'),
                    {'host': 1, 'addr': 'hostb:8476'})
                return
            time.sleep(0.02)

    t = threading.Thread(target=joiner)
    t.start()
    try:
        rc = sup.run()
    finally:
        t.join()
    assert rc == 0
    assert sup.members == [0, 1] and sup.gen == 1
    assert sup.grows == 1 and sup.crashes == 0 and sup.hangs == 0
    assert sup._member_addrs[1] == 'hostb:8476'
    # the announcement was consumed — a later death of host 1 cannot
    # replay it into a spurious grow
    assert not (lease / 'join-1.json').exists()
    report = json.loads((lease / 'incident-host0.json').read_text())
    kinds = [e['kind'] for e in report['events']]
    assert 'grow' in kinds and 'fenced' not in kinds
    grow = next(e for e in report['events'] if e['kind'] == 'grow')
    assert grow['from'] == 1 and grow['to'] == 2
    assert grow['joiners'] == [1] and grow['gen'] == 1
    exits = [e for e in report['events'] if e['kind'] == 'trainer_exit']
    assert any(e.get('reason') == 'grow' for e in exits), exits
    assert report['grows'][0]['to'] == 2
    assert report['counters']['grows'] == 1


def test_pod_supervisor_stale_join_announcement_aborts_grow(tmp_path):
    """A join-*.json left by a previous life (its announcer never
    claims) must not churn the pod: the barrier times out, the grow
    aborts at the SAME membership and generation, the stale file is
    scrubbed, and the relaunched trainer finishes — no livelock on the
    supervisor's own lingering claims."""
    import json
    from kfac_pytorch_tpu.resilience.elastic import PodSupervisor
    lease = tmp_path / 'lease'
    lease.mkdir()
    resilience.atomic_write_json(str(lease / 'join-1.json'),
                                 {'host': 1, 'addr': None})
    sup = PodSupervisor([sys.executable, '-c', 'import time;time.sleep(1)'],
                        host_id=0, num_hosts=1, lease_dir=str(lease),
                        max_restarts=1, backoff_base=0.01,
                        settle=0.1, grow_timeout=0.5,
                        poll_period=0.02, child_kill_grace=1.0)
    # NOTE: _clear_stale_protocol_files scrubs gen-0 join debris at
    # startup, which already defuses this scenario — drop the file
    # AFTER construction but impersonate mid-run appearance by writing
    # it again once run() starts via a pre-cleared dir: simplest is to
    # re-create it post-scrub from the popen hook
    real_popen = sup.popen
    wrote = []

    def popen_hook(argv, **kw):
        if not wrote:
            wrote.append(1)
            resilience.atomic_write_json(str(lease / 'join-1.json'),
                                         {'host': 1, 'addr': None})
        return real_popen(argv, **kw)

    sup.popen = popen_hook
    assert sup.run() == 0
    assert sup.members == [0] and sup.gen == 0 and sup.grows == 0
    assert not (lease / 'join-1.json').exists()
    # the whole barrier dir went with the abort: a later REAL joiner
    # baselines on the highest grow-gen dir it sees, and a leftover
    # aborted dir would make this very generation unjoinable
    assert not (lease / 'grow-gen1').exists()
    report = json.loads((lease / 'incident-host0.json').read_text())
    kinds = [e['kind'] for e in report['events']]
    assert 'grow_aborted' in kinds and 'grow' not in kinds
    assert 'fenced' not in kinds


def test_pod_supervisor_grow_succeeds_after_aborted_attempt(tmp_path):
    """Abort-then-rejoin regression (review finding): a stale-join
    abort at gen g+1 must not poison a LATER real join at the same
    generation — the barrier dir is removed with the abort, so the
    real joiner's startup baseline excludes it and both sides reopen
    gen g+1 cleanly."""
    from kfac_pytorch_tpu.resilience.elastic import PodSupervisor
    lease = tmp_path / 'lease'
    sup = PodSupervisor(['t'], host_id=0, num_hosts=1,
                        lease_dir=str(lease), settle=0.05,
                        grow_timeout=0.3, poll_period=0.02)
    # stale announcement: nobody claims -> abort, dir scrubbed
    resilience.atomic_write_json(str(lease / 'join-9.json'),
                                 {'host': 9, 'addr': None})
    assert sup._grow(sup._join_announced()) is False
    assert sup.gen == 0 and not (lease / 'grow-gen1').exists()
    # real join at the SAME generation: joiner claims concurrently
    import threading

    def joiner_claims():
        import time as _t
        deadline = _t.monotonic() + 5
        while _t.monotonic() < deadline:
            if (lease / 'grow-gen1' / 'member-0.json').exists():
                resilience.atomic_write_json(
                    str(lease / 'grow-gen1' / 'member-1.json'),
                    {'host': 1, 'addr': None})
                return
            _t.sleep(0.01)

    resilience.atomic_write_json(str(lease / 'join-1.json'),
                                 {'host': 1, 'addr': None})
    t = threading.Thread(target=joiner_claims)
    t.start()
    try:
        assert sup._grow(sup._join_announced()) is True
    finally:
        t.join()
    assert sup.members == [0, 1] and sup.gen == 1


def test_grow_abort_on_partial_claims_never_adopts_subset(tmp_path):
    """Review finding: a straggler incumbent racing a peer's
    abort-cleanup can read an emptied barrier dir — its claims then
    contain only itself, and the abort guard must treat ANY subset of
    the current membership as an abort, never as a 'grow' down to a
    singleton that split-brains the pod."""
    from kfac_pytorch_tpu.resilience.elastic import PodSupervisor
    lease = tmp_path / 'lease'
    sup = PodSupervisor(['t'], host_id=0, num_hosts=2,
                        lease_dir=str(lease), settle=0.05,
                        grow_timeout=0.3, poll_period=0.02)
    # ghost announcement, peer 1 never claims either (its abort already
    # scrubbed the dir): our claims come back as just ourselves
    resilience.atomic_write_json(str(lease / 'join-9.json'),
                                 {'host': 9, 'addr': None})
    assert sup._grow(sup._join_announced()) is False
    assert sup.members == [0, 1] and sup.gen == 0 and sup.grows == 0


def test_grow_yields_to_concurrent_shrink_at_same_generation(tmp_path):
    """Review finding: a join announcement racing an unconfirmed peer
    death can put peers in the shrink barrier for gen g+1 while we sit
    in the grow one. The shrink lane wins: the grow abandons, our grow
    claim is withdrawn (a waiting joiner must not stabilize on it),
    and the generation does not move."""
    import json
    from kfac_pytorch_tpu.resilience.elastic import PodSupervisor
    lease = tmp_path / 'lease'
    sup = PodSupervisor(['t'], host_id=0, num_hosts=2,
                        lease_dir=str(lease), settle=0.05,
                        grow_timeout=5.0, poll_period=0.02)
    (lease / 'shrink-gen1').mkdir(parents=True)
    resilience.atomic_write_json(
        str(lease / 'shrink-gen1' / 'survivor-1.json'),
        {'host': 1, 'addr': None})
    resilience.atomic_write_json(str(lease / 'join-3.json'),
                                 {'host': 3, 'addr': None})
    assert sup._grow(sup._join_announced()) is False
    assert sup.gen == 0 and sup.grows == 0
    assert not (lease / 'grow-gen1' / 'member-0.json').exists()
    events = [e['kind'] for e in sup.report.events]
    assert 'grow_yielded' in events and 'grow' not in events


def test_wait_child_idle_lane_reads_are_o_changes(tmp_path):
    """Watch-driven settle regression: a HEALTHY pod's supervisor loop
    must not re-scan the shrink/grow/join/suspend lanes on every child
    poll — the decoded reads are gated on the backend's change feeds,
    so dozens of idle iterations cost one baseline scan, and a single
    key write (here: a join announcement) triggers exactly one more
    round. hb_interval is set far beyond the test so the OLD paced
    path could never have seen the announcement — reacting to it at
    all proves the lanes now ride the watch, and the read counter
    proves the idle cost is O(changes), not O(polls)."""
    from kfac_pytorch_tpu import coord as coord_mod
    from kfac_pytorch_tpu.resilience.elastic import PodSupervisor

    class CountingCoord:
        """Counts the DECODED protocol reads (the expensive scans the
        watch gate exists to skip); watch/get_many_versioned pass
        through to the inner backend uncounted."""

        def __init__(self, inner):
            self._inner = inner
            self.reads = 0

        def get(self, key):
            self.reads += 1
            return self._inner.get(key)

        def get_many(self, prefix):
            self.reads += 1
            return self._inner.get_many(prefix)

        def __getattr__(self, name):
            return getattr(self._inner, name)

    lease = tmp_path / 'lease'
    lease.mkdir()

    class FakeChild:
        """Stays alive for many supervisor polls, announces a joiner
        partway through, and exits late as a safety valve (reaching
        the valve means the watch never delivered — the reason
        assertion below then fails loudly instead of hanging)."""

        def __init__(self):
            self.polls = 0

        def poll(self):
            self.polls += 1
            if self.polls == 30:
                resilience.atomic_write_json(
                    str(lease / 'join-7.json'), {'host': 7, 'addr': None})
            return 0 if self.polls >= 400 else None

        def wait(self):
            return 0

        def terminate(self):
            self.polls = 10 ** 6

        def kill(self):
            self.polls = 10 ** 6

    counting = CountingCoord(coord_mod.backend_from_env(str(lease)))
    sup = PodSupervisor(['t'], host_id=0, num_hosts=2,
                        lease_dir=str(lease), poll_period=0.005,
                        hb_interval=300.0, coord=counting)
    sup.child = FakeChild()
    rc, reason = sup._wait_child()
    assert reason == 'grow' and rc == 0
    # many idle iterations actually happened...
    assert sup.child.polls >= 25
    # ...but only two read rounds: the first-iteration baseline (4
    # reads: shrink claims, suspend marker, join announcements, grow
    # claims) and the announcement-triggered round. Headroom to 10 so
    # an extra lane read is a tweak, not a flake; the old per-poll
    # shrink scan alone would exceed it several times over.
    assert counting.reads <= 10, counting.reads


def test_join_timeout_withdraws_orphan_barrier_claim(tmp_path):
    """Review finding: a joiner that claimed into a barrier but timed
    out before admission must take its claim back out — the incumbents
    would otherwise count a host that already exited and grow a
    membership with a permanently missing rank."""
    import threading
    import time as _t
    from kfac_pytorch_tpu.resilience.elastic import (
        RC_JOIN_FAILED, PodSupervisor)
    lease = tmp_path / 'lease'
    sup = PodSupervisor(['t'], host_id=1, num_hosts=3,
                        lease_dir=str(lease), join=True,
                        join_timeout=3.0, settle=0.05,
                        grow_timeout=60.0, poll_period=0.02)
    claim_dir = lease / 'grow-gen1'

    def open_barrier():
        # the barrier opens AFTER the joiner's baseline snapshot, with
        # a claim naming a member that never arrives — the joiner
        # claims, waits for coverage, and times out unadmitted
        _t.sleep(0.3)
        claim_dir.mkdir(parents=True)
        resilience.atomic_write_json(
            str(claim_dir / 'member-0.json'),
            {'host': 0, 'addr': None, 'members': [0, 2]})

    t = threading.Thread(target=open_barrier)
    t.start()
    try:
        assert sup.run() == RC_JOIN_FAILED
    finally:
        t.join()
    assert (claim_dir / 'member-0.json').exists()  # claimed mid-run
    assert not (claim_dir / 'member-1.json').exists()
    assert not (lease / 'join-1.json').exists()


def test_joiner_reclaims_after_incumbent_abort_at_same_gen(tmp_path):
    """Review finding: if the incumbents abort the barrier (rmtree
    deletes the joiner's claim with it) and re-arm the SAME generation
    on the next announcement, the joiner must notice its claim is gone
    and re-write it — `claimed_gen` alone would skip the re-claim and
    the join could never succeed after one abort."""
    import threading
    import time as _t
    from kfac_pytorch_tpu.resilience.elastic import PodSupervisor
    lease = tmp_path / 'lease'
    sup = PodSupervisor(['t'], host_id=1, num_hosts=2,
                        lease_dir=str(lease), join=True,
                        join_timeout=15.0, settle=0.2,
                        grow_timeout=10.0, poll_period=0.02,
                        hb_interval=0.05)
    claim_dir = lease / 'grow-gen1'

    def incumbent():
        import shutil
        from kfac_pytorch_tpu.resilience.heartbeat import (
            read_join_announcements)
        deadline = _t.monotonic() + 10
        # the barrier opens only AFTER the announcement (the real flow;
        # also guarantees the joiner snapshotted its baseline first)
        while _t.monotonic() < deadline:
            if read_join_announcements(lease):
                break
            _t.sleep(0.01)
        # open the barrier, wait for the joiner's claim...
        claim_dir.mkdir(parents=True)
        while _t.monotonic() < deadline:
            if (claim_dir / 'member-1.json').exists():
                break
            _t.sleep(0.01)
        # ...abort: the whole dir goes, the joiner's claim with it...
        shutil.rmtree(claim_dir, ignore_errors=True)
        _t.sleep(0.3)
        # ...then re-arm the SAME generation and admit (exist_ok: the
        # joiner's own re-claim may have re-created the dir already —
        # the real _grow uses makedirs(exist_ok=True) too)
        claim_dir.mkdir(parents=True, exist_ok=True)
        resilience.atomic_write_json(
            str(claim_dir / 'member-0.json'),
            {'host': 0, 'addr': None, 'members': [0]})

    t = threading.Thread(target=incumbent)
    t.start()
    try:
        assert sup._join_pod() is True
    finally:
        t.join()
    assert sup.members == [0, 1] and sup.gen == 1
    assert (claim_dir / 'member-1.json').exists()  # the re-claim


def test_joiner_waits_for_slow_incumbent_named_in_claims(tmp_path):
    """Review finding: the joiner must adopt the SAME membership the
    incumbents' barrier closes with. Incumbent claims publish their
    membership; a joiner seeing claims {fast incumbent, itself} stable
    must keep waiting for the slow incumbent those claims name."""
    import threading
    import time as _t
    from kfac_pytorch_tpu.resilience.elastic import PodSupervisor
    lease = tmp_path / 'lease'
    lease.mkdir()
    sup = PodSupervisor(['t'], host_id=3, num_hosts=4,
                        lease_dir=str(lease), join=True,
                        join_timeout=15.0, settle=0.2,
                        grow_timeout=10.0, poll_period=0.02)

    def incumbents():
        deadline = _t.monotonic() + 10
        from kfac_pytorch_tpu.resilience.heartbeat import (
            read_join_announcements)
        while _t.monotonic() < deadline:
            if read_join_announcements(lease):
                break
            _t.sleep(0.01)
        claim_dir = lease / 'grow-gen1'
        claim_dir.mkdir()
        # fast incumbent claims immediately, naming BOTH incumbents
        resilience.atomic_write_json(
            str(claim_dir / 'member-0.json'),
            {'host': 0, 'addr': None, 'members': [0, 2]})
        # slow incumbent (child_kill_grace-style delay, > settle)
        _t.sleep(1.0)
        resilience.atomic_write_json(
            str(claim_dir / 'member-2.json'),
            {'host': 2, 'addr': None, 'members': [0, 2]})

    t = threading.Thread(target=incumbents)
    t.start()
    try:
        assert sup._join_pod() is True
    finally:
        t.join()
    # adopted the FULL membership, not the stable-but-partial prefix
    assert sup.members == [0, 2, 3] and sup.gen == 1


def test_pod_supervisor_join_mode_admitted(tmp_path):
    """The joiner side: --join announces, waits for the incumbents'
    barrier, claims into it, adopts the agreed membership/generation,
    and only then launches its trainer as a member."""
    import json
    import threading
    import time as _time
    from kfac_pytorch_tpu.resilience.elastic import PodSupervisor
    from kfac_pytorch_tpu.resilience.heartbeat import (
        read_join_announcements)
    lease = tmp_path / 'lease'
    lease.mkdir()
    sup = PodSupervisor(_world_gated_trainer(tmp_path, '2'),
                        host_id=1, num_hosts=2, lease_dir=str(lease),
                        join=True, join_timeout=10.0,
                        max_restarts=1, backoff_base=0.01,
                        settle=0.2, poll_period=0.02,
                        child_kill_grace=1.0, hb_grace=60.0)

    def incumbent():
        deadline = _time.monotonic() + 10
        while _time.monotonic() < deadline:
            if read_join_announcements(lease):
                break
            _time.sleep(0.02)
        claim_dir = lease / 'grow-gen1'
        claim_dir.mkdir()
        resilience.atomic_write_json(str(claim_dir / 'member-0.json'),
                                     {'host': 0, 'addr': 'hosta:8476',
                                      'members': [0]})

    t = threading.Thread(target=incumbent)
    t.start()
    try:
        rc = sup.run()
    finally:
        t.join()
    assert rc == 0
    assert sup.members == [0, 1] and sup.gen == 1 and sup.joins == 1
    assert sup._member_addrs[0] == 'hosta:8476'
    assert not (lease / 'join-1.json').exists()  # withdrawn on admission
    report = json.loads((lease / 'incident-host1.json').read_text())
    kinds = [e['kind'] for e in report['events']]
    assert 'join_announce' in kinds and 'join_admitted' in kinds
    admitted = next(e for e in report['events']
                    if e['kind'] == 'join_admitted')
    assert admitted['members'] == [0, 1] and admitted['rank'] == 1
    assert report['counters']['joins'] == 1


def test_pod_supervisor_join_timeout_exits_116(tmp_path):
    import json
    from kfac_pytorch_tpu.resilience.elastic import (
        RC_JOIN_FAILED, PodSupervisor)
    lease = tmp_path / 'lease'
    sup = PodSupervisor([sys.executable, '-c', 'pass'],
                        host_id=1, num_hosts=2, lease_dir=str(lease),
                        join=True, join_timeout=0.3,
                        settle=0.05, poll_period=0.02)
    assert sup.run() == RC_JOIN_FAILED == 116
    assert not (lease / 'join-1.json').exists()  # withdrawn on give-up
    report = json.loads((lease / 'incident-host1.json').read_text())
    kinds = [e['kind'] for e in report['events']]
    assert 'join_failed' in kinds and 'launch' not in kinds
    assert report['counters']['join_failed'] == 1


def test_pod_supervisor_peer_grow_claims_join_not_fence(tmp_path):
    """The fence-vs-join distinction: an uncorroborated NEXT-generation
    claim set in the shrink lane means we are the one being declared
    dead (fence); the same situation in the GROW lane is an invitation
    — a peer saw an announcement we missed — and we claim into the
    barrier instead of fencing."""
    import json
    from kfac_pytorch_tpu.resilience.elastic import PodSupervisor
    lease = tmp_path / 'lease'
    sup = PodSupervisor(_world_gated_trainer(tmp_path, '3'),
                        host_id=0, num_hosts=2, lease_dir=str(lease),
                        max_restarts=1, backoff_base=0.01,
                        settle=0.2, grow_timeout=5.0, hb_grace=300.0,
                        poll_period=0.02, child_kill_grace=1.0)
    # peer 1 (incumbent) and host 2 (the joiner we never saw announce)
    # have already claimed the next generation's grow barrier
    claim_dir = lease / 'grow-gen1'

    real_popen = sup.popen
    wrote = []

    def popen_hook(argv, **kw):
        if not wrote:  # after the gen-0 scrub, before the first wait
            wrote.append(1)
            claim_dir.mkdir(parents=True)
            resilience.atomic_write_json(str(claim_dir / 'member-1.json'),
                                         {'host': 1, 'addr': None})
            resilience.atomic_write_json(str(claim_dir / 'member-2.json'),
                                         {'host': 2, 'addr': None})
        return real_popen(argv, **kw)

    sup.popen = popen_hook
    assert sup.run() == 0
    assert sup.members == [0, 1, 2] and sup.gen == 1 and sup.grows == 1
    report = json.loads((lease / 'incident-host0.json').read_text())
    kinds = [e['kind'] for e in report['events']]
    assert 'fenced' not in kinds and 'grow' in kinds
    grow = next(e for e in report['events'] if e['kind'] == 'grow')
    assert grow['joiners'] == [1, 2] or grow['joiners'] == [2], grow


def test_pod_supervisor_child_env_tcp_peers(tmp_path):
    """KFAC_HB_TRANSPORT=tcp pass-through: the trainer contract gets a
    peer map re-derived for the CURRENT membership (rank=host:port from
    the claim-published addresses), and falls back to file leases when
    an address is missing."""
    from kfac_pytorch_tpu.resilience import heartbeat as hb_mod
    from kfac_pytorch_tpu.resilience.elastic import PodSupervisor
    base_env = {'PATH': os.environ.get('PATH', ''),
                hb_mod.ENV_TRANSPORT: 'tcp', hb_mod.ENV_PORT: '9000'}
    sup = PodSupervisor(['t'], host_id=2, num_hosts=3,
                        lease_dir=str(tmp_path / 'lease'), env=base_env)
    sup.members = [0, 2]
    sup.gen = 2
    sup._member_addrs = {0: 'h0:8476', 2: 'h2:8476'}
    env = sup._child_env()
    assert env[hb_mod.ENV_TRANSPORT] == 'tcp'
    assert env[hb_mod.ENV_PEERS] == '0=h0:9000,1=h2:9000'
    assert env[hb_mod.ENV_GEN] == '2'
    # missing member address: file-lease fallback, never a stale peer map
    sup._member_addrs = {0: 'h0:8476', 2: None}
    env = sup._child_env()
    assert env[hb_mod.ENV_TRANSPORT] == 'file'
    assert hb_mod.ENV_PEERS not in env
    # generation 0, membership unchanged: the launcher's full-world
    # peer map (KFAC_HB_WORKERS-derived) passes through VERBATIM even
    # though no --host-addr claims exist yet — downgrading a real pod
    # to file leases at launch was the review finding
    launch_env = dict(base_env,
                      **{hb_mod.ENV_PEERS: '0=w0:9000,1=w1:9000,'
                                           '2=w2:9000'})
    sup0 = PodSupervisor(['t'], host_id=1, num_hosts=3,
                         lease_dir=str(tmp_path / 'lease0'),
                         env=launch_env)
    env = sup0._child_env()
    assert env[hb_mod.ENV_TRANSPORT] == 'tcp'
    assert env[hb_mod.ENV_PEERS] == '0=w0:9000,1=w1:9000,2=w2:9000'


def test_guard_final_save_runs_with_watchdog_paused(tmp_path, monkeypatch):
    """The PreemptionGuard grace-window save must not race the step
    watchdog: inside ``paused()`` even a save far exceeding the step
    deadline cannot trip it."""
    import threading
    import time
    monkeypatch.setattr(checkpoint, '_HAS_ORBAX', False)
    tripped = threading.Event()
    wd = StepWatchdog(0.1, action=tripped.set)
    guard = checkpoint.PreemptionGuard()
    try:
        wd.arm()
        os.kill(os.getpid(), signal.SIGTERM)
        assert guard.should_stop()
        with wd.paused():
            time.sleep(0.3)  # a "slow" final save, > deadline
            checkpoint.save_checkpoint(tmp_path, 0, {'w': np.zeros(4)})
        assert not tripped.is_set()
    finally:
        guard.uninstall()
        wd.stop()
    assert (tmp_path / 'checkpoint-0.pkl').exists()


# ---------------------------------------------------------------------------
# quorum-gated shrink + lineage fencing (ISSUE 7: partition tolerance;
# the real 3-host partition drill is in tests/test_pod_chaos.py, -m slow)
# ---------------------------------------------------------------------------

def _quorum_sup(tmp_path, host_id, num_hosts, lease='lease', **kw):
    from kfac_pytorch_tpu.resilience.elastic import PodSupervisor
    kw.setdefault('settle', 0.0)
    kw.setdefault('shrink_timeout', 0.15)
    kw.setdefault('poll_period', 0.01)
    return PodSupervisor(['trainer'], host_id=host_id,
                         num_hosts=num_hosts,
                         lease_dir=str(tmp_path / lease), **kw)


def _plant_claim(tmp_path, gen, host, lease='lease'):
    d = tmp_path / lease / f'shrink-gen{gen}'
    d.mkdir(parents=True, exist_ok=True)
    resilience.atomic_write_json(str(d / f'survivor-{host}.json'),
                                 {'host': host, 'addr': None})


def test_shrink_quorum_minority_fences_instead_of_committing(tmp_path):
    """The 2|1 partition seen from the MINORITY: both peers look dead,
    the barrier closes with a single claimant — a strict minority of
    the generation's membership. The shrink must NOT commit (no rival
    generation, no lineage bump), and the events must carry the
    partition grammar."""
    import json
    sup = _quorum_sup(tmp_path, 0, 3)
    committed = sup._shrink({1: {}, 2: {}})
    assert committed is False
    assert sup.gen == 0 and sup.members == [0, 1, 2]
    assert sup.shrinks == 0
    assert sup._current_lineage() == 0  # a fenced side's lineage freezes
    kinds = [e['kind'] for e in sup.report.events]
    assert 'partition_suspected' in kinds
    assert 'quorum_lost' in kinds
    assert 'shrink' not in kinds
    q = next(e for e in sup.report.events if e['kind'] == 'quorum_lost')
    assert q['claimants'] == [0] and q['membership'] == [0, 1, 2]
    # the dead barrier holds no claim of ours for a healed majority to
    # misread as corroboration
    assert not (tmp_path / 'lease' / 'shrink-gen1'
                / 'survivor-0.json').exists()
    assert sup.report.counters.get('quorum_lost') == 1


def test_shrink_quorum_majority_commits_and_bumps_lineage(tmp_path):
    """The same partition seen from the MAJORITY: two claimants out of
    three members commit, the generation advances, and the lineage
    epoch is persisted for commit fencing."""
    import json
    _plant_claim(tmp_path, 1, 2)
    sup = _quorum_sup(tmp_path, 0, 3)
    assert sup._shrink({1: {}}) is True
    assert sup.members == [0, 2] and sup.gen == 1
    assert sup._current_lineage() == 1
    doc = json.loads((tmp_path / 'lease' / 'lineage.json').read_text())
    assert doc['lineage'] == 1
    # one host lost of three: not a partition-suspicion event
    kinds = [e['kind'] for e in sup.report.events]
    assert 'partition_suspected' not in kinds
    assert 'shrink' in kinds
    sup._hb.stop()


def test_shrink_even_split_tiebreak_lowest_host_side_survives(tmp_path):
    """The 2|2 even split: quorum is exactly half on both sides, and
    the deterministic tiebreak — the side holding the LOWEST live host
    of generation g's membership — must let exactly one side commit.
    The partition matrix (ChaosTransport config injected directly, as
    the drill's env would) keeps each side blind to the other's claims
    even though they share the lease dir."""
    import time
    from kfac_pytorch_tpu.resilience.chaos_net import (
        NetFaultConfig, parse_partition_spec)
    cfg = NetFaultConfig(seed=0,
                         windows=parse_partition_spec('0:100000=0,1|2,3'),
                         t0=time.time())
    # side A = {0, 1} (holds host 0), side B = {2, 3}
    supA = _quorum_sup(tmp_path, 0, 4, net_chaos=cfg)
    supB = _quorum_sup(tmp_path, 2, 4, net_chaos=cfg)
    _plant_claim(tmp_path, 1, 1)   # A's partner already claimed
    _plant_claim(tmp_path, 1, 3)   # B's partner already claimed
    assert supA._shrink({2: {}, 3: {}}) is True
    assert supA.members == [0, 1] and supA.gen == 1
    assert supB._shrink({0: {}, 1: {}}) is False
    assert supB.gen == 0 and supB.members == [0, 1, 2, 3]
    kindsB = [e['kind'] for e in supB.report.events]
    assert 'partition_suspected' in kindsB and 'quorum_lost' in kindsB
    supA._hb.stop()


def test_clean_exit_done_marker_exempts_from_quorum(tmp_path):
    """Graceful completion is not partition evidence: the last live
    host of a winding-down pod must commit its shrink (and finish), not
    fence itself because the majority 'disappeared'."""
    sup = _quorum_sup(tmp_path, 2, 3)
    # hosts 0 and 1 finished and left their done markers
    for h in (0, 1):
        resilience.atomic_write_json(
            str(tmp_path / 'lease' / f'done-{h}.json'),
            {'host': h, 'gen': 0})
    assert sup._shrink({0: {}, 1: {}}) is True
    assert sup.members == [2] and sup.gen == 1
    kinds = [e['kind'] for e in sup.report.events]
    assert 'partition_suspected' not in kinds
    assert 'quorum_lost' not in kinds


def test_pod_supervisor_clean_exit_writes_done_marker(tmp_path):
    from kfac_pytorch_tpu.resilience.elastic import PodSupervisor
    sup = PodSupervisor([sys.executable, '-c', 'pass'], host_id=0,
                        num_hosts=1, lease_dir=str(tmp_path / 'lease'),
                        max_restarts=1, backoff_base=0.01,
                        poll_period=0.02)
    assert sup.run() == 0
    assert (tmp_path / 'lease' / 'done-0.json').exists()


def test_fence_on_uncorroborated_shrink_exits_117(tmp_path):
    """The original fence path (peers shrinking around us, nobody looks
    dead from here) now exits the dedicated RC_FENCED=117 — distinct
    from peer_dead (115) so automation can react differently: heal +
    --join, never blind relaunch."""
    import threading
    from kfac_pytorch_tpu.resilience.elastic import RC_FENCED, PodSupervisor
    lease = tmp_path / 'lease'
    sup = PodSupervisor([sys.executable, '-c',
                         'import time; time.sleep(600)'],
                        host_id=0, num_hosts=2, lease_dir=str(lease),
                        max_restarts=1, backoff_base=0.01,
                        hb_interval=0.05, hb_deadline=0.3,
                        settle=0.05, shrink_timeout=0.5,
                        poll_period=0.02, child_kill_grace=1.0)

    def peer_claims():
        # written AFTER startup (the gen-0 scrub would eat it), while
        # our trainer is healthy: an uncorroborated next-gen claim set
        import time
        time.sleep(0.5)
        _plant_claim(tmp_path, 1, 1)

    t = threading.Thread(target=peer_claims)
    t.start()
    try:
        rc = sup.run()
    finally:
        t.join()
    assert rc == RC_FENCED == 117
    import json
    report = json.loads((lease / 'incident-host0.json').read_text())
    assert report['fenced'] is True
    assert any(e['kind'] == 'fenced' for e in report['events'])


def test_world_stamp_lineage_is_monotonic(tmp_path):
    """Commit fencing at the write site: the stamp carries the lineage
    epoch and refuses to move backward — a fenced fork's straggler
    cannot clobber the surviving lineage's stamp."""
    checkpoint.write_world_stamp(tmp_path, 3, gen=1, lineage=1)
    info = checkpoint.read_world_stamp_info(tmp_path)
    assert info['lineage'] == 1 and info['num_devices'] == 3
    checkpoint.write_world_stamp(tmp_path, 2, gen=2, lineage=2)  # forward
    assert checkpoint.read_world_stamp_info(tmp_path)['lineage'] == 2
    with pytest.raises(checkpoint.StaleLineageError):
        checkpoint.write_world_stamp(tmp_path, 3, gen=1, lineage=1)
    # the refused write left the stamp untouched
    assert checkpoint.read_world_stamp_info(tmp_path)['lineage'] == 2
    # lineage-less writers (pre-elastic runs, KFAC_LINEAGE unset) are
    # exempt: nothing to compare, reference behavior preserved
    checkpoint.write_world_stamp(tmp_path, 4)
    assert 'lineage' not in checkpoint.read_world_stamp_info(tmp_path)


def test_elastic_resume_refuses_abandoned_fork(tmp_path, monkeypatch):
    """Commit fencing at the resume site: a process at lineage L must
    refuse checkpoints stamped with a NEWER lineage — it belongs to a
    fork the pod abandoned, and 'resume then retrain then re-save'
    would clobber the majority's state."""
    monkeypatch.delenv('KFAC_LINEAGE', raising=False)
    checkpoint.write_world_stamp(tmp_path, 2, lineage=3)
    with pytest.raises(checkpoint.StaleLineageError):
        resilience.elastic_resume(tmp_path, 5, None, None,
                                  make_precond=None, lineage=1)
    # same check picks the lineage up from the supervisor's env
    monkeypatch.setenv('KFAC_LINEAGE', '2')
    with pytest.raises(checkpoint.StaleLineageError):
        resilience.elastic_resume(tmp_path, 5, None, None,
                                  make_precond=None)
    # at (or past) the stamp's lineage the path is open again — empty
    # dir, so it just reports nothing restorable
    restored, epoch, old = resilience.elastic_resume(
        tmp_path, 5, None, None, make_precond=None, lineage=3)
    assert restored is None and epoch is None


def test_read_claims_skips_torn_json_and_filters_partition(tmp_path):
    """Protocol-file readers tolerate torn writes (skip-and-retry) and
    honor the partition matrix — a cut host's claims are invisible."""
    import time
    from kfac_pytorch_tpu.resilience.chaos_net import (
        NetFaultConfig, parse_partition_spec)
    cfg = NetFaultConfig(seed=0,
                         windows=parse_partition_spec('0:100000=0|1'),
                         t0=time.time())
    sup = _quorum_sup(tmp_path, 0, 3, net_chaos=cfg)
    d = tmp_path / 'lease' / 'shrink-gen1'
    d.mkdir(parents=True)
    resilience.atomic_write_json(str(d / 'survivor-2.json'),
                                 {'host': 2, 'addr': None})
    resilience.atomic_write_json(str(d / 'survivor-1.json'),
                                 {'host': 1, 'addr': None})
    (d / 'survivor-9.json').write_text('{"host": 9, "ad')  # torn
    claims = sup._read_claims('shrink-gen1')
    assert 2 in claims          # reachable, intact
    assert 1 not in claims      # partitioned away
    assert 9 not in claims      # torn: skipped, not crashed


def test_child_env_exports_lineage_and_idmap(tmp_path):
    from kfac_pytorch_tpu.resilience import chaos_net
    from kfac_pytorch_tpu.resilience.chaos_net import NetFaultConfig
    from kfac_pytorch_tpu.resilience.elastic import ENV_LINEAGE
    sup = _quorum_sup(tmp_path, 0, 3, net_chaos=NetFaultConfig(seed=1))
    sup.members = [0, 2]
    sup.gen = 1
    sup._lineage_mem = 1
    env = sup._child_env()
    assert env[ENV_LINEAGE] == '1'
    # rank->pod-host map: rank 1 is pod host 2 after the shrink
    assert env[chaos_net.ENV_NET_IDMAP] == '0=0,1=2'


def test_lineage_persists_across_supervisor_incarnations(tmp_path):
    """A whole-pod restart reusing the lease dir adopts the previous
    incarnation's lineage (the file survives the gen-0 scrub), so its
    trainers do not read their own checkpoints as 'a newer lineage'."""
    sup = _quorum_sup(tmp_path, 0, 3)
    sup.gen = 1
    sup._bump_lineage()
    sup.gen = 2
    sup._bump_lineage()
    assert sup._current_lineage() == 2
    fresh = _quorum_sup(tmp_path, 0, 3)
    fresh._clear_stale_protocol_files()
    assert (tmp_path / 'lease' / 'lineage.json').exists()
    assert fresh._current_lineage() == 2


def test_two_host_pod_tiebreak_documented_tradeoff(tmp_path):
    """The even-split tiebreak's availability contract, pinned: a
    2-host pod survives the HIGHER host's death (host 0 holds the
    tiebreak and shrinks on) but fences on the lowest host's death —
    from the survivor's side that silence is indistinguishable from a
    partition, and fencing is the only answer that can never fork the
    run."""
    sup0 = _quorum_sup(tmp_path, 0, 2, lease='a')
    assert sup0._shrink({1: {}}) is True
    assert sup0.members == [0] and sup0.gen == 1
    sup1 = _quorum_sup(tmp_path, 1, 2, lease='b')
    assert sup1._shrink({0: {}}) is False
    assert sup1.gen == 0
    assert any(e['kind'] == 'quorum_lost' for e in sup1.report.events)
