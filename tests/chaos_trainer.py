"""Miniature REAL trainer for the supervisor/watchdog/pod chaos drills.

A full example trainer (resnet32) is too slow to relaunch repeatedly in
a test, so this is the smallest program that still exercises every
resilience path end-to-end with the REAL components: TinyCNN + K-FAC
preconditioner, the real ``build_train_step`` (so the env-driven
hang/crash/slow faults fire exactly where they would in production),
per-epoch ``save_checkpoint`` and ``auto_resume`` (so a supervised
relaunch genuinely resumes), the step watchdog, the retrying I/O path,
and the straggler governor.

Pod mode (``--num-hosts N --host-id I``, the peer-death drills): each
host process runs the SAME N-device data-parallel mesh computation on
simulated CPU devices — a stand-in for one slice of a pod that keeps
every pod-level mechanism REAL across processes: the peer heartbeat
(``KFAC_HB_*`` env from the pod supervisor), the ``RC_PEER_DEAD`` abort,
the world stamp next to the checkpoints, and the elastic resume that
reshards the K-FAC factors when a relaunch arrives with a smaller
world. Because every host computes the full (seeded) batch stream, the
step schedule is world-size independent — the DONE line of a shrunken
run must equal an undisturbed one's.

Protocol with tests/test_chaos.py + tests/test_pod_chaos.py (stdout):
  ``EPOCH <e> step=<s> loss=<l>``  after each epoch (post-checkpoint)
  ``RESUMED from=checkpoint-<e> step=<s>``  on any resume
  ``RESHARDED from_world=<o> to_world=<n> step=<s>``  on elastic resume
  ``WORLD_RESCALE from_world=<o> to_world=<n> global_batch=<b> lr=<l>
  lr_factor=<f>``  on elastic resume (the world-change hook fired;
  this trainer's batch stream is global-fixed, so lr_factor is 1 and
  the schedule stays world-size independent — the line PROVES the
  hook ran without perturbing schedule equivalence)
  ``DONE final_step=<s> epochs=<e>``  on clean completion
The DONE line is the schedule-equivalence assertion: a SIGKILLed /
hung / restarted / shrunken run must end with the same line as an
uninterrupted one.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..'))
os.environ.setdefault('JAX_PLATFORMS', 'cpu')
# pod mode runs an N-device mesh inside one process; force enough
# simulated CPU devices BEFORE jax initializes (same trick as conftest)
if '--xla_force_host_platform_device_count' not in \
        os.environ.get('XLA_FLAGS', ''):
    os.environ['XLA_FLAGS'] = (os.environ.get('XLA_FLAGS', '')
                               + ' --xla_force_host_platform_device_count=4')

import jax
import numpy as np
import optax

jax.config.update('jax_platforms', 'cpu')

import kfac_pytorch_tpu as kfac
from kfac_pytorch_tpu import data as kdata
from kfac_pytorch_tpu import resilience, training
from kfac_pytorch_tpu.models.tiny import TinyCNN
from kfac_pytorch_tpu.utils import checkpoint
from kfac_pytorch_tpu.utils.runlog import install_flush_hooks


def main():
    p = argparse.ArgumentParser()
    p.add_argument('--epochs', type=int, default=3)
    p.add_argument('--batch-size', type=int, default=8)
    p.add_argument('--num-examples', type=int, default=32)
    p.add_argument('--checkpoint-dir', required=True)
    p.add_argument('--step-deadline', type=float, default=0)
    p.add_argument('--straggler-budget', type=float, default=0)
    p.add_argument('--io-retries', type=int, default=3)
    p.add_argument('--seed', type=int, default=0)
    # pod mode (resilience/heartbeat.py + elastic.py)
    p.add_argument('--num-hosts', type=int, default=1,
                   help='pod world size: the K-FAC mesh spans this many '
                        'simulated devices; >1 enables the env-driven '
                        'peer heartbeat and world-stamped checkpoints')
    p.add_argument('--host-id', type=int, default=0)
    args = p.parse_args()

    import logging
    logging.basicConfig(level=logging.INFO, format='%(message)s',
                        stream=sys.stdout, force=True)
    install_flush_hooks()
    # structured tracing (KFAC_TRACE_DIR, off by default): the drills'
    # per-host trace JSONL is what kfac-obs merges into the pod timeline
    from kfac_pytorch_tpu.obs import trace as obs_trace
    tracer = obs_trace.install_from_env()

    x, y = kdata.synthetic_classification(
        args.num_examples, (8, 8, 3), 10, seed=args.seed)
    loader = kdata.Loader(x, y, args.batch_size, train=True,
                          seed=args.seed, shard=(0, 1))

    world = max(1, args.num_hosts)
    axis = 'batch' if world > 1 else None
    mesh = None
    if world > 1:
        from jax.sharding import Mesh
        mesh = Mesh(np.array(jax.devices()[:world]), ('batch',))

    def make_precond(nd):
        pre = kfac.KFAC(variant='eigen', lr=0.05, damping=0.003,
                        fac_update_freq=1, kfac_update_freq=2,
                        num_devices=nd,
                        axis_name='batch' if nd > 1 else None)
        return pre

    model = TinyCNN()
    precond = make_precond(world)
    tx = training.sgd(0.05, momentum=0.9)
    state = training.init_train_state(
        model, tx, precond, jax.random.PRNGKey(args.seed),
        np.zeros((args.batch_size, 8, 8, 3), np.float32))

    def make_old_precond(nd):
        # elastic resume: the OLD world's preconditioner over the SAME
        # layer list (the metas the set-up new-world plan discovered)
        pre = make_precond(nd)
        pre.setup(precond.plan.metas)
        return pre

    io_retry = (resilience.RetryPolicy(attempts=args.io_retries + 1,
                                       base_delay=0.05)
                if args.io_retries > 0 else None)

    def on_world_change(ow, nw):
        # the accuracy-preserving hook: this trainer's (seeded) batch
        # stream is GLOBAL-fixed, so the rescale is exactly identity —
        # printing the protocol line proves the hook fired on every
        # shrink/grow without perturbing the DONE-line schedule
        res = training.world_change_rescale(ow, nw, lr=0.05,
                                            global_batch=args.batch_size)
        print(res.log_line(), flush=True)

    start_epoch = 0
    restored, resume, old_world = resilience.elastic_resume(
        args.checkpoint_dir, args.epochs, precond, state,
        make_precond=make_old_precond, retry=io_retry,
        on_world_change=on_world_change)
    if resume is not None:
        state = restored
        start_epoch = resume + 1
        if old_world is not None:
            print(f'RESHARDED from_world={old_world} to_world={world} '
                  f'step={int(state.step)}', flush=True)
        print(f'RESUMED from=checkpoint-{resume} step={int(state.step)}',
              flush=True)

    heartbeat = resilience.heartbeat_from_env()
    if heartbeat is not None:
        heartbeat.start()
    governor = None
    if args.straggler_budget > 0:
        governor = resilience.StragglerGovernor(precond,
                                                args.straggler_budget)
    watchdog = None
    if args.step_deadline > 0:
        watchdog = resilience.StepWatchdog(args.step_deadline)

    def loss_fn(outputs, batch):
        return optax.softmax_cross_entropy_with_integer_labels(
            outputs, batch['label']).mean()

    step = training.build_train_step(model, tx, precond, loss_fn,
                                     axis_name=axis, mesh=mesh,
                                     straggler=governor,
                                     heartbeat=heartbeat, tracer=tracer)
    loss = float('nan')
    for epoch in range(start_epoch, args.epochs):
        for batch in loader.epoch(retry=io_retry):
            if watchdog is not None:
                watchdog.arm(tag=f'step {int(state.step)}')
            state, m = step(state, batch, lr=0.05, damping=0.003)
            loss = float(m['loss'])  # blocking read, inside the deadline
            if watchdog is not None:
                watchdog.disarm()
        checkpoint.save_checkpoint(args.checkpoint_dir, epoch, state,
                                   retry=io_retry)
        # gen is provenance; lineage is PROTOCOL — the stamp refuses to
        # move backward, and elastic_resume refuses a newer-lineage
        # stamp, so a fenced fork can neither resume nor clobber
        checkpoint.write_world_stamp(args.checkpoint_dir, world,
                                     gen=os.environ.get('KFAC_POD_GEN'),
                                     lineage=os.environ.get('KFAC_LINEAGE'))
        print(f'EPOCH {epoch} step={int(state.step)} loss={loss:.4f}',
              flush=True)
        if tracer is not None:
            tracer.flush()
    checkpoint.wait_for_checkpoints()
    if watchdog is not None:
        watchdog.stop()
    if heartbeat is not None:
        heartbeat.stop()
    if tracer is not None:
        tracer.flush()
    print(f'DONE final_step={int(state.step)} epochs={args.epochs}',
          flush=True)


if __name__ == '__main__':
    main()
