"""Supervisor/watchdog chaos drills with REAL subprocesses (``-m slow``).

Each drill runs tests/chaos_trainer.py (a miniature real K-FAC trainer
with per-epoch checkpoints and auto-resume) under the kfac-supervise
restart loop, injects a process-level fault via ``KFAC_FAULT_*`` envs —
a SIGKILL mid-epoch, a step hang — and asserts the supervised run
completes with the SAME final schedule line (``DONE final_step=...``)
as an uninterrupted control run. ``KFAC_FAULT_ONCE_DIR`` makes each
fault fire exactly once across restarts, so the drills are
deterministic; the only real time in play is the generous watchdog
deadline the hang drill must actually exceed.
"""

import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TRAINER = os.path.join(REPO, 'tests', 'chaos_trainer.py')


def _env(**extra):
    """Clean fault env (no stray KFAC_FAULT_* leaks into the strict
    from_env) + forced CPU platform for the subprocesses."""
    base = {k: v for k, v in os.environ.items()
            if not k.startswith('KFAC_FAULT_')}
    base['JAX_PLATFORMS'] = 'cpu'
    base.update(extra)
    return base


def _run(cmd, env, timeout=540):
    p = subprocess.run(cmd, env=env, cwd=REPO, stdout=subprocess.PIPE,
                       stderr=subprocess.STDOUT, text=True,
                       timeout=timeout)
    return p.returncode, p.stdout


def _trainer_cmd(ckpt_dir, *extra):
    return [sys.executable, TRAINER, '--epochs', '3',
            '--checkpoint-dir', str(ckpt_dir), *extra]


def _supervise_cmd(ckpt_dir, *extra, max_restarts=2):
    return [sys.executable, '-m',
            'kfac_pytorch_tpu.resilience.supervisor',
            '--max-restarts', str(max_restarts),
            '--backoff-base', '0.2', '--',
            *_trainer_cmd(ckpt_dir, *extra)]


def _done_line(out):
    lines = [l for l in out.splitlines() if l.startswith('DONE ')]
    assert lines, f'no DONE line; output tail: {out[-3000:]}'
    return lines[-1]


def _control_done(tmp_path):
    rc, out = _run(_trainer_cmd(tmp_path / 'ckpt_control'), _env())
    assert rc == 0, out[-3000:]
    return _done_line(out)


def test_supervisor_resumes_after_sigkill_to_schedule_equivalence(
        tmp_path):
    """SIGKILL the real trainer mid-epoch-1 (env-driven, one-shot across
    restarts): the supervisor observes signal death, relaunches, the
    trainer auto-resumes from checkpoint-0 and completes the SAME epoch
    schedule as an uninterrupted run."""
    control = _control_done(tmp_path)
    env = _env(KFAC_FAULT_CRASH_STEP='5',
               KFAC_FAULT_CRASH_MODE='sigkill',
               KFAC_FAULT_ONCE_DIR=str(tmp_path / 'once'))
    rc, out = _run(_supervise_cmd(tmp_path / 'ckpt'), env)
    assert rc == 0, out[-3000:]
    assert 'killed by signal 9' in out
    assert 'restart 1/2' in out
    assert 'RESUMED from=checkpoint-0' in out
    assert _done_line(out) == control


def test_step_hang_trips_watchdog_dumps_stacks_and_restarts(tmp_path):
    """Hang the real trainer at step 5: the armed watchdog trips within
    its (generous, real) deadline, writes an all-thread stack dump into
    the log, exits rc=114; the supervisor classifies the hang,
    relaunches, and the resumed run completes the control schedule."""
    control = _control_done(tmp_path)
    env = _env(KFAC_FAULT_HANG_STEP='5',
               KFAC_FAULT_ONCE_DIR=str(tmp_path / 'once'))
    rc, out = _run(_supervise_cmd(tmp_path / 'ckpt',
                                  '--step-deadline', '40'), env)
    assert rc == 0, out[-3000:]
    # the watchdog post-mortem made it into the captured run log
    assert 'watchdog: step deadline exceeded' in out
    assert 'MainThread' in out          # the all-thread stack dump
    assert 'maybe_hang' in out          # ...naming the hung frame
    # the supervisor saw the distinct hang rc, not a generic crash
    assert 'hang (watchdog abort)' in out
    assert 'RESUMED from=checkpoint-0' in out
    assert _done_line(out) == control
