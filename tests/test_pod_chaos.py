"""Pod-level chaos drill with REAL processes (``-m slow``).

The acceptance drill for the pod-resilience layer: a two-host pod (one
``kfac-pod-supervise`` + one real mini trainer per host, sharing a
lease directory) loses host 1 to SIGKILL mid-run — the whole process
GROUP dies, exactly like a host vanishing. The survivor must:

- detect the death via the peer HEARTBEAT (within its deadline — not
  via a watchdog timeout: the trainer runs with a deliberately huge
  step deadline and the log must show no watchdog trip),
- abort its trainer with ``RC_PEER_DEAD`` (115),
- run the shrink protocol down to world size 1,
- relaunch, reshard the K-FAC factor state through ``elastic_resume``
  (the ``RESHARDED from_world=2 to_world=1`` protocol line),
- and finish with the SAME ``DONE`` schedule line as an undisturbed
  single-host control run,
- leaving an incident report JSON naming the dead host, the detection
  latency, and the restarts taken.
"""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TRAINER = os.path.join(REPO, 'tests', 'chaos_trainer.py')

HB_DEADLINE = 4.0


def _env(**extra):
    base = {k: v for k, v in os.environ.items()
            if not (k.startswith('KFAC_FAULT_')
                    or k.startswith('KFAC_HB_'))}
    base['JAX_PLATFORMS'] = 'cpu'
    base.update(extra)
    return base


def _done_line(out):
    lines = [l for l in out.splitlines() if l.startswith('DONE ')]
    assert lines, f'no DONE line; output tail: {out[-3000:]}'
    return lines[-1]


def _control_done(tmp_path):
    p = subprocess.run(
        [sys.executable, TRAINER, '--epochs', '3',
         '--checkpoint-dir', str(tmp_path / 'ckpt_control')],
        env=_env(), cwd=REPO, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True, timeout=540)
    assert p.returncode == 0, p.stdout[-3000:]
    return _done_line(p.stdout)


def _pod_cmd(host_id, lease, ckpt_dir):
    return [
        sys.executable, '-m', 'kfac_pytorch_tpu.resilience.elastic',
        '--host-id', str(host_id), '--num-hosts', '2',
        '--lease-dir', str(lease),
        '--max-restarts', '3', '--backoff-base', '0.2',
        '--hb-interval', '0.3', '--hb-deadline', str(HB_DEADLINE),
        '--hb-grace', '180', '--settle', '1', '--shrink-timeout', '8',
        '--',
        sys.executable, TRAINER, '--epochs', '3',
        '--checkpoint-dir', str(ckpt_dir),
        '--num-hosts', '{num_hosts}', '--host-id', '{host_id}',
        '--step-deadline', '300',  # watchdog present but MUST not fire
    ]


def _has_checkpoint(ckpt_dir, epoch=0):
    return (os.path.isdir(os.path.join(ckpt_dir, f'checkpoint-{epoch}'))
            or os.path.exists(os.path.join(ckpt_dir,
                                           f'checkpoint-{epoch}.pkl')))


def test_pod_shrinks_to_survivor_after_host_sigkill(tmp_path):
    control = _control_done(tmp_path)
    lease = tmp_path / 'lease'
    ckpt0, ckpt1 = str(tmp_path / 'ckpt_h0'), str(tmp_path / 'ckpt_h1')
    out0_path = tmp_path / 'host0.out'
    out1_path = tmp_path / 'host1.out'
    # pace every trainer step (the slow-step fault, all steps): the mini
    # trainer's raw epochs are faster than the heartbeat deadline, and a
    # survivor that FINISHES before it can detect the death proves
    # nothing — with ~1.5s/step the remaining schedule is several
    # detection windows long. KFAC_TRACE_DIR: every trainer writes a
    # per-host trace JSONL — the third artifact class kfac-obs merges.
    trace_dir = tmp_path / 'trace'
    pod_env = _env(KFAC_FAULT_SLOW_STEP='0:999',
                   KFAC_FAULT_SLOW_SECS='1.5',
                   KFAC_TRACE_DIR=str(trace_dir))
    procs = []
    try:
        with open(out0_path, 'wb') as f0, open(out1_path, 'wb') as f1:
            for host_id, ckpt, f in ((0, ckpt0, f0), (1, ckpt1, f1)):
                procs.append(subprocess.Popen(
                    _pod_cmd(host_id, lease, ckpt), env=pod_env, cwd=REPO,
                    stdout=f, stderr=subprocess.STDOUT,
                    start_new_session=True))  # its own group == "a host"

            # wait until BOTH hosts banked epoch 0 (resumable state
            # exists and the run is mid-flight), then kill host 1's
            # whole process group — supervisor, trainer, everything
            deadline = time.time() + 420
            while time.time() < deadline:
                if procs[0].poll() is not None or procs[1].poll() is not None:
                    pytest.fail('a pod member exited before the kill; '
                                'host0 tail: '
                                + out0_path.read_text()[-3000:])
                if _has_checkpoint(ckpt0) and _has_checkpoint(ckpt1):
                    break
                time.sleep(0.5)
            else:
                pytest.fail('epoch-0 checkpoints never appeared; host0 '
                            'tail: ' + out0_path.read_text()[-3000:])
            kill_t = time.time()
            os.killpg(os.getpgid(procs[1].pid), signal.SIGKILL)
            procs[1].wait(timeout=30)

            # the survivor must finish the whole schedule on its own
            rc0 = procs[0].wait(timeout=420)
            detect_wall = time.time() - kill_t
    finally:
        for p in procs:
            if p.poll() is None:
                try:
                    os.killpg(os.getpgid(p.pid), signal.SIGKILL)
                except ProcessLookupError:
                    pass

    out0 = out0_path.read_text()
    assert rc0 == 0, out0[-4000:]

    # detection came from the heartbeat, not the (300s) watchdog
    assert 'declared dead' in out0, out0[-4000:]
    assert 'step deadline exceeded' not in out0
    # and it was fast: the whole recover-and-finish took far less wall
    # time than a single watchdog deadline
    assert detect_wall < 300, detect_wall

    # shrink happened and the relaunched trainer resharded the factors
    assert 'elastic: shrinking world 2 -> 1' in out0, out0[-4000:]
    assert 'RESHARDED from_world=2 to_world=1' in out0, out0[-4000:]
    assert 'RESUMED from=checkpoint-' in out0

    # schedule equivalence: same DONE line as the undisturbed control
    assert _done_line(out0) == control

    # incident report: names the dead host, the detection latency, the
    # restarts taken, and the shrink
    report = json.loads((lease / 'incident-host0.json').read_text())
    assert report['host_id'] == 0
    dead = report['what_died']
    assert dead and dead[0]['peer'] == 1, report
    # latency ~ heartbeat deadline (+ poll slack), nowhere near the
    # 300s watchdog deadline
    assert HB_DEADLINE <= dead[0]['detect_s'] < 60, dead
    assert report['restarts_taken'] >= 1
    assert report['shrinks'] and report['shrinks'][0]['from'] == 2
    assert report['shrinks'][0]['to'] == 1
    assert report['gave_up'] is False
    exits = [e for e in report['events'] if e['kind'] == 'trainer_exit']
    from kfac_pytorch_tpu.resilience.heartbeat import RC_PEER_DEAD
    assert any(e.get('rc') == RC_PEER_DEAD for e in exits), exits

    # kfac-obs: ONE clock-aligned pod timeline from the drill's three
    # artifact classes (stdout runlogs, the incident report, the
    # per-host trace JSONL) — the ROADMAP "pod-level timeline" item.
    # Host death, heartbeat detection, shrink and reshard-resume must
    # all be present as events, in causal order on the merged clock.
    import glob

    from kfac_pytorch_tpu.obs import aggregate
    paths = [str(out0_path), str(out1_path),
             str(lease / 'incident-host0.json')]
    traces = sorted(glob.glob(str(trace_dir / '*.jsonl')))
    assert traces, 'trainers wrote no trace JSONL under KFAC_TRACE_DIR'
    timeline = aggregate.build_timeline(paths + traces)
    events = timeline['events']
    kinds = [e['kind'] for e in events]

    def first(kind, **match):
        for i, e in enumerate(events):
            if e['kind'] == kind and all(
                    e['detail'].get(k) == v for k, v in match.items()):
                return i
        raise AssertionError(
            f'{kind} {match or ""} missing from timeline; kinds: '
            f'{sorted(set(kinds))}')

    # the dead host's death + its detection (peer named, latency carried)
    i_dead = first('peer_dead', peer=1)
    detect = events[i_dead]['detail'].get('detect_s')
    assert detect and detect >= HB_DEADLINE, events[i_dead]
    # the survivor's trainer aborting RC_PEER_DEAD (host-death fallout)
    i_exit = first('trainer_exit', rc=RC_PEER_DEAD)
    # the shrink agreement and the resharded resume
    i_shrink = first('shrink')
    i_reshard = first('resharded')
    i_resume = first('resumed')
    assert i_dead < i_shrink < i_reshard, (i_dead, i_shrink, i_reshard)
    assert i_exit < i_shrink
    assert i_reshard <= i_resume
    # clock-aligned: the causally-ordered events carry non-decreasing
    # aligned wall stamps (same machine here — exact clock)
    walls = [events[i]['wall_aligned'] for i in
             (i_dead, i_shrink, i_reshard)]
    assert all(w is not None for w in walls), walls
    assert walls == sorted(walls), walls
    # per-step spans made it into the merged trace artifact
    merged = aggregate.merged_chrome_trace(timeline)
    assert any(e.get('ph') == 'X' and e.get('name') == 'kfac.dispatch'
               for e in merged['traceEvents'])

    # CI artifact export: keep the drill's debris + the aggregated
    # timeline when the workflow asks for it
    art = os.environ.get('KFAC_DRILL_ARTIFACTS')
    if art:
        import shutil
        os.makedirs(art, exist_ok=True)
        for p in paths + traces:
            shutil.copy(p, art)
        with open(os.path.join(art, 'timeline.json'), 'w') as f:
            json.dump({k: v for k, v in timeline.items()
                       if not k.startswith('_')}, f, indent=2,
                      default=str)
        with open(os.path.join(art, 'pod_trace.json'), 'w') as f:
            json.dump(merged, f)
