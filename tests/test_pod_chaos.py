"""Pod-level chaos drills with REAL processes (``-m slow``).

Two acceptance drills for the pod-resilience layer. The SHRINK drill:
a two-host pod (one ``kfac-pod-supervise`` + one real mini trainer per
host, sharing a lease directory) loses host 1 to SIGKILL mid-run — the
whole process GROUP dies, exactly like a host vanishing. The survivor
must:

- detect the death via the peer HEARTBEAT (within its deadline — not
  via a watchdog timeout: the trainer runs with a deliberately huge
  step deadline and the log must show no watchdog trip),
- abort its trainer with ``RC_PEER_DEAD`` (115),
- run the shrink protocol down to world size 1,
- relaunch, reshard the K-FAC factor state through ``elastic_resume``
  (the ``RESHARDED from_world=2 to_world=1`` protocol line),
- and finish with the SAME ``DONE`` schedule line as an undisturbed
  single-host control run,
- leaving an incident report JSON naming the dead host, the detection
  latency, and the restarts taken.

The CHURN drill (ISSUE 6, elastic GROW): a THREE-host pod loses host 1
to SIGKILL, shrinks 3 -> 2, re-admits the repaired host through the
join protocol (``kfac-pod-supervise --join`` announces, the incumbents
run the grow barrier, factor state reshards UP), grows 2 -> 3 — and
survives the whole cycle TWICE, ending schedule-equivalent with
incident JSON recording both shrinks and both grows and a ``kfac-obs``
timeline pinning death -> shrink -> join -> grow in causal clock order.
"""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TRAINER = os.path.join(REPO, 'tests', 'chaos_trainer.py')

HB_DEADLINE = 4.0

#: coordination-backend overlay (the TcpKv drill legs): every process
#: of the drill — supervisors AND trainers — picks the backend and the
#: seeded backend-fault schedule up from these envs
_COORD_OVERLAY = {}


def _env(**extra):
    base = {k: v for k, v in os.environ.items()
            if not (k.startswith('KFAC_FAULT_')
                    or k.startswith('KFAC_HB_')
                    or k.startswith('KFAC_COORD_'))}
    base['JAX_PLATFORMS'] = 'cpu'
    base.update(_COORD_OVERLAY)
    base.update(extra)
    return base


@pytest.fixture
def tcpkv_coord():
    """Run the whole drill on the TCP KV coordination backend with
    seeded backend faults armed: a live kfac-coord-serve store in this
    process, KFAC_COORD_BACKEND=tcp in every child, and mild
    KFAC_FAULT_COORD_* probabilities (high enough that per-op retries
    actually fire over a multi-minute drill, low enough that the
    5-attempt budget keeps give-ups out of a healthy run)."""
    from kfac_pytorch_tpu.coord import TcpKvServer
    srv = TcpKvServer('127.0.0.1', 0)
    # FAIL=0.05 sizes the drill's statistics: the supervisors make a
    # few hundred retried coord ops over the run, so some retries fire
    # with near-certainty (P[none] < 1e-4), while a give-up needs 5
    # consecutive injected failures on one op (~3e-7) — never in a
    # healthy drill
    _COORD_OVERLAY.update({
        'KFAC_COORD_BACKEND': 'tcp',
        'KFAC_COORD_ADDR': f'127.0.0.1:{srv.port}',
        'KFAC_FAULT_COORD_SEED': '5',
        'KFAC_FAULT_COORD_FAIL': '0.05',
        'KFAC_FAULT_COORD_TORN': '0.05',
        'KFAC_FAULT_COORD_STALE': '0.05',
    })
    try:
        yield srv
    finally:
        _COORD_OVERLAY.clear()
        srv.close()


@pytest.fixture
def replicated_coord():
    """Run the whole drill on the REPLICATED (quorum) coordination
    backend: three live KV replicas in this process and
    KFAC_COORD_BACKEND=replicated in every child. No seeded backend
    faults — the disturbance under test is a whole replica dying (the
    tests close servers from this list mid-drill), and the quorum layer
    must absorb exactly one such loss without a single visible
    coordination failure."""
    from kfac_pytorch_tpu.coord import TcpKvServer
    servers = [TcpKvServer('127.0.0.1', 0) for _ in range(3)]
    _COORD_OVERLAY.update({
        'KFAC_COORD_BACKEND': 'replicated',
        'KFAC_COORD_ADDRS': ','.join(
            f'127.0.0.1:{s.port}' for s in servers),
    })
    try:
        yield servers
    finally:
        _COORD_OVERLAY.clear()
        for s in servers:
            s.close()


def _done_line(out):
    lines = [l for l in out.splitlines() if l.startswith('DONE ')]
    assert lines, f'no DONE line; output tail: {out[-3000:]}'
    return lines[-1]


def _control_done(tmp_path):
    p = subprocess.run(
        [sys.executable, TRAINER, '--epochs', '3',
         '--checkpoint-dir', str(tmp_path / 'ckpt_control')],
        env=_env(), cwd=REPO, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True, timeout=540)
    assert p.returncode == 0, p.stdout[-3000:]
    return _done_line(p.stdout)


def _pod_cmd(host_id, lease, ckpt_dir):
    return [
        sys.executable, '-m', 'kfac_pytorch_tpu.resilience.elastic',
        '--host-id', str(host_id), '--num-hosts', '2',
        '--lease-dir', str(lease),
        '--max-restarts', '3', '--backoff-base', '0.2',
        '--hb-interval', '0.3', '--hb-deadline', str(HB_DEADLINE),
        '--hb-grace', '180', '--settle', '1', '--shrink-timeout', '8',
        '--',
        sys.executable, TRAINER, '--epochs', '3',
        '--checkpoint-dir', str(ckpt_dir),
        '--num-hosts', '{num_hosts}', '--host-id', '{host_id}',
        '--step-deadline', '300',  # watchdog present but MUST not fire
    ]


def _has_checkpoint(ckpt_dir, epoch=0):
    return (os.path.isdir(os.path.join(ckpt_dir, f'checkpoint-{epoch}'))
            or os.path.exists(os.path.join(ckpt_dir,
                                           f'checkpoint-{epoch}.pkl')))


def test_pod_shrinks_to_survivor_after_host_sigkill(tmp_path):
    _run_shrink_drill(tmp_path)


def test_pod_shrinks_on_tcpkv_backend_with_coord_faults(tmp_path,
                                                        tcpkv_coord):
    """The same 2-host SIGKILL drill with ZERO shared-filesystem
    coordination: every barrier claim, heartbeat lease, lineage bump
    and join/done marker rides the TCP KV server — wrapped in the
    seeded ChaosBackend, so the whole shrink survives a coordination
    plane that times out, tears and staleness-serves reads — and the
    backend's retries are visible in the incident report."""
    _run_shrink_drill(tmp_path, art_subdir='coord',
                      expect_coord_retries=True)


def test_pod_shrinks_on_replicated_backend_with_replica_kill(
        tmp_path, replicated_coord):
    """The 2-host SIGKILL drill on the QUORUM backend, with a second
    simultaneous failure: the instant host 1's process group dies, one
    of the three KV replicas dies with it. Every barrier claim, lineage
    bump, heartbeat lease and join/done marker of the shrink rides the
    remaining 2/3 majority — the drill must finish exactly like the
    healthy-backend leg, with the replica loss visible only as the
    backend's own replica_down emission, never as a coord retry storm
    or a coord_lost."""
    _run_shrink_drill(
        tmp_path, art_subdir='replicated',
        on_host_kill=lambda: replicated_coord[2].close(),
        expect_replica_down=True)


def test_pod_exits_118_when_replicated_quorum_lost(tmp_path,
                                                   replicated_coord):
    """TRUE quorum loss is loud, never a wedge: with two of three
    replicas dead the majority is gone, every coordination op degrades
    below quorum, the retry budget spends itself, and both supervisors
    exit RC_COORD_LOST (118) with the coord_lost event in the incident
    report — a host that cannot reach a majority must stop deciding
    membership instead of treating the one reachable replica as truth."""
    from kfac_pytorch_tpu.coord import RC_COORD_LOST
    from kfac_pytorch_tpu.resilience.incident import IncidentReport

    lease = tmp_path / 'lease'
    ckpt0, ckpt1 = str(tmp_path / 'ckpt_h0'), str(tmp_path / 'ckpt_h1')
    out0_path = tmp_path / 'host0.out'
    out1_path = tmp_path / 'host1.out'
    # pace the steps (same reasoning as the shrink drill): the schedule
    # must still be mid-flight when the quorum goes away
    pod_env = _env(KFAC_FAULT_SLOW_STEP='0:999',
                   KFAC_FAULT_SLOW_SECS='1.5')
    procs = []
    try:
        with open(out0_path, 'wb') as f0, open(out1_path, 'wb') as f1:
            for host_id, ckpt, f in ((0, ckpt0, f0), (1, ckpt1, f1)):
                procs.append(subprocess.Popen(
                    _pod_cmd(host_id, lease, ckpt), env=pod_env, cwd=REPO,
                    stdout=f, stderr=subprocess.STDOUT,
                    start_new_session=True))
            deadline = time.time() + 420
            while time.time() < deadline:
                if any(p.poll() is not None for p in procs):
                    pytest.fail('a pod member exited before the quorum '
                                'kill; host0 tail: '
                                + out0_path.read_text()[-3000:])
                if _has_checkpoint(ckpt0) and _has_checkpoint(ckpt1):
                    break
                time.sleep(0.5)
            else:
                pytest.fail('epoch-0 checkpoints never appeared; host0 '
                            'tail: ' + out0_path.read_text()[-3000:])
            # kill the MAJORITY — staged, so the runlog tells the whole
            # escalation story: one replica down first (ops succeed on
            # the 2/3 majority and the backend logs quorum DEGRADED),
            # then the second (below quorum: every op fails, quorum
            # LOST). Heartbeat leases publish every 0.3s, so 2s of
            # degraded operation is dozens of successful quorum ops.
            replicated_coord[0].close()
            time.sleep(2.0)
            replicated_coord[1].close()
            rcs = [p.wait(timeout=180) for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                try:
                    os.killpg(os.getpgid(p.pid), signal.SIGKILL)
                except ProcessLookupError:
                    pass

    out0 = out0_path.read_text()
    assert rcs == [RC_COORD_LOST, RC_COORD_LOST], (rcs, out0[-4000:])
    assert 'coordination backend lost' in out0, out0[-4000:]
    # the runlog tells the escalation story the incident grammar
    # scrapes: replica down -> quorum degraded (2/3 window) -> quorum
    # lost -> give-up
    assert 'coord-replicated: quorum lost' in out0, out0[-4000:]
    rep = IncidentReport(host_id=0).scrape_lines(out0.splitlines())
    assert rep.counters.get('replica_down', 0) >= 2, rep.counters
    assert rep.counters.get('quorum_degraded', 0) >= 1, rep.counters
    assert rep.counters.get('coord_lost', 0) >= 1, rep.counters
    # and the incident report names the exit for the operator
    report = json.loads((lease / 'incident-host0.json').read_text())
    lost = [e for e in report['events'] if e['kind'] == 'coord_lost']
    assert lost and lost[0]['rc'] == RC_COORD_LOST, report['events']


def _run_shrink_drill(tmp_path, art_subdir=None,
                      expect_coord_retries=False,
                      on_host_kill=None, expect_replica_down=False):
    control = _control_done(tmp_path)
    lease = tmp_path / 'lease'
    ckpt0, ckpt1 = str(tmp_path / 'ckpt_h0'), str(tmp_path / 'ckpt_h1')
    out0_path = tmp_path / 'host0.out'
    out1_path = tmp_path / 'host1.out'
    # pace every trainer step (the slow-step fault, all steps): the mini
    # trainer's raw epochs are faster than the heartbeat deadline, and a
    # survivor that FINISHES before it can detect the death proves
    # nothing — with ~1.5s/step the remaining schedule is several
    # detection windows long. KFAC_TRACE_DIR: every trainer writes a
    # per-host trace JSONL — the third artifact class kfac-obs merges.
    trace_dir = tmp_path / 'trace'
    pod_env = _env(KFAC_FAULT_SLOW_STEP='0:999',
                   KFAC_FAULT_SLOW_SECS='1.5',
                   KFAC_TRACE_DIR=str(trace_dir))
    procs = []
    try:
        with open(out0_path, 'wb') as f0, open(out1_path, 'wb') as f1:
            for host_id, ckpt, f in ((0, ckpt0, f0), (1, ckpt1, f1)):
                procs.append(subprocess.Popen(
                    _pod_cmd(host_id, lease, ckpt), env=pod_env, cwd=REPO,
                    stdout=f, stderr=subprocess.STDOUT,
                    start_new_session=True))  # its own group == "a host"

            # wait until BOTH hosts banked epoch 0 (resumable state
            # exists and the run is mid-flight), then kill host 1's
            # whole process group — supervisor, trainer, everything
            deadline = time.time() + 420
            while time.time() < deadline:
                if procs[0].poll() is not None or procs[1].poll() is not None:
                    pytest.fail('a pod member exited before the kill; '
                                'host0 tail: '
                                + out0_path.read_text()[-3000:])
                if _has_checkpoint(ckpt0) and _has_checkpoint(ckpt1):
                    break
                time.sleep(0.5)
            else:
                pytest.fail('epoch-0 checkpoints never appeared; host0 '
                            'tail: ' + out0_path.read_text()[-3000:])
            kill_t = time.time()
            if on_host_kill is not None:
                # the replicated leg's second simultaneous failure: a
                # KV replica dies along with the host
                on_host_kill()
            os.killpg(os.getpgid(procs[1].pid), signal.SIGKILL)
            procs[1].wait(timeout=30)

            # the survivor must finish the whole schedule on its own
            rc0 = procs[0].wait(timeout=420)
            detect_wall = time.time() - kill_t
    finally:
        for p in procs:
            if p.poll() is None:
                try:
                    os.killpg(os.getpgid(p.pid), signal.SIGKILL)
                except ProcessLookupError:
                    pass

    out0 = out0_path.read_text()
    assert rc0 == 0, out0[-4000:]

    # detection came from the heartbeat, not the (300s) watchdog
    assert 'declared dead' in out0, out0[-4000:]
    assert 'step deadline exceeded' not in out0
    # and it was fast: the whole recover-and-finish took far less wall
    # time than a single watchdog deadline
    assert detect_wall < 300, detect_wall

    # shrink happened and the relaunched trainer resharded the factors
    assert 'elastic: shrinking world 2 -> 1' in out0, out0[-4000:]
    assert 'RESHARDED from_world=2 to_world=1' in out0, out0[-4000:]
    assert 'RESUMED from=checkpoint-' in out0

    # schedule equivalence: same DONE line as the undisturbed control
    assert _done_line(out0) == control

    # incident report: names the dead host, the detection latency, the
    # restarts taken, and the shrink
    report = json.loads((lease / 'incident-host0.json').read_text())
    assert report['host_id'] == 0
    dead = report['what_died']
    assert dead and dead[0]['peer'] == 1, report
    # latency ~ heartbeat deadline (+ poll slack), nowhere near the
    # 300s watchdog deadline
    assert HB_DEADLINE <= dead[0]['detect_s'] < 60, dead
    assert report['restarts_taken'] >= 1
    assert report['shrinks'] and report['shrinks'][0]['from'] == 2
    assert report['shrinks'][0]['to'] == 1
    assert report['gave_up'] is False
    if expect_coord_retries:
        # the seeded backend faults really fired and the retry layer
        # rode them out: evidence from either host's supervisor log or
        # the incident counters (host 1 dies mid-run but its phase-1
        # retries still count)
        out1 = out1_path.read_text()
        retried = (report['counters'].get('coord_retries', 0)
                   + out0.count('coord: retry')
                   + out1.count('coord: retry'))
        assert retried >= 1, (report['counters'], out0[-1500:])
        assert report['counters'].get('coord_lost', 0) == 0
        assert 'coordination backend lost' not in out0
    if expect_replica_down:
        # the quorum layer NAMED the dead replica in the survivor's
        # runlog — and absorbed it: no give-up, no coord_lost, and the
        # incident grammar picks the emission up as a counter
        from kfac_pytorch_tpu.resilience.incident import IncidentReport
        assert 'coord-replicated: replica' in out0, out0[-4000:]
        rep = IncidentReport(host_id=0).scrape_lines(out0.splitlines())
        assert rep.counters.get('replica_down', 0) >= 1, rep.counters
        assert report['counters'].get('coord_lost', 0) == 0
        assert 'coordination backend lost' not in out0
    exits = [e for e in report['events'] if e['kind'] == 'trainer_exit']
    from kfac_pytorch_tpu.resilience.heartbeat import RC_PEER_DEAD
    # the trainer's own monitor and the supervisor's race to the same
    # detection (same deadline, same silence): the trainer self-aborts
    # RC_PEER_DEAD, or the supervisor confirms first and stops it for
    # the shrink (reason='peer_dead'). Both are the heartbeat path —
    # the watchdog-less 'step deadline' assertion above pins that.
    assert any(e.get('rc') == RC_PEER_DEAD
               or e.get('reason') == 'peer_dead' for e in exits), exits

    # kfac-obs: ONE clock-aligned pod timeline from the drill's three
    # artifact classes (stdout runlogs, the incident report, the
    # per-host trace JSONL) — the ROADMAP "pod-level timeline" item.
    # Host death, heartbeat detection, shrink and reshard-resume must
    # all be present as events, in causal order on the merged clock.
    import glob

    from kfac_pytorch_tpu.obs import aggregate
    paths = [str(out0_path), str(out1_path),
             str(lease / 'incident-host0.json')]
    traces = sorted(glob.glob(str(trace_dir / '*.jsonl')))
    assert traces, 'trainers wrote no trace JSONL under KFAC_TRACE_DIR'
    timeline = aggregate.build_timeline(paths + traces)
    events = timeline['events']
    kinds = [e['kind'] for e in events]

    def first(kind, **match):
        for i, e in enumerate(events):
            if e['kind'] == kind and all(
                    e['detail'].get(k) == v for k, v in match.items()):
                return i
        raise AssertionError(
            f'{kind} {match or ""} missing from timeline; kinds: '
            f'{sorted(set(kinds))}')

    # the dead host's death + its detection (peer named, latency carried)
    i_dead = first('peer_dead', peer=1)
    detect = events[i_dead]['detail'].get('detect_s')
    assert detect and detect >= HB_DEADLINE, events[i_dead]
    # the survivor's trainer going down for the peer death (either its
    # own RC_PEER_DEAD self-abort, or the supervisor confirming first
    # and stopping it — same detection race as the incident assertion)
    i_exit = next(i for i, e in enumerate(events)
                  if e['kind'] == 'trainer_exit'
                  and (e['detail'].get('rc') == RC_PEER_DEAD
                       or e['detail'].get('reason') == 'peer_dead'))
    # the shrink agreement and the resharded resume
    i_shrink = first('shrink')
    i_reshard = first('resharded')
    i_resume = first('resumed')
    assert i_dead < i_shrink < i_reshard, (i_dead, i_shrink, i_reshard)
    assert i_exit < i_shrink
    assert i_reshard <= i_resume
    # clock-aligned: the causally-ordered events carry non-decreasing
    # aligned wall stamps (same machine here — exact clock)
    walls = [events[i]['wall_aligned'] for i in
             (i_dead, i_shrink, i_reshard)]
    assert all(w is not None for w in walls), walls
    assert walls == sorted(walls), walls
    # per-step spans made it into the merged trace artifact
    merged = aggregate.merged_chrome_trace(timeline)
    assert any(e.get('ph') == 'X' and e.get('name') == 'kfac.dispatch'
               for e in merged['traceEvents'])

    # CI artifact export: keep the drill's debris + the aggregated
    # timeline when the workflow asks for it (the TcpKv leg's land
    # under coord/ alongside the posix-backend drills')
    art = os.environ.get('KFAC_DRILL_ARTIFACTS')
    if art:
        import shutil
        if art_subdir:
            art = os.path.join(art, art_subdir)
        os.makedirs(art, exist_ok=True)
        for p in paths + traces:
            shutil.copy(p, art)
        with open(os.path.join(art, 'timeline.json'), 'w') as f:
            json.dump({k: v for k, v in timeline.items()
                       if not k.startswith('_')}, f, indent=2,
                      default=str)
        with open(os.path.join(art, 'pod_trace.json'), 'w') as f:
            json.dump(merged, f)


# ---------------------------------------------------------------------------
# the churn drill: SIGKILL -> shrink(3->2) -> rejoin -> grow(2->3), twice
# ---------------------------------------------------------------------------

CHURN_HB_DEADLINE = 3.0
CHURN_EPOCHS = 16
CHURN_BATCH = 12       # divides worlds 1/2/3 (shard_map needs even shards)
CHURN_EXAMPLES = 72    # 6 steps/epoch


def _churn_trainer_args(ckpt_dir):
    return [sys.executable, TRAINER, '--epochs', str(CHURN_EPOCHS),
            '--batch-size', str(CHURN_BATCH),
            '--num-examples', str(CHURN_EXAMPLES),
            '--checkpoint-dir', str(ckpt_dir),
            '--num-hosts', '{num_hosts}', '--host-id', '{host_id}',
            '--step-deadline', '300']  # watchdog present, must NOT fire


def _churn_cmd(host_id, lease, ckpt_dir, join=False):
    cmd = [sys.executable, '-m', 'kfac_pytorch_tpu.resilience.elastic',
           '--host-id', str(host_id), '--num-hosts', '3',
           '--lease-dir', str(lease),
           '--max-restarts', '6', '--backoff-base', '0.2',
           '--hb-interval', '0.25', '--hb-deadline',
           str(CHURN_HB_DEADLINE),
           '--hb-grace', '300', '--settle', '0.8',
           '--shrink-timeout', '8', '--grow-timeout', '10']
    if join:
        cmd += ['--join', '--join-timeout', '300']
    return cmd + ['--'] + _churn_trainer_args(ckpt_dir)


def _wait_count(path, needle, count, timeout, procs=()):
    """Poll ``path`` until ``needle`` occurs >= ``count`` times; fail
    fast if any of ``procs`` (that should outlive this phase) died."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        text = path.read_text() if path.exists() else ''
        if text.count(needle) >= count:
            return text
        for tag, p in procs:
            if p.poll() is not None:
                pytest.fail(f'{tag} exited rc={p.returncode} while '
                            f'waiting for {needle!r} x{count}; tail: '
                            + text[-3000:])
        time.sleep(0.3)
    pytest.fail(f'{needle!r} x{count} never appeared in {path}; tail: '
                + (path.read_text()[-3000:] if path.exists() else '<none>'))


def _killpg(proc):
    try:
        os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
    except ProcessLookupError:
        pass


def _wait_stamp(ckpt_dir, world, timeout, procs=()):
    """Wait until the checkpoint world stamp says ``world`` — i.e. the
    pod has BANKED an epoch at that world size. The churn only proves an
    upward reshard if the shrunken generation checkpointed before the
    rejoin (the stamp is written after each epoch's save), so each
    cycle gates on it before moving to the next phase."""
    deadline = time.time() + timeout
    path = os.path.join(str(ckpt_dir), 'world.json')
    while time.time() < deadline:
        try:
            with open(path) as f:
                if json.load(f).get('num_devices') == world:
                    return
        except (OSError, ValueError):
            pass
        for tag, p in procs:
            if p.poll() is not None:
                pytest.fail(f'{tag} exited rc={p.returncode} while '
                            f'waiting for world stamp {world}')
        time.sleep(0.3)
    pytest.fail(f'world stamp never became {world} in {path}')


def test_pod_survives_churn_kill_and_rejoin(tmp_path):
    """Train-through-churn: kill -> shrink(3->2) -> rejoin -> grow(2->3),
    twice, schedule-equivalent at DONE with the full death->shrink->
    join->grow story on the merged kfac-obs timeline."""
    # undisturbed single-host control fixes the schedule contract
    p = subprocess.run(
        [sys.executable, TRAINER, '--epochs', str(CHURN_EPOCHS),
         '--batch-size', str(CHURN_BATCH),
         '--num-examples', str(CHURN_EXAMPLES),
         '--checkpoint-dir', str(tmp_path / 'ckpt_control')],
        env=_env(), cwd=REPO, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True, timeout=540)
    assert p.returncode == 0, p.stdout[-3000:]
    control = _done_line(p.stdout)

    lease = tmp_path / 'lease'
    trace_dir = tmp_path / 'trace'
    ckpts = {h: str(tmp_path / f'ckpt_h{h}') for h in range(3)}
    outs = {h: tmp_path / f'host{h}.out' for h in range(3)}
    rejoin_outs = [tmp_path / 'rejoin1.out', tmp_path / 'rejoin2.out']
    # pace steps so every churn phase overlaps live training, never a
    # finished schedule; per-host trace JSONL feeds the timeline merge
    pod_env = _env(KFAC_FAULT_SLOW_STEP='0:999',
                   KFAC_FAULT_SLOW_SECS='1.5',
                   KFAC_TRACE_DIR=str(trace_dir))

    def start(cmd, out_path):
        f = open(out_path, 'wb')
        proc = subprocess.Popen(cmd, env=pod_env, cwd=REPO, stdout=f,
                                stderr=subprocess.STDOUT,
                                start_new_session=True)
        proc._outfile = f
        return proc

    procs = {}
    rejoins = []
    try:
        for h in range(3):
            procs[h] = start(_churn_cmd(h, lease, ckpts[h]), outs[h])

        # epoch 0 banked everywhere: resumable state exists, run is live
        deadline = time.time() + 420
        while time.time() < deadline:
            if any(p.poll() is not None for p in procs.values()):
                pytest.fail('a pod member exited before the first kill; '
                            'host0 tail: ' + outs[0].read_text()[-3000:])
            if all(_has_checkpoint(ckpts[h]) for h in range(3)):
                break
            time.sleep(0.5)
        else:
            pytest.fail('epoch-0 checkpoints never appeared; host0 tail: '
                        + outs[0].read_text()[-3000:])

        survivors = [('host0', procs[0]), ('host2', procs[2])]
        victim = procs[1]
        for cycle in (1, 2):
            # kill the current host-1 incarnation's whole process group
            _killpg(victim)
            victim.wait(timeout=30)
            # survivors agree on the shrink and resume resharded DOWN
            _wait_count(outs[0], 'elastic: shrinking world 3 -> 2',
                        cycle, 240, survivors)
            _wait_count(outs[0], 'RESHARDED from_world=3 to_world=2',
                        cycle, 240, survivors)
            # let the SHRUNKEN generation bank an epoch (stamp -> 2):
            # only then does the grow relaunch genuinely reshard UP —
            # rejoining against a still-3-stamped checkpoint would
            # resume same-world and prove nothing
            _wait_stamp(ckpts[0], 2, 240, survivors)
            # the repaired host comes back through the join protocol
            rejoin = start(_churn_cmd(1, lease, ckpts[1], join=True),
                           rejoin_outs[cycle - 1])
            rejoins.append(rejoin)
            watch = survivors + [(f'rejoin{cycle}', rejoin)]
            _wait_count(outs[0], 'elastic: growing world 2 -> 3',
                        cycle, 300, watch)
            # and the incumbents' trainers reshard UP into the grown pod
            _wait_count(outs[0], 'RESHARDED from_world=2 to_world=3',
                        cycle, 300, watch)
            # grown generation banks an epoch (stamp -> 3) before the
            # next kill, so cycle 2 reshards down from a real world-3
            # checkpoint again
            _wait_stamp(ckpts[0], 3, 300, watch)
            victim = rejoin

        # everyone left finishes the schedule (the end-game may cascade
        # further shrinks as hosts complete at different epochs — that
        # is the elastic layer working, not a failure)
        rc0 = procs[0].wait(timeout=600)
        rc2 = procs[2].wait(timeout=600)
        rcr = rejoins[1].wait(timeout=600)
    finally:
        for proc in list(procs.values()) + rejoins:
            if proc.poll() is None:
                _killpg(proc)
            f = getattr(proc, '_outfile', None)
            if f is not None:
                f.close()

    out0 = outs[0].read_text()
    assert rc0 == 0, out0[-4000:]
    assert rc2 == 0, outs[2].read_text()[-4000:]
    assert rcr == 0, rejoin_outs[1].read_text()[-4000:]

    # detection was heartbeat-speed, never the (300s) watchdog
    assert 'declared dead' in out0
    assert 'step deadline exceeded' not in out0
    # nobody fenced, nobody gave up
    assert 'fenced' not in out0 and 'giving up' not in out0

    # both full churn cycles are in host 0's story
    assert out0.count('elastic: shrinking world 3 -> 2') >= 2
    assert out0.count('elastic: growing world 2 -> 3') >= 2
    assert out0.count('RESHARDED from_world=3 to_world=2') >= 2
    assert out0.count('RESHARDED from_world=2 to_world=3') >= 2
    # the world-change hook fired on every transport, identity rescale
    assert 'WORLD_RESCALE from_world=2 to_world=3' in out0
    assert 'lr_factor=1' in out0
    # the rejoiner announced and was admitted, twice
    for r_out in rejoin_outs:
        text = r_out.read_text()
        assert 'join: host 1 announcing to pod' in text
        assert 'join: admitted into pod' in text, text[-2000:]

    # schedule equivalence across the whole churn
    assert _done_line(out0) == control

    # incident report: both shrinks AND both grows, with the joiner named
    report = json.loads((lease / 'incident-host0.json').read_text())
    assert report['gave_up'] is False
    shrinks = [s for s in report['shrinks'] if s['from'] == 3]
    assert len(shrinks) >= 2, report['shrinks']
    grows = [g for g in report['grows']
             if g['from'] == 2 and g['to'] == 3]
    assert len(grows) >= 2, report['grows']
    assert all(g['joiners'] == [1] for g in grows), grows
    assert report['counters']['grows'] >= 2
    # generations interleave: shrink gen < grow gen < next shrink gen
    gens = [(e['gen'], e['kind']) for e in report['events']
            if e['kind'] in ('shrink', 'grow')]
    assert [k for _, k in gens[:4]] == ['shrink', 'grow', 'shrink',
                                       'grow'], gens
    assert [g for g, _ in gens] == sorted(g for g, _ in gens), gens

    # kfac-obs: ONE clock-aligned timeline from logs + incidents +
    # traces, pinning death -> shrink -> join -> grow causally
    import glob

    from kfac_pytorch_tpu.obs import aggregate
    paths = [str(o) for o in outs.values()]
    paths += [str(o) for o in rejoin_outs]
    paths += sorted(glob.glob(str(lease / 'incident-host*.json')))
    traces = sorted(glob.glob(str(trace_dir / '*.jsonl')))
    assert traces, 'trainers wrote no trace JSONL under KFAC_TRACE_DIR'
    timeline = aggregate.build_timeline(paths + traces)
    events = timeline['events']
    kinds = [e['kind'] for e in events]

    def first(kind, after=0, **match):
        for i in range(after, len(events)):
            e = events[i]
            if e['kind'] == kind and all(
                    e['detail'].get(k) == v for k, v in match.items()):
                return i
        raise AssertionError(
            f'{kind} {match or ""} missing after index {after}; kinds: '
            f'{sorted(set(kinds))}')

    # first cycle in causal order, then the SECOND death strictly after
    # the first grow — the timeline proves churn, not a single incident
    i_dead = first('peer_dead', peer=1)
    i_shrink = first('shrink', after=i_dead)
    i_join = first('join_announce', after=i_shrink)
    i_grow = first('grow', after=i_join)
    i_dead2 = first('peer_dead', after=i_grow, peer=1)
    i_shrink2 = first('shrink', after=i_dead2)
    i_join2 = first('join_announce', after=i_shrink2)
    i_grow2 = first('grow', after=i_join2)
    order = [i_dead, i_shrink, i_join, i_grow,
             i_dead2, i_shrink2, i_join2, i_grow2]
    assert order == sorted(order), order
    walls = [events[i]['wall_aligned'] for i in order]
    assert all(w is not None for w in walls), walls
    assert walls == sorted(walls), walls
    # the upward transports and the rescale hook made the timeline too
    assert 'grow_resharded' in kinds
    assert 'world_rescale' in kinds

    # CI artifact export: keep the churn debris + aggregated timeline
    art = os.environ.get('KFAC_DRILL_ARTIFACTS')
    if art:
        import shutil
        churn_art = os.path.join(art, 'churn')
        os.makedirs(churn_art, exist_ok=True)
        for src in paths + traces:
            shutil.copy(src, churn_art)
        with open(os.path.join(churn_art, 'timeline.json'), 'w') as f:
            json.dump({k: v for k, v in timeline.items()
                       if not k.startswith('_')}, f, indent=2,
                      default=str)
        with open(os.path.join(churn_art, 'pod_trace.json'), 'w') as f:
            json.dump(aggregate.merged_chrome_trace(timeline), f)


# ---------------------------------------------------------------------------
# the partition drill (ISSUE 7): seeded 2|1 ChaosTransport partition ->
# majority shrinks and trains on, minority fences rc=117 with zero
# checkpoint commits, heal -> --join rejoin -> grow, schedule-equivalent
# ---------------------------------------------------------------------------

PART_HB_DEADLINE = 3.0
PART_EPOCHS = 10
PART_BATCH = 12      # divides worlds 1/2/3 (shard_map needs even shards)
PART_EXAMPLES = 72   # 6 steps/epoch


def _part_cmd(host_id, lease, ckpt_dir, join=False):
    cmd = [sys.executable, '-m', 'kfac_pytorch_tpu.resilience.elastic',
           '--host-id', str(host_id), '--num-hosts', '3',
           '--lease-dir', str(lease),
           '--max-restarts', '6', '--backoff-base', '0.2',
           '--hb-interval', '0.25', '--hb-deadline',
           str(PART_HB_DEADLINE),
           '--hb-grace', '300', '--settle', '0.8',
           '--shrink-timeout', '8', '--grow-timeout', '10']
    if join:
        cmd += ['--join', '--join-timeout', '300']
    return cmd + ['--',
                  sys.executable, TRAINER, '--epochs', str(PART_EPOCHS),
                  '--batch-size', str(PART_BATCH),
                  '--num-examples', str(PART_EXAMPLES),
                  '--checkpoint-dir', str(ckpt_dir),
                  '--num-hosts', '{num_hosts}', '--host-id', '{host_id}',
                  '--step-deadline', '300']


def _ckpt_snapshot(ckpt_dir):
    """Names + world stamp of a checkpoint dir — the 'no checkpoint
    finalized after the fence' witness."""
    names = sorted(os.listdir(ckpt_dir)) if os.path.isdir(ckpt_dir) else []
    stamp = None
    try:
        with open(os.path.join(str(ckpt_dir), 'world.json')) as f:
            stamp = f.read()
    except OSError:
        pass
    return names, stamp


def test_pod_partition_quorum_fences_minority_then_rejoins(tmp_path):
    """The split-brain drill: a seeded ChaosTransport partition cuts a
    3-host pod 2|1 mid-run. The majority {0, 2} must pass the quorum
    gate, shrink to world 2 and keep training; the minority {1} must
    LOSE quorum and fence itself with RC_FENCED=117, finalizing zero
    checkpoints after the fence. When the partition heals, the fenced
    host rejoins through the ordinary --join grow lane and the run ends
    schedule-equivalent to an unpartitioned control — with the whole
    story (partition_suspected -> quorum_lost/fenced -> shrink -> join
    -> grow) pinned on the merged kfac-obs timeline."""
    from kfac_pytorch_tpu.resilience.elastic import RC_FENCED

    p = subprocess.run(
        [sys.executable, TRAINER, '--epochs', str(PART_EPOCHS),
         '--batch-size', str(PART_BATCH),
         '--num-examples', str(PART_EXAMPLES),
         '--checkpoint-dir', str(tmp_path / 'ckpt_control')],
        env=_env(), cwd=REPO, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True, timeout=540)
    assert p.returncode == 0, p.stdout[-3000:]
    control = _done_line(p.stdout)

    lease = tmp_path / 'lease'
    trace_dir = tmp_path / 'trace'
    part_file = tmp_path / 'partition.json'
    ckpts = {h: str(tmp_path / f'ckpt_h{h}') for h in range(3)}
    outs = {h: tmp_path / f'host{h}.out' for h in range(3)}
    rejoin_out = tmp_path / 'rejoin1.out'
    # pace steps so the partition always lands mid-training; the chaos
    # env arms the deterministic network layer in every process (the
    # partition matrix lives in the live file the test writes below)
    pod_env = _env(KFAC_FAULT_SLOW_STEP='0:999',
                   KFAC_FAULT_SLOW_SECS='1.5',
                   KFAC_TRACE_DIR=str(trace_dir),
                   KFAC_FAULT_NET_SEED='7',
                   KFAC_FAULT_NET_PARTITION_FILE=str(part_file))

    def start(cmd, out_path):
        f = open(out_path, 'wb')
        proc = subprocess.Popen(cmd, env=pod_env, cwd=REPO, stdout=f,
                                stderr=subprocess.STDOUT,
                                start_new_session=True)
        proc._outfile = f
        return proc

    procs = {}
    rejoin = None
    try:
        for h in range(3):
            procs[h] = start(_part_cmd(h, lease, ckpts[h]), outs[h])

        # epoch 0 banked everywhere: resumable state exists, run is live
        deadline = time.time() + 420
        while time.time() < deadline:
            if any(p.poll() is not None for p in procs.values()):
                pytest.fail('a pod member exited before the partition; '
                            'host0 tail: ' + outs[0].read_text()[-3000:])
            if all(_has_checkpoint(ckpts[h]) for h in range(3)):
                break
            time.sleep(0.5)
        else:
            pytest.fail('epoch-0 checkpoints never appeared; host0 tail: '
                        + outs[0].read_text()[-3000:])

        # CUT: {0, 2} | {1}, written atomically into the live partition
        # file every ChaosTransport/protocol reader polls
        now = time.time()
        tmp = str(part_file) + '.tmp'
        with open(tmp, 'w') as f:
            json.dump({'windows': [{'start': now, 'end': now + 3600,
                                    'groups': [[0, 2], [1]]}]}, f)
        os.replace(tmp, str(part_file))

        # the minority loses quorum and fences with the dedicated rc
        rc1 = procs[1].wait(timeout=240)
        assert rc1 == RC_FENCED, (rc1, outs[1].read_text()[-4000:])
        fence_snapshot = _ckpt_snapshot(ckpts[1])

        # the majority commits the shrink and keeps training
        majority = [('host0', procs[0]), ('host2', procs[2])]
        _wait_count(outs[0], 'elastic: shrinking world 3 -> 2', 1, 240,
                    majority)
        _wait_count(outs[0], 'RESHARDED from_world=3 to_world=2', 1, 240,
                    majority)
        # the shrunken generation banks an epoch (stamp -> 2) so the
        # rejoin genuinely reshards UP afterwards
        _wait_stamp(ckpts[0], 2, 240, majority)

        # zero checkpoint commits on the fenced host since the fence
        assert _ckpt_snapshot(ckpts[1]) == fence_snapshot

        # HEAL: remove the partition file, then rejoin via the grow lane
        os.remove(part_file)
        rejoin = start(_part_cmd(1, lease, ckpts[1], join=True),
                       rejoin_out)
        watch = majority + [('rejoin1', rejoin)]
        _wait_count(outs[0], 'elastic: growing world 2 -> 3', 1, 300,
                    watch)
        _wait_count(outs[0], 'RESHARDED from_world=2 to_world=3', 1, 300,
                    watch)

        rc0 = procs[0].wait(timeout=600)
        rc2 = procs[2].wait(timeout=600)
        rcr = rejoin.wait(timeout=600)
    finally:
        for proc in list(procs.values()) + ([rejoin] if rejoin else []):
            if proc.poll() is None:
                _killpg(proc)
            f = getattr(proc, '_outfile', None)
            if f is not None:
                f.close()

    out0, out1, out2 = (outs[h].read_text() for h in range(3))
    assert rc0 == 0, out0[-4000:]
    assert rc2 == 0, out2[-4000:]
    assert rcr == 0, rejoin_out.read_text()[-4000:]

    # the minority's story: suspicion -> quorum verdict -> fence
    assert 'partition suspected' in out1, out1[-4000:]
    assert 'quorum lost' in out1, out1[-4000:]
    assert 'Fencing this host' in out1, out1[-4000:]
    # the majority never fences, never loses quorum, never gives up
    for text in (out0, out2):
        assert 'quorum lost' not in text
        assert 'Fencing this host' not in text
        assert 'giving up' not in text
    # detection was heartbeat-speed, never the (300s) watchdog
    assert 'declared dead' in out0
    assert 'step deadline exceeded' not in out0

    # the healed host rejoined through the ordinary join lane
    rejoin_text = rejoin_out.read_text()
    assert 'join: host 1 announcing to pod' in rejoin_text
    assert 'join: admitted into pod' in rejoin_text, rejoin_text[-2000:]

    # schedule equivalence across partition + fence + rejoin
    assert _done_line(out0) == control

    # incident JSON: the partition grammar landed as structured events.
    # The FENCED incarnation's report was rotated to .prev when the
    # rejoin incarnation wrote its own — both survive.
    report = json.loads(
        (lease / 'incident-host1.json.prev').read_text())
    kinds = [e['kind'] for e in report['events']]
    assert 'partition_suspected' in kinds
    assert 'quorum_lost' in kinds
    assert 'fenced' in kinds
    assert report['fenced'] is True
    assert report['counters'].get('quorum_lost', 0) >= 1
    q = next(e for e in report['events'] if e['kind'] == 'quorum_lost')
    assert q['claimants'] == [1] and q['membership'] == [0, 1, 2]
    # the rejoin incarnation's own (clean) report is the current one
    rejoin_report = json.loads((lease / 'incident-host1.json').read_text())
    assert rejoin_report['fenced'] is False
    assert any(e['kind'] == 'join_admitted'
               for e in rejoin_report['events'])
    report0 = json.loads((lease / 'incident-host0.json').read_text())
    assert report0['fenced'] is False
    assert report0['shrinks'] and report0['shrinks'][0]['from'] == 3

    # lineage: the majority committed membership changes (shrink+grow),
    # so its world stamp carries a monotonic lineage >= 2; the fenced
    # fork never advanced past the pre-partition epoch
    with open(os.path.join(ckpts[0], 'world.json')) as f:
        stamp0 = json.load(f)
    assert stamp0.get('lineage', 0) >= 2, stamp0

    # kfac-obs: one merged timeline pins the causal story
    import glob

    from kfac_pytorch_tpu.obs import aggregate
    paths = [str(o) for o in outs.values()] + [str(rejoin_out)]
    paths += sorted(glob.glob(str(lease / 'incident-host*.json')))
    traces = sorted(glob.glob(str(trace_dir / '*.jsonl')))
    assert traces, 'trainers wrote no trace JSONL under KFAC_TRACE_DIR'
    timeline = aggregate.build_timeline(paths + traces)
    events = timeline['events']
    kinds = [e['kind'] for e in events]

    def first(kind, after=0, **match):
        for i in range(after, len(events)):
            e = events[i]
            if e['kind'] == kind and all(
                    e['detail'].get(k) == v for k, v in match.items()):
                return i
        raise AssertionError(
            f'{kind} {match or ""} missing after index {after}; kinds: '
            f'{sorted(set(kinds))}')

    i_susp = first('partition_suspected')
    i_qlost = first('quorum_lost', after=i_susp)
    i_fence = first('fenced', after=i_susp)
    i_shrink = first('shrink', after=i_susp)
    i_join = first('join_announce',
                   after=max(i_qlost, i_fence, i_shrink))
    i_grow = first('grow', after=i_join)
    walls = [events[i]['wall_aligned'] for i in
             (i_susp, i_join, i_grow)]
    assert all(w is not None for w in walls), walls
    assert walls == sorted(walls), walls
    # the chaos layer itself left solver inputs on the timeline sources
    assert aggregate.solve_offsets(traces) is not None  # no crash

    # CI artifact export: partition debris + aggregated timeline under
    # partition/, alongside the churn drill's churn/ artifacts
    art = os.environ.get('KFAC_DRILL_ARTIFACTS')
    if art:
        import shutil
        part_art = os.path.join(art, 'partition')
        os.makedirs(part_art, exist_ok=True)
        for src in paths + traces:
            shutil.copy(src, part_art)
        with open(os.path.join(part_art, 'timeline.json'), 'w') as f:
            json.dump({k: v for k, v in timeline.items()
                       if not k.startswith('_')}, f, indent=2,
                      default=str)
        with open(os.path.join(part_art, 'pod_trace.json'), 'w') as f:
            json.dump(aggregate.merged_chrome_trace(timeline), f)


# ---------------------------------------------------------------------------
# TcpKv backend legs of the standing churn + partition drills: the same
# acceptance runs with the coordination plane on the KV server and the
# seeded backend faults armed. Nightly tier (the 2-host TcpKv leg above
# rides the regular chaos job; these add ~25 min each).
# ---------------------------------------------------------------------------


@pytest.mark.nightly
def test_pod_churn_on_tcpkv_backend(tmp_path, tcpkv_coord):
    test_pod_survives_churn_kill_and_rejoin(tmp_path)


@pytest.mark.nightly
def test_pod_partition_on_tcpkv_backend(tmp_path, tcpkv_coord):
    test_pod_partition_quorum_fences_minority_then_rejoins(tmp_path)
