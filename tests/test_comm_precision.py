"""Comm-compressed, fully-overlapped factor exchange
(parallel/collectives.py wire dtypes + KFAC(comm_precision=,
comm_prefetch=)).

Pins the tentpole contracts:

1. Wire formats: per-row int8 quantization error bound, bf16 gathers
   exact w.r.t. bf16 rounding (the bitcast-u16 wire), reduce-scatter
   stats reduce == pmean + own-row slice, EF residual algebra.
2. world=1 (``axis_name=None``) is a zero-comm IDENTITY path: any
   ``comm_precision`` is bit-identical to fp32 on one device.
3. Convergence parity on the tiny-MLP micro harness over a real
   2-device mesh: bf16 tracks fp32 tightly, int8+EF within a pinned
   loss tolerance; the EF residual is live (non-zero) for lossy MPD
   runs and absent for DP/fp32 runs.
4. EF residual state survives checkpoint save/restore and is
   ZERO-FILLED by ``reshard_kfac_state`` on an elastic world change
   (like the E-KFAC scales — transport-transient error state).
5. Cross-step prefetch (``comm_prefetch``): the published decomposition
   is bit-identical to the unprefetched run's, THIS step preconditions
   with the previous table (no same-step consumer), the first
   decomposition of a run is never prefetched, and the dispatch records
   overlapping ``kfac.CommunicateInverse.prefetch`` /
   ``kfac.Precondition`` trace spans with ``consumer_step = step + 1``.
6. The drift gate and the analytic volume model speak the same
   compression factors (obs/drift.scale_comm_scenarios,
   plan.FactorPlan.comm_volume).
"""

import functools

import flax.linen as linen
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import kfac_pytorch_tpu as kfac
from kfac_pytorch_tpu import capture, training
from kfac_pytorch_tpu import nn as knn
from kfac_pytorch_tpu.obs import drift
from kfac_pytorch_tpu.obs.trace import TraceRecorder
from kfac_pytorch_tpu.parallel import collectives as coll

pytestmark = pytest.mark.core


class MLP(linen.Module):
    @linen.compact
    def __call__(self, x, train=True):
        x = knn.Dense(8, name='fc1')(x)
        x = linen.relu(x)
        x = knn.Dense(3, name='fc2')(x)
        return x


def _batch(n=8):
    rng = np.random.RandomState(0)
    return {'input': jnp.asarray(rng.randn(n, 5), jnp.float32),
            'label': jnp.asarray(rng.randint(0, 3, n))}


def _ce(outputs, batch):
    return optax.softmax_cross_entropy_with_integer_labels(
        outputs, batch['label']).mean()


def _trainer(variant='eigen', ndev=1, comm_precision='fp32',
             comm_prefetch=False, kfac_freq=1, stagger=False, lr=0.1,
             tracer=None):
    model = MLP()
    mesh = (Mesh(np.array(jax.devices()[:ndev]), ('batch',))
            if ndev > 1 else None)
    axis = 'batch' if ndev > 1 else None
    pre = kfac.KFAC(variant=variant, lr=lr, damping=0.003,
                    kfac_update_freq=kfac_freq, num_devices=ndev,
                    axis_name=axis, bucket_fn=lambda d: 16,
                    comm_precision=comm_precision,
                    comm_prefetch=comm_prefetch, stagger=stagger)
    tx = training.sgd(lr, momentum=0.9)
    state = training.init_train_state(model, tx, pre,
                                      jax.random.PRNGKey(0),
                                      _batch()['input'])
    step = training.build_train_step(model, tx, pre, _ce, axis_name=axis,
                                     mesh=mesh, tracer=tracer)
    return step, state, pre


# ---------------------------------------------------------------------------
# wire formats
# ---------------------------------------------------------------------------

def test_quantize_rows_roundtrip_error_bound():
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(5, 7, 7) * np.array(
        [1e-3, 1.0, 50.0, 0.0, 3.0])[:, None, None], jnp.float32)
    q, scale = coll.quantize_rows(x)
    assert q.dtype == jnp.int8 and scale.dtype == jnp.float32
    back = coll.dequantize_rows(q, scale)
    # per-row absmax/254 error bound (half a quantization step... the
    # round() gives absmax/127/2 per entry); the all-zero row is exact
    absmax = np.abs(np.asarray(x)).max(axis=(1, 2))
    err = np.abs(np.asarray(back) - np.asarray(x)).max(axis=(1, 2))
    assert np.all(err <= absmax / 254 + 1e-12), (err, absmax)
    assert np.all(np.asarray(back)[3] == 0)


def test_check_wire_dtype_rejects_unknown():
    with pytest.raises(ValueError, match='comm_precision'):
        coll.check_wire_dtype('fp4')
    with pytest.raises(ValueError, match='comm_precision'):
        kfac.KFAC(variant='eigen', comm_precision='f16')


def test_comm_prefetch_validation():
    # comm_pred variants gather preconditioned grads — the step's own
    # consumer, cannot be deferred
    with pytest.raises(ValueError, match='comm_prefetch'):
        kfac.KFAC(variant='eigen_dp', comm_prefetch=True)
    with pytest.raises(ValueError, match='ekfac'):
        kfac.KFAC(variant='ekfac', comm_prefetch=True)
    # fine on the comm_inverse layouts
    kfac.KFAC(variant='eigen', comm_prefetch=True)
    kfac.KFAC(variant='inverse', communicate_inverse_or_not=True,
              comm_prefetch=True)


def _mesh8():
    return Mesh(np.array(jax.devices()[:8]), ('x',))


def test_pmean_scatter_matches_pmean_plus_slice():
    mesh = _mesh8()
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(8, 16, 4, 4), jnp.float32)

    @jax.jit
    @functools.partial(jax.shard_map, mesh=mesh, in_specs=P('x'),
                       out_specs=(P('x'), P('x')))
    def f(xs):
        got, _ = coll.pmean_scatter_ef(xs[0], 'x', 'fp32', None)
        full = coll.pmean(xs[0], 'x')
        idx = coll.axis_index('x')
        want = jax.lax.dynamic_slice_in_dim(full, idx * 2, 2, axis=0)
        return got[None], want[None]

    got, want = f(x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-6, atol=1e-7)


def test_pmean_scatter_ef_residual_algebra():
    """bf16 EF over a mesh: the residual equals (x + r) - bf16(x + r)
    per device, it stays bounded over repeated reduces (no blow-up),
    and the EF property holds — the TIME-AVERAGED output over k reduces
    of the same data is closer to the true mean than the residual-free
    reduce's (whose quantization bias never cancels). The remaining
    common floor is the collective's bf16 OUTPUT rounding, which EF by
    design cannot see (it compensates the send, not the sum)."""
    mesh = _mesh8()
    rng = np.random.RandomState(3)
    # values with bf16-visible rounding error
    x = jnp.asarray(1.0 + 0.001 * rng.randn(8, 16, 4, 4), jnp.float32)
    k = 8

    @jax.jit
    @functools.partial(jax.shard_map, mesh=mesh, in_specs=P('x'),
                       out_specs=(P('x'), P('x'), P('x'), P('x')))
    def f(xs):
        r = jnp.zeros_like(xs[0])
        tot_ef = tot_ne = first_r = None
        for _ in range(k):
            m, r = coll.pmean_scatter_ef(xs[0], 'x', 'bf16', r)
            tot_ef = m if tot_ef is None else tot_ef + m
            first_r = r if first_r is None else first_r
            mn, _ = coll.pmean_scatter_ef(xs[0], 'x', 'bf16',
                                          jnp.zeros_like(xs[0]))
            tot_ne = mn if tot_ne is None else tot_ne + mn
        return (tot_ef[None] / k, tot_ne[None] / k, first_r[None],
                r[None])

    ef, ne, r1, rk = (np.asarray(v) for v in f(x))
    xr = np.asarray(x).reshape(8, 16, 4, 4)
    want_r1 = xr - np.asarray(
        jnp.asarray(xr).astype(jnp.bfloat16).astype(jnp.float32))
    np.testing.assert_allclose(r1.reshape(8, 16, 4, 4), want_r1,
                               rtol=0, atol=1e-7)
    # residuals stay bounded by a few quantization steps (no blow-up)
    assert np.abs(rk).max() <= np.abs(want_r1).max() * 4 + 1e-7
    true_mean = xr.mean(axis=0)                       # [16, 4, 4]
    e_ef = np.abs(ef.reshape(16, 4, 4) - true_mean).mean()
    e_ne = np.abs(ne.reshape(16, 4, 4) - true_mean).mean()
    assert e_ef < e_ne, (e_ef, e_ne)


@pytest.mark.parametrize('precision', ['bf16', 'int8'])
def test_all_gather_rows_compressed_mesh(precision):
    mesh = _mesh8()
    rng = np.random.RandomState(4)
    x = jnp.asarray(rng.randn(8, 2, 6, 6), jnp.float32)

    @jax.jit
    @functools.partial(jax.shard_map, mesh=mesh, in_specs=P('x'),
                       out_specs=P(None))
    def f(xs):
        return coll.all_gather_rows_compressed(xs.reshape(2, 6, 6), 'x',
                                               precision)

    got = np.asarray(f(x))
    full = np.asarray(x).reshape(16, 6, 6)
    if precision == 'bf16':
        # the u16 bitcast wire is EXACT w.r.t. bf16 rounding
        want = np.asarray(jnp.asarray(full).astype(jnp.bfloat16)
                          .astype(jnp.float32))
        np.testing.assert_array_equal(got, want)
    else:
        absmax = np.abs(full).max(axis=(1, 2), keepdims=True)
        assert np.all(np.abs(got - full) <= absmax / 254 + 1e-12)


# ---------------------------------------------------------------------------
# world=1 identity + convergence parity
# ---------------------------------------------------------------------------

@pytest.mark.parametrize('variant', ['eigen', 'eigen_dp'])
@pytest.mark.parametrize('precision', ['bf16', 'int8'])
def test_world1_identity_bitwise(variant, precision):
    """axis_name=None must stay a zero-comm identity path: any
    comm_precision is BIT-identical to fp32 on one device."""
    batch = _batch()

    def run(p):
        step, state, _ = _trainer(variant=variant, comm_precision=p)
        out = []
        for _ in range(5):
            state, m = step(state, batch, lr=0.1, damping=0.003)
            out.append(float(m['loss']))
        return out, state

    l32, s32 = run('fp32')
    lq, sq = run(precision)
    assert l32 == lq
    for a, b in zip(jax.tree.leaves(s32.params), jax.tree.leaves(sq.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.parametrize('variant,lr,damping',
                         [('eigen', 0.1, 0.003),
                          ('inverse_dp', 0.05, 0.03)])
def test_convergence_parity_mesh(variant, lr, damping):
    """The micro harness over a real 2-device mesh: bf16 tracks fp32
    tightly, int8+EF within a pinned tolerance; the EF residual is live
    exactly when a lossy MPD reduce exists."""
    batch = _batch()

    def run(p, steps=12):
        step, state, pre = _trainer(variant=variant, ndev=2,
                                    comm_precision=p, lr=lr)
        losses = []
        for _ in range(steps):
            state, m = step(state, batch, lr=lr, damping=damping)
            losses.append(float(m['loss']))
        return losses, state, pre

    l32, s32, _ = run('fp32')
    l16, s16, p16 = run('bf16')
    l8, s8, _ = run('int8')
    drop = l32[0] - l32[-1]
    assert drop > 0.1, l32                       # the harness trains
    # bf16: indistinguishable at the loss level (EF'd stats reduce +
    # bf16-rounded gathers on a damped decomposition)
    assert abs(l16[-1] - l32[-1]) <= 0.02 * drop, (l32[-1], l16[-1])
    # int8+EF: within the pinned tolerance of fp32 (the quantized
    # eigenbasis adds a noise floor near convergence — the pin is that
    # int8 achieves >=85% of the fp32 loss drop on this harness)
    assert abs(l8[-1] - l32[-1]) <= 0.15 * drop, (l32[-1], l8[-1])
    if variant == 'eigen':
        # lossy MPD reduce -> EF residual live (non-zero after steps)
        assert s16.kfac_state.comm_err is not None
        total = sum(float(jnp.abs(v).sum())
                    for v in s16.kfac_state.comm_err.values())
        assert total > 0
        # fp32 carries NO residual state
        assert s32.kfac_state.comm_err is None
    else:
        # DP variants never reduce stats -> no residual under any wire
        assert s16.kfac_state.comm_err is None
        assert s8.kfac_state.comm_err is None


def test_ekfac_composes_with_compressed_wire():
    """The ekfac scales pmean rides the lossy wire (no EF — documented)
    and the run stays finite and training."""
    batch = _batch()
    step, state, _ = _trainer(variant='ekfac', ndev=2,
                              comm_precision='bf16')
    losses = []
    for _ in range(8):
        state, m = step(state, batch, lr=0.1, damping=0.03)
        losses.append(float(m['loss']))
    assert all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0], losses


# ---------------------------------------------------------------------------
# EF residual state: checkpoint + elastic reshard
# ---------------------------------------------------------------------------

def test_comm_err_checkpoint_roundtrip(tmp_path):
    from kfac_pytorch_tpu.utils.checkpoint import (restore_checkpoint,
                                                   save_checkpoint)
    batch = _batch()
    step, state, _ = _trainer(variant='eigen', ndev=2,
                              comm_precision='bf16')
    for _ in range(3):
        state, _ = step(state, batch, lr=0.1, damping=0.003)
    assert state.kfac_state.comm_err is not None
    save_checkpoint(str(tmp_path), 0, state)
    fresh_step, fresh, _ = _trainer(variant='eigen', ndev=2,
                                    comm_precision='bf16')
    restored = restore_checkpoint(str(tmp_path), 0, fresh)
    for k, v in state.kfac_state.comm_err.items():
        np.testing.assert_array_equal(
            np.asarray(restored.kfac_state.comm_err[k]), np.asarray(v))
    # and the restored state steps without re-seeding (structure
    # intact); decommit from the restore device first, as the elastic
    # resume path does, so the mesh can reshard it
    restored = jax.tree.map(np.asarray, restored)
    restored, m = fresh_step(restored, batch, lr=0.1, damping=0.003)
    assert np.isfinite(float(m['loss']))


def test_pre_compression_checkpoint_upgrades_host_side():
    """A state carrying comm_err=None (fp32 checkpoint) dispatched
    through a lossy-configured trainer is seeded with zeros BEFORE the
    jitted call — one state structure for every variant."""
    batch = _batch()
    step32, state32, _ = _trainer(variant='eigen', ndev=2,
                                  comm_precision='fp32')
    state32, _ = step32(state32, batch, lr=0.1, damping=0.003)
    assert state32.kfac_state.comm_err is None
    step16, _, _ = _trainer(variant='eigen', ndev=2,
                            comm_precision='bf16')
    out, m = step16(state32, batch, lr=0.1, damping=0.003)
    assert np.isfinite(float(m['loss']))
    assert out.kfac_state.comm_err is not None


def test_lossy_checkpoint_restores_into_fp32_run(tmp_path):
    """The DOWNGRADE direction: a checkpoint taken under a lossy
    comm_precision (carries KFACState.comm_err) restored by a run
    configured at fp32 (target has comm_err=None). auto_resume must
    rebuild a placeholder from the checkpoint's saved shapes, restore,
    and DISCARD the residual — not scan past the checkpoint as
    'unreadable' and silently restart from scratch."""
    from kfac_pytorch_tpu.utils.checkpoint import (auto_resume,
                                                   save_checkpoint)
    batch = _batch()
    step16, state16, _ = _trainer(variant='eigen', ndev=2,
                                  comm_precision='bf16')
    for _ in range(3):
        state16, _ = step16(state16, batch, lr=0.1, damping=0.003)
    assert state16.kfac_state.comm_err is not None
    save_checkpoint(str(tmp_path), 0, state16)
    step32, fresh32, _ = _trainer(variant='eigen', ndev=2,
                                  comm_precision='fp32')
    assert fresh32.kfac_state.comm_err is None
    restored, epoch = auto_resume(str(tmp_path), 5, fresh32)
    assert epoch == 0 and restored is not None
    assert restored.kfac_state.comm_err is None
    for k, v in state16.kfac_state.factors.items():
        np.testing.assert_array_equal(
            np.asarray(restored.kfac_state.factors[k]), np.asarray(v))
    restored = jax.tree.map(np.asarray, restored)
    restored, m = step32(restored, batch, lr=0.1, damping=0.003)
    assert np.isfinite(float(m['loss']))


def test_reshard_zero_fills_comm_err_on_grow():
    """Elastic grow 1 -> 2: factors transport exactly, the EF residual
    re-initializes to zeros in the NEW world's shape (like the ekfac
    scales — error state re-accumulates, it is never transported)."""
    from kfac_pytorch_tpu.utils.checkpoint import reshard_kfac_state
    batch = _batch()
    step1, state1, p1 = _trainer(variant='eigen', ndev=1,
                                 comm_precision='bf16')
    for _ in range(3):
        state1, _ = step1(state1, batch, lr=0.1, damping=0.003)
    k1 = state1.kfac_state
    # world=1 is the identity path: residual stays exactly zero
    assert all(not np.any(np.asarray(v)) for v in k1.comm_err.values())
    p2 = kfac.KFAC(variant='eigen', num_devices=2, axis_name='batch',
                   bucket_fn=lambda d: 16, comm_precision='bf16')
    p2.setup(p1.plan.metas)
    k2 = reshard_kfac_state(p1, p2, k1)
    assert k2.comm_err is not None
    for d in p2.plan.bucket_dims:
        b = p2.plan.buckets[d]
        assert k2.comm_err[str(d)].shape == (2 * b.n_rows, d, d)
        assert not np.any(np.asarray(k2.comm_err[str(d)]))
    # the factor statistics themselves transported exactly
    for i, meta in enumerate(p1.plan.metas):
        ba_o, ra_o, bg_o, rg_o, _ = p1.plan.layer_rows[i]
        ba_n, ra_n, bg_n, rg_n, _ = p2.plan.layer_rows[i]
        da, dg = meta.in_dim, meta.out_dim
        np.testing.assert_array_equal(
            np.asarray(k2.factors[str(ba_n)])[ra_n, :da, :da],
            np.asarray(k1.factors[str(ba_o)])[ra_o, :da, :da])
        np.testing.assert_array_equal(
            np.asarray(k2.factors[str(bg_n)])[rg_n, :dg, :dg],
            np.asarray(k1.factors[str(bg_o)])[rg_o, :dg, :dg])


# ---------------------------------------------------------------------------
# cross-step prefetch
# ---------------------------------------------------------------------------

def test_prefetch_publishes_same_table_consumes_previous():
    """comm_prefetch changes WHEN the gathered table is consumed, never
    what is published: the stored decomposition after every step is
    bit-identical to the unprefetched run's (frozen params via lr=0),
    while the refresh step's preconditioning uses the PREVIOUS table."""
    batch = _batch()
    step_p, state_p, _ = _trainer(variant='eigen', kfac_freq=2,
                                  comm_prefetch=True, lr=0.0)
    step_n, state_n, _ = _trainer(variant='eigen', kfac_freq=2,
                                  comm_prefetch=False, lr=0.0)
    for t in range(6):
        state_p, _ = step_p(state_p, batch, lr=0.0, damping=0.003)
        state_n, _ = step_n(state_n, batch, lr=0.0, damping=0.003)
        for a, b in zip(jax.tree.leaves(state_p.kfac_state.decomp),
                        jax.tree.leaves(state_n.kfac_state.decomp)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # the first inverse update is NEVER prefetched (cold table): the
    # dispatch cache records pf=False for the first (uf, ui) key
    first_keys = [k for k in step_p.variants if len(k) == 5 and k[1]]
    assert any(k[4] is False for k in first_keys), step_p.variants


def test_prefetch_defers_consumption_one_step():
    """Direct engine-level pin: with prefetch, grads returned at an
    inverse-update step are preconditioned with the PREVIOUS stored
    decomposition."""
    model = MLP()
    batch = _batch()
    variables = capture.init(model, jax.random.PRNGKey(0),
                             batch['input'])
    metas = capture.collect_layer_meta(model, variables, batch['input'])
    pre = kfac.KFAC(variant='eigen', num_devices=1, axis_name=None,
                    bucket_fn=lambda d: 16, comm_prefetch=True)
    pre.setup(metas)
    loss_fn = lambda out: _ce(out, batch)  # noqa: E731
    _, _, grads, acts, gs, _ = capture.value_and_grad_with_capture(
        model, loss_fn, variables, batch['input'])
    state0 = pre.init()
    _, state1 = pre.step(state0, grads, acts, gs)       # table A
    # prefetch step: publishes table B, preconditions with table A
    g_pref, state2 = pre.step(state1, grads, acts, gs, prefetch=True)
    # reference: precondition with table A, no inverse update
    g_prev, _ = pre.step(state1, grads, acts, gs, update_inverse=False)
    for a, b in zip(jax.tree.leaves(g_pref), jax.tree.leaves(g_prev)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # and the published table B is the fresh one, not A
    changed = any(
        not np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree.leaves(state2.decomp),
                        jax.tree.leaves(state1.decomp)))
    assert changed


@pytest.mark.parametrize('mode', ['prefetch', 'stagger'])
def test_prefetch_trace_spans_overlap(mode):
    """The dispatch records the schedule: a CommunicateInverse.prefetch
    span whose args pin consumer_step == step + 1 (no same-step
    consumer), wall-overlapping the Precondition span of the SAME step
    — the trace-level witness that the gather rides under the pred
    einsums."""
    batch = _batch()
    tracer = TraceRecorder(None)
    step, state, _ = _trainer(variant='eigen', kfac_freq=2,
                              comm_prefetch=(mode == 'prefetch'),
                              stagger=(mode == 'stagger'), tracer=tracer)
    for _ in range(5):
        state, _ = step(state, batch, lr=0.1, damping=0.003)
    evs = tracer.events()
    gathers = [e for e in evs
               if e.get('name') == 'kfac.CommunicateInverse.prefetch']
    preds = {e['args']['step']: e for e in evs
             if e.get('name') == 'kfac.Precondition'}
    assert gathers, [e.get('name') for e in evs]
    for g in gathers:
        step_i = g['args']['step']
        assert g['args']['consumer_step'] == step_i + 1
        if mode == 'stagger':
            assert g['args']['cohort'] == step_i % 2
        p = preds[step_i]
        # wall overlap of the two spans
        g0, g1 = g['ts'], g['ts'] + g['dur']
        p0, p1 = p['ts'], p['ts'] + p['dur']
        assert max(g0, p0) < min(g1, p1), (g, p)
    # step 0 (the cold full decomposition) must NOT be prefetched
    assert 0 not in {g['args']['step'] for g in gathers}


# ---------------------------------------------------------------------------
# drift gate + analytic volume model
# ---------------------------------------------------------------------------

def test_scale_comm_scenarios_per_wire_dtype():
    block = {'scenarios': {
        'central': {'phases_s': {'CommunicateFactor': 0.30,
                                 'CommunicateInverse': 0.146,
                                 'ComputeInverse_eigh_full': 2.0}},
        'optimistic': {'phases_s': {'CommunicateFactor': 0.20,
                                    'CommunicateInverse': 0.10}},
    }}
    for wd, (f, i) in {'fp32': (1.0, 1.0), 'bf16': (0.5, 0.5),
                       'int8': (0.5, 0.25)}.items():
        out = drift.scale_comm_scenarios(block, wd)
        c = out['scenarios']['central']['phases_s']
        assert c['CommunicateFactor'] == pytest.approx(0.30 * f)
        assert c['CommunicateInverse'] == pytest.approx(0.146 * i)
        # compute phases untouched
        assert c['ComputeInverse_eigh_full'] == 2.0
        if wd != 'fp32':
            assert out['comm_precision'] == wd
    # the original block is never mutated
    assert block['scenarios']['central']['phases_s'][
        'CommunicateFactor'] == 0.30


def test_drift_block_covers_compressed_runs():
    block = {'scenarios': {
        'optimistic': {'phases_s': {'CommunicateInverse': 0.08}},
        'conservative': {'phases_s': {'CommunicateInverse': 0.16}},
        'central': {'phases_s': {'CommunicateInverse': 0.12}},
    }}
    # a bf16 run measuring half the fp32 band: drift under the raw
    # model, ok under the compression-scaled one
    measured = {'CommunicateInverse': 0.06}
    raw = drift.drift_block(measured, block, platform='TPU v5e',
                            variant='eigen')
    scaled = drift.drift_block(measured, block, platform='TPU v5e',
                               variant='eigen', comm_precision='bf16')
    assert raw['gate']['verdict'] == 'drift'
    assert scaled['gate']['verdict'] == 'ok'
    assert scaled['comm_precision'] == 'bf16'


def test_plan_comm_volume_compression_factors():
    model = MLP()
    batch = _batch()
    variables = capture.init(model, jax.random.PRNGKey(0),
                             batch['input'])
    metas = capture.collect_layer_meta(model, variables, batch['input'])
    pre = kfac.KFAC(variant='eigen', num_devices=2, axis_name='batch',
                    bucket_fn=lambda d: 16)
    plan = pre.setup(metas)
    v32 = plan.comm_volume(stats_reduce='pmean', method='eigh',
                           comm_precision='fp32')
    v16 = plan.comm_volume(stats_reduce='pmean', method='eigh',
                           comm_precision='bf16')
    v8 = plan.comm_volume(stats_reduce='pmean', method='eigh',
                          comm_precision='int8')
    assert v32['FactorComm'] > 0 and v32['InverseComm'] > 0
    assert v32['PredComm'] == 0
    # bf16 halves both; int8 quarters the gather body (+ scale side
    # channel) while the reduce floors at bf16
    assert v16['FactorComm'] == v32['FactorComm'] // 2
    assert v16['InverseComm'] == v32['InverseComm'] // 2
    assert v8['FactorComm'] == v16['FactorComm']
    assert v8['InverseComm'] < v16['InverseComm']
    # DP layout: no factor reduce, pred gather instead
    pre_dp = kfac.KFAC(variant='eigen_dp', num_devices=2,
                       axis_name='batch', bucket_fn=lambda d: 16)
    plan_dp = pre_dp.setup(metas)
    vdp = plan_dp.comm_volume(stats_reduce='local', method='eigh',
                              comm_precision='bf16')
    assert vdp['FactorComm'] == 0 and vdp['InverseComm'] == 0
    assert vdp['PredComm'] > 0


def test_analytic_comm_model_cli_helper():
    from scripts.comm_models import analytic_comm_volumes
    vols = analytic_comm_volumes('resnet20', 'eigen', ndev=8)
    assert set(vols) == {'fp32', 'bf16', 'int8'}
    t32 = sum(vols['fp32'].values())
    t16 = sum(vols['bf16'].values())
    assert 0.4 <= t16 / t32 <= 0.55   # ~half, modulo the evals vector
