"""Mesh-sharded decomposition (plan.build_decomp_shard + the engine
shard compute/merge + KFAC(decomp_shard=True)) and the decomp_impl
knob's engine paths.

Pins the tentpole contracts:

1. Balance on the REAL trigger: a plan where one device owns the only
   large bucket — the sharded layout's per-device valid rows stay
   within 2x of the mean and the padded per-device critical path
   (Σ_b S_b·D³, the work the uniform compiled program actually runs)
   never exceeds owner-local's (Σ_b R_b·D³), strictly undercutting it
   when ownership is imbalanced.
2. Exactness: decomp_shard=True produces BIT-IDENTICAL decomposition
   state to the owner-local staggered schedule — world=1 through the
   preconditioner API for all four variants, world=2 through the
   jitted trainer (lr=0, frozen factors) on a fake mesh for both comm
   modes. Sharding moves work, never values.
3. Coverage: every valid cohort row is decomposed by exactly one
   device and returns to exactly its own stored row (the gather-merge
   tables are a bijection over the cohort).
4. Health: a blown remote decomposition row keeps the stored row (the
   merge's per-row screen), and the screen is what saved it.
5. decomp_impl: the iterative kernels route through the full AND
   staggered engine paths (explicit impl implies warm seeding), with
   ctor validation rejecting method-mismatched kernels.
"""

import flax.linen as linen
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh

import kfac_pytorch_tpu as kfac
from kfac_pytorch_tpu import capture, engine, training
from kfac_pytorch_tpu import nn as knn
from kfac_pytorch_tpu.capture import LayerMeta
from kfac_pytorch_tpu.plan import (build_cohorts, build_decomp_shard,
                                   build_plan)

pytestmark = pytest.mark.core


class MLP(linen.Module):
    @linen.compact
    def __call__(self, x, train=True):
        x = knn.Dense(8, name='fc1')(x)
        x = linen.relu(x)
        x = knn.Dense(3, name='fc2')(x)
        return x


def _setup(variant, batch=4, **kw):
    model = MLP()
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(batch, 5), jnp.float32)
    y = jnp.asarray(rng.randn(batch, 3), jnp.float32)
    variables = capture.init(model, jax.random.PRNGKey(0), x)
    metas = capture.collect_layer_meta(model, variables, x)
    precond = kfac.KFAC(variant=variant, num_devices=1, axis_name=None,
                        bucket_fn=lambda d: 16, **kw)
    precond.setup(metas)
    state = precond.init()
    loss_fn = lambda out: jnp.mean((out - y) ** 2)  # noqa: E731
    _, _, grads, acts, gs, _ = capture.value_and_grad_with_capture(
        model, loss_fn, variables, x)
    return precond, state, grads, acts, gs, metas


def _imbalanced_plan(P=4, F=2, big=512, small=48, layers=16):
    """Round-robin ownership puts every big-factor layer (index % P
    == 0) on device 0 — the one-owner-holds-the-large-bucket trigger."""
    metas = {}
    for i in range(layers):
        d = big if i % P == 0 else small
        m = LayerMeta(name=f'l{i}', path=(f'l{i}',), kind='dense',
                      use_bias=False, in_dim=d, out_dim=d,
                      kernel_shape=(d, d))
        metas[m.name] = m
    plan = build_plan(metas, num_devices=P, comm_mode='pred')
    cohorts = build_cohorts(plan, F)
    return plan, cohorts, build_decomp_shard(plan, cohorts)


# ---------------------------------------------------------------------------
# the shard layout: balance, critical path, coverage
# ---------------------------------------------------------------------------

def test_shard_balances_imbalanced_plan_within_2x():
    plan, cohorts, shard = _imbalanced_plan()
    counts = shard.shard_count
    assert counts.sum() > 0
    mean = counts.mean()
    # the satellite acceptance bound: per-device decomposed rows within
    # 2x of the mean even when one device owns the only large bucket
    assert counts.max() <= 2 * max(mean, 1.0), counts
    # every valid cohort row assigned exactly once
    total_valid = sum(int(plan.buckets[b].valid.sum())
                      for b in plan.bucket_dims)
    assert int(counts.sum()) == total_valid


@pytest.mark.parametrize('F', [1, 2, 4])
def test_shard_critical_path_never_exceeds_owner_local(F):
    plan, cohorts, shard = _imbalanced_plan(F=F)
    owner = sum(t.shape[2] * d ** 3 for d, t in cohorts.rows.items())
    sharded = sum(t.shape[2] * d ** 3 for d, t in shard.src.items())
    # the padded per-device work of the uniform program: sharding may
    # never cost more, and must strictly win on the imbalanced plan
    assert sharded <= owner, (F, sharded, owner)
    assert sharded < owner, (F, sharded, owner)


def test_shard_tables_are_a_bijection_over_the_cohort():
    plan, cohorts, shard = _imbalanced_plan(F=3)
    P = plan.num_devices
    for f in range(3):
        for bdim in plan.bucket_dims:
            b = plan.buckets[bdim]
            R = cohorts.rows[bdim].shape[2]
            S = shard.src[bdim].shape[2]
            # valid cohort rows, as stored global rows
            cohort_rows = {d * b.per_dev + int(r)
                           for d in range(P)
                           for r, v in zip(cohorts.rows[bdim][f, d],
                                           cohorts.valid[bdim][f, d])
                           if v}
            # src tables: each valid slot names a gathered cohort slot
            # and the stored row it refreshes — collectively exactly
            # the cohort, each exactly once
            seen_rows = []
            for p in range(P):
                for j in range(S):
                    if shard.src_valid[bdim][f, p, j]:
                        src_flat = int(shard.src[bdim][f, p, j])
                        d, r = divmod(src_flat, R)
                        assert cohorts.valid[bdim][f, d, r]
                        grow = d * b.per_dev + int(
                            cohorts.rows[bdim][f, d, r])
                        assert grow == int(shard.src_global[bdim][f, p, j])
                        # the res table routes the result slot back to
                        # this exact stored row
                        assert int(shard.res_slot[bdim][f, grow]) == p * S + j
                        assert bool(shard.res_valid[bdim][f, grow])
                        seen_rows.append(grow)
            assert sorted(seen_rows) == sorted(cohort_rows)
            # rows outside the cohort never marked fresh
            outside = set(range(b.n_rows)) - cohort_rows
            for grow in outside:
                assert not shard.res_valid[bdim][f, grow]


def test_comm_volume_decomp_comm_entry():
    plan, cohorts, shard = _imbalanced_plan(F=2)
    v0 = plan.comm_volume(stats_reduce='local', method='eigh')
    assert v0['DecompComm'] == 0
    v = plan.comm_volume(stats_reduce='local', method='eigh',
                         decomp_shard=shard)
    assert v['DecompComm'] > 0
    # the shard exchange REPLACES the staggered InverseComm gather:
    # pricing both would over-count the sharded step
    vi = plan.comm_volume(stats_reduce='local', method='eigh',
                          comm_mode='inverse', decomp_shard=shard)
    assert vi['InverseComm'] == 0 and vi['DecompComm'] == v['DecompComm']
    # bf16 wire halves the shard exchange like every other gather
    v16 = plan.comm_volume(stats_reduce='local', method='eigh',
                           comm_precision='bf16', decomp_shard=shard)
    assert v16['DecompComm'] == v['DecompComm'] // 2
    # cholesky ships no eigenvalue vectors
    vc = plan.comm_volume(stats_reduce='local', method='cholesky',
                          decomp_shard=shard)
    assert vc['DecompComm'] < v['DecompComm']


# ---------------------------------------------------------------------------
# exactness: sharded == owner-local, bit for bit
# ---------------------------------------------------------------------------

@pytest.mark.parametrize('variant', ['eigen_dp', 'inverse_dp', 'eigen',
                                     'inverse'])
def test_shard_world1_bit_parity(variant):
    F = 3
    ps, ss, grads, acts, gs, _ = _setup(variant, kfac_update_freq=F,
                                        decomp_shard=True)
    assert ps.stagger  # decomp_shard implies the staggered schedule
    po, so, *_ = _setup(variant, kfac_update_freq=F, stagger=True)
    _, ss = ps.step(ss, grads, acts, gs)
    _, so = po.step(so, grads, acts, gs)
    for t in range(2 * F):
        _, ss = ps.step(ss, grads, acts, gs, stagger_update=True)
        _, so = po.step(so, grads, acts, gs, stagger_update=True)
    for comp in ss.decomp:
        for k in ss.decomp[comp]:
            np.testing.assert_array_equal(
                np.asarray(ss.decomp[comp][k]),
                np.asarray(so.decomp[comp][k]),
                err_msg=f'{variant} {comp}[{k}]')


def _batch(n=8):
    rng = np.random.RandomState(0)
    return {'input': jnp.asarray(rng.randn(n, 5), jnp.float32),
            'label': jnp.asarray(rng.randint(0, 3, n))}


def _ce(outputs, batch):
    return optax.softmax_cross_entropy_with_integer_labels(
        outputs, batch['label']).mean()


def _trainer(shard, variant, F=2, ndev=2, lr=0.0):
    model = MLP()
    mesh = Mesh(np.array(jax.devices()[:ndev]), ('batch',))
    precond = kfac.KFAC(variant=variant, lr=lr, damping=0.003,
                        fac_update_freq=1, kfac_update_freq=F,
                        num_devices=ndev, axis_name='batch',
                        bucket_fn=lambda d: 16, stagger=True,
                        decomp_shard=shard)
    tx = training.sgd(lr, momentum=0.9)
    state = training.init_train_state(model, tx, precond,
                                      jax.random.PRNGKey(0),
                                      _batch()['input'])
    step = training.build_train_step(model, tx, precond, _ce,
                                     axis_name='batch', mesh=mesh)
    return step, state, precond


@pytest.mark.parametrize('variant', ['eigen_dp', 'eigen'])
def test_shard_world2_trainer_bit_parity(variant):
    """Through the jitted trainer on a 2-device fake mesh with frozen
    params (lr=0): the sharded run's decomposition state is
    bit-identical to the owner-local staggered run's — for both the
    sharded store ('eigen_dp', comm_pred) and the replicated store
    ('eigen', comm_inverse, where the shard exchange REPLACES the
    stagger merge gather)."""
    batch = _batch()
    step_s, state_s, _ = _trainer(True, variant)
    step_o, state_o, _ = _trainer(False, variant)
    for _ in range(5):
        state_s, _ = step_s(state_s, batch, lr=0.0, damping=0.003)
        state_o, _ = step_o(state_o, batch, lr=0.0, damping=0.003)
    for comp in state_s.kfac_state.decomp:
        for k in state_s.kfac_state.decomp[comp]:
            np.testing.assert_array_equal(
                np.asarray(state_s.kfac_state.decomp[comp][k]),
                np.asarray(state_o.kfac_state.decomp[comp][k]),
                err_msg=f'{variant} {comp}[{k}]')


def test_shard_trainer_trains_finite_with_lr():
    """End-to-end sanity: a real (lr>0) sharded run stays finite and
    actually moves the params."""
    batch = _batch()
    step, state, _ = _trainer(True, 'eigen_dp', lr=0.05)
    p0 = jax.tree.map(lambda a: np.asarray(a).copy(), state.params)
    for _ in range(5):
        state, m = step(state, batch, lr=0.05, damping=0.003)
    assert np.isfinite(float(m['loss']))
    moved = any(not np.array_equal(a, np.asarray(b)) for a, b in zip(
        jax.tree.leaves(p0), jax.tree.leaves(state.params)))
    assert moved


# ---------------------------------------------------------------------------
# health + rebase + validation
# ---------------------------------------------------------------------------

def test_shard_merge_guard_keeps_stored_rows_on_nonfinite():
    ps, ss, grads, acts, gs, _ = _setup('eigen_dp', kfac_update_freq=2,
                                        decomp_shard=True)
    _, ss = ps.step(ss, grads, acts, gs)
    shard = ps.decomp_shard_plan
    cohort_idx = jnp.int32(1)
    results = engine.compute_shard_decomposition(
        ps.plan, ps.cohorts, shard, ss.factors, cohort_idx,
        jnp.float32(ps.damping), ps.method, ps.eps, None)
    poisoned = jax.tree.map(lambda x: jnp.full_like(x, jnp.nan), results)
    merged = engine.merge_shard_decomposition(
        ps.plan, shard, ss.decomp, poisoned, cohort_idx, None,
        ps.comm_mode, ps.method, guard=True)
    for comp in ('evals', 'evecs'):
        for key in merged[comp]:
            np.testing.assert_array_equal(np.asarray(merged[comp][key]),
                                          np.asarray(ss.decomp[comp][key]))
    # guard off: the NaNs land (the screen is what saved it)
    raw = engine.merge_shard_decomposition(
        ps.plan, shard, ss.decomp, poisoned, cohort_idx, None,
        ps.comm_mode, ps.method, guard=False)
    assert any(not np.isfinite(np.asarray(v)).all()
               for comp in ('evals', 'evecs') for v in raw[comp].values())


def test_scheduler_rescale_rebuilds_shard_plan():
    ps, *_ = _setup('eigen_dp', kfac_update_freq=4, decomp_shard=True)
    assert ps.decomp_shard_plan.num_cohorts == 4
    sched = kfac.KFACParamScheduler(ps, update_freq_alpha=2,
                                    update_freq_schedule=[1])
    sched.step(1)
    assert ps.kfac_update_freq == 8
    assert ps.cohorts.num_cohorts == 8
    assert ps.decomp_shard_plan.num_cohorts == 8
    # coverage preserved across the rebase
    total = sum(int(ps.plan.buckets[b].valid.sum())
                for b in ps.plan.bucket_dims)
    assert int(ps.decomp_shard_plan.shard_count.sum()) == 8 * 0 + total


@pytest.mark.filterwarnings('ignore::UserWarning')
def test_decomp_shard_and_impl_validation():
    # decomp_shard implies stagger, and inherits stagger's exclusions
    p = kfac.KFAC(variant='eigen_dp', decomp_shard=True, num_devices=1,
                  axis_name=None)
    assert p.stagger
    with pytest.raises(ValueError, match='ekfac'):
        kfac.KFAC(variant='ekfac_dp', decomp_shard=True, num_devices=1,
                  axis_name=None)
    with pytest.raises(ValueError, match='CommunicateInverse'):
        kfac.KFAC(variant='eigen_dp', decomp_shard=True,
                  exclude_parts='CommunicateInverse', num_devices=1,
                  axis_name=None)
    # method-mismatched kernels rejected at construction
    with pytest.raises(ValueError, match='newton_schulz'):
        kfac.KFAC(variant='eigen_dp', decomp_impl='newton_schulz',
                  num_devices=1, axis_name=None)
    with pytest.raises(ValueError, match='eigh kernel'):
        kfac.KFAC(variant='inverse_dp', decomp_impl='subspace',
                  num_devices=1, axis_name=None)
    with pytest.raises(ValueError, match='decomp_impl'):
        kfac.KFAC(variant='eigen_dp', decomp_impl='bogus',
                  num_devices=1, axis_name=None)
    # 'auto' resolves per method
    assert kfac.KFAC(variant='eigen_dp', decomp_impl='auto',
                     num_devices=1, axis_name=None
                     ).resolved_decomp_impl == 'subspace'
    assert kfac.KFAC(variant='inverse_dp', decomp_impl='auto',
                     num_devices=1, axis_name=None
                     ).resolved_decomp_impl == 'newton_schulz'


# ---------------------------------------------------------------------------
# decomp_impl engine paths
# ---------------------------------------------------------------------------

@pytest.mark.parametrize('variant,impl', [('eigen_dp', 'subspace'),
                                          ('inverse_dp', 'newton_schulz')])
def test_decomp_impl_full_path_tracks_xla(variant, impl):
    """The iterative kernels (explicit decomp_impl, warm through the
    trainer gate) track the cold kernel's preconditioned gradients:
    exactly for Newton-Schulz (residual gate at f32 noise), loosely for
    subspace (any orthogonal basis of a cluster is equivalent)."""
    model = MLP()
    batch = _batch(4)

    def run(decomp_impl):
        precond = kfac.KFAC(variant=variant, lr=0.05, damping=0.003,
                            fac_update_freq=1, kfac_update_freq=2,
                            num_devices=1, axis_name=None,
                            bucket_fn=lambda d: 16,
                            decomp_impl=decomp_impl)
        tx = training.sgd(0.05, momentum=0.9)
        state = training.init_train_state(model, tx, precond,
                                          jax.random.PRNGKey(0),
                                          batch['input'])
        step = training.build_train_step(model, tx, precond, _ce)
        losses = []
        for _ in range(8):
            state, m = step(state, batch, lr=0.05, damping=0.003)
            losses.append(float(m['loss']))
        return losses

    base = run('xla')
    warm = run(impl)
    assert all(np.isfinite(warm))
    # early steps track tightly; later ones compound the kernels'
    # bounded approximation (NS residual gate 5%; subspace cluster
    # mixing) — the contract is "same optimizer", not bit equality
    np.testing.assert_allclose(warm[:4], base[:4], rtol=0.05)
    assert warm[-1] < 0.75 * warm[0]          # still genuinely training
    assert abs(warm[-1] - base[-1]) < 0.3 * base[0]


@pytest.mark.parametrize('variant,impl', [('eigen_dp', 'subspace'),
                                          ('inverse_dp', 'newton_schulz')])
def test_decomp_impl_stagger_path_stays_close(variant, impl):
    """The staggered cohort path seeds the iterative kernels from the
    stored decomposition (frozen factors: the warm result equals the
    cold one to f32 noise)."""
    F = 2
    ps, ss, grads, acts, gs, _ = _setup(variant, kfac_update_freq=F,
                                        stagger=True, decomp_impl=impl)
    po, so, *_ = _setup(variant, kfac_update_freq=F, stagger=True)
    _, ss = ps.step(ss, grads, acts, gs)
    _, so = po.step(so, grads, acts, gs)
    for _ in range(2 * F):
        _, ss = ps.step(ss, grads, acts, gs, stagger_update=True)
        _, so = po.step(so, grads, acts, gs, stagger_update=True)
    # tolerance = what the kernels promise: subspace re-fits the
    # spectrum near-exactly under slow drift; the NS result is accepted
    # at residual max|I - A X| <= NS_ACCEPT_RESID (5%), so its inverse
    # is close-but-not-bit-equal to the Cholesky one
    if ps.method == 'cholesky':
        comps, rtol, atol = ['invs'], 0.15, 1e-2
    else:
        comps, rtol, atol = ['evals'], 2e-4, 2e-5
    for comp in comps:
        for k in ss.decomp[comp]:
            np.testing.assert_allclose(np.asarray(ss.decomp[comp][k]),
                                       np.asarray(so.decomp[comp][k]),
                                       rtol=rtol, atol=atol,
                                       err_msg=f'{comp}[{k}]')


def test_decomp_impls_agree_across_modules():
    """autotune restates the preconditioner's impl tuple (stdlib-only
    import constraint) — they must never drift apart."""
    from kfac_pytorch_tpu import autotune, preconditioner
    assert autotune.DECOMP_IMPLS == preconditioner.DECOMP_IMPLS
    for method, ladder in autotune.DECOMP_LADDERS.items():
        assert set(ladder) <= set(preconditioner.DECOMP_IMPLS)
