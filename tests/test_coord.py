"""The pluggable coordination backend (kfac_pytorch_tpu/coord/).

Pins the tentpole contracts with NO subprocesses (the real-process
drills live in tests/test_pod_chaos.py / test_service_chaos.py behind
-m slow):

1. Both backends honor the primitive contract — atomic puts, versioned
   CAS (create-only / expected-version / ANY), prefix list/scan,
   delete(-prefix), poll-based watch — and the POSIX backend is
   BYTE-compatible with the atomic-rename protocol files everything
   already reads.
2. The TCP KV backend is a real non-POSIX store: namespace isolation,
   server-enforced TTL leases, CoordTimeout (never a hang) when the
   server is gone.
3. ChaosBackend's faults are seeded and deterministic — op failures,
   outage windows, torn/stale reads, spurious CAS conflicts, premature
   lease expiry — and the strict faults.from_env surface rejects
   typo'd drills.
4. RetryingBackend rides out transients with bounded jittered backoff
   and gives up LOUDLY (CoordGiveUp + the machine-greppable form).
5. The queue's epoch CAS stays exactly-once on both backends, even
   under injected coordination faults; the shrink barrier still fences
   the minority instead of split-braining on the KV backend.
6. The static gate: no protocol code outside coord/ touches lease-dir
   files directly anymore — the lint that keeps the abstraction from
   rotting.
"""

import json
import os
import random
import threading
import time

import pytest

from kfac_pytorch_tpu import coord
from kfac_pytorch_tpu.coord import (
    ANY, ChaosBackend, CoordFaultConfig, CoordGiveUp, CoordTimeout,
    PosixDirBackend, ReplicatedKvBackend, RetryingBackend, TcpKvBackend,
    TcpKvServer)
from kfac_pytorch_tpu.resilience import atomic_write_json
from kfac_pytorch_tpu.resilience.retry import ManualClock, RetryPolicy

pytestmark = pytest.mark.core

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope='module')
def kv_server():
    srv = TcpKvServer('127.0.0.1', 0)
    yield srv
    srv.close()


@pytest.fixture(scope='module')
def kv_trio():
    servers = [TcpKvServer('127.0.0.1', 0) for _ in range(3)]
    yield servers
    for srv in servers:
        srv.close()


def _replicated(kv_trio, namespace, **kw):
    return ReplicatedKvBackend(
        [TcpKvBackend(('127.0.0.1', srv.port), namespace=namespace)
         for srv in kv_trio], **kw)


@pytest.fixture(params=['posix', 'tcp', 'replicated'])
def backend(request, tmp_path, kv_server, kv_trio):
    if request.param == 'posix':
        return PosixDirBackend(str(tmp_path / 'root'))
    if request.param == 'tcp':
        return TcpKvBackend(('127.0.0.1', kv_server.port),
                            namespace=str(tmp_path / 'root'))
    # the full primitive contract must hold through the quorum merge
    # too — same tests, zero special-casing
    return _replicated(kv_trio, str(tmp_path / 'root'))


# ---------------------------------------------------------------------------
# the primitive contract, on both backends
# ---------------------------------------------------------------------------

def test_put_get_roundtrip_and_versions(backend):
    assert backend.get('a/x.json') is None
    v1 = backend.put('a/x.json', {'host': 1, 'seq': 1})
    got = backend.get('a/x.json')
    assert got.value == {'host': 1, 'seq': 1}
    assert got.version == v1
    v2 = backend.put('a/x.json', {'host': 1, 'seq': 2})
    assert v2 != v1
    assert backend.get('a/x.json').value['seq'] == 2


def test_put_cas_expected_version(backend):
    backend.put('job.json', {'epoch': 0})
    got = backend.get('job.json')
    # stale token refused, nothing applied
    assert backend.put_cas('job.json', {'epoch': 9}, 'bogus') is None
    assert backend.get('job.json').value == {'epoch': 0}
    # matching token applies and returns a NEW version
    v2 = backend.put_cas('job.json', {'epoch': 1}, got.version)
    assert v2 is not None and v2 != got.version
    # the consumed token is now stale
    assert backend.put_cas('job.json', {'epoch': 2},
                           got.version) is None
    assert backend.get('job.json').value == {'epoch': 1}


def test_put_cas_create_only_and_any(backend):
    assert backend.put_cas('new.json', {'n': 1}, None) is not None
    assert backend.put_cas('new.json', {'n': 2}, None) is None
    assert backend.get('new.json').value == {'n': 1}
    assert backend.put_cas('new.json', {'n': 3}, ANY) is not None
    assert backend.get('new.json').value == {'n': 3}


def test_list_prefix_and_get_many(backend):
    backend.put('shrink-gen3/survivor-0.json', {'host': 0})
    backend.put('shrink-gen3/survivor-2.json', {'host': 2})
    backend.put('grow-gen4/member-1.json', {'host': 1})
    backend.put('lineage.json', {'lineage': 2})
    assert backend.list('shrink-gen3/') == [
        'shrink-gen3/survivor-0.json', 'shrink-gen3/survivor-2.json']
    many = backend.get_many('shrink-gen3/')
    assert {p['host'] for p in many.values()} == {0, 2}
    # a bare prefix scans across "directories"
    assert 'grow-gen4/member-1.json' in backend.list('grow-gen')


def test_delete_and_delete_prefix(backend):
    backend.put('grow-gen2/member-0.json', {'host': 0})
    backend.put('grow-gen2/member-1.json', {'host': 1})
    backend.put('keep.json', {})
    assert backend.delete('grow-gen2/member-0.json') is True
    assert backend.delete('grow-gen2/member-0.json') is False
    assert backend.delete_prefix('grow-gen2/') == 1
    assert backend.list('grow-gen2/') == []
    assert backend.get('keep.json') is not None


def test_watch_reports_puts_and_deletes(backend):
    backend.put('w/a.json', {'v': 1})
    w = backend.watch('w/')
    assert w.poll() == {'w/a.json': 'put'}
    assert w.poll() == {}
    backend.put('w/a.json', {'v': 2})
    backend.put('w/b.json', {'v': 1})
    changes = w.poll()
    assert changes == {'w/a.json': 'put', 'w/b.json': 'put'}
    backend.delete('w/b.json')
    assert w.poll() == {'w/b.json': 'delete'}


def test_bad_keys_rejected(backend):
    for bad in ('/abs', 'a/../b', '', 'a//b'):
        with pytest.raises(ValueError):
            backend.put(bad, {})


# ---------------------------------------------------------------------------
# POSIX specifics: byte-compat + torn reads
# ---------------------------------------------------------------------------

def test_posix_bytes_identical_to_atomic_write_json(tmp_path):
    """The rolling-upgrade contract: the backend's files are the SAME
    bytes the old direct writers produced, so mixed-version pods and
    every existing drill grammar keep working."""
    b = PosixDirBackend(str(tmp_path))
    payload = {'host': 1, 'seq': 7, 'addr': None, 'wall': 123.5}
    b.put('hb-1.json', payload)
    atomic_write_json(str(tmp_path / 'ref.json'), payload)
    assert (tmp_path / 'hb-1.json').read_bytes() \
        == (tmp_path / 'ref.json').read_bytes()
    # and the indent=2 form (queue records) matches too
    b.put('job.json', payload, indent=2)
    atomic_write_json(str(tmp_path / 'ref2.json'), payload, indent=2)
    assert (tmp_path / 'job.json').read_bytes() \
        == (tmp_path / 'ref2.json').read_bytes()


def test_posix_torn_read_returns_none_then_recovers(tmp_path):
    b = PosixDirBackend(str(tmp_path))
    (tmp_path / 'claim.json').write_text('{"host": 1, "ad')  # torn
    assert b.get('claim.json') is None
    b.put('claim.json', {'host': 1})
    assert b.get('claim.json').value == {'host': 1}


def test_posix_does_not_scaffold_root_on_reads(tmp_path):
    missing = tmp_path / 'nope'
    b = PosixDirBackend(str(missing))
    assert b.get('x.json') is None and b.list('') == []
    assert not missing.exists()


# ---------------------------------------------------------------------------
# TCP KV specifics: namespaces, TTL leases, dead server
# ---------------------------------------------------------------------------

def test_tcpkv_namespace_isolation(kv_server):
    a = TcpKvBackend(('127.0.0.1', kv_server.port), namespace='/pod/a')
    b = TcpKvBackend(('127.0.0.1', kv_server.port), namespace='/pod/b')
    a.put('hb-0.json', {'seq': 1})
    assert b.get('hb-0.json') is None
    assert b.list('') == []
    assert a.get('hb-0.json').value == {'seq': 1}


def test_tcpkv_ttl_lease_expires_server_side(kv_server):
    b = TcpKvBackend(('127.0.0.1', kv_server.port),
                     namespace='/ttl-test')
    lease = b.lease('hb-0.json', 0.2, {'seq': 1})
    assert b.get('hb-0.json') is not None
    lease.refresh({'seq': 2})   # refresh restarts the TTL
    time.sleep(0.12)
    assert b.get('hb-0.json').value == {'seq': 2}
    time.sleep(0.35)
    assert b.get('hb-0.json') is None       # expired: owner went silent
    assert b.list('') == []                 # and it is gone from scans


def test_tcpkv_dead_server_raises_coord_timeout():
    srv = TcpKvServer('127.0.0.1', 0)
    port = srv.port
    srv.close()
    b = TcpKvBackend(('127.0.0.1', port), namespace='/x', timeout=0.3)
    with pytest.raises(CoordTimeout):
        b.get('anything.json')
    with pytest.raises(CoordTimeout):
        b.put('anything.json', {})


def test_tcpkv_reuses_one_socket_across_ops(kv_server, tmp_path):
    """Connection reuse is the point of the persistent client: many
    ops, ONE socket — no per-op connect()/close() churn against the
    store every heartbeat tick."""
    b = TcpKvBackend(('127.0.0.1', kv_server.port),
                     namespace=str(tmp_path / 'ns'))
    b.put('a.json', {'v': 0})
    sock = b._sock
    assert sock is not None
    for i in range(5):
        b.put('a.json', {'v': i})
        assert b.get('a.json').value == {'v': i}
        b.list('')
    assert b._sock is sock      # still the first connection
    b.close()
    assert b._sock is None


def test_tcpkv_reused_socket_absorbs_server_restart(tmp_path):
    """The mid-stream restart pin: a stale reused socket must be
    transparent for idempotent READS (resent once on a fresh
    connection), LOUD for writes (the op may or may not have applied —
    replay safety belongs to the CAS-token layer, not the socket)."""
    srv = TcpKvServer('127.0.0.1', 0)
    port = srv.port
    ns = str(tmp_path / 'ns')
    b = TcpKvBackend(('127.0.0.1', port), namespace=ns, timeout=0.5)
    try:
        b.put('k.json', {'v': 1})
        stale = b._sock
        assert stale is not None
        srv.close()
        srv = TcpKvServer('127.0.0.1', port)   # restart, same port
        # read on the stale socket: absorbed (fresh store -> None),
        # and the client is now on a NEW connection
        assert b.get('k.json') is None
        assert b._sock is not None and b._sock is not stale
        b.put('k.json', {'v': 2})
        srv.close()
        srv = TcpKvServer('127.0.0.1', port)
        # write on the stale socket: surfaced, never silently replayed
        with pytest.raises(CoordTimeout):
            b.put('k.json', {'v': 3})
        # and the very next op reconnects cleanly
        assert b.get('k.json') is None
        b.put('k.json', {'v': 4})
        assert b.get('k.json').value == {'v': 4}
    finally:
        srv.close()
        b.close()


# ---------------------------------------------------------------------------
# ReplicatedKvBackend: absorb one replica, repair it, degrade loudly
# ---------------------------------------------------------------------------

def _trio(tmp_path, **kw):
    servers = [TcpKvServer('127.0.0.1', 0) for _ in range(3)]
    kw.setdefault('down_cooldown', 0.05)
    b = ReplicatedKvBackend(
        [TcpKvBackend(('127.0.0.1', s.port),
                      namespace=str(tmp_path / 'ns'), timeout=0.4)
         for s in servers], **kw)
    return servers, b


def test_replicated_one_replica_down_is_invisible(tmp_path):
    servers, b = _trio(tmp_path)
    try:
        b.put('a.json', {'v': 1})
        servers[1].close()
        # every primitive keeps answering on the 2/3 quorum — zero
        # caller-visible errors
        assert b.get('a.json').value == {'v': 1}
        b.put('a.json', {'v': 2})
        got = b.get('a.json')
        assert got.value == {'v': 2}
        assert b.put_cas('a.json', {'v': 3}, got.version) is not None
        assert b.get('a.json').value == {'v': 3}
        assert b.list('') == ['a.json']
        assert b.counts.get('replica_down', 0) >= 1
    finally:
        for s in servers:
            s.close()


def test_replicated_restarted_empty_replica_is_repaired(tmp_path):
    servers, b = _trio(tmp_path)
    try:
        b.put('a.json', {'v': 1})
        port = servers[1].port
        servers[1].close()
        b.put('a.json', {'v': 2})          # applied on replicas 0, 2
        servers[1] = TcpKvServer('127.0.0.1', port)  # EMPTY store
        time.sleep(0.06)                   # past the down cooldown
        # the majority answer wins; the lagging replica is repaired
        # read-through in the same pass
        assert b.get('a.json').value == {'v': 2}
        assert b.counts.get('replica_repair', 0) >= 1
        direct = TcpKvBackend(('127.0.0.1', port),
                              namespace=str(tmp_path / 'ns'))
        envelope = direct.get('a.json').value
        assert envelope['v'] == {'v': 2}   # caught back up
        direct.close()
    finally:
        for s in servers:
            s.close()


def test_replicated_quorum_loss_is_loud(tmp_path):
    servers, b = _trio(tmp_path)
    try:
        b.put('a.json', {'v': 1})
        servers[0].close()
        servers[2].close()
        with pytest.raises(CoordTimeout, match='quorum'):
            b.get('a.json')
        with pytest.raises(CoordTimeout, match='quorum'):
            b.put('a.json', {'v': 2})
    finally:
        for s in servers:
            s.close()


def test_backend_from_env_replicated(tmp_path, kv_trio, monkeypatch):
    monkeypatch.setenv(coord.ENV_BACKEND, 'replicated')
    monkeypatch.delenv(coord.ENV_ADDRS, raising=False)
    with pytest.raises(ValueError, match='KFAC_COORD_ADDRS'):
        coord.backend_from_env(str(tmp_path), retry=False)
    monkeypatch.setenv(coord.ENV_ADDRS, f'127.0.0.1:{kv_trio[0].port}')
    with pytest.raises(ValueError, match='at least 2'):
        coord.backend_from_env(str(tmp_path), retry=False)
    monkeypatch.setenv(
        coord.ENV_ADDRS,
        ','.join(f'127.0.0.1:{s.port}' for s in kv_trio))
    b = coord.backend_from_env(str(tmp_path), retry=False)
    assert isinstance(b, ReplicatedKvBackend)
    wrapped = coord.backend_from_env(str(tmp_path))
    assert isinstance(wrapped, RetryingBackend)
    assert isinstance(wrapped.inner, ReplicatedKvBackend)
    wrapped.put('x.json', {'v': 1})
    assert wrapped.get('x.json').value == {'v': 1}
    # armed chaos lands PER REPLICA (decorrelated seeds), never on the
    # merge — a lockstep fault on all three is the one correlated
    # failure a quorum cannot absorb, so the drill must not inject it
    monkeypatch.setenv('KFAC_FAULT_COORD_FAIL', '0.25')
    monkeypatch.setenv('KFAC_FAULT_COORD_SEED', '7')
    b = coord.backend_from_env(str(tmp_path / 'chaos'), retry=False)
    assert isinstance(b, ReplicatedKvBackend)
    seeds = set()
    for rep in b.replicas:
        assert isinstance(rep, ChaosBackend)
        seeds.add(rep.cfg.seed)
    assert len(seeds) == len(b.replicas)


def test_shrink_majority_commits_on_replicated_backend(tmp_path,
                                                       kv_trio):
    """The barrier + lineage bump land through the quorum merge — with
    one replica ALREADY DEAD the whole time."""
    from kfac_pytorch_tpu.resilience.elastic import PodSupervisor
    servers = [TcpKvServer('127.0.0.1', 0) for _ in range(3)]
    backend = ReplicatedKvBackend(
        [TcpKvBackend(('127.0.0.1', s.port),
                      namespace=str(tmp_path / 'lease'), timeout=0.4)
         for s in servers], down_cooldown=0.05)
    servers[2].close()                    # one replica down mid-drill
    try:
        sup = PodSupervisor(['trainer'], host_id=0, num_hosts=3,
                            lease_dir=str(tmp_path / 'lease'),
                            coord=backend, settle=0.0,
                            shrink_timeout=0.15, poll_period=0.01)
        backend.put('shrink-gen1/survivor-2.json',
                    {'host': 2, 'addr': None})
        assert sup._shrink({1: {}}) is True
        assert sup.members == [0, 2] and sup.gen == 1
        assert backend.get('lineage.json').value['lineage'] == 1
        assert sup._current_lineage() == 1
        sup._hb.stop()
    finally:
        for s in servers:
            s.close()


def test_backend_from_env_selection(tmp_path, kv_server, monkeypatch):
    monkeypatch.delenv(coord.ENV_BACKEND, raising=False)
    b = coord.backend_from_env(str(tmp_path), retry=False)
    assert isinstance(b, PosixDirBackend)
    monkeypatch.setenv(coord.ENV_BACKEND, 'tcp')
    with pytest.raises(ValueError, match='KFAC_COORD_ADDR'):
        coord.backend_from_env(str(tmp_path), retry=False)
    monkeypatch.setenv(coord.ENV_ADDR, f'127.0.0.1:{kv_server.port}')
    b = coord.backend_from_env(str(tmp_path), retry=False)
    assert isinstance(b, TcpKvBackend)
    assert isinstance(coord.backend_from_env(str(tmp_path)),
                      RetryingBackend)
    monkeypatch.setenv(coord.ENV_BACKEND, 'zookeeper')
    with pytest.raises(ValueError, match='posix.*tcp|tcp.*posix'):
        coord.backend_from_env(str(tmp_path))


# ---------------------------------------------------------------------------
# ChaosBackend: seeded, deterministic, each fault lane real
# ---------------------------------------------------------------------------

def _chaos(tmp_path, name='c', **cfg):
    return ChaosBackend(PosixDirBackend(str(tmp_path / name)),
                        CoordFaultConfig(**cfg))


def test_chaos_schedule_is_deterministic(tmp_path):
    def run(name):
        b = _chaos(tmp_path, name, seed=11, fail=0.4, torn=0.3)
        outcomes = []
        for i in range(30):
            try:
                b.put('k.json', {'i': i})
                outcomes.append('put')
            except CoordTimeout:
                outcomes.append('fail')
            got = None
            try:
                got = b.get('k.json')
            except CoordTimeout:
                outcomes.append('gfail')
            outcomes.append('none' if got is None else 'val')
        return outcomes, list(b.trace)
    o1, t1 = run('a')
    o2, t2 = run('b')
    assert o1 == o2
    assert [e[:2] for e in t1] == [e[:2] for e in t2]
    assert 'fail' in o1 and 'none' in o1 and 'val' in o1


def test_chaos_outage_window_fails_every_op(tmp_path):
    now = time.time()
    b = _chaos(tmp_path, seed=1, windows=((0.0, 3600.0),), t0=now)
    for op in (lambda: b.get('x.json'), lambda: b.put('x.json', {}),
               lambda: b.list(''), lambda: b.delete('x.json')):
        with pytest.raises(CoordTimeout):
            op()
    assert b.counts['window'] >= 4
    # outside the window everything works
    b2 = _chaos(tmp_path, 'c2', seed=1, windows=((1000.0, 2000.0),),
                t0=now)
    b2.put('x.json', {'ok': 1})
    assert b2.get('x.json').value == {'ok': 1}


def test_chaos_torn_read_presents_as_skip(tmp_path):
    b = _chaos(tmp_path, seed=5, torn=1.0)
    b.put('x.json', {'v': 1})
    assert b.get('x.json') is None
    assert b.counts['torn'] >= 1


def test_chaos_stale_read_returns_previous_value(tmp_path):
    b = _chaos(tmp_path, seed=2, stale=1.0)
    b.put('x.json', {'v': 1})
    first = b.get('x.json')          # no previous value yet: fresh
    assert first.value == {'v': 1}
    b.put('x.json', {'v': 2})
    assert b.get('x.json').value == {'v': 1}   # stale: the OLD value
    assert b.counts['stale'] >= 1


def test_chaos_spurious_cas_conflict_not_applied(tmp_path):
    b = _chaos(tmp_path, seed=3, cas=1.0)
    inner = b.inner
    inner.put('job.json', {'epoch': 0})
    got = inner.get('job.json')
    assert b.put_cas('job.json', {'epoch': 1}, got.version) is None
    # NOT applied: the caller re-reads and re-derives, nothing moved
    assert inner.get('job.json').value == {'epoch': 0}
    assert b.counts['cas_conflict'] == 1


def test_chaos_premature_lease_expiry_drops_publish(tmp_path):
    b = _chaos(tmp_path, seed=4, lease_expire=1.0)
    b.put('hb-0.json', {'seq': 1}, ttl=5.0)    # a lease publish: dropped
    assert b.inner.get('hb-0.json') is None
    assert b.counts['lease_expire'] == 1
    b.put('claim.json', {'host': 0})           # non-lease put: untouched
    assert b.inner.get('claim.json') is not None


def test_chaos_env_contract_is_strict(monkeypatch):
    from kfac_pytorch_tpu.coord import chaos
    monkeypatch.setenv('KFAC_FAULT_COORD_SEED', '7')
    monkeypatch.setenv('KFAC_FAULT_COORD_FAIL', '0.25')
    monkeypatch.setenv('KFAC_FAULT_COORD_WINDOWS', '5:10;20:30')
    cfg = chaos.from_env()
    assert cfg.seed == 7 and cfg.fail == 0.25
    assert cfg.windows == ((5.0, 10.0), (20.0, 30.0))
    monkeypatch.setenv('KFAC_FAULT_COORD_FAIL', '1.5')
    with pytest.raises(ValueError):
        chaos.from_env()
    monkeypatch.setenv('KFAC_FAULT_COORD_FAIL', '0.1')
    monkeypatch.setenv('KFAC_FAULT_COORD_WINDOWS', '10:5')
    with pytest.raises(ValueError):
        chaos.from_env()


def test_faults_from_env_registers_coord_drills(monkeypatch):
    faults = pytest.importorskip('kfac_pytorch_tpu.faults')
    monkeypatch.setenv('KFAC_FAULT_COORD_SEED', '1')
    monkeypatch.setenv('KFAC_FAULT_COORD_CAS', '0.5')
    faults.from_env()  # known + well-formed: accepted
    monkeypatch.setenv('KFAC_FAULT_COORD_CASS', '0.5')  # typo
    with pytest.raises(ValueError, match='KFAC_FAULT_COORD_CASS'):
        faults.from_env()


# ---------------------------------------------------------------------------
# RetryingBackend: ride out transients, give up loudly
# ---------------------------------------------------------------------------

def _retrying(inner, attempts=6):
    clock = ManualClock()
    rb = RetryingBackend(
        inner, policy=RetryPolicy(attempts=attempts, base_delay=0.05,
                                  max_delay=0.5,
                                  retry_on=(CoordTimeout,)),
        clock=clock, rng=random.Random(0))
    return rb, clock


def test_retrying_backend_rides_out_transients(tmp_path):
    from kfac_pytorch_tpu import resilience
    resilience.counters.reset()
    b = _chaos(tmp_path, seed=11, fail=0.5)
    rb, clock = _retrying(b)
    for i in range(10):
        rb.put('k.json', {'i': i})
    assert rb.get('k.json').value == {'i': 9}
    stats = rb.stats()
    assert stats['retries'] >= 1 and stats['gave_up'] == 0
    assert stats['wait_s'] > 0 and clock.sleeps
    assert resilience.counters.get('coord_retries') == stats['retries']


def test_retrying_backend_gives_up_loudly(tmp_path, caplog):
    b = _chaos(tmp_path, seed=1, fail=1.0)
    rb, _ = _retrying(b, attempts=3)
    with caplog.at_level('ERROR', logger='kfac_pytorch_tpu.coord.base'):
        with pytest.raises(CoordGiveUp):
            rb.get('z.json')
    assert rb.stats()['gave_up'] == 1
    text = '\n'.join(r.getMessage() for r in caplog.records)
    assert 'coord: giving up op=get' in text
    assert '[resilience: coord_gave_up=1]' in text
    # the incident grammar picks the give-up out of a scraped log
    from kfac_pytorch_tpu.resilience.incident import IncidentReport
    report = IncidentReport().scrape_lines(text.splitlines())
    assert any(e['kind'] == 'coord_gave_up' for e in report.events)


def test_cas_conflict_is_an_answer_not_a_retry(tmp_path):
    b = _chaos(tmp_path, seed=3, cas=1.0)
    rb, clock = _retrying(b)
    b.inner.put('j.json', {'epoch': 0})
    got = b.inner.get('j.json')
    assert rb.put_cas('j.json', {'epoch': 1}, got.version) is None
    assert not clock.sleeps  # no backoff burned on a semantic answer


# ---------------------------------------------------------------------------
# heartbeat leases over the backend
# ---------------------------------------------------------------------------

def test_backend_lease_transport_over_kv(kv_server, tmp_path):
    from kfac_pytorch_tpu.resilience.heartbeat import (
        BackendLeaseTransport, PeerHeartbeat)
    ns = str(tmp_path / 'pod')
    t0 = BackendLeaseTransport(
        TcpKvBackend(('127.0.0.1', kv_server.port), namespace=ns),
        0, prefix='sup')
    t1 = BackendLeaseTransport(
        TcpKvBackend(('127.0.0.1', kv_server.port), namespace=ns),
        1, prefix='sup')
    clock = ManualClock()
    deaths = []
    mon = PeerHeartbeat(t0, 0, 2, interval=1.0, deadline=5.0,
                        startup_grace=2.0, clock=clock.monotonic,
                        on_dead=lambda p, i: deaths.append(p))
    t1.publish({'host': 1, 'seq': 1, 'gen': 0, 'pid': 99})
    mon.poll_once()
    assert not deaths
    for seq in range(2, 5):                   # advancing: alive
        t1.publish({'host': 1, 'seq': seq, 'gen': 0, 'pid': 99})
        clock.sleep(2.0)
        mon.poll_once()
    assert not deaths
    clock.sleep(6.0)                          # silence past the deadline
    mon.poll_once()
    assert deaths == [1]


# ---------------------------------------------------------------------------
# the queue's epoch CAS under injected coordination faults
# ---------------------------------------------------------------------------

def _queue(backend, wall=None):
    from kfac_pytorch_tpu.service.queue import JobQueue
    return JobQueue('/unused-root', backend=backend,
                    **({'wall': wall} if wall else {}))


def _spec(**over):
    base = {'tenant': 'alice', 'trainer': 'cifar10_resnet',
            'args': ['--epochs', '1'], 'hosts': 1, 'retry_budget': 2}
    base.update(over)
    return base


def test_queue_lifecycle_on_kv_backend(kv_server, tmp_path):
    q = _queue(TcpKvBackend(('127.0.0.1', kv_server.port),
                            namespace=str(tmp_path / 'svc')))
    q.submit(_spec())
    created = q.ingest()
    assert [r['id'] for r in created] == [1]
    assert q.backend.list('incoming/') == []     # spool consumed
    running = q.claim(created[0])
    assert running['state'] == 'running' and running['epoch'] == 1
    done = q.mark_done(running)
    assert done['state'] == 'done'
    assert q.counts()['done'] == 1


@pytest.mark.parametrize('flavor', ['posix', 'tcp'])
def test_queue_requeue_exactly_once_per_observation(
        flavor, tmp_path, kv_server):
    if flavor == 'posix':
        backend = PosixDirBackend(str(tmp_path / 'svc'))
    else:
        backend = TcpKvBackend(('127.0.0.1', kv_server.port),
                               namespace=str(tmp_path / 'svc'))
    q = _queue(backend)
    q.submit(_spec())
    rec = q.ingest()[0]
    running = q.claim(rec)
    # two observers of the same dead generation hold the SAME record:
    # the first requeue bumps the epoch, the second must no-op
    first = q.requeue(dict(running), rc=117, reason='fenced')
    second = q.requeue(dict(running), rc=117, reason='fenced')
    assert first is not None and second is None
    final = q.read(rec['id'])
    assert final['state'] == 'queued' and final['requeues'] == 1


def test_queue_suspend_resume_exactly_once_under_chaos(backend):
    """The SUSPENDED -> queued -> RUNNING lane holds the same
    exactly-once contract as requeue, on every backend (POSIX / KV /
    replicated quorum) and through injected coordination faults: an
    ack-lost suspend REPLAY no-ops (every rank's RC_SUSPENDED exit
    observes the same epoch), a replayed resume no-ops, and the
    resumed claim runs attempt 2 with the retry budget untouched."""
    chaos = ChaosBackend(backend,
                         CoordFaultConfig(seed=13, fail=0.05, torn=0.05,
                                          cas=0.2))
    q = _queue(chaos)
    clean = _queue(backend)
    clean.submit(_spec())
    rec = clean.ingest()[0]

    def apply(fn):
        # ride out the fault schedule the way the scheduler's poll
        # loop does: a raised fault or an exhausted CAS loop just
        # retries from a fresh read next cycle
        for _ in range(40):
            try:
                out = fn()
            except CoordTimeout:
                continue
            if out is not None:
                return out
        return None

    def replay_noops(fn):
        # a REPLAY must never apply: every completed call answers None
        # (a raised fault is a non-answer, not an apply)
        for _ in range(5):
            try:
                assert fn() is None
            except CoordTimeout:
                pass

    running = apply(lambda: q.claim(q.read(rec['id']) or rec))
    assert running is not None
    # two observers of the suspend (two ranks exiting 119) hold the
    # SAME record; a chaos-swallowed ack makes the first caller retry —
    # the epoch CAS still applies the park exactly once
    parked = apply(lambda: q.suspend(dict(running), rc=119,
                                     reason='preempt', last_hosts='h0'))
    assert parked is not None and parked['state'] == 'suspended'
    replay_noops(lambda: q.suspend(dict(running), rc=119,
                                   reason='preempt'))
    stored = clean.read(rec['id'])
    assert stored['state'] == 'suspended'
    assert stored['requeues'] == 0                       # uncharged
    assert stored.get('charged_requeues', 0) == 0
    # resume: exactly once too, ready immediately (no backoff)
    resumed = apply(lambda: q.resume(dict(parked)))
    assert resumed is not None
    assert resumed['state'] == 'queued'
    assert resumed['last_reason'] == 'resume'
    assert resumed['not_before'] == 0.0
    replay_noops(lambda: q.resume(dict(parked)))
    claimed = apply(lambda: q.claim(q.read(rec['id']) or resumed))
    assert claimed is not None
    assert claimed['state'] == 'running' and claimed['attempt'] == 2
    assert claimed['requeues'] == 0
    # the whole arc burned exactly four epochs: claim, suspend,
    # resume, claim — nothing double-applied under the faults
    assert clean.read(rec['id'])['epoch'] == 4


def test_queue_epoch_cas_survives_spurious_conflicts(tmp_path):
    """A chaos-injected CAS conflict must not swallow a transition:
    the bounded re-read/retry loop applies it exactly once (the epoch
    check still refuses genuinely stale observations)."""
    chaos = ChaosBackend(PosixDirBackend(str(tmp_path / 'svc')),
                         CoordFaultConfig(seed=9, cas=0.5))
    q = _queue(chaos)
    q.submit(_spec())
    created = []
    for _ in range(10):   # a conflicted create just re-polls next cycle
        created = q.ingest()
        if created:
            break
    rec = created[0]
    # an exhausted CAS loop returns None WITHOUT applying; the caller's
    # next cycle retries from a fresh read — loop like the scheduler's
    # poll loop does, and pin that the net effect is exactly one apply
    running = None
    for _ in range(20):
        running = q.claim(q.read(rec['id']) or rec)
        if running is not None:
            break
    assert running is not None, 'claim lost to a spurious conflict'
    requeued = None
    for _ in range(20):
        requeued = q.requeue(dict(running), rc=115, reason='peer_dead')
        if requeued is not None:
            break
    assert requeued is not None
    again = q.requeue(dict(running), rc=115, reason='peer_dead')
    assert again is None                       # stale epoch: refused
    final = q.read(rec['id'])
    assert final['requeues'] == 1 and final['epoch'] == requeued['epoch']


def test_queue_ingest_idempotent_under_chaos(tmp_path):
    """Repeated ingests under seeded faults never duplicate a job (the
    origin dedup + create-only CAS), and the spool is eventually
    drained."""
    chaos = ChaosBackend(PosixDirBackend(str(tmp_path / 'svc')),
                         CoordFaultConfig(seed=21, fail=0.2, torn=0.2))
    q = _queue(chaos)
    clean = _queue(chaos.inner)
    for i in range(3):
        clean.submit(_spec(tenant=f'tenant{i}'))
    for _ in range(200):  # keep ingesting through the fault schedule
        try:
            q.ingest()
        except CoordTimeout:
            continue
        if not clean.backend.list('incoming/'):
            break
    jobs = clean.jobs()
    assert [j['id'] for j in jobs] == [1, 2, 3]
    assert sorted(j['spec']['tenant'] for j in jobs) \
        == ['tenant0', 'tenant1', 'tenant2']
    assert clean.backend.list('incoming/') == []


# ---------------------------------------------------------------------------
# the shrink barrier on the KV backend (fence-not-split-brain)
# ---------------------------------------------------------------------------

def _kv_sup(tmp_path, kv_server, host_id, num_hosts, **kw):
    from kfac_pytorch_tpu.resilience.elastic import PodSupervisor
    backend = TcpKvBackend(('127.0.0.1', kv_server.port),
                           namespace=str(tmp_path / 'lease'))
    kw.setdefault('settle', 0.0)
    kw.setdefault('shrink_timeout', 0.15)
    kw.setdefault('poll_period', 0.01)
    return PodSupervisor(['trainer'], host_id=host_id,
                         num_hosts=num_hosts,
                         lease_dir=str(tmp_path / 'lease'),
                         coord=backend, **kw), backend


def test_shrink_majority_commits_on_kv_backend(tmp_path, kv_server):
    sup, backend = _kv_sup(tmp_path, kv_server, 0, 3)
    backend.put('shrink-gen1/survivor-2.json', {'host': 2, 'addr': None})
    assert sup._shrink({1: {}}) is True
    assert sup.members == [0, 2] and sup.gen == 1
    # lineage lives on the KV server, not on any filesystem
    assert backend.get('lineage.json').value['lineage'] == 1
    assert sup._current_lineage() == 1
    sup._hb.stop()


def test_shrink_minority_fences_on_kv_backend(tmp_path, kv_server):
    sup, backend = _kv_sup(tmp_path, kv_server, 0, 3)
    assert sup._shrink({1: {}, 2: {}}) is False
    assert sup.gen == 0 and sup.members == [0, 1, 2]
    assert backend.get('lineage.json') is None   # lineage frozen
    # the dead barrier holds no claim of ours
    assert backend.list('shrink-gen1/') == []
    kinds = [e['kind'] for e in sup.report.events]
    assert 'quorum_lost' in kinds


def test_shrink_commits_through_injected_backend_faults(tmp_path,
                                                        kv_server):
    """The acceptance pin: barrier + lineage survive a flaky
    coordination backend — the retry wrapper rides out seeded op
    failures and the retries are VISIBLE in the supervisor's counters
    (-> the [resilience: ...] line -> the incident report)."""
    backend = RetryingBackend(
        ChaosBackend(
            TcpKvBackend(('127.0.0.1', kv_server.port),
                         namespace=str(tmp_path / 'lease')),
            CoordFaultConfig(seed=13, fail=0.3)),
        policy=RetryPolicy(attempts=8, base_delay=0.001,
                           max_delay=0.01, retry_on=(CoordTimeout,)),
        rng=random.Random(0))
    from kfac_pytorch_tpu.resilience.elastic import PodSupervisor
    sup = PodSupervisor(['trainer'], host_id=0, num_hosts=3,
                        lease_dir=str(tmp_path / 'lease'),
                        coord=backend, settle=0.0, shrink_timeout=0.3,
                        poll_period=0.01)
    backend.put('shrink-gen1/survivor-2.json', {'host': 2, 'addr': None})
    assert sup._shrink({1: {}}) is True
    assert sup.members == [0, 2]
    counts = sup.counts()
    assert counts.get('coord_retries', 0) >= 1
    from kfac_pytorch_tpu.utils.runlog import resilience_suffix
    assert 'coord_retries=' in resilience_suffix(counts)
    sup._hb.stop()


def test_supervisor_coord_give_up_exits_118(tmp_path):
    """A dead coordination plane is a LOUD, classified exit — never a
    wedge: the supervisor kills its trainer and exits RC_COORD_LOST."""
    import sys
    from kfac_pytorch_tpu.resilience.elastic import (
        PodSupervisor, RC_COORD_LOST)
    backend = RetryingBackend(
        ChaosBackend(PosixDirBackend(str(tmp_path / 'lease')),
                     CoordFaultConfig(seed=1, fail=1.0)),
        policy=RetryPolicy(attempts=2, base_delay=0.001,
                           retry_on=(CoordTimeout,)),
        rng=random.Random(0))
    sup = PodSupervisor(
        [sys.executable, '-c', 'import time; time.sleep(60)'],
        host_id=0, num_hosts=2, lease_dir=str(tmp_path / 'lease'),
        coord=backend, poll_period=0.01, backoff_base=0.01)
    rc = sup.run()
    assert rc == RC_COORD_LOST == 118
    # trainer stopped — or never launched: a dead backend at startup
    # (the lineage baseline read) must fail BEFORE a child exists
    assert sup.child is None or sup.child.poll() is not None
    report = json.loads(
        (tmp_path / 'lease' / 'incident-host0.json').read_text())
    assert any(e['kind'] == 'coord_lost' for e in report['events'])
    assert report['counters'].get('coord_gave_ups', 0) >= 1
    from kfac_pytorch_tpu.service.scheduler import classify_rc
    assert classify_rc(rc) == 'coord_lost'


# ---------------------------------------------------------------------------
# polling audit: paced scan loops with an accounted cumulative wait
# ---------------------------------------------------------------------------

def test_poll_pacer_jittered_cap_and_accounting():
    from kfac_pytorch_tpu.resilience.retry import PollPacer
    clock = ManualClock()
    total = [0.0]
    pace = PollPacer.for_period(0.2, clock=clock, rng=random.Random(0),
                                total=total)
    delays = [pace.sleep() for _ in range(40)]
    # jitter-bounded: never below base*(1-j), never above cap*(1+j)
    assert all(0.2 * 0.75 - 1e-9 <= d <= 0.8 * 1.25 + 1e-9
               for d in delays), (min(delays), max(delays))
    # grows toward the cap, then stays bounded there
    assert max(delays[10:]) <= 0.8 * 1.25 + 1e-9
    assert delays[0] < max(delays)
    assert pace.waited == pytest.approx(sum(delays))
    assert total[0] == pytest.approx(sum(delays))
    pace.reset()
    assert pace.sleep() <= 0.2 * 1.25 + 1e-9


def test_poll_pacer_survives_long_lived_loops():
    """A pacer lives for a whole supervise loop (hours): the exponent
    must saturate, never overflow float range (~1750 iterations used
    to raise OverflowError and kill the supervisor)."""
    from kfac_pytorch_tpu.resilience.retry import PollPacer, RetryPolicy
    clock = ManualClock()
    pace = PollPacer.for_period(0.2, clock=clock, rng=random.Random(1))
    for _ in range(5000):
        assert 0.0 < pace.sleep() <= 0.8 * 1.25 + 1e-9
    # and RetryPolicy.delay itself is overflow-safe for any k
    policy = RetryPolicy(base_delay=0.1, max_delay=2.0, multiplier=1.5,
                         jitter=0.0)
    assert policy.delay(10_000, random.Random(0)) == 2.0


def test_supervisor_counts_surface_poll_wait(tmp_path):
    import sys
    from kfac_pytorch_tpu.resilience.elastic import PodSupervisor
    sup = PodSupervisor([sys.executable, '-c', 'import time; '
                         'time.sleep(0.3)'],
                        host_id=0, num_hosts=1,
                        lease_dir=str(tmp_path / 'lease'),
                        poll_period=0.02)
    assert sup.run() == 0
    assert sup._poll_wait[0] > 0
    assert 'poll_wait_s' in sup.counts()


# ---------------------------------------------------------------------------
# the remote-launcher seam
# ---------------------------------------------------------------------------

def test_launcher_local_is_identity():
    from kfac_pytorch_tpu.service.scheduler import Launcher
    argv, env = Launcher('h0').render(['python', 'x.py'], {'A': '1'})
    assert argv == ['python', 'x.py'] and env == {'A': '1'}


def test_launcher_remote_renders_prefix_and_env_reexport():
    from kfac_pytorch_tpu.service.scheduler import Launcher
    base = {'HOME': '/home/op', 'PATH': '/bin', 'KFAC_OLD': 'same',
            'KFAC_COORD_BACKEND': 'tcp'}
    env = dict(base, KFAC_TENANT='alice', KFAC_HB_PORT='8600',
               CUSTOM_SET='by-service')
    argv, penv = Launcher('r1', ['ssh', '{host}', '--']).render(
        ['python', '-m', 'mod', '--flag'], env, base_env=base)
    assert penv is None                      # local ssh inherits
    assert argv[:3] == ['ssh', 'r1', '--']
    assert argv[3] == 'env'
    # every KFAC_*/JAX_* var is forwarded — INCLUDING ones the
    # controller merely inherited (KFAC_COORD_BACKEND: ssh would drop
    # it and the remote side would silently fall back to posix) — plus
    # anything the service set or changed; unrelated inherited vars
    # (HOME, PATH) stay out of the command line
    reexport = argv[4:argv.index('python')]
    assert reexport == ['CUSTOM_SET=by-service',
                        'KFAC_COORD_BACKEND=tcp', 'KFAC_HB_PORT=8600',
                        'KFAC_OLD=same', 'KFAC_TENANT=alice']
    assert argv[-4:] == ['python', '-m', 'mod', '--flag']
    # shell metacharacters are quoted for the remote shell: ssh
    # flattens argv, and an unquoted ';' (the coord outage-window
    # spec!) would split the remote command in two
    argv2, _ = Launcher('r1', ['ssh', '{host}']).render(
        ['python'], {'KFAC_FAULT_COORD_WINDOWS': '10:40;90:95'},
        base_env={})
    assert "KFAC_FAULT_COORD_WINDOWS='10:40;90:95'" in argv2


def test_tcpkv_cas_replay_with_token_is_idempotent(kv_server, tmp_path):
    """A CAS whose response was lost on the wire must not read as a
    self-conflict on the replay: the retry layer sends one idempotency
    token per logical op and the server answers the replay with the
    original success."""
    b = TcpKvBackend(('127.0.0.1', kv_server.port),
                     namespace=str(tmp_path / 'cas'))
    b.put('job.json', {'epoch': 0})
    got = b.get('job.json')
    v1 = b.put_cas('job.json', {'epoch': 1}, got.version, token='tok-1')
    assert v1 is not None
    # the REPLAY (same token, now-stale expect): original success, not
    # a conflict — and nothing is applied twice
    v2 = b.put_cas('job.json', {'epoch': 1}, got.version, token='tok-1')
    assert v2 == v1
    assert b.get('job.json').value == {'epoch': 1}
    # a DIFFERENT writer with the same stale expect still conflicts
    assert b.put_cas('job.json', {'epoch': 9}, got.version,
                     token='tok-2') is None


def test_retrying_backend_cas_token_survives_retry(tmp_path, kv_server):
    """The retry wrapper generates ONE token per logical CAS, so an
    attempt replayed after an injected timeout lands as the same
    logical write (pinned against the KV server through chaos)."""
    inner = TcpKvBackend(('127.0.0.1', kv_server.port),
                         namespace=str(tmp_path / 'casr'))
    inner.put('job.json', {'epoch': 0})
    got = inner.get('job.json')

    class FlakyOnce:
        """Apply the CAS, then pretend the response was lost once."""

        def __init__(self):
            self.failed = False

        def __getattr__(self, name):
            return getattr(inner, name)

        def put_cas(self, key, value, expect_version, **kw):
            version = inner.put_cas(key, value, expect_version, **kw)
            if not self.failed:
                self.failed = True
                raise CoordTimeout('response lost after apply')
            return version

    rb, _ = _retrying(FlakyOnce())
    version = rb.put_cas('job.json', {'epoch': 1}, got.version)
    assert version is not None                 # replay, not conflict
    assert inner.get('job.json').value == {'epoch': 1}


def test_scheduler_dry_run_pins_remote_rank_argv(tmp_path):
    """hosts.json carries a launch prefix -> the admitted rank's argv
    is the rendered remote command (prefix + env re-export + the
    kfac-pod-supervise module invocation), popen env inherited."""
    from kfac_pytorch_tpu.service.scheduler import AdmissionController
    captured = []

    class FakeProc:
        pid = 4242

        def poll(self):
            return None

        def wait(self, timeout=None):
            return 0

    def fake_popen(argv, **kw):
        captured.append((argv, kw))
        return FakeProc()

    svc = tmp_path / 'svc'
    ctl = AdmissionController(
        str(svc), hosts={'h0': 1}, popen=fake_popen,
        trainers={'mini': 'tests/chaos_trainer.py'})
    # re-home the pool onto a remote host via the live hosts.json seam
    ctl.coord.put('hosts.json', {'hosts': {
        'r1': {'slots': 1, 'launch': ['ssh', '{host}', '--']}}},
        indent=2)
    ctl.queue.submit(_spec(trainer='mini'))
    ctl.step()
    assert captured, 'no launch captured'
    argv, kw = captured[0]
    assert argv[:3] == ['ssh', 'r1', '--'] and argv[3] == 'env'
    assert kw.get('env') is None             # inherited, not passed
    joined = ' '.join(argv)
    assert 'kfac_pytorch_tpu.resilience.elastic' in joined
    assert 'chaos_trainer.py' in joined
    # the env re-export carries the tenant namespace + port block
    assert any(a.startswith('KFAC_TENANT=alice') for a in argv)
    assert any(a.startswith('KFAC_HB_PORT=') for a in argv)
    assert any(a.startswith('KFAC_JOB_ID=job-') for a in argv)


# ---------------------------------------------------------------------------
# the static gate: no backend bypass outside coord/
# ---------------------------------------------------------------------------

def test_no_protocol_module_bypasses_the_backend():
    """The lint that keeps the abstraction from rotting: the protocol
    modules may not reach around the coordination backend with direct
    lease-dir file IO. Since ISSUE 15 the ad-hoc AST scan that lived
    here IS a framework rule — the forbidden-call set and the artifact
    allowlist have exactly one home
    (kfac_pytorch_tpu/analysis/rules/coord_bypass.py), shared by the CI
    ``lint`` job, the ``kfac-lint --rule coord-bypass`` CLI, and this
    thin invocation; extending the allowlist still means editing a
    reviewed file, which is the point."""
    from kfac_pytorch_tpu.analysis import run_lint
    from kfac_pytorch_tpu.analysis.rules import ALL_RULES
    res = run_lint(REPO, ALL_RULES, rule_ids=['coord-bypass'])
    assert not res.findings, (
        'direct protocol-file IO outside coord/ (route it through the '
        'CoordBackend, or allowlist a genuine artifact in '
        'analysis/rules/coord_bypass.py):\n  '
        + '\n  '.join(f.render() for f in res.findings))
