"""Golden tests for Kronecker-factor statistics ops.

Oracles are independent numpy implementations of the documented reference
semantics (reference: kfac/utils.py:33-140).
"""

import numpy as np
import jax.numpy as jnp
import pytest

from kfac_pytorch_tpu import ops

pytestmark = pytest.mark.core


def np_patches(x, kh, kw, sh, sw, ph, pw):
    """Naive im2col oracle: NHWC -> [N, OH, OW, kh*kw*C], (kh, kw, c) order."""
    n, h, w, c = x.shape
    xp = np.pad(x, ((0, 0), (ph, ph), (pw, pw), (0, 0)))
    oh = (h + 2 * ph - kh) // sh + 1
    ow = (w + 2 * pw - kw) // sw + 1
    out = np.zeros((n, oh, ow, kh * kw * c), dtype=x.dtype)
    for i in range(oh):
        for j in range(ow):
            win = xp[:, i * sh:i * sh + kh, j * sw:j * sw + kw, :]
            out[:, i, j, :] = win.reshape(n, -1)  # (kh, kw, c) row-major
    return out


def test_extract_patches_matches_naive():
    rng = np.random.RandomState(0)
    x = rng.randn(2, 6, 5, 3).astype(np.float32)
    got = np.asarray(ops.extract_patches(jnp.asarray(x), (3, 2), (2, 1), (1, 0)))
    want = np_patches(x, 3, 2, 2, 1, 1, 0)
    np.testing.assert_allclose(got, want, atol=1e-6)


@pytest.mark.parametrize('use_bias', [True, False])
def test_compute_a_dense(use_bias):
    rng = np.random.RandomState(1)
    a = rng.randn(8, 5).astype(np.float32)
    am = np.concatenate([a, np.ones((8, 1), np.float32)], 1) if use_bias else a
    want = am.T @ am / 8
    got = np.asarray(ops.compute_a_dense(jnp.asarray(a), use_bias))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_compute_a_dense_seq_mean():
    # sequence inputs are token-averaged first (reference kfac/utils.py:97-99)
    rng = np.random.RandomState(2)
    a = rng.randn(4, 7, 5).astype(np.float32)
    am = a.mean(1)
    am = np.concatenate([am, np.ones((4, 1), np.float32)], 1)
    want = am.T @ am / 4
    got = np.asarray(ops.compute_a_dense(jnp.asarray(a), True))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize('use_bias', [True, False])
def test_compute_a_conv(use_bias):
    rng = np.random.RandomState(3)
    x = rng.randn(3, 5, 5, 2).astype(np.float32)
    p = np_patches(x, 3, 3, 1, 1, 1, 1)  # [3,5,5,18]
    spatial = p.shape[1] * p.shape[2]
    rows = p.reshape(-1, p.shape[-1])
    if use_bias:
        rows = np.concatenate([rows, np.ones((rows.shape[0], 1), np.float32)], 1)
    rows = rows / spatial
    want = rows.T @ rows / 3
    got = np.asarray(ops.compute_a_conv(jnp.asarray(x), (3, 3), (1, 1), (1, 1),
                                        use_bias))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-6)


@pytest.mark.parametrize('batch_averaged', [True, False])
def test_compute_g_dense(batch_averaged):
    rng = np.random.RandomState(4)
    g = rng.randn(6, 4).astype(np.float32)
    scaled = g * 6 if batch_averaged else g
    want = scaled.T @ scaled / 6 if batch_averaged else g.T @ g / 6
    # batch_averaged: G = g^T (g*N) = (gN)^T (gN) / N
    want = (g * 6).T @ (g * 6) / 6 if batch_averaged else g.T @ g / 6
    got = np.asarray(ops.compute_g_dense(jnp.asarray(g), batch_averaged))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize('batch_averaged', [True, False])
def test_compute_g_conv(batch_averaged):
    rng = np.random.RandomState(5)
    g = rng.randn(3, 4, 4, 6).astype(np.float32)  # NHWC
    n, oh, ow, c = g.shape
    spatial = oh * ow
    rows = g.reshape(-1, c)
    if batch_averaged:
        rows = rows * n
    rows = rows * spatial
    want = rows.T @ rows / (n * spatial)
    got = np.asarray(ops.compute_g_conv(jnp.asarray(g), batch_averaged))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_update_running_avg():
    cur = jnp.ones((3, 3))
    new = jnp.full((3, 3), 2.0)
    out = ops.update_running_avg(new, cur, 0.25)
    np.testing.assert_allclose(np.asarray(out), 0.75 * 1 + 0.25 * 2)
