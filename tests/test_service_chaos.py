"""The multi-tenant service chaos drill (``-m slow``) — the tentpole's
acceptance run, with REAL processes end to end.

Three tenant jobs (alice / bob / carol, one job each) are submitted to
a ``kfac-serve`` scheduler subprocess packing a 3-host pool
(``hosts.json``: h0/h1/h2, two slots each — the drill's "3-host pod").
Each job runs the miniature-but-real chaos trainer under its own
``kfac-pod-supervise``, in its own tenant namespace, with its own
heartbeat-port block. Mid-run, one job's host is LOST: the pool file
drops it and the scheduler SIGKILLs that job's whole process group —
exactly how a vanished host looks from the controller. The service
must:

- log ``pool_shrink`` and requeue the displaced job (uncharged — a
  capacity loss is not the tenant's fault) exactly once,
- re-admit it onto the surviving hosts (now co-located with another
  tenant's job — the per-job lease dirs and port blocks keep them
  apart),
- let it RESUME from its own checkpoints (not restart the schedule),
- and finish ALL THREE jobs: zero lost, zero duplicated, every
  tenant's DONE line schedule-equivalent to an undisturbed control,
- with ``kfac-obs`` rendering each tenant's admit -> failure ->
  requeue -> done story from the service log + tenant namespace, and
  the ``--follow`` endpoint streaming the same events live.
"""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TRAINER = os.path.join(REPO, 'tests', 'chaos_trainer.py')

EPOCHS = 8
BATCH = 8
EXAMPLES = 32          # 4 steps/epoch
TENANTS = ('alice', 'bob', 'carol')


#: coordination-backend overlay (the TcpKv drill leg): scheduler and
#: supervisor subprocesses pick the backend + backend-fault schedule up
#: from these envs
_COORD_OVERLAY = {}


def _env(**extra):
    base = {k: v for k, v in os.environ.items()
            if not (k.startswith('KFAC_FAULT_')
                    or k.startswith('KFAC_HB_')
                    or k.startswith('KFAC_COORD_')
                    or k in ('KFAC_TENANT', 'KFAC_JOB_ID',
                             'KFAC_PROM_FILE', 'KFAC_TRACE_DIR'))}
    base['JAX_PLATFORMS'] = 'cpu'
    base.update(_COORD_OVERLAY)
    base.update(extra)
    return base


@pytest.fixture
def tcpkv_coord(monkeypatch):
    """Service drill on the TCP KV coordination backend: the queue,
    hosts.json pool and every pod protocol ride the KV server; the
    scheduler subprocess additionally runs with mild seeded
    KFAC_FAULT_COORD_* probabilities. The test process itself submits
    through the same backend (env-selected), faults unarmed — chaos
    belongs between the SERVICE and its backend, not in the harness."""
    from kfac_pytorch_tpu.coord import TcpKvServer
    srv = TcpKvServer('127.0.0.1', 0)
    monkeypatch.setenv('KFAC_COORD_BACKEND', 'tcp')
    monkeypatch.setenv('KFAC_COORD_ADDR', f'127.0.0.1:{srv.port}')
    _COORD_OVERLAY.update({
        'KFAC_COORD_BACKEND': 'tcp',
        'KFAC_COORD_ADDR': f'127.0.0.1:{srv.port}',
        'KFAC_FAULT_COORD_SEED': '5',
        'KFAC_FAULT_COORD_FAIL': '0.02',
        'KFAC_FAULT_COORD_TORN': '0.02',
    })
    try:
        yield srv
    finally:
        _COORD_OVERLAY.clear()
        srv.close()


def _done_line(text):
    lines = [ln for ln in text.splitlines() if ln.startswith('DONE ')]
    assert lines, f'no DONE line; tail: {text[-3000:]}'
    return lines[-1]


def _trainer_args():
    return ['--epochs', str(EPOCHS), '--batch-size', str(BATCH),
            '--num-examples', str(EXAMPLES),
            '--checkpoint-dir', '{ckpt}',
            '--num-hosts', '{num_hosts}', '--host-id', '{host_id}']


def _spec(tenant):
    return {'tenant': tenant, 'trainer': 'mini',
            'args': _trainer_args(), 'hosts': 1, 'retry_budget': 2}


def test_service_survives_host_loss_zero_jobs_lost(tmp_path):
    from kfac_pytorch_tpu import coord
    from kfac_pytorch_tpu.obs import aggregate
    from kfac_pytorch_tpu.service import JobQueue

    # the undisturbed control fixes the schedule contract every tenant
    # job must end with — displaced or not
    p = subprocess.run(
        [sys.executable, TRAINER, '--epochs', str(EPOCHS),
         '--batch-size', str(BATCH), '--num-examples', str(EXAMPLES),
         '--checkpoint-dir', str(tmp_path / 'ckpt_control')],
        env=_env(), cwd=REPO, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True, timeout=540)
    assert p.returncode == 0, p.stdout[-3000:]
    control = _done_line(p.stdout)

    svc = tmp_path / 'svc'
    queue = JobQueue(svc, trainers={'mini': TRAINER})
    for tenant in TENANTS:
        queue.submit(_spec(tenant))

    # pace the trainers so the host loss always lands mid-schedule
    sched_env = _env(KFAC_FAULT_SLOW_STEP='0:999',
                     KFAC_FAULT_SLOW_SECS='0.5')
    svc_out = tmp_path / 'svc.out'
    sched_cmd = [
        sys.executable, '-m', 'kfac_pytorch_tpu.service.scheduler',
        'run', '--service-dir', str(svc),
        '--hosts', 'h0=2,h1=2,h2=2',
        '--trainer', f'mini={TRAINER}',
        '--poll', '0.3', '--backoff-base', '0.3', '--backoff-max', '2',
        '--max-restarts', '2', '--hb-interval', '0.3',
        '--hb-deadline', '3', '--drain', '--max-seconds', '900']
    f_out = open(svc_out, 'wb')
    sched = subprocess.Popen(sched_cmd, env=sched_env, cwd=REPO,
                             stdout=f_out, stderr=subprocess.STDOUT,
                             start_new_session=True)

    def _fail(msg):
        tail = svc_out.read_text()[-3000:] if svc_out.exists() else ''
        pytest.fail(f'{msg}; scheduler tail: {tail}')

    def _ckpt0(rec):
        ckpt = os.path.join(rec.get('ns', ''), 'ckpt')
        return (os.path.isdir(os.path.join(ckpt, 'checkpoint-0'))
                or os.path.exists(os.path.join(ckpt,
                                               'checkpoint-0.pkl')))

    victim = None
    try:
        # every job admitted and mid-flight (epoch 0 banked, not done)
        deadline = time.time() + 420
        while time.time() < deadline:
            if sched.poll() is not None:
                _fail(f'scheduler exited rc={sched.returncode} before '
                      'the host loss')
            jobs = queue.jobs()
            running = [r for r in jobs if r['state'] == 'running']
            if (len(jobs) == 3 and len(running) == 3
                    and all(_ckpt0(r) for r in running)):
                break
            time.sleep(0.5)
        else:
            _fail('3 running jobs with banked checkpoints never '
                  'appeared')

        # the drill's SIGKILL: drop the victim's host from the pool.
        # The scheduler kills the job's whole process group (SIGKILL)
        # and requeues it — a vanished host, as seen from the service.
        victim = next(r for r in queue.jobs()
                      if r['state'] == 'running')
        victim_tenant = victim['spec']['tenant']
        victim_host = victim['placement']['0']
        hosts = {h: 2 for h in ('h0', 'h1', 'h2') if h != victim_host}
        # through the env-selected coordination backend: the identical
        # atomic hosts.json file on posix, the KV key on the tcp leg
        coord.backend_from_env(str(svc), retry=False, chaos=False).put(
            'hosts.json', {'hosts': hosts}, indent=2)

        rc = sched.wait(timeout=900)
        assert rc == 0, _fail(f'scheduler rc={rc}')
    finally:
        if sched.poll() is None:
            try:
                os.killpg(os.getpgid(sched.pid), signal.SIGKILL)
            except ProcessLookupError:
                pass
        f_out.close()

    # -- zero lost, zero duplicated -------------------------------------
    jobs = queue.jobs()
    assert len(jobs) == 3, [r['id'] for r in jobs]
    assert all(r['state'] == 'done' for r in jobs), \
        [(r['id'], r['state']) for r in jobs]
    by_tenant = {r['spec']['tenant']: r for r in jobs}
    assert set(by_tenant) == set(TENANTS)
    displaced = by_tenant[victim_tenant]
    assert displaced['requeues'] == 1
    assert displaced['last_reason'] == 'host_lost'
    assert displaced['attempt'] == 2
    assert displaced.get('charged_requeues', 0) == 0
    for tenant in TENANTS:
        if tenant != victim_tenant:
            assert by_tenant[tenant]['requeues'] == 0
            assert by_tenant[tenant]['attempt'] == 1
    # jobs that shared a host got disjoint heartbeat-port blocks
    assert len({r['port'] for r in jobs}) == 3

    service_log = (svc / 'service.log').read_text()
    assert 'pool_shrink' in service_log
    assert service_log.count(
        f'job_requeue job={displaced["id"]}') == 1   # exactly once
    assert 'job_lost' not in service_log
    assert service_log.count('job_done') == 3

    # -- every tenant finished schedule-equivalent; the displaced job
    # RESUMED from its own checkpoints instead of restarting ------------
    for tenant, rec in by_tenant.items():
        log = os.path.join(rec['ns'], 'logs', 'host0.out')
        text = open(log, errors='replace').read()
        assert _done_line(text) == control, (tenant, text[-2000:])
        if tenant == victim_tenant:
            assert 'RESUMED from=checkpoint-' in text, text[-3000:]

    # -- kfac-obs: the per-tenant timeline tells the whole story --------
    displaced_ns = by_tenant[victim_tenant]['ns']
    timeline = aggregate.build_timeline(
        [str(svc / 'service.log'), displaced_ns], recursive=True)
    events = [e for e in timeline['events']
              if e['detail'].get('tenant') in (victim_tenant, None)]

    def first(kind, after=0, **match):
        for i in range(after, len(events)):
            e = events[i]
            if e['kind'] == kind and all(
                    e['detail'].get(k) == v for k, v in match.items()):
                return i
        raise AssertionError(
            f'{kind} {match or ""} missing after {after}; kinds: '
            f'{sorted({e["kind"] for e in events})}')

    i_admit = first('job_admit', attempt=1, tenant=victim_tenant)
    i_shrink = first('pool_shrink', after=i_admit)
    i_requeue = first('job_requeue', after=i_admit,
                      tenant=victim_tenant)
    i_readmit = first('job_admit', after=i_requeue, attempt=2,
                      tenant=victim_tenant)
    i_done = first('job_done', after=i_readmit, tenant=victim_tenant)
    order = [i_admit, i_shrink, i_requeue, i_readmit, i_done]
    assert order == sorted(order), order
    walls = [events[i]['wall_aligned'] for i in order]
    assert all(w is not None for w in walls) and walls == sorted(walls)
    # the trainer's own protocol events merged in from the namespace
    kinds = {e['kind'] for e in timeline['events']}
    assert 'run_done' in kinds and 'resumed' in kinds

    # -- the --follow live endpoint replays the same story --------------
    import io
    out = io.StringIO()
    aggregate.follow([str(svc / 'service.log'), displaced_ns],
                     interval=0.1, duration=0.3, recursive=True,
                     out=out)
    followed = out.getvalue()
    assert 'job_requeue' in followed and 'job_done' in followed

    # -- CI artifact export: queue state + per-tenant timelines ---------
    art = os.environ.get('KFAC_DRILL_ARTIFACTS')
    if art:
        import shutil
        root = os.path.join(art, 'service')
        os.makedirs(root, exist_ok=True)
        shutil.copy(svc / 'service.log', root)
        shutil.copy(svc_out, root)
        if os.path.isdir(queue.jobs_dir):   # posix backend: literal files
            shutil.copytree(queue.jobs_dir,
                            os.path.join(root, 'queue-state'),
                            dirs_exist_ok=True)
        else:                               # KV backend: dump the records

            with open(os.path.join(root, 'queue-state.json'), 'w') as f:
                json.dump(queue.jobs(), f, indent=2, default=str)
        for tenant, rec in by_tenant.items():
            tdir = os.path.join(root, tenant)
            os.makedirs(tdir, exist_ok=True)
            shutil.copytree(os.path.join(rec['ns'], 'logs'),
                            os.path.join(tdir, 'logs'),
                            dirs_exist_ok=True)
            t = aggregate.build_timeline(
                [str(svc / 'service.log'), rec['ns']], recursive=True)
            with open(os.path.join(tdir, 'timeline.json'), 'w') as f:
                json.dump({k: v for k, v in t.items()
                           if not k.startswith('_')}, f, indent=2,
                          default=str)


# ---------------------------------------------------------------------------
# TcpKv backend leg: the same 3-tenant acceptance drill with the queue,
# capacity pool and every pod protocol on the KV server, backend faults
# armed. Nightly tier (adds a full drill run).
# ---------------------------------------------------------------------------


@pytest.mark.nightly
def test_service_drill_on_tcpkv_backend(tmp_path, tcpkv_coord):
    test_service_survives_host_loss_zero_jobs_lost(tmp_path)


# ---------------------------------------------------------------------------
# Preemption drill: priority preemption via checkpoint-suspend, then a
# host drain forces the resumed victims to MIGRATE. Real processes end
# to end — the ISSUE's multi-tenant acceptance run.
# ---------------------------------------------------------------------------


def test_service_preempts_suspends_and_migrates(tmp_path):
    """Two low-priority tenants (alice w=1, bob w=2) fill a 2-host pool;
    a non-preemptible priority-10 job (carol) needing the WHOLE pool
    lands mid-run. The service must checkpoint-suspend both victims
    (rc=119, uncharged), admit carol the same cycle, survive h0
    starting to drain under carol (non-preemptible: finishes in
    place), then resume both victims on the one surviving host — the
    one that ran on h0 migrating — and finish all three jobs
    schedule-equivalent to an undisturbed control."""
    from kfac_pytorch_tpu import coord
    from kfac_pytorch_tpu.obs import aggregate
    from kfac_pytorch_tpu.service import JobQueue
    from kfac_pytorch_tpu.service.scheduler import RC_SUSPENDED

    p = subprocess.run(
        [sys.executable, TRAINER, '--epochs', str(EPOCHS),
         '--batch-size', str(BATCH), '--num-examples', str(EXAMPLES),
         '--checkpoint-dir', str(tmp_path / 'ckpt_control')],
        env=_env(), cwd=REPO, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True, timeout=540)
    assert p.returncode == 0, p.stdout[-3000:]
    control = _done_line(p.stdout)

    svc = tmp_path / 'svc'
    queue = JobQueue(svc, trainers={'mini': TRAINER})
    queue.submit(dict(_spec('alice'), weight=1.0))
    queue.submit(dict(_spec('bob'), weight=2.0))

    sched_env = _env(KFAC_FAULT_SLOW_STEP='0:999',
                     KFAC_FAULT_SLOW_SECS='0.5')
    svc_out = tmp_path / 'svc.out'
    sched_cmd = [
        sys.executable, '-m', 'kfac_pytorch_tpu.service.scheduler',
        'run', '--service-dir', str(svc),
        '--hosts', 'h0=1,h1=1',
        '--trainer', f'mini={TRAINER}',
        '--poll', '0.3', '--backoff-base', '0.3', '--backoff-max', '2',
        '--max-restarts', '2', '--hb-interval', '0.3',
        '--hb-deadline', '3', '--suspend-grace', '60',
        '--drain', '--max-seconds', '900']
    f_out = open(svc_out, 'wb')
    sched = subprocess.Popen(sched_cmd, env=sched_env, cwd=REPO,
                             stdout=f_out, stderr=subprocess.STDOUT,
                             start_new_session=True)

    def _fail(msg):
        tail = svc_out.read_text()[-3000:] if svc_out.exists() else ''
        pytest.fail(f'{msg}; scheduler tail: {tail}')

    def _ckpt0(rec):
        ckpt = os.path.join(rec.get('ns', ''), 'ckpt')
        return (os.path.isdir(os.path.join(ckpt, 'checkpoint-0'))
                or os.path.exists(os.path.join(ckpt,
                                               'checkpoint-0.pkl')))

    def _by_tenant(state=None):
        recs = {r['spec']['tenant']: r for r in queue.jobs()}
        if state is None:
            return recs
        return {t: r for t, r in recs.items() if r['state'] == state}

    victims = ('alice', 'bob')
    try:
        # both victims admitted, mid-schedule, checkpoint-0 banked
        deadline = time.time() + 420
        while time.time() < deadline:
            if sched.poll() is not None:
                _fail(f'scheduler exited rc={sched.returncode} before '
                      'the preemptor landed')
            running = _by_tenant('running')
            if (set(running) == set(victims)
                    and all(_ckpt0(r) for r in running.values())):
                break
            time.sleep(0.5)
        else:
            _fail('victims never reached running-with-checkpoint')
        victim_host = {t: r['placement']['0']
                       for t, r in _by_tenant('running').items()}

        # the preemptor: the whole pool, top priority, not itself
        # suspendable
        queue.submit({'tenant': 'carol', 'trainer': 'mini',
                      'args': _trainer_args(), 'hosts': 2,
                      'priority': 10, 'preemptible': False,
                      'retry_budget': 2})

        # both victims park SUSPENDED (uncharged) and carol admits
        deadline = time.time() + 420
        while time.time() < deadline:
            if sched.poll() is not None:
                _fail(f'scheduler exited rc={sched.returncode} '
                      'mid-preemption')
            recs = _by_tenant()
            if (all(recs[t]['state'] == 'suspended' for t in victims)
                    and recs.get('carol', {}).get('state') == 'running'):
                break
            time.sleep(0.5)
        else:
            _fail('preemption never parked both victims with carol '
                  'running')
        for t in victims:
            rec = _by_tenant()[t]
            assert rec['last_rc'] == RC_SUSPENDED, rec
            assert rec['last_reason'] == 'preempt', rec
            assert rec['requeues'] == 0, rec          # uncharged
            assert rec['last_hosts'] == victim_host[t], rec

        # drain h0 under carol: non-preemptible, she finishes in
        # place; the victims must resume on h1 only
        coord.backend_from_env(str(svc), retry=False, chaos=False).put(
            'hosts.json',
            {'hosts': {'h0': {'slots': 1, 'draining': True},
                       'h1': 1}}, indent=2)

        rc = sched.wait(timeout=900)
        assert rc == 0, _fail(f'scheduler rc={rc}')
    finally:
        if sched.poll() is None:
            try:
                os.killpg(os.getpgid(sched.pid), signal.SIGKILL)
            except ProcessLookupError:
                pass
        f_out.close()

    # -- all three done; victims uncharged, resumed once ---------------
    by_tenant = _by_tenant()
    assert set(by_tenant) == {'alice', 'bob', 'carol'}
    assert all(r['state'] == 'done' for r in by_tenant.values()), \
        {t: r['state'] for t, r in by_tenant.items()}
    assert by_tenant['carol']['requeues'] == 0
    for t in victims:
        rec = by_tenant[t]
        assert rec['requeues'] == 0, rec              # never charged
        assert rec.get('charged_requeues', 0) == 0
        assert rec['attempt'] == 2, rec               # exactly one resume
        assert rec['last_reason'] == 'resume', rec

    service_log = (svc / 'service.log').read_text()
    for t in victims:
        jid = by_tenant[t]['id']
        assert service_log.count(f'job_preempt job={jid} ') == 1
        assert service_log.count(f'job_suspend job={jid} ') == 1
        assert f'job_suspend job={jid} tenant={t} rc={RC_SUSPENDED}' \
            in service_log
    assert 'job_lost' not in service_log
    assert service_log.count('job_done') == 3
    assert 'tenant_share' in service_log
    # the victim that ran on the drained host crossed hosts on resume
    migrant = next(t for t in victims if victim_host[t] == 'h0')
    assert (f'job_migrate job={by_tenant[migrant]["id"]} '
            f'tenant={migrant} from=h0 to=h1') in service_log

    # -- schedule equivalence + the suspend fence held -----------------
    for t in victims:
        rec = by_tenant[t]
        log = os.path.join(rec['ns'], 'logs', 'host0.out')
        text = open(log, errors='replace').read()
        assert _done_line(text) == control, (t, text[-2000:])
        assert 'RESUMED from=checkpoint-' in text, text[-3000:]
        assert 'suspending on request' in text, text[-3000:]
        assert 'no further commits' in text, text[-3000:]

    # -- kfac-obs: each victim's timeline tells the whole story --------
    for t in victims:
        ns = by_tenant[t]['ns']
        timeline = aggregate.build_timeline(
            [str(svc / 'service.log'), ns], recursive=True)
        events = [e for e in timeline['events']
                  if e['detail'].get('tenant') in (t, None)]

        def first(kind, after=0, **match):
            for i in range(after, len(events)):
                e = events[i]
                if e['kind'] == kind and all(
                        e['detail'].get(k) == v
                        for k, v in match.items()):
                    return i
            raise AssertionError(
                f'{kind} {match or ""} missing after {after}; kinds: '
                f'{sorted({e["kind"] for e in events})}')

        i_admit = first('job_admit', attempt=1, tenant=t)
        i_pre = first('job_preempt', after=i_admit, tenant=t)
        i_susp = first('job_suspend', after=i_admit, tenant=t)
        i_re = first('job_admit', after=i_susp, attempt=2, tenant=t)
        i_done = first('job_done', after=i_re, tenant=t)
        order = [i_admit, i_pre, i_susp, i_re, i_done]
        assert order == sorted(order), (t, order)
        if t == migrant:
            i_mig = first('job_migrate', after=i_susp, tenant=t)
            assert i_re <= i_mig <= i_done, (i_re, i_mig, i_done)

    # -- CI artifact export --------------------------------------------
    art = os.environ.get('KFAC_DRILL_ARTIFACTS')
    if art:
        import shutil
        root = os.path.join(art, 'service-preempt')
        os.makedirs(root, exist_ok=True)
        shutil.copy(svc / 'service.log', root)
        shutil.copy(svc_out, root)
        if os.path.isdir(queue.jobs_dir):
            shutil.copytree(queue.jobs_dir,
                            os.path.join(root, 'queue-state'),
                            dirs_exist_ok=True)
        else:
            with open(os.path.join(root, 'queue-state.json'), 'w') as f:
                json.dump(queue.jobs(), f, indent=2, default=str)
        for t, rec in by_tenant.items():
            tdir = os.path.join(root, t)
            os.makedirs(tdir, exist_ok=True)
            shutil.copytree(os.path.join(rec['ns'], 'logs'),
                            os.path.join(tdir, 'logs'),
                            dirs_exist_ok=True)
            tl = aggregate.build_timeline(
                [str(svc / 'service.log'), rec['ns']], recursive=True)
            with open(os.path.join(tdir, 'timeline.json'), 'w') as f:
                json.dump({k: v for k, v in tl.items()
                           if not k.startswith('_')}, f, indent=2,
                          default=str)
