"""Expert-parallel Switch MoE (parallel/moe.py) on the CPU mesh: with no
capacity overflow the all_to_all-dispatched computation must EXACTLY
equal the dense per-token mixture ``y_t = p_t * FFN_{e_t}(x_t)`` —
forward and gradients — and dropped tokens must zero out cleanly."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen
from jax.sharding import Mesh, PartitionSpec as P

from kfac_pytorch_tpu.parallel.moe import ExpertFFN, SwitchMoE

NE, TL, D, DH = 4, 8, 10, 16     # experts/ranks, tokens per rank, dims


def _params(seed):
    rng = np.random.RandomState(seed)
    gate = {'kernel': jnp.asarray(rng.randn(D, NE) * 0.5, jnp.float32),
            'bias': jnp.asarray(rng.randn(NE) * 0.1, jnp.float32)}
    experts = []
    for i in range(NE):
        r = np.random.RandomState(100 + i)
        experts.append({
            'w_in': {'kernel': jnp.asarray(r.randn(D, DH) * 0.4,
                                           jnp.float32),
                     'bias': jnp.asarray(r.randn(DH) * 0.1, jnp.float32)},
            'w_out': {'kernel': jnp.asarray(r.randn(DH, D) * 0.4,
                                            jnp.float32),
                      'bias': jnp.asarray(r.randn(D) * 0.1, jnp.float32)},
        })
    stacked = jax.tree.map(lambda *a: jnp.stack(a), *experts)
    return gate, experts, stacked


def _dense_oracle(gate, experts, x):
    """y_t = p_t * FFN_{e_t}(x_t), computed expert-by-expert densely."""
    logits = x @ gate['kernel'] + gate['bias']
    probs = jax.nn.softmax(logits, axis=-1)
    e = jnp.argmax(probs, axis=-1)
    p = jnp.take_along_axis(probs, e[:, None], axis=1)[:, 0]
    outs = jnp.stack([
        ExpertFFN(D, DH).apply({'params': ep}, x) for ep in experts])
    y = jnp.take_along_axis(outs, e[None, :, None], axis=0)[0]
    return y * p[:, None]


def test_switch_moe_matches_dense_mixture():
    x = jnp.asarray(np.random.RandomState(0).randn(NE * TL, D),
                    jnp.float32)
    y_target = jnp.asarray(np.random.RandomState(1).randn(NE * TL, D),
                           jnp.float32)
    gate, experts, stacked = _params(7)
    mesh = Mesh(np.array(jax.devices()[:NE]), ('expert',))
    # capacity = ALL local tokens -> nothing can drop -> exact
    moe = SwitchMoE(D, DH, capacity=TL, axis='expert')
    especs = jax.tree.map(lambda _: P('expert'), stacked)

    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=({'gate': P(), 'expert': especs}, P('expert'),
                  P('expert')),
        out_specs=(P('expert'), P(), {'gate': P(),
                                      'expert': especs}))
    def run(params, x, y_target):
        local = {'gate': params['gate'],
                 'expert': jax.tree.map(lambda a: a[0], params['expert'])}

        def loss_fn(p):
            out, _ = moe.apply({'params': p}, x)
            return jax.lax.pmean(((out - y_target) ** 2).mean(),
                                 'expert'), out

        (loss, out), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(local)
        return out, loss, {'gate': grads['gate'],
                           'expert': jax.tree.map(lambda a: a[None],
                                                  grads['expert'])}

    params = {'gate': gate, 'expert': stacked}
    out_ep, loss_ep, grads_ep = run(params, x, y_target)

    def dense_loss(gp):
        out = _dense_oracle(gp['gate'], [
            jax.tree.map(lambda a: a[i], gp['expert'])
            for i in range(NE)], x)
        return ((out - y_target) ** 2).mean(), out

    (loss_d, out_d), grads_d = jax.value_and_grad(
        dense_loss, has_aux=True)({'gate': gate, 'expert': stacked})

    np.testing.assert_allclose(np.asarray(out_ep), np.asarray(out_d),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(float(loss_ep), float(loss_d), rtol=1e-6)
    # expert grads: EP computes d(local-mean)/dtheta; pmean makes the
    # loss the global mean on both sides
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6),
        grads_ep, grads_d)


def test_switch_moe_capacity_drops_zero():
    """capacity=1 forces overflow: dropped tokens produce EXACTLY zero
    output (Switch semantics) and the aux mask reports them."""
    x = jnp.asarray(np.random.RandomState(3).randn(TL, D), jnp.float32)
    gate, experts, _ = _params(8)
    moe = SwitchMoE(D, DH, capacity=1, axis=None)
    # axis=None: one local expert (index 0), gate width 1 -> everything
    # routes to it; tokens after the first must drop
    params = {'gate': {'kernel': gate['kernel'][:, :1],
                       'bias': gate['bias'][:1]},
              'expert': experts[0]}
    y, aux = moe.apply({'params': params}, x)
    assert bool(aux['dropped'][0]) is False
    assert bool(aux['dropped'][1:].all()) is True
    np.testing.assert_array_equal(np.asarray(y[1:]), 0)
    assert np.abs(np.asarray(y[0])).max() > 0