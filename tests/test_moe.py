"""Expert-parallel Switch MoE (parallel/moe.py) on the CPU mesh: with no
capacity overflow the all_to_all-dispatched computation must EXACTLY
equal the dense per-token mixture ``y_t = p_t * FFN_{e_t}(x_t)`` —
forward and gradients — and dropped tokens must zero out cleanly."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from kfac_pytorch_tpu.parallel.moe import ExpertFFN, SwitchMoE
from tests import helpers

# See tests/test_tp.py: these oracles take grads INSIDE the shard_map
# body, which the legacy shard_map shim (check_rep=False) mis-transposes
# for replicated operands. Live probe, not a version pin; the owner-
# local expert K-FAC path is covered backend-independently by
# tests/test_meshplan.py with oracle capture operands.
requires_body_autodiff = pytest.mark.skipif(
    helpers.shard_map_body_autodiff_broken(),
    reason='legacy shard_map shim (check_rep=False) mis-transposes '
           'in-body autodiff: replicated-operand cotangents miss their '
           'cross-axis psum (probe: tests/helpers.py'
           '::shard_map_body_autodiff_broken)')

NE, TL, D, DH = 4, 8, 10, 16     # experts/ranks, tokens per rank, dims


def _params(seed):
    rng = np.random.RandomState(seed)
    gate = {'kernel': jnp.asarray(rng.randn(D, NE) * 0.5, jnp.float32),
            'bias': jnp.asarray(rng.randn(NE) * 0.1, jnp.float32)}
    experts = []
    for i in range(NE):
        r = np.random.RandomState(100 + i)
        experts.append({
            'w_in': {'kernel': jnp.asarray(r.randn(D, DH) * 0.4,
                                           jnp.float32),
                     'bias': jnp.asarray(r.randn(DH) * 0.1, jnp.float32)},
            'w_out': {'kernel': jnp.asarray(r.randn(DH, D) * 0.4,
                                            jnp.float32),
                      'bias': jnp.asarray(r.randn(D) * 0.1, jnp.float32)},
        })
    stacked = jax.tree.map(lambda *a: jnp.stack(a), *experts)
    return gate, experts, stacked


def _dense_oracle(gate, experts, x):
    """y_t = p_t * FFN_{e_t}(x_t), computed expert-by-expert densely."""
    logits = x @ gate['kernel'] + gate['bias']
    probs = jax.nn.softmax(logits, axis=-1)
    e = jnp.argmax(probs, axis=-1)
    p = jnp.take_along_axis(probs, e[:, None], axis=1)[:, 0]
    outs = jnp.stack([
        ExpertFFN(D, DH).apply({'params': ep}, x) for ep in experts])
    y = jnp.take_along_axis(outs, e[None, :, None], axis=0)[0]
    return y * p[:, None]


@requires_body_autodiff
def test_switch_moe_matches_dense_mixture():
    x = jnp.asarray(np.random.RandomState(0).randn(NE * TL, D),
                    jnp.float32)
    y_target = jnp.asarray(np.random.RandomState(1).randn(NE * TL, D),
                           jnp.float32)
    gate, experts, stacked = _params(7)
    mesh = Mesh(np.array(jax.devices()[:NE]), ('expert',))
    # capacity = ALL local tokens -> nothing can drop -> exact
    moe = SwitchMoE(D, DH, capacity=TL, axis='expert')
    especs = jax.tree.map(lambda _: P('expert'), stacked)

    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=({'gate': P(), 'expert': especs}, P('expert'),
                  P('expert')),
        out_specs=(P('expert'), P(), {'gate': P(),
                                      'expert': especs}))
    def run(params, x, y_target):
        local = {'gate': params['gate'],
                 'expert': jax.tree.map(lambda a: a[0], params['expert'])}

        def loss_fn(p):
            out, _ = moe.apply({'params': p}, x)
            return jax.lax.pmean(((out - y_target) ** 2).mean(),
                                 'expert'), out

        (loss, out), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(local)
        return out, loss, {'gate': grads['gate'],
                           'expert': jax.tree.map(lambda a: a[None],
                                                  grads['expert'])}

    params = {'gate': gate, 'expert': stacked}
    out_ep, loss_ep, grads_ep = run(params, x, y_target)

    def dense_loss(gp):
        out = _dense_oracle(gp['gate'], [
            jax.tree.map(lambda a: a[i], gp['expert'])
            for i in range(NE)], x)
        return ((out - y_target) ** 2).mean(), out

    (loss_d, out_d), grads_d = jax.value_and_grad(
        dense_loss, has_aux=True)({'gate': gate, 'expert': stacked})

    np.testing.assert_allclose(np.asarray(out_ep), np.asarray(out_d),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(float(loss_ep), float(loss_d), rtol=1e-6)
    # expert grads: EP computes d(local-mean)/dtheta; pmean makes the
    # loss the global mean on both sides
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6),
        grads_ep, grads_d)


def test_switch_moe_capacity_drops_zero():
    """capacity=1 forces overflow: dropped tokens produce EXACTLY zero
    output (Switch semantics) and the aux mask reports them."""
    x = jnp.asarray(np.random.RandomState(3).randn(TL, D), jnp.float32)
    gate, experts, _ = _params(8)
    moe = SwitchMoE(D, DH, capacity=1, axis=None)
    # axis=None: one local expert (index 0), gate width 1 -> everything
    # routes to it; tokens after the first must drop
    params = {'gate': {'kernel': gate['kernel'][:, :1],
                       'bias': gate['bias'][:1]},
              'expert': experts[0]}
    y, aux = moe.apply({'params': params}, x)
    assert bool(aux['dropped'][0]) is False
    assert bool(aux['dropped'][1:].all()) is True
    np.testing.assert_array_equal(np.asarray(y[1:]), 0)
    assert np.abs(np.asarray(y[0])).max() > 0

@requires_body_autodiff
def test_moe_kfac_dp_ep_invariance():
    """One K-FAC step (MPD 'eigen' over the data axis) on a 2x2
    ('data', 'expert') mesh matches the expert-mesh-only full-batch run
    — data sharding must not change the preconditioned update with the
    expert capture riding the all_to_all dispatch.

    The loss fed to the capture MUST be the LOCAL mean (the framework's
    convention everywhere): the engine's G-factor scaling assumes
    local-mean cotangents, so a globally-psum-normalized loss makes the
    G scale depend on the shard size and breaks cross-mesh comparisons
    (diagnosed round 3 — looked like an engine bug, was a harness one).
    With the convention respected, (1,2)-vs-expert-only is EXACT and
    nd=2 matches to MPD-eigen tolerance."""
    import kfac_pytorch_tpu as kfac
    from kfac_pytorch_tpu import capture

    ND, NE2 = 2, 2
    T = NE2 * TL
    x = jnp.asarray(np.random.RandomState(5).randn(ND * T, D), jnp.float32)
    y = jnp.asarray(np.random.RandomState(6).randn(ND * T, D), jnp.float32)
    gate, experts, stacked = _params(11)
    gate = {'kernel': gate['kernel'][:, :NE2], 'bias': gate['bias'][:NE2]}
    stacked2 = jax.tree.map(lambda a: a[:NE2], stacked)
    local = SwitchMoE(D, DH, capacity=T, axis=None)

    def make_pre(nd, axis):
        pre = kfac.KFAC(variant='eigen', lr=0.1, damping=0.01,
                        fac_update_freq=1, kfac_update_freq=1,
                        num_devices=nd, axis_name=axis)
        xs = x[:T]
        variables = capture.init(local, jax.random.PRNGKey(0), xs)
        pre.setup(capture.collect_layer_meta(local, variables, xs))
        return pre

    especs = jax.tree.map(lambda _: P('expert'), stacked2)
    params = {'gate': gate, 'expert': stacked2}


    def run(mesh, axes, kfac_axis, nd, cap):
        # capacity = the mesh's LOCAL token count: no token can drop and
        # every expert's TOTAL buffer rows (sources x capacity, summed
        # over the K-FAC world) are equal across meshes — the factor
        # normalization counts buffer rows, so unequal buffers would
        # scale the factors differently and break the invariance
        moe = SwitchMoE(D, DH, capacity=cap, axis='expert')
        pre = make_pre(nd, kfac_axis)
        kstate = jax.tree.map(lambda a: jnp.stack([a] * NE2), pre.init())
        inner = (pre.state_pspecs(kfac_axis) if kfac_axis
                 else jax.tree.map(lambda _: P(),
                                   pre.state_pspecs(None)))
        kspecs = jax.tree.map(lambda s: P('expert', *s), inner,
                              is_leaf=lambda v: isinstance(v, P))
        xspec = P(axes) if isinstance(axes, str) else P(axes)

        @functools.partial(
            jax.shard_map, mesh=mesh,
            in_specs=({'gate': P(), 'expert': especs}, kspecs,
                      xspec, xspec),
            out_specs={'gate': P(), 'expert': especs})
        def step(params, kstate, x, y):
            local_p = {'gate': params['gate'],
                       'expert': jax.tree.map(lambda a: a[0],
                                              params['expert'])}
            all_axes = (('data', 'expert') if kfac_axis else 'expert')
            # LOCAL-mean loss (the capture convention) + explicit grad
            # averaging over the K-FAC world — NOT a globally-normalized
            # psum loss, which would scale the G factors by shard size
            _, _, grads, acts, gs, _ = \
                capture.value_and_grad_with_capture(
                    moe, lambda o: ((o[0] - y) ** 2).mean(),
                    {'params': local_p}, x, axis_name=all_axes)
            if kfac_axis:
                grads = kfac.parallel.average_grads(grads, kfac_axis)
            k = jax.tree.map(lambda a: a[0], kstate)
            new_grads, _ = pre.step(k, grads, acts, gs,
                                    axis_name=kfac_axis)
            return {'gate': new_grads['gate'],
                    'expert': jax.tree.map(lambda a: a[None],
                                           new_grads['expert'])}

        return step(params, kstate, x, y)

    total = ND * T
    mesh_e = Mesh(np.array(jax.devices()[:NE2]), ('expert',))
    want = run(mesh_e, 'expert', None, 1, cap=total // NE2)
    # (1, 2): same K-FAC world of one -> exact
    mesh_1 = Mesh(np.array(jax.devices()[:NE2]).reshape(1, NE2),
                  ('data', 'expert'))
    got1 = run(mesh_1, ('data', 'expert'), 'data', 1, cap=total // NE2)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7),
        got1, want)
    # (2, 2): distributed MPD world of two -> data sharding must not
    # change the math (grads differ only by f32 reduction order)
    mesh_2 = Mesh(np.array(jax.devices()[:ND * NE2]).reshape(ND, NE2),
                  ('data', 'expert'))
    got2 = run(mesh_2, ('data', 'expert'), 'data', ND,
               cap=total // (ND * NE2))
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-3, atol=1e-4),
        got2, want)
