"""Observability subsystem (kfac_pytorch_tpu/obs/).

Pins, per ISSUE 5's acceptance list:
- span nesting, the bounded ring, and flush-on-SIGTERM through the
  runlog chain;
- Perfetto/Chrome-trace schema validity of every emitted JSONL line;
- registry -> epoch-line suffix BYTE-compatibility with the legacy
  hand-plumbed path (health / resilience / kfac_phase);
- kfac-obs merging a pod drill's artifact classes (runlog + incident
  JSON + trace JSONL) into one ordered, clock-aligned timeline;
- drift ratios pinned on a synthetic predicted/measured pair, plus the
  schema over the real perfmodel block;
- exporters: JSONL, Prometheus textfile, native TensorBoard roundtrip,
  and rank gating.
"""

import json
import os
import signal
import subprocess
import sys
import textwrap

import pytest

from kfac_pytorch_tpu.obs import aggregate, drift, metrics, trace

pytestmark = pytest.mark.core

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- trace ---------------------------------------------------------------------


def test_span_nesting_and_taxonomy():
    rec = trace.TraceRecorder(None)
    with rec.span('outer', cat='kfac'):
        with rec.span('kfac.ComputeFactor', cat='kfac'):
            pass
    spans = [e for e in rec.events() if e['ph'] == 'X']
    # completion order: inner closes first
    assert [s['name'] for s in spans] == ['kfac.ComputeFactor', 'outer']
    inner, outer = spans
    # nesting: inner lies within outer on the wall axis
    assert outer['ts'] <= inner['ts']
    assert inner['ts'] + inner['dur'] <= outer['ts'] + outer['dur'] + 1e3
    assert trace.taxonomy_phases(('stats', 'pred', 'decomp', 'gather')) == [
        'CommunicateInverse', 'ComputeFactor', 'ComputeInverse',
        'Precondition']


def test_ring_buffer_bounded():
    rec = trace.TraceRecorder(None, maxlen=16)
    for i in range(50):
        rec.instant(f'e{i}')
    assert len(rec.events()) == 16
    assert rec.dropped == 50 + 2 - 16  # + metadata & clock_sync events
    # newest events survive
    assert rec.events()[-1]['name'] == 'e49'


def test_flush_appends_and_clears(tmp_path):
    path = str(tmp_path / 't.jsonl')
    rec = trace.TraceRecorder(path)
    rec.instant('a')
    n = rec.flush()
    assert n == 3  # metadata + clock_sync + a
    rec.instant('b')
    rec.flush()
    names = [json.loads(l)['name'] for l in open(path)]
    assert names == ['process_name', 'clock_sync', 'a', 'b']
    assert rec.events() == []


def test_trace_jsonl_is_valid_perfetto_schema(tmp_path):
    path = str(tmp_path / 't.jsonl')
    rec = trace.TraceRecorder(path, process_id=3)
    with rec.span('kfac.step', cat='kfac.step',
                  phases=['ComputeFactor', 'Precondition']):
        pass
    rec.instant('watchdog_trip', deadline_s=1.5)
    rec.counter('steps', {'n': 1})
    rec.complete('bench.iter', 0.01, cat='bench', i=0)
    rec.flush()
    lines = [l for l in open(path).read().splitlines() if l]
    assert lines
    for line in lines:
        evt = json.loads(line)  # every line independently parseable
        assert isinstance(evt['name'], str) and evt['name']
        assert evt['ph'] in ('X', 'i', 'C', 'M')
        assert isinstance(evt['pid'], int) and evt['pid'] == 3
        assert isinstance(evt['tid'], int)
        assert isinstance(evt['ts'], (int, float)) and evt['ts'] >= 0
        if evt['ph'] == 'X':
            assert evt['dur'] >= 0
            assert isinstance(evt.get('cat'), str)
        if evt['ph'] == 'i':
            assert evt['s'] in ('g', 'p', 't')
        if 'args' in evt:
            assert isinstance(evt['args'], dict)
    # and the merged form loads as one Perfetto trace object
    merged = aggregate.merged_chrome_trace(
        {'events': [], 'sources': [],
         '_trace_events': [json.loads(l) for l in lines]})
    assert isinstance(merged['traceEvents'], list)


def test_flush_on_sigterm_runlog_chain(tmp_path):
    """A SIGTERM with NO manual flush must still land the buffered
    events in the JSONL — the recorder rides the runlog flush chain."""
    path = tmp_path / 'sig.jsonl'
    script = textwrap.dedent(f"""
        import os, signal, sys
        sys.path.insert(0, {REPO!r})
        from kfac_pytorch_tpu.obs import trace
        rec = trace.install({str(path)!r})
        rec.instant('before_sigterm', step=7)
        os.kill(os.getpid(), signal.SIGTERM)
        print('UNREACHABLE')  # the chained handler re-delivers SIGTERM
    """)
    p = subprocess.run([sys.executable, '-c', script],
                       capture_output=True, text=True, timeout=120)
    assert p.returncode == -signal.SIGTERM, (p.returncode, p.stderr)
    assert 'UNREACHABLE' not in p.stdout
    names = [json.loads(l)['name'] for l in open(path)]
    assert 'before_sigterm' in names


def test_module_level_noops_without_recorder():
    assert trace.get() is None or trace.uninstall() is not None
    trace.uninstall()
    assert trace.instant('nobody_home') is None
    with trace.span('nobody_home'):
        pass
    assert trace.flush() == 0


def test_install_from_env_role_naming(tmp_path):
    env = {trace.ENV_TRACE_DIR: str(tmp_path), 'JAX_PROCESS_ID': '2'}
    rec = trace.install_from_env(env=env, role='sup')
    try:
        assert rec.path.endswith('trace-host2-sup.jsonl')
        assert rec.process_id == 2
    finally:
        trace.uninstall()
    # role applies to an exact-path target too (two co-hosted processes
    # must never append into one file)
    exact = {trace.ENV_TRACE_DIR: str(tmp_path / 'run.jsonl')}
    rec = trace.install_from_env(env=exact, role='sup')
    try:
        assert rec.path.endswith('run-sup.jsonl')
    finally:
        trace.uninstall()
    assert trace.install_from_env(env={}) is None


# -- registry / suffix byte-compatibility -------------------------------------


def _old_suffixes(health_epoch, res_delta, phase_ms):
    from kfac_pytorch_tpu.utils.runlog import (health_suffix,
                                               kfac_phase_suffix,
                                               resilience_suffix)
    return (health_suffix(health_epoch) + resilience_suffix(res_delta)
            + kfac_phase_suffix(phase_ms))


def test_registry_suffixes_byte_identical_to_legacy(tmp_path):
    """Drive the SAME event stream through the legacy plumbing and the
    registry; the epoch-line suffix strings must match byte-for-byte —
    including the all-clean epoch rendering to ''."""
    from kfac_pytorch_tpu import resilience
    from kfac_pytorch_tpu.utils.metrics import HealthMonitor, PhaseTimers
    from kfac_pytorch_tpu.utils.runlog import counter_deltas
    resilience.counters.reset()
    try:
        gov_counts = {'straggler_level': 0, 'straggler_degrades': 0}

        # legacy side
        import logging
        quiet = logging.getLogger('test_obs.quiet')
        quiet.setLevel(logging.CRITICAL)
        old_mon = HealthMonitor(quiet)
        old_timers = PhaseTimers()
        # registry side
        reg = metrics.Registry(process_id=0)
        new_mon = HealthMonitor(quiet, registry=reg)
        new_timers = PhaseTimers(registry=reg)
        reg.add_collector(metrics.resilience_collector(lambda: gov_counts))
        res_prev = {}

        def epoch(mets_seq, phase_seq, res_bumps, gov):
            nonlocal res_prev
            for name, by in res_bumps:
                resilience.counters.bump(name, by)
            gov_counts.update(gov)
            for m in mets_seq:
                old_mon.update(m)
                new_mon.update(m)
            for phases, secs in phase_seq:
                old_timers.record(phases, secs)
                new_timers.record(phases, secs)
            res_now = resilience.counters.snapshot()
            res_now.update(gov_counts)
            res_delta, res_prev = counter_deltas(res_now, res_prev), res_now
            legacy = _old_suffixes(old_mon.epoch_flush(), res_delta,
                                   old_timers.epoch_flush())
            via_registry = reg.epoch_suffixes()
            new_mon.epoch_flush()
            assert via_registry == legacy, (via_registry, legacy)
            return legacy

        # epoch 0: clean — both must render ''
        s0 = epoch([{'health/skipped': 0, 'health/fallbacks': 0,
                     'health/rung': 0}],
                   [(('pred',), 0.010), (('pred',), 0.012)],
                   [], {'straggler_level': 0})
        assert s0.startswith(' kfac_phase_ms=')  # phases always render
        # epoch 1: health events + resilience counters + phase marginals
        s1 = epoch([{'health/skipped': 1, 'health/fallbacks': 0,
                     'health/rung': 1},
                    {'health/skipped': 2, 'health/fallbacks': 1,
                     'health/rung': 2}],
                   [(('pred',), 0.010),
                    (('pred', 'stats', 'decomp', 'gather'), 0.050)],
                   [('io_retries', 2), ('watchdog_trips', 1)],
                   {'straggler_level': 1, 'straggler_degrades': 1})
        assert '[health: skipped=2 sgd_fallbacks=1 max_rung=2]' in s1
        assert 'io_retries=2' in s1 and 'straggler_level=1' in s1
        assert 'decomp+gather+stats' in s1
        # epoch 2: quiet again — deltas reset, stale phase gauges gone,
        # gauge-typed level passes through
        s2 = epoch([{'health/skipped': 2, 'health/fallbacks': 1,
                     'health/rung': 0}], [], [],
                   {'straggler_level': 1})
        assert '[health:' not in s2
        assert 'kfac_phase_ms' not in s2
        assert 'io_retries' not in s2
        assert 'straggler_level=1' in s2
    finally:
        resilience.counters.reset()


def test_registry_counter_monotonic_and_types():
    reg = metrics.Registry(process_id=0)
    c = reg.counter('a')
    with pytest.raises(ValueError):
        c.inc(-1)
    c.inc(3)
    c.set_total(2)       # ignored: monotonic
    assert c.value == 3
    with pytest.raises(TypeError):
        reg.gauge('a')   # type collision
    w = reg.watermark('w')
    w.set(5)
    w.set(2)
    assert reg.epoch_flush()['w'] == 5
    assert reg.epoch_flush()['w'] == 0  # watermark reset per epoch


def test_health_monitor_resume_baseline_not_reannounced():
    """A restored cumulative baseline must not appear in the first
    epoch's registry deltas (mirrors the legacy monitor semantics)."""
    import logging

    class FakeHealth:
        skipped, fallbacks, rung = 4, 1, 0

    class FakeState:
        health = FakeHealth()

    reg = metrics.Registry(process_id=0)
    quiet = logging.getLogger('test_obs.quiet2')
    quiet.setLevel(logging.CRITICAL)
    from kfac_pytorch_tpu.utils.metrics import HealthMonitor
    HealthMonitor(quiet, state=FakeState(), registry=reg)
    assert reg.epoch_suffixes() == ''


# -- exporters -----------------------------------------------------------------


def _populated_registry(process_id=0):
    reg = metrics.Registry(process_id=process_id)
    reg.counter('resilience/io_retries').inc(2)
    reg.gauge('kfac_phase/pred').set(1.5)
    h = reg.histogram('step_seconds', buckets=(0.01, 0.1, 1.0))
    for v in (0.005, 0.05, 0.5, 5.0):
        h.observe(v)
    return reg


def test_jsonl_exporter(tmp_path):
    reg = _populated_registry()
    reg.add_exporter(metrics.JsonlExporter(str(tmp_path / 'm.jsonl')))
    assert reg.export(step=0) == 1
    assert reg.export(step=1) == 1
    lines = [json.loads(l) for l in open(tmp_path / 'm.jsonl')]
    assert [l['step'] for l in lines] == [0, 1]
    m = lines[1]['metrics']
    assert m['resilience/io_retries'] == 2
    assert m['step_seconds']['count'] == 4
    assert m['step_seconds']['buckets'] == {
        '0.01': 1, '0.1': 2, '1.0': 3, '+Inf': 4}  # cumulative


def test_prometheus_textfile_exporter(tmp_path):
    path = str(tmp_path / 'kfac.prom')
    reg = _populated_registry()
    reg.add_exporter(metrics.PrometheusTextfileExporter(path))
    reg.export(step=0)
    text = open(path).read()
    # the registry's real kinds drive the TYPE lines (no name heuristics)
    assert '# TYPE kfac_resilience_io_retries counter' in text
    assert 'kfac_resilience_io_retries 2' in text
    assert '# TYPE kfac_kfac_phase_pred gauge' in text
    assert 'kfac_step_seconds_bucket{le="+Inf"} 4' in text
    assert 'kfac_step_seconds_count 4' in text
    assert 'kfac_step_seconds_sum' in text
    # atomic write: no tmp debris
    assert not os.path.exists(path + '.tmp')


def test_tensorboard_exporter_roundtrip(tmp_path):
    from kfac_pytorch_tpu.utils.summary import read_scalars
    reg = _populated_registry()
    reg.add_exporter(metrics.TensorBoardExporter(str(tmp_path)))
    reg.export(step=3)
    series = read_scalars(str(tmp_path))
    assert series['resilience/io_retries'] == [(3, 2.0)]
    assert series['kfac_phase/pred'] == [(3, 1.5)]
    (step, mean), = series['step_seconds/mean']
    assert step == 3 and abs(mean - 5.555 / 4) < 1e-4


def test_epoch_gauges_survive_flush_for_exporters(tmp_path):
    """The trainers render the epoch line (flushing the per-epoch
    gauges) BEFORE exporting; the exporters must still see the phase
    timings — staleness hides them from the NEXT epoch line only."""
    from kfac_pytorch_tpu.utils.metrics import PhaseTimers
    reg = metrics.Registry(process_id=0)
    timers = PhaseTimers(registry=reg)
    reg.add_exporter(metrics.JsonlExporter(str(tmp_path / 'm.jsonl')))
    timers.record(('pred',), 0.010)
    s = reg.epoch_suffixes()
    assert 'kfac_phase_ms=' in s
    reg.export(step=0)
    snap = json.loads(open(tmp_path / 'm.jsonl').read())['metrics']
    assert snap['kfac_phase/pred'] == 10.0
    assert 'kfac_phase/step_mean' in snap
    # but an idle next epoch renders no stale phase suffix
    assert 'kfac_phase_ms=' not in reg.epoch_suffixes()


def test_setup_trainer_helper(tmp_path):
    from kfac_pytorch_tpu import obs
    try:
        tracer, reg = obs.setup_trainer(trace_dir=str(tmp_path),
                                        prom_file=str(tmp_path / 'p'))
        assert tracer is trace.get()
        assert tracer.path.endswith('trace-host0.jsonl')
        assert len(reg._exporters) == 2 and len(reg._collectors) == 1
    finally:
        trace.uninstall()
    # no trace dir, no env: tracing off, registry still built
    tracer, reg = obs.setup_trainer()
    assert (tracer is None) == (trace.ENV_TRACE_DIR not in os.environ)
    trace.uninstall()


def test_export_rank_gated(tmp_path):
    reg = _populated_registry(process_id=1)
    reg.add_exporter(metrics.JsonlExporter(str(tmp_path / 'm.jsonl')))
    assert reg.export(step=0) == 0
    assert not os.path.exists(tmp_path / 'm.jsonl')


# -- aggregation (kfac-obs) ----------------------------------------------------


def _write_drill_artifacts(tmp_path):
    """Synthesize the 2-host SIGKILL drill's artifact classes with the
    EXACT line forms the modules emit (the regexes are shared with
    resilience.incident, so a drifted form fails here AND there)."""
    # host0.out: timestamped pod-supervisor lines interleaved with the
    # trainer's clockless protocol/heartbeat lines, in causal order
    host0 = tmp_path / 'host0.out'
    host0.write_text('\n'.join([
        '2026-08-03 10:00:00,100 pod-supervisor: launching gen 0',
        'EPOCH 0 step=2 loss=2.1000',
        'heartbeat: peer 1 declared dead — no heartbeat advance for '
        '4.52s (deadline 4.00s, last step 3) [resilience: peer_dead=1 '
        'peer=1 detect_s=4.52]',
        '2026-08-03 10:00:08,000 elastic: shrinking world 2 -> 1 '
        'survivors=[0] gen=1',
        'RESHARDED from_world=2 to_world=1 step=4',
        'RESUMED from=checkpoint-0 step=4',
        'EPOCH 1 step=6 loss=1.9000',
        'DONE final_step=8 epochs=3',
    ]) + '\n')
    host1 = tmp_path / 'host1.out'
    host1.write_text(
        '2026-08-03 10:00:01,000 pod-supervisor: launching gen 0\n'
        'EPOCH 0 step=2 loss=2.1000\n')
    # incident-host0.json via the real producer; live walls sit on the
    # same clock the log asctimes parse to (one machine, like the drill)
    base = aggregate._parse_asctime('2026-08-03 10:00:00,100 x')
    from kfac_pytorch_tpu.resilience.incident import IncidentReport
    rep = IncidentReport(host_id=0)
    rep.add_event('peer_dead', peer=1, detect_s=4.52, wall=base + 5.0)
    rep.add_event('trainer_exit', rc=115, reason='peer dead',
                  wall=base + 5.5)
    rep.add_event('shrink', wall=base + 7.9,
                  **{'from': 2, 'to': 1, 'survivors': [0], 'gen': 1})
    rep.write(str(tmp_path / 'incident-host0.json'))
    # per-host trace JSONL via the real recorder, on the same synthetic
    # clock (injectable clock — the drill's files all share one machine)
    rec = trace.TraceRecorder(str(tmp_path / 'trace-host0.jsonl'),
                              process_id=0, clock=lambda: base + 4.6)
    with rec.span('kfac.dispatch', cat='kfac.step', step=3,
                  phases=['ComputeFactor']):
        pass
    rec.instant('peer_dead', peer=1, detect_s=4.52)
    rec.flush()
    # the registry's metrics.jsonl lives in the same --trace dir in real
    # runs: it must be ignored by the trace loader, not leak junk rows
    (tmp_path / 'metrics.jsonl').write_text(json.dumps(
        {'wall': base, 'step': 0, 'metrics': {'health/skipped': 0}}) + '\n')
    return host0, host1


def test_aggregate_merges_artifacts_into_ordered_timeline(tmp_path):
    host0, host1 = _write_drill_artifacts(tmp_path)
    timeline = aggregate.build_timeline([str(tmp_path)])
    events = timeline['events']
    kinds = [e['kind'] for e in events]
    for needed in ('peer_dead', 'shrink', 'resharded', 'resumed',
                   'trainer_exit', 'run_done'):
        assert needed in kinds, (needed, sorted(set(kinds)))

    def first(kind):
        return next(i for i, e in enumerate(events) if e['kind'] == kind)

    # causal order on the merged clock
    assert first('peer_dead') < first('shrink') < first('resharded')
    assert first('resharded') < first('resumed') < first('run_done')
    # clock alignment: the clockless RESHARDED line inherited the
    # preceding timestamped shrink line's wall (carry-forward)
    resh = events[first('resharded')]
    assert resh['wall'] is None
    shrink_wall = aggregate._parse_asctime('2026-08-03 10:00:08,000 x')
    assert resh['wall_aligned'] is not None
    assert 0 <= resh['wall_aligned'] - shrink_wall < 1.0
    # host attribution from filenames / payloads
    assert events[first('resharded')]['host'] == 0
    assert {s['kind'] for s in timeline['sources']} == {
        'trace', 'incident', 'log'}
    # detail fields parsed and coerced
    d = events[first('shrink')]['detail']
    assert (d['from'], d['to']) == (2, 1)


def test_aggregate_cli_writes_timeline_and_merged_trace(tmp_path, capsys):
    _write_drill_artifacts(tmp_path)
    out = tmp_path / 'timeline.json'
    tout = tmp_path / 'pod_trace.json'
    rc = aggregate.main([str(tmp_path), '-o', str(out),
                         '--trace-out', str(tout)])
    assert rc == 0
    printed = capsys.readouterr().out
    assert 'pod timeline' in printed and 'peer_dead' in printed
    doc = json.loads(out.read_text())
    assert doc['events'] and doc['sources']
    assert '_trace_events' not in doc
    merged = json.loads(tout.read_text())
    names = [e['name'] for e in merged['traceEvents']]
    # raw spans AND injected log/incident instants share the canvas
    assert 'kfac.dispatch' in names
    assert 'shrink' in names
    # every merged event is trace-shaped: the co-located metrics.jsonl
    # (not Chrome-trace events) must not have leaked junk rows
    assert all('ph' in e and 'name' in e for e in merged['traceEvents'])


def test_aggregate_offset_applies(tmp_path):
    _write_drill_artifacts(tmp_path)
    base = aggregate.build_timeline([str(tmp_path / 'host0.out')])
    moved = aggregate.build_timeline([str(tmp_path / 'host0.out')],
                                     offsets={0: 100.0})
    w0 = [e['wall_aligned'] for e in base['events']
          if e['wall_aligned'] is not None]
    w1 = [e['wall_aligned'] for e in moved['events']
          if e['wall_aligned'] is not None]
    assert all(abs(b - a - 100.0) < 1e-6 for a, b in zip(w0, w1))


def test_incident_scrapes_trace_jsonl(tmp_path):
    path = str(tmp_path / 't.jsonl')
    rec = trace.TraceRecorder(path, process_id=0)
    rec.instant('watchdog_trip', deadline_s=2.0, rc=114)
    rec.instant('clock_sync_is_meta_not_resilience')  # cat=resilience!
    with rec.span('kfac.step'):
        pass
    rec.flush()
    from kfac_pytorch_tpu.resilience.incident import IncidentReport
    rep = IncidentReport(host_id=0).scrape_path(path)
    kinds = [e['kind'] for e in rep.events]
    assert 'watchdog_trip' in kinds
    assert 'kfac.step' not in kinds  # spans are not incident events
    trip = next(e for e in rep.events if e['kind'] == 'watchdog_trip')
    assert trip['rc'] == 114 and trip['wall'] is not None


# -- drift ---------------------------------------------------------------------


def _synthetic_predicted():
    phases = {'Model': 0.10, 'Precondition': 0.02, 'ComputeFactor': 0.05,
              'ComputeInverse_chol': 0.04, 'ComputeInverse_eigh_full': 8.0}
    return {'predicted_not_measured': True, 'scenarios': {
        'optimistic': {'phases_s': {k: v * 0.5 for k, v in phases.items()}},
        'central': {'phases_s': dict(phases)},
        'conservative': {'phases_s': {k: v * 2 for k, v in phases.items()}},
    }}


def test_drift_ratios_pinned_on_synthetic_pair():
    pred = _synthetic_predicted()
    measured = {'Model': 0.15, 'ComputeFactor': 0.05,
                'CommunicateFactor': 0.30}
    block = drift.drift_block(measured, pred, platform='TPU v5 lite',
                              variant='inverse_dp')
    assert block['comparable'] is True
    m = block['phases']['Model']
    assert m['ratio'] == 1.5                       # 0.15 / 0.10 central
    assert m['band_s'] == [0.05, 0.2]
    assert m['within_band'] is True                # inside [0.5x, 2x]
    f = block['phases']['ComputeFactor']
    assert f['ratio'] == 1.0 and f['within_band'] is True
    # no single-chip prediction for comm phases -> explicit null
    c = block['phases']['CommunicateFactor']
    assert c['predicted_s'] == {} and c['ratio'] is None
    assert c['within_band'] is None
    assert block['gate']['verdict'] == 'ok'
    assert block['gate']['violations'] == []

    # out-of-band measurement on the model chip: the gate trips
    bad = drift.drift_block({'Model': 0.5}, pred, platform='TPU v5e')
    assert bad['phases']['Model']['within_band'] is False
    assert bad['gate']['verdict'] == 'drift'
    assert bad['gate']['violations'] == ['Model']
    # same numbers on CPU: advisory, never chip evidence
    adv = drift.drift_block({'Model': 0.5}, pred, platform='cpu_fallback')
    assert adv['comparable'] is False
    assert adv['gate']['verdict'] == 'advisory'
    # tolerance widens the band
    tol = drift.drift_block({'Model': 0.5}, pred, platform='TPU v5e',
                            tolerance=3.0)
    assert tol['phases']['Model']['within_band'] is True

    # variant binds ComputeInverse to the right kernel
    chol = drift.drift_block({'ComputeInverse': 0.04}, pred,
                             platform='TPU v5e', variant='inverse_dp')
    assert chol['phases']['ComputeInverse']['ratio'] == 1.0
    eig = drift.drift_block({'ComputeInverse': 0.04}, pred,
                            platform='TPU v5e', variant='eigen_dp')
    assert eig['phases']['ComputeInverse']['ratio'] == round(0.04 / 8.0, 4)
    # joint phases sum their parts
    joint = drift.drift_block({'Model+ComputeFactor': 0.15}, pred,
                              platform='TPU v5e')
    assert joint['phases']['Model+ComputeFactor']['predicted_s'][
        'central'] == 0.15
    assert joint['phases']['Model+ComputeFactor']['ratio'] == 1.0


def test_drift_measured_adapters():
    got = drift.measured_from_phase_timers(
        {'pred': 1.0, 'stats': 2.0, 'decomp+gather': 30.0,
         'step_mean': 10.0})
    assert got == {'Precondition': 0.001, 'ComputeFactor': 0.002,
                   'ComputeInverse+CommunicateInverse': 0.030,
                   'step_mean': 0.010}
    extra = {'sgd_iter_s': 0.1, 'inverse_dp_iter_s_freq1': 0.18,
             'phase_breakdown_s': None}
    got = drift.measured_from_bench_extras(extra)
    assert got['Model'] == 0.1
    assert abs(got['Precondition+ComputeFactor+ComputeInverse']
               - 0.08) < 1e-12
    # with the breakdown ladder present, its per-phase numbers win
    extra['phase_breakdown_s'] = {'Total': 0.2, 'ComputeFactor': 0.03,
                                  'CommunicateInverse': 0.01, 'Rest': 0.1}
    got = drift.measured_from_bench_extras(extra)
    assert got['ComputeFactor'] == 0.03
    assert 'Total' not in got and 'Rest' not in got
    assert 'Precondition+ComputeFactor+ComputeInverse' not in got


def test_drift_block_over_real_perfmodel():
    perfmodel = pytest.importorskip('kfac_pytorch_tpu.perfmodel')
    pred = perfmodel.predict_block()
    if 'scenarios' not in pred:
        pytest.skip(f'perf inputs unavailable: {pred.get("error")}')
    block = drift.drift_block({'Model': 0.1, 'ComputeFactor': 0.02},
                              pred, platform='cpu smoke')
    assert 'error' not in block
    assert block['phases']['Model']['ratio'] is not None
    assert block['gate']['verdict'] == 'advisory'
    # malformed predicted never raises
    assert 'phases' in drift.drift_block({'Model': 0.1}, None)
    assert drift.micro_measured({'unstaggered': {
        'steady_ms': 10.0, 'refresh_ms': 35.0}}) == {
        'Model+Precondition+ComputeFactor': 0.01,
        'ComputeInverse': 0.025}
    assert drift.micro_measured({}) == {}


# -- training integration ------------------------------------------------------


def test_training_dispatch_and_step_spans():
    """build_train_step(tracer=) emits kfac.dispatch spans with the
    taxonomy phase set; PhaseTimers(tracer=) emits the kfac.step span."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import kfac_pytorch_tpu as kfac
    from kfac_pytorch_tpu import training
    from kfac_pytorch_tpu.models.tiny import TinyCNN
    from kfac_pytorch_tpu.utils.metrics import PhaseTimers

    rec = trace.TraceRecorder(None)
    timers = PhaseTimers(tracer=rec)
    rng = np.random.RandomState(0)
    batch = {'input': jnp.asarray(rng.randn(4, 8, 8, 3), jnp.float32),
             'label': jnp.asarray(rng.randint(0, 10, 4))}
    model = TinyCNN()
    tx = training.sgd(0.05)
    precond = kfac.KFAC(variant='eigen_dp', lr=0.05, damping=0.003,
                        fac_update_freq=1, kfac_update_freq=2,
                        num_devices=1, axis_name=None)
    state = training.init_train_state(model, tx, precond,
                                      jax.random.PRNGKey(0),
                                      batch['input'])

    def loss_fn(outputs, batch):
        return optax.softmax_cross_entropy_with_integer_labels(
            outputs, batch['label']).mean()

    step = training.build_train_step(model, tx, precond, loss_fn,
                                     tracer=rec)
    import time as _time
    for _ in range(3):
        t0 = _time.perf_counter()
        state, m = step(state, batch, lr=0.05, damping=0.003)
        float(m['loss'])
        timers.record(step.last_phases, _time.perf_counter() - t0)
    spans = [e for e in rec.events() if e['ph'] == 'X']
    dispatches = [s for s in spans if s['name'] == 'kfac.dispatch']
    steps = [s for s in spans if s['name'] == 'kfac.step']
    assert len(dispatches) == 3 and len(steps) == 3
    # step 0 is the first full decomposition; its phase args carry the
    # ledger taxonomy
    assert dispatches[0]['args']['step'] == 0
    all_phases = {p for s in steps for p in s['args']['phases']}
    assert 'ComputeFactor' in all_phases
    assert all_phases <= {'ComputeFactor', 'ComputeInverse',
                          'CommunicateInverse', 'Precondition'}


# -- automatic clock-offset solving (ISSUE 7 satellite) ------------------------


def _sync_trace(path, pid, rows):
    """Write a minimal trace JSONL of clock_sync instants.
    rows: [(receiver_wall, peer, peer_wall)]"""
    with open(path, 'w') as f:
        f.write(json.dumps({'ph': 'M', 'name': 'process_name',
                            'pid': pid, 'tid': 0, 'ts': 0,
                            'args': {'name': f'host{pid}'}}) + '\n')
        for wall, peer, peer_wall in rows:
            f.write(json.dumps({'name': 'clock_sync', 'ph': 'i',
                                'cat': 'meta', 's': 'p',
                                'ts': wall * 1e6, 'pid': pid, 'tid': 0,
                                'args': {'peer': peer,
                                         'peer_wall': peer_wall}})
                    + '\n')


def test_solve_offsets_recovers_injected_skew(tmp_path):
    """Host 1's clock runs 3.5s AHEAD. The cross-host clock_sync pairs
    (sender wall vs receiver wall at delivery, latency-biased upward)
    must solve host 1's correction to ~-3.5s, anchored at host 0."""
    T0, skew = 1_000_000.0, 3.5
    rows0, rows1 = [], []
    for i in range(6):
        t = T0 + 10 * i
        lat = 0.02 * (i + 1)        # varying latency; min ~0.02
        # host 0 receives host 1's payload: stamped on 1's fast clock
        rows0.append((t + lat, 1, t + skew))
        # host 1 receives host 0's payload: its local clock reads fast
        rows1.append((t + lat + skew, 0, t))
    _sync_trace(tmp_path / 'trace-host0.jsonl', 0, rows0)
    _sync_trace(tmp_path / 'trace-host1.jsonl', 1, rows1)
    offsets = aggregate.solve_offsets([str(tmp_path / 'trace-host0.jsonl'),
                                       str(tmp_path / 'trace-host1.jsonl')])
    assert set(offsets) == {1}
    assert offsets[1] == pytest.approx(-skew, abs=0.05)


def test_solve_offsets_bfs_propagates_through_indirect_links(tmp_path):
    """Host 2 only ever exchanged beats with host 1 (never with the
    anchor host 0): its offset must still solve through the 0<->1<->2
    link chain."""
    T0 = 5_000.0
    # host 1 runs +2.0s fast, host 2 +1.0s fast (both vs host 0)
    _sync_trace(tmp_path / 't0.jsonl', 0, [(T0, 1, T0 + 2.0)])
    _sync_trace(tmp_path / 't1.jsonl', 1,
                [(T0 + 2.0, 0, T0), (T0 + 2.0, 2, T0 + 1.0)])
    offsets = aggregate.solve_offsets([str(tmp_path / 't0.jsonl'),
                                       str(tmp_path / 't1.jsonl')])
    # e1 = +2.0 -> offset -2.0; e2 = e1 - (ts1 - peer_wall2) = 2 - 1 = 1
    assert offsets[1] == pytest.approx(-2.0, abs=1e-6)
    assert offsets[2] == pytest.approx(-1.0, abs=1e-6)


def test_solve_offsets_falls_back_to_empty_without_pairs(tmp_path):
    """No clock_sync pairs (tracing off, single host): the solver
    returns {} and the timeline keeps its carry-forward alignment."""
    rec = trace.TraceRecorder(str(tmp_path / 'plain.jsonl'), process_id=0)
    with rec.span('kfac.step'):
        pass
    rec.flush()
    assert aggregate.solve_offsets([str(tmp_path / 'plain.jsonl')]) == {}
    log = tmp_path / 'host0.out'
    log.write_text('EPOCH 0 step=5 loss=1.0\n')
    assert aggregate.solve_offsets([str(log)]) == {}


def test_heartbeat_emits_cross_host_clock_sync_pairs(tmp_path):
    """The solver's inputs come from the heartbeat monitors: every 8th
    publish with a fresh peer advance records a clock_sync instant
    carrying (peer, peer_wall)."""
    from kfac_pytorch_tpu.resilience.heartbeat import (
        FileLeaseTransport, PeerHeartbeat)
    from kfac_pytorch_tpu.resilience.retry import ManualClock
    rec = trace.install(None)
    try:
        clock = ManualClock()
        h0 = PeerHeartbeat(FileLeaseTransport(tmp_path, 0), 0, 2,
                           interval=1.0, deadline=50.0,
                           startup_grace=60.0, clock=clock.monotonic,
                           on_dead=lambda p, i: None)
        t1 = FileLeaseTransport(tmp_path, 1)
        for seq in range(1, 20):
            t1.publish({'host': 1, 'seq': seq, 'pid': 9, 'gen': 0,
                        'wall': 123456.0 + seq})
            h0.poll_once()
            clock.sleep(1.0)
        syncs = [e for e in rec.events()
                 if e.get('name') == 'clock_sync'
                 and (e.get('args') or {}).get('peer') == 1]
        assert syncs, 'no cross-host clock_sync emitted'
        assert all(isinstance(s['args']['peer_wall'], float)
                   for s in syncs)
        # throttled: every 8th publish, not every poll
        assert len(syncs) <= 4
    finally:
        trace.uninstall()


def test_aggregate_cli_solves_offsets_by_default(tmp_path, capsys):
    _sync_trace(tmp_path / 'trace-host0.jsonl', 0,
                [(1000.0, 1, 998.0)])
    _sync_trace(tmp_path / 'trace-host1.jsonl', 1,
                [(1002.0, 0, 1000.0)])
    aggregate.main([str(tmp_path / 'trace-host0.jsonl'),
                    str(tmp_path / 'trace-host1.jsonl')])
    out = capsys.readouterr().out
    assert 'clock offsets solved' in out and 'host1=' in out
    aggregate.main(['--no-solve-offsets',
                    str(tmp_path / 'trace-host0.jsonl')])
    out = capsys.readouterr().out
    assert 'clock offsets solved' not in out
