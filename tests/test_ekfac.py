"""E-KFAC (variant='ekfac', beyond the reference — George et al. 2018):
per-example second moments in the joint Kronecker eigenbasis replace the
eigenvalue outer product ``dg (x) da``.

Pinned here:
  1. the scales equal an explicit per-example oracle exactly (dense);
  2. the E-KFAC diagonal is a provably better Fisher approximation than
     the K-FAC eigenvalues in the SAME basis (the paper's optimality
     theorem, checked in Frobenius norm against the empirical Fisher);
  3. MPD invariance — nd=2 sharded scales (pmean) match the world-1
     full-batch run;
  4. zero scales (fresh start / restored pre-ekfac checkpoint) fall back
     to the plain eigen denominator exactly;
  5. the squared-overlap basis transport is exact under sign flips.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from flax import linen
from jax.sharding import Mesh, PartitionSpec as P

import kfac_pytorch_tpu as kfac
from kfac_pytorch_tpu import capture, engine, ops, training
from kfac_pytorch_tpu import nn as knn

pytestmark = pytest.mark.core

B, DIN, DOUT = 16, 8, 5


class OneLayer(linen.Module):
    @linen.compact
    def __call__(self, x, train=True):
        return knn.Dense(DOUT, name='fc')(x)


def _data(seed=0):
    rng = np.random.RandomState(seed)
    return (jnp.asarray(rng.randn(B, DIN), jnp.float32),
            jnp.asarray(rng.randint(0, DOUT, B)))


def _ce(out, y):
    return optax.softmax_cross_entropy_with_integer_labels(out, y).mean()


def _make_pre(variant, num_devices=1, axis_name=None, **kw):
    # bucket_fn=identity: no padding, so the oracle can work in true dims
    pre = kfac.KFAC(variant=variant, lr=0.1, damping=0.01,
                    fac_update_freq=1, kfac_update_freq=1,
                    factor_decay=1.0, num_devices=num_devices,
                    axis_name=axis_name, bucket_fn=lambda d: d, **kw)
    model = OneLayer()
    x, _ = _data()
    variables = capture.init(model, jax.random.PRNGKey(0), x)
    pre.setup(capture.collect_layer_meta(model, variables, x))
    return pre, model, variables


def _capture_batch(model, variables, x, y):
    return capture.value_and_grad_with_capture(
        model, lambda out: _ce(out, y), variables, x)


def test_ekfac_scales_match_per_example_oracle():
    x, y = _data()
    pre, model, variables = _make_pre('ekfac')
    _, _, grads, acts, gs, _ = _capture_batch(model, variables, x, y)
    _, state = pre.step(pre.init(), grads, acts, gs)

    meta = pre.plan.metas[0]
    pg = pre.plan.pred_groups[0]
    qa = np.asarray(state.decomp['evecs'][str(pg.da)][int(pg.row_a[0])])
    qg = np.asarray(state.decomp['evecs'][str(pg.dg)][int(pg.row_g[0])])
    got = np.asarray(state.decomp['scales']['g0'][0])

    # oracle: explicit per-example gradient matrices g_b a_b^T (bias ones
    # column; cotangents un-batch-averaged), projected and squared
    a_rows = np.concatenate(
        [np.asarray(x), np.ones((B, 1), np.float32)], axis=1)
    g_tilde = np.asarray(capture.layer_g(gs, meta))
    want = np.zeros((pg.dg, pg.da), np.float64)
    for b in range(B):
        grad_b = np.outer(B * g_tilde[b], a_rows[b])
        want += (qg.T @ grad_b @ qa) ** 2
    want /= B
    # factor_decay=1.0 -> the state holds exactly the one-batch moments
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=1e-6)


def test_ekfac_conv_scales_match_per_patch_oracle():
    """Conv path: the scales equal the explicit per-(example, position)
    oracle under the same patch rows and normalizations the A/G factor
    stats use (patch rows / spatial; g rows x N x spatial)."""
    N, HW, CIN, COUT = 6, 8, 3, 4

    class OneConv(linen.Module):
        @linen.compact
        def __call__(self, x, train=True):
            return knn.Conv(COUT, (3, 3), strides=(1, 1), padding='SAME',
                            name='c')(x)

    rng = np.random.RandomState(21)
    x = jnp.asarray(rng.randn(N, HW, HW, CIN), jnp.float32)
    y = jnp.asarray(rng.randn(N, HW, HW, COUT), jnp.float32)
    model = OneConv()
    variables = capture.init(model, jax.random.PRNGKey(0), x)
    pre = kfac.KFAC(variant='ekfac', lr=0.1, damping=0.01,
                    fac_update_freq=1, kfac_update_freq=1,
                    factor_decay=1.0, num_devices=1,
                    bucket_fn=lambda d: d)
    pre.setup(capture.collect_layer_meta(model, variables, x))
    _, _, grads, acts, gs, _ = capture.value_and_grad_with_capture(
        model, lambda out: ((out - y) ** 2).mean(), variables, x)
    _, state = pre.step(pre.init(), grads, acts, gs)

    meta = pre.plan.metas[0]
    pg = pre.plan.pred_groups[0]
    qa = np.asarray(state.decomp['evecs'][str(pg.da)][int(pg.row_a[0])])
    qg = np.asarray(state.decomp['evecs'][str(pg.dg)][int(pg.row_g[0])])
    got = np.asarray(state.decomp['scales']['g0'][0])

    patches = np.asarray(ops.extract_patches(
        capture.layer_act(acts, meta), meta.kernel_size, meta.strides,
        meta.padding))
    spatial = patches.shape[1] * patches.shape[2]
    arows = patches.reshape(-1, patches.shape[-1])
    arows = np.concatenate(
        [arows, np.ones((arows.shape[0], 1), np.float32)], axis=1)
    arows = arows / spatial
    g_tilde = np.asarray(capture.layer_g(gs, meta))
    grows = g_tilde.reshape(-1, COUT) * N * spatial
    want = np.zeros((pg.dg, pg.da), np.float64)
    for r in range(arows.shape[0]):
        want += np.outer((qg.T @ grows[r]) ** 2, (qa.T @ arows[r]) ** 2)
    want /= N
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=1e-7)


def test_ekfac_diag_beats_kfac_eigenvalues_in_frobenius():
    """The paper's optimality theorem: s is the exact diagonal of
    Q^T F_emp Q, hence the best diagonal in that basis — the K-FAC
    eigenvalue outer product can only be worse (or equal)."""
    x, y = _data(seed=3)
    pre, model, variables = _make_pre('ekfac')
    _, _, grads, acts, gs, _ = _capture_batch(model, variables, x, y)
    _, state = pre.step(pre.init(), grads, acts, gs)

    meta = pre.plan.metas[0]
    pg = pre.plan.pred_groups[0]
    qa = np.asarray(state.decomp['evecs'][str(pg.da)][int(pg.row_a[0])])
    qg = np.asarray(state.decomp['evecs'][str(pg.dg)][int(pg.row_g[0])])
    da = np.asarray(state.decomp['evals'][str(pg.da)][int(pg.row_a[0])])
    dg = np.asarray(state.decomp['evals'][str(pg.dg)][int(pg.row_g[0])])
    s = np.asarray(state.decomp['scales']['g0'][0])

    a_rows = np.concatenate(
        [np.asarray(x), np.ones((B, 1), np.float32)], axis=1)
    g_tilde = np.asarray(capture.layer_g(gs, meta))
    dim = pg.dg * pg.da
    f_emp = np.zeros((dim, dim), np.float64)
    for b in range(B):
        v = np.kron(B * g_tilde[b], a_rows[b])
        f_emp += np.outer(v, v)
    f_emp /= B
    q_joint = np.kron(qg, qa)

    def frob(diag):
        approx = q_joint @ np.diag(diag) @ q_joint.T
        return np.linalg.norm(f_emp - approx)

    err_ekfac = frob(s.flatten())
    err_kfac = frob(np.outer(dg, da).flatten())
    assert err_ekfac <= err_kfac + 1e-8, (err_ekfac, err_kfac)
    # and on generic data the improvement is strict
    assert err_ekfac < 0.999 * err_kfac, (err_ekfac, err_kfac)


def test_ekfac_mpd_invariance():
    """nd=2 sharded run (factors AND scales pmean'd) == world-1 full
    batch — data sharding must not change the preconditioned update."""
    ND = 2
    x, y = _data(seed=5)
    pre1, model, variables = _make_pre('ekfac')
    _, _, grads, acts, gs, _ = _capture_batch(model, variables, x, y)
    want, _ = pre1.step(pre1.init(), grads, acts, gs)

    pre_n, _, _ = _make_pre('ekfac', num_devices=ND, axis_name='batch')
    mesh = Mesh(np.array(jax.devices()[:ND]), ('batch',))
    kstate = pre_n.init()
    kspecs = pre_n.state_pspecs('batch')

    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(), kspecs, P('batch'), P('batch')),
        out_specs=P())
    def sharded(params, kstate, x, y):
        _, _, grads, acts, gs, _ = capture.value_and_grad_with_capture(
            model, lambda out: _ce(out, y), {'params': params}, x,
            axis_name='batch')
        grads = kfac.parallel.average_grads(grads, 'batch')
        new_grads, _ = pre_n.step(kstate, grads, acts, gs,
                                  axis_name='batch')
        return new_grads

    got = sharded(variables['params'], kstate, x, y)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6),
        got, want)


def test_ekfac_zero_scales_fall_back_to_eigen():
    """All-zero scales (fresh start, or a restored checkpoint from a
    pre-ekfac run) must reproduce the plain eigen preconditioner
    exactly, per member."""
    x, y = _data(seed=7)
    pre_e, model, variables = _make_pre('eigen')
    _, _, grads, acts, gs, _ = _capture_batch(model, variables, x, y)
    want, state_e = pre_e.step(pre_e.init(), grads, acts, gs)

    pre_k, _, _ = _make_pre('ekfac')
    st = pre_k.init()
    st = st.replace(factors=state_e.factors,
                    decomp={**state_e.decomp,
                            'scales': st.decomp['scales']})
    # no factor/inverse update: precondition with the carried state and
    # its zero scales -> the Kronecker denominator must be used
    got, _ = pre_k.step(st, grads, update_factors=False,
                        update_inverse=False)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7),
        got, want)


def test_ekfac_accepts_pre_ekfac_checkpoint_state():
    """A state whose decomp has NO 'scales' key at all (restored from a
    run that predates the variant) must step without crashing — zeros
    are defaulted and the first factor update populates them."""
    x, y = _data(seed=15)
    pre_e, model, variables = _make_pre('eigen')
    _, _, grads, acts, gs, _ = _capture_batch(model, variables, x, y)
    _, state_e = pre_e.step(pre_e.init(), grads, acts, gs)

    pre_k, _, _ = _make_pre('ekfac')
    st = pre_k.init().replace(factors=state_e.factors,
                              decomp=state_e.decomp)  # no 'scales' key
    new_grads, new_state = pre_k.step(st, grads, acts, gs)
    assert all(np.isfinite(np.asarray(v)).all()
               for v in jax.tree.leaves(new_grads))
    assert 'scales' in new_state.decomp
    assert all(bool(jnp.any(v != 0))
               for v in new_state.decomp['scales'].values())


def test_ekfac_dp_world1_matches_ekfac():
    """With one device the owner-local ('ekfac_dp') and replicated
    ('ekfac') layouts see identical data and bases — the preconditioned
    gradients must agree."""
    x, y = _data(seed=17)
    pre_r, model, variables = _make_pre('ekfac')
    _, _, grads, acts, gs, _ = _capture_batch(model, variables, x, y)
    want, _ = pre_r.step(pre_r.init(), grads, acts, gs)

    pre_d, _, _ = _make_pre('ekfac_dp')
    got, state_d = pre_d.step(pre_d.init(), grads, acts, gs)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7),
        got, want)
    assert all(bool(jnp.any(v != 0))
               for v in state_d.decomp['scales'].values())


def test_ekfac_dp_uses_owner_local_scales():
    """nd=2: layer i's scales (and factors) must come from the OWNER's
    local shard only — host oracle recomputes the full E-KFAC pred from
    per-shard captures, mirroring
    tests/test_distributed.py::test_dp_uses_owner_local_stats."""
    from flax import linen as flinen

    ND = 2
    decay, damping = 1.0, 0.01

    class MLP2(flinen.Module):
        @flinen.compact
        def __call__(self, x, train=True):
            x = flinen.relu(knn.Dense(7, name='fc1')(x))
            return knn.Dense(DOUT, name='fc2')(x)

    x, y = _data(seed=19)
    model = MLP2()
    variables = capture.init(model, jax.random.PRNGKey(0), x)
    metas = capture.collect_layer_meta(model, variables, x)
    pre = kfac.KFAC(variant='ekfac_dp', lr=0.1, damping=damping,
                    fac_update_freq=1, kfac_update_freq=1,
                    factor_decay=decay, kl_clip=None,
                    num_devices=ND, axis_name='batch',
                    bucket_fn=lambda d: d)
    pre.setup(metas)

    mesh = Mesh(np.array(jax.devices()[:ND]), ('batch',))
    kspecs = pre.state_pspecs('batch')

    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(), kspecs, P('batch'), P('batch')),
        out_specs=P())
    def sharded(params, kstate, x, y):
        _, _, grads, acts, gs, _ = capture.value_and_grad_with_capture(
            model, lambda out: _ce(out, y), {'params': params}, x,
            axis_name='batch')
        grads = kfac.parallel.average_grads(grads, 'batch')
        new_grads, _ = pre.step(kstate, grads, acts, gs,
                                axis_name='batch')
        return new_grads

    got = sharded(variables['params'], pre.init(), x, y)

    # host oracle: per-shard captures; layer i owned round-robin
    h = len(x) // ND
    shard = []
    for d in range(ND):
        xs, ys = x[d * h:(d + 1) * h], y[d * h:(d + 1) * h]
        _, _, sg, sa, sgs, _ = capture.value_and_grad_with_capture(
            model, lambda out: _ce(out, ys), variables, xs)
        shard.append((sg, sa, sgs))
    grads_full = jax.tree.map(
        lambda *g: sum(np.asarray(v) for v in g) / ND,
        *[s[0] for s in shard])

    for i, (name, meta) in enumerate(metas.items()):
        owner = i % ND
        _, sa, sgs = shard[owner]
        a_loc = np.asarray(sa[name]['a'])
        g_loc = np.asarray(sgs[name]['g'])
        n_loc = a_loc.shape[0]
        arows = np.concatenate(
            [a_loc, np.ones((n_loc, 1), np.float32)], axis=1)
        ghat = g_loc * n_loc
        A = (arows.T @ arows) / n_loc
        G = (ghat.T @ ghat) / n_loc
        dA, QA = np.linalg.eigh(A)
        dG, QG = np.linalg.eigh(G)
        # owner-local E-KFAC moments from the owner's own rows
        pa, pg = arows @ QA, ghat @ QG
        s = (pg ** 2).T @ (pa ** 2) / n_loc
        gm = np.concatenate(
            [np.asarray(grads_full[name]['kernel']).T,
             np.asarray(grads_full[name]['bias'])[:, None]], axis=1)
        v2 = (QG.T @ gm @ QA) / (s + damping)
        want = QG @ v2 @ QA.T
        gk = np.concatenate([np.asarray(got[name]['kernel']).T,
                             np.asarray(got[name]['bias'])[:, None]], 1)
        np.testing.assert_allclose(gk, want, rtol=2e-3, atol=1e-4)


def test_ekfac_dp_accepts_pre_ekfac_checkpoint_state_sharded():
    """A pre-ekfac ('eigen_dp') state with no 'scales' key restored into
    'ekfac_dp' at world size > 1 must step inside shard_map without
    crashing OR silently broadcasting the wrong layout: the in-trace
    zero-scales default must use the LOCAL slot count."""
    ND = 2
    x, y = _data(seed=29)
    pre_e, model, variables = _make_pre('eigen_dp', num_devices=ND,
                                        axis_name='batch')
    pre_k, _, _ = _make_pre('ekfac_dp', num_devices=ND,
                            axis_name='batch')
    mesh = Mesh(np.array(jax.devices()[:ND]), ('batch',))
    kspecs_e = pre_e.state_pspecs('batch')
    kspecs_k = pre_k.state_pspecs('batch')

    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(), kspecs_e, P('batch'), P('batch')),
        out_specs=(P(), kspecs_e))
    def warm(params, kstate, x, y):
        _, _, grads, acts, gs, _ = capture.value_and_grad_with_capture(
            model, lambda out: _ce(out, y), {'params': params}, x,
            axis_name='batch')
        grads = kfac.parallel.average_grads(grads, 'batch')
        return pre_e.step(kstate, grads, acts, gs, axis_name='batch')

    _, state_e = warm(variables['params'], pre_e.init(), x, y)

    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(), kspecs_e, P('batch'), P('batch')),
        out_specs=(P(), kspecs_k))
    def resume(params, kstate, x, y):
        _, _, grads, acts, gs, _ = capture.value_and_grad_with_capture(
            model, lambda out: _ce(out, y), {'params': params}, x,
            axis_name='batch')
        grads = kfac.parallel.average_grads(grads, 'batch')
        return pre_k.step(kstate, grads, acts, gs, axis_name='batch')

    got, state_k = resume(variables['params'], state_e, x, y)
    assert all(np.isfinite(np.asarray(v)).all()
               for v in jax.tree.leaves(got))
    # the out-specs round-trip pins the sharded GLOBAL scale layout
    want_shapes = {k: v.shape
                   for k, v in pre_k.init().decomp['scales'].items()}
    got_shapes = {k: v.shape for k, v in state_k.decomp['scales'].items()}
    assert got_shapes == want_shapes, (got_shapes, want_shapes)


def test_ekfac_dp_trains_and_composes():
    """ekfac_dp through build_train_step on the 4-device mesh with the
    amortized basis: loss decreases, scales populate."""
    from flax import linen as flinen

    class MLP3(flinen.Module):
        @flinen.compact
        def __call__(self, x, train=True):
            x = flinen.relu(knn.Dense(12, name='fc1')(x))
            return knn.Dense(DOUT, name='head')(x)

    ND = 4
    x, y = _data(seed=23)
    model = MLP3()
    pre = kfac.KFAC(variant='ekfac_dp', lr=0.1, damping=0.01,
                    fac_update_freq=1, kfac_update_freq=1,
                    basis_update_freq=4, num_devices=ND,
                    axis_name='batch')
    tx = training.sgd(0.1, momentum=0.9)
    state = training.init_train_state(model, tx, pre,
                                      jax.random.PRNGKey(0), x)
    mesh = Mesh(np.array(jax.devices()[:ND]), ('batch',))
    step = training.build_train_step(
        model, tx, pre, lambda o, b: _ce(o, b['label']),
        axis_name='batch', mesh=mesh, donate=False)
    losses = []
    for _ in range(10):
        state, m = step(state, {'input': x, 'label': y},
                        lr=0.1, damping=0.01)
        losses.append(float(m['loss']))
    assert losses[-1] < losses[0], losses
    assert all(bool(jnp.any(v != 0))
               for v in state.kfac_state.decomp['scales'].values())


def test_ekfac_rotation_exact_under_sign_flips():
    """Basis transport sanity: flipping eigenvector signs (the eigh
    gauge freedom) must leave the transported scales unchanged."""
    x, y = _data(seed=9)
    pre, model, variables = _make_pre('ekfac')
    _, _, grads, acts, gs, _ = _capture_batch(model, variables, x, y)
    _, state = pre.step(pre.init(), grads, acts, gs)
    decomp = state.decomp
    flip = jax.tree.map(lambda q: -q, decomp['evecs'])
    flipped = {'evals': decomp['evals'], 'evecs': flip}
    out = engine.rotate_ekfac_scales(pre.plan, decomp['scales'],
                                     decomp, flipped)
    np.testing.assert_allclose(np.asarray(out['g0']),
                               np.asarray(decomp['scales']['g0']),
                               rtol=1e-5, atol=1e-7)


def test_ekfac_trains():
    """Two-layer model, a few steps through build_train_step: loss
    decreases and the scales populate."""
    class MLP(linen.Module):
        @linen.compact
        def __call__(self, x, train=True):
            x = linen.relu(knn.Dense(12, name='fc1')(x))
            return knn.Dense(DOUT, name='head')(x)

    x, y = _data(seed=11)
    model = MLP()
    pre = kfac.KFAC(variant='ekfac', lr=0.1, damping=0.01,
                    fac_update_freq=1, kfac_update_freq=1, num_devices=1)
    tx = training.sgd(0.1, momentum=0.9)
    state = training.init_train_state(model, tx, pre,
                                      jax.random.PRNGKey(0), x)
    step = training.build_train_step(
        model, tx, pre, lambda o, b: _ce(o, b['label']))
    losses = []
    for _ in range(5):
        state, m = step(state, {'input': x, 'label': y},
                        lr=0.1, damping=0.01)
        losses.append(float(m['loss']))
    assert losses[-1] < losses[0], losses
    assert all(bool(jnp.any(v != 0))
               for v in state.kfac_state.decomp['scales'].values())


def test_ekfac_composes_with_amortized_basis():
    """The amortization combo this variant exists for: full eigh every
    basis_update_freq inverse updates, eigenvalue-refresh between — with
    the E-KFAC moments still updating EVERY factor step, the stale-basis
    steps carry per-example-corrected scales instead of merely re-fitted
    Kronecker eigenvalues. One trains-and-populates check through the
    trainer gating."""
    class MLP(linen.Module):
        @linen.compact
        def __call__(self, x, train=True):
            x = linen.relu(knn.Dense(12, name='fc1')(x))
            return knn.Dense(DOUT, name='head')(x)

    x, y = _data(seed=13)
    model = MLP()
    pre = kfac.KFAC(variant='ekfac', lr=0.1, damping=0.01,
                    fac_update_freq=1, kfac_update_freq=1,
                    basis_update_freq=4, num_devices=1)
    tx = training.sgd(0.1, momentum=0.9)
    state = training.init_train_state(model, tx, pre,
                                      jax.random.PRNGKey(0), x)
    step = training.build_train_step(
        model, tx, pre, lambda o, b: _ce(o, b['label']))
    losses = []
    for _ in range(10):   # spans two full decomps + refresh steps
        state, m = step(state, {'input': x, 'label': y},
                        lr=0.1, damping=0.01)
        losses.append(float(m['loss']))
    assert losses[-1] < losses[0], losses
    assert all(bool(jnp.any(v != 0))
               for v in state.kfac_state.decomp['scales'].values())
