"""kfac-lint (kfac_pytorch_tpu/analysis/): the six project-invariant
rules, the framework mechanics (suppressions, the baseline ratchet),
the central env registry's cross-checks, and the self-clean gate.

Per ISSUE 15, every rule gets a FIXTURE pair — one synthetic snippet it
must catch, one clean snippet it must pass — so a rule that silently
stops firing (the classic linter failure mode) breaks here, not in
review. The fixtures build a minimal fake repo in tmp_path, including
tiny stand-ins for the statically-read registries (envspec.ENV,
incident._PATTERNS, autotune.KNOB_ATTRS), which doubles as a test of
the no-import static readers.

No jax needed anywhere in this file — by design (the CI lint job runs
on a bare Python; so does the analysis package).
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from kfac_pytorch_tpu.analysis import run_lint
from kfac_pytorch_tpu.analysis.core import load_baseline
from kfac_pytorch_tpu.analysis.rules import ALL_RULES, RULE_IDS

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# fixture repo builder
# ---------------------------------------------------------------------------

#: stand-in registries the rules read statically out of the fake repo
_FAKE_AUTOTUNE = "KNOB_ATTRS = ('kfac_update_freq', 'damping')\n"
_FAKE_ENVSPEC = textwrap.dedent('''\
    def E(name, kind, consumer, doc, choices=(), default=None):
        return name
    ENV = (
        E('KFAC_DECLARED', 'flag', 'x.py', 'a declared knob'),
    )
''')
_FAKE_INCIDENT = textwrap.dedent('''\
    import re
    _PATTERNS = (
        ('shrink', re.compile(
            r'elastic: shrinking world (?P<f>\\d+) -> (?P<t>\\d+) '
            r'survivors=(?P<s>\\[[^\\]]*\\]) gen=(?P<g>\\d+)')),
    )
    EVENT_PATTERNS = _PATTERNS
''')


def make_repo(tmp_path, files):
    """A minimal fake repo: pyproject.toml, the three registry
    stand-ins, plus ``files`` ({relpath: source})."""
    (tmp_path / 'pyproject.toml').write_text('[project]\nname="x"\n')
    base = {
        'kfac_pytorch_tpu/__init__.py': '',
        'kfac_pytorch_tpu/autotune.py': _FAKE_AUTOTUNE,
        'kfac_pytorch_tpu/envspec.py': _FAKE_ENVSPEC,
        'kfac_pytorch_tpu/resilience/__init__.py': '',
        'kfac_pytorch_tpu/resilience/incident.py': _FAKE_INCIDENT,
    }
    base.update(files)
    for rel, src in base.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return tmp_path


def findings(tmp_path, files, rule):
    root = make_repo(tmp_path, files)
    res = run_lint(str(root), ALL_RULES, rule_ids=[rule])
    return res.findings


# ---------------------------------------------------------------------------
# rule: knob-writer
# ---------------------------------------------------------------------------

def test_knob_writer_catches_direct_assignment(tmp_path):
    out = findings(tmp_path, {'kfac_pytorch_tpu/rogue.py': '''
        def tune(precond):
            precond.kfac_update_freq = 100     # racing writer (PR 9)
    '''}, 'knob-writer')
    assert len(out) == 1 and 'kfac_update_freq' in out[0].message


def test_knob_writer_catches_setattr_with_literal(tmp_path):
    out = findings(tmp_path, {'kfac_pytorch_tpu/rogue.py': '''
        def tune(precond):
            setattr(precond, 'damping', 1e-3)
    '''}, 'knob-writer')
    assert len(out) == 1 and 'damping' in out[0].message


def test_knob_writer_allows_init_and_arbiter(tmp_path):
    out = findings(tmp_path, {
        'kfac_pytorch_tpu/clean.py': '''
            class KFAC:
                def __init__(self, kfac_update_freq=100):
                    self.kfac_update_freq = kfac_update_freq
                    self.damping = 3e-3
        ''',
        # the arbiter module itself is exempt wholesale
        'kfac_pytorch_tpu/autotune.py': (
            _FAKE_AUTOTUNE
            + 'def _apply(precond):\n'
              '    precond.damping = 1e-3\n'),
    }, 'knob-writer')
    assert out == []


# ---------------------------------------------------------------------------
# rule: coord-bypass
# ---------------------------------------------------------------------------

def test_coord_bypass_catches_direct_io_in_protocol_module(tmp_path):
    out = findings(tmp_path, {
        'kfac_pytorch_tpu/resilience/heartbeat.py': '''
            import os
            def publish(path, payload):
                with open(path, 'w') as f:    # bypassing the backend
                    f.write(payload)
                os.replace(path, path + '.final')
        '''}, 'coord-bypass')
    assert len(out) == 2
    assert any('open' in f.message for f in out)
    assert any('os.replace' in f.message for f in out)


def test_coord_bypass_honors_artifact_allowlist(tmp_path):
    # elastic.run is an allowlisted ARTIFACT path; queue.py has no
    # allowance at all but backend-routed code has nothing to flag
    out = findings(tmp_path, {
        'kfac_pytorch_tpu/resilience/elastic.py': '''
            def run(log_path):
                with open(log_path, 'w') as f:
                    f.write('per-host run log — a named artifact')
        ''',
        'kfac_pytorch_tpu/service/queue.py': '''
            def enqueue(backend, key, doc):
                return backend.put_cas(key, doc, expect_version=None)
        '''}, 'coord-bypass')
    assert out == []


def test_coord_bypass_matches_runtime_test_on_real_repo():
    """The migrated tests/test_coord.py gate and the CLI rule are the
    same check: clean on the shipped tree."""
    res = run_lint(REPO, ALL_RULES, rule_ids=['coord-bypass'])
    assert res.findings == []


# ---------------------------------------------------------------------------
# rule: env-contract
# ---------------------------------------------------------------------------

def test_env_contract_catches_undeclared_name(tmp_path):
    out = findings(tmp_path, {'kfac_pytorch_tpu/knobs.py': '''
        import os
        def read():
            return os.environ.get('KFAC_UNDECLARED_KNOB')
    '''}, 'env-contract')
    assert len(out) == 1 and 'KFAC_UNDECLARED_KNOB' in out[0].message


def test_env_contract_catches_undeclared_constant_definition(tmp_path):
    # the ENV_FOO = 'KFAC_...' idiom is covered at the definition site,
    # so reads routed through constants (or dict params) can't hide
    out = findings(tmp_path, {'kfac_pytorch_tpu/knobs.py': '''
        ENV_TYPO = 'KFAC_COMM_PRECISON'
    '''}, 'env-contract')
    assert len(out) == 1 and 'KFAC_COMM_PRECISON' in out[0].message


def test_env_contract_catches_dynamic_env_name(tmp_path):
    out = findings(tmp_path, {'kfac_pytorch_tpu/knobs.py': '''
        import os
        def read(i):
            return os.environ.get(f'KFAC_KNOB_{i}')
    '''}, 'env-contract')
    assert len(out) == 1 and 'dynamic' in out[0].message


def test_env_contract_passes_declared_and_nonenv(tmp_path):
    out = findings(tmp_path, {'kfac_pytorch_tpu/knobs.py': '''
        import os
        __all__ = ['KFAC_LOOKS_LIKE_ENV_BUT_IS_A_SYMBOL']
        def read():
            """Docstrings may mention KFAC_ANYTHING freely."""
            home = os.environ.get('HOME')          # not our namespace
            flag = os.environ.get('KFAC_DECLARED')  # declared stand-in
            scan = [k for k in os.environ if k.startswith('KFAC_')]
            return home, flag, scan
    '''}, 'env-contract')
    assert out == []


# ---------------------------------------------------------------------------
# rule: event-grammar
# ---------------------------------------------------------------------------

def test_event_grammar_catches_drifted_form(tmp_path):
    # same head as the 'shrink' pattern, reworded tail: classic drift
    out = findings(tmp_path, {'kfac_pytorch_tpu/resilience/el.py': '''
        def emit(log, a, b, s, g):
            log.info('elastic: shrinking world %d => %d now=%s g=%d',
                     a, b, s, g)
    '''}, 'event-grammar')
    assert len(out) == 1 and 'shrink' in out[0].message


def test_event_grammar_passes_conforming_and_unrelated(tmp_path):
    out = findings(tmp_path, {'kfac_pytorch_tpu/resilience/el.py': '''
        def emit(log, a, b, s, g, suffix):
            # conforming emit (optional %s suffix is legal)
            log.info('elastic: shrinking world %d -> %d survivors=%s '
                     'gen=%d%s', a, b, s, g, suffix)
            # narration that claims no grammar head
            log.info('elastic setup: lease dir ready')
    '''}, 'event-grammar')
    assert out == []


# ---------------------------------------------------------------------------
# rule: atomic-write
# ---------------------------------------------------------------------------

def test_atomic_write_catches_bare_dump(tmp_path):
    out = findings(tmp_path, {'kfac_pytorch_tpu/writer.py': '''
        import json
        def save(path, doc):
            with open(path, 'w') as f:
                json.dump(doc, f)
    '''}, 'atomic-write')
    assert len(out) == 1 and 'torn' in out[0].message


def test_atomic_write_catches_dumps_write(tmp_path):
    out = findings(tmp_path, {'kfac_pytorch_tpu/writer.py': '''
        import json
        def save(path, doc):
            f = open(path, 'w')
            f.write(json.dumps(doc))
            f.close()
    '''}, 'atomic-write')
    assert len(out) == 1


def test_atomic_write_passes_helper_and_read_mode(tmp_path):
    out = findings(tmp_path, {
        # the implementation module is exempt (it IS the discipline)
        'kfac_pytorch_tpu/resilience/__init__.py': '''
            import json, os
            def atomic_write_json(path, obj, **kw):
                tmp = f'{path}.tmp-{os.getpid()}'
                with open(tmp, 'w') as f:
                    json.dump(obj, f, **kw)
                os.replace(tmp, path)
        ''',
        'kfac_pytorch_tpu/writer.py': '''
            import json
            from kfac_pytorch_tpu.resilience import atomic_write_json
            def save(path, doc):
                atomic_write_json(path, doc)
            def load(path):
                with open(path) as f:
                    return json.load(f)
        '''}, 'atomic-write')
    assert out == []


# ---------------------------------------------------------------------------
# rule: trace-purity
# ---------------------------------------------------------------------------

def test_trace_purity_catches_impure_traced_callee(tmp_path):
    # engine.py is traced by charter; the impurity hides one call hop
    # away, so this also pins the propagation
    out = findings(tmp_path, {'kfac_pytorch_tpu/engine.py': '''
        import time
        def _stamp():
            return time.time()
        def update_factors(factors):
            return factors, _stamp()
    '''}, 'trace-purity')
    assert len(out) == 1 and 'time.time' in out[0].message


def test_trace_purity_catches_jit_wrapped_local(tmp_path):
    out = findings(tmp_path, {'kfac_pytorch_tpu/training.py': '''
        import functools
        import jax
        def build(step_args):
            def one_step(state, batch):
                print('step!')
                return state
            fn = functools.partial(one_step, extra=step_args)
            return jax.jit(fn)
    '''}, 'trace-purity')
    assert len(out) == 1 and 'print' in out[0].message


def test_trace_purity_passes_hostside_impurity(tmp_path):
    out = findings(tmp_path, {'kfac_pytorch_tpu/training.py': '''
        import time
        import jax
        def build():
            def one_step(state):
                return state
            return jax.jit(one_step)
        def host_loop(step_fn):
            t0 = time.time()          # host side: fine
            print('launching')        # host side: fine
            return step_fn, t0
    '''}, 'trace-purity')
    assert out == []


# ---------------------------------------------------------------------------
# framework mechanics: suppressions + the baseline ratchet
# ---------------------------------------------------------------------------

def test_suppression_comment_waives_one_site(tmp_path):
    out = findings(tmp_path, {'kfac_pytorch_tpu/writer.py': '''
        import json
        def save(path, doc):
            with open(path, 'w') as f:
                # kfac-lint: disable=atomic-write -- single-writer CLI artifact
                json.dump(doc, f)
    '''}, 'atomic-write')
    assert out == []


def test_suppression_is_rule_scoped(tmp_path):
    # suppressing a DIFFERENT rule does not waive this one
    out = findings(tmp_path, {'kfac_pytorch_tpu/writer.py': '''
        import json
        def save(path, doc):
            with open(path, 'w') as f:
                json.dump(doc, f)  # kfac-lint: disable=env-contract
    '''}, 'atomic-write')
    assert len(out) == 1


def test_baseline_pins_and_ratchets(tmp_path):
    root = make_repo(tmp_path, {'kfac_pytorch_tpu/writer.py': (
        'import json\n'
        'def save(path, doc):\n'
        "    with open(path, 'w') as f:\n"
        '        json.dump(doc, f)\n')})
    res = run_lint(str(root), ALL_RULES, rule_ids=['atomic-write'])
    assert len(res.findings) == 1
    key = ('atomic-write:kfac_pytorch_tpu/writer.py:'
           'json.dump(doc, f)')
    # justified baseline entry: finding moves to baselined, run passes
    ok = run_lint(str(root), ALL_RULES, rule_ids=['atomic-write'],
                  baseline={key: 'pre-ISSUE-15 site, tracked burn-down'})
    assert ok.findings == [] and len(ok.baselined) == 1 \
        and not ok.failed
    # an EMPTY/TODO justification does not count
    bad = run_lint(str(root), ALL_RULES, rule_ids=['atomic-write'],
                   baseline={key: 'TODO: justify or fix'})
    assert len(bad.findings) == 1 and bad.failed
    # stale entries fail too — the ratchet only burns down
    stale = run_lint(str(root), ALL_RULES, rule_ids=['atomic-write'],
                     baseline={key: 'justified',
                               'atomic-write:gone.py:x': 'fixed ages ago'})
    assert stale.failed and stale.stale_baseline == [
        'atomic-write:gone.py:x']


def test_cli_write_baseline_roundtrip(tmp_path):
    """--write-baseline accepts current findings but stamps TODO
    justifications that still fail the gate until a human writes why."""
    from kfac_pytorch_tpu.analysis import cli
    root = make_repo(tmp_path, {'kfac_pytorch_tpu/writer.py': '''
        import json
        def save(path, doc):
            with open(path, 'w') as f:
                json.dump(doc, f)
    '''})
    bl = tmp_path / 'baseline.json'
    assert cli.main(['--root', str(root), '--baseline', str(bl),
                     '--write-baseline']) == 0
    entries = json.load(open(bl))['entries']
    assert len(entries) == 1
    # TODO placeholder: the gate still fails
    assert cli.main(['--root', str(root), '--baseline', str(bl)]) == 1
    # a written justification passes it
    key = next(iter(entries))
    bl.write_text(json.dumps({'entries': {key: 'pre-lint site'}}))
    assert cli.main(['--root', str(root), '--baseline', str(bl)]) == 0


def test_knob_writer_ignores_reads_in_subscript_targets(tmp_path):
    # `table[cfg.damping] = 1` READS the knob as a key — not a write
    out = findings(tmp_path, {'kfac_pytorch_tpu/lookup.py': '''
        def index(table, cfg):
            table[cfg.damping] = 1
    '''}, 'knob-writer')
    assert out == []


def test_atomic_write_scoping_is_per_function(tmp_path):
    # a caller-supplied stream named like another function's write
    # handle must not be implicated
    out = findings(tmp_path, {'kfac_pytorch_tpu/streams.py': '''
        import json
        def writer(p):
            with open(p, 'w') as f:
                f.write('plain text log')
        def sender(f, obj):
            json.dump(obj, f)     # f is a socket/stream parameter
    '''}, 'atomic-write')
    assert out == []


def test_stale_detection_scoped_to_active_rules(tmp_path):
    # a --rule-filtered run must not condemn entries of rules that
    # never ran this invocation
    root = make_repo(tmp_path, {})
    res = run_lint(str(root), ALL_RULES, rule_ids=['coord-bypass'],
                   baseline={'knob-writer:somewhere.py:x = 1': 'justified'})
    assert res.stale_baseline == [] and not res.failed
    # ...but a full run does judge (and fail) it
    res = run_lint(str(root), ALL_RULES,
                   baseline={'knob-writer:somewhere.py:x = 1': 'justified'})
    assert res.stale_baseline == ['knob-writer:somewhere.py:x = 1']


def test_todo_justification_is_not_also_reported_stale(tmp_path):
    # an unjustified entry gets ONE actionable verdict (write the
    # justification), never the contradictory 'fixed? delete it'
    root = make_repo(tmp_path, {'kfac_pytorch_tpu/writer.py': '''
        import json
        def save(path, doc):
            with open(path, 'w') as f:
                json.dump(doc, f)
    '''})
    key = ('atomic-write:kfac_pytorch_tpu/writer.py:'
           'json.dump(doc, f)')
    res = run_lint(str(root), ALL_RULES, rule_ids=['atomic-write'],
                   baseline={key: 'TODO'})
    assert len(res.findings) == 1 and 'justification' in \
        res.findings[0].message
    assert res.stale_baseline == []


def test_cli_write_baseline_preserves_other_rules_entries(tmp_path):
    # --rule X --write-baseline must not clobber rule Y's justified
    # entries (they were not re-checked this invocation)
    from kfac_pytorch_tpu.analysis import cli
    root = make_repo(tmp_path, {'kfac_pytorch_tpu/writer.py': '''
        import json
        def save(path, doc):
            with open(path, 'w') as f:
                json.dump(doc, f)
    '''})
    bl = tmp_path / 'baseline.json'
    keep = {'env-contract:kfac_pytorch_tpu/other.py:x': 'justified why'}
    bl.write_text(json.dumps({'entries': keep}))
    assert cli.main(['--root', str(root), '--baseline', str(bl),
                     '--rule', 'atomic-write', '--write-baseline']) == 0
    entries = json.load(open(bl))['entries']
    assert entries['env-contract:kfac_pytorch_tpu/other.py:x'] \
        == 'justified why'
    assert any(k.startswith('atomic-write:') for k in entries)


def test_unknown_rule_id_is_an_error(tmp_path):
    root = make_repo(tmp_path, {})
    with pytest.raises(KeyError):
        run_lint(str(root), ALL_RULES, rule_ids=['no-such-rule'])


# ---------------------------------------------------------------------------
# the self-clean gate + the no-jax CLI entry
# ---------------------------------------------------------------------------

def test_repo_is_lint_clean_outside_the_baseline():
    """THE acceptance gate: kfac-lint over the shipped tree reports
    nothing beyond lint-baseline.json (which is empty — every violation
    ISSUE 15's rules found was fixed, and new ones must be too)."""
    baseline = load_baseline(os.path.join(REPO, 'lint-baseline.json'))
    res = run_lint(REPO, ALL_RULES, baseline=baseline)
    assert set(res.rules_run) == set(RULE_IDS)
    assert res.findings == [], '\n'.join(f.render() for f in res.findings)
    assert res.stale_baseline == []


def test_cli_runs_without_jax_import():
    """The CI lint job's exact invocation: the cli file run as a bare
    script, with jax/flax imports BLOCKED — the bootstrap must keep the
    package root (which imports jax) out of the import chain."""
    blocker = (
        "import runpy, sys\n"
        "class B:\n"
        "    def find_module(self, name, path=None):\n"
        "        if name.split('.')[0] in ('jax', 'jaxlib', 'flax',\n"
        "                                  'optax', 'numpy'):\n"
        "            return self\n"
        "    def load_module(self, name):\n"
        "        raise ImportError('blocked heavy import: ' + name)\n"
        "sys.meta_path.insert(0, B())\n"
        "sys.argv = ['kfac-lint', '--json']\n"
        "runpy.run_path(%r, run_name='__main__')\n"
    ) % os.path.join(REPO, 'kfac_pytorch_tpu', 'analysis', 'cli.py')
    out = subprocess.run([sys.executable, '-c', blocker], cwd=REPO,
                         capture_output=True, text=True, timeout=120)
    # cli.py ends in sys.exit(main()) -> rc 0 and JSON on stdout
    assert out.returncode == 0, (out.stdout[-2000:], out.stderr[-2000:])
    doc = json.loads(out.stdout)
    assert doc['failed'] is False and doc['findings'] == []


# ---------------------------------------------------------------------------
# envspec: the registry's cross-checks
# ---------------------------------------------------------------------------

def test_envspec_validate_flags_typo_and_malformed():
    from kfac_pytorch_tpu import envspec
    probs = envspec.validate_environ({'KFAC_COMM_PRECISON': 'bf16'})
    assert len(probs) == 1 and 'not declared' in probs[0]
    probs = envspec.validate_environ({'KFAC_COMM_PRECISION': 'fp16'})
    assert len(probs) == 1 and 'must be one of' in probs[0]
    probs = envspec.validate_environ({'KFAC_FAULT_NAN_GRAD_STEP': '3,x'})
    assert len(probs) == 1 and 'malformed step spec' in probs[0]
    assert envspec.validate_environ(
        {'KFAC_COMM_PRECISION': 'bf16', 'KFAC_FAULT_NAN_GRAD_STEP': '4:8',
         'PATH': '/bin'}) == []


def test_envspec_backs_faults_strict_registry():
    """Satellite: faults.from_env STRICT validation derives from the
    central registry (the import-time cross-pin in faults.py), so the
    two can never drift."""
    pytest.importorskip('jax')
    from kfac_pytorch_tpu import envspec, faults
    assert faults.KNOWN_ENVS == envspec.declared('KFAC_FAULT_')
    assert faults.KNOWN_ENVS <= envspec.DECLARED


def test_envspec_readme_table_in_sync():
    """The README env table is generated from the registry; a knob
    declared (or re-documented) without regenerating it fails here:
    python kfac_pytorch_tpu/envspec.py --table."""
    from kfac_pytorch_tpu import envspec
    readme = open(os.path.join(REPO, 'README.md'), encoding='utf-8').read()
    begin, end = '<!-- envspec:begin -->', '<!-- envspec:end -->'
    assert begin in readme and end in readme, \
        'README is missing the envspec table markers'
    block = readme.split(begin, 1)[1].split(end, 1)[0].strip()
    assert block == envspec.markdown_table().strip()


def test_launch_tpu_sh_validates_through_envspec(tmp_path):
    """Satellite: a typo'd KFAC_* export kills the launch via the
    registry gate (not a silent no-op on an allocated pod)."""
    dump = tmp_path / 'noop.py'
    dump.write_text('print("RAN")\n')
    env = {k: v for k, v in os.environ.items()
           if not k.startswith('KFAC_')}
    bad = subprocess.run(
        ['bash', os.path.join(REPO, 'launch_tpu.sh'), str(dump)],
        env={**env, 'KFAC_COMM_PRECISON': 'bf16'},
        capture_output=True, text=True, timeout=60)
    assert bad.returncode == 1
    assert 'not declared' in bad.stderr
    assert 'RAN' not in bad.stdout
