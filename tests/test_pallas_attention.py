"""Pallas flash-attention block kernel tests (interpret mode on the CPU
mesh): the fused kernel must produce bitwise-compatible online-softmax
pieces and exact gradients vs the plain-XLA block implementation, both
standalone and composed into ring attention."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import importlib

from kfac_pytorch_tpu.ops.pallas_attention import flash_block_attn

# the package re-exports the function under the submodule's name, so the
# module object must come from importlib
ring_mod = importlib.import_module(
    'kfac_pytorch_tpu.parallel.ring_attention')

BH, LQ, LK, D = 4, 32, 32, 16
SCALE = D ** -0.5


def _inputs(seed=0):
    rng = np.random.RandomState(seed)
    q = jnp.asarray(rng.randn(BH, LQ, D), jnp.float32)
    k = jnp.asarray(rng.randn(BH, LK, D), jnp.float32)
    v = jnp.asarray(rng.randn(BH, LK, D), jnp.float32)
    mask = jnp.asarray(rng.rand(BH, LK) > 0.2, jnp.float32)
    return q, k, v, mask


def _reference(q, k, v, mask, q_start, k_start, causal):
    # additive bias, matching the framework's convention everywhere
    # (degenerate fully-masked rows keep their s-dependence)
    s = jnp.einsum('bqd,bkd->bqk', q, k) * SCALE
    if causal:
        qpos = q_start + jnp.arange(LQ)[:, None]
        kpos = k_start + jnp.arange(LK)[None, :]
        s = s + jnp.where(qpos >= kpos, 0.0, -1e30)
    s = s + jnp.where(mask[:, None, :] > 0.5, 0.0, -1e30)
    m = jnp.max(s, axis=-1)
    p = jnp.exp(s - m[..., None])
    return m, p.sum(-1), jnp.einsum('bqk,bkd->bqd', p, v)


@pytest.mark.parametrize('causal', [False, True])
@pytest.mark.parametrize('starts', [(0, 0), (64, 32)])
def test_kernel_matches_reference(causal, starts):
    q, k, v, mask = _inputs()
    m, l, pv = flash_block_attn(q, k, v, mask,
                                jnp.asarray(starts, jnp.int32), SCALE,
                                causal, True)
    rm, rl, rpv = _reference(q, k, v, mask, *starts, causal)
    np.testing.assert_allclose(np.asarray(m), np.asarray(rm), atol=1e-5)
    np.testing.assert_allclose(np.asarray(l), np.asarray(rl),
                               atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(pv), np.asarray(rpv),
                               atol=1e-4, rtol=1e-4)


@pytest.mark.parametrize('tq,tk', [(8, 16), (16, 8), (32, 32)])
def test_kernel_tile_override_exact(monkeypatch, tq, tk):
    """KFAC_FLASH_TQ/TK (the on-chip tile-sweep knobs) change only the
    schedule, never the math: every tile shape must reproduce the
    reference exactly, including causal with non-zero global starts."""
    monkeypatch.setenv('KFAC_FLASH_TQ', str(tq))
    monkeypatch.setenv('KFAC_FLASH_TK', str(tk))
    q, k, v, mask = _inputs(seed=2)
    m, l, pv = flash_block_attn(q, k, v, mask,
                                jnp.asarray((64, 32), jnp.int32), SCALE,
                                True, True)
    rm, rl, rpv = _reference(q, k, v, mask, 64, 32, True)
    np.testing.assert_allclose(np.asarray(m), np.asarray(rm), atol=1e-5)
    np.testing.assert_allclose(np.asarray(l), np.asarray(rl),
                               atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(pv), np.asarray(rpv),
                               atol=1e-4, rtol=1e-4)
    # a non-dividing request falls back to a dividing power-of-two tile
    from kfac_pytorch_tpu.ops.pallas_attention import _fwd_tile
    monkeypatch.setenv('KFAC_FLASH_TK', '480')
    assert _fwd_tile('KFAC_FLASH_TK', 128, 640) == 128  # 480→256→128|640
    monkeypatch.setenv('KFAC_FLASH_TK', '512')
    assert _fwd_tile('KFAC_FLASH_TK', 128, 8192) == 512
    monkeypatch.setenv('KFAC_FLASH_TK', '512')
    assert _fwd_tile('KFAC_FLASH_TK', 128, 384) == 128  # clamp→pow2→divide
    monkeypatch.delenv('KFAC_FLASH_TK')
    assert _fwd_tile('KFAC_FLASH_TK', 128, 24) == 8


def test_kernel_gradients_match_xla_blocks():
    q, k, v, mask = _inputs(seed=1)
    q4 = q[:, None]  # [BH, 1(head), L, D] for the dispatch layout
    k4, v4 = k[:, None], v[:, None]

    def loss(impl, q4, k4, v4):
        out = ring_mod.ring_attention(
            q4, k4, v4, axis_name=None, causal=True,
            kv_mask=mask > 0.5, block_impl=impl)
        return (out.astype(jnp.float32) ** 2).sum()

    g_pallas = jax.grad(functools.partial(loss, 'pallas_interpret'),
                        argnums=(0, 1, 2))(q4, k4, v4)
    g_xla = jax.grad(functools.partial(loss, 'xla'),
                     argnums=(0, 1, 2))(q4, k4, v4)
    for a, b in zip(g_pallas, g_xla):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, rtol=5e-4)


def test_non_tile_multiple_length_values_and_grads():
    """L=160 (>128, not a multiple of 128): the dispatch must pad to the
    tile grid — regression for silent tail truncation."""
    rng = np.random.RandomState(3)
    B, H, L = 1, 2, 160
    mk = lambda: jnp.asarray(rng.randn(B, H, L, D), jnp.float32)
    q, k, v = mk(), mk(), mk()

    def loss(impl, q, k, v):
        out = ring_mod.ring_attention(q, k, v, axis_name=None, causal=True,
                                      block_impl=impl)
        return (out ** 2).sum(), out

    (lp, out_p), gp = jax.value_and_grad(
        functools.partial(loss, 'pallas_interpret'), argnums=(0, 1, 2),
        has_aux=True)(q, k, v)
    (lx, out_x), gx = jax.value_and_grad(
        functools.partial(loss, 'xla'), argnums=(0, 1, 2),
        has_aux=True)(q, k, v)
    np.testing.assert_allclose(np.asarray(out_p), np.asarray(out_x),
                               atol=2e-5, rtol=2e-5)
    for a, b in zip(gp, gx):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, rtol=5e-4)


def test_ring_gradients_finite_with_fully_future_blocks():
    """Causal ring steps where the K/V block lies entirely in this
    device's future leave the kernel's online-softmax m at its -1e30 init
    (every tile causally skipped — a contract the XLA block path does not
    share). Gradients through the combine must stay finite and equal to
    the XLA path's even with large-magnitude scores pressing on the
    recompute backward's exp."""
    devs = jax.devices()[:4]
    mesh = Mesh(np.array(devs), ('seq',))
    rng = np.random.RandomState(4)
    B, H, L = 1, 2, 64
    # scale 10x: raw scores reach O(100), past exp overflow at ~88
    mk = lambda: jnp.asarray(10.0 * rng.randn(B, H, L, D), jnp.float32)
    q, k, v = mk(), mk(), mk()
    spec = P(None, None, 'seq', None)

    def loss(impl, q, k, v):
        out = jax.shard_map(
            functools.partial(ring_mod.ring_attention, axis_name='seq',
                              causal=True, block_impl=impl),
            mesh=mesh, in_specs=(spec,) * 3, out_specs=spec,
            check_vma=False)(q, k, v)
        return (out.astype(jnp.float32) ** 2).sum()

    gp = jax.grad(functools.partial(loss, 'pallas_interpret'),
                  argnums=(0, 1, 2))(q, k, v)
    gx = jax.grad(functools.partial(loss, 'xla'),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gp, gx):
        assert np.isfinite(np.asarray(a)).all()
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-3, rtol=1e-3)


def test_diag_tile_clamps_identity_on_needed_iterations():
    """The causal copy-elision clamps only run on real TPU (the
    interpreter can't evaluate vma-tagged meta), so pin their math here:
    for every grid iteration whose tile the kernel actually computes
    (last_q >= first_k), the clamped K-tile index must equal j and the
    clamped q-tile index must equal iq — a wrong clamp would feed the
    kernel the wrong tile with no test to catch it."""
    from kfac_pytorch_tpu.ops.pallas_attention import (_diag_k_tile,
                                                       _diag_q_tile)
    for q_start, k_start, tq, tk, nq, nk in [
            (0, 0, 8, 8, 4, 4), (0, 0, 128, 128, 3, 3),
            (64, 32, 16, 8, 5, 7), (256, 0, 128, 128, 2, 4),
            (0, 256, 8, 16, 6, 3), (96, 96, 32, 32, 4, 4)]:
        meta = jnp.asarray([q_start, k_start], jnp.int32)
        for iq in range(nq):
            for j in range(nk):
                last_q = q_start + (iq + 1) * tq - 1
                first_k = k_start + j * tk
                needed = last_q >= first_k
                kj = int(jnp.minimum(j, _diag_k_tile(iq, meta, tq, tk)))
                qi = int(jnp.maximum(
                    iq, _diag_q_tile(j, meta, tq, tk, nq)))
                if needed:
                    assert kj == j, (q_start, k_start, tq, tk, iq, j, kj)
                    assert qi == iq, (q_start, k_start, tq, tk, iq, j, qi)
                # skipped iterations may point anywhere in range
                assert 0 <= kj < nk and 0 <= qi < nq


def test_pallas_bwd_matches_recompute_bwd(monkeypatch):
    """The fused Pallas backward and the JAX blockwise-recompute backward
    are two implementations of the same VJP — gradients must match to
    numerical noise (causal + key masking + block offsets exercised)."""
    q, k, v, mask = _inputs(seed=5)
    starts = jnp.asarray((64, 32), jnp.int32)

    def loss(q, k, v):
        m, l, pv = flash_block_attn(q, k, v, mask, starts, SCALE, True,
                                    True)
        return (l ** 2).sum() + (pv ** 2).sum()

    grads = {}
    for impl in ['pallas', 'recompute']:
        monkeypatch.setenv('KFAC_ATTN_BWD_IMPL', impl)
        grads[impl] = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(grads['pallas'], grads['recompute']):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-4, rtol=2e-4)


def test_bwd_impl_auto_policy():
    """'auto' resolves by static block key length: blockwise recompute
    below the measured v5e crossover, fused Pallas backward at/above it
    (logs/onchip/queue_0731_0346.flash_bwd_ab.log: 8k recompute 45 ms vs
    fused 62 ms; 32k fused 0.66 s vs recompute 9.9 s)."""
    from kfac_pytorch_tpu.ops.pallas_attention import (
        AUTO_BWD_PALLAS_MIN_LK, _bwd_impl_for)
    assert _bwd_impl_for('auto', 1024) == 'recompute'
    assert _bwd_impl_for('auto', AUTO_BWD_PALLAS_MIN_LK - 128) == 'recompute'
    assert _bwd_impl_for('auto', AUTO_BWD_PALLAS_MIN_LK) == 'pallas'
    assert _bwd_impl_for('auto', 2 * AUTO_BWD_PALLAS_MIN_LK) == 'pallas'
    # explicit choices pass through untouched; junk is rejected
    assert _bwd_impl_for('pallas', 8) == 'pallas'
    assert _bwd_impl_for('recompute', 1 << 20) == 'recompute'
    with pytest.raises(ValueError):
        _bwd_impl_for('fused', 1024)


def test_fwd_impl_auto_policy(monkeypatch):
    """'auto' forward resolves by static block key length, mirroring the
    backward policy: XLA blockwise below the measured v5e crossover
    (fwd+bwd 8k: XLA 43.5 ms vs Pallas 59.4; 16k: 103.6 vs 180.9), the
    Pallas kernel at/above it (32k: only Pallas compiles,
    logs/onchip/queue_0731_0346.summary) — VERDICT r2 #3."""
    from kfac_pytorch_tpu.parallel.ring_attention import (
        AUTO_FWD_PALLAS_MIN_LK, _default_block_impl, _fwd_impl_for)
    assert _fwd_impl_for('auto', 1024) == 'xla'
    assert _fwd_impl_for('auto', AUTO_FWD_PALLAS_MIN_LK - 128) == 'xla'
    assert _fwd_impl_for('auto', AUTO_FWD_PALLAS_MIN_LK) == 'pallas'
    assert _fwd_impl_for('auto', 2 * AUTO_FWD_PALLAS_MIN_LK) == 'pallas'
    # explicit choices pass through untouched; junk is rejected
    assert _fwd_impl_for('xla', 1 << 20) == 'xla'
    assert _fwd_impl_for('pallas', 8) == 'pallas'
    assert _fwd_impl_for('pallas_interpret', 8) == 'pallas_interpret'
    with pytest.raises(ValueError):
        _fwd_impl_for('fused', 1024)
    # off-TPU default stays 'xla' (tests run on the CPU mesh); cleared
    # env so a KFAC_ATTN_IMPL override in the test environment can't
    # perturb the default-path assertion
    monkeypatch.delenv('KFAC_ATTN_IMPL', raising=False)
    assert _default_block_impl() in ('xla', 'auto')


def test_ring_with_pallas_blocks_matches_dense():
    devs = jax.devices()[:8]
    mesh = Mesh(np.array(devs), ('seq',))
    rng = np.random.RandomState(2)
    B, H, L = 2, 2, 64
    mk = lambda: jnp.asarray(rng.randn(B, H, L, D), jnp.float32)
    q, k, v = mk(), mk(), mk()

    spec = P(None, None, 'seq', None)
    # check_vma=False: the Pallas interpreter does not yet propagate
    # varying-manual-axes through its closed_call (TPU lowering does)
    out = jax.jit(jax.shard_map(
        functools.partial(ring_mod.ring_attention, axis_name='seq',
                          causal=True, block_impl='pallas_interpret'),
        mesh=mesh, in_specs=(spec,) * 3, out_specs=spec,
        check_vma=False))(q, k, v)

    s = jnp.einsum('bhqd,bhkd->bhqk', q, k) * SCALE
    s = jnp.where(jnp.arange(L)[:, None] >= jnp.arange(L)[None, :],
                  s, -1e30)
    ref = jnp.einsum('bhqk,bhkd->bhqd', jax.nn.softmax(s, -1), v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
