"""Staggered preconditioner refresh (plan.build_cohorts + the
engine cohort decompose/merge + KFAC(stagger=True)).

Pins the tentpole contracts:

1. Exactness: after any full ``kfac_update_freq`` window, every slot's
   stored decomposition equals what the unstaggered schedule would have
   computed at the step that slot's cohort refreshed on — the cohort
   eigh/Cholesky IS the full one, just row-subsetted (world=1 via the
   preconditioner API, world=2 through the jitted trainer on a fake
   mesh).
2. Bit-stability: rows outside the refreshed cohort keep their stored
   bits exactly (the merge scatter touches only cohort rows; padding
   writes re-write the stored value).
3. Compile-count guard: the cohort index is TRACED — turning stagger on
   compiles no more distinct step programs than leaving it off, for any
   ``kfac_update_freq``.
4. Cohort balance: max per-step Σ D³ over cohorts ≤ ~2x the mean, and
   max per-step refreshed rows ≤ ceil(total_rows / kfac_update_freq).
"""

import math

import flax.linen as linen
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh

import kfac_pytorch_tpu as kfac
from kfac_pytorch_tpu import capture, engine, training
from kfac_pytorch_tpu import nn as knn
from kfac_pytorch_tpu.capture import LayerMeta
from kfac_pytorch_tpu.plan import build_cohorts, build_plan, default_bucket_fn

pytestmark = pytest.mark.core


class MLP(linen.Module):
    @linen.compact
    def __call__(self, x, train=True):
        x = knn.Dense(8, name='fc1')(x)
        x = linen.relu(x)
        x = knn.Dense(3, name='fc2')(x)
        return x


def _setup(variant, batch=4, **kw):
    model = MLP()
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(batch, 5), jnp.float32)
    y = jnp.asarray(rng.randn(batch, 3), jnp.float32)
    variables = capture.init(model, jax.random.PRNGKey(0), x)
    metas = capture.collect_layer_meta(model, variables, x)
    precond = kfac.KFAC(variant=variant, num_devices=1, axis_name=None,
                        bucket_fn=lambda d: 16, **kw)
    precond.setup(metas)
    state = precond.init()
    loss_fn = lambda out: jnp.mean((out - y) ** 2)  # noqa: E731
    _, _, grads, acts, gs, _ = capture.value_and_grad_with_capture(
        model, loss_fn, variables, x)
    return precond, state, grads, acts, gs, metas


# ---------------------------------------------------------------------------
# satellite: default_bucket_fn boundary values
# ---------------------------------------------------------------------------

def test_default_bucket_fn_boundaries():
    # {min, 1.5·2^k, 2^k} ladder up to 1024, multiples of 256 above
    assert default_bucket_fn(1) == 128
    assert default_bucket_fn(128) == 128
    assert default_bucket_fn(129) == 192
    assert default_bucket_fn(192) == 192
    assert default_bucket_fn(193) == 256
    assert default_bucket_fn(1024) == 1024
    assert default_bucket_fn(1025) == 1280   # first step past the ladder
    # large multiples of 256 stay exact (ResNet-50's 4608 case)
    assert default_bucket_fn(4608) == 4608
    # large non-multiple rounds UP to the next multiple of 256
    assert default_bucket_fn(5000) == 5120
    assert default_bucket_fn(2304 + 1) == 2560
    # monotone, and never below the input
    prev = 0
    for d in (1, 64, 128, 129, 191, 192, 193, 767, 768, 769, 1024, 1025,
              1279, 1280, 4608, 5000):
        b = default_bucket_fn(d)
        assert b >= d and b >= prev
        prev = b


# ---------------------------------------------------------------------------
# cohort layout: balance + row budget
# ---------------------------------------------------------------------------

def _synthetic_plan(dims, num_devices=1):
    metas = {}
    for i, (din, dout) in enumerate(dims):
        m = LayerMeta(name=f'l{i}', path=(f'l{i}',), kind='dense',
                      use_bias=False, in_dim=din, out_dim=dout,
                      kernel_shape=(din, dout))
        metas[m.name] = m
    return build_plan(metas, num_devices=num_devices, comm_mode='pred')


@pytest.mark.parametrize('num_cohorts', [2, 4, 8])
def test_cohort_cost_balance_and_row_budget(num_cohorts):
    # a mixed-size model: several bucket classes, enough slots per device
    dims = [(48, 96), (96, 96), (96, 192), (192, 192), (192, 384),
            (384, 384), (384, 192), (192, 96)]
    plan = _synthetic_plan(dims)
    cohorts = build_cohorts(plan, num_cohorts)
    costs = cohorts.cohort_cost[0]
    assert costs.sum() > 0
    # max per-step Σ D³ over cohorts ≤ ~2x the mean
    assert costs.max() <= 2.0 * costs.mean() + 1e-9, costs
    # every valid row appears in exactly one cohort; none dropped
    total = cohorts.total_rows()
    n_valid = sum(int(plan.buckets[b].valid.sum()) for b in plan.bucket_dims)
    assert total == n_valid
    # max per-step refreshed rows ≤ ceil(total / F) (count-first greedy
    # keeps cohort counts within ±1 at all times)
    assert cohorts.max_rows_per_step() <= math.ceil(total / num_cohorts)
    assert cohorts.cohort_count.max() - cohorts.cohort_count.min() <= 1


def test_cohort_padding_points_outside_cohort():
    """Padding rows must never collide with a real update in the same
    cohort — that is what makes the merge scatter deterministic."""
    dims = [(48, 96), (96, 192), (192, 384), (20, 30), (30, 40)]
    plan = _synthetic_plan(dims)
    cohorts = build_cohorts(plan, 4)
    for bdim in plan.bucket_dims:
        rows, valid = cohorts.rows[bdim], cohorts.valid[bdim]
        for f in range(cohorts.num_cohorts):
            for d in range(plan.num_devices):
                real = {int(r) for r, v in zip(rows[f, d], valid[f, d]) if v}
                pads = [int(r) for r, v in zip(rows[f, d], valid[f, d])
                        if not v]
                assert not (real & set(pads)), (bdim, f, d)


# ---------------------------------------------------------------------------
# exactness, world=1 (direct preconditioner API)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize('variant', ['eigen_dp', 'inverse_dp', 'eigen',
                                     'inverse'])
def test_stagger_exactness_world1(variant):
    """Staggered cohort rows equal the unstaggered (full, every-step)
    schedule's decomposition at the refresh step; untouched rows are
    bit-stable."""
    F = 3
    ps, ss, grads, acts, gs, _ = _setup(variant, kfac_update_freq=F,
                                        stagger=True)
    pf, sf, *_ = _setup(variant, kfac_update_freq=1)
    # step 0: the cold start is a full decomposition in both schedules
    _, ss = ps.step(ss, grads, acts, gs)
    _, sf = pf.step(sf, grads, acts, gs)
    layout = ps.cohorts
    assert layout is not None and layout.num_cohorts == F
    comps = ['invs'] if ps.method == 'cholesky' else ['evals', 'evecs']
    for t in range(1, 2 * F + 1):
        prev = jax.tree.map(lambda a: np.asarray(a).copy(), ss.decomp)
        _, ss = ps.step(ss, grads, acts, gs, stagger_update=True)
        _, sf = pf.step(sf, grads, acts, gs)
        # factor trajectories identical by construction
        for k in ss.factors:
            np.testing.assert_array_equal(np.asarray(ss.factors[k]),
                                          np.asarray(sf.factors[k]))
        c = t % F
        for bdim in ps.plan.bucket_dims:
            key = str(bdim)
            touched = {int(r) for r, v in zip(layout.rows[bdim][c, 0],
                                              layout.valid[bdim][c, 0]) if v}
            for comp in comps:
                new = np.asarray(ss.decomp[comp][key])
                ref = np.asarray(sf.decomp[comp][key])
                old = prev[comp][key]
                for r in range(new.shape[0]):
                    if r in touched:
                        np.testing.assert_allclose(
                            new[r], ref[r], rtol=1e-5, atol=1e-6,
                            err_msg=f'{comp}[{key}] row {r} step {t}')
                    else:
                        np.testing.assert_array_equal(
                            new[r], old[r],
                            err_msg=f'{comp}[{key}] row {r} (untouched) '
                                    f'step {t}')


def test_stagger_double_buffer_pred_uses_previous_table():
    """The staggered step preconditions with the PREVIOUS stored table
    (the cohort it decomposes publishes next step): with unchanged
    factors, the staggered pred equals a no-update step's pred."""
    ps, ss, grads, acts, gs, metas = _setup('eigen_dp', kfac_update_freq=2,
                                            stagger=True)
    _, ss = ps.step(ss, grads, acts, gs)
    g_stale, _ = ps.step(ss, grads, update_factors=False,
                         update_inverse=False)
    g_stag, _ = ps.step(ss, grads, update_factors=False,
                        stagger_update=True)
    for name in metas:
        np.testing.assert_allclose(np.asarray(g_stag[name]['kernel']),
                                   np.asarray(g_stale[name]['kernel']),
                                   atol=0)


def test_stagger_merge_guard_keeps_stored_rows_on_nonfinite():
    """A blown cohort decomposition row falls back to the stored row
    (per-row screen in the merge), instead of poisoning the table."""
    ps, ss, grads, acts, gs, _ = _setup('eigen_dp', kfac_update_freq=2,
                                        stagger=True)
    _, ss = ps.step(ss, grads, acts, gs)
    layout = ps.cohorts
    cohort_idx = jnp.int32(1)
    cohort = engine.compute_cohort_decomposition(
        ps.plan, layout, ss.factors, cohort_idx, jnp.float32(ps.damping),
        ps.method, ps.eps, None)
    poisoned = jax.tree.map(lambda x: jnp.full_like(x, jnp.nan), cohort)
    merged = engine.merge_cohort_decomposition(
        ps.plan, layout, ss.decomp, poisoned, cohort_idx, None,
        ps.comm_mode, ps.method, guard=True)
    for comp in ('evals', 'evecs'):
        for key in merged[comp]:
            np.testing.assert_array_equal(np.asarray(merged[comp][key]),
                                          np.asarray(ss.decomp[comp][key]))
    # guard off: the NaNs land (proves the screen is what saved it)
    merged_raw = engine.merge_cohort_decomposition(
        ps.plan, layout, ss.decomp, poisoned, cohort_idx, None,
        ps.comm_mode, ps.method, guard=False)
    assert any(not np.isfinite(np.asarray(v)).all()
               for comp in ('evals', 'evecs')
               for v in merged_raw[comp].values())


@pytest.mark.filterwarnings('ignore::UserWarning')
def test_stagger_validation():
    with pytest.raises(ValueError, match='stagger'):
        kfac.KFAC(variant='eigen_dp', stagger=True, basis_update_freq=10,
                  num_devices=1, axis_name=None)
    with pytest.raises(ValueError, match='stagger'):
        kfac.KFAC(variant='inverse_dp', stagger=True, warm_start_basis=True,
                  num_devices=1, axis_name=None)
    with pytest.raises(ValueError, match='ekfac'):
        kfac.KFAC(variant='ekfac_dp', stagger=True, num_devices=1,
                  axis_name=None)


def test_scheduler_rebases_cohort_layout():
    """KFACParamScheduler rescaling kfac_update_freq must rebase the
    cohort layout (the satellite mirror of the last_full_step rebase)."""
    ps, *_ = _setup('eigen_dp', kfac_update_freq=4, stagger=True)
    assert ps.cohorts.num_cohorts == 4
    sched = kfac.KFACParamScheduler(ps, update_freq_alpha=2,
                                    update_freq_schedule=[1])
    sched.step(1)
    assert ps.kfac_update_freq == 8
    assert ps.cohorts.num_cohorts == 8
    # every valid slot still covered exactly once per window
    total = sum(int(ps.plan.buckets[b].valid.sum())
                for b in ps.plan.bucket_dims)
    assert ps.cohorts.total_rows() == total


# ---------------------------------------------------------------------------
# trainer integration: compile-count guard + world=2 exactness
# ---------------------------------------------------------------------------

def _batch(n=8):
    rng = np.random.RandomState(0)
    return {'input': jnp.asarray(rng.randn(n, 5), jnp.float32),
            'label': jnp.asarray(rng.randint(0, 3, n))}


def _ce(outputs, batch):
    return optax.softmax_cross_entropy_with_integer_labels(
        outputs, batch['label']).mean()


def _trainer(stagger, kfac_freq, fac_freq=1, ndev=1, mesh=None, lr=0.05,
             variant='eigen_dp'):
    model = MLP()
    precond = kfac.KFAC(variant=variant, lr=lr, damping=0.003,
                        fac_update_freq=fac_freq, kfac_update_freq=kfac_freq,
                        num_devices=ndev,
                        axis_name='batch' if ndev > 1 else None,
                        bucket_fn=lambda d: 16, stagger=stagger)
    tx = training.sgd(lr, momentum=0.9)
    state = training.init_train_state(model, tx, precond,
                                      jax.random.PRNGKey(0),
                                      _batch()['input'])
    step = training.build_train_step(
        model, tx, precond, _ce,
        axis_name='batch' if ndev > 1 else None, mesh=mesh)
    return step, state, precond


@pytest.mark.parametrize('fac_freq,kfac_freq', [(1, 4), (2, 4)])
def test_stagger_compile_count_guard(fac_freq, kfac_freq):
    """The cohort index must be traced, not a Python-level cache key:
    with stagger on, build_train_step's variant cache compiles no more
    distinct programs than with it off, over a schedule covering several
    full windows."""
    batch = _batch()

    def run(stagger):
        step, state, _ = _trainer(stagger, kfac_freq, fac_freq)
        for _ in range(3 * kfac_freq):
            state, _ = step(state, batch, lr=0.05, damping=0.003)
        return step.variants

    v_off = run(False)
    v_on = run(True)
    assert len(v_on) <= len(v_off), (sorted(map(str, v_on)),
                                     sorted(map(str, v_off)))
    # and the stagger keys carry the cohort count, not the cohort index
    stag_keys = [k for k in v_on if 'stagger' in k]
    assert stag_keys and all(k[2] == kfac_freq for k in stag_keys)


def test_stagger_phases_reported():
    """step_fn.last_phases must reflect the staggered dispatch (feeds
    the PhaseTimers/kfac_phase_ms observability)."""
    batch = _batch()
    step, state, _ = _trainer(True, 2, fac_freq=2)
    state, _ = step(state, batch, lr=0.05, damping=0.003)   # full
    assert 'decomp' in step.last_phases
    state, _ = step(state, batch, lr=0.05, damping=0.003)   # stagger, no uf
    assert step.last_phases == ('pred', 'decomp')
    state, _ = step(state, batch, lr=0.05, damping=0.003)   # stagger + uf
    assert step.last_phases == ('pred', 'stats', 'decomp')


@pytest.mark.parametrize('variant', ['eigen_dp', 'eigen'])
def test_stagger_world2_trainer_exactness(variant):
    """Through the jitted trainer on a 2-device fake mesh, with frozen
    params (lr=0) so both runs see identical factor trajectories: the
    staggered run's cohort rows equal the full-every-step run's rows at
    the refresh step, untouched rows bit-stable. 'eigen' additionally
    routes the cohort through the comm_inverse double-buffered gather
    (only the cohort rows travel; the merged table is replicated)."""
    ndev, F = 2, 2
    mesh = Mesh(np.array(jax.devices()[:ndev]), ('batch',))
    batch = _batch(8)
    step_s, state_s, ps = _trainer(True, F, ndev=ndev, mesh=mesh, lr=0.0,
                                   variant=variant)
    step_f, state_f, pf = _trainer(False, 1, ndev=ndev, mesh=mesh, lr=0.0,
                                   variant=variant)
    # step 0: full decomposition in both
    state_s, _ = step_s(state_s, batch, lr=0.0, damping=0.003)
    state_f, _ = step_f(state_f, batch, lr=0.0, damping=0.003)
    layout = ps.cohorts
    for t in range(1, 2 * F + 1):
        prev = jax.tree.map(lambda a: np.asarray(a).copy(),
                            state_s.kfac_state.decomp)
        state_s, _ = step_s(state_s, batch, lr=0.0, damping=0.003)
        state_f, _ = step_f(state_f, batch, lr=0.0, damping=0.003)
        for k in state_s.kfac_state.factors:
            np.testing.assert_array_equal(
                np.asarray(state_s.kfac_state.factors[k]),
                np.asarray(state_f.kfac_state.factors[k]))
        c = t % F
        for bdim in ps.plan.bucket_dims:
            key = str(bdim)
            b = ps.plan.buckets[bdim]
            touched = set()
            for d in range(ndev):
                for r, v in zip(layout.rows[bdim][c, d],
                                layout.valid[bdim][c, d]):
                    if v:
                        touched.add(d * b.per_dev + int(r))
            for comp in ('evals', 'evecs'):
                new = np.asarray(state_s.kfac_state.decomp[comp][key])
                ref = np.asarray(state_f.kfac_state.decomp[comp][key])
                old = prev[comp][key]
                for r in range(new.shape[0]):
                    if r in touched:
                        np.testing.assert_allclose(
                            new[r], ref[r], rtol=1e-5, atol=1e-6,
                            err_msg=f'{comp}[{key}] row {r} step {t}')
                    else:
                        np.testing.assert_array_equal(
                            new[r], old[r],
                            err_msg=f'{comp}[{key}] row {r} (untouched) '
                                    f'step {t}')


def test_stagger_eigh_fault_drill_heals(monkeypatch):
    """Chaos parity with the full path: an injected eigh blowup on a
    staggered step (KFAC_FAULT_EIGH_STEP) is healed by the merge's
    per-row screen — training continues finite, and the poisoned
    cohort's stored rows keep serving the previous decomposition."""
    monkeypatch.setenv('KFAC_FAULT_EIGH_STEP', '2')
    batch = _batch(16)
    step, state, _ = _trainer(True, 2, lr=0.1)
    for _ in range(6):
        state, m = step(state, batch, lr=0.1, damping=0.003)
        assert np.isfinite(float(m['loss']))
    for comp in ('evals', 'evecs'):
        for v in state.kfac_state.decomp[comp].values():
            assert np.isfinite(np.asarray(v)).all()


def test_stagger_training_reduces_loss():
    """End-to-end sanity: a staggered K-FAC run still trains."""
    batch = _batch(16)
    step, state, _ = _trainer(True, 3, lr=0.1)
    losses = []
    for _ in range(8):
        state, m = step(state, batch, lr=0.1, damping=0.003)
        losses.append(float(m['loss']))
    assert all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0], losses


# ---------------------------------------------------------------------------
# satellite: phase timers + epoch-line suffix
# ---------------------------------------------------------------------------

def test_phase_timers_marginal_attribution():
    from kfac_pytorch_tpu.utils.metrics import PhaseTimers
    t = PhaseTimers()
    for _ in range(4):
        t.record(('pred',), 0.010)
    for _ in range(2):
        t.record(('pred', 'stats'), 0.014)
    t.record(('pred', 'stats', 'decomp', 'gather'), 0.050)
    out = t.epoch_flush()
    assert abs(out['pred'] - 10.0) < 1e-6
    assert abs(out['stats'] - 4.0) < 1e-6
    assert abs(out['decomp+gather'] - 36.0) < 1e-6
    assert abs(out['step_max'] - 50.0) < 1e-6
    assert out['step_mean'] > 0
    # flushed: second call is empty
    assert t.epoch_flush() == {}


def test_kfac_phase_suffix_format():
    from kfac_pytorch_tpu.utils.runlog import kfac_phase_suffix
    assert kfac_phase_suffix({}) == ''
    s = kfac_phase_suffix({'pred': 1.234, 'decomp+gather': 10.0})
    assert s.startswith(' kfac_phase_ms=')
    assert 'decomp+gather:10.00' in s and 'pred:1.23' in s
