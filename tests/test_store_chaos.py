"""Store-plane chaos drill with REAL processes (``-m slow``).

The durable-checkpoint acceptance drill (ISSUE 18): a two-host pod
checkpoints through the GCS-style HTTP object store with the seeded
``KFAC_FAULT_STORE_*`` lanes armed (torn uploads, lost acks, flat
failures), loses host 1 to SIGKILL mid-run, and the survivor must:

- ride out every injected store fault through the per-op retry layer
  (``store: retry`` visible, never a give-up),
- shrink to world 1 and resume from the last *verified* manifest —
  a planted torn commit (blobs, no manifest) is skipped by the resume
  scan, never selected,
- finish with the SAME ``DONE`` schedule line as an undisturbed
  single-host control run.

Then the scrub story on the dead host's namespace: ``kfac-ckpt-verify
--sync-mirror`` banks a mirror, one blob is corrupted in place on the
store, and a second scrub detects it by content hash and repairs it
from the mirror — the whole ``ckpt_commit -> ckpt_corrupt ->
ckpt_repair -> ckpt_verify`` story visible through the incident
grammar and the ``kfac-obs`` timeline with zero new aggregation code.
"""

import json
import os
import re
import signal
import subprocess
import sys
import time

import pytest

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TRAINER = os.path.join(REPO, 'tests', 'chaos_trainer.py')

HB_DEADLINE = 4.0

#: store-backend overlay: every process of the drill — supervisors,
#: trainers, the verifier — picks the HTTP store and the seeded
#: store-fault schedule up from these envs
_STORE_OVERLAY = {}


def _env(**extra):
    base = {k: v for k, v in os.environ.items()
            if not (k.startswith('KFAC_FAULT_')
                    or k.startswith('KFAC_HB_')
                    or k.startswith('KFAC_COORD_')
                    or k.startswith('KFAC_STORE_'))}
    base['JAX_PLATFORMS'] = 'cpu'
    base.update(_STORE_OVERLAY)
    base.update(extra)
    return base


@pytest.fixture
def http_store():
    """A live kfac-store-serve object server in this process, selected
    by every child via KFAC_STORE_BACKEND=http — no shared-filesystem
    durability anywhere in the drill — with mild seeded store faults.
    FAIL/TORN/ACK_LOST at 0.05 each sizes the statistics like the coord
    drill's: an orbax epoch commit is a dozen-odd retried store ops, so
    retries fire with near-certainty over the run, while a give-up
    needs a whole attempt budget of consecutive injected failures on
    one op — never in a healthy drill. The silent get-path lanes
    (PARTIAL/STALE) stay unarmed: they are NOT retryable by design
    (the manifest hash check is their detector) and the scrub phase
    plants its corruption deterministically instead."""
    from kfac_pytorch_tpu.store import StoreHttpServer
    srv = StoreHttpServer('127.0.0.1', 0).start()
    _STORE_OVERLAY.update({
        'KFAC_STORE_BACKEND': 'http',
        'KFAC_STORE_ADDR': srv.address,
        'KFAC_FAULT_STORE_SEED': '5',
        'KFAC_FAULT_STORE_FAIL': '0.05',
        'KFAC_FAULT_STORE_TORN': '0.05',
        'KFAC_FAULT_STORE_ACK_LOST': '0.05',
    })
    try:
        yield srv
    finally:
        _STORE_OVERLAY.clear()
        srv.stop()


def _client(srv, ckpt_dir):
    """A direct, fault-free client on a namespace — the test's own eye
    on the store (and its corruption-planting hand), outside the chaos
    wrap the drill processes live behind."""
    from kfac_pytorch_tpu.store import HttpStore
    return HttpStore(srv.address,
                     namespace=os.path.abspath(str(ckpt_dir)))


def _done_line(out):
    lines = [l for l in out.splitlines() if l.startswith('DONE ')]
    assert lines, f'no DONE line; output tail: {out[-3000:]}'
    return lines[-1]


def _control_done(tmp_path):
    # the control runs on the default posix store, no faults: schedule
    # equivalence is about the training schedule, not the byte plane
    env = {k: v for k, v in _env().items()
           if not (k.startswith('KFAC_FAULT_')
                   or k.startswith('KFAC_STORE_'))}
    p = subprocess.run(
        [sys.executable, TRAINER, '--epochs', '3',
         '--checkpoint-dir', str(tmp_path / 'ckpt_control')],
        env=env, cwd=REPO, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True, timeout=540)
    assert p.returncode == 0, p.stdout[-3000:]
    return _done_line(p.stdout)


def _pod_cmd(host_id, lease, ckpt_dir):
    return [
        sys.executable, '-m', 'kfac_pytorch_tpu.resilience.elastic',
        '--host-id', str(host_id), '--num-hosts', '2',
        '--lease-dir', str(lease),
        '--max-restarts', '3', '--backoff-base', '0.2',
        '--hb-interval', '0.3', '--hb-deadline', str(HB_DEADLINE),
        '--hb-grace', '180', '--settle', '1', '--shrink-timeout', '8',
        '--',
        sys.executable, TRAINER, '--epochs', '3',
        '--checkpoint-dir', str(ckpt_dir),
        '--num-hosts', '{num_hosts}', '--host-id', '{host_id}',
        '--step-deadline', '300',
    ]


def _run_verify(root, mirror, out_path):
    """One kfac-ckpt-verify scrub over ``root`` on the HTTP store —
    fault lanes stripped: the scrub verdict must be truthful, not a
    coin flip on an injected read failure."""
    env = {k: v for k, v in _env().items()
           if not k.startswith('KFAC_FAULT_')}
    p = subprocess.run(
        [sys.executable, '-m', 'kfac_pytorch_tpu.store.verify',
         '--root', root, '--mirror', mirror, '--sync-mirror'],
        env=env, cwd=REPO, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True, timeout=120)
    out_path.write_text(p.stdout)
    return p.returncode, p.stdout


def test_store_chaos_drill_survivor_resumes_verified_manifest(
        tmp_path, http_store):
    from kfac_pytorch_tpu.store.manifest import (
        blob_sha256, manifest_epochs, read_manifest)

    control = _control_done(tmp_path)
    lease = tmp_path / 'lease'
    ckpt0, ckpt1 = str(tmp_path / 'ckpt_h0'), str(tmp_path / 'ckpt_h1')
    out0_path = tmp_path / 'host0.out'
    out1_path = tmp_path / 'host1.out'
    # pace every step (same reasoning as the pod drills): the schedule
    # must be several detection windows long when the host dies
    pod_env = _env(KFAC_FAULT_SLOW_STEP='0:999',
                   KFAC_FAULT_SLOW_SECS='1.5')
    cli0, cli1 = _client(http_store, ckpt0), _client(http_store, ckpt1)
    procs = []
    try:
        with open(out0_path, 'wb') as f0, open(out1_path, 'wb') as f1:
            for host_id, ckpt, f in ((0, ckpt0, f0), (1, ckpt1, f1)):
                procs.append(subprocess.Popen(
                    _pod_cmd(host_id, lease, ckpt), env=pod_env,
                    cwd=REPO, stdout=f, stderr=subprocess.STDOUT,
                    start_new_session=True))

            # wait until BOTH hosts COMMITTED epoch 0 — committed means
            # the manifest object exists on the store, not a local file
            deadline = time.time() + 420
            while time.time() < deadline:
                if any(p.poll() is not None for p in procs):
                    pytest.fail('a pod member exited before the kill; '
                                'host0 tail: '
                                + out0_path.read_text()[-3000:])
                if (0 in manifest_epochs(cli0)
                        and 0 in manifest_epochs(cli1)):
                    break
                time.sleep(0.5)
            else:
                pytest.fail('epoch-0 manifests never appeared on the '
                            'store; host0 tail: '
                            + out0_path.read_text()[-3000:])
            os.killpg(os.getpgid(procs[1].pid), signal.SIGKILL)
            procs[1].wait(timeout=30)
            # the planted TORN COMMIT: a writer that died mid-epoch-2
            # leaves a checkpoint tree with no manifest. The survivor
            # has several seconds of heartbeat detection + shrink ahead
            # of it, so this lands well before its resume scan — which
            # must SKIP it (epoch 2 is uncommitted) and land on the
            # newest manifested epoch instead
            os.makedirs(os.path.join(ckpt0, 'checkpoint-2'),
                        exist_ok=True)

            rc0 = procs[0].wait(timeout=420)
    finally:
        for p in procs:
            if p.poll() is None:
                try:
                    os.killpg(os.getpgid(p.pid), signal.SIGKILL)
                except ProcessLookupError:
                    pass

    out0 = out0_path.read_text()
    out1 = out1_path.read_text()
    assert rc0 == 0, out0[-4000:]

    # the shrink-and-resume story, all through the HTTP store
    assert 'elastic: shrinking world 2 -> 1' in out0, out0[-4000:]
    assert 'RESUMED from=checkpoint-' in out0
    assert _done_line(out0) == control

    # the resume scan refused the torn commit by name ...
    assert 'checkpoint-2' in out0 and 'has no manifest (torn commit)' \
        in out0, out0[-4000:]
    # ... and every resume landed on a COMMITTED (manifested) epoch,
    # never the planted epoch-2 torso
    resumed = [int(m.group(1)) for m in
               re.finditer(r'RESUMED from=checkpoint-(\d+)', out0)]
    assert resumed, out0[-4000:]
    committed = manifest_epochs(cli0)
    assert all(e in committed and e < 2 for e in resumed), (
        resumed, sorted(committed))

    # the injected store faults really fired and the retry layer rode
    # them out — visible retries, zero give-ups, zero store_lost exits
    assert ('store: retry' in out0) or ('store: retry' in out1), \
        out0[-2000:] + out1[-2000:]
    assert 'store: giving up' not in out0, out0[-4000:]
    assert 'checkpoint store lost' not in out0, out0[-4000:]
    # every epoch's commit point narrated in the incident grammar
    assert 'ckpt: committed manifest epoch=' in out0

    # ------------------------------------------------------------------
    # scrub phase, on the DEAD host's namespace: backup pass, planted
    # in-place corruption, detection by content hash, mirror repair
    # ------------------------------------------------------------------
    ns = os.path.abspath(ckpt1)
    mirror = str(tmp_path / 'mirror')
    rc, vout1 = _run_verify(ns, mirror, tmp_path / 'verify1.out')
    assert rc == 0, vout1[-3000:]
    assert 'ckpt: verified epoch=' in vout1
    assert 'ckpt: corrupt blob' not in vout1

    newest = max(manifest_epochs(cli1))
    manifest = read_manifest(cli1, newest)
    key = sorted(manifest['blobs'])[0]
    spec = manifest['blobs'][key]
    blob = cli1.get(key)
    assert blob is not None and blob_sha256(blob.data) == spec['sha256']
    # same length, different bytes: the silent bit-rot case only the
    # manifest's recorded hash can catch
    cli1.put(key, bytes(b ^ 0xFF for b in blob.data))

    rc, vout2 = _run_verify(ns, mirror, tmp_path / 'verify2.out')
    assert rc == 0, vout2[-3000:]
    assert f'ckpt: corrupt blob key={key} epoch={newest} ' \
           f'reason=hash_mismatch' in vout2, vout2[-3000:]
    assert f'ckpt: repaired blob key={key} epoch={newest} ' \
           f'source=mirror' in vout2, vout2[-3000:]
    restored = cli1.get(key)
    assert restored is not None \
        and blob_sha256(restored.data) == spec['sha256']

    # the incident grammar reads the whole durability story off the
    # scrub log with zero new aggregation code
    from kfac_pytorch_tpu.resilience.incident import IncidentReport
    rep = IncidentReport(host_id=1).scrape_lines(vout2.splitlines())
    kinds = [e['kind'] for e in rep.events]
    assert 'ckpt_corrupt' in kinds and 'ckpt_repair' in kinds, kinds
    assert rep.counters.get('ckpt_repaired', 0) >= 1, rep.counters

    # kfac-obs: ONE timeline over the drill's runlogs + both scrub
    # logs — commit, corruption, repair and the clean re-verify all
    # land as events, with the repair after the corruption
    from kfac_pytorch_tpu.obs import aggregate
    paths = [str(out0_path), str(out1_path),
             str(tmp_path / 'verify1.out'), str(tmp_path / 'verify2.out')]
    incident = lease / 'incident-host0.json'
    if incident.exists():
        paths.append(str(incident))
    timeline = aggregate.build_timeline(paths)
    kinds = [e['kind'] for e in timeline['events']]
    for kind in ('ckpt_commit', 'ckpt_verify', 'ckpt_corrupt',
                 'ckpt_repair'):
        assert kind in kinds, (kind, sorted(set(kinds)))
    scrub_events = [e['kind'] for e in timeline['events']
                    if e['kind'] in ('ckpt_corrupt', 'ckpt_repair')]
    assert scrub_events.index('ckpt_corrupt') \
        < scrub_events.index('ckpt_repair')

    # CI artifact export, same contract as the pod drills
    art = os.environ.get('KFAC_DRILL_ARTIFACTS')
    if art:
        import shutil
        art = os.path.join(art, 'store')
        os.makedirs(art, exist_ok=True)
        for p in paths:
            shutil.copy(p, art)
        with open(os.path.join(art, 'timeline.json'), 'w') as f:
            json.dump({k: v for k, v in timeline.items()
                       if not k.startswith('_')}, f, indent=2,
                      default=str)
