"""Native library parity: C++ schedulers/augmentation vs numpy."""

import numpy as np
import pytest

from kfac_pytorch_tpu import native_lib
from kfac_pytorch_tpu.parallel import partition


@pytest.mark.skipif(native_lib.get_lib() is None,
                    reason='native build unavailable')
def test_block_partition_matches_python():
    rng = np.random.RandomState(0)
    costs = rng.rand(40) * 10
    for p in (1, 3, 8):
        nat = native_lib.block_partition(costs, p)
        py = partition.block_partition(costs, p)
        # both optimal: bottleneck costs must match (owner arrays may
        # differ between equally-optimal partitions)
        def bot(owners):
            return max(costs[owners == d].sum() for d in range(p)
                       if (owners == d).any())
        assert np.isclose(bot(nat), bot(py))


@pytest.mark.skipif(native_lib.get_lib() is None,
                    reason='native build unavailable')
def test_lpt_matches_python():
    rng = np.random.RandomState(1)
    costs = rng.rand(30)
    nat = native_lib.lpt_assign(costs, 4)
    py = partition.balanced_assign(costs, 4)
    np.testing.assert_array_equal(nat, py)


@pytest.mark.skipif(native_lib.get_lib() is None,
                    reason='native build unavailable')
def test_augment_matches_numpy():
    rng = np.random.RandomState(2)
    x = rng.randn(3, 8, 8, 3).astype(np.float32)
    offs = rng.randint(0, 9, size=(3, 2)).astype(np.int32)
    flips = np.array([0, 1, 0], np.uint8)
    nat = native_lib.augment_crop_flip(x, offs, flips)
    xp = np.pad(x, ((0, 0), (4, 4), (4, 4), (0, 0)), mode='reflect')
    for i in range(3):
        oy, ox = offs[i]
        win = xp[i, oy:oy + 8, ox:ox + 8]
        want = win[:, ::-1] if flips[i] else win
        np.testing.assert_allclose(nat[i], want)
