"""Deterministic network chaos (resilience/chaos_net.py).

Everything here is wall-clock-free: the ChaosTransport takes injectable
monotonic/wall clocks, and every fault decision is a pure function of
``(seed, src, dst, seq)`` — two runs over the same poll sequence must
produce IDENTICAL delivery traces, which is the acceptance pin for the
partition drill's reproducibility.
"""

import json
import os

import pytest

from kfac_pytorch_tpu.resilience import atomic_write_json, chaos_net
from kfac_pytorch_tpu.resilience.chaos_net import (
    ChaosTransport, NetFaultConfig, parse_idmap, parse_partition_spec)
from kfac_pytorch_tpu.resilience.retry import ManualClock

pytestmark = pytest.mark.core


class ScriptedTransport:
    """Inner transport the tests drive by hand."""

    def __init__(self):
        self.peers = {}
        self.published = []
        self.closed = False

    def publish(self, payload):
        self.published.append(payload)

    def read_peers(self):
        return {h: dict(p) for h, p in self.peers.items()}

    def close(self):
        self.closed = True


def _drive(cfg, n=40, seed_payload=None):
    """One scripted run: peer 1 publishes seq 1..n, one poll per second
    on a manual clock. Returns (delivery trace, delivered seq list)."""
    clock = ManualClock()
    inner = ScriptedTransport()
    t = ChaosTransport(inner, cfg, 0, clock=clock.monotonic,
                       wall=clock.monotonic)
    delivered = []
    for seq in range(1, n + 1):
        inner.peers[1] = dict(seed_payload or {}, host=1, seq=seq,
                              pid=7, gen=0)
        out = t.read_peers()
        if 1 in out:
            delivered.append(out[1]['seq'])
        clock.sleep(1.0)
    # drain: let delayed payloads arrive
    for _ in range(10):
        out = t.read_peers()
        if 1 in out:
            delivered.append(out[1]['seq'])
        clock.sleep(1.0)
    return list(t.trace), delivered


def test_identical_seed_reproduces_identical_delivery_trace():
    cfg = NetFaultConfig(seed=11, drop=0.2, delay=3.5, dup=0.3,
                         reorder=0.6)
    trace_a, delivered_a = _drive(cfg, n=60)
    trace_b, delivered_b = _drive(cfg, n=60)
    assert trace_a == trace_b
    assert delivered_a == delivered_b
    # the schedule genuinely exercised every fault kind at these rates
    kinds = {k for k, _, _ in trace_a}
    assert {'deliver', 'drop', 'dup', 'reorder'} <= kinds, kinds


def test_different_seed_changes_the_schedule():
    cfg = NetFaultConfig(seed=11, drop=0.3, delay=2.5, dup=0.25,
                         reorder=0.25)
    other = NetFaultConfig(seed=12, drop=0.3, delay=2.5, dup=0.25,
                           reorder=0.25)
    assert _drive(cfg)[0] != _drive(other)[0]


def test_drop_one_starves_the_link_without_crashing():
    trace, delivered = _drive(NetFaultConfig(seed=1, drop=1.0))
    assert delivered == []
    assert trace and all(k == 'drop' for k, _, _ in trace)


def test_delay_holds_payloads_then_delivers_without_invention():
    """Delayed payloads arrive late but intact: everything delivered
    was genuinely published, and a pure-delay link never regresses the
    LATEST delivered seq below what a stale repeat would show."""
    trace, delivered = _drive(NetFaultConfig(seed=3, delay=3.0))
    assert delivered, 'pure delay must still deliver'
    assert set(delivered) <= set(range(1, 41))
    # no drops/dups/reorders configured: none may appear
    assert {k for k, _, _ in trace} <= {'deliver'}
    fresh = [s for i, s in enumerate(delivered)
             if i == 0 or s != delivered[i - 1]]
    assert fresh == sorted(fresh)


def test_duplicate_redelivers_stale_payload_between_fresh_ones():
    trace, delivered = _drive(NetFaultConfig(seed=5, dup=1.0))
    dups = [s for k, _, s in trace if k == 'dup']
    assert dups, 'dup=1.0 must redeliver'
    # a duplicated delivery repeats a seq AFTER it first appeared
    for s in dups:
        assert delivered.index(s) < len(delivered) - 1 or s == delivered[-1]


def test_partition_window_cuts_only_between_groups():
    cfg = NetFaultConfig(
        seed=0, windows=parse_partition_spec('10:40=0,2|1'), t0=0.0)
    assert cfg.partitioned(1, 0, 15.0)
    assert cfg.partitioned(0, 1, 15.0)
    assert not cfg.partitioned(2, 0, 15.0)     # same group
    assert not cfg.partitioned(0, 1, 45.0)     # window over
    assert not cfg.partitioned(0, 1, 9.9)      # window not yet open
    assert not cfg.partitioned(0, 5, 15.0)     # unlisted host: connected
    assert not cfg.partitioned(1, 1, 15.0)     # self


def test_partition_applies_to_wrapped_reads():
    clock = ManualClock()
    inner = ScriptedTransport()
    cfg = NetFaultConfig(seed=0,
                         windows=parse_partition_spec('5:100=0|1'),
                         t0=0.0)
    t = ChaosTransport(inner, cfg, 0, clock=clock.monotonic,
                       wall=clock.monotonic)
    inner.peers[1] = {'host': 1, 'seq': 1, 'pid': 7}
    assert 1 in t.read_peers()                 # before the window
    clock.sleep(10.0)
    inner.peers[1] = {'host': 1, 'seq': 2, 'pid': 7}
    out = t.read_peers()                       # inside: link cut
    assert 1 not in out
    assert ('partition', 1, 2) in t.trace
    # publish passes through untouched either way
    t.publish({'host': 0, 'seq': 9})
    assert inner.published[-1]['seq'] == 9


def test_partition_file_cuts_and_heals_live(tmp_path):
    part = tmp_path / 'partition.json'
    cfg = NetFaultConfig(seed=0, partition_file=str(part))
    assert not cfg.partitioned(0, 1, 50.0)     # no file: connected
    atomic_write_json(str(part), {'windows': [
        {'start': 40.0, 'end': 60.0, 'groups': [[0, 2], [1]]}]})
    assert cfg.partitioned(0, 1, 50.0)
    assert not cfg.partitioned(0, 2, 50.0)
    assert not cfg.partitioned(0, 1, 65.0)     # window expired
    os.remove(part)                            # HEAL: file gone
    assert not cfg.partitioned(0, 1, 50.0)
    # torn JSON reads as "no partition", never a crash
    part.write_text('{"windows": [{"sta')
    assert not cfg.partitioned(0, 1, 50.0)


def test_idmap_translates_ranks_to_pod_hosts():
    """After a shrink the trainer ranks drift from pod host ids: rank 1
    is pod host 2. The partition matrix must keep cutting on POD host
    ids through the supervisor-exported map."""
    cfg = NetFaultConfig(seed=0,
                         windows=parse_partition_spec('0:100=0,2|1'),
                         t0=0.0, idmap=parse_idmap('0=0,1=2'))
    # rank 0 (host 0) <-> rank 1 (host 2): SAME side, never cut
    assert not cfg.partitioned(0, 1, 50.0)


def test_from_env_strict_and_optional(monkeypatch):
    for k in chaos_net.NET_ENVS:
        monkeypatch.delenv(k, raising=False)
    assert chaos_net.from_env() is None
    monkeypatch.setenv(chaos_net.ENV_NET_SEED, '42')
    monkeypatch.setenv(chaos_net.ENV_NET_DROP, '0.25')
    monkeypatch.setenv(chaos_net.ENV_NET_PARTITION, '10:20=0|1')
    monkeypatch.setenv(chaos_net.ENV_NET_T0, '1000')
    cfg = chaos_net.from_env()
    assert cfg.seed == 42 and cfg.drop == 0.25 and cfg.t0 == 1000.0
    assert cfg.partitioned(0, 1, 1015.0)
    for env, bad in ((chaos_net.ENV_NET_DROP, '1.5'),
                     (chaos_net.ENV_NET_SEED, 'xyz'),
                     (chaos_net.ENV_NET_PARTITION, '10=0|1'),
                     (chaos_net.ENV_NET_PARTITION, '10:20=0'),
                     (chaos_net.ENV_NET_PARTITION, '20:10=0|1'),
                     (chaos_net.ENV_NET_PARTITION, '10:20=0|0,1')):
        old = os.environ.get(env)
        monkeypatch.setenv(env, bad)
        with pytest.raises(ValueError):
            chaos_net.from_env()
        monkeypatch.setenv(env, old)


def test_faults_from_env_registers_the_net_contract(monkeypatch):
    """The STRICT faults.from_env must know the whole KFAC_FAULT_NET_*
    surface (a typo'd drill fails loudly) and must re-raise malformed
    sub-specs at build time."""
    from kfac_pytorch_tpu import faults
    for k in list(os.environ):
        if k.startswith('KFAC_FAULT_'):
            monkeypatch.delenv(k)
    monkeypatch.setenv(chaos_net.ENV_NET_SEED, '1')
    monkeypatch.setenv(chaos_net.ENV_NET_PARTITION, '5:9=0|1')
    faults.from_env()  # well-formed: accepted
    monkeypatch.setenv('KFAC_FAULT_NET_TYPO', '1')
    with pytest.raises(ValueError, match='NET_TYPO'):
        faults.from_env()
    monkeypatch.delenv('KFAC_FAULT_NET_TYPO')
    monkeypatch.setenv(chaos_net.ENV_NET_DELAY, '-3')
    with pytest.raises(ValueError, match='NET_DELAY'):
        faults.from_env()


def test_maybe_wrap_and_close_pass_through(monkeypatch):
    for k in chaos_net.NET_ENVS:
        monkeypatch.delenv(k, raising=False)
    inner = ScriptedTransport()
    assert chaos_net.maybe_wrap(inner, 0) is inner  # env off: untouched
    monkeypatch.setenv(chaos_net.ENV_NET_SEED, '7')
    wrapped = chaos_net.maybe_wrap(inner, 0)
    assert isinstance(wrapped, ChaosTransport)
    wrapped.close()
    assert inner.closed


def test_partition_file_spec_roundtrip_shapes():
    windows = parse_partition_spec('0:5=0|1;10:20=0,1|2,3')
    assert len(windows) == 2
    assert windows[1].groups == (frozenset({0, 1}), frozenset({2, 3}))
    with pytest.raises(ValueError):
        parse_idmap('0:1')
    assert parse_idmap('0=0, 1=2') == {0: 0, 1: 2}
