"""End-to-end single-device preconditioner correctness.

The oracle is a straightforward per-layer dense implementation of the
documented K-FAC math (reference semantics: kfac_preconditioner_inv.py /
eigen_dp.py) with no bucketing, padding, or stacking — the stacked-bucket
engine must reproduce it exactly (identity padding is exact).
"""

import flax.linen as linen
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import kfac_pytorch_tpu as kfac
from kfac_pytorch_tpu import capture, ops
from kfac_pytorch_tpu import nn as knn

pytestmark = pytest.mark.core


class MLP(linen.Module):
    @linen.compact
    def __call__(self, x):
        x = knn.Dense(8, name='fc1')(x)
        x = linen.relu(x)
        x = knn.Dense(3, name='fc2')(x)
        return x


def _setup(variant, **kw):
    model = MLP()
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(4, 5), jnp.float32)
    y = jnp.asarray(rng.randn(4, 3), jnp.float32)
    variables = capture.init(model, jax.random.PRNGKey(0), x)
    metas = capture.collect_layer_meta(model, variables, x)
    precond = kfac.KFAC(variant=variant, num_devices=1, axis_name=None,
                        bucket_fn=lambda d: 16, **kw)
    precond.setup(metas)
    state = precond.init()
    loss_fn = lambda out: jnp.mean((out - y) ** 2)
    loss, out, grads, acts, gs, _ = capture.value_and_grad_with_capture(
        model, loss_fn, variables, x)
    return precond, state, grads, acts, gs, metas


def _grad_mat(grads, name):
    g = grads[name]['kernel'].T
    return np.concatenate([np.asarray(g),
                           np.asarray(grads[name]['bias'])[:, None]], 1)


def _oracle_factors(acts, gs, metas, decay):
    """step-0 running averages: alpha*stat + (1-alpha)*I."""
    out = {}
    for name, m in metas.items():
        A = np.asarray(ops.compute_a_dense(acts[name]['a'], True))
        G = np.asarray(ops.compute_g_dense(gs[name]['g'], True))
        mA = decay * A + (1 - decay) * np.eye(A.shape[0], dtype=np.float32)
        mG = decay * G + (1 - decay) * np.eye(G.shape[0], dtype=np.float32)
        out[name] = (mA, mG)
    return out


def _kl_clip(preds, gmats, lr, kl):
    vg = sum(float(np.sum(p * g)) for p, g in zip(preds, gmats)) * lr ** 2
    return min(1.0, np.sqrt(kl / abs(vg)))


@pytest.mark.parametrize('variant', ['eigen_dp', 'eigen'])
def test_eigen_variants_match_oracle(variant):
    lr, damping, decay, kl = 0.1, 0.003, 0.95, 0.001
    precond, state, grads, acts, gs, metas = _setup(
        variant, lr=lr, damping=damping, factor_decay=decay, kl_clip=kl)
    new_grads, new_state = precond.step(state, grads, acts, gs)

    factors = _oracle_factors(acts, gs, metas, decay)
    preds, gmats = [], []
    for name in metas:
        mA, mG = factors[name]
        dA, QA = np.linalg.eigh(mA)
        dG, QG = np.linalg.eigh(mG)
        dA = dA * (dA > 1e-10)
        dG = dG * (dG > 1e-10)
        gm = _grad_mat(grads, name)
        v1 = QG.T @ gm @ QA
        v2 = v1 / (np.outer(dG, dA) + damping)
        preds.append(QG @ v2 @ QA.T)
        gmats.append(gm)
    nu = _kl_clip(preds, gmats, lr, kl)
    for name, pred in zip(metas, preds):
        got = _grad_mat(new_grads, name)
        np.testing.assert_allclose(got, pred * nu, rtol=1e-3, atol=1e-4)
    assert int(new_state.step) == 1


@pytest.mark.parametrize('variant', ['inverse_dp', 'inverse'])
def test_inverse_variants_match_oracle(variant):
    lr, damping, decay, kl = 0.1, 0.003, 0.95, 0.001
    precond, state, grads, acts, gs, metas = _setup(
        variant, lr=lr, damping=damping, factor_decay=decay, kl_clip=kl)
    new_grads, _ = precond.step(state, grads, acts, gs)

    factors = _oracle_factors(acts, gs, metas, decay)
    preds, gmats = [], []
    for name in metas:
        mA, mG = factors[name]
        pi = np.sqrt((np.trace(mA) / mA.shape[0]) / (np.trace(mG) / mG.shape[0]))
        Ad = mA + np.sqrt(damping) * pi * np.eye(mA.shape[0])
        Gd = mG + np.sqrt(damping) / pi * np.eye(mG.shape[0])
        gm = _grad_mat(grads, name)
        preds.append(np.linalg.inv(Gd) @ gm @ np.linalg.inv(Ad))
        gmats.append(gm)
    nu = _kl_clip(preds, gmats, lr, kl)
    for name, pred in zip(metas, preds):
        got = _grad_mat(new_grads, name)
        np.testing.assert_allclose(got, pred * nu, rtol=1e-3, atol=1e-4)


def test_stale_decomposition_reuse():
    """Steps without update flags must reuse the stored decomposition and
    running factors (freq gating, kfac_preconditioner_base.py:198-213)."""
    precond, state, grads, acts, gs, metas = _setup('eigen_dp')
    g1, s1 = precond.step(state, grads, acts, gs)
    # same grads, no updates -> same pred from stored decomp
    g2, s2 = precond.step(s1, grads, update_factors=False,
                          update_inverse=False)
    for name in metas:
        np.testing.assert_allclose(np.asarray(g1[name]['kernel']),
                                   np.asarray(g2[name]['kernel']), atol=1e-6)
    # factors unchanged when update_factors=False
    for k in s1.factors:
        np.testing.assert_allclose(np.asarray(s1.factors[k]),
                                   np.asarray(s2.factors[k]), atol=0)


@pytest.mark.parametrize('variant', ['eigen_dp', 'eigen'])
def test_basis_refresh_exact_with_unchanged_factors(variant):
    """With factors unchanged, the eigenvalue-only refresh
    (update_basis=False) reproduces the full eigendecomposition's
    preconditioning exactly: diag(Q^T F Q) = d when Q is F's eigenbasis."""
    precond, state, grads, acts, gs, metas = _setup(
        variant, basis_update_freq=100)
    g_full, s1 = precond.step(state, grads, acts, gs)
    # refresh in the retained basis (factors frozen -> same spectrum)
    g_ref, s2 = precond.step(s1, grads, update_factors=False,
                             update_inverse=True, update_basis=False)
    for name in metas:
        np.testing.assert_allclose(np.asarray(g_full[name]['kernel']),
                                   np.asarray(g_ref[name]['kernel']),
                                   rtol=1e-4, atol=1e-5)
    for k in s1.decomp['evals']:
        np.testing.assert_allclose(np.asarray(s1.decomp['evals'][k]),
                                   np.asarray(s2.decomp['evals'][k]),
                                   rtol=1e-4, atol=1e-5)
        # basis retained bit-for-bit
        np.testing.assert_allclose(np.asarray(s1.decomp['evecs'][k]),
                                   np.asarray(s2.decomp['evecs'][k]), atol=0)


def test_basis_refresh_tracks_factor_change():
    """After a factor update, the refresh re-fits eigenvalues to the NEW
    factors in the old basis: evals must move toward diag(Q^T F' Q)."""
    precond, state, grads, acts, gs, metas = _setup(
        'eigen_dp', basis_update_freq=100)
    _, s1 = precond.step(state, grads, acts, gs)
    # second factor update drifts the running averages, then refresh
    _, s2 = precond.step(s1, grads, acts, gs, update_basis=False)
    for k in s1.decomp['evals']:
        q = np.asarray(s1.decomp['evecs'][k])
        f = np.asarray(s2.factors[k])
        want = np.einsum('mji,mjk,mki->mi', q, f, q)
        want = want * (want > precond.eps)
        np.testing.assert_allclose(np.asarray(s2.decomp['evals'][k]), want,
                                   rtol=1e-4, atol=1e-5)


def test_warm_start_basis_matches_cold_eigh(monkeypatch):
    """With the jacobi eigh and unchanged factors, a warm-started full
    decomposition (rotate into the stored basis, few sweeps, rotate back)
    must reproduce the cold decomposition's preconditioning."""
    monkeypatch.setenv('KFAC_EIGH_IMPL', 'jacobi')
    precond, state, grads, acts, gs, metas = _setup(
        'eigen_dp', warm_start_basis=True)
    g_cold, s1 = precond.step(state, grads, acts, gs)
    g_warm, s2 = precond.step(s1, grads, update_factors=False,
                              update_inverse=True, update_basis=True,
                              warm_basis=True)
    for name in metas:
        np.testing.assert_allclose(np.asarray(g_cold[name]['kernel']),
                                   np.asarray(g_warm[name]['kernel']),
                                   rtol=1e-3, atol=1e-4)
    for k in s1.decomp['evals']:
        np.testing.assert_allclose(np.asarray(s1.decomp['evals'][k]),
                                   np.asarray(s2.decomp['evals'][k]),
                                   rtol=1e-3, atol=1e-4)


def test_warm_start_validation(monkeypatch):
    # opting in while the eigh impl is XLA (which cannot warm-start) warns
    monkeypatch.delenv('KFAC_EIGH_IMPL', raising=False)
    with pytest.warns(UserWarning, match='warm_start_basis'):
        _setup('eigen_dp', warm_start_basis=True)
    # Cholesky variants warm-start via Newton-Schulz — accepted, no
    # eigh-impl warning (the env knob is irrelevant to that path)
    import warnings as _w
    with _w.catch_warnings(record=True) as rec:
        _w.simplefilter('always')
        _setup('inverse_dp', warm_start_basis=True)
    assert not any('warm_start_basis' in str(x.message) for x in rec)


def test_basis_update_freq_gating_and_validation():
    precond, *_ = _setup('eigen_dp', basis_update_freq=30,
                         kfac_update_freq=10)
    # staleness-based: no full decomposition yet -> always full; then
    # full again once 30 steps have passed since the last one —
    # independent of kfac_update_freq (no lcm aliasing)
    assert precond.should_update_basis(0, None)
    assert not precond.should_update_basis(10, 0)
    assert not precond.should_update_basis(20, 0)
    assert precond.should_update_basis(30, 0)
    assert precond.should_update_basis(55, 25)
    with pytest.raises(ValueError):
        _setup('inverse_dp', basis_update_freq=10)


def test_no_kl_clip_and_plain_passthrough():
    precond, state, grads, acts, gs, metas = _setup('eigen_dp', kl_clip=None)
    new_grads, _ = precond.step(state, grads, acts, gs)
    assert new_grads['fc1']['kernel'].shape == grads['fc1']['kernel'].shape
    # exclude ComputeInverse -> grads unchanged
    precond2, state2, grads2, acts2, gs2, _ = _setup(
        'eigen_dp', exclude_parts='ComputeInverse')
    out, _ = precond2.step(state2, grads2, acts2, gs2)
    np.testing.assert_allclose(np.asarray(out['fc1']['kernel']),
                               np.asarray(grads2['fc1']['kernel']), atol=0)


def test_param_scheduler():
    precond, *_ = _setup('eigen_dp', damping=0.03, fac_update_freq=2,
                         kfac_update_freq=10)
    sched = kfac.KFACParamScheduler(
        precond, damping_alpha=0.5, damping_schedule=[2, 4],
        update_freq_alpha=2, update_freq_schedule=[3])
    sched.step(2)
    assert np.isclose(precond.damping, 0.015)
    assert precond.kfac_update_freq == 10
    sched.step(4)
    assert np.isclose(precond.damping, 0.0075)
    assert precond.fac_update_freq == 4 and precond.kfac_update_freq == 20
    assert precond.should_update_factors(8)
    assert not precond.should_update_factors(9)


def test_warm_basis_on_fresh_state_degrades_to_cold(monkeypatch):
    """Direct API call step(warm_basis=True) on a never-decomposed state:
    the zero stored 'basis' must be treated as identity (cold Jacobi), not
    rotated into (ADVICE r1: trainer-side gating was the only safety)."""
    monkeypatch.setenv('KFAC_EIGH_IMPL', 'jacobi')
    precond, state, grads, acts, gs, metas = _setup(
        'eigen_dp', warm_start_basis=True)
    g_cold, _ = precond.step(state, grads, acts, gs)
    g_warm, s_warm = precond.step(state, grads, acts, gs, warm_basis=True)
    for name in metas:
        np.testing.assert_allclose(np.asarray(g_cold[name]['kernel']),
                                   np.asarray(g_warm[name]['kernel']),
                                   rtol=1e-3, atol=1e-4)
    for k in s_warm.decomp['evals']:
        assert np.all(np.isfinite(np.asarray(s_warm.decomp['evals'][k])))


def test_warm_start_long_interval_warns(monkeypatch):
    """ADVICE r1: warm_start_basis with a long full-decomposition interval
    and default warm_sweeps must emit the calibration warning."""
    import warnings as _w

    monkeypatch.setenv('KFAC_EIGH_IMPL', 'jacobi')
    with _w.catch_warnings(record=True) as rec:
        _w.simplefilter('always')
        kfac.KFAC(variant='eigen_dp', warm_start_basis=True,
                  basis_update_freq=25, num_devices=1, axis_name=None)
    assert any('warm_sweeps' in str(x.message) for x in rec)
    with _w.catch_warnings(record=True) as rec:
        _w.simplefilter('always')
        kfac.KFAC(variant='eigen_dp', warm_start_basis=True,
                  basis_update_freq=25, warm_sweeps=8,
                  num_devices=1, axis_name=None)
    assert not any('warm_sweeps' in str(x.message) for x in rec)


@pytest.mark.parametrize('variant', ['eigen_dp', 'eigen'])
def test_warm_start_subspace_matches_cold_eigh(monkeypatch, variant):
    """With the subspace tracker and unchanged factors, a warm full
    decomposition must reproduce the cold one exactly-to-noise: the
    stored basis already diagonalizes the factors, so the perturbative
    rotation K vanishes and only CholeskyQR2 noise remains. 'eigen'
    additionally routes through the comm_inverse gathered layout
    (local_evecs re-slices the stored rows — at this test's
    num_devices=1 the slice offset is degenerate; the multi-device mesh
    path is covered by the training-level warm tracking test)."""
    monkeypatch.setenv('KFAC_EIGH_IMPL', 'subspace')
    precond, state, grads, acts, gs, metas = _setup(
        variant, warm_start_basis=True)
    g_cold, s1 = precond.step(state, grads, acts, gs)
    g_warm, s2 = precond.step(s1, grads, update_factors=False,
                              update_inverse=True, update_basis=True,
                              warm_basis=True)
    for name in metas:
        np.testing.assert_allclose(np.asarray(g_cold[name]['kernel']),
                                   np.asarray(g_warm[name]['kernel']),
                                   rtol=1e-3, atol=1e-4)
    for k in s1.decomp['evals']:
        np.testing.assert_allclose(np.asarray(s1.decomp['evals'][k]),
                                   np.asarray(s2.decomp['evals'][k]),
                                   rtol=1e-3, atol=1e-4)



@pytest.mark.parametrize('variant', ['inverse_dp', 'inverse'])
def test_warm_start_newton_schulz_matches_cold_cholesky(variant):
    """Cholesky-variant warm step (Newton-Schulz seeded by the stored
    inverse) must reproduce the cold Cholesky preconditioning on
    unchanged factors — 'inverse' additionally routes local_invs through
    the comm_pred owner layout; a fresh (zero-inverse) state under
    warm_basis=True must fall back to Cholesky via the residual gate and
    still be exact."""
    precond, state, grads, acts, gs, metas = _setup(
        variant, warm_start_basis=True)
    g_cold, s1 = precond.step(state, grads, acts, gs)
    g_warm, s2 = precond.step(s1, grads, update_factors=False,
                              update_inverse=True, warm_basis=True)
    for name in metas:
        np.testing.assert_allclose(np.asarray(g_cold[name]['kernel']),
                                   np.asarray(g_warm[name]['kernel']),
                                   rtol=1e-3, atol=1e-4)
    # zero-seed fallback: warm requested on the fresh state
    g_fb, _ = precond.step(state, grads, acts, gs, warm_basis=True)
    for name in metas:
        np.testing.assert_allclose(np.asarray(g_fb[name]['kernel']),
                                   np.asarray(g_cold[name]['kernel']),
                                   rtol=1e-4, atol=1e-5)


def test_warm_newton_schulz_exact_across_damping_change():
    """KFACParamScheduler halves damping between inverse updates: the
    stored inverse is then stale exactly in the small-eigenvalue
    directions (relative residual ~ |Δdamping|/damping). The warm step
    must remain exact — either NS converges to the NEW damped inverse or
    the residual gate falls back to Cholesky."""
    precond, state, grads, acts, gs, metas = _setup(
        'inverse_dp', warm_start_basis=True, damping=0.003)
    _, s1 = precond.step(state, grads, acts, gs)
    from kfac_pytorch_tpu.preconditioner import KFACHyperParams
    colds = {}
    for new_damp in (0.0015, 0.03):
        hyper = KFACHyperParams(lr=jnp.float32(0.1),
                                damping=jnp.float32(new_damp))
        g_warm, _ = precond.step(s1, grads, update_factors=False,
                                 update_inverse=True, warm_basis=True,
                                 hyper=hyper)
        g_cold, _ = precond.step(s1, grads, update_factors=False,
                                 update_inverse=True, warm_basis=False,
                                 hyper=hyper)
        colds[new_damp] = g_cold
        for name in metas:
            np.testing.assert_allclose(np.asarray(g_warm[name]['kernel']),
                                       np.asarray(g_cold[name]['kernel']),
                                       rtol=5e-3, atol=1e-4)
    # sanity: the hyper override really reaches the math — different
    # dampings must produce different preconditioned gradients
    name = next(iter(metas))
    assert not np.allclose(np.asarray(colds[0.0015][name]['kernel']),
                           np.asarray(colds[0.03][name]['kernel']),
                           rtol=1e-3)
