"""bench.py output contract (the driver records its stdout as the
round's official BENCH artifact — a regression here silently zeroes a
round): one JSON line, stable key set with explicit nulls for
unmeasured legs, an overrides marker on non-default configs, partial
emission + file checkpoint when killed mid-run."""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SMOKE = dict(KFAC_PLATFORM='cpu', KFAC_HOST_DEVICES='1',
             BENCH_MODEL='resnet20', BENCH_IMG='32', BENCH_BATCH='8',
             BENCH_ITERS='3')


def _run_bench(tmp_path, timeout, extra_env=(), expect_kill=False):
    # strip every BENCH_*/KFAC_* var from the inherited shell — the
    # repo's own workflow exports BENCH_FULL/BENCH_BREAKDOWN/
    # KFAC_EIGH_IMPL etc., and any of those leaking in changes the leg
    # set the contract assertions pin
    env = {k: v for k, v in os.environ.items()
           if k not in ('XLA_FLAGS', 'JAX_PLATFORMS')
           and not k.startswith(('BENCH_', 'KFAC_'))}
    env.update(SMOKE, BENCH_PARTIAL_PATH=str(tmp_path / 'partial.json'))
    env.update(extra_env)
    p = subprocess.Popen([sys.executable, 'bench.py'], cwd=REPO, env=env,
                         stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
                         text=True)
    if expect_kill:
        time.sleep(timeout)
        p.send_signal(signal.SIGTERM)
        out, _ = p.communicate(timeout=120)
        return p.returncode, out
    out, _ = p.communicate(timeout=timeout)
    return p.returncode, out


@pytest.mark.slow
def test_bench_json_contract_and_partial_checkpoint(tmp_path):
    rc, out = _run_bench(tmp_path, timeout=900)
    assert rc == 0, out
    lines = [l for l in out.splitlines() if l.strip()]
    assert len(lines) == 1, lines  # ONE JSON line on stdout
    d = json.loads(lines[0])
    assert d['metric'] == 'resnet50_imagenet_dpkfac_imgs_per_sec_per_chip'
    assert d['unit'] == 'imgs/s'
    assert d['value'] and d['value'] > 0
    assert d['vs_baseline'] and d['vs_baseline'] > 0
    extra = d['extra']
    # every leg key present — explicit null for unmeasured legs, so a
    # failed leg reads as null, never as an absent key
    for key in ('sgd_iter_s', 'inverse_dp_iter_s_freq1',
                'inverse_dp_iter_s_freq10',
                'inverse_dp_iter_s_freq1_warm_ns',
                'eigen_dp_iter_s_freq10', 'eigen_dp_iter_s_freq10_basis100',
                'eigen_dp_iter_s_freq10_warm_subspace',
                'kfac_overhead_vs_sgd_freq1', 'kfac_overhead_vs_sgd_freq10',
                'model_flops_per_iter', 'mfu_inverse_dp_freq1',
                'peak_flops', 'phase_breakdown_s', 'eigh_impl',
                'autotune', 'decomp'):
        assert key in extra, key
    # the analytic perf model's predictions ride along, clearly labeled
    # (VERDICT r4 #1: a tunnel-down round must still carry falsifiable
    # numbers) — and they must have computed cleanly, not error'd
    assert extra['predicted']['predicted_not_measured'] is True
    assert 'error' not in extra['predicted'], extra['predicted']
    # the obs.drift block pairs the measured legs with the prediction:
    # per-phase ratios present, and a CPU smoke run is advisory-only
    # (comparable: false) — it must never read as chip evidence
    dr = extra['drift']
    assert dr['measured_vs_predicted'] is True
    assert 'error' not in dr, dr
    assert dr['comparable'] is False
    assert dr['gate']['verdict'] == 'advisory'
    assert dr['phases']['Model']['measured_s'] > 0
    assert dr['phases']['Model']['ratio'] is not None
    assert extra['eigen_dp_iter_s_freq10'] is None  # BENCH_FULL unset
    # smoke config must be marked — a partial emission of a smoke run
    # must never read as an official resnet50 number
    assert extra['overrides']['model'] == 'resnet20'
    # the file checkpoint matches the emitted result
    ck = json.loads((tmp_path / 'partial.json').read_text())
    assert ck['value'] == d['value']
    assert ck['extra']['overrides'] == extra['overrides']


@pytest.mark.slow
def test_bench_sigterm_partial_emission(tmp_path):
    # 100 iters makes the headline leg long enough that a 30s TERM lands
    # mid-run; the process must still emit one parseable JSON line with
    # the overrides marker (headline value may or may not have landed)
    rc, out = _run_bench(tmp_path, timeout=30,
                         extra_env={'BENCH_ITERS': '100'},
                         expect_kill=True)
    assert rc != 0
    lines = [l for l in out.splitlines() if l.strip()]
    assert len(lines) == 1, lines
    d = json.loads(lines[0])
    assert 'SIGTERM' in d.get('error', ''), d
    assert d['extra']['overrides']['iters'] == 100
    # the checkpoint file exists from the pre-probe seed at minimum
    assert (tmp_path / 'partial.json').exists()
