"""Axis-aware K-FAC on composed meshes (kfac_pytorch_tpu/meshplan).

Spec grammar, rule matching and the analytic per-axis comm volume are
pure-python. The parity tests feed ORACLE capture operands (acts/gs/
grads as explicit shard_map inputs) into ``pre.step`` — the backend's
in-body shard_map autodiff is unusable here (see tests/test_tp.py), and
the preconditioner's own collectives are forward-only and exact — and
assert the composed dp×tp / dp×ep preconditioned step BITWISE equal to
the dp-only reference, plus axis-aware replan round-trips carrying the
factor EMAs row-exact."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from kfac_pytorch_tpu import meshplan as mp
from kfac_pytorch_tpu.capture import LayerMeta
from kfac_pytorch_tpu.parallel import mesh as meshlib
from kfac_pytorch_tpu.parallel import moe, tp
from kfac_pytorch_tpu.preconditioner import KFAC

ND, B = 2, 8


# ---------------------------------------------------------------------------
# spec grammar + rules (pure python)
# ---------------------------------------------------------------------------

def test_parse_mesh_spec_grammar():
    axes = mp.parse_mesh_spec('dp2xtp4')
    assert [(a.name, a.size, a.role) for a in axes] == [
        ('data', 2, 'data'), ('model', 4, 'tensor')]
    axes = mp.parse_mesh_spec('dp2xsp2xtp2xep1xpp1=stages')
    assert [a.role for a in axes] == [
        'data', 'sequence', 'tensor', 'expert', 'pipeline']
    assert axes[-1].name == 'stages'
    assert mp.world_size(axes) == 4          # data x sequence only
    assert mp.total_devices(axes) == 8       # every axis
    assert mp.data_axis_names(axes) == ('data', 'seq')
    # round-trip through format
    assert mp.parse_mesh_spec(mp.format_mesh_spec(axes)) == axes
    # AxisSpec tuples pass through (and re-validate)
    assert mp.parse_mesh_spec(axes) == axes


@pytest.mark.parametrize('bad', [
    'tp2',                # no data/sequence axis
    'dp2xtp2xtp2',        # duplicate axis name
    'dp2xtp2xtp2=m2',     # two tensor axes
    'dp2xzz2',            # unknown tag
    'dp0',                # non-positive size
])
def test_parse_mesh_spec_rejects(bad):
    with pytest.raises(ValueError):
        mp.parse_mesh_spec(bad)


def test_layer_axis_rule_validation():
    with pytest.raises(ValueError):
        mp.LayerAxisRule(pattern='x', a_roles=('data',))
    with pytest.raises(ValueError):
        mp.LayerAxisRule(pattern='x', local_roles=('tensor',))
    # reducing factors over expert/pipeline is never legal
    with pytest.raises(ValueError):
        mp.LayerAxisRule(pattern='x', a_roles=('expert',))


def test_default_rules_match_megatron_names():
    rules = mp.default_rules()
    col = mp.match_rule(rules, 'self_attn/w_q/slice')
    assert col is not None and col.a_roles == ('tensor',) \
        and col.g_roles == ()
    row = mp.match_rule(rules, 'ffn/w_2/slice')
    assert row is not None and row.g_roles == ('tensor',) \
        and row.a_roles == ()
    exp = mp.match_rule(rules, 'expert/w_in')
    assert exp is not None and exp.local_roles == ('expert',)
    assert mp.match_rule(rules, 'head') is None
    # first match wins
    first = mp.LayerAxisRule(pattern='w_q', g_roles=('tensor',))
    assert mp.match_rule((first,) + rules, 'self_attn/w_q/slice') is first


# ---------------------------------------------------------------------------
# shared oracle fixtures
# ---------------------------------------------------------------------------

def _dense(name, din, dout):
    return LayerMeta(name=name, path=tuple(name.split('/')), kind='dense',
                     use_bias=True, in_dim=din + 1, out_dim=dout,
                     kernel_shape=(din, dout))


def _tp_metas():
    return {('l1', 'slice'): _dense('l1/slice', 6, 4),
            ('l2', 'slice'): _dense('l2/slice', 4, 5)}


def _moe_metas():
    return {('expert', 'w_in'): _dense('expert/w_in', 6, 4),
            ('expert', 'w_out'): _dense('expert/w_out', 4, 5)}


def _oracle_inputs(metas, seed=0, lead=(ND,)):
    """Per-data-rank capture operands with leading dims ``lead``."""
    rng = np.random.RandomState(seed)

    def arr(*shape):
        return jnp.asarray(rng.randn(*(lead + shape)), jnp.float32)

    acts, gs, grads = {}, {}, {}
    for path, m in metas.items():
        din, dout = m.kernel_shape
        node_a = acts
        node_g = gs
        node_gr = grads
        for k in path[:-1]:
            node_a = node_a.setdefault(k, {})
            node_g = node_g.setdefault(k, {})
            node_gr = node_gr.setdefault(k, {})
        node_a[path[-1]] = {'a': arr(B, din)}
        node_g[path[-1]] = {'g': arr(B, dout)}
        node_gr[path[-1]] = {'kernel': arr(din, dout), 'bias': arr(dout)}
    return acts, gs, grads


TP_RULES = tp.axis_rules(column=('l1',), row=('l2',))
MOE_RULES = moe.axis_rules(experts=('expert',))


# ---------------------------------------------------------------------------
# plan construction + analytic comm volume (pure python)
# ---------------------------------------------------------------------------

def test_build_mesh_plan_tensor_rows_and_dp_degenerate():
    from kfac_pytorch_tpu.plan import build_plan, same_row_layout
    metas = _tp_metas()
    plan = mp.build_mesh_plan(metas, 'dp2xtp2', comm_mode='inverse',
                              rules=TP_RULES)
    # column layer contributes its A row, row layer its G row
    assert plan.tensor_reduce_rows('model') == 2
    marked = {r for rws in plan.tensor_rows['model'].values() for r in rws}
    assert len(marked) == 2
    # the base plan IS the dp-only plan over the data world
    ref = build_plan(metas, num_devices=2, comm_mode='inverse')
    assert same_row_layout(plan.base, ref)
    assert plan.world_size == 2 and plan.axis_name == 'data'


def test_comm_volume_per_axis_analytic():
    metas = _tp_metas()
    # no captured layer matches an expert-local rule here: the plan
    # builds (expert-replicated fallback) but says so out loud
    with pytest.warns(UserWarning, match='expert axis'):
        plan = mp.build_mesh_plan(metas, 'dp2xtp2xep1xpp1',
                                  comm_mode='inverse',
                                  rules=TP_RULES + MOE_RULES)
    vol = plan.comm_volume(stats_reduce='mean', method='eigh')
    # tensor axis: ONLY FactorComm, bytes = sum over marked rows of D^2*4
    want = sum(bdim * bdim * 4 * len(rws)
               for bdim, rws in plan.tensor_rows['model'].items())
    assert vol['model']['FactorComm'] == want > 0
    assert all(v == 0 for k, v in vol['model'].items()
               if k != 'FactorComm')
    # expert/pipeline axes: zero factor bytes by construction
    assert all(v == 0 for v in vol['expert'].values())
    assert all(v == 0 for v in vol['stage'].values())
    # bf16 wire halves the tensor payload
    vol16 = plan.comm_volume(stats_reduce='mean', method='eigh',
                             comm_precision='bf16')
    assert vol16['model']['FactorComm'] * 2 == want


def test_extra_reduce_env_knob(monkeypatch):
    plan = mp.build_mesh_plan(_tp_metas(), 'dp2xtp2', comm_mode='inverse',
                              rules=TP_RULES)
    assert plan.extra_reduce()          # live by default
    monkeypatch.setenv('KFAC_MESH_TP_REDUCE', '0')
    assert plan.extra_reduce() == ()


def test_stage_partition():
    metas = _tp_metas()
    s0 = mp.stage_partition(metas, 2, 0)
    s1 = mp.stage_partition(metas, 2, 1)
    assert set(s0) | set(s1) == set(metas) and not set(s0) & set(s1)
    explicit = mp.stage_partition(metas, 2, 1,
                                  stage_of=lambda name: 1)
    assert set(explicit) == set(metas)
    with pytest.raises(ValueError):
        mp.stage_partition(metas, 2, 0, stage_of=lambda name: 1)


# ---------------------------------------------------------------------------
# KFAC wiring
# ---------------------------------------------------------------------------

def test_kfac_mesh_axes_derives_world():
    pre = KFAC(variant='eigen', mesh_axes='dp2xtp2', mesh_rules=TP_RULES)
    assert pre.num_devices == 2 and pre.axis_name == 'data'
    with pytest.raises(ValueError):
        KFAC(variant='eigen', mesh_axes='dp2xtp2', num_devices=4)
    with pytest.raises(ValueError):
        KFAC(variant='eigen', mesh_axes='dp2xtp2', axis_name='batch')
    with pytest.raises(ValueError):
        KFAC(variant='eigen', mesh_rules=TP_RULES)  # rules without mesh


def _mesh_step(pre, mesh, n_extra, grads, acts, gs):
    """One preconditioned step with oracle operands; state replicated
    over every non-data mesh axis, inputs sharded over all axes."""
    kspecs = pre.state_pspecs()
    names = tuple(n for n, _ in mesh.shape.items())
    lead = len(names)
    io_spec = P(*names)

    @functools.partial(jax.shard_map, mesh=mesh,
                       in_specs=(kspecs, io_spec, io_spec, io_spec),
                       out_specs=(io_spec, kspecs))
    def step(kstate, grads, acts, gs):
        def sq(t):
            return jax.tree.map(
                lambda a: a.reshape(a.shape[lead:]), t)
        g2, st2 = pre.step(kstate, sq(grads), sq(acts), sq(gs))
        exp = lambda t: jax.tree.map(  # noqa: E731
            lambda a: a.reshape((1,) * lead + a.shape), t)
        return exp(g2), st2

    return step(pre.init(), grads, acts, gs)


def _dup(tree, axis, n):
    """Tile a leading-[data,...] tree with an extra mesh axis."""
    return jax.tree.map(
        lambda a: jnp.broadcast_to(
            jnp.expand_dims(a, axis),
            a.shape[:axis] + (n,) + a.shape[axis:]), tree)


def _dp_reference(metas, grads, acts, gs, variant='eigen'):
    pre = KFAC(variant=variant, lr=0.1, damping=0.01,
               num_devices=ND, axis_name='data')
    pre.setup(metas)
    mesh = meshlib.make_mesh(ND, axis_name='data')
    return _mesh_step(pre, mesh, 0, grads, acts, gs)


def test_dp_only_mesh_spec_bit_identical_to_legacy():
    """KFAC(mesh_axes='dp2') is the SAME preconditioner as the legacy
    KFAC(num_devices=2, axis_name='data') — bitwise, grads and state."""
    metas = _tp_metas()
    acts, gs, grads = _oracle_inputs(metas)
    gref, stref = _dp_reference(metas, grads, acts, gs)

    pre = KFAC(variant='eigen', lr=0.1, damping=0.01, mesh_axes='dp2')
    pre.setup(metas)
    mesh, _ = meshlib.make_composed_mesh('dp2')
    got, stc = _mesh_step(pre, mesh, 0, grads, acts, gs)
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)), got, gref)
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)), stc.factors, stref.factors)


def test_composed_dp_tp_parity_bitwise():
    """dp2xtp2 with the tensor-axis factor reduce LIVE: replicated
    slice-capture operands make the pmean an average of identical f32
    values (exact for a power-of-2 world), so the composed step is
    BITWISE the dp-only reference and tp-invariant across model ranks."""
    metas = _tp_metas()
    acts, gs, grads = _oracle_inputs(metas)
    gref, stref = _dp_reference(metas, grads, acts, gs)

    pre = KFAC(variant='eigen', lr=0.1, damping=0.01,
               mesh_axes='dp2xtp2', mesh_rules=TP_RULES)
    pre.setup(metas)
    assert pre.mesh_plan.extra_reduce()   # the reduce is in the trace
    mesh, _ = meshlib.make_composed_mesh('dp2xtp2')
    got, stc = _mesh_step(pre, mesh, 1,
                          _dup(grads, 1, 2), _dup(acts, 1, 2),
                          _dup(gs, 1, 2))
    for (path, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(got),
            jax.tree_util.tree_leaves_with_path(gref)):
        a = np.asarray(a)
        b = np.asarray(b)
        label = jax.tree_util.keystr(path)
        assert np.array_equal(a[:, 0], a[:, 1]), \
            f'{label}: not tp-invariant'
        assert np.array_equal(a[:, 0], b.reshape(a[:, 0].shape)), \
            f'{label}: composed != dp-only'
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)), stc.factors, stref.factors)


def test_composed_dp_ep_owner_local_parity_bitwise():
    """dp2xep2 with PER-EXPERT capture operands: each expert rank's
    preconditioned step must BITWISE equal a dp-only run fed only that
    expert's capture — owner-local factors, zero cross-expert mixing
    (the zero-FactorComm claim, numerically)."""
    metas = _moe_metas()
    NE = 2
    pre = KFAC(variant='eigen', lr=0.1, damping=0.01,
               mesh_axes='dp2xep2', mesh_rules=MOE_RULES)
    pre.setup(metas)
    assert pre.mesh_plan.extra_reduce() == ()   # nothing to reduce
    mesh, _ = meshlib.make_composed_mesh('dp2xep2')

    per_e = [_oracle_inputs(metas, seed=10 + e) for e in range(NE)]
    stack = lambda i: jax.tree.map(  # noqa: E731
        lambda *a: jnp.stack(a, axis=1), *[pe[i] for pe in per_e])
    acts, gs, grads = stack(0), stack(1), stack(2)
    got, _ = _mesh_step(pre, mesh, 1, grads, acts, gs)

    for e in range(NE):
        a_e, g_e, gr_e = per_e[e]
        want, _ = _dp_reference(metas, gr_e, a_e, g_e)
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a)[:, e],
                np.asarray(b).reshape(np.asarray(a)[:, e].shape)),
            got, want)


# ---------------------------------------------------------------------------
# axis-aware replan round-trips
# ---------------------------------------------------------------------------

def _factor_leaves(state):
    return [np.asarray(x) for x in jax.tree_util.tree_leaves(state.factors)]


@pytest.mark.parametrize('spec,rules', [
    ('dp2xtp2', TP_RULES),
    ('dp2xep2', MOE_RULES),
])
def test_replan_composed_to_dp_round_trip(spec, rules):
    """dp×tp→dp and dp×ep→dp keep the data world, so the factor EMAs
    carry ROW-EXACT through replan — and the round trip back restores
    the composed plan with the state again untouched."""
    metas = _tp_metas() if 'tp' in spec else _moe_metas()
    acts, gs, grads = _oracle_inputs(metas)
    pre = KFAC(variant='eigen', lr=0.1, damping=0.01,
               mesh_axes=spec, mesh_rules=rules)
    pre.setup(metas)
    mesh, _ = meshlib.make_composed_mesh(spec)
    _, st = _mesh_step(pre, mesh, 1,
                       _dup(grads, 1, 2), _dup(acts, 1, 2),
                       _dup(gs, 1, 2))
    before = _factor_leaves(st)

    carried = pre.replan(st, mesh_axes='dp2')
    assert pre.mesh_axes is not None and len(pre.mesh_axes) == 1
    assert pre.mesh_plan.extra_reduce() == ()
    for a, b in zip(before, _factor_leaves(carried)):
        np.testing.assert_array_equal(a, b)

    back = pre.replan(carried, mesh_axes=spec)
    assert [x.name for x in pre.mesh_axes] == \
        [x.name for x in mp.parse_mesh_spec(spec)]
    for a, b in zip(before, _factor_leaves(back)):
        np.testing.assert_array_equal(a, b)

    cleared = pre.replan(back, mesh_axes=None)
    assert pre.mesh_axes is None and pre.mesh_plan is None
    for a, b in zip(before, _factor_leaves(cleared)):
        np.testing.assert_array_equal(a, b)


def test_replan_mesh_axes_exclusive_with_world_args():
    pre = KFAC(variant='eigen', mesh_axes='dp2xtp2', mesh_rules=TP_RULES)
    pre.setup(_tp_metas())
    with pytest.raises(ValueError):
        pre.replan(num_devices=4)       # resize goes through mesh_axes
    with pytest.raises(ValueError):
        pre.replan(mesh_axes='dp4', num_devices=4)
