"""Live replanning (ISSUE 14): rebuild FactorPlan/KFACState mid-run.

The invariants the acceptance criteria name:

  - replan-to-identical-plan is a bit-identical no-op on the whole
    params/opt/factor pytree (the verbatim carry path);
  - an eigen <-> inverse_dp round trip preserves the factor EMAs (and,
    for a pure comm-mode round trip on a lossy wire, the EF residual)
    exactly — decompositions rebuild across a method change through
    the trainer's re-armed seen-inverse gate;
  - the arbiter's comm_mode commit is APPLIED (a queued replan the
    trainer swaps in between steps) and the variant cache invalidates
    exactly once per switch;
  - replan during stagger rebuilds the cohort tables (per-bucket
    cadence overrides land in plan.build_cohorts' bucket_freq) without
    a same-step consumer — training continues preconditioned;
  - elastic_resume routes the cross-world transport through replan,
    carrying the decompositions (same method) so the relaunch resumes
    preconditioning immediately.

NOTE on cross-MODE numerics: the two comm modes are the same
algorithm (world=1 is pinned bit-identical below). At world>1 their
trajectories are only float-equal on a backend whose data-parallel
gradient psum is healthy — this container's is not (the documented
seed 'distributed' env failures), so the multi-device tests here pin
layout/state/plumbing invariants, never cross-mode trajectories.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh

import kfac_pytorch_tpu as kfac
from kfac_pytorch_tpu import autotune, plan as kplan, training
from kfac_pytorch_tpu import utils as kutils
from tests.helpers import TinyCNN

pytestmark = pytest.mark.core

B, HW = 8, 8


def _batch(seed=0):
    rng = np.random.RandomState(seed)
    return {'input': jnp.asarray(rng.randn(B, HW, HW, 3), jnp.float32),
            'label': jnp.asarray(rng.randint(0, 10, B))}


def _ce(outputs, batch):
    return optax.softmax_cross_entropy_with_integer_labels(
        outputs, batch['label']).mean()


def _make(nd, model, variant='eigen_dp', comm_mode=None, **kw):
    axis = 'batch' if nd > 1 else None
    mesh = (Mesh(np.array(jax.devices()[:nd]), ('batch',)) if nd > 1
            else None)
    pre = kfac.KFAC(variant=variant, lr=0.1, damping=0.003,
                    fac_update_freq=1, kfac_update_freq=2,
                    num_devices=nd, axis_name=axis, comm_mode=comm_mode,
                    **kw)
    tx = training.sgd(0.1, momentum=0.9)
    state = training.init_train_state(model, tx, pre,
                                      jax.random.PRNGKey(0),
                                      _batch()['input'])
    step = training.build_train_step(model, tx, pre, _ce,
                                     axis_name=axis, mesh=mesh,
                                     donate=False)
    return pre, state, step


def _run(step, state, n, start=0):
    for i in range(start, start + n):
        state, m = step(state, _batch(i), lr=0.1, damping=0.003)
    return state, float(m['loss'])


def _tree_equal(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# ---------------------------------------------------------------------------
# ctor override + world=1 mode equivalence
# ---------------------------------------------------------------------------

def test_ctor_comm_mode_override_and_validation():
    pre = kfac.KFAC(variant='eigen_dp', num_devices=2, axis_name='batch',
                    comm_mode='inverse')
    assert pre.comm_mode == 'inverse'
    pre2 = kfac.KFAC(variant='eigen', num_devices=2, axis_name='batch',
                     comm_mode='pred')
    assert pre2.comm_mode == 'pred'
    with pytest.raises(ValueError, match='comm_mode'):
        kfac.KFAC(variant='eigen_dp', comm_mode='sideways')
    # comm_prefetch needs the inverse road — a pred override must fail
    # at construction, not at trace time
    with pytest.raises(ValueError, match='comm_prefetch'):
        kfac.KFAC(variant='eigen', comm_mode='pred', comm_prefetch=True)
    # review regression: the eigen auto-distribute rule must collapse
    # under a pred override (world > #layers used to crash setup — and
    # the adopted-knobs relaunch chain can construct exactly this)
    from kfac_pytorch_tpu import capture
    import flax.linen as linen
    from kfac_pytorch_tpu import nn as knn

    class TwoMLP(linen.Module):
        @linen.compact
        def __call__(self, x, train=True):
            x = x.reshape((x.shape[0], -1))
            x = linen.relu(knn.Dense(7, name='d0')(x))
            return knn.Dense(5, name='out')(x)

    m = TwoMLP()
    x = jnp.zeros((8, 6), jnp.float32)
    variables = capture.init(m, jax.random.PRNGKey(0), x)
    metas = capture.collect_layer_meta(m, variables, x)
    pre_p = kfac.KFAC(variant='eigen', comm_mode='pred', num_devices=4,
                      axis_name='batch')
    pre_p.setup(metas)
    assert pre_p._distributed is False
    pre_i = kfac.KFAC(variant='eigen', num_devices=4, axis_name='batch')
    pre_i.setup(metas)
    assert pre_i._distributed is True   # the auto rule still fires


def test_world1_modes_bit_identical():
    """The two comm modes are one algorithm: at world=1 (no
    collectives) the trajectories must agree bit-for-bit."""
    model = TinyCNN(batch_norm=False)
    out = {}
    for mode in ('pred', 'inverse'):
        pre, state, step = _make(1, model, comm_mode=mode)
        state, loss = _run(step, state, 4)
        out[mode] = (loss, jax.device_get(state.params))
    assert out['pred'][0] == out['inverse'][0]
    _tree_equal(out['pred'][1], out['inverse'][1])


# ---------------------------------------------------------------------------
# the replan invariants
# ---------------------------------------------------------------------------

def test_replan_to_identical_plan_is_bitwise_noop():
    """Same comm mode, same world, same overrides -> the VERBATIM carry
    path: the returned state is the input state (not one byte moved),
    no invalidator fires, and continuing the run is bit-identical to a
    control that never replanned."""
    model = TinyCNN(batch_norm=False)
    pre, state, step = _make(2, model)
    prec, statec, stepc = _make(2, model)
    state, _ = _run(step, state, 3)
    statec, _ = _run(stepc, statec, 3)
    fired = []
    autotune.arbiter_for(pre).add_invalidator(lambda: fired.append(1))
    nvars = len(step.variants)
    carried = pre.replan(state.kfac_state, comm_mode=pre.comm_mode)
    assert carried is state.kfac_state      # verbatim, same arrays
    assert not fired                        # nothing trace-affecting
    assert len(step.variants) == nvars      # cache untouched
    state, loss = _run(step, state, 3, start=3)
    statec, lossc = _run(stepc, statec, 3, start=3)
    assert loss == lossc
    _tree_equal(jax.device_get(state.params), jax.device_get(statec.params))
    _tree_equal(jax.device_get(state.opt_state),
                jax.device_get(statec.opt_state))
    _tree_equal(jax.device_get(state.kfac_state.factors),
                jax.device_get(statec.kfac_state.factors))


def test_pure_comm_mode_roundtrip_carries_state_verbatim():
    """eigen (pmean, eigh) on a lossy bf16 wire: a pred round trip
    keeps the SAME row layout, method and EF tracking — both replans
    take the verbatim path, so factors, decompositions AND the
    comm_err residual come back bit-identical (the 'preserves
    EMAs/EF residuals exactly' criterion)."""
    model = TinyCNN(batch_norm=False)
    pre, state, step = _make(2, model, variant='eigen',
                             comm_precision='bf16')
    state, _ = _run(step, state, 4)
    k0 = jax.device_get(state.kfac_state)
    assert k0.comm_err is not None
    assert any(np.any(np.asarray(v)) for v in k0.comm_err.values())
    k1 = pre.replan(state.kfac_state, comm_mode='pred')
    assert pre.comm_mode == 'pred' and pre.plan.comm_mode == 'pred'
    assert k1 is state.kfac_state           # layout unchanged: verbatim
    k2 = pre.replan(k1, comm_mode='inverse')
    assert pre.plan.comm_mode == 'inverse'
    _tree_equal(jax.device_get(k2), k0)


def test_variant_roundtrip_preserves_factor_emas_exactly():
    """eigen -> inverse_dp -> eigen: the cross-METHOD round trip. The
    factor EMAs (the state that takes thousands of steps to rebuild)
    and the step counter survive exactly; the decomposition structure
    flips eigh <-> cholesky and rebuilds from the carried factors."""
    model = TinyCNN(batch_norm=False)
    pre, state, step = _make(2, model, variant='eigen')
    state, _ = _run(step, state, 4)
    k0 = jax.device_get(state.kfac_state)
    k1 = pre.replan(state.kfac_state, variant='inverse_dp')
    assert (pre.variant, pre.method, pre.stats_reduce, pre.comm_mode) \
        == ('inverse_dp', 'cholesky', 'local', 'pred')
    assert 'invs' in k1.decomp and 'evals' not in k1.decomp
    # cross-method: decompositions restart from zero, factors carried
    assert all(not np.any(np.asarray(v))
               for v in k1.decomp['invs'].values())
    k2 = pre.replan(k1, variant='eigen')
    assert (pre.variant, pre.method, pre.comm_mode) \
        == ('eigen', 'eigh', 'inverse')
    assert int(k2.step) == int(k0.step)
    _tree_equal(jax.device_get(k2.factors), k0.factors)


def test_trainer_rearms_after_cross_method_replan():
    """After a method-changing replan zeroes the decomposition, the
    invalidator re-arms the trainer's seen-inverse gate: gradients pass
    through (factors still accumulate) until the next inverse refresh
    rebuilds the decomposition from the carried EMAs — then training
    is preconditioned again and stays finite."""
    model = TinyCNN(batch_norm=False)
    pre, state, step = _make(2, model, variant='eigen')
    state, _ = _run(step, state, 4)
    carried = pre.replan(state.kfac_state, variant='inverse_dp')
    assert step.variants == {}              # invalidated exactly here
    state = state.replace(kfac_state=carried)
    state, loss = _run(step, state, 4, start=4)
    assert np.isfinite(loss)
    assert any(np.any(np.asarray(v) != 0)
               for v in jax.device_get(
                   state.kfac_state).decomp['invs'].values())


def test_arbiter_comm_mode_commit_applies_with_one_invalidation():
    """The acceptance criterion: a KnobArbiter comm_mode commit is an
    APPLIED switch — the attribute flips, a replan is queued, the
    variant cache invalidates exactly once, and the next dispatch
    swaps the plan in and keeps training on the carried state."""
    model = TinyCNN(batch_norm=False)
    pre, state, step = _make(2, model)
    state, _ = _run(step, state, 3)
    arb = autotune.arbiter_for(pre)
    fired = []
    arb.add_invalidator(lambda: fired.append(1))
    arb.propose('tuner', comm_mode='inverse')
    assert pre.comm_mode == 'inverse'
    assert pre.pending_replan is not None
    assert pre.plan.comm_mode == 'pred'     # swap deferred to the step
    assert len(fired) == 1
    state, loss = _run(step, state, 3, start=3)
    assert np.isfinite(loss)
    assert pre.pending_replan is None
    assert pre.plan.comm_mode == 'inverse'
    assert len(fired) == 1                  # exactly once per switch
    # re-proposing the same mode is a no-op: no second invalidation
    arb.propose('tuner', comm_mode='inverse')
    assert len(fired) == 1 and pre.pending_replan is None
    # and back: a second switch fires exactly one more
    arb.propose('tuner', comm_mode='pred')
    state, loss = _run(step, state, 3, start=6)
    assert np.isfinite(loss)
    assert pre.plan.comm_mode == 'pred' and len(fired) == 2


def test_replan_during_stagger_rebuilds_cohorts_with_bucket_overrides():
    """Per-bucket cadence (ISSUE 14 satellite b): a replan with
    bucket_overrides rebuilds the cohort tables through rebase_cohorts
    — the stretched bucket's rows refresh every base*m steps, the
    window expands, the carried decomposition keeps preconditioning
    (no factors_only relapse), and training stays finite."""
    model = TinyCNN(batch_norm=False)
    pre, state, step = _make(2, model, variant='eigen_dp', stagger=True)
    state, _ = _run(step, state, 4)         # past the first full decomp
    base_f = pre.cohorts.base_freq
    assert pre.cohorts.bucket_freq == {}
    big = max(pre.plan.bucket_dims)
    carried = pre.replan(state.kfac_state, bucket_overrides={big: 2})
    assert carried is state.kfac_state      # layout unchanged: verbatim
    assert pre.bucket_stagger_freq == {big: 2}
    layout = pre.cohorts
    assert layout.base_freq == base_f
    assert layout.bucket_freq == {big: 2}
    assert layout.num_cohorts == 2 * base_f
    # the stretched bucket's rows appear with period base*2, others base
    for bdim in pre.plan.bucket_dims:
        period = base_f * (2 if bdim == big else 1)
        rows, valid = layout.rows[bdim], layout.valid[bdim]
        for d in range(pre.plan.num_devices):
            seen = {}
            for f in range(layout.num_cohorts):
                for j in range(rows.shape[2]):
                    if valid[f, d, j]:
                        seen.setdefault(int(rows[f, d, j]), []).append(f)
            for fs in seen.values():
                gaps = set(np.diff(fs + [fs[0] + layout.num_cohorts]))
                assert gaps == {period}, (bdim, fs, period)
    state = state.replace(kfac_state=carried)
    state, loss = _run(step, state, 2 * layout.num_cohorts, start=4)
    assert np.isfinite(loss)
    # clearing the overrides restores the uniform window
    pre.replan(state.kfac_state, bucket_overrides={})
    assert pre.cohorts.num_cohorts == base_f


def test_bucket_overrides_validation():
    model = TinyCNN(batch_norm=False)
    pre, state, _ = _make(2, model)
    with pytest.raises(ValueError, match='stagger'):
        pre.replan(state.kfac_state, bucket_overrides={128: 2})
    pre_s, state_s, step_s = _make(2, model, stagger=True)
    with pytest.raises(ValueError, match='>= 1'):
        pre_s.replan(state_s.kfac_state,
                     bucket_overrides={pre_s.plan.bucket_dims[0]: 0})
    with pytest.raises(ValueError, match='powers of two'):
        pre_s.replan(state_s.kfac_state,
                     bucket_overrides={pre_s.plan.bucket_dims[0]: 3})
    with pytest.raises(ValueError, match='unknown bucket'):
        kplan.build_cohorts(pre_s.plan, 2, bucket_freq={7: 2})
    plan_before = pre_s.plan
    with pytest.raises(ValueError, match='unknown bucket'):
        # rejected BEFORE the atomic commit: a bad dim failing inside a
        # later lazy rebase would wedge every staggered dispatch
        pre_s.replan(state_s.kfac_state, bucket_overrides={999: 2})
    assert pre_s.plan is plan_before and pre_s.bucket_stagger_freq == {}
    state_s, loss = _run(step_s, state_s, 2)   # still trains
    assert np.isfinite(loss)
    with pytest.raises(ValueError, match='window'):
        kplan.build_cohorts(pre_s.plan, 2,
                            bucket_freq={pre_s.plan.bucket_dims[0]: 3,
                                         pre_s.plan.bucket_dims[1]: 7,
                                         pre_s.plan.bucket_dims[2]: 11}
                            if len(pre_s.plan.bucket_dims) >= 3 else
                            {pre_s.plan.bucket_dims[0]: 129 * 2})


def test_replan_num_devices_transports_like_reshard():
    """The elastic lane: replan(num_devices=) equals
    reshard_kfac_state(carry_decomp=True) — factors by the per-layer
    remap, decompositions carried row-for-row (same method), new pad
    rows at the zero init."""
    model = TinyCNN(batch_norm=False)
    pre2, state2, step2 = _make(2, model, variant='eigen')
    pre4, _, _ = _make(4, model, variant='eigen')
    state2, _ = _run(step2, state2, 4)
    # an independent expectation from the transport primitive
    want = kutils.reshard_kfac_state(pre2, pre4, state2.kfac_state,
                                     carry_decomp=True)
    pre_t, _, _ = _make(2, model, variant='eigen')
    got = pre_t.replan(jax.device_get(state2.kfac_state),
                       num_devices=4, axis_name='batch')
    assert pre_t.num_devices == 4
    assert kplan.same_row_layout(pre_t.plan, pre4.plan)
    _tree_equal(jax.device_get(got), jax.device_get(want))
    # the carried decomposition is live, not zeroed
    assert any(np.any(np.asarray(v) != 0)
               for v in jax.device_get(got).decomp['evals'].values())


def test_elastic_resume_routes_through_replan(tmp_path, monkeypatch):
    """elastic_resume's cross-world transport now rides replan: the
    restored state carries the decomposition (same method), so the
    relaunched world preconditions immediately instead of passing
    gradients through until the next refresh."""
    from kfac_pytorch_tpu import resilience
    from kfac_pytorch_tpu.utils import checkpoint as ckpt
    monkeypatch.setattr(ckpt, '_HAS_ORBAX', False)
    model = TinyCNN(batch_norm=False)
    pre2, state2, step2 = _make(2, model, variant='eigen')
    state2, _ = _run(step2, state2, 3)
    ckpt.save_checkpoint(tmp_path, 0, state2)
    ckpt.write_world_stamp(tmp_path, 2)
    pre4, state4, step4 = _make(4, model, variant='eigen')

    def make_old(nd):
        pre = kfac.KFAC(variant='eigen', lr=0.1, damping=0.003,
                        fac_update_freq=1, kfac_update_freq=2,
                        num_devices=nd,
                        axis_name='batch' if nd > 1 else None)
        pre.setup(pre4.plan.metas)
        return pre

    restored, epoch, old_world = resilience.elastic_resume(
        tmp_path, 5, pre4, state4, make_precond=make_old)
    assert epoch == 0 and old_world == 2
    want = kutils.reshard_kfac_state(pre2, pre4, state2.kfac_state,
                                     carry_decomp=True)
    _tree_equal(jax.device_get(restored.kfac_state),
                jax.device_get(want))
    assert any(np.any(np.asarray(v) != 0)
               for v in jax.device_get(
                   restored.kfac_state).decomp['evals'].values())
    # and training continues in the grown world, preconditioned from
    # the first post-resume step (seen-inverse derives True from the
    # carried decomposition)
    state, loss = _run(step4, restored, 2, start=3)
    assert np.isfinite(loss)


def test_replan_validation_rules():
    model = TinyCNN(batch_norm=False)
    pre, state, _ = _make(2, model, variant='eigen',
                          comm_prefetch=True)
    with pytest.raises(ValueError, match='comm_prefetch'):
        pre.replan(state.kfac_state, comm_mode='pred')
    pre_ns, state_ns, _ = _make(2, model, variant='inverse_dp',
                                decomp_impl='newton_schulz')
    with pytest.raises(ValueError, match='newton_schulz'):
        pre_ns.replan(state_ns.kfac_state, variant='eigen')
    with pytest.raises(KeyError):
        pre.replan(state.kfac_state, variant='nope')
    with pytest.raises(ValueError, match='num_devices'):
        pre.replan(state.kfac_state, num_devices=0)


# ---------------------------------------------------------------------------
# the controller rung + the adopted-knob carry
# ---------------------------------------------------------------------------

def test_controller_comm_mode_candidates_gated():
    """The comm_mode rung exists only where the replan path does: a
    meshed, set-up, non-ekfac preconditioner; the analytic prior
    orders the preferred mode first."""
    model = TinyCNN(batch_norm=False)
    pre, state, step = _make(2, model)
    ctl = autotune.KnobController(pre, window=4, settle=0,
                                  tune=('comm_mode',))
    cands = ctl._candidates()
    assert ('comm_mode', 'pred', 'inverse') in cands
    # prior ordering: force a choice and check it leads
    ctl.comm_mode_choice = 'inverse'
    assert ctl._candidates()[0] == ('comm_mode', 'pred', 'inverse')
    # world=1 (no axis): no comm_mode candidates
    pre1, _, _ = _make(1, model)
    ctl1 = autotune.KnobController(pre1, window=4, settle=0,
                                   tune=('comm_mode',))
    assert ctl1._candidates() == []


def test_adopted_knobs_export_and_requeue_overlay(tmp_path):
    """The kfac-serve carry (PR 10 follow-on): the controller's
    adopted-knobs.json snapshot, filtered through the spec grammar,
    lands in the requeued record and overlays the relaunch argv."""
    # 1) the controller writes the snapshot next to its decision log
    pre = kfac.KFAC(variant='eigen_dp', fac_update_freq=1,
                    kfac_update_freq=4, num_devices=1)
    ctl = autotune.KnobController(
        pre, window=2, settle=0, tune=('kfac_update_freq',),
        decision_log=str(tmp_path / 'trace' / 'decisions.jsonl'))
    ctl.arbiter.propose('tuner', kfac_update_freq=8)
    ctl._decision('commit', knob='kfac_update_freq', frm=4, to=8)
    doc = json.loads((tmp_path / 'trace'
                      / autotune.ADOPTED_KNOBS_FILENAME).read_text())
    assert doc['kfac_update_freq'] == 8
    assert doc['kfac_comm_mode'] == 'pred'
    assert set(doc) <= {f for f in autotune.ADOPTED_KNOB_FLAGS.values()}
    # every exported name is spec-valid (submit-time grammar lockstep)
    from kfac_pytorch_tpu.service.spec import KFAC_KNOBS
    assert set(autotune.ADOPTED_KNOB_FLAGS.values()) <= KFAC_KNOBS

    # 2) the scheduler overlays the adopted knobs into the relaunch argv
    from kfac_pytorch_tpu.service.spec import validate_spec
    spec = validate_spec({'tenant': 'alice', 'trainer': 'cifar10_resnet',
                          'knobs': {'kfac_update_freq': 4}})
    spec.knobs.update({k: v for k, v in doc.items()})
    argv = spec.trainer_argv()
    i = argv.index('--kfac-update-freq')
    assert argv[i + 1] == '8'
    assert '--kfac-comm-mode' in argv
    assert argv[argv.index('--kfac-comm-mode') + 1] == 'pred'


def test_scheduler_requeue_carries_adopted_knobs(tmp_path):
    """End-to-end through the AdmissionController: a running job's
    trace dir gains adopted-knobs.json, the job dies, the requeue
    stores the snapshot on the record, and the relaunch argv runs at
    the adopted cadence."""
    import logging
    import time as _time
    from kfac_pytorch_tpu.service.scheduler import AdmissionController

    class _FakeProc:
        _pid = [41000]

        def __init__(self):
            _FakeProc._pid[0] += 1
            self.pid = _FakeProc._pid[0]
            self.rc = None

        def poll(self):
            return self.rc

    class _FakePopen:
        def __init__(self):
            self.launches = []
            self.procs = []

        def __call__(self, argv, env=None, **kw):
            proc = _FakeProc()
            self.launches.append((list(argv), dict(env or {})))
            self.procs.append(proc)
            return proc

    popen = _FakePopen()
    ctl = AdmissionController(
        tmp_path / 'svc', hosts={'h0': 2},
        trainers={'mini': 'tests/chaos_trainer.py'},
        popen=popen, killer=lambda p: None, wall=_time.time,
        backoff_base=0.0, backoff_max=0.0,
        log=logging.getLogger('svc-replan-test'))
    # validated at ingest against the controller's EXTENDED registry
    ctl.queue.submit({'tenant': 'alice', 'trainer': 'mini',
                      'knobs': {'kfac_update_freq': 4}})
    ctl.step()
    assert len(popen.launches) == 1
    argv0 = popen.launches[0][0]
    assert argv0[argv0.index('--kfac-update-freq') + 1] == '4'
    run = next(iter(ctl.running.values()))
    trace = run.ns['trace']
    with open(f'{trace}/{autotune.ADOPTED_KNOBS_FILENAME}', 'w') as f:
        json.dump({'kfac_update_freq': 16, 'kfac_comm_mode': 'inverse',
                   'not_a_knob': 'ignored', 'kfac_stagger': True}, f)
    popen.procs[0].rc = 113                 # crash -> budgeted requeue
    ctl.step()                              # reap + requeue + re-admit
    rec = next(iter(ctl.queue.jobs()))
    assert rec['adopted_knobs'] == {'kfac_update_freq': 16,
                                    'kfac_comm_mode': 'inverse'}
    assert len(popen.launches) == 2
    argv1 = popen.launches[-1][0]
    assert argv1[argv1.index('--kfac-update-freq') + 1] == '16'
    assert argv1[argv1.index('--kfac-comm-mode') + 1] == 'inverse'
    ctl.stop()
