"""The durable checkpoint plane's object store (kfac_pytorch_tpu/store/).

Pins the tentpole contracts with NO jax and no subprocesses (the
real-process store-chaos drill lives in CI):

1. Both backends honor the primitive contract — whole-object get/put,
   head, prefix list, preconditioned puts (create-only / replace-exact
   / ANY) where a conflict is an ANSWER (None), not an error — and
   generations are CONTENT HASHES, so the same bytes carry the same
   token on the posix store and on the HTTP store (what lets
   kfac-ckpt-verify repair from a mirror by token equality).
2. Torn uploads are atomic: a put that dies mid-stream commits NOTHING
   — a reader sees the old object or none, never a partial.
3. Ack-lost puts replay idempotently: the HTTP server's token memory
   answers the retry with the ORIGINAL success, so a create-only put
   whose ack was lost never self-conflicts.
4. ChaosStore's fault schedule is a pure function of
   (seed, op, key, attempt) — identical runs, identical traces — and
   the strict faults.from_env surface rejects typo'd drills.
5. RetryingStore rides out transients with bounded jittered backoff,
   counts every retry, and gives up LOUDLY (StoreGiveUp + the
   machine-greppable form that escalates to RC_STORE_LOST=120).
6. The manifest plane: build/parse roundtrip, corrupt-blob
   classification, and the kfac-ckpt-verify scrub repairing from a
   mirror and from an older epoch holding the same content.
"""

import json
import logging
import os

import pytest

from kfac_pytorch_tpu.store import (
    ANY, ChaosStore, HttpStore, PosixStore, RC_STORE_LOST,
    RetryingStore, StoreFaultConfig, StoreGiveUp, StoreHttpServer,
    StoreTimeout, generation_of, store_from_env)
from kfac_pytorch_tpu.store import chaos as store_chaos
from kfac_pytorch_tpu.store import verify as store_verify
from kfac_pytorch_tpu.store.manifest import (
    build_manifest, encode_manifest, manifest_epochs, manifest_key,
    parse_manifest, verify_blob, verify_epoch)
from kfac_pytorch_tpu.resilience.retry import ManualClock, RetryPolicy

pytestmark = pytest.mark.core


@pytest.fixture(scope='module')
def http_server():
    srv = StoreHttpServer('127.0.0.1', 0)
    srv.start()
    yield srv
    srv.stop()


@pytest.fixture(params=['posix', 'http'])
def store(request, tmp_path, http_server):
    if request.param == 'posix':
        yield PosixStore(str(tmp_path / 'root'))
    else:
        s = HttpStore(f'127.0.0.1:{http_server.port}',
                      namespace=str(tmp_path / 'root'))
        yield s
        s.close()


# -- 1. the primitive contract, identically on both backends --------------

def test_put_get_head_roundtrip(store):
    assert store.get('a/b.bin') is None
    assert store.head('a/b.bin') is None
    gen = store.put('a/b.bin', b'payload')
    assert gen == generation_of(b'payload')
    data, got_gen = store.get('a/b.bin')
    assert data == b'payload' and got_gen == gen
    meta = store.head('a/b.bin')
    assert meta.generation == gen and meta.size == len(b'payload')


def test_generations_are_content_hashes_cross_backend(tmp_path,
                                                      http_server):
    posix = PosixStore(str(tmp_path / 'p'))
    http = HttpStore(f'127.0.0.1:{http_server.port}',
                     namespace=str(tmp_path / 'h'))
    try:
        assert posix.put('k', b'hello world') \
            == http.put('k', b'hello world')
    finally:
        http.close()


def test_preconditions_are_answers_not_errors(store):
    gen = store.put('k', b'v1', if_generation=None)   # create-only
    assert gen is not None
    # create-only against an existing object: conflict answer
    assert store.put('k', b'v2', if_generation=None) is None
    # replace-exact with the right token wins...
    gen2 = store.put('k', b'v2', if_generation=gen)
    assert gen2 == generation_of(b'v2')
    # ...and a stale token answers None without clobbering
    assert store.put('k', b'v3', if_generation=gen) is None
    assert store.get('k').data == b'v2'
    # ANY is unconditional
    assert store.put('k', b'v3') == generation_of(b'v3')


def test_list_and_delete_prefix(store):
    for name in ('checkpoint-1.pkl', 'checkpoint-1.manifest.json',
                 'checkpoint-2/a/b.bin', 'other.txt'):
        store.put(name, b'x')
    assert sorted(store.list('checkpoint-1')) == [
        'checkpoint-1.manifest.json', 'checkpoint-1.pkl']
    metas = store.list_meta('checkpoint-2/')
    assert set(metas) == {'checkpoint-2/a/b.bin'}
    assert metas['checkpoint-2/a/b.bin'].size == 1
    assert store.delete('other.txt') is True
    assert store.delete('other.txt') is False   # idempotent
    store.delete_prefix('checkpoint-2/')
    assert store.list('checkpoint-2/') == []
    assert sorted(store.list('')) == [
        'checkpoint-1.manifest.json', 'checkpoint-1.pkl']


def test_bad_keys_rejected(store):
    for bad in ('/abs', 'a/../b', '', 'a//b', '..'):
        with pytest.raises(ValueError):
            store.put(bad, b'x')
        with pytest.raises(ValueError):
            store.get(bad)


def test_dead_http_server_is_a_timeout_not_a_hang():
    s = HttpStore('127.0.0.1:1', namespace='ns', timeout=0.5)
    try:
        with pytest.raises(StoreTimeout):
            s.get('k')
    finally:
        s.close()


def test_http_namespace_isolation(http_server):
    a = HttpStore(f'127.0.0.1:{http_server.port}', namespace='ns-a')
    b = HttpStore(f'127.0.0.1:{http_server.port}', namespace='ns-b')
    try:
        a.put('k', b'from-a')
        assert b.get('k') is None
        assert b.list('') == []
    finally:
        a.close()
        b.close()


# -- 2. torn uploads are atomic -------------------------------------------

def test_torn_upload_commits_nothing(store):
    store.put('k', b'old')
    chaos = ChaosStore(store, StoreFaultConfig(seed=7, torn=1.0))
    with pytest.raises(StoreTimeout):
        chaos.put('k', b'new-longer-payload')
    assert chaos.counts['torn'] == 1
    # the atomicity contract: old object intact, generation unchanged
    blob = store.get('k')
    assert blob.data == b'old' and blob.generation == generation_of(b'old')


def test_http_server_discards_short_body(http_server, tmp_path):
    """A PUT whose connection died mid-body (Content-Length mismatch)
    must be rejected by the server with nothing committed."""
    import http.client
    s = HttpStore(f'127.0.0.1:{http_server.port}',
                  namespace=str(tmp_path / 'torn'))
    try:
        s.put('k', b'old')
        conn = http.client.HTTPConnection(
            '127.0.0.1', http_server.port, timeout=5)
        conn.putrequest('PUT', s._obj_path(s._full('k')))
        conn.putheader('Content-Length', '100')   # promises 100 bytes
        conn.endheaders()
        conn.send(b'partial')                      # delivers 7, dies
        conn.close()
        blob = s.get('k')
        assert blob.data == b'old'
    finally:
        s.close()


# -- 3. ack-lost replay is idempotent -------------------------------------

def _seed_firing_once(op, key, lane, p):
    """A seed whose lane fires on attempt 1 but not attempt 2 — the
    deterministic schedule makes this a pure search, no flakiness."""
    for seed in range(1, 2000):
        cfg = StoreFaultConfig(seed=seed)
        if store_chaos._u(cfg, op, key, 1, lane) < p \
                and store_chaos._u(cfg, op, key, 2, lane) >= p:
            return seed
    raise AssertionError('no such seed in range')


def test_ack_lost_create_only_replay_lands_as_original_success(
        http_server, tmp_path):
    """The commit lands, the ack dies, the retry replays the same
    idempotency token — the server answers the ORIGINAL success
    instead of a create-only self-conflict."""
    seed = _seed_firing_once('put', 'k', lane=3, p=0.5)
    inner = HttpStore(f'127.0.0.1:{http_server.port}',
                      namespace=str(tmp_path / 'ack'))
    chaos = ChaosStore(inner, StoreFaultConfig(seed=seed, ack_lost=0.5))
    retrying = RetryingStore(chaos, clock=ManualClock())
    try:
        gen = retrying.put('k', b'payload', if_generation=None)
        assert chaos.counts['ack_lost'] == 1
        assert retrying.stats()['retries'] == 1
        assert gen == generation_of(b'payload')
        assert inner.get('k').data == b'payload'
    finally:
        retrying.close()


def test_ack_lost_unconditional_replay_is_idempotent_on_posix(tmp_path):
    """Local backends have no token memory and need none for ANY puts:
    replaying the same bytes re-commits the same content hash."""
    seed = _seed_firing_once('put', 'k', lane=3, p=0.5)
    inner = PosixStore(str(tmp_path / 'root'))
    chaos = ChaosStore(inner, StoreFaultConfig(seed=seed, ack_lost=0.5))
    retrying = RetryingStore(chaos, clock=ManualClock())
    gen = retrying.put('k', b'payload')
    assert chaos.counts['ack_lost'] == 1
    assert gen == generation_of(b'payload')


# -- 4. deterministic chaos, strict env -----------------------------------

def test_chaos_schedule_is_deterministic(tmp_path):
    def run(name):
        cfg = StoreFaultConfig(seed=11, fail=0.4, torn=0.4,
                               partial=0.4, ack_lost=0.2)
        chaos = ChaosStore(PosixStore(str(tmp_path / name)), cfg)
        for i in range(30):
            key = f'k{i % 3}'
            try:
                chaos.put(key, f'v{i}'.encode())
            except StoreTimeout:
                pass
            try:
                chaos.get(key)
            except StoreTimeout:
                pass
        return list(chaos.trace)
    first, second = run('a'), run('b')
    assert first == second
    assert first   # the probabilities above must actually fire


def test_partial_read_presents_committed_generation(tmp_path):
    """The bit-rot shape only a content-hash check catches: truncated
    bytes under the REAL generation token."""
    inner = PosixStore(str(tmp_path / 'root'))
    inner.put('k', b'0123456789')
    chaos = ChaosStore(inner, StoreFaultConfig(seed=3, partial=1.0))
    blob = chaos.get('k')
    assert blob.data == b'01234'
    assert blob.generation == generation_of(b'0123456789')


def test_store_chaos_env_contract_is_strict():
    env = {store_chaos.ENV_STORE_TORN: '2.0'}
    with pytest.raises(ValueError):
        store_chaos.from_env(env=env)
    with pytest.raises(ValueError):
        store_chaos.from_env(env={store_chaos.ENV_STORE_SEED: 'abc'})
    assert store_chaos.from_env(env={}) is None
    cfg = store_chaos.from_env(env={
        store_chaos.ENV_STORE_SEED: '9',
        store_chaos.ENV_STORE_ACK_LOST: '0.25',
        store_chaos.ENV_STORE_WINDOWS: '10:40;90:95',
        store_chaos.ENV_STORE_T0: '100.0'})
    assert cfg.seed == 9 and cfg.ack_lost == 0.25
    assert cfg.windows == ((10.0, 40.0), (90.0, 95.0))
    assert cfg.unavailable(120.0) and not cfg.unavailable(150.0)


def test_faults_from_env_registers_store_drills(monkeypatch):
    from kfac_pytorch_tpu import faults
    monkeypatch.setenv(store_chaos.ENV_STORE_SEED, '5')
    monkeypatch.setenv(store_chaos.ENV_STORE_FAIL, '0.1')
    faults.from_env()   # strict surface accepts the armed drill
    monkeypatch.setenv(store_chaos.ENV_STORE_FAIL, 'banana')
    with pytest.raises(ValueError):
        faults.from_env()


# -- 5. bounded retries, loud give-up -------------------------------------

def _retrying(inner, attempts=4):
    return RetryingStore(
        inner,
        policy=RetryPolicy(attempts=attempts, base_delay=0.01,
                           max_delay=0.02, jitter=0.0,
                           retry_on=(StoreTimeout,)),
        clock=ManualClock())


def test_retrying_store_rides_out_transients(tmp_path):
    seed = _seed_firing_once('put', 'k', lane=1, p=0.5)
    chaos = ChaosStore(PosixStore(str(tmp_path / 'root')),
                       StoreFaultConfig(seed=seed, torn=0.5))
    retrying = _retrying(chaos)
    assert retrying.put('k', b'v') == generation_of(b'v')
    stats = retrying.stats()
    assert stats['retries'] == 1 and stats['gave_up'] == 0


def test_retrying_store_gives_up_loudly(tmp_path, caplog):
    cfg = StoreFaultConfig(seed=1, windows=((0.0, float('inf')),),
                           t0=0.0)
    chaos = ChaosStore(PosixStore(str(tmp_path / 'root')), cfg)
    retrying = _retrying(chaos, attempts=3)
    with caplog.at_level(logging.WARNING, logger='kfac_pytorch_tpu'
                                                 '.store.base'):
        with pytest.raises(StoreGiveUp):
            retrying.get('k')
    assert retrying.stats() == {'retries': 2, 'gave_up': 1,
                                'wait_s': pytest.approx(0.03)}
    assert any('store: giving up op=get key=k after 3 attempts' in r
               and '[resilience: store_gave_up=1]' in r
               for r in (rec.getMessage() for rec in caplog.records))


def test_store_from_env_selection(tmp_path, http_server, monkeypatch):
    s = store_from_env(str(tmp_path / 'a'), env={})
    assert isinstance(s, RetryingStore) \
        and isinstance(s.inner, PosixStore)
    env = {'KFAC_STORE_BACKEND': 'http',
           'KFAC_STORE_ADDR': f'127.0.0.1:{http_server.port}'}
    h = store_from_env(str(tmp_path / 'a'), env=env)
    assert isinstance(h.inner, HttpStore)
    h.close()
    with pytest.raises(ValueError):
        store_from_env(str(tmp_path / 'a'),
                       env={'KFAC_STORE_BACKEND': 'http'})
    with pytest.raises(ValueError):
        store_from_env(str(tmp_path / 'a'),
                       env={'KFAC_STORE_BACKEND': 'ftp'})
    chaotic = store_from_env(
        str(tmp_path / 'a'),
        env={store_chaos.ENV_STORE_SEED: '3',
             store_chaos.ENV_STORE_FAIL: '0.5'})
    assert isinstance(chaotic.inner, ChaosStore)


# -- 6. the manifest plane and the scrub ----------------------------------

def _commit_epoch(store, epoch, data):
    key = f'checkpoint-{epoch}.pkl'
    store.put(key, data)
    manifest = build_manifest(epoch, 'pickle', {key: data})
    store.put(manifest_key(epoch), encode_manifest(manifest))
    return key


def test_manifest_roundtrip_and_epochs(store):
    _commit_epoch(store, 0, b'state-0')
    _commit_epoch(store, 2, b'state-2')
    store.put('checkpoint-1.pkl', b'torn')   # blob without manifest
    assert sorted(manifest_epochs(store)) == [0, 2]
    manifest = parse_manifest(store.get(manifest_key(2)).data)
    assert manifest['epoch'] == 2 and manifest['kind'] == 'pickle'
    assert verify_epoch(store, manifest) == []
    assert parse_manifest(b'not json') is None
    assert parse_manifest(json.dumps({'format': 99}).encode()) is None


def test_verify_blob_classifies_corruption(store):
    key = _commit_epoch(store, 0, b'0123456789')
    manifest = parse_manifest(store.get(manifest_key(0)).data)
    spec = manifest['blobs'][key]
    assert verify_blob(store, key, spec) is None
    store.put(key, b'0123456789'[:5])
    assert verify_blob(store, key, spec) == 'size_mismatch'
    store.put(key, b'012345678X')
    assert verify_blob(store, key, spec) == 'hash_mismatch'
    store.delete(key)
    assert verify_blob(store, key, spec) == 'missing'


def test_scrub_repairs_from_mirror(store, tmp_path, caplog):
    key = _commit_epoch(store, 0, b'precious-state')
    mirror = PosixStore(str(tmp_path / 'mirror'))
    with caplog.at_level(logging.INFO,
                         logger='kfac_pytorch_tpu.store.verify'):
        # backup pass: the clean scrub populates the mirror
        assert store_verify.scrub(store, mirror=mirror,
                                  sync_mirror=True) == (1, 0, 0)
        assert mirror.get(key).data == b'precious-state'
        # bit-rot lands; the next scrub repairs it from the mirror
        store.put(key, b'precious-stat3')
        assert store_verify.scrub(store, mirror=mirror) == (1, 1, 0)
    assert store.get(key).data == b'precious-state'
    messages = [rec.getMessage() for rec in caplog.records]
    assert any('ckpt: corrupt blob key=%s' % key in m
               and 'reason=hash_mismatch' in m for m in messages)
    assert any('ckpt: repaired blob key=%s' % key in m
               and 'source=mirror' in m
               and '[resilience: ckpt_repaired=1]' in m
               for m in messages)
    assert any('ckpt: verified epoch=0 blobs=1' in m for m in messages)


def test_scrub_repairs_from_older_epoch_by_content_hash(store):
    """Same bytes under an older epoch's key repair a newer epoch —
    content-addressed, never state substitution."""
    _commit_epoch(store, 1, b'converged-state')
    key2 = _commit_epoch(store, 2, b'converged-state')
    store.delete(key2)
    assert store_verify.scrub(store) == (2, 1, 0)
    assert store.get(key2).data == b'converged-state'


def test_scrub_reports_unrepairable(store):
    key = _commit_epoch(store, 0, b'only-copy')
    store.put(key, b'only-cop?')
    verified, repaired, unrepaired = store_verify.scrub(store)
    assert (verified, repaired, unrepaired) == (0, 0, 1)


def test_verify_cli_roundtrip(tmp_path, monkeypatch):
    root = tmp_path / 'ckpt'
    store = PosixStore(str(root))
    key = _commit_epoch(store, 3, b'cli-state')
    monkeypatch.delenv('KFAC_STORE_BACKEND', raising=False)
    assert store_verify.main(['--root', str(root)]) == 0
    (root / key).write_bytes(b'cli-stat3')
    # no repair source: unrepaired corruption is exit 1
    assert store_verify.main(['--root', str(root), '--no-repair']) == 1


def test_verify_cli_store_lost_exits_120(monkeypatch, caplog):
    monkeypatch.setenv('KFAC_STORE_BACKEND', 'http')
    monkeypatch.setenv('KFAC_STORE_ADDR', '127.0.0.1:1')
    with caplog.at_level(logging.ERROR):
        assert store_verify.main(['--root', 'ns']) == RC_STORE_LOST
    assert any('checkpoint store lost' in rec.getMessage()
               and 'store_lost=1' in rec.getMessage()
               for rec in caplog.records)
