"""exclude_parts ablation plumbing (reference:
kfac_preconditioner_base.py:96-99, 200-225 — each flag removes one
pipeline stage; used for the phase-attribution subtraction method,
scripts/time_breakdown.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import kfac_pytorch_tpu as kfac
from kfac_pytorch_tpu import models, training
from tests.helpers import TinyCNN


def _run_steps(exclude_parts, n=2, variant='eigen_dp'):
    model = TinyCNN()
    precond = kfac.KFAC(variant=variant, lr=0.1, damping=0.003,
                        exclude_parts=exclude_parts)
    tx = training.sgd(0.1, momentum=0.9)
    x = jnp.asarray(np.random.RandomState(0).randn(4, 16, 16, 3),
                    jnp.float32)
    batch = {'input': x, 'label': jnp.asarray([0, 1, 2, 3])}
    state = training.init_train_state(model, tx, precond,
                                      jax.random.PRNGKey(0), x)

    def ce(outputs, b):
        return optax.softmax_cross_entropy_with_integer_labels(
            outputs, b['label']).mean()

    step = training.build_train_step(model, tx, precond, ce,
                                     extra_mutable=('batch_stats',))
    for _ in range(n):
        state, _ = step(state, batch, lr=0.1, damping=0.003)
    return state


def _factor_norm(state):
    return float(sum(jnp.abs(f).sum()
                     for f in jax.tree.leaves(state.kfac_state.factors)))


def _decomp_norm(state):
    return float(sum(jnp.abs(d).sum()
                     for d in jax.tree.leaves(state.kfac_state.decomp)))


def test_exclude_compute_factor_leaves_factors_untouched():
    full = _run_steps('')
    ablated = _run_steps('ComputeFactor')
    init = _run_steps('ComputeFactor', n=0)  # state as initialized
    assert abs(_factor_norm(full) - _factor_norm(init)) > 1e-3
    # with the stage ablated the factor state never changes from init
    np.testing.assert_allclose(
        np.concatenate([np.asarray(x).ravel() for x in
                        jax.tree.leaves(ablated.kfac_state.factors)]),
        np.concatenate([np.asarray(x).ravel() for x in
                        jax.tree.leaves(init.kfac_state.factors)]))


def test_exclude_compute_inverse_skips_decomposition():
    ablated = _run_steps('ComputeInverse')
    assert _decomp_norm(ablated) == 0.0
    # factors still accumulate (only the decomposition stage is ablated)
    assert _factor_norm(ablated) > 0


def test_exclude_communicate_inverse_disables_kl_clip_rescale():
    # reference parity: the nu-rescale reads the gathered preds, so the
    # comm ablation also skips the clip (inv.py:188-217 under ablation)
    full = _run_steps('')
    noclip = _run_steps('CommunicateInverse')
    pf = jax.tree.leaves(full.params)[0]
    pn = jax.tree.leaves(noclip.params)[0]
    assert not np.allclose(np.asarray(pf), np.asarray(pn))


def test_excluded_runs_remain_finite():
    for parts in ('CommunicateFactor',
                  'CommunicateInverse,ComputeInverse',
                  'CommunicateInverse,ComputeInverse,CommunicateFactor,'
                  'ComputeFactor'):
        state = _run_steps(parts, n=1)
        for leaf in jax.tree.leaves(state.params):
            assert np.isfinite(np.asarray(leaf)).all(), parts
