"""Warm-kernel accuracy regression gate (VERDICT r3 #8).

The 40-epoch hardened-digits A/B (scripts/run_digits_hard_ab.sh)
established that K-FAC decisively beats SGD — seed-robust across the
two 40-epoch seeds (NOTES r4 error-bar table) — while the
warm/amortized kernels' apparent few-point accuracy cost turned out to
sit INSIDE the cross-seed spread (at seed 43 basis10 is the best K-FAC
leg): accuracy-neutral on this task. This gate therefore pins
SAME-SEED bands as a regression detector (a warm-kernel change that
collapses a leg or disengages a knob), not as a cost claim:
a compact in-process replica of the same task (300 train digits, 30%
train-label noise, clean val) through the REAL build_train_step engine
on the 4-device mesh, seeded end to end.

Bands are deliberately loose (short horizon, small model): the gate
exists to catch collapses and silently-disengaged warm paths, not to
re-litigate single points of val accuracy. NOTE the gate does NOT
assert K-FAC-beats-SGD: on this small MLP task SGD wins outright
(0.88 vs ~0.73-0.74 at 20 epochs, seed 0) — the second-order value
evidence lives in the 40-epoch CONV A/B (K-FAC +147q..220q over SGD,
NOTES r3) and README's convergence section; this file only pins the
warm-kernel cost RELATIVE to cold on a fixed task.

Calibration (seed 0, 2026-08-01): sgd .8811, cold_eigen .7428,
cold_chol .7321, warm_ns .7201, basis10 .7228, warm_subspace .7295 —
warm-vs-cold gaps 1.2-2.0 points; gate at 6.
"""

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from flax import linen
from jax.sharding import Mesh

import kfac_pytorch_tpu as kfac
from kfac_pytorch_tpu import nn as knn
from kfac_pytorch_tpu import training

# slow AND nightly: 6 20-epoch CPU trainings take tens of minutes — the
# heaviest block of the old slow set (VERDICT r4 weak #6). The nightly
# marker makes it opt-in (-m nightly / KFAC_NIGHTLY=1, see conftest);
# staying 'slow' too keeps it out of tier-1 math either way.
pytestmark = [pytest.mark.slow, pytest.mark.nightly]

ND, BATCH, EPOCHS, SEED = 4, 32, 20, 0
TRAIN_N, NOISE = 300, 0.3
# calibrated on this task: damping 0.003 (the conv recipe's) oscillates
# on the tiny MLP; 0.03 + 5-epoch warmup trains every variant cleanly
LR, DAMPING, WARMUP = 0.1, 0.03, 5


class MLP(linen.Module):
    @linen.compact
    def __call__(self, x, train=True):
        x = linen.relu(knn.Dense(64, name='fc1')(x))
        return knn.Dense(10, name='head')(x)


def _digits_hard():
    """300 train / rest val sklearn digits, 30% train-label noise,
    stratified-ish via the fixed shuffle; val labels clean."""
    from sklearn.datasets import load_digits
    d = load_digits()
    x = (d.data / 16.0).astype(np.float32)
    y = d.target.astype(np.int32)
    rng = np.random.RandomState(7)
    order = rng.permutation(len(y))
    x, y = x[order], y[order]
    xt, yt = x[:TRAIN_N], y[:TRAIN_N].copy()
    xv, yv = x[TRAIN_N:], y[TRAIN_N:]
    flip = rng.rand(TRAIN_N) < NOISE
    yt[flip] = (yt[flip] + rng.randint(1, 10, flip.sum())) % 10
    return xt, yt, xv, yv


def _run_leg(variant, xt, yt, xv, yv, eigh_impl=None, **kfac_kw):
    # pin the impl for EVERY leg (ambient KFAC_EIGH_IMPL would skew the
    # cold legs' calibrated bands) and restore the caller's value after
    prior = os.environ.get('KFAC_EIGH_IMPL')
    os.environ['KFAC_EIGH_IMPL'] = eigh_impl if eigh_impl else 'xla'
    try:
        mesh = Mesh(np.array(jax.devices()[:ND]), ('batch',))
        model = MLP()
        precond = None
        if variant is not None:
            # kfac_update_freq=1 like the 40-epoch A/B's kfac=1 legs —
            # the warm/amortized paths only engage with frequent
            # decompositions (at freq 10 over this short horizon the
            # warm legs were bit-identical to cold: vacuous gate)
            precond = kfac.KFAC(variant=variant, lr=LR, damping=DAMPING,
                                fac_update_freq=1, kfac_update_freq=1,
                                num_devices=ND, axis_name='batch',
                                **kfac_kw)
        # the trainer's plumbing exactly: ONE schedule drives both the
        # optax step size and the hyper.lr the kl_clip scale reads — a
        # constant-tx/decayed-hyper mismatch explodes K-FAC at the decay
        from kfac_pytorch_tpu import utils as kutils
        steps_per_epoch = (len(xt) // BATCH)
        lr_fn = kutils.warmup_multistep(LR, steps_per_epoch, WARMUP,
                                        [12, 16])
        tx = training.sgd(lr_fn, momentum=0.9, weight_decay=5e-4)
        state = training.init_train_state(
            model, tx, precond, jax.random.PRNGKey(SEED), xt[:2])

        def ce(outputs, batch):
            return optax.softmax_cross_entropy_with_integer_labels(
                outputs, batch['label']).mean()

        step = training.build_train_step(model, tx, precond, ce,
                                         axis_name='batch', mesh=mesh,
                                         donate=False)
        fwd = jax.jit(functools.partial(model.apply, train=False))
        rng = np.random.RandomState(SEED)
        n = (len(xt) // BATCH) * BATCH
        for epoch in range(EPOCHS):
            order = rng.permutation(len(xt))[:n]
            for i in range(0, n, BATCH):
                sl = order[i:i + BATCH]
                batch = {'input': jnp.asarray(xt[sl]),
                         'label': jnp.asarray(yt[sl])}
                state, _ = step(state, batch,
                                lr=float(lr_fn(int(state.step))),
                                damping=DAMPING)
        logits = fwd({'params': state.params}, jnp.asarray(xv))
        return float((np.asarray(jnp.argmax(logits, -1)) == yv).mean())
    finally:
        if prior is None:
            os.environ.pop('KFAC_EIGH_IMPL', None)
        else:
            os.environ['KFAC_EIGH_IMPL'] = prior


def test_warm_kernel_accuracy_bands():
    xt, yt, xv, yv = _digits_hard()
    acc = {
        'sgd': _run_leg(None, xt, yt, xv, yv),
        'cold_eigen': _run_leg('eigen_dp', xt, yt, xv, yv),
        'cold_chol': _run_leg('inverse_dp', xt, yt, xv, yv),
        'warm_ns': _run_leg('inverse_dp', xt, yt, xv, yv,
                            warm_start_basis=True),
        'basis10': _run_leg('eigen_dp', xt, yt, xv, yv,
                            basis_update_freq=10),
        'warm_subspace': _run_leg('eigen_dp', xt, yt, xv, yv,
                                  eigh_impl='subspace',
                                  warm_start_basis=True),
        # E-KFAC (beyond reference): per-example moments in the joint
        # eigenbasis — alone, and with the amortized basis it exists for
        'ekfac': _run_leg('ekfac', xt, yt, xv, yv),
        'ekfac_basis10': _run_leg('ekfac', xt, yt, xv, yv,
                                  basis_update_freq=10),
    }
    print('warm-gate accuracies:', {k: round(v, 4) for k, v in acc.items()})

    # 1. every leg actually trains (chance is 0.10; constant-prediction
    #    collapse lands there, divergence lands below 0.5)
    for leg, a in acc.items():
        assert a > 0.5, (leg, a)
    # 2. warm kernels stay within the band of their cold counterparts
    #    (calibrated gaps 1.2-2.0 points; gate at 6 to absorb
    #    short-horizon noise while catching collapses)
    assert acc['warm_ns'] > acc['cold_chol'] - 0.06, acc
    assert acc['basis10'] > acc['cold_eigen'] - 0.06, acc
    assert acc['warm_subspace'] > acc['cold_eigen'] - 0.06, acc
    # 3. the warm paths ENGAGED: a warm leg bit-identical to its cold
    #    counterpart means the knob silently became a no-op (exactly
    #    what happened at kfac_update_freq=10 during calibration)
    assert acc['warm_ns'] != acc['cold_chol'], acc
    assert acc['basis10'] != acc['cold_eigen'], acc
    assert acc['warm_subspace'] != acc['cold_eigen'], acc
    # 4. E-KFAC: calibrated floors (.678/.709 at seed 0; gate 8 points
    #    under) and amortization-path engagement (basis_update_freq must
    #    change the trajectory)
    assert acc['ekfac'] > 0.60, acc
    assert acc['ekfac_basis10'] > 0.60, acc
    assert acc['ekfac_basis10'] != acc['ekfac'], acc


def test_ekfac_damping_ladder():
    """Seeded regression for the E-KFAC damping sensitivity (VERDICT r4
    #4): ekfac's exact second-moment denominators are systematically
    larger than the Kronecker product, so on this MLP task it prefers a
    lambda ~10x the eigen recipe's (NOTES r4 ladder, seed 0: .671/.652/
    .755/.832 at .003/.01/.1/.3 vs .678 at the gate's .03). Pins the
    DIRECTION at the ladder's endpoints — a change that makes the
    matched-lambda leg stop beating the recipe-lambda leg means the
    moment scaling (or its damping interaction) changed."""
    xt, yt, xv, yv = _digits_hard()
    recipe = _run_leg('ekfac', xt, yt, xv, yv)            # DAMPING=0.03
    prior = globals()['DAMPING']
    try:
        globals()['DAMPING'] = 0.3
        matched = _run_leg('ekfac', xt, yt, xv, yv)
    finally:
        globals()['DAMPING'] = prior
    print(f'ekfac damping ladder: recipe(0.03)={recipe:.4f} '
          f'matched(0.3)={matched:.4f}')
    # calibrated gap ~15 points (.832 vs .678); gate at 5 to absorb
    # short-horizon noise while catching a sign flip of the effect
    assert matched > recipe + 0.05, (recipe, matched)


def test_ekfac_damping_warning_fires_once():
    """The one-time construction warning behind the ladder: ekfac
    variants inherit eigen-calibrated damping silently otherwise."""
    import warnings

    from kfac_pytorch_tpu import preconditioner as P
    prior = P._EKFAC_DAMPING_WARNED
    try:
        P._EKFAC_DAMPING_WARNED = False
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter('always')
            kfac.KFAC(variant='ekfac', damping=0.003)
            kfac.KFAC(variant='ekfac_dp', damping=0.003)
            kfac.KFAC(variant='eigen_dp', damping=0.003)
        msgs = [str(x.message) for x in w if 'ekfac' in str(x.message)]
        assert len(msgs) == 1, msgs  # once per process, ekfac only
        assert 'damping' in msgs[0]
    finally:
        P._EKFAC_DAMPING_WARNED = prior
