"""Compiler-level communication properties (scripts/comm_count.py): the
DP-KFAC variants' whole point — owner-local factor stats delete the
factor allreduce — must be visible in the compiled SPMD module itself
(reference scripts/time_breakdown.py:27 ledger: MPD FactorComm 0.300 s /
InverseComm 0.146 s vs the DP variants' pred-gather only)."""

import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..'))

from tests.helpers import TinyCNN


@pytest.mark.slow
def test_dp_comm_volume_below_mpd():
    from scripts.comm_count import collective_ledger

    vols, phases = {}, {}
    for variant in ('sgd', 'eigen', 'eigen_dp', 'ekfac', 'ekfac_dp'):
        led = collective_ledger(variant, ndev=8,
                                model=TinyCNN(batch_norm=False), hw=8)
        vols[variant] = led['total_bytes']
        phases[variant] = led['by_phase']
    # SGD's gradient allreduce is the floor; MPD eigen adds the factor
    # reduce-scatter + eigenbasis gather on top; DP must sit strictly
    # between — above the floor (it still gathers preconditioned
    # grads), below MPD
    assert vols['sgd'] < vols['eigen_dp'] < vols['eigen'], vols
    # the FactorComm-deletion claim, phase-attributed: DP has ZERO
    # factor/inverse comm (only the pred gather), MPD pays for both.
    # (The old >2x total-volume margin no longer holds in result-byte
    # terms: the stats reduce is now a reduce-scatter — each device
    # receives only its own rows — which shrank MPD's ledger footprint
    # by the world size. The per-phase pin is the sharper claim.)
    assert phases['eigen_dp'].get('FactorComm', {}).get('bytes', 0) == 0
    assert phases['eigen_dp'].get('InverseComm', {}).get('bytes', 0) == 0
    assert phases['eigen']['FactorComm']['bytes'] > 0
    assert phases['eigen']['InverseComm']['bytes'] > 0
    assert phases['eigen_dp']['PredComm']['bytes'] > 0
    # E-KFAC comm story (compiler-pinned): owner-local moments add ZERO
    # bytes over eigen_dp; the MPD variant pays for its scales pmean
    assert vols['ekfac_dp'] == vols['eigen_dp'], vols
    assert vols['ekfac'] > vols['eigen'], vols


@pytest.mark.slow
def test_compressed_wire_byte_ledger():
    """Compression acceptance, compiler-verified on the per-phase
    per-dtype ledger: bf16 factor comm drops the K-FAC collective bytes
    >= 40% on BOTH the MPD 'eigen' path (stats reduce + decomposition
    gather) and the 'inverse_dp' comm path (pred gather); int8 drops
    further on the gathers; and the non-K-FAC collective floor stays
    byte-identical under every wire dtype (compression never touches
    the gradient path)."""
    from scripts.comm_count import (FLOOR_PHASE, check_floor,
                                    collective_ledger)

    specs = {'sgd': ('sgd', 'fp32'),
             'eigen': ('eigen', 'fp32'),
             'eigen:bf16': ('eigen', 'bf16'),
             'eigen:int8': ('eigen', 'int8'),
             'inverse_dp': ('inverse_dp', 'fp32'),
             'inverse_dp:bf16': ('inverse_dp', 'bf16')}
    ledgers = {}
    for spec, (variant, precision) in specs.items():
        ledgers[spec] = collective_ledger(
            variant, ndev=8, model=TinyCNN(batch_norm=False), hw=8,
            comm_precision=precision)
    # the SGD floor holds: only gradient-path all-reduces, and every
    # compressed spec's floor phase is byte-identical to its fp32
    # counterpart's
    check_floor(ledgers)
    sgd = ledgers['sgd']['total_bytes']

    def extra(spec):
        return ledgers[spec]['total_bytes'] - sgd

    # >= 40% total K-FAC collective-byte reduction (the ISSUE 8 gate)
    assert extra('eigen:bf16') <= 0.6 * extra('eigen'), (
        extra('eigen'), extra('eigen:bf16'))
    assert extra('inverse_dp:bf16') <= 0.6 * extra('inverse_dp'), (
        extra('inverse_dp'), extra('inverse_dp:bf16'))
    # int8 compresses the gathers harder than bf16
    assert extra('eigen:int8') < extra('eigen:bf16')

    # phase attribution: the MPD path shows factor + inverse comm, the
    # DP path only the pred gather; compressed dtypes land on the wire
    eig16 = ledgers['eigen:bf16']['by_phase']
    assert 'FactorComm' in eig16 and 'InverseComm' in eig16
    assert set(eig16['InverseComm']['by_dtype']) == {'u16'}
    eig8 = ledgers['eigen:int8']['by_phase']
    assert 's8' in eig8['InverseComm']['by_dtype']
    dp16 = ledgers['inverse_dp:bf16']['by_phase']
    assert 'FactorComm' not in dp16 and 'InverseComm' not in dp16
    assert set(dp16['PredComm']['by_dtype']) == {'u16'}
    # the bf16 pred gather is exactly half its fp32 counterpart
    dp32 = ledgers['inverse_dp']['by_phase']
    assert dp16['PredComm']['bytes'] * 2 == dp32['PredComm']['bytes']
    # and the floor phase exists everywhere the loss pmean does
    assert FLOOR_PHASE in ledgers['sgd']['by_phase']
