"""Compiler-level communication properties (scripts/comm_count.py): the
DP-KFAC variants' whole point — owner-local factor stats delete the
factor allreduce — must be visible in the compiled SPMD module itself
(reference scripts/time_breakdown.py:27 ledger: MPD FactorComm 0.300 s /
InverseComm 0.146 s vs the DP variants' pred-gather only)."""

import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..'))

from tests.helpers import TinyCNN


@pytest.mark.slow
def test_dp_comm_volume_below_mpd():
    from scripts.comm_count import collective_counts

    vols = {}
    for variant in ('sgd', 'eigen', 'eigen_dp', 'ekfac', 'ekfac_dp'):
        _, by_kind = collective_counts(variant, ndev=8,
                                       model=TinyCNN(batch_norm=False),
                                       hw=8)
        vols[variant] = sum(by_kind.values())
    # SGD's gradient allreduce is the floor; MPD eigen adds the factor
    # pmean + eigenbasis gather on top; DP must sit strictly between —
    # above the floor (it still gathers preconditioned grads), well
    # below MPD (no factor comm)
    assert vols['sgd'] < vols['eigen_dp'] < vols['eigen'], vols
    # the deletion must be substantial, not incidental: DP's extra comm
    # over SGD is less than half of MPD's extra
    extra_dp = vols['eigen_dp'] - vols['sgd']
    extra_mpd = vols['eigen'] - vols['sgd']
    assert extra_dp < 0.5 * extra_mpd, vols
    # E-KFAC comm story (compiler-pinned): owner-local moments add ZERO
    # bytes over eigen_dp; the MPD variant pays for its scales pmean
    assert vols['ekfac_dp'] == vols['eigen_dp'], vols
    assert vols['ekfac'] > vols['eigen'], vols
