"""Deterministic fleet simulator (kfac_pytorch_tpu/sim/).

jax-free by design — this module must collect and pass with nothing
but the stdlib + pytest installed (the CI ``fleet-sim`` job). It pins
the ISSUE's acceptance properties:

1. Determinism: two runs with the same seed produce byte-identical
   JSONL traces (the trace carries sim time + semantic events only —
   no wall clocks, ports, pids or CAS nonces to leak through).
2. Scale: a 1,000-host sweep (125 pods x 8, kills + partitions + two
   replica outages + a 10-job service lane) completes in well under
   60s of wall time on CPU, driving the REAL PodSupervisor barrier,
   PeerHeartbeat detection, JobQueue epoch CAS and 3-replica quorum
   code.
3. Safety properties over the trace:
   - quorum shrink never splits brain: at most one commit per
     (pod, generation), and a partition's minority side always fences;
   - fencing never loses a committed lineage: per-pod committed
     lineage epochs are strictly monotonic;
   - exactly-once requeue: each planned first-launch failure produces
     ONE job_requeue, and every job still finishes;
   - one KV replica down (and later restored EMPTY) is invisible:
     zero coord_lost, read-through repair observed.
"""

import json
import sys
import time

import pytest

from kfac_pytorch_tpu.sim import SimConfig, run_fleet_sim, write_trace
from kfac_pytorch_tpu.sim.fleet import EventLoop
from kfac_pytorch_tpu.resilience.retry import ManualClock


def _canon(trace):
    return '\n'.join(json.dumps(e, sort_keys=True) for e in trace)


def _kinds(trace):
    out = {}
    for e in trace:
        out.setdefault(e['kind'], []).append(e)
    return out


# -- the event loop itself ---------------------------------------------------


def test_event_loop_fires_in_time_then_insertion_order():
    clock = ManualClock()
    loop = EventLoop(clock)
    fired = []
    loop.at(2.0, lambda: fired.append('b'))
    loop.at(1.0, lambda: fired.append('a'))
    loop.at(2.0, lambda: fired.append('c'))  # same t: insertion order
    assert loop.run(10.0)
    assert fired == ['a', 'b', 'c']
    assert clock.now == 2.0


def test_event_loop_never_rewinds_a_busy_clock():
    # an event that sleeps on the shared clock (a barrier settle) moves
    # time PAST later events' stamps; they fire late, not backwards
    clock = ManualClock()
    loop = EventLoop(clock)
    seen = []
    loop.at(1.0, lambda: clock.sleep(5.0))
    loop.at(2.0, lambda: seen.append(clock.now))
    assert loop.run(10.0)
    assert seen == [6.0]


def test_event_loop_deadline_reports_undrained():
    loop = EventLoop(ManualClock())
    loop.at(100.0, lambda: None)
    assert loop.run(50.0) is False


# -- determinism -------------------------------------------------------------


def test_same_seed_same_trace_bytes(tmp_path):
    cfg = SimConfig(hosts=128, pod_size=8, kill_pods=4,
                    partition_pods=2, jobs=5, fail_jobs=2, seed=11)
    a = run_fleet_sim(cfg, tmp_path / 'a')
    b = run_fleet_sim(cfg, tmp_path / 'b')
    pa = write_trace(a, tmp_path / 'a.jsonl')
    pb = write_trace(b, tmp_path / 'b.jsonl')
    assert open(pa, 'rb').read() == open(pb, 'rb').read()
    assert len(a) > 20  # a real sweep, not an empty trace


def test_different_seed_different_trace(tmp_path):
    base = dict(hosts=64, pod_size=8, kill_pods=2, partition_pods=1,
                jobs=3, fail_jobs=1)
    a = run_fleet_sim(SimConfig(seed=1, **base), tmp_path / 'a')
    b = run_fleet_sim(SimConfig(seed=2, **base), tmp_path / 'b')
    assert _canon(a) != _canon(b)  # the seed actually steers the plan


# -- the 1,000-host sweep ----------------------------------------------------


@pytest.fixture(scope='module')
def fleet_trace(tmp_path_factory):
    """One 1,000-host sweep shared by every property test below; its
    wall time is the scale assertion."""
    cfg = SimConfig()  # the CI profile: 1000 hosts, all faults armed
    t0 = time.monotonic()
    trace = run_fleet_sim(cfg, tmp_path_factory.mktemp('fleet'))
    wall = time.monotonic() - t0
    return cfg, trace, wall


def test_thousand_hosts_in_seconds(fleet_trace):
    cfg, trace, wall = fleet_trace
    assert wall < 60.0, f'1000-host sweep took {wall:.1f}s'
    start = trace[0]
    assert start['kind'] == 'sim_start' and start['hosts'] == 1000
    assert trace[-1]['kind'] == 'sim_end' and trace[-1]['drained']


def test_one_replica_down_is_invisible(fleet_trace):
    cfg, trace, _ = fleet_trace
    k = _kinds(trace)
    assert 'coord_lost' not in k, k.get('coord_lost')
    assert len(k['replica_down']) == len(cfg.replica_outages)
    assert len(k['replica_up']) == len(cfg.replica_outages)
    end = trace[-1]
    assert end['repaired'], 'restarted empty replica was never repaired'
    assert end['degraded'], 'outage never even degraded the quorum'


def test_no_split_brain_one_commit_per_generation(fleet_trace):
    cfg, trace, _ = fleet_trace
    commits = _kinds(trace)['shrink_commit']
    seen = set()
    for e in commits:
        key = (e['pod'], e['gen'])
        assert key not in seen, f'two commits for {key}: split brain'
        seen.add(key)


def test_partition_minority_always_fences(fleet_trace):
    cfg, trace, _ = fleet_trace
    k = _kinds(trace)
    partitions = k['partition']
    assert len(partitions) == cfg.partition_pods
    fenced = {e['pod'] for e in k['fenced']}
    commits = {e['pod']: e for e in k['shrink_commit']}
    for p in partitions:
        pod = p['pod']
        assert pod in fenced, f'pod {pod} minority never fenced'
        commit = commits[pod]
        # the committed membership is exactly the majority side, in
        # BOTH race orders (minority first and majority first)
        assert commit['survivors'] == p['majority'], p
        assert not set(p['minority']) & set(commit['survivors'])


def test_kill_pods_commit_without_the_victim(fleet_trace):
    cfg, trace, _ = fleet_trace
    k = _kinds(trace)
    kills = k['host_kill']
    assert len(kills) == cfg.kill_pods
    commits = {e['pod']: e for e in k['shrink_commit']}
    detected = {(e['pod'], e['peer']) for e in k['peer_dead']}
    for kill in kills:
        pod, victim = kill['pod'], kill['host']
        assert (pod, victim) in detected, \
            f'pod {pod} never detected host {victim} dead'
        commit = commits[pod]
        assert victim not in commit['survivors']
        assert len(commit['survivors']) == cfg.pod_size - 1


def test_committed_lineage_strictly_monotonic(fleet_trace):
    cfg, trace, _ = fleet_trace
    per_pod = {}
    for e in _kinds(trace)['shrink_commit']:
        per_pod.setdefault(e['pod'], []).append(e['lineage'])
    for pod, lineages in per_pod.items():
        assert all(b > a for a, b in zip(lineages, lineages[1:])), \
            f'pod {pod} lineage not strictly monotonic: {lineages}'
        assert lineages[0] >= 1  # a commit always bumps past the seed 0


def test_exactly_once_requeue_and_all_jobs_finish(fleet_trace):
    cfg, trace, _ = fleet_trace
    k = _kinds(trace)
    assert len(k['job_submit']) == cfg.jobs
    requeues = k.get('job_requeue', [])
    # one requeue per planned first-launch failure — through two
    # replica outages — and not one more
    assert len(requeues) == cfg.fail_jobs
    assert sorted(e['job'] for e in requeues) == \
        list(range(1, cfg.fail_jobs + 1))
    assert all(e['requeues'] == 1 for e in requeues)
    assert all(e['rc'] == 115 for e in requeues)
    done = k.get('job_done', [])
    assert len(done) == cfg.jobs, 'jobs lost or stuck'
    assert 'job_lost' not in k
    assert trace[-1]['jobs_finished']


# -- multi-tenant sweep: preemption + autoscale + drain ----------------------


@pytest.fixture(scope='module')
def mt_trace(tmp_path_factory):
    """One combined multi-tenant sweep shared by the policy property
    tests: a high-priority preemptor lands mid-run, autoscale is armed,
    and a service host drains at t=6 — on top of the usual kills,
    partition and replica outage."""
    cfg = SimConfig(hosts=48, pod_size=8, kill_pods=2, partition_pods=1,
                    jobs=6, fail_jobs=1, seed=7, preempt_jobs=1,
                    autoscale=True, drain_at=6.0)
    trace = run_fleet_sim(cfg, tmp_path_factory.mktemp('mt'))
    return cfg, trace


def test_mt_sweep_same_seed_same_trace_bytes(mt_trace, tmp_path):
    cfg, trace = mt_trace
    again = run_fleet_sim(cfg, tmp_path / 'again')
    assert _canon(trace) == _canon(again)


def test_preemption_suspends_then_every_tenant_finishes(mt_trace):
    cfg, trace = mt_trace
    k = _kinds(trace)
    suspended = k.get('job_suspend', [])
    assert any(e['reason'] == 'preempt' for e in suspended)
    assert all(e['rc'] == 119 for e in suspended)  # RC_SUSPENDED
    # every suspend the scheduler requested was delivered to a pod
    assert len(k.get('pod_suspend', [])) == len(suspended)
    # no tenant starves: every submitted job — victims included — runs
    # to completion, and nothing is ever lost
    total = cfg.jobs + cfg.preempt_jobs
    assert sorted(e['job'] for e in k['job_submit']) == \
        list(range(1, total + 1))
    assert sorted(e['job'] for e in k.get('job_done', [])) == \
        list(range(1, total + 1))
    assert 'job_lost' not in k
    end = trace[-1]
    assert end['jobs_finished'] and end['coord_lost'] == 0
    assert end['jobs_suspended'] == len(suspended)


def test_autoscale_requests_are_honored(mt_trace):
    cfg, trace = mt_trace
    scales = _kinds(trace).get('autoscale', [])
    assert scales, 'autoscale armed but no scale event fired'
    # queued demand grows the pool first; the drained queue shrinks it
    assert scales[0]['action'] == 'grow'
    assert scales[0]['capacity'] >= scales[0]['desired']
    assert scales[-1]['action'] == 'shrink'
    assert trace[-1]['autoscaled'] == len(scales)


def test_drain_migrates_preemptible_jobs_off_the_host(mt_trace):
    cfg, trace = mt_trace
    k = _kinds(trace)
    drains = k.get('host_drain', [])
    assert len(drains) == 1
    host = drains[0]['host']
    drained = [e for e in k.get('job_suspend', [])
               if e['reason'] == 'drain']
    assert drained, 'drain never suspended a running job'
    migrated = k.get('job_migrate', [])
    assert migrated, 'suspended jobs never migrated'
    # every drain-suspended job comes back on hosts that exclude the
    # draining one
    for e in drained:
        moves = [m for m in migrated
                 if m['job'] == e['job'] and m['t'] >= e['t']]
        assert moves, f'job {e["job"]} never left {host}'
        assert all(host not in m['dst'].split(',') for m in moves)


# -- the 10,000-host envelope (slow) -----------------------------------------


@pytest.mark.slow
def test_ten_thousand_hosts_byte_identical_within_budget(tmp_path):
    """The full coordination envelope (ISSUE 18): 10,000 hosts — 1,250
    pods of 8 on one shared KV plane — with the default fault profile
    (kills, partitions, both replica outages) PLUS the multi-tenant
    policies (preemption, autoscale, drain) armed, run TWICE: the
    traces must be byte-identical, nothing may be lost, and the wall
    budget pins the prefix-indexed KV scan (a whole-store scan per
    heartbeat read is quadratic in fleet size and blows this budget by
    an order of magnitude)."""
    cfg = SimConfig(hosts=10000, preempt_jobs=2, autoscale=True,
                    drain_at=6.0)
    t0 = time.monotonic()
    a = run_fleet_sim(cfg, tmp_path / 'a')
    wall = time.monotonic() - t0
    b = run_fleet_sim(cfg, tmp_path / 'b')
    pa = write_trace(a, tmp_path / 'a.jsonl')
    pb = write_trace(b, tmp_path / 'b.jsonl')
    assert open(pa, 'rb').read() == open(pb, 'rb').read()
    assert wall < 420.0, f'10k-host sweep took {wall:.1f}s'
    start, end = a[0], a[-1]
    assert start['kind'] == 'sim_start' and start['hosts'] == 10000
    assert end['kind'] == 'sim_end'
    assert end['coord_lost'] == 0
    assert end['jobs_finished'] and end['drained'] and end['repaired']
    k = _kinds(a)
    assert 'job_lost' not in k
    assert len(k['host_kill']) == cfg.kill_pods
    assert len(k['partition']) == cfg.partition_pods
    assert sorted(e['job'] for e in k['job_done']) == \
        list(range(1, cfg.jobs + cfg.preempt_jobs + 1))


# -- CLI ---------------------------------------------------------------------


def test_cli_writes_parseable_trace(tmp_path):
    from kfac_pytorch_tpu.sim.__main__ import main
    out = tmp_path / 'trace.jsonl'
    rc = main(['--hosts', '48', '--kill-pods', '2',
               '--partition-pods', '1', '--jobs', '2', '--fail-jobs',
               '1', '--seed', '5', '--out', str(out),
               '--root', str(tmp_path / 'root')])
    assert rc == 0
    lines = out.read_text().splitlines()
    events = [json.loads(l) for l in lines]
    assert events[0]['kind'] == 'sim_start'
    assert events[-1]['kind'] == 'sim_end'
    assert events[-1]['coord_lost'] == 0


def test_sim_package_is_jax_free():
    # the CI fleet-sim job runs without jax installed; importing the
    # simulator (and running it, covered above) must not pull jax in
    for mod in list(sys.modules):
        if mod == 'jax' or mod.startswith('jax.'):
            pytest.skip('jax already imported by an earlier test '
                        'module in this process')
    import kfac_pytorch_tpu.sim  # noqa: F401
    assert not any(m == 'jax' or m.startswith('jax.')
                   for m in sys.modules)
