"""Fused Pallas capture kernels (ops/pallas_capture.py, ISSUE 19).

Pins the numerical contract from the module docstring, under the Pallas
interpreter on the CPU tier:

1. Every STAT kernel (dense A/G, conv A/G, all bias x batch_averaged x
   padding/stride combinations) reproduces the ops/factors.py reference
   BIT-FOR-BIT when the row reduction fits one grid step — the strict-
   mode pins hold XLA's jit rewrites to the eager rounding sequence.
   Multi-tile runs (KFAC_CAPTURE_TR) stay value-equal; the VMEM cap
   (KFAC_CAPTURE_MAX_F) falls back to the reference exactly.
2. The EMA epilogue is algebraically identical, DETERMINISTIC across
   repeated invocations, and within one fp32 rounding of the unfused
   two-pass program (its final combine FMA-contracts under jit — the
   one documented exception to bitwise); a traced alpha two-passes and
   stays fully bitwise.
3. ef_quantize emits the exact xc/bf16-wire/residual algebra of
   collectives.pmean_scatter_ef's two-pass branch, bitwise — including
   under an 8-device shard_map (the wire bytes never change; the
   comm_count '+pallas' spec pins the ledger side).
4. End-to-end world=1: a KFAC step with capture_impl='pallas'
   (including the fully fused update_factors_fused path DP variants
   take) matches capture_impl=None, and capture_impl='xla' IS the
   legacy path bit-for-bit.
5. The compile-count guard: a capture_impl ladder switch through the
   arbiter clears the variant cache exactly once; replaying the
   committed trajectory compiles nothing new.
"""

import flax.linen as linen
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import kfac_pytorch_tpu as kfac
from kfac_pytorch_tpu import autotune, capture, training
from kfac_pytorch_tpu import nn as knn
from kfac_pytorch_tpu.ops import factors, pallas_capture

pytestmark = pytest.mark.core


def _rng(seed=0):
    return np.random.RandomState(seed)


# ---------------------------------------------------------------------------
# 1. statistic-kernel bit parity vs ops/factors.py (single grid step)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize('use_bias', [True, False])
def test_a_dense_bitwise(use_bias):
    a = jnp.asarray(_rng(1).randn(32, 12), jnp.float32)
    ref = factors.compute_a_dense(a, use_bias)
    got = pallas_capture.compute_a_dense(a, use_bias, interpret=True)
    assert got.dtype == ref.dtype and got.shape == ref.shape
    assert np.array_equal(np.asarray(got), np.asarray(ref))


def test_a_dense_ndim3_seq_mean_bitwise():
    # [N, T, D] activations (the transformer capture shape): the
    # sequence mean happens OUTSIDE the kernel, identically to the
    # reference
    a = jnp.asarray(_rng(2).randn(8, 6, 10), jnp.float32)
    ref = factors.compute_a_dense(a, True)
    got = pallas_capture.compute_a_dense(a, True, interpret=True)
    assert np.array_equal(np.asarray(got), np.asarray(ref))


@pytest.mark.parametrize('batch_averaged', [True, False])
def test_g_dense_bitwise(batch_averaged):
    g = jnp.asarray(_rng(3).randn(32, 9), jnp.float32)
    ref = factors.compute_g_dense(g, batch_averaged)
    got = pallas_capture.compute_g_dense(g, batch_averaged,
                                         interpret=True)
    assert np.array_equal(np.asarray(got), np.asarray(ref))


@pytest.mark.parametrize('batch_averaged', [True, False])
def test_g_conv_bitwise(batch_averaged):
    g = jnp.asarray(_rng(4).randn(4, 5, 5, 7), jnp.float32)
    ref = factors.compute_g_conv(g, batch_averaged)
    got = pallas_capture.compute_g_conv(g, batch_averaged,
                                        interpret=True)
    assert np.array_equal(np.asarray(got), np.asarray(ref))


@pytest.mark.parametrize('use_bias', [True, False])
@pytest.mark.parametrize('strides', [(1, 1), (2, 2)])
@pytest.mark.parametrize('padding', ['SAME', 'VALID', (1, 1),
                                     ((1, 2), (0, 1))])
def test_a_conv_bitwise(use_bias, strides, padding):
    a = jnp.asarray(_rng(5).randn(4, 9, 9, 3), jnp.float32)
    ref = factors.compute_a_conv(a, (3, 3), strides, padding, use_bias)
    got = pallas_capture.compute_a_conv(a, (3, 3), strides, padding,
                                        use_bias, interpret=True)
    assert got.shape == ref.shape
    assert np.array_equal(np.asarray(got), np.asarray(ref))


def test_a_conv_rect_kernel_bitwise():
    # non-square taps exercise the (ki, kj) slice loop asymmetrically
    a = jnp.asarray(_rng(6).randn(3, 8, 10, 2), jnp.float32)
    ref = factors.compute_a_conv(a, (1, 3), (1, 2), 'SAME', True)
    got = pallas_capture.compute_a_conv(a, (1, 3), (1, 2), 'SAME', True,
                                        interpret=True)
    assert np.array_equal(np.asarray(got), np.asarray(ref))


def test_multi_tile_value_equal(monkeypatch):
    # KFAC_CAPTURE_TR splits the row reduction across grid steps: the
    # fp32 partial sums accumulate in row-tile order — value-equal up
    # to summation order, never a shape/scaling change
    monkeypatch.setenv('KFAC_CAPTURE_TR', '8')
    a = jnp.asarray(_rng(7).randn(32, 12), jnp.float32)
    ref = factors.compute_a_dense(a, True)
    got = pallas_capture.compute_a_dense(a, True, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-6, atol=1e-7)
    # and the tile knob actually split the grid (divisor lowering)
    assert pallas_capture._row_tile(32, 12) == 8


def test_row_tile_lowers_to_divisor(monkeypatch):
    monkeypatch.setenv('KFAC_CAPTURE_TR', '7')
    assert pallas_capture._row_tile(32, 12) == 4   # nearest divisor <= 7
    monkeypatch.delenv('KFAC_CAPTURE_TR')
    # whole reduction fits the VMEM budget -> one grid step
    assert pallas_capture._row_tile(32, 12) == 32


def test_max_f_cap_falls_back_to_reference(monkeypatch):
    # a factor dim over the VMEM cap stays on the XLA path (bitwise
    # trivially — it IS the reference), with the EMA still applied
    monkeypatch.setenv('KFAC_CAPTURE_MAX_F', '8')
    a = jnp.asarray(_rng(8).randn(16, 12), jnp.float32)   # F=13 > 8
    cur = jnp.eye(13, dtype=jnp.float32)
    ref = factors.update_running_avg(
        factors.compute_a_dense(a, True), cur, 0.95)
    got = pallas_capture.compute_a_dense(a, True, ema=(cur, 0.95),
                                         interpret=True)
    assert np.array_equal(np.asarray(got), np.asarray(ref))


# ---------------------------------------------------------------------------
# 2. the EMA epilogue contract
# ---------------------------------------------------------------------------

def _two_pass_ema(stat_fn, cur, alpha):
    return factors.update_running_avg(stat_fn(), cur, alpha)


@pytest.mark.parametrize('kind', ['a_dense', 'a_conv', 'g_dense',
                                  'g_conv'])
def test_ema_epilogue_within_one_rounding(kind):
    r = _rng(9)
    if kind == 'a_dense':
        x = jnp.asarray(r.randn(16, 10), jnp.float32)
        ref_stat = lambda: factors.compute_a_dense(x, True)
        fused = lambda ema: pallas_capture.compute_a_dense(
            x, True, ema=ema, interpret=True)
        f = 11
    elif kind == 'a_conv':
        x = jnp.asarray(r.randn(3, 7, 7, 2), jnp.float32)
        ref_stat = lambda: factors.compute_a_conv(
            x, (3, 3), (1, 1), 'SAME', True)
        fused = lambda ema: pallas_capture.compute_a_conv(
            x, (3, 3), (1, 1), 'SAME', True, ema=ema, interpret=True)
        f = 19
    elif kind == 'g_dense':
        x = jnp.asarray(r.randn(16, 6), jnp.float32)
        ref_stat = lambda: factors.compute_g_dense(x, True)
        fused = lambda ema: pallas_capture.compute_g_dense(
            x, True, ema=ema, interpret=True)
        f = 6
    else:
        x = jnp.asarray(r.randn(3, 5, 5, 4), jnp.float32)
        ref_stat = lambda: factors.compute_g_conv(x, True)
        fused = lambda ema: pallas_capture.compute_g_conv(
            x, True, ema=ema, interpret=True)
        f = 4
    cur = jnp.asarray(r.randn(f, f).astype(np.float32))
    stat = np.asarray(ref_stat())
    ref = np.asarray(_two_pass_ema(ref_stat, cur, 0.95))
    got = np.asarray(fused((cur, 0.95)))
    # algebraically identical; the final cur*(1-a) + stat*a combine may
    # FMA-contract under jit — ONE fewer fp32 rounding than the unfused
    # program (module docstring contract). A single dropped rounding is
    # worth <= ~1 ulp of the LARGER TERM (where the combine cancels,
    # ulp(ref) itself shrinks but the absolute error cannot), so the
    # bound is in ulps of the intermediate magnitudes
    mag = np.maximum(np.abs(np.asarray(cur)) * np.float32(0.05),
                     np.abs(stat) * np.float32(0.95))
    ulp = np.spacing(mag.astype(np.float32))
    assert np.all(np.abs(got - ref) <= 2 * ulp), (
        np.max(np.abs(got - ref) / ulp))
    # ...and deterministic: a second invocation is bit-identical
    again = np.asarray(fused((cur, 0.95)))
    assert np.array_equal(got, again)


def test_ema_stable_across_steps():
    # iterate the fused EMA as the preconditioner does (output feeds
    # back as `cur`): the trajectory tracks the unfused one within
    # accumulated single-rounding error and never drifts structurally
    r = _rng(10)
    x = jnp.asarray(r.randn(16, 10), jnp.float32)
    stat = factors.compute_a_dense(x, True)
    cur_ref = jnp.eye(11, dtype=jnp.float32)
    cur_fused = cur_ref
    for _ in range(10):
        cur_ref = factors.update_running_avg(stat, cur_ref, 0.95)
        cur_fused = pallas_capture.compute_a_dense(
            x, True, ema=(cur_fused, 0.95), interpret=True)
    np.testing.assert_allclose(np.asarray(cur_fused),
                               np.asarray(cur_ref),
                               rtol=1e-6, atol=1e-7)
    # symmetry is preserved exactly (both inputs symmetric)
    got = np.asarray(cur_fused)
    assert np.array_equal(got, got.T)


def test_traced_alpha_two_passes_bitwise():
    # a TRACED decay cannot be closed over by the kernel: the ema kwarg
    # falls back to stat-kernel + update_running_avg — fully bitwise vs
    # the reference (no fused emit involved)
    x = jnp.asarray(_rng(11).randn(16, 10), jnp.float32)
    cur = jnp.eye(11, dtype=jnp.float32)
    alpha = jnp.float32(0.95)                 # traced, not a python float
    assert not pallas_capture._ema_static((cur, alpha))
    ref = factors.update_running_avg(
        factors.compute_a_dense(x, True), cur, alpha)
    got = pallas_capture.compute_a_dense(x, True, ema=(cur, alpha),
                                         interpret=True)
    assert np.array_equal(np.asarray(got), np.asarray(ref))


# ---------------------------------------------------------------------------
# 3. ef_quantize: the wire-quantize + error-feedback epilogue
# ---------------------------------------------------------------------------

def test_ef_quantize_bitwise_vs_two_pass():
    r = _rng(12)
    x = jnp.asarray(r.randn(8, 6, 6), jnp.float32)
    res = jnp.asarray(r.randn(8, 6, 6).astype(np.float32) * 1e-3)
    wire, new_res = pallas_capture.ef_quantize(x, res, interpret=True)
    xc = x + res
    ref_wire = xc.astype(jnp.bfloat16)
    ref_res = xc - ref_wire.astype(jnp.float32)
    assert wire.dtype == jnp.bfloat16
    assert np.array_equal(np.asarray(wire, dtype=np.float32),
                          np.asarray(ref_wire, dtype=np.float32))
    assert np.array_equal(np.asarray(new_res), np.asarray(ref_res))


def test_ef_quantize_bitwise_under_shard_map():
    # the fused epilogue inside the per-device program of an 8-way
    # shard_map (the pmean_scatter_ef call site): wire and residual
    # stay bitwise vs the two-pass algebra on every shard
    ndev = 8
    if len(jax.devices()) < ndev:
        pytest.skip('needs 8 host devices (conftest XLA_FLAGS)')
    mesh = Mesh(np.array(jax.devices()[:ndev]), ('x',))
    r = _rng(13)
    x = jnp.asarray(r.randn(ndev * 4, 6), jnp.float32)
    res = jnp.asarray(r.randn(ndev * 4, 6).astype(np.float32) * 1e-3)

    def fused(xs, rs):
        return pallas_capture.ef_quantize(
            xs, rs, interpret=pallas_capture.interpret_default())

    def two_pass(xs, rs):
        xc = xs + rs
        wire = xc.astype(jnp.bfloat16)
        return wire, xc - wire.astype(xs.dtype)

    kw = dict(mesh=mesh, in_specs=(P('x'), P('x')),
              out_specs=(P('x'), P('x')))
    w1, r1 = jax.jit(jax.shard_map(fused, **kw))(x, res)
    w2, r2 = jax.jit(jax.shard_map(two_pass, **kw))(x, res)
    assert np.array_equal(np.asarray(w1, dtype=np.float32),
                          np.asarray(w2, dtype=np.float32))
    assert np.array_equal(np.asarray(r1), np.asarray(r2))


# ---------------------------------------------------------------------------
# 4. end-to-end world=1 parity through KFAC.step
# ---------------------------------------------------------------------------

class MLP(linen.Module):
    @linen.compact
    def __call__(self, x, train=True):
        x = knn.Dense(8, name='fc1')(x)
        x = linen.relu(x)
        x = knn.Dense(3, name='fc2')(x)
        return x


def _setup(variant, capture_impl, **kw):
    model = MLP()
    r = _rng(0)
    x = jnp.asarray(r.randn(4, 5), jnp.float32)
    y = jnp.asarray(r.randn(4, 3), jnp.float32)
    variables = capture.init(model, jax.random.PRNGKey(0), x)
    metas = capture.collect_layer_meta(model, variables, x)
    precond = kfac.KFAC(variant=variant, num_devices=1, axis_name=None,
                        bucket_fn=lambda d: 16,
                        capture_impl=capture_impl, **kw)
    precond.setup(metas)
    state = precond.init()
    loss_fn = lambda out: jnp.mean((out - y) ** 2)
    _, _, grads, acts, gs, _ = capture.value_and_grad_with_capture(
        model, loss_fn, variables, x)
    return precond, state, grads, acts, gs


def _tree_equal(a, b):
    flat_a = jax.tree_util.tree_leaves(a)
    flat_b = jax.tree_util.tree_leaves(b)
    assert len(flat_a) == len(flat_b)
    return all(np.array_equal(np.asarray(x), np.asarray(y))
               for x, y in zip(flat_a, flat_b))


@pytest.mark.parametrize('variant', ['inverse', 'eigen_dp'])
def test_step_world1_pallas_matches_legacy(variant):
    """world=1 trajectory parity: 'pallas' (the DP variant takes the
    fully fused update_factors_fused path) preconditions identically to
    the legacy capture — same grads, same factor state — across two
    steps (step 2 consumes step 1's EMA)."""
    pre_x, st_x, grads, acts, gs = _setup(variant, None)
    pre_p, st_p, _, _, _ = _setup(variant, 'pallas')
    for _ in range(2):
        g_x, st_x = pre_x.step(st_x, grads, acts, gs)
        g_p, st_p = pre_p.step(st_p, grads, acts, gs)
    if variant == 'eigen_dp':
        # the DP variant takes update_factors_fused: the EMA emit may
        # FMA-contract (the documented one-rounding exception), so the
        # factor state tracks within ulp-level tolerance — and the
        # damped eigendecomposition amplifies that ulp into ~1e-4
        # relative on the preconditioned gradient (condition ~1/damping)
        for k in st_x.factors:
            np.testing.assert_allclose(
                np.asarray(st_p.factors[k]), np.asarray(st_x.factors[k]),
                rtol=1e-6, atol=1e-7)
        g_rtol, g_atol = 5e-4, 1e-6
    else:
        # stat kernels + two-pass EMA: fully bitwise
        assert _tree_equal(st_x.factors, st_p.factors)
        g_rtol, g_atol = 1e-6, 1e-8
    np.testing.assert_allclose(
        np.asarray(g_p['fc1']['kernel']), np.asarray(g_x['fc1']['kernel']),
        rtol=g_rtol, atol=g_atol)
    np.testing.assert_allclose(
        np.asarray(g_p['fc2']['kernel']), np.asarray(g_x['fc2']['kernel']),
        rtol=g_rtol, atol=g_atol)


def test_step_world1_xla_is_legacy_bitwise():
    """capture_impl='xla' routes through the identical ops/factors.py
    calls — bit-for-bit the None (legacy) program."""
    pre_n, st_n, grads, acts, gs = _setup('eigen', None)
    pre_x, st_x, _, _, _ = _setup('eigen', 'xla')
    g_n, st_n = pre_n.step(st_n, grads, acts, gs)
    g_x, st_x = pre_x.step(st_x, grads, acts, gs)
    assert _tree_equal(st_n.factors, st_x.factors)
    assert _tree_equal(g_n, g_x)


def test_auto_resolves_to_pallas():
    pre = kfac.KFAC(variant='eigen', capture_impl='auto')
    assert pre.resolved_capture_impl == 'pallas'
    assert kfac.KFAC(variant='eigen').resolved_capture_impl is None
    with pytest.raises(ValueError, match='capture_impl'):
        kfac.KFAC(variant='eigen', capture_impl='fused')


# ---------------------------------------------------------------------------
# 5. compile-count guard on ladder switches
# ---------------------------------------------------------------------------

def _ce(outputs, batch):
    return optax.softmax_cross_entropy_with_integer_labels(
        outputs, batch['label']).mean()


def test_capture_ladder_switch_compile_count():
    """A capture_impl move through the arbiter clears the variant cache
    (trace-affecting, like comm_precision); steps at the committed rung
    then fill a bounded variant set, and REPLAYING the committed
    trajectory compiles exactly nothing."""
    r = _rng(0)
    batch = {'input': jnp.asarray(r.randn(8, 5), jnp.float32),
             'label': jnp.asarray(r.randint(0, 3, 8))}
    model = MLP()
    pre = kfac.KFAC(variant='eigen_dp', lr=0.05, damping=0.003,
                    num_devices=1, axis_name=None,
                    bucket_fn=lambda d: 16, capture_impl='xla')
    tx = training.sgd(0.05, momentum=0.9)
    state = training.init_train_state(model, tx, pre,
                                      jax.random.PRNGKey(0),
                                      batch['input'])
    step = training.build_train_step(model, tx, pre, _ce,
                                     axis_name=None, mesh=None)
    arb = autotune.arbiter_for(pre)
    for _ in range(3):
        state, _ = step(state, batch, lr=0.05, damping=0.003)
    assert step.variants
    # the ladder commit: xla -> pallas clears the cache exactly once
    arb.propose('tuner', capture_impl='pallas')
    assert pre.capture_impl == 'pallas'
    assert not step.variants
    for _ in range(4):
        state, m = step(state, batch, lr=0.05, damping=0.003)
    assert np.isfinite(float(m['loss']))
    committed = set(step.variants)
    assert committed
    # zero recompiles replaying the committed trajectory
    for _ in range(6):
        state, _ = step(state, batch, lr=0.05, damping=0.003)
    assert set(step.variants) == committed, (
        sorted(map(str, set(step.variants) - committed)))
