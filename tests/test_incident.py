"""Incident-report scraper drills (resilience/incident.py).

The scraper's contract is "parses exactly what the resilience modules
emit", so the fixture lines below are copied from the real log formats
(supervisor restarts/give-up, watchdog trip, heartbeat declaration,
elastic shrink, straggler ladder, trainer RESUMED/RESHARDED protocol
lines) — a format drift in either direction fails here.
"""

import json

import pytest

from kfac_pytorch_tpu.resilience.incident import (
    IncidentReport, main as incident_main, scrape_paths)

LOG = """\
2026-08-02 10:00:01 epoch 0: train_loss 1.9 val_loss 1.8 val_acc 0.40 (12.1s)
2026-08-02 10:00:09 straggler: step-time EMA 2.513s over budget 1.000s at step 37 — stretching update freqs to fac=2 kfac=4 (level 1/3)
2026-08-02 10:00:30 straggler: recovered (EMA 0.612s) at step 61 — update freqs restored to fac=1 kfac=2
2026-08-02 10:00:41 epoch 1: train_loss 1.2 val_loss 1.3 val_acc 0.55 (11.8s) [resilience: io_retries=2 straggler_degrades=1 straggler_recoveries=1]
2026-08-02 10:01:02 heartbeat: peer 1 declared dead — no heartbeat advance for 3.21s (deadline 3.00s, last step 88) [resilience: peer_dead=1 peer=1 detect_s=3.21]
2026-08-02 10:01:02 watchdog: step deadline exceeded (40.0s, step 88) — dumping all thread stacks and exiting rc=114 so the supervisor can restart this trainer
2026-08-02 10:01:03 supervisor: trainer exited rc=-9 (killed by signal 9) — restart 1/3 in 0.41s [resilience: crashes=1 restarts=1]
2026-08-02 10:01:05 elastic: shrinking world 2 -> 1 survivors=[0] gen=1 [resilience: restarts=1 shrinks=1]
RESHARDED from_world=2 to_world=1 step=88
RESUMED from=checkpoint-1 step=88
2026-08-02 10:02:00 epoch 2: train_loss 0.9 val_loss 1.0 val_acc 0.61 (12.0s)
"""

GAVE_UP = ('2026-08-02 11:00:00 supervisor: trainer exited rc=113 (crash) '
           'and the restart budget (2) is spent — giving up '
           '[resilience: crashes=3 gave_up=1 restarts=2]')

# one full GROW cycle, fixture lines copied from the real log forms
# (heartbeat.JoinAnnouncer, elastic._grow / _join_pod, elastic_resume,
# training.WorldRescale.log_line) — the churn counterpart of LOG above
GROW_LOG = """\
2026-08-02 12:00:00,000 join: host 1 announcing to pod (lease /shared/hb) [resilience: join_announce=1 host=1]
2026-08-02 12:00:01,000 pod-supervisor: join announced — stopping the trainer for the grow barrier
2026-08-02 12:00:01,000 elastic: grow claim written host=0 gen=2
2026-08-02 12:00:02,000 elastic: grow claim written host=1 gen=2
2026-08-02 12:00:03,000 elastic: growing world 2 -> 3 members=[0, 1, 2] gen=2 joiners=[1] [resilience: restarts=2 shrinks=1 grows=1]
2026-08-02 12:00:04,000 join: admitted into pod as rank 1 — world 3 gen=2 members=[0, 1, 2] [resilience: joins=1]
2026-08-02 12:00:09,000 elastic: grow reshard from_world=2 to_world=3 step=142
RESHARDED from_world=2 to_world=3 step=142
WORLD_RESCALE from_world=2 to_world=3 global_batch=96 lr=0.1 lr_factor=1
RESUMED from=checkpoint-3 step=142
"""


def test_scrape_extracts_grow_cycle():
    """The grow-lane grammar (ISSUE 6 satellite): every protocol stage
    of a rejoin — announcement, claims, barrier agreement, upward
    reshard, hyper-parameter rescale — is a typed event, and the shared
    EVENT_PATTERNS table means kfac-obs renders the same cycle with no
    code of its own."""
    rep = IncidentReport(host_id=0).scrape_lines(GROW_LOG.splitlines())
    kinds = [e['kind'] for e in rep.events]
    for expected in ('join_announce', 'grow_claim', 'grow',
                     'grow_resharded', 'world_rescale', 'resharded',
                     'resumed'):
        assert expected in kinds, (expected, kinds)
    d = rep.to_dict()
    assert d['grows'] == [{'from': 2, 'to': 3, 'members': '[0, 1, 2]',
                           'joiners': '[1]', 'gen': 2}]
    grow_claims = [e for e in rep.events if e['kind'] == 'grow_claim']
    assert [(e['host'], e['gen']) for e in grow_claims] == [(0, 2),
                                                            (1, 2)]
    reshard = next(e for e in rep.events
                   if e['kind'] == 'grow_resharded')
    assert (reshard['from'], reshard['to'], reshard['step']) == (2, 3,
                                                                 142)
    rescale = next(e for e in rep.events
                   if e['kind'] == 'world_rescale')
    assert rescale['global_batch'] == 96 and rescale['lr_factor'] == 1
    # cumulative counters: grows/joins max'd, announce-host field is
    # NOT a counter
    assert rep.counters['grows'] == 1 and rep.counters['joins'] == 1
    assert 'host' not in rep.counters
    assert 'pod grew 2 -> 3 hosts' in rep.summary()


def test_grow_events_land_on_the_pod_timeline(tmp_path):
    """Shared-grammar invariant, exercised from the OTHER consumer: the
    kfac-obs timeline renders the grow cycle in causal clock order from
    the same pattern table."""
    from kfac_pytorch_tpu.obs import aggregate
    log = tmp_path / 'host0.out'
    log.write_text(GROW_LOG)
    timeline = aggregate.build_timeline([str(log)])
    kinds = [e['kind'] for e in timeline['events']]
    i_join = kinds.index('join_announce')
    i_claim = kinds.index('grow_claim')
    i_grow = kinds.index('grow')
    i_reshard = kinds.index('grow_resharded')
    assert i_join < i_claim < i_grow < i_reshard
    walls = [timeline['events'][i]['wall_aligned']
             for i in (i_join, i_claim, i_grow, i_reshard)]
    assert all(w is not None for w in walls)
    assert walls == sorted(walls)


def _report(text=LOG):
    return IncidentReport(host_id=0).scrape_lines(text.splitlines())


def test_scrape_extracts_every_event_kind():
    kinds = [e['kind'] for e in _report().events]
    for expected in ('straggler_degrade', 'straggler_recover',
                     'peer_dead', 'watchdog_trip', 'restart', 'shrink',
                     'resharded', 'resumed'):
        assert expected in kinds, (expected, kinds)


def test_report_answers_the_incident_questions():
    d = _report().to_dict()
    # what died
    assert d['what_died'] == [{'peer': 1, 'detect_s': 3.21,
                               'wall': None}]
    # when / how fast it was caught
    assert d['what_died'][0]['detect_s'] < 40.0  # beat the watchdog
    # restarts taken
    assert d['restarts_taken'] == 1
    # shrink history
    assert d['shrinks'] == [{'from': 2, 'to': 1, 'survivors': '[0]',
                             'gen': 1}]
    # degrade windows
    assert d['degrade_windows'] == 1
    assert d['gave_up'] is False


def test_counter_aggregation_sums_deltas_maxes_cumulatives():
    rep = IncidentReport()
    rep.scrape_lines([
        'epoch 1: x [resilience: io_retries=2]',
        'epoch 2: x [resilience: io_retries=3]',          # delta: sum
        'supervisor: x [resilience: restarts=1 crashes=1]',
        'supervisor: x [resilience: restarts=2 crashes=2]',  # cum: max
    ])
    assert rep.counters['io_retries'] == 5
    assert rep.counters['restarts'] == 2
    assert rep.counters['crashes'] == 2
    # heartbeat event FIELDS riding in a suffix are not counters
    rep.scrape_lines(['x [resilience: peer_dead=1 peer=1 detect_s=3.2]'])
    assert 'peer' not in rep.counters and 'detect_s' not in rep.counters
    assert rep.counters['peer_dead'] == 1


REPLICATED_LOG = """\
coord-replicated: replica 10.0.0.2:8479 down — coord kv 10.0.0.2:8479 \
unreachable ([Errno 111] Connection refused) (2/3 replicas reachable) \
[resilience: replica_down=1]
coord-replicated: quorum degraded — 2 of 3 replicas answering \
(quorum 2) [resilience: quorum_degraded=1]
coord-replicated: replica 10.0.0.2:8479 repaired key=lineage.json \
rrev=4 [resilience: replica_repair=1]
"""


def test_scrape_extracts_replicated_quorum_story():
    """The replicated backend's log forms land in the shared grammar:
    an operator timeline reads replica_down -> quorum_degraded ->
    replica_repair with NO coord_lost in between — one replica down is
    the absorbed case, not an incident verdict."""
    rep = IncidentReport(host_id=0).scrape_lines(
        REPLICATED_LOG.splitlines())
    by_kind = {}
    for e in rep.events:
        by_kind.setdefault(e['kind'], []).append(e)
    down = by_kind['replica_down'][0]
    assert down['replica'] == '10.0.0.2:8479'
    assert (down['up'], down['total']) == (2, 3)
    deg = by_kind['quorum_degraded'][0]
    assert (deg['up'], deg['total'], deg['quorum']) == (2, 3, 2)
    repair = by_kind['replica_repair'][0]
    assert repair['replica'] == '10.0.0.2:8479'
    assert repair['key'] == 'lineage.json' and repair['rrev'] == 4
    assert 'coord_lost' not in by_kind and 'coord_gave_up' not in by_kind
    # the [resilience: ...] suffixes aggregate as per-event deltas
    assert rep.counters['replica_down'] == 1
    assert rep.counters['quorum_degraded'] == 1
    assert rep.counters['replica_repair'] == 1


def test_replicated_events_come_from_the_real_emitters(tmp_path):
    """Grammar-vs-emitter drift gate: scrape lines PRODUCED by the real
    ReplicatedKvBackend (a replica killed under it), not hand-copied
    fixtures."""
    import logging
    import time
    from kfac_pytorch_tpu.coord import ReplicatedKvBackend, TcpKvBackend
    from kfac_pytorch_tpu.coord import TcpKvServer
    servers = [TcpKvServer('127.0.0.1', 0) for _ in range(3)]
    logger = logging.getLogger('test-replicated-emitters')
    records = []

    class _Capture(logging.Handler):
        def emit(self, record):
            records.append(record.getMessage())

    logger.addHandler(_Capture())
    logger.setLevel(logging.DEBUG)
    b = ReplicatedKvBackend(
        [TcpKvBackend(('127.0.0.1', s.port),
                      namespace=str(tmp_path), timeout=0.3)
         for s in servers], log=logger, down_cooldown=0.01)
    try:
        b.put('lineage.json', {'lineage': 1})
        port = servers[1].port
        servers[1].close()
        b.put('lineage.json', {'lineage': 2})
        servers[1] = TcpKvServer('127.0.0.1', port)  # empty store
        time.sleep(0.02)
        assert b.get('lineage.json').value == {'lineage': 2}
    finally:
        for s in servers:
            s.close()
    rep = IncidentReport(host_id=0).scrape_lines(records)
    kinds = {e['kind'] for e in rep.events}
    assert {'replica_down', 'quorum_degraded',
            'replica_repair'} <= kinds, (kinds, records)


def test_gave_up_is_machine_detectable():
    rep = IncidentReport().scrape_lines([GAVE_UP])
    d = rep.to_dict()
    assert d['gave_up'] is True
    assert rep.counters['gave_up'] == 1
    assert 'GAVE UP' in rep.summary()


def test_live_events_and_scraped_lines_compose():
    rep = IncidentReport(host_id=0)
    rep.add_event('peer_dead', peer=3, detect_s=1.5, last_step=200)
    rep.add_event('shrink', **{'from': 4, 'to': 3,
                               'survivors': [0, 1, 2], 'gen': 1})
    rep.scrape_lines(['epoch 9: x [resilience: io_retries=1]'])
    d = rep.to_dict()
    assert d['what_died'][0]['peer'] == 3
    assert d['shrinks'][0]['survivors'] == [0, 1, 2]
    assert d['counters']['io_retries'] == 1
    s = rep.summary()
    assert 'peer 3 died' in s and '4 -> 3' in s


def test_write_is_atomic_json(tmp_path):
    rep = _report()
    out = tmp_path / 'incident.json'
    rep.write(str(out))
    d = json.loads(out.read_text())
    assert d['host_id'] == 0
    assert d['what_died'][0]['peer'] == 1
    assert not list(tmp_path.glob('*.tmp-*'))  # no torn tmp left behind


def test_cli_scrapes_files_and_writes_report(tmp_path, capsys):
    log1 = tmp_path / 'run1.log'
    log1.write_text(LOG)
    log2 = tmp_path / 'run2.log'
    log2.write_text(GAVE_UP + '\n')
    out = tmp_path / 'incident.json'
    rc = incident_main([str(log1), str(log2), '-o', str(out)])
    assert rc == 0
    stdout = capsys.readouterr().out
    assert 'peer 1 died' in stdout
    assert 'GAVE UP' in stdout
    d = json.loads(out.read_text())
    assert sorted(d['sources']) == sorted([str(log1), str(log2)])
    assert d['gave_up'] is True


def test_scrape_paths_merges(tmp_path):
    (tmp_path / 'a.log').write_text(LOG)
    (tmp_path / 'b.log').write_text(LOG)
    rep = scrape_paths([str(tmp_path / 'a.log'), str(tmp_path / 'b.log')])
    assert len(rep.to_dict()['what_died']) == 2


def test_clean_run_summary():
    rep = IncidentReport(host_id=2).scrape_lines(
        ['epoch 0: train_loss 1.0 val_loss 1.0 val_acc 0.5 (9.0s)'])
    assert 'clean run' in rep.summary()
    d = rep.to_dict()
    assert d['what_died'] == [] and d['restarts_taken'] == 0


@pytest.mark.parametrize('line,key,value', [
    (GAVE_UP, 'gave_up', 1),
    ('x [resilience: watchdog_trips=2]', 'watchdog_trips', 2),
])
def test_suffix_parse_contract(line, key, value):
    from kfac_pytorch_tpu.utils.runlog import parse_resilience_suffix
    assert parse_resilience_suffix(line)[key] == value


def test_supervisor_terminal_verdicts_are_events():
    """Regression for the ISSUE 15 event-grammar lint finding: the
    supervisor's preemption-shutdown and configured-stop verdicts were
    emitted with k=v payloads that no EVENT_PATTERNS regex matched —
    invisible on incident reports and kfac-obs timelines while the
    third terminal verdict (gave_up) was a first-class event. Pin the
    two new patterns against the exact emit forms in supervisor.py."""
    rep = IncidentReport(host_id=0).scrape_lines([
        'supervisor: trainer exited rc=-15 after forwarded signal '
        '— preemption shutdown, not restarting '
        '[resilience: restarts=0]',
        'supervisor: trainer exited rc=117 (configured stop code) '
        '— not restarting [resilience: restarts=1]',
    ])
    by = {e['kind']: e for e in rep.events}
    assert by['preempt_stop']['rc'] == -15
    assert by['stop_rc']['rc'] == 117
