"""NLP model-family tests: Transformer (enc-dec), BERT QA, LSTM LM,
beam/greedy decoding, and corpus BLEU (reference model zoo:
examples/transformer/, pytorch_squad_bert.py, wikitext_models.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kfac_pytorch_tpu import capture
from kfac_pytorch_tpu.models import bert, transformer, translator
from kfac_pytorch_tpu.models.rnn import wikitext_lstm

SRC_V, TRG_V, B, L = 53, 57, 2, 10


@pytest.fixture(scope='module')
def tiny_transformer():
    model = transformer.multi30k_transformer(
        SRC_V, TRG_V, d_word_vec=32, d_model=32, d_inner=64, n_layers=2,
        n_head=4, d_k=8, d_v=8, dropout=0.0)
    rng = np.random.RandomState(0)
    src = jnp.asarray(rng.randint(4, SRC_V, (B, L)))
    trg = jnp.asarray(rng.randint(4, TRG_V, (B, L)))
    variables = capture.init(model, jax.random.PRNGKey(0), src, trg,
                             train=False)
    return model, variables, src, trg


def test_transformer_logits_shape(tiny_transformer):
    model, variables, src, trg = tiny_transformer
    out = model.apply(variables, src, trg, train=False)
    assert out.shape == (B, L, TRG_V)
    assert np.isfinite(np.asarray(out)).all()


def test_transformer_kfac_layers_discovered(tiny_transformer):
    model, variables, src, trg = tiny_transformer
    metas = capture.collect_layer_meta(model, variables, src, trg,
                                       train=False)
    # attention q/k/v/o + 2 FFN per layer, 2 enc + 2 dec layers (dec has
    # self+cross attn); default head is weight-tied (no Dense layer)
    assert len(metas) > 20
    # untied head: a vocab-sized Dense appears and the exclusion drops it
    untied = transformer.multi30k_transformer(
        SRC_V, TRG_V, d_word_vec=32, d_model=32, d_inner=64, n_layers=2,
        n_head=4, d_k=8, d_v=8, dropout=0.0,
        trg_emb_prj_weight_sharing=False)
    uvars = capture.init(untied, jax.random.PRNGKey(0), src, trg,
                         train=False)
    m_all = capture.collect_layer_meta(untied, uvars, src, trg,
                                       train=False)
    m_excl = capture.collect_layer_meta(
        untied, uvars, src, trg, train=False,
        exclude_vocabulary_size=TRG_V)
    assert len(m_excl) == len(m_all) - 1  # vocab-sized head dropped


def test_greedy_and_beam_decode(tiny_transformer):
    model, variables, src, _ = tiny_transformer
    g = translator.greedy_decode(model, variables, src, bos_idx=2,
                                 eos_idx=3, max_len=8)
    assert g.shape[0] == B and g.shape[1] <= 9
    # beam search is per-sentence (reference Translator semantics)
    hyp = translator.beam_search_decode(model, variables, src[0],
                                        bos_idx=2, eos_idx=3, beam_size=3,
                                        max_len=8)
    assert isinstance(hyp, list) and 0 < len(hyp) <= 9
    assert all(isinstance(t, int) for t in hyp)


def test_bleu_sanity():
    perfect = translator.bleu([[1, 2, 3, 4, 5]], [[1, 2, 3, 4, 5]])
    assert abs(perfect - 100.0) < 1e-6
    bad = translator.bleu([[9, 9, 9, 9, 9]], [[1, 2, 3, 4, 5]])
    assert bad < 1.0
    partial = translator.bleu([[1, 2, 3, 4, 5, 9]], [[1, 2, 3, 4, 5]])
    assert bad < partial < perfect


def test_bert_tiny_qa_shapes():
    model = bert.bert_tiny_qa()
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, 100, (B, 16)))
    inputs = (ids, jnp.zeros_like(ids),
              jnp.ones_like(ids, dtype=jnp.float32))
    variables = capture.init(model, jax.random.PRNGKey(0), inputs,
                             train=False)
    start, end = model.apply(variables, inputs, train=False)
    assert start.shape == (B, 16) and end.shape == (B, 16)


def test_wikitext_lstm_forward():
    model = wikitext_lstm(vocab_size=64, embed_dim=32, hidden_dim=32,
                          num_layers=2, dropout=0.0)
    toks = jnp.asarray(np.random.RandomState(0).randint(0, 64, (B, 12)))
    variables = capture.init(model, jax.random.PRNGKey(0), toks,
                             train=False)
    out = model.apply(variables, toks, train=False)
    assert out.shape == (B, 12, 64)


def test_kfac_lstm_capture_and_training():
    """kfac_lstm=True (beyond reference: the reference's RNN K-FAC is
    declared broken, pytorch_wikitext_rnn.py:6): the scanned cell's ih/hh
    projections are discovered, capture per-timestep (a, g), and an
    eigen_dp step trains the LM."""
    import optax

    import kfac_pytorch_tpu as kfac
    from kfac_pytorch_tpu import capture, training

    m = wikitext_lstm(50, embed_dim=16, hidden_dim=16, num_layers=1,
                      dropout=0.0, kfac_lstm=True)
    toks = jnp.asarray(np.random.RandomState(0).randint(0, 50, (4, 8)))
    batch = {'input': toks, 'label': jnp.roll(toks, -1, 1)}
    variables = capture.init(m, jax.random.PRNGKey(0), toks, train=False)

    metas = capture.collect_layer_meta(m, variables, toks, train=False,
                                       exclude_vocabulary_size=50)
    assert set(metas) == {'lstm_scan_0/ih', 'lstm_scan_0/hh'}, metas
    assert metas['lstm_scan_0/ih'].in_dim == 17    # E + bias
    assert metas['lstm_scan_0/hh'].in_dim == 16    # H, no bias
    assert metas['lstm_scan_0/hh'].out_dim == 64   # 4H

    def ce(o, b):
        return optax.softmax_cross_entropy_with_integer_labels(
            o, b['label']).mean()

    _, _, _, acts, gs, _ = capture.value_and_grad_with_capture(
        m, lambda o: ce(o, batch), variables, toks, train=False)
    # time axis is stacked in front by nn.scan: per-timestep capture
    assert acts['lstm_scan_0']['hh']['a'].shape == (8, 4, 16)
    assert gs['lstm_scan_0']['hh']['g'].shape == (8, 4, 64)
    # both projections share the same gate cotangent
    np.testing.assert_allclose(np.asarray(gs['lstm_scan_0']['hh']['g']),
                               np.asarray(gs['lstm_scan_0']['ih']['g']),
                               atol=1e-6)

    precond = kfac.KFAC(variant='eigen_dp', lr=0.5, damping=0.003,
                        fac_update_freq=1, kfac_update_freq=1,
                        num_devices=1, axis_name=None,
                        exclude_vocabulary_size=50)
    tx = training.sgd(0.5, momentum=0.9)
    state = training.init_train_state(m, tx, precond, jax.random.PRNGKey(0),
                                      batch['input'])
    step = training.build_train_step(m, tx, precond, ce)
    losses = []
    for _ in range(8):
        state, mm = step(state, batch, lr=0.5, damping=0.003)
        losses.append(float(mm['loss']))
    assert losses[-1] < losses[0] - 0.5, losses
    assert [me.name for me in precond.plan.metas] == [
        'lstm_scan_0/ih', 'lstm_scan_0/hh']
