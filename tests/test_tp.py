"""Tensor-parallel layers + per-slice K-FAC (parallel/tp.py) on the CPU
mesh: forward/backward must be EXACTLY the unsharded dense math, and each
model-rank's K-FAC must equal an exact per-slice oracle (the same local
module run on one device with the other ranks' partial output folded into
the loss as a constant)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from flax import linen
from jax.sharding import Mesh, PartitionSpec as P

import kfac_pytorch_tpu as kfac
from kfac_pytorch_tpu import capture
from kfac_pytorch_tpu import nn as knn
from kfac_pytorch_tpu.parallel import tp
from tests import helpers

# These oracles differentiate INSIDE the shard_map body; the legacy
# shard_map shim (check_rep=False) drops the cross-axis psum on
# replicated-operand cotangents there, so they cannot run on this
# backend. The guard is a live probe, not a version pin — the tests
# come back automatically on a backend with vma-tracked shard_map.
# K-FAC's own composed-mesh step path is covered backend-independently
# by tests/test_meshplan.py (oracle capture operands, no in-body grads).
requires_body_autodiff = pytest.mark.skipif(
    helpers.shard_map_body_autodiff_broken(),
    reason='legacy shard_map shim (check_rep=False) mis-transposes '
           'in-body autodiff: replicated-operand cotangents miss their '
           'cross-axis psum (probe: tests/helpers.py'
           '::shard_map_body_autodiff_broken)')

B, DIN, DH, DOUT, NM = 8, 6, 8, 5, 2     # NM model ranks; DH_local = DH/NM
DH_L = DH // NM
LR, DAMPING = 0.1, 0.01

PARAM_SPECS = {
    'l1': {'slice': {'kernel': P(None, 'model'), 'bias': P('model')}},
    'l2': {'slice': {'kernel': P('model', None)}, 'bias': P()},
}


class TPMLP(linen.Module):
    """Column -> relu -> Row; with axis=None this same module IS the
    single-device per-slice oracle (local widths, no reduction)."""
    axis: object = 'model'

    @linen.compact
    def __call__(self, x, train=True):
        x = tp.ColumnParallelDense(DH_L, axis=self.axis, name='l1')(x)
        x = linen.relu(x)
        return tp.RowParallelDense(DOUT, axis=self.axis, name='l2')(x)


def _data(seed=0):
    rng = np.random.RandomState(seed)
    return (jnp.asarray(rng.randn(B, DIN), jnp.float32),
            jnp.asarray(rng.randint(0, DOUT, B)))


def _global_params(seed=1):
    rng = np.random.RandomState(seed)
    return {
        'l1': {'slice': {
            'kernel': jnp.asarray(rng.randn(DIN, DH) * 0.5, jnp.float32),
            'bias': jnp.asarray(rng.randn(DH) * 0.1, jnp.float32)}},
        'l2': {'slice': {
            'kernel': jnp.asarray(rng.randn(DH, DOUT) * 0.5, jnp.float32)},
            'bias': jnp.asarray(rng.randn(DOUT) * 0.1, jnp.float32)},
    }


def _slice_params(gp, i):
    """Model-rank i's local view of the global params."""
    s = slice(i * DH_L, (i + 1) * DH_L)
    return {
        'l1': {'slice': {'kernel': gp['l1']['slice']['kernel'][:, s],
                         'bias': gp['l1']['slice']['bias'][s]}},
        'l2': {'slice': {'kernel': gp['l2']['slice']['kernel'][s]},
               'bias': gp['l2']['bias']},
    }


def _ce(out, y):
    return optax.softmax_cross_entropy_with_integer_labels(out, y).mean()


def _model_mesh():
    return Mesh(np.array(jax.devices()[:NM]), ('model',))


@requires_body_autodiff
def test_tp_forward_backward_exact():
    """The sharded column->row computation IS the full dense math: outputs
    match the unsharded model exactly, and every rank's parameter grads
    are the corresponding slices of the full model's grads."""
    x, y = _data()
    gp = _global_params()
    model = TPMLP(axis='model')

    @functools.partial(jax.shard_map, mesh=_model_mesh(),
                       in_specs=(PARAM_SPECS, P(), P()),
                       out_specs=(P(), PARAM_SPECS))
    def fwd_bwd(params, x, y):
        def loss_fn(p):
            return _ce(model.apply({'params': p}, x), y)
        loss, grads = jax.value_and_grad(loss_fn)(params)
        return loss, grads

    loss_tp, grads_tp = fwd_bwd(gp, x, y)

    class FullMLP(linen.Module):
        @linen.compact
        def __call__(self, x):
            x = knn.Dense(DH, name='l1')(x)
            x = linen.relu(x)
            return knn.Dense(DOUT, name='l2')(x)

    full_params = {'l1': {'kernel': gp['l1']['slice']['kernel'],
                          'bias': gp['l1']['slice']['bias']},
                   'l2': {'kernel': gp['l2']['slice']['kernel'],
                          'bias': gp['l2']['bias']}}

    def full_loss(p):
        return _ce(FullMLP().apply({'params': p}, x), y)

    loss_full, grads_full = jax.value_and_grad(full_loss)(full_params)
    np.testing.assert_allclose(float(loss_tp), float(loss_full), rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(grads_tp['l1']['slice']['kernel']),
        np.asarray(grads_full['l1']['kernel']), atol=1e-6)
    np.testing.assert_allclose(
        np.asarray(grads_tp['l1']['slice']['bias']),
        np.asarray(grads_full['l1']['bias']), atol=1e-6)
    np.testing.assert_allclose(
        np.asarray(grads_tp['l2']['slice']['kernel']),
        np.asarray(grads_full['l2']['kernel']), atol=1e-6)
    np.testing.assert_allclose(
        np.asarray(grads_tp['l2']['bias']),
        np.asarray(grads_full['l2']['bias']), atol=1e-6)


def _make_precond(variant, num_devices=1, axis_name=None):
    pre = kfac.KFAC(variant=variant, lr=LR, damping=DAMPING,
                    fac_update_freq=1, kfac_update_freq=1,
                    num_devices=num_devices, axis_name=axis_name)
    local = TPMLP(axis=None)
    x, _ = _data()
    variables = capture.init(local, jax.random.PRNGKey(0), x)
    metas = capture.collect_layer_meta(local, variables, x)
    pre.setup(metas)
    return pre


@pytest.mark.parametrize('variant', ['eigen_dp', 'inverse_dp'])
@requires_body_autodiff
def test_tp_kfac_matches_per_slice_oracle(variant):
    """Each model-rank's preconditioned update equals the exact oracle:
    the SAME local module on one device, with the other ranks' partial
    output folded into the loss as a constant (so its capture sees
    exactly the rank's activations and cotangents)."""
    x, y = _data()
    gp = _global_params()
    model = TPMLP(axis='model')
    pre = _make_precond(variant)
    state0 = pre.init()
    # per-model-rank K-FAC state: identical init stacked on a leading
    # 'model'-sharded axis; each rank squeezes its own copy inside
    kstate = jax.tree.map(lambda a: jnp.stack([a] * NM), state0)
    kspecs = jax.tree.map(lambda _: P('model'), kstate)

    @functools.partial(jax.shard_map, mesh=_model_mesh(),
                       in_specs=(PARAM_SPECS, kspecs, P(), P()),
                       out_specs=PARAM_SPECS)
    def tp_step(params, kstate, x, y):
        # axis_name marks the taps varying over 'model': without it the
        # zero taps are axis-invariant and vma autodiff would psum their
        # cotangents across model ranks (x NM factor in every G)
        _, _, grads, acts, gs, _ = capture.value_and_grad_with_capture(
            model, lambda out: _ce(out, y), {'params': params}, x,
            axis_name='model')
        k = jax.tree.map(lambda a: a[0], kstate)
        new_grads, _ = pre.step(k, grads, acts, gs)
        return new_grads

    got = tp_step(gp, kstate, x, y)

    # full output for the constant-folding oracle loss
    class FullMLP(linen.Module):
        @linen.compact
        def __call__(self, x):
            x = knn.Dense(DH, name='l1')(x)
            x = linen.relu(x)
            return knn.Dense(DOUT, name='l2')(x)
    full_y = FullMLP().apply({'params': {
        'l1': {'kernel': gp['l1']['slice']['kernel'],
               'bias': gp['l1']['slice']['bias']},
        'l2': {'kernel': gp['l2']['slice']['kernel'],
               'bias': gp['l2']['bias']}}}, x)

    local = TPMLP(axis=None)
    for i in range(NM):
        sp = _slice_params(gp, i)
        own_y = local.apply({'params': sp}, x)
        const = jax.lax.stop_gradient(full_y - own_y)
        pre_i = _make_precond(variant)
        _, _, grads, acts, gs, _ = capture.value_and_grad_with_capture(
            local, lambda out: _ce(out + const, y), {'params': sp}, x)
        want, _ = pre_i.step(pre_i.init(), grads, acts, gs)
        s = slice(i * DH_L, (i + 1) * DH_L)
        np.testing.assert_allclose(
            np.asarray(got['l1']['slice']['kernel'][:, s]),
            np.asarray(want['l1']['slice']['kernel']),
            rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(
            np.asarray(got['l1']['slice']['bias'][s]),
            np.asarray(want['l1']['slice']['bias']),
            rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(
            np.asarray(got['l2']['slice']['kernel'][s]),
            np.asarray(want['l2']['slice']['kernel']),
            rtol=1e-4, atol=1e-5)
        # the replicated post-reduction bias is outside the slice factors:
        # its update is the plain gradient, identical on every rank
        np.testing.assert_allclose(np.asarray(got['l2']['bias']),
                                   np.asarray(want['l2']['bias']),
                                   rtol=1e-4, atol=1e-5)


@requires_body_autodiff
def test_dp_tp_kfac_matches_model_only_full_batch():
    """2x2 ('data', 'model') mesh with the K-FAC world on the data axis
    (MPD 'eigen': pmean-reduced stats) == the model-only mesh run on the
    full batch — data sharding must not change the math."""
    ND = 2
    x, y = _data()
    gp = _global_params()
    model = TPMLP(axis='model')

    pre_dp = _make_precond('eigen', num_devices=ND, axis_name='data')
    state0 = pre_dp.init()
    kstate = jax.tree.map(lambda a: jnp.stack([a] * NM), state0)
    kpspecs = pre_dp.state_pspecs('data')
    # leading 'model' axis on every leaf, then the kfac world's own specs
    kspecs = jax.tree.map(lambda s: P('model', *s), kpspecs,
                          is_leaf=lambda v: isinstance(v, P))
    mesh = Mesh(np.array(jax.devices()[:ND * NM]).reshape(ND, NM),
                ('data', 'model'))

    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(PARAM_SPECS, kspecs, P('data'), P('data')),
        out_specs=PARAM_SPECS)
    def dp_tp_step(params, kstate, x, y):
        # taps must vary over EVERY mesh axis of the step ('data' AND
        # 'model') or their cotangents get cross-rank psummed
        _, _, grads, acts, gs, _ = capture.value_and_grad_with_capture(
            model, lambda out: _ce(out, y), {'params': params}, x,
            axis_name=('data', 'model'))
        grads = kfac.parallel.average_grads(grads, 'data')
        k = jax.tree.map(lambda a: a[0], kstate)
        new_grads, _ = pre_dp.step(k, grads, acts, gs, axis_name='data')
        return new_grads

    got = dp_tp_step(gp, kstate, x, y)

    pre_1 = _make_precond('eigen')
    k1 = jax.tree.map(lambda a: jnp.stack([a] * NM), pre_1.init())

    @functools.partial(jax.shard_map, mesh=_model_mesh(),
                       in_specs=(PARAM_SPECS,
                                 jax.tree.map(lambda _: P('model'), k1),
                                 P(), P()),
                       out_specs=PARAM_SPECS)
    def tp_step(params, kstate, x, y):
        _, _, grads, acts, gs, _ = capture.value_and_grad_with_capture(
            model, lambda out: _ce(out, y), {'params': params}, x,
            axis_name='model')
        k = jax.tree.map(lambda a: a[0], kstate)
        new_grads, _ = pre_1.step(k, grads, acts, gs)
        return new_grads

    want = tp_step(gp, k1, x, y)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-4),
        got, want)


# ---------------------------------------------------------------------------
# Megatron transformer block
# ---------------------------------------------------------------------------

TD, TH, TDK, TDI, TL = 16, 4, 4, 32, 6   # d_model, heads, d_k=d_v, d_inner, L
TH_L, TDI_L = TH // NM, TDI // NM

TP_BLOCK_SPECS = {
    'self_attn': {
        'w_q': {'slice': {'kernel': P(None, 'model')}},
        'w_k': {'slice': {'kernel': P(None, 'model')}},
        'w_v': {'slice': {'kernel': P(None, 'model')}},
        'w_o': {'slice': {'kernel': P('model', None)}},
        'ln': {'scale': P(), 'bias': P()}},
    'ffn': {
        'w_1': {'slice': {'kernel': P(None, 'model'), 'bias': P('model')}},
        'w_2': {'slice': {'kernel': P('model', None)}, 'bias': P()},
        'ln': {'scale': P(), 'bias': P()}},
}


def _block_data(seed=3):
    rng = np.random.RandomState(seed)
    return jnp.asarray(rng.randn(B, TL, TD), jnp.float32)


def _plain_block_params(seed=4):
    from kfac_pytorch_tpu.models.transformer import EncoderLayer
    plain = EncoderLayer(TD, TDI, TH, TDK, TDK, dropout=0.0)
    params = plain.init(jax.random.PRNGKey(seed), _block_data(), None,
                        train=False)['params']
    return plain, params


def _tp_block_params(pp):
    """Global TP-structured params from the plain block's (head-block
    column slicing is contiguous, so the full arrays transfer as-is)."""
    a, f = pp['self_attn'], pp['ffn']
    return {
        'self_attn': {
            'w_q': {'slice': {'kernel': a['w_q']['kernel']}},
            'w_k': {'slice': {'kernel': a['w_k']['kernel']}},
            'w_v': {'slice': {'kernel': a['w_v']['kernel']}},
            'w_o': {'slice': {'kernel': a['w_o']['kernel']}},
            'ln': dict(a['ln'])},
        'ffn': {
            'w_1': {'slice': {'kernel': f['w_1']['kernel'],
                              'bias': f['w_1']['bias']}},
            'w_2': {'slice': {'kernel': f['w_2']['kernel']},
                    'bias': f['w_2']['bias']},
            'ln': dict(f['ln'])},
    }


def test_tp_encoder_block_matches_dense_block():
    """The full Megatron block (sharded attention heads + sharded FFN)
    reproduces models/transformer.EncoderLayer exactly — outputs AND the
    parameter gradients (slices thereof) on a 2-rank model mesh."""
    x = _block_data()
    plain, pp = _plain_block_params()
    tpp = _tp_block_params(pp)
    block = tp.TPEncoderLayer(TD, TDI_L, TH_L, TDK, TDK, dropout=0.0)

    @functools.partial(jax.shard_map, mesh=_model_mesh(),
                       in_specs=(TP_BLOCK_SPECS, P()),
                       out_specs=(P(), TP_BLOCK_SPECS))
    def fwd_bwd(params, x):
        def loss_fn(p):
            out = block.apply({'params': p}, x, None, train=False)
            return (out ** 2).mean(), out
        (loss, out), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        return loss, grads

    loss_tp, grads_tp = fwd_bwd(tpp, x)

    def plain_loss(p):
        out = plain.apply({'params': p}, x, None, train=False)
        return (out ** 2).mean()

    loss_pl, grads_pl = jax.value_and_grad(plain_loss)(pp)
    np.testing.assert_allclose(float(loss_tp), float(loss_pl), rtol=1e-6)
    flat_tp = _tp_block_params(grads_pl)  # plain grads in TP layout
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5),
        grads_tp, flat_tp)


@requires_body_autodiff
def test_tp_encoder_block_kfac_dp_tp_invariance():
    """One K-FAC step on the Megatron block over a 2x2 ('data', 'model')
    mesh (MPD 'eigen' over the data axis) equals the model-only mesh run
    on the full batch — data sharding must not change the math, with the
    TP block's full capture set (6 sliced dense sublayers) in play."""
    ND = 2
    x = _block_data()
    y = _block_data(seed=9)  # regression target
    _, pp = _plain_block_params()
    tpp = _tp_block_params(pp)
    block = tp.TPEncoderLayer(TD, TDI_L, TH_L, TDK, TDK, dropout=0.0)
    local = tp.TPEncoderLayer(TD, TDI_L, TH_L, TDK, TDK, axis=None,
                              dropout=0.0)

    def mse(out, target):
        return ((out - target) ** 2).mean()

    def make_pre(nd, axis):
        pre = kfac.KFAC(variant='eigen', lr=LR, damping=DAMPING,
                        fac_update_freq=1, kfac_update_freq=1,
                        num_devices=nd, axis_name=axis)
        variables = capture.init(local, jax.random.PRNGKey(0), x,
                                 None, train=False)
        pre.setup(capture.collect_layer_meta(local, variables, x, None,
                                             train=False))
        return pre

    pre_dp = make_pre(ND, 'data')
    kstate = jax.tree.map(lambda a: jnp.stack([a] * NM), pre_dp.init())
    kspecs = jax.tree.map(lambda s: P('model', *s),
                          pre_dp.state_pspecs('data'),
                          is_leaf=lambda v: isinstance(v, P))
    mesh = Mesh(np.array(jax.devices()[:ND * NM]).reshape(ND, NM),
                ('data', 'model'))

    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(TP_BLOCK_SPECS, kspecs, P('data'), P('data')),
        out_specs=TP_BLOCK_SPECS)
    def dp_tp_step(params, kstate, x, y):
        _, _, grads, acts, gs, _ = capture.value_and_grad_with_capture(
            block, lambda out: mse(out, y), {'params': params}, x, None,
            train=False, axis_name=('data', 'model'))
        grads = kfac.parallel.average_grads(grads, 'data')
        k = jax.tree.map(lambda a: a[0], kstate)
        new_grads, _ = pre_dp.step(k, grads, acts, gs, axis_name='data')
        return new_grads

    got = dp_tp_step(tpp, kstate, x, y)

    pre_1 = make_pre(1, None)
    k1 = jax.tree.map(lambda a: jnp.stack([a] * NM), pre_1.init())

    @functools.partial(jax.shard_map, mesh=_model_mesh(),
                       in_specs=(TP_BLOCK_SPECS,
                                 jax.tree.map(lambda _: P('model'), k1),
                                 P(), P()),
                       out_specs=TP_BLOCK_SPECS)
    def tp_step(params, kstate, x, y):
        _, _, grads, acts, gs, _ = capture.value_and_grad_with_capture(
            block, lambda out: mse(out, y), {'params': params}, x, None,
            train=False, axis_name='model')
        k = jax.tree.map(lambda a: a[0], kstate)
        new_grads, _ = pre_1.step(k, grads, acts, gs)
        return new_grads

    want = tp_step(tpp, k1, x, y)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-3, atol=1e-4),
        got, want)


@requires_body_autodiff
def test_tp_sp_block_3axis_matches_dense_block():
    """The FULL 3-D mesh: ('data', 'seq', 'model') 2x2x2 — batch sharded
    over data, tokens over seq (exact ring attention rotates K/V per
    local head group), heads+FFN over model. Output and grad slices must
    equal the dense EncoderLayer on the full batch, causal masking on."""
    ND, NS = 2, 2
    x = _block_data()          # [B, TL, TD]; TL=6 splits over NS=2
    plain, pp = _plain_block_params()
    tpp = _tp_block_params(pp)
    block = tp.TPEncoderLayer(TD, TDI_L, TH_L, TDK, TDK, seq_axis='seq',
                              causal=True, dropout=0.0)
    mesh = Mesh(np.array(jax.devices()[:ND * NS * NM]).reshape(ND, NS, NM),
                ('data', 'seq', 'model'))
    xspec = P('data', 'seq')

    @functools.partial(jax.shard_map, mesh=mesh,
                       in_specs=(TP_BLOCK_SPECS, xspec),
                       out_specs=(xspec, TP_BLOCK_SPECS))
    def fwd_bwd(params, x):
        def loss_fn(p):
            out = block.apply({'params': p}, x, None, train=False)
            # global-mean loss: local sum / global count, then psum —
            # invariant over all three axes
            s = (out ** 2).sum() / (B * TL * TD)
            return jax.lax.psum(s, ('data', 'seq')), out
        (loss, out), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        del loss
        return out, grads

    out_tp, grads_tp = fwd_bwd(tpp, x)

    # dense oracle: the same math with a causal mask
    causal = jnp.tril(jnp.ones((TL, TL), bool))[None, None]

    def plain_loss(p):
        out = plain.apply({'params': p}, x, causal, train=False)
        return (out ** 2).mean(), out

    (_, out_pl), grads_pl = jax.value_and_grad(
        plain_loss, has_aux=True)(pp)
    np.testing.assert_allclose(np.asarray(out_tp), np.asarray(out_pl),
                               rtol=2e-4, atol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-5),
        grads_tp, _tp_block_params(grads_pl))
