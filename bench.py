"""Headline benchmark: ResNet-50 ImageNet-shape training with DP-KFAC on
one TPU chip — imgs/sec/chip and K-FAC step overhead vs SGD.

Mirrors the reference's SPEED mode (examples/pytorch_imagenet_resnet.py:21,
388-394: mean steady-state iteration time) and its efficiency config
(train_imagenet.sh: bs 32/chip, DP-KFAC, damping 0.002).

The flagship variant on TPU is ``inverse_dp`` (Cholesky): XLA's TPU
eigendecomposition is iteration-bound (~17x slower than the blocked
Cholesky inverse at ResNet-50 factor sizes, scripts/bench_ops.py), while
Cholesky+triangular-solve is matmul-bound and MXU-friendly. ``eigen_dp``
(the reference's default) is benchmarked at its deployed amortization
(update freq 10, pytorch_imagenet_resnet.py:94).

vs_baseline: reference 1-GPU K-FAC iteration 0.487 s at bs 32
(scripts/time_breakdown.py:26) = 65.7 imgs/s, factor+inverse every step —
compared against our inverse_dp at the same every-step setting.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
"""

import json
import os
import sys
import time
import traceback

import jax

# Persistent compile cache: the four measured programs cost many minutes
# of XLA compilation on first run; cached reruns start timing immediately.
jax.config.update('jax_compilation_cache_dir',
                  os.environ.get('JAX_COMPILATION_CACHE_DIR',
                                 os.path.expanduser('~/.cache/jax_comp')))
jax.config.update('jax_persistent_cache_min_compile_time_secs', 1.0)

import jax.numpy as jnp
import numpy as np
import optax

import kfac_pytorch_tpu as kfac
from kfac_pytorch_tpu import models, training

BATCH = 32
IMG = 224
WARMUP = 3
BASELINE_KFAC_ITER_S = 0.487  # scripts/time_breakdown.py:26 (1 GPU, bs 32)


def _ce(outputs, batch):
    return optax.softmax_cross_entropy_with_integer_labels(
        outputs, batch['label']).mean()


def _time_steps(step, state, batch, iters, warmup=WARMUP, **kw):
    for _ in range(warmup):
        state, m = step(state, batch, **kw)
    jax.block_until_ready(m)
    t0 = time.perf_counter()
    for _ in range(iters):
        state, m = step(state, batch, **kw)
    jax.block_until_ready(m)
    return (time.perf_counter() - t0) / iters, state


def _measure_variant(model, tx, batch, variant, fac, kfac_freq, iters,
                     basis_freq=None, warm_start=False):
    # the amortized path dispatches a distinct compiled program (the
    # eigenvalue-refresh variant) first at step kfac_freq — warm past it
    # so its XLA compile cannot land inside the timed window
    warmup = WARMUP if basis_freq is None else kfac_freq + 2
    precond = kfac.KFAC(variant=variant, lr=0.0125, damping=0.002,
                        fac_update_freq=fac, kfac_update_freq=kfac_freq,
                        num_devices=1, axis_name=None,
                        assignment='balanced', basis_update_freq=basis_freq,
                        warm_start_basis=warm_start)
    state = training.init_train_state(model, tx, precond,
                                      jax.random.PRNGKey(0), batch['input'])
    step = training.build_train_step(model, tx, precond, _ce,
                                     extra_mutable=('batch_stats',))
    s, _ = _time_steps(step, state, batch, iters, warmup=warmup,
                       lr=0.0125, damping=0.002)
    return s


def main():
    rng = np.random.RandomState(0)
    batch = {
        'input': jnp.asarray(rng.randn(BATCH, IMG, IMG, 3), jnp.bfloat16),
        'label': jnp.asarray(rng.randint(0, 1000, BATCH)),
    }
    model = models.resnet50(dtype=jnp.bfloat16)
    tx = training.sgd(0.0125, momentum=0.9, weight_decay=5e-5)

    # SGD baseline
    state = training.init_train_state(model, tx, None, jax.random.PRNGKey(0),
                                      batch['input'])
    sgd_step = training.build_train_step(model, tx, None, _ce,
                                         extra_mutable=('batch_stats',))
    sgd_s, _ = _time_steps(sgd_step, state, batch, 20)

    # flagship: inverse_dp, factor+inverse EVERY step (the reference
    # breakdown setting) and at the deployed freq-10 amortization
    inv1_s = _measure_variant(model, tx, batch, 'inverse_dp', 1, 1, 20)

    def _optional(fn):
        # secondary measurements must not kill the headline result if the
        # chip tunnel hiccups mid-compile; the traceback goes to stderr
        # (stdout stays one clean JSON line) so a real bug in the measured
        # path is still diagnosable from a null field
        try:
            return fn()
        except Exception:
            traceback.print_exc(file=sys.stderr)
            return None

    inv10_s = _optional(lambda: _measure_variant(
        model, tx, batch, 'inverse_dp', 10, 10, 20))
    # reference-default eigen_dp at deployed amortization: opt-in — its
    # eigh program is by far the slowest compile and the headline metric
    # doesn't use it (BENCH_FULL=1 to include)
    eig10_s = eig_amort_s = None
    if os.environ.get('BENCH_FULL'):
        eig10_s = _optional(lambda: _measure_variant(
            model, tx, batch, 'eigen_dp', 10, 10, 10))
        # + eigenbasis amortization: full eigh every 100 steps, eigenvalue
        # refresh at the freq-10 inverse updates. The timed window
        # contains refreshes only — which IS the steady state at this
        # cadence (fulls are 1 in 10 inverse updates); warm-started fulls
        # never land in a 10-iter window, so warm_start is deliberately
        # NOT part of this measurement (the kwarg exists for a future
        # full-in-window config). Combine with KFAC_EIGH_IMPL=jacobi|auto
        # to switch the eigh kernel of the fulls outside the window.
        eig_amort_s = _optional(lambda: _measure_variant(
            model, tx, batch, 'eigen_dp', 10, 10, 10, basis_freq=100))

    imgs_per_sec = BATCH / inv1_s
    result = {
        'metric': 'resnet50_imagenet_dpkfac_imgs_per_sec_per_chip',
        'value': round(imgs_per_sec, 2),
        'unit': 'imgs/s',
        'vs_baseline': round(imgs_per_sec / (BATCH / BASELINE_KFAC_ITER_S),
                             3),
        'extra': {
            'sgd_iter_s': round(sgd_s, 4),
            'inverse_dp_iter_s_freq1': round(inv1_s, 4),
            'inverse_dp_iter_s_freq10': (round(inv10_s, 4)
                                         if inv10_s is not None else None),
            'eigen_dp_iter_s_freq10': (round(eig10_s, 4)
                                       if eig10_s is not None else None),
            'eigen_dp_iter_s_freq10_basis100': (
                round(eig_amort_s, 4) if eig_amort_s is not None else None),
            # the eigen measurements' semantics depend on the eigh kernel
            'eigh_impl': os.environ.get('KFAC_EIGH_IMPL', 'xla'),
            'kfac_overhead_vs_sgd_freq1': round(inv1_s / sgd_s, 3),
            'kfac_overhead_vs_sgd_freq10': (round(inv10_s / sgd_s, 3)
                                            if inv10_s is not None else None),
            'batch': BATCH, 'img': IMG, 'device': str(jax.devices()[0]),
        },
    }
    print(json.dumps(result))


if __name__ == '__main__':
    main()
