"""Headline benchmark: ResNet-50 ImageNet-shape training with eigen_dp
K-FAC on one TPU chip — imgs/sec/chip and K-FAC step overhead vs SGD.

Mirrors the reference's SPEED mode (examples/pytorch_imagenet_resnet.py:21,
388-394: mean iteration time over ~60 steady-state iterations) and its
efficiency config (train_imagenet.sh: bs 32/chip, eigen_dp, damping 0.002,
factor+inverse update every iteration — the setting behind the
time_breakdown.py anchors).

vs_baseline: reference 1-GPU K-FAC iteration 0.487 s at bs 32
(scripts/time_breakdown.py:26) = 65.7 imgs/s.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
"""

import json
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

import kfac_pytorch_tpu as kfac
from kfac_pytorch_tpu import models, training

BATCH = 32
IMG = 224
WARMUP = 5
ITERS = 30
BASELINE_KFAC_ITER_S = 0.487  # scripts/time_breakdown.py:26 (1 GPU, bs 32)


def _ce(outputs, batch):
    return optax.softmax_cross_entropy_with_integer_labels(
        outputs, batch['label']).mean()


def _time_steps(step, state, batch, iters, **kw):
    for _ in range(WARMUP):
        state, m = step(state, batch, **kw)
    jax.block_until_ready(m)
    t0 = time.perf_counter()
    for _ in range(iters):
        state, m = step(state, batch, **kw)
    jax.block_until_ready(m)
    return (time.perf_counter() - t0) / iters, state


def main():
    rng = np.random.RandomState(0)
    batch = {
        'input': jnp.asarray(rng.randn(BATCH, IMG, IMG, 3), jnp.bfloat16),
        'label': jnp.asarray(rng.randint(0, 1000, BATCH)),
    }
    model = models.resnet50(dtype=jnp.bfloat16)
    tx = training.sgd(0.0125, momentum=0.9, weight_decay=5e-5)

    # --- SGD baseline ---------------------------------------------------
    state = training.init_train_state(model, tx, None, jax.random.PRNGKey(0),
                                      batch['input'])
    sgd_step = training.build_train_step(model, tx, None, _ce,
                                         extra_mutable=('batch_stats',))
    sgd_s, _ = _time_steps(sgd_step, state, batch, ITERS)

    # --- K-FAC eigen_dp, update every iteration (reference breakdown
    # setting) -----------------------------------------------------------
    precond = kfac.KFAC(variant='eigen_dp', lr=0.0125, damping=0.002,
                        fac_update_freq=1, kfac_update_freq=1,
                        num_devices=1, axis_name=None,
                        assignment='balanced')
    state = training.init_train_state(model, tx, precond,
                                      jax.random.PRNGKey(0), batch['input'])
    kfac_step = training.build_train_step(model, tx, precond, _ce,
                                          extra_mutable=('batch_stats',))
    kfac_s, state = _time_steps(kfac_step, state, batch, ITERS,
                                lr=0.0125, damping=0.002)

    # --- amortized setting (kfac freq 10, the deployed configuration,
    # pytorch_imagenet_resnet.py:94) -------------------------------------
    precond.fac_update_freq = 10
    precond.kfac_update_freq = 10
    amort_s, _ = _time_steps(kfac_step, state, batch, ITERS,
                             lr=0.0125, damping=0.002)

    imgs_per_sec = BATCH / kfac_s
    result = {
        'metric': 'resnet50_imagenet_kfac_imgs_per_sec_per_chip',
        'value': round(imgs_per_sec, 2),
        'unit': 'imgs/s',
        'vs_baseline': round(kfac_s and imgs_per_sec
                             / (BATCH / BASELINE_KFAC_ITER_S), 3),
        'extra': {
            'sgd_iter_s': round(sgd_s, 4),
            'kfac_iter_s_freq1': round(kfac_s, 4),
            'kfac_iter_s_freq10': round(amort_s, 4),
            'kfac_overhead_vs_sgd_freq1': round(kfac_s / sgd_s, 3),
            'kfac_overhead_vs_sgd_freq10': round(amort_s / sgd_s, 3),
            'batch': BATCH, 'img': IMG, 'device': str(jax.devices()[0]),
        },
    }
    print(json.dumps(result))


if __name__ == '__main__':
    main()
